"""Time emulated-f64 Cholesky + triangular solves at m=10000 on the TPU,
plus the Kahan-candidate costs: this number decides the phase-2 design
for the 10k x 50k reference config."""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")

m = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
rng = np.random.default_rng(0)
# SPD with spread ~1e10
B = rng.standard_normal((m, m + 64)) / np.sqrt(m)
d = 10.0 ** rng.uniform(-5, 5, size=m + 64)
M = (B * d) @ B.T + 1e-6 * np.eye(m)
M64 = jnp.asarray(M, dtype=jnp.float64)
rhs = jnp.asarray(rng.standard_normal(m), dtype=jnp.float64)

def tme(label, fn, *args, reps=2):
    t0 = time.perf_counter(); r = jax.block_until_ready(fn(*args)); t1 = time.perf_counter()
    ts = []
    for _ in range(reps):
        t2 = time.perf_counter(); r = jax.block_until_ready(fn(*args)); ts.append(time.perf_counter()-t2)
    print(f"{label}: compile+first={t1-t0:.1f}s steady={min(ts):.3f}s", flush=True)
    return r

chol = jax.jit(jnp.linalg.cholesky)
L = tme("f64 cholesky", chol, M64)
cs = jax.jit(lambda L, r: jax.scipy.linalg.cho_solve((L, True), r))
tme("f64 cho_solve 1 rhs", cs, L, rhs, reps=3)
chol32 = jax.jit(lambda M: jnp.linalg.cholesky(M.astype(jnp.float32)))
L32 = tme("f32 cholesky (from f64 M)", chol32, M64)
tri = jax.jit(lambda L: jax.scipy.linalg.solve_triangular(L, jnp.eye(L.shape[0], dtype=L.dtype), lower=True))
tme("f32 triangular inverse", tri, L32)
print("PROBE DONE", flush=True)
