"""End-to-end elastic recovery probe: injected device loss on an 8-virtual-
device CPU mesh, recovered by mesh re-formation, with the fault history
printed — the fastest way to see (and demo) the SHRINK rung working
without TPU hardware.

Run: python scripts/probe_elastic.py
Exit 0 iff the solve recovered via SHRINK (not backend degradation) and
matched the fault-free objective within 1e-8.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distributedlpsolver_tpu.ipm import Status, solve  # noqa: E402
from distributedlpsolver_tpu.models.generators import random_dense_lp  # noqa: E402
from distributedlpsolver_tpu.supervisor import (  # noqa: E402
    FaultKind,
    InjectedFault,
    SupervisorConfig,
    supervised_solve,
)


def main() -> int:
    devs = jax.devices()
    print(f"devices: {len(devs)} × {devs[0].platform}")
    problem = random_dense_lp(30, 70, seed=7)

    t0 = time.perf_counter()
    reference = solve(problem, backend="sharded", fused_loop=False)
    print(
        f"fault-free : {reference.summary()} "
        f"({time.perf_counter() - t0:.1f}s wall)"
    )

    lost = (devs[5].id, devs[6].id)
    plan = [InjectedFault(FaultKind.DEVICE_LOST, iteration=3, device_ids=lost)]
    sup = SupervisorConfig(
        fault_plan=plan,
        adaptive_timeout=True,
        backoff_base=0.01,
    )
    t0 = time.perf_counter()
    r = supervised_solve(problem, backend="sharded", supervisor=sup)
    wall = time.perf_counter() - t0
    print(f"with loss  : {r.summary()} ({wall:.1f}s wall)")
    print("fault history:")
    for f in r.faults:
        print(
            f"  {f.kind.value}@it{f.iteration} [{f.backend}] "
            f"devices={list(f.devices)} -> {f.action} "
            f"(recovery {f.recovery_overhead_s:.3f}s)"
        )

    err = abs(r.objective - reference.objective) / (
        1.0 + abs(reference.objective)
    )
    shrunk = any(f.action.startswith("shrink:") for f in r.faults)
    ok = (
        r.status == Status.OPTIMAL
        and r.backend == "sharded"
        and shrunk
        and err <= 1e-8
    )
    print(
        f"objective agreement: {err:.2e} (<= 1e-8), "
        f"recovered via {'SHRINK' if shrunk else 'NOT-shrink (FAIL)'}"
    )
    print("PROBE", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
