"""Prototype + measure a GEMM-dominated batched f64 Cholesky-inverse on
TPU. Motivation (measured, probe_batched_parts.py): XLA's emulated-f64
`jnp.linalg.cholesky` on (128,128,128) costs ~345 ms and a single f64
cho_solve ~130 ms, while emulated-f64 GEMM runs at ~150 GFLOP/s with
2e-15 max rel error and fused f64 elementwise at ~2 ns/element. So a
panel factorization whose O(m^3) is GEMM and whose only sequential part
is a p-column recursion should demolish the builtin.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import distributedlpsolver_tpu  # noqa: F401
import jax, jax.numpy as jnp, numpy as np
import functools


def _factor_diag_block(D):
    """(B, p, p) SPD -> (C, W): C = chol(D), W = C^-1. Unrolled p-step
    column recursion; static slices only."""
    B, p, _ = D.shape
    C = jnp.zeros_like(D)
    for i in range(p):
        r = jnp.sqrt(D[:, i, i])                       # (B,)
        col = D[:, i:, i] / r[:, None]                 # (B, p-i)
        C = C.at[:, i:, i].set(col)
        if i + 1 < p:
            t = col[:, 1:]                             # (B, p-i-1)
            D = D.at[:, i + 1:, i + 1:].add(-t[:, :, None] * t[:, None, :])
    # forward substitution on identity: W = C^-1 (row recursion)
    W = jnp.zeros_like(C)
    for i in range(p):
        if i == 0:
            row = jnp.zeros((B, p), C.dtype).at[:, 0].set(1.0 / C[:, 0, 0])
        else:
            e = jnp.zeros((B, p), C.dtype).at[:, i].set(1.0)
            acc = jnp.einsum("bj,bjk->bk", C[:, i, :i], W[:, :i, :])
            row = (e - acc) / C[:, i, i][:, None]
        W = W.at[:, i, :].set(row)
    return C, W


@functools.partial(jax.jit, static_argnames=("panel",))
def chol_inv_batched(M, panel=16):
    """(B, m, m) SPD -> Linv (B, m, m), lower-triangular, with
    M^-1 = Linv^T @ Linv. Panel loop via fori_loop; all O(m^3) in GEMM."""
    B, m, _ = M.shape
    p = panel
    P = m // p
    rows = jnp.arange(m)
    X0 = jnp.broadcast_to(jnp.eye(m, dtype=M.dtype), (B, m, m))

    def body(j, carry):
        T, X = carry
        g0 = j * p
        D = jax.lax.dynamic_slice(T, (0, g0, g0), (B, p, p))
        C, W = _factor_diag_block(D)
        Tpan = jax.lax.dynamic_slice(T, (0, 0, g0), (B, m, p))
        # full-height panel of L: rows >= g0 (panel rows give C exactly)
        Lpan = jnp.einsum("bmp,bqp->bmq", Tpan, W)
        mask = (rows[:, None] >= g0).astype(M.dtype)
        Lpan = Lpan * mask[None]
        below = (rows[:, None] >= g0 + p).astype(M.dtype)
        Lbelow = Lpan * below[None]
        # trailing Schur update (processed region becomes garbage — never read)
        T = T - jnp.einsum("bmp,bnp->bmn", Lbelow, Lbelow)
        # inversion pass, fused: X[panel,:] = W @ X[panel,:]; X[below,:] -= Lbelow @ X[panel,:]
        Xp = jax.lax.dynamic_slice(X, (0, g0, 0), (B, p, m))
        Xp = jnp.einsum("bpq,bqm->bpm", W, Xp)
        X = jax.lax.dynamic_update_slice(X, Xp, (0, g0, 0))
        X = X - jnp.einsum("bmp,bpn->bmn", Lbelow, Xp)
        return T, X

    _, X = jax.lax.fori_loop(0, P, body, (M, X0))
    return X


def timeit(name, fn, *args, reps=5):
    np.asarray(fn(*args))
    ts = []
    for k in range(reps):
        a0 = args[0] * (1.0 + 1e-9 * (k + 1))
        t0 = time.perf_counter()
        np.asarray(fn(a0, *args[1:]))
        ts.append(time.perf_counter() - t0)
    print(f"{name:46s} best {min(ts)*1e3:9.1f} ms")


# ---- correctness (CPU-verified) --------------------------------------
rng = np.random.default_rng(0)
for B, m, p in [(4, 64, 16), (2, 128, 16)]:
    G = rng.standard_normal((B, m, 2 * m))
    d = np.exp(rng.uniform(-10, 10, (B, 2 * m)))
    M_np = np.einsum("bmn,bn,bkn->bmk", G, d, G) + 1e-8 * np.eye(m)[None] * np.abs(
        np.einsum("bmn,bn,bkn->bmk", G, d, G)
    ).max()
    Linv = np.asarray(chol_inv_batched(jnp.asarray(M_np), panel=p))
    Minv = np.einsum("bqm,bqk->bmk", Linv, Linv)
    err = np.abs(np.einsum("bmk,bkl->bml", Minv, M_np) - np.eye(m)[None]).max()
    cond = np.linalg.cond(M_np).max()
    print(f"B={B} m={m}: ||Minv·M - I||_max = {err:.2e}  (cond≈{cond:.1e})")

# ---- timing ----------------------------------------------------------
B, m = 128, 128
G = rng.standard_normal((B, m, 4 * m))
d = np.exp(rng.uniform(-12, 12, (B, 4 * m)))
M_np = np.einsum("bmn,bn,bkn->bmk", G, d, G)
M_np += 1e-9 * np.abs(M_np).max() * np.eye(m)[None]
M = jnp.asarray(M_np)

for p in (8, 16, 32):
    timeit(f"chol_inv_batched (B=128,m=128,p={p}) f64", lambda M, p=p: chol_inv_batched(M, panel=p)[:, 0, 0], M)

@jax.jit
def builtin_chol(M):
    return jnp.linalg.cholesky(M)[:, 0, 0]

timeit("builtin jnp.linalg.cholesky f64", builtin_chol, M)

# solve cost: two batched GEMVs with Linv
Linv = chol_inv_batched(M, panel=16)
rhs = jnp.asarray(rng.standard_normal((B, m)))

@jax.jit
def solve_inv(Linv, rhs):
    t = jnp.einsum("bqm,bq->bm", Linv, jnp.einsum("bmq,bq->bm", Linv, rhs))
    return t[:, 0]

np.asarray(solve_inv(Linv, rhs))
ts = []
for k in range(5):
    r0 = rhs * (1.0 + 1e-9 * (k + 1))
    t0 = time.perf_counter(); np.asarray(solve_inv(Linv, r0)); ts.append(time.perf_counter() - t0)
print(f"{'solve via Linv (2 GEMVs) f64':46s} best {min(ts)*1e3:9.1f} ms")

# single large: m=2048, B=1 (scale check toward the 10k endgame)
m2 = 2048
G2 = rng.standard_normal((1, m2, m2 + 512))
M2_np = np.einsum("bmn,bkn->bmk", G2, G2) + 1e-6 * m2 * np.eye(m2)[None]
M2 = jnp.asarray(M2_np)
for p in (128, 256):
    timeit(f"chol_inv_batched (B=1,m=2048,p={p}) f64", lambda M, p=p: chol_inv_batched(M, panel=p)[:, 0, 0], M2)
Linv2 = np.asarray(chol_inv_batched(M2, panel=128))[0]
err2 = np.abs(Linv2.T @ Linv2 @ M2_np[0] - np.eye(m2)).max()
print(f"m=2048 ||Minv·M - I||_max = {err2:.2e}")

@jax.jit
def builtin_chol2(M):
    return jnp.linalg.cholesky(M)[:, 0, 0]
timeit("builtin cholesky f64 m=2048", builtin_chol2, M2)
print("done")
