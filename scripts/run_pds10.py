"""pds-10-class block-angular run with a FULLY MEASURED CPU baseline
(VERDICT round 3 item 5): a size where the cpu-sparse baseline finishes
end-to-end (hours, not the >1-day pds-20-class solve), so the block
backend's vs_baseline is a measured ratio, not an s/iter extrapolation.

Size: K=32, 432x1400 per block, 800 linking rows -> 14624 rows — the
pds-10 row class (real pds-10: 16558 rows; BASELINE.json:8's smaller
sibling). The 800 dense linking rows still fill the sparse factorization
(the pds-20 cost mechanism), but at ~1/8 the link-cube cost the full CPU
solve completes.

Usage: python scripts/run_pds10.py tpu|cpu
  tpu: block backend on the real chip  -> .pds10_tpu.json
  cpu: cpu-sparse end-to-end baseline  -> .pds10_cpu.json
Merge both into SCALE_RUNS.json["pds10"] when done.
"""
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Measurement envelope: `--require-tpu` aborts (exit 4) instead of
# silently measuring host CPU when the accelerator is missing (the
# BENCH_r05 failure class).
from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]

mode = sys.argv[1] if len(sys.argv) > 1 else "tpu"
if mode == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import block_angular_lp

K, mb, nb, link = 32, 432, 1400, 800
print(f"building K={K} {mb}x{nb} link={link}...", flush=True)
p = block_angular_lp(K, mb, nb, link, seed=0, sparse=True, density=0.005)
print(f"built {p.shape}, nnz={p.A.nnz}", flush=True)

t0 = time.time()
if mode == "cpu":
    r = solve(p, backend="cpu-sparse", verbose=True, max_iter=120)
    tag = "cpu-sparse (SciPy sparse-direct normal equations, 1 host core)"
else:
    from bench import _solve_timed  # tunnel-transient retry wrapper
    from distributedlpsolver_tpu.backends.block_angular import (
        BlockAngularBackend,
    )

    _solve_timed(p, "block", max_iter=3)  # compile warm-up
    be = BlockAngularBackend()  # explicit instance: phase_report access
    t0 = time.time()
    r = _solve_timed(p, be, max_iter=120)
    tag = "block@tpu"
wall = time.time() - t0
print(
    f"{tag}: {r.status.name} obj={r.objective:.6f} iters={r.iterations} "
    f"gap={r.rel_gap:.2e} pinf={r.pinf:.2e} dinf={r.dinf:.2e} "
    f"solve={r.solve_time:.2f}s wall={wall:.1f}s",
    flush=True,
)
row = {
    "config": f"pds-10-class block_angular(K={K},{mb}x{nb},link={link}), "
              f"{p.shape[0]} rows (BASELINE.json:8 smaller sibling)",
    "backend": tag,
    "time_s": round(r.solve_time, 3),
    "iters": int(r.iterations),
    "iters_per_sec": round(r.iters_per_sec, 3),
    "status": r.status.value,
    "tol": 1e-8,
    "objective": float(r.objective),
}
if mode == "tpu":
    # Per-phase wall split + FLOP/s vs seed rates, keyed by the
    # backend-recorded phase mode (utils/utilization.py — shared with
    # run_pds20_tpu.py).
    from distributedlpsolver_tpu.utils.utilization import fold_utilization

    report = list(getattr(be, "phase_report", []))
    if report:
        flops_it = float(be._f64_flops)
        row["flops_per_iter_est"] = f"{flops_it:.3g}"
        row["phase_report"] = fold_utilization(report, flops_it)

out = os.path.join(_REPO, f".pds10_{mode}.json")
with open(out, "w") as fh:
    json.dump(row, fh, indent=2)
print(json.dumps(row), flush=True)
