"""The reference-scale dense config (BASELINE.json:9): 10000 x 50000 to a
1e-8 relative duality gap on the IPM `tpu` backend (two-phase + PCG).

Writes the suite row to /root/repo/BENCH_10K.json on success. Run with
TPULP_SEG_VERBOSE=1 for live segment progress.
"""
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

# Measurement envelope: `--require-tpu` aborts (exit 4) instead of
# silently measuring host CPU when the accelerator is missing (the
# BENCH_r05 failure class).
from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]

m, n = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (10000, 50000)
max_iter = int(sys.argv[3]) if len(sys.argv) > 3 else 200
# CG sweep cap: one PCG-phase Mehrotra iteration is ONE device program
# holding 2 CG solves, and near the f32 floor each runs its full cap at
# ~0.5 s/sweep (ew-f64 GEMV pair, measured) — cap 40 keeps the worst
# program ~40 s, under the ~60 s tunnel execution watchdog.
cg_iters = int(sys.argv[4]) if len(sys.argv) > 4 else 40

from distributedlpsolver_tpu.backends.dense import DenseJaxBackend
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import random_dense_lp

print(f"building {m}x{n}...", flush=True)
t0 = time.time()
p = random_dense_lp(m, n, seed=2)  # same seed as the bench suite row
print(f"built in {time.time()-t0:.0f}s", flush=True)

# Explicit backend instance so the endgame's per-dispatch timings
# (be.endgame_timings) can be folded into the artifact after the solve.
be = DenseJaxBackend()
t0 = time.time()
r = solve(p, backend=be, max_iter=max_iter, cg_iters=cg_iters)
wall = time.time() - t0
print(
    f"RESULT: {r.status.name} obj={r.objective:.8f} iters={r.iterations} "
    f"gap={r.rel_gap:.2e} pinf={r.pinf:.2e} dinf={r.dinf:.2e} "
    f"solve={r.solve_time:.1f}s setup={r.setup_time:.1f}s wall={wall:.1f}s",
    flush=True,
)
row = {
    "config": f"random dense {m}x{n} (reference scale, BASELINE.json:9)",
    "backend": r.backend,
    "time_s": round(r.solve_time, 2),
    "iters": int(r.iterations),
    "iters_per_sec": round(r.iters_per_sec, 3),
    "status": r.status.value,
    "tol": 1e-8,
    "rel_gap": float(r.rel_gap),
    "pinf": float(r.pinf),
    "dinf": float(r.dinf),
    "setup_s": round(r.setup_time, 1),
    "wall_s": round(wall, 1),
    "phase_report": list(getattr(be, "phase_report", [])),
    "endgame_timings": getattr(be, "endgame_timings", []),
}
with open("/root/repo/BENCH_10K.json", "w") as fh:
    json.dump(row, fh, indent=2)
print(json.dumps(row), flush=True)
