"""stormG2_1000-scale HINT-LESS run (VERDICT round-4 item 8): push the
storm-class stand-in to >=100k rows — the order of magnitude the real
Mittelmann instance has (hundreds of thousands of rows) — and record
detection time, solve outcome, and whichever constraint binds first.

Default shape: K=1024 blocks of 96x192 with 64 linking rows
= 98,368 + 64 rows (~100k), sparse, arriving hint-less.

Writes /root/repo/.storm100k.json. Optional argv: K mb nb link density.
"""
import json
import sys
import time

sys.path.insert(0, "/root/repo")

# Measurement envelope: `--require-tpu` aborts (exit 4) instead of
# silently measuring host CPU when the accelerator is missing (the
# BENCH_r05 failure class).
from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]

K, mb, nb, link = (
    (int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
    if len(sys.argv) > 4 else (1024, 96, 192, 64)
)
density = float(sys.argv[5]) if len(sys.argv) > 5 else 0.06

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import block_angular_lp
from distributedlpsolver_tpu.models.structure import detect_block_structure

print(f"building K={K} {mb}x{nb} link={link} density={density}...", flush=True)
t0 = time.time()
p = block_angular_lp(K, mb, nb, link, seed=3, sparse=True, density=density)
p.block_structure = None  # hint-less, like a real MPS file
t_build = time.time() - t0
print(f"built {p.shape}, nnz={p.A.nnz} in {t_build:.0f}s", flush=True)

out = {"config": f"storm100k-class block_angular(K={K},{mb}x{nb},link={link},"
                 f"density={density}), {p.A.shape[0]} rows, HINT-LESS",
       "rows": int(p.A.shape[0]), "cols": int(p.A.shape[1]),
       "nnz": int(p.A.nnz)}
try:
    t0 = time.time()
    hint = detect_block_structure(p)
    t_detect = time.time() - t0
    assert hint is not None, "detection declined the structure"
    out["detect_s"] = round(t_detect, 2)
    out["detected_blocks"] = int(hint["num_blocks"])
    print(f"detected K={hint['num_blocks']} in {t_detect:.2f}s", flush=True)
    p.block_structure = hint

    solve(p, backend="block", max_iter=3)  # warm compile
    t0 = time.time()
    r = solve(p, backend="block", max_iter=120)
    wall = time.time() - t0
    out.update({
        "backend": "block@tpu", "status": r.status.value,
        "objective": r.objective, "iters": int(r.iterations),
        "rel_gap": float(r.rel_gap), "pinf": float(r.pinf),
        "dinf": float(r.dinf), "time_s": round(r.solve_time, 2),
        "wall_s": round(wall, 1),
    })
    print(f"TPU block: {r.status.name} obj={r.objective:.6f} "
          f"iters={r.iterations} gap={r.rel_gap:.2e} "
          f"solve={r.solve_time:.2f}s wall={wall:.1f}s", flush=True)
except Exception as e:  # record WHERE it binds instead of dying silently
    out["failed"] = f"{type(e).__name__}: {str(e)[:500]}"
    print("FAILED:", out["failed"], flush=True)

with open("/root/repo/.storm100k.json", "w") as fh:
    json.dump(out, fh, indent=1)
print("wrote .storm100k.json", flush=True)
