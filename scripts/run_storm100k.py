"""storm-100k A/B harness for the f64 program-class fault (ROUND5_NOTES
lever 4, VERDICT round-4 item 8): the ≥100k-row storm class binds on an
f64 phase KERNEL fault — the worker crashes on the big-K batched f64
programs — not on HBM, while chunk ≤128 program shapes stay healthy.
This script runs the SAME hint-less storm-class instance through two
arms and records, per arm, the per-phase program-class stamp
(backends.block_angular.phase_program_class) plus outcome/timing:

* ``oneshot``  — grouping off (DLPS_BLOCK_K_GROUP=0): the pre-lever-4
  one-shot f64 phase programs, the arm that reproduces the fault class;
* ``kgroup``   — per-K-group sequential chunking at the default ≤128
  (DLPS_BLOCK_K_GROUP=128), the lever-4 fix.

Each arm runs in its OWN SUBPROCESS: ``_K_GROUP`` is read once at
import and jit traces key on operand shapes, not module globals — two
arms sharing a process would silently share compiled programs and
measure nothing.

Default shape: K=1024 blocks of 96x192 with 64 linking rows
= 98,368 + 64 rows (~100k), sparse, arriving hint-less.

Measurement envelope: ``--require-tpu`` aborts with exit 4 instead of
silently measuring host CPU when the accelerator is missing (the
BENCH_r05 failure class). Off-TPU the harness still runs (CPU has no
program-class fault to reproduce, but the A/B plumbing stays testable
on small shapes).

Writes /root/repo/.storm100k.json. Optional argv: K mb nb link density.
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, "/root/repo")

from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]


def _shape():
    if len(sys.argv) > 4:
        K, mb, nb, link = (int(a) for a in sys.argv[1:5])
        density = float(sys.argv[5]) if len(sys.argv) > 5 else 0.06
    else:
        K, mb, nb, link, density = 1024, 96, 192, 64, 0.06
    return K, mb, nb, link, density


def _arm_main(out_path):
    """One arm: build, detect, solve — grouping already fixed by the
    parent's DLPS_BLOCK_K_GROUP before this interpreter imported jax."""
    import jax.numpy as jnp

    from distributedlpsolver_tpu.backends import block_angular as ba
    from distributedlpsolver_tpu.ipm import solve
    from distributedlpsolver_tpu.models.generators import block_angular_lp
    from distributedlpsolver_tpu.models.structure import detect_block_structure

    K, mb, nb, link, density = _shape()
    out = {"k_group": ba._K_GROUP}
    print(f"[arm k_group={ba._K_GROUP}] building K={K} {mb}x{nb} "
          f"link={link} density={density}...", flush=True)
    t0 = time.time()
    p = block_angular_lp(K, mb, nb, link, seed=3, sparse=True,
                         density=density)
    p.block_structure = None  # hint-less, like a real MPS file
    out.update({
        "rows": int(p.A.shape[0]), "cols": int(p.A.shape[1]),
        "nnz": int(p.A.nnz), "build_s": round(time.time() - t0, 1),
    })
    try:
        t0 = time.time()
        hint = detect_block_structure(p)
        assert hint is not None, "detection declined the structure"
        out["detect_s"] = round(time.time() - t0, 2)
        out["detected_blocks"] = int(hint["num_blocks"])
        p.block_structure = hint
        # Per-phase program-class stamps — the quantity this harness
        # exists to A/B: the f32 phase keeps one-shot shapes in both
        # arms; the f64 phases are the lever-4 target.
        out["phase_program_class"] = {
            "f32": ba.phase_program_class(K, jnp.float32),
            "f64": ba.phase_program_class(K, jnp.float64),
        }

        solve(p, backend="block", max_iter=3)  # warm compile
        t0 = time.time()
        r = solve(p, backend="block", max_iter=120)
        wall = time.time() - t0
        out.update({
            "status": r.status.value, "objective": r.objective,
            "iters": int(r.iterations), "rel_gap": float(r.rel_gap),
            "pinf": float(r.pinf), "dinf": float(r.dinf),
            "time_s": round(r.solve_time, 2), "wall_s": round(wall, 1),
        })
        print(f"[arm k_group={ba._K_GROUP}] {r.status.name} "
              f"obj={r.objective:.6f} iters={r.iterations} "
              f"solve={r.solve_time:.2f}s wall={wall:.1f}s", flush=True)
    except Exception as e:  # record WHERE it binds instead of dying silently
        out["failed"] = f"{type(e).__name__}: {str(e)[:500]}"
        print(f"[arm k_group={ba._K_GROUP}] FAILED:", out["failed"],
              flush=True)
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)


if "--arm" in sys.argv:
    i = sys.argv.index("--arm")
    path = sys.argv[i + 1]
    sys.argv = sys.argv[:i] + sys.argv[i + 2:]
    _arm_main(path)
    sys.exit(0)


K, mb, nb, link, density = _shape()
out = {
    "config": f"storm100k-class block_angular(K={K},{mb}x{nb},link={link},"
              f"density={density}), HINT-LESS, A/B oneshot vs kgroup",
    "arms": {},
}
import jax

out["platform"] = jax.devices()[0].platform

for name, group in (("oneshot", "0"), ("kgroup", "128")):
    arm_path = f"/root/repo/.storm100k.{name}.json"
    env = dict(os.environ, DLPS_BLOCK_K_GROUP=group)
    print(f"=== arm {name} (DLPS_BLOCK_K_GROUP={group}) ===", flush=True)
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--arm", arm_path]
        + sys.argv[1:],
        env=env,
    )
    arm = {"exit_code": proc.returncode,
           "harness_wall_s": round(time.time() - t0, 1)}
    # A crashed worker (the fault class under A/B) leaves no JSON — the
    # exit code IS the datum then.
    if os.path.exists(arm_path):
        with open(arm_path) as fh:
            arm.update(json.load(fh))
        os.remove(arm_path)
    out["arms"][name] = arm

a, b = out["arms"].get("oneshot", {}), out["arms"].get("kgroup", {})
if "time_s" in a and "time_s" in b:
    out["kgroup_speedup"] = round(a["time_s"] / max(b["time_s"], 1e-9), 3)
if "objective" in a and "objective" in b:
    out["arms_agree"] = bool(
        abs(a["objective"] - b["objective"])
        <= 1e-6 * (1 + abs(a["objective"]))
    )

with open("/root/repo/.storm100k.json", "w") as fh:
    json.dump(out, fh, indent=1)
print("wrote .storm100k.json", flush=True)
