"""pds-20-class Schur-path run (VERDICT round 2 item 2): the K=64,
432x1400-per-block, 1600 linking-row instance (~29k rows) on the TPU
block backend (two-phase segmented Schur), plus an optional 8-virtual-
device mesh run proving the K-sharded memory story.

Writes /root/repo/.pds20_tpu.json. The CPU baseline is measured
separately (scripts/run_pds20_cpu.py) because one cpu-sparse iteration
takes ~40 min at this scale — its artifact records measured s/iter.

Usage: python scripts/run_pds20_tpu.py [mesh]
  'mesh' runs on 8 virtual CPU devices instead (set
  XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""
import json
import sys
import time

sys.path.insert(0, "/root/repo")

# Measurement envelope: `--require-tpu` aborts (exit 4) instead of
# silently measuring host CPU when the accelerator is missing (the
# BENCH_r05 failure class).
from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]

on_mesh = len(sys.argv) > 1 and sys.argv[1] == "mesh"
if on_mesh:
    import jax

    jax.config.update("jax_platforms", "cpu")

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import block_angular_lp

K, mb, nb, link = 64, 432, 1400, 1600
print(f"building K={K} {mb}x{nb} link={link}...", flush=True)
p = block_angular_lp(K, mb, nb, link, seed=0, sparse=True, density=0.005)
print(f"built {p.shape}, nnz={p.A.nnz}", flush=True)

import jax

from distributedlpsolver_tpu.backends.block_angular import (
    BlockAngularBackend,
)

if on_mesh:
    from distributedlpsolver_tpu.parallel import make_mesh

    mesh = make_mesh(devices=jax.devices()[:8])
    be = BlockAngularBackend(mesh=mesh)
    tag = "block@8dev-mesh"
else:
    be = BlockAngularBackend()  # explicit instance: phase_report access
    tag = "block@tpu"

# Auto mode resolves to the lowering-safe huge-shape plan: f32 phase 1 →
# PCG at the handoff tol (ew-f64 matrix-free operator — no emulated-f64
# dot_generals, whose 8×-f32 operand-split temps OOMed this shape) →
# n-chunked true-f64 Schur finisher ("f64c") at 1e-8.
solve(p, backend=be, max_iter=3)  # compile warm-up
t0 = time.time()
r = solve(p, backend=be, max_iter=120)
wall = time.time() - t0
print(
    f"{tag}: {r.status.name} obj={r.objective:.6f} iters={r.iterations} "
    f"gap={r.rel_gap:.2e} pinf={r.pinf:.2e} dinf={r.dinf:.2e} "
    f"solve={r.solve_time:.2f}s wall={wall:.1f}s",
    flush=True,
)
row = {
    "config": f"pds-20-class block_angular(K={K},{mb}x{nb},link={link}), "
              f"{p.shape[0]} rows (BASELINE.json:8 target class)",
    "backend": tag,
    "time_s": round(r.solve_time, 3),
    "iters": int(r.iterations),
    "iters_per_sec": round(r.iters_per_sec, 2),
    "status": r.status.value,
    "tol": 1e-8,
    "objective": float(r.objective),
}

# Utilization (VERDICT round 3 item 4): per-phase wall split from the
# shared segment driver, FLOP/s vs seed rates keyed by the
# backend-recorded phase mode (utils/utilization.py).
from distributedlpsolver_tpu.utils.utilization import fold_utilization

report = list(getattr(be, "phase_report", []))
if report and not on_mesh:
    # mesh mode is correctness-only (virtual CPU devices emulate f64 in
    # software) — a % of the TPU seed rates would be meaningless there.
    flops_it = float(be._f64_flops)  # same op count for f32 and f64c
    row["flops_per_iter_est"] = f"{flops_it:.3g}"
    row["phase_report"] = fold_utilization(report, flops_it)
out = "/root/repo/.pds20_mesh.json" if on_mesh else "/root/repo/.pds20_tpu.json"
with open(out, "w") as fh:
    json.dump(row, fh, indent=2)
print(json.dumps(row), flush=True)
