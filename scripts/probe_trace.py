"""Distributed-tracing acceptance probe: one trace across the plane
(README "Distributed tracing & fleet telemetry").

A live 2-backend plane behind a hedging router, every process writing
its own Chrome-trace file. Legs:

  warm      → identical general-form MPS solves (solo path, the
              sparse-iterative CG engine) through the router until
              every backend's latency digest can drive a hedge delay;
  hedge     → SIGSTOP one backend and keep sending: a request routed
              to the frozen primary must hedge to the sibling — both
              legs carry the SAME trace_id as sibling spans;
  reconcile → `cli obs-agg` against the live plane: the router's hedge
              ledger, the backends' request records, and the journals'
              lifecycle counts must line up EXACTLY (checks all ok,
              forwards_total == solves sent);
  merge     → graceful drain (traces flush), then `cli obs-agg --trace`
              merges the three per-process files: the hedged request's
              trace_id must connect >= 4 spans across >= 2 processes —
              router ingress + hedge legs + backend pipeline + solver
              depth (ipm.iter / cg.solve) — in one Perfetto artifact.

Run: python scripts/probe_trace.py [--budget-s S]
Exit 0 iff every check passes.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedlpsolver_tpu.net.chaos import ChaosPlane  # noqa: E402
from distributedlpsolver_tpu.net.router import RouterConfig  # noqa: E402

# Tiny general-form LP (inequality rows -> the per-request solo path,
# pinned to the sparse-iterative backend so the solve emits CG spans).
# First solve per process compiles (~2.5 s CPU); warm solves are ~6 ms.
MPS_TEXT = """NAME          TRACEPROBE
ROWS
 N  COST
 G  R1
 G  R2
COLUMNS
    X         COST      1.0        R1        1.0
    X         R2        3.0
    Y         COST      1.0        R1        2.0
    Y         R2        1.0
RHS
    RHS       R1        3.0        R2        4.0
ENDATA
"""


def http_json(url, body=None, timeout=60.0):
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, OSError, ConnectionError, ValueError) as e:
        return 599, {"error": f"{type(e).__name__}: {e}"}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-s", type=float, default=0.0)
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()
    t_probe = time.perf_counter()

    workdir = tempfile.mkdtemp(prefix="dlps-trace-")
    plane = ChaosPlane(workdir)
    registry_path = os.path.join(workdir, "registry.json")
    route_log = os.path.join(workdir, "router.jsonl")
    traces = {
        name: os.path.join(workdir, f"{name}.trace.json")
        for name in ("router-1", "backend-a", "backend-b")
    }

    ok = True

    def fail(msg):
        nonlocal ok
        print(f"FAIL: {msg}")
        ok = False

    # -- plane: 2 solo-path backends + a hedging router ------------------
    for name in ("backend-a", "backend-b"):
        plane.spawn_backend(
            name,
            extra_flags=[
                "--flush-ms", "20", "--batch", "2",
                "--solo-backend", "sparse-iterative",
                "--trace-path", traces[name],
                "--metrics-path",
                os.path.join(workdir, f"{name}.metrics.txt"),
            ],
        )
    for name in ("backend-a", "backend-b"):
        if not plane.wait_ready(plane.procs[name], 180):
            fail(f"{name} did not come up")
            plane.shutdown_all()
            print("FAIL")
            return 1
    router = plane.spawn_router(
        "router-1",
        [plane.procs[n].url for n in ("backend-a", "backend-b")],
        registry_path,
        extra_flags=[
            "--hedge-rate-cap", "0.5",
            "--retry-budget", "50", "--retry-budget-burst", "50",
            "--log-jsonl", route_log,
            "--trace-path", traces["router-1"],
        ],
    )
    if not plane.wait_ready(router, 60):
        fail("router did not come up")
        plane.shutdown_all()
        print("FAIL")
        return 1
    print(f"plane up: 2 backends behind {router.url}")

    def statusz(url=None):
        c, o = http_json((url or router.url) + "/statusz", timeout=5.0)
        return o if c == 200 else {}

    sent = 0

    def wave(n, tenant, conc=2, timeout=90.0):
        nonlocal sent
        base = sent
        lock = threading.Lock()
        resp = []

        def one(k):
            code, out = http_json(
                router.url + "/v1/solve",
                {"mps_text": MPS_TEXT, "tenant": tenant,
                 "id": f"{tenant}-{base + k}", "tol": 1e-6},
                timeout=timeout,
            )
            with lock:
                resp.append((code, out))

        ts = []
        for k in range(n):
            t = threading.Thread(target=one, args=(k,), daemon=True)
            t.start()
            ts.append(t)
            if len(ts) % conc == 0:
                time.sleep(0.02)
        for t in ts:
            t.join(timeout=timeout + 30)
        sent += n
        return resp

    # -- warm leg: every digest must be able to drive a hedge delay ------
    need = RouterConfig().hedge_min_samples
    while sent < 60:
        resp = wave(4, "warm")
        bad = [
            (c, o) for c, o in resp
            if not (c == 200 and o.get("status") == "optimal")
        ]
        if bad:
            fail(f"warm solve failed: {bad[:3]}")
            break
        fwd = [b.get("forwards", 0) for b in statusz().get("backends", [])]
        if fwd and min(fwd) >= need:
            break
    fwd = [b.get("forwards", 0) for b in statusz().get("backends", [])]
    print(f"warm: {sent} solves; per-backend forwards={fwd} (need {need})")
    if not fwd or min(fwd) < need:
        fail(f"digests never warmed: forwards={fwd}")

    # -- hedge leg: freeze one backend, a routed request must hedge ------
    plane.sigstop("backend-a")
    print("[hedge] SIGSTOP backend-a")
    hedged = 0
    for _ in range(10):
        resp = wave(1, "hedge", timeout=60.0)
        c, o = resp[0]
        if not (c == 200 and o.get("status") == "optimal"):
            fail(f"hedge-leg solve without honest verdict: {c} {o}")
            break
        outcomes = statusz().get("hedging", {}).get("outcomes", {})
        hedged = sum(
            v for k, v in outcomes.items()
            if not k.startswith("suppressed_")
        )
        if hedged:
            break
    plane.sigcont("backend-a")
    h = statusz().get("hedging", {})
    print(
        f"[hedge] SIGCONT backend-a; launched={h.get('hedges_launched')} "
        f"outcomes={h.get('outcomes')}"
    )
    if not hedged:
        fail("no hedge ever launched against the frozen primary")

    # The thawed primary finishes its stalled leg: wait until backend
    # request records balance the router's attempt ledger.
    expect = h.get("forwards_total", 0) + h.get("hedges_launched", 0)
    deadline = time.monotonic() + 30.0
    records = -1
    while time.monotonic() < deadline:
        records = sum(
            int((statusz(plane.procs[n].url).get("stats") or {})
                .get("requests", 0))
            for n in ("backend-a", "backend-b")
        )
        if records >= expect:
            break
        time.sleep(0.2)
    print(f"[hedge] attempt ledger {expect} vs backend records {records}")

    # -- reconcile leg: obs-agg over the LIVE plane ----------------------
    agg_out = os.path.join(workdir, "agg")
    proc = subprocess.run(
        [sys.executable, "-m", "distributedlpsolver_tpu.cli", "obs-agg",
         "--registry", registry_path, "--router", router.url,
         "--out", agg_out, "--json"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if proc.returncode != 0:
        fail(f"obs-agg (live plane) exited {proc.returncode}: "
             f"{proc.stderr[-500:]}")
        fleet = {}
    else:
        fleet = json.loads(proc.stdout)
    rec = fleet.get("reconciliation", {})
    checks = {c["name"]: c for c in rec.get("checks", [])}
    print(f"[reconcile] checks="
          f"{ {k: v['status'] for k, v in checks.items()} }")
    if not rec.get("consistent"):
        fail(f"reconciliation reports drift: {rec.get('checks')}")
    for name in ("hedge_outcomes_accounted", "attempts_vs_backend_records",
                 "journal_vs_backend_records"):
        if checks.get(name, {}).get("status") != "ok":
            fail(f"reconciliation check {name} not ok: {checks.get(name)}")
    if rec.get("totals", {}).get("forwards_total") != sent:
        fail(
            f"ledger forwards_total {rec.get('totals', {}).get('forwards_total')} "
            f"!= {sent} solves sent"
        )

    # -- the hedged request's trace_id (from the router's hedge event) ---
    hedge_trace_id = None
    try:
        with open(route_log) as fh:
            for line in fh:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("event") == "hedge" and e.get("trace_id"):
                    hedge_trace_id = e["trace_id"]
    except OSError:
        pass
    if not hedge_trace_id:
        fail("no hedge event carried a trace_id in the router JSONL")

    # -- drain: flush every process's trace artifact ---------------------
    for name in ("backend-a", "backend-b"):
        http_json(plane.procs[name].url + "/quitquitquit", body={},
                  timeout=10.0)
    os.kill(router.pid, signal.SIGINT)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if all(os.path.exists(p) for p in traces.values()):
            break
        time.sleep(0.2)
    missing = [n for n, p in traces.items() if not os.path.exists(p)]
    if missing:
        fail(f"trace artifacts never flushed: {missing}")

    # -- merge leg: one connected Perfetto artifact ----------------------
    if not missing and hedge_trace_id:
        merge_out = os.path.join(workdir, "agg-merge")
        proc = subprocess.run(
            [sys.executable, "-m", "distributedlpsolver_tpu.cli",
             "obs-agg", "--out", merge_out, "--json"]
            + [a for n in traces for a in ("--trace", f"{n}={traces[n]}")],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            fail(f"obs-agg (merge) exited {proc.returncode}: "
                 f"{proc.stderr[-500:]}")
        else:
            fleet2 = json.loads(proc.stdout)
            summary = (fleet2.get("trace_summary") or {}).get(
                hedge_trace_id, {}
            )
            names = summary.get("names", [])
            print(
                f"[merge] trace {hedge_trace_id}: {summary.get('spans')} "
                f"spans across {summary.get('processes')} processes"
            )
            if summary.get("spans", 0) < 4:
                fail(f"hedged trace has {summary.get('spans')} spans (<4)")
            if summary.get("processes", 0) < 2:
                fail(
                    f"hedged trace crossed {summary.get('processes')} "
                    f"process(es) (<2)"
                )
            if not any(n.startswith("route.") for n in names):
                fail(f"no router span in the hedged trace: {names}")
            if not any(
                n.startswith("ipm.") or n.startswith("cg.") for n in names
            ):
                fail(f"no solver-depth span in the hedged trace: {names}")
            merged_path = os.path.join(merge_out, "trace_merged.json")
            try:
                with open(merged_path) as fh:
                    merged = json.load(fh)
                evs = merged["traceEvents"]
                flows = [
                    e for e in evs
                    if e.get("cat") == "trace_flow"
                    and (e.get("args") or {}).get("trace_id")
                    == hedge_trace_id
                ]
                if not (
                    any(e["ph"] == "s" for e in flows)
                    and any(e["ph"] == "f" for e in flows)
                ):
                    fail(
                        f"hedged trace has no complete flow chain "
                        f"({[e.get('ph') for e in flows]})"
                    )
                else:
                    print(
                        f"[merge] {len(evs)} events, flow chain of "
                        f"{len(flows)} over {merged_path}"
                    )
            except (OSError, ValueError, KeyError) as e:
                fail(f"merged Perfetto artifact unreadable: {e}")

    plane.shutdown_all()
    if not args.keep_workdir and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        print(f"workdir kept for post-mortem: {workdir}")

    probe_wall = time.perf_counter() - t_probe
    if args.budget_s and probe_wall > args.budget_s:
        fail(f"probe took {probe_wall:.1f}s > budget {args.budget_s:.0f}s")
    print(f"probe wall: {probe_wall:.1f}s")
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
