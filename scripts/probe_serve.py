"""Serve-layer load probe: drive the async batching SolveService with a
randomly-shaped request stream on the 8-virtual-CPU-device rig and print
the service's own telemetry — the fastest way to see (and demo)
continuous batching, mesh-sharded bucket dispatch, the pipelined
pack/solve overlap, deadline handling, fault recovery, and the
zero-recompile warm path without TPU hardware.

Run: python scripts/probe_serve.py [--requests N] [--quick]
                                   [--mesh-devices K] [--budget-s S]
Exit 0 iff every in-deadline request is OPTIMAL, the doomed-deadline
request is TIMEOUT, the injected batch fault is recovered, a second warm
wave compiles nothing, the dispatch timing report shows nonzero
pack/solve overlap (full-size probe only — a handful of quick-mode
dispatches can legitimately serialize), the correlated-stream leg hits
the warm cache with median warm iterations STRICTLY below cold at zero
extra compiles (the warm-start & amortization layer's acceptance), and
the wall clock fits the --budget-s envelope when one is given (the
tier-1 serving-throughput regression guard).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distributedlpsolver_tpu.backends.batched import bucket_cache_size  # noqa: E402
from distributedlpsolver_tpu.ipm import Status  # noqa: E402
from distributedlpsolver_tpu.models.generators import (  # noqa: E402
    correlated_request_stream,
    random_request_stream,
)
from distributedlpsolver_tpu.serve import ServiceConfig, SolveService  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--quick", action="store_true", help="small smoke load")
    ap.add_argument(
        "--mesh-devices", type=int, default=2,
        help="batch-axis mesh width for bucket dispatches (0 = unsharded)",
    )
    ap.add_argument(
        "--budget-s", type=float, default=0.0,
        help="fail if the whole probe exceeds this wall time (0 = no "
        "budget) — the tier-1 serving-throughput envelope",
    )
    ap.add_argument(
        "--metrics-path", default=None,
        help="write a Prometheus-text metrics snapshot here and fail "
        "unless it is produced and non-trivial",
    )
    ap.add_argument(
        "--trace-path", default=None,
        help="write a Chrome-trace JSON here and fail unless it loads "
        "and holds a connected cross-thread request track",
    )
    args = ap.parse_args()
    t_probe = time.perf_counter()
    n = 24 if args.quick else args.requests
    print(f"devices: {len(jax.devices())} × {jax.devices()[0].platform}")

    injected = []

    def injector(seq, key):
        if seq == 1 and not injected:  # fault exactly one dispatch, once
            injected.append(seq)
            raise RuntimeError("probe-injected batch fault")

    cfg = ServiceConfig(
        batch=8, flush_s=0.02, fault_injector=injector,
        mesh_devices=args.mesh_devices,
        metrics_path=args.metrics_path,
        trace_path=args.trace_path,
    )
    with SolveService(cfg) as svc:
        t0 = time.perf_counter()
        futs = [svc.submit(p) for p in random_request_stream(n, seed=7)]
        doomed = svc.submit(
            next(random_request_stream(1, seed=99)), deadline=1e-4,
            name="doomed",
        )
        svc.drain(timeout=600)
        wall = time.perf_counter() - t0
        results = [f.result(timeout=10) for f in futs]
        doomed_r = doomed.result(timeout=10)

        # Warm wave: same shapes again — zero recompiles expected.
        cache0 = bucket_cache_size()
        t1 = time.perf_counter()
        n_warm = 16 if args.quick else max(16, n // 2)
        warm = [svc.submit(p) for p in random_request_stream(n_warm, seed=8)]
        svc.drain(timeout=600)
        warm_wall = time.perf_counter() - t1
        warm_r = [f.result(timeout=10) for f in warm]
        recompiles = bucket_cache_size() - cache0

        # Correlated-stream leg (warm-start & amortization layer): a
        # seeded same-models/perturbed-b/c stream, a cold leg that
        # populates the fingerprint cache, then a steady-state leg that
        # must (a) hit the cache, (b) cut the median iterations-per-
        # request strictly below cold, and (c) compile nothing.
        n_corr = 16 if args.quick else 48
        legs = [
            svc.submit(p)
            for p in correlated_request_stream(n_corr, seed=31)
        ]
        svc.drain(timeout=600)
        corr_cold = [f.result(timeout=10) for f in legs]
        cache1 = bucket_cache_size()
        legs = [
            svc.submit(p)
            for p in correlated_request_stream(
                n_corr, seed=31, offset=n_corr
            )
        ]
        svc.drain(timeout=600)
        corr_warm = [f.result(timeout=10) for f in legs]
        corr_recompiles = bucket_cache_size() - cache1
        stats = svc.stats()
        report = svc.dispatch_report()

    n_opt = sum(r.status is Status.OPTIMAL for r in results + warm_r)
    overlapped = [r for r in report if r["overlap_ms"] > 0]
    print(
        f"wave 1: {len(results)} requests in {wall:.2f}s "
        f"({len(results) / wall:.1f} rps incl. compile); warm wave: "
        f"{len(warm_r)} in {warm_wall:.2f}s ({len(warm_r) / warm_wall:.1f} rps)"
    )
    print(
        f"  p50={stats['latency_ms_p50']:.0f}ms p95={stats['latency_ms_p95']:.0f}ms "
        f"p99={stats['latency_ms_p99']:.0f}ms "
        f"padding_waste={stats['mean_padding_waste']:.2f} "
        f"buckets={stats['buckets']} mesh_devices={stats['mesh_devices']}"
    )
    print(
        f"  pipeline: {len(report)} dispatches, pack {stats['pack_ms_total']:.1f}ms "
        f"total, overlap {stats['overlap_ms_total']:.1f}ms total "
        f"({len(overlapped)} dispatches overlapped a pack)"
    )
    print(
        f"  idle: {stats['idle']['waits']} waits, "
        f"{stats['idle']['sleep_s']:.2f}s slept (event-driven, no poll tick)"
    )
    print(
        f"  doomed deadline: {doomed_r.status.value}; injected faults "
        f"recovered: {len(injected)}; warm-wave recompiles: {recompiles}"
    )
    # Correlated-leg verdicts: nonzero cache-hit ratio, median warm
    # iterations STRICTLY below cold on the same stream, honest 1e-8
    # verdicts throughout, zero warm recompiles.
    import numpy as np

    warm_hits = [r for r in corr_warm if r.warm == "warm"]
    hit_ratio = len(warm_hits) / max(len(corr_warm), 1)
    med_warm = float(np.median([r.iterations for r in warm_hits])) if warm_hits else 0.0
    cold_iters = [r.iterations for r in corr_cold if r.warm != "warm"]
    med_cold = float(np.median(cold_iters)) if cold_iters else 0.0
    corr_opt = sum(
        r.status is Status.OPTIMAL for r in corr_cold + corr_warm
    )
    print(
        f"  correlated stream: {len(corr_cold)}+{len(corr_warm)} requests, "
        f"cache hit ratio {hit_ratio:.0%}, median iters "
        f"{med_cold:.0f} cold -> {med_warm:.0f} warm, "
        f"recompiles {corr_recompiles}, "
        f"warm_cache={stats['warm_cache']}"
    )
    probe_wall = time.perf_counter() - t_probe
    ok = (
        n_opt == len(results) + len(warm_r)
        and doomed_r.status is Status.TIMEOUT
        and len(injected) == 1
        and recompiles == 0
    )
    if corr_opt != len(corr_cold) + len(corr_warm):
        print("FAIL: correlated-stream requests not all OPTIMAL")
        ok = False
    if hit_ratio <= 0.0:
        print("FAIL: correlated stream produced no warm-cache hits")
        ok = False
    if not (med_warm < med_cold):
        print(
            f"FAIL: median warm iterations ({med_warm}) not strictly "
            f"below cold ({med_cold})"
        )
        ok = False
    if corr_recompiles != 0:
        print(f"FAIL: warm leg compiled {corr_recompiles} programs")
        ok = False
    if not args.quick:
        # Acceptance: the pipelined dispatcher must actually overlap host
        # pack with device solve under sustained load.
        if stats["overlap_ms_total"] <= 0.0:
            print("FAIL: no pack/solve overlap recorded under load")
            ok = False
    if args.budget_s and probe_wall > args.budget_s:
        print(
            f"FAIL: probe took {probe_wall:.1f}s > budget {args.budget_s:.0f}s"
        )
        ok = False
    # Observability artifacts (written at service shutdown): both must
    # exist and be VALID, not just present — the tier-1 smoke relies on
    # this probe to prove the obs layer end-to-end without TPU hardware.
    if args.metrics_path:
        try:
            text = open(args.metrics_path).read()
            n_samples = sum(
                1 for l in text.splitlines() if l and not l.startswith("#")
            )
            assert "serve_dispatches_total" in text
            assert "serve_requests_total" in text
            print(f"  metrics snapshot: {n_samples} samples "
                  f"-> {args.metrics_path}")
        except Exception as e:
            print(f"FAIL: metrics snapshot invalid: {e}")
            ok = False
    if args.trace_path:
        try:
            import json

            trace = json.load(open(args.trace_path))
            events = trace["traceEvents"]
            # ≥1 connected cross-thread request track: some request id
            # whose async begin/end events span more than one thread.
            by_id = {}
            for e in events:
                if e.get("cat") == "request" and e.get("ph") in ("b", "e"):
                    by_id.setdefault(e["id"], []).append(e)
            connected = [
                rid for rid, evs in by_id.items()
                if len({e["tid"] for e in evs}) > 1
                and sum(e["ph"] == "b" for e in evs)
                == sum(e["ph"] == "e" for e in evs)
            ]
            assert connected, "no cross-thread request track"
            print(
                f"  trace: {len(events)} events, {len(by_id)} request "
                f"tracks ({len(connected)} cross-thread) -> "
                f"{args.trace_path}"
            )
        except Exception as e:
            print(f"FAIL: trace invalid: {e}")
            ok = False
    print(f"probe wall: {probe_wall:.1f}s")
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
