"""Serve-layer load probe: drive the async batching SolveService with a
randomly-shaped request stream on the 8-virtual-CPU-device rig and print
the service's own telemetry — the fastest way to see (and demo)
continuous batching, deadline handling, fault recovery, and the
zero-recompile warm path without TPU hardware.

Run: python scripts/probe_serve.py [--requests N] [--quick]
Exit 0 iff every in-deadline request is OPTIMAL, the doomed-deadline
request is TIMEOUT, the injected batch fault is recovered, and a second
warm wave compiles nothing.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distributedlpsolver_tpu.backends.batched import bucket_cache_size  # noqa: E402
from distributedlpsolver_tpu.ipm import Status  # noqa: E402
from distributedlpsolver_tpu.models.generators import (  # noqa: E402
    random_request_stream,
)
from distributedlpsolver_tpu.serve import ServiceConfig, SolveService  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--quick", action="store_true", help="small smoke load")
    args = ap.parse_args()
    n = 24 if args.quick else args.requests
    print(f"devices: {len(jax.devices())} × {jax.devices()[0].platform}")

    injected = []

    def injector(seq, key):
        if seq == 1 and not injected:  # fault exactly one dispatch, once
            injected.append(seq)
            raise RuntimeError("probe-injected batch fault")

    cfg = ServiceConfig(
        batch=8, flush_s=0.02, fault_injector=injector,
    )
    with SolveService(cfg) as svc:
        t0 = time.perf_counter()
        futs = [svc.submit(p) for p in random_request_stream(n, seed=7)]
        doomed = svc.submit(
            next(random_request_stream(1, seed=99)), deadline=1e-4,
            name="doomed",
        )
        svc.drain(timeout=600)
        wall = time.perf_counter() - t0
        results = [f.result(timeout=10) for f in futs]
        doomed_r = doomed.result(timeout=10)

        # Warm wave: same shapes again — zero recompiles expected.
        cache0 = bucket_cache_size()
        warm = [svc.submit(p) for p in random_request_stream(16, seed=8)]
        svc.drain(timeout=600)
        warm_r = [f.result(timeout=10) for f in warm]
        recompiles = bucket_cache_size() - cache0
        stats = svc.stats()

    n_opt = sum(r.status is Status.OPTIMAL for r in results + warm_r)
    print(
        f"wave 1: {len(results)} requests in {wall:.2f}s "
        f"({len(results) / wall:.1f} rps incl. compile)"
    )
    print(
        f"  p50={stats['latency_ms_p50']:.0f}ms p95={stats['latency_ms_p95']:.0f}ms "
        f"padding_waste={stats['mean_padding_waste']:.2f} "
        f"buckets={stats['buckets']}"
    )
    print(
        f"  doomed deadline: {doomed_r.status.value}; injected faults "
        f"recovered: {len(injected)}; warm-wave recompiles: {recompiles}"
    )
    ok = (
        n_opt == len(results) + len(warm_r)
        and doomed_r.status is Status.TIMEOUT
        and len(injected) == 1
        and recompiles == 0
    )
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
