"""TPU A/B: default two-phase (f64 phase 2) vs PCG phase 2 at a given shape."""
import sys, time
import numpy as np
sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import random_dense_lp

m, n = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (1024, 4096)
modes = sys.argv[3].split(",") if len(sys.argv) > 3 else ["pcg", "direct"]
p = random_dense_lp(m, n, seed=0)
print(f"shape {m}x{n}", flush=True)
for mode in modes:
    t0 = time.perf_counter()
    r = solve(p, backend="tpu", solve_mode=mode, max_iter=3)  # warm-up: compile
    t_warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    r = solve(p, backend="tpu", solve_mode=mode)
    t = time.perf_counter() - t0
    print(f"mode={mode}: {r.status.name} obj={r.objective:.6f} iters={r.iterations} "
          f"gap={r.rel_gap:.2e} pinf={r.pinf:.2e} dinf={r.dinf:.2e} "
          f"solve={r.solve_time:.2f}s total={t:.2f}s warmup={t_warm:.1f}s", flush=True)
