"""neos3-class UNSTRUCTURED sparse row (VERDICT round-4 item 6 /
BASELINE.json:10): a sparse LP whose random pattern defeats block-angular
detection, measured through BOTH candidate executors at 1e-8 —
`cpu-sparse` (the sparse-direct host path the auto rule routes to) and
`pdlp` (the TPU first-order backend) — so the routing decision is a
recorded measurement instead of an implicit default.

Writes /root/repo/.neos3_sparse.json.
"""
import json, resource, sys, time

sys.path.insert(0, "/root/repo")

# Measurement envelope: `--require-tpu` aborts (exit 4) instead of
# silently measuring host CPU when the accelerator is missing (the
# BENCH_r05 failure class).
from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]
import numpy as np

m, n, density = 20000, 40000, 0.0005
from distributedlpsolver_tpu.models.generators import random_sparse_lp
from distributedlpsolver_tpu.models.problem import to_interior_form
from distributedlpsolver_tpu.models.structure import detect_block_structure
from distributedlpsolver_tpu.ipm import solve

p = random_sparse_lp(m, n, density=density, seed=0)
inf = to_interior_form(p)
print(f"built {p.A.shape}, nnz={p.A.nnz}", flush=True)
t0 = time.time()
hint = detect_block_structure(inf.A)
t_detect = time.time() - t0
print(f"detection: {hint if hint is None else 'FOUND ' + str(hint.get('num_blocks'))} "
      f"in {t_detect:.2f}s", flush=True)

out = {"config": f"unstructured sparse {m}x{n} d={density} seed=0 (neos3-class, B:10)",
       "nnz": int(p.A.nnz), "detection": None if hint is None else int(hint["num_blocks"]),
       "detect_s": round(t_detect, 3), "tol": 1e-8}

# ---- pdlp on TPU at 1e-8 (bounded budget; record where it lands) ------
import jax
if jax.default_backend() == "tpu":
    try:
        r1 = solve(p, backend="pdlp", tol=1e-4, max_iter=200000)  # warm
        t0 = time.time()
        rp = solve(p, backend="pdlp", tol=1e-8, max_iter=400000)
        out["pdlp"] = {
            "status": rp.status.value, "time_s": round(time.time() - t0, 2),
            "rel_gap": float(rp.rel_gap), "pinf": float(rp.pinf),
            "dinf": float(rp.dinf), "iters": int(rp.iterations),
            "note": "TPU restarted PDHG; 1e-8 target",
        }
    except Exception as e:  # a worker crash must not sink the CPU half
        out["pdlp"] = {"failed": f"{type(e).__name__}: {str(e)[:300]}"}
    print("pdlp:", out["pdlp"], flush=True)

# ---- cpu-sparse at 1e-8 (quiet host required) -------------------------
u0 = resource.getrusage(resource.RUSAGE_SELF)
t0 = time.time()
rc = solve(p, backend="cpu-sparse", max_iter=120)
wall = time.time() - t0
u1 = resource.getrusage(resource.RUSAGE_SELF)
out["cpu_sparse"] = {
    "status": rc.status.value, "time_s": round(rc.solve_time, 2),
    "wall_s": round(wall, 2),
    "process_cpu_s": round((u1.ru_utime - u0.ru_utime) + (u1.ru_stime - u0.ru_stime), 2),
    "objective": rc.objective, "iters": int(rc.iterations),
    "rel_gap": float(rc.rel_gap),
}
print("cpu-sparse:", out["cpu_sparse"], flush=True)

# ---- the recorded routing decision ------------------------------------
pd = out.get("pdlp", {})
winner = "cpu-sparse"
if pd.get("status") == "optimal" and pd.get("time_s", 1e30) < out["cpu_sparse"]["time_s"]:
    winner = "pdlp"
out["route_at_1e-8"] = winner
out["routing_rule"] = (
    "auto routes hint-less sparse (detection finds nothing) to cpu-sparse; "
    "measured here against pdlp at the same 1e-8 target"
)
with open("/root/repo/.neos3_sparse.json", "w") as fh:
    json.dump(out, fh, indent=1)
print("wrote .neos3_sparse.json; winner:", winner, flush=True)
