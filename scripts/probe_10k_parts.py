"""Properly-timed primitives at reference scale (inputs varied per rep to
defeat any remote execution caching)."""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.backends import dense as D
from distributedlpsolver_tpu.ops import normal_eq_pallas, pad_for_pallas

m, n = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (10000, 50000)
rng = np.random.default_rng(0)
print(f"shape {m}x{n}", flush=True)

# Build an SPD M once on device from a thin factor (avoid 800MB host xfer)
B64 = jnp.asarray(rng.standard_normal((m, 2048)) / 45.0, dtype=jnp.float64)
mk = jax.jit(lambda B, eps: B @ B.T + (1.0 + eps) * jnp.eye(m, dtype=B.dtype))
rhs = jnp.asarray(rng.standard_normal(m), dtype=jnp.float64)

def tme(label, fn, argf, reps=3):
    t0 = time.perf_counter(); r = jax.block_until_ready(fn(*argf(0))); t1 = time.perf_counter()
    ts = []
    for i in range(1, reps + 1):
        t2 = time.perf_counter(); r = jax.block_until_ready(fn(*argf(i))); ts.append(time.perf_counter() - t2)
    print(f"{label}: compile+first={t1-t0:.1f}s steady={min(ts):.3f}s", flush=True)
    return r

M = jax.block_until_ready(mk(B64, 0.0))
chol = jax.jit(jnp.linalg.cholesky)
L64 = tme("f64 cholesky m=%d" % m, chol, lambda i: (mk(B64, 1e-7 * i),))
cs = jax.jit(lambda L, r: jax.scipy.linalg.cho_solve((L, True), r))
tme("f64 cho_solve 1rhs", cs, lambda i: (L64, rhs + i), reps=3)

chol32 = jax.jit(lambda M: jnp.linalg.cholesky(M.astype(jnp.float32)))
L32 = tme("f32 cholesky", chol32, lambda i: (mk(B64, 1e-7 * i),))
cs32 = jax.jit(lambda L, r: jax.scipy.linalg.cho_solve((L, True), r.astype(jnp.float32)))
tme("f32 cho_solve 1rhs", cs32, lambda i: (L32, rhs + i), reps=3)
del M

# assembly pieces at m x n
A64 = jnp.asarray(rng.standard_normal((m, n)) / np.sqrt(n), dtype=jnp.float64)
Af = pad_for_pallas(A64.astype(jnp.float32))
d64 = jnp.asarray(10.0 ** rng.uniform(-5, 5, size=n), dtype=jnp.float64)
pasm = jax.jit(lambda Af, d: normal_eq_pallas(Af, d.astype(jnp.float32), out_m=m))
tme("pallas f32 assembly", pasm, lambda i: (Af, d64 + i))
gemv = jax.jit(lambda v: D._matvec_chunked(A64, d64 * D._rmatvec_chunked(A64, v)))
tme("f64 chunked GEMV pair", gemv, lambda i: (rhs + i,), reps=5)
asm64 = jax.jit(lambda d: D._normal_eq_chunked(A64, d))
tme("f64 chunked assembly", asm64, lambda i: (d64 + i,), reps=1)
print("PROBE DONE", flush=True)
