"""Multi-host acceptance probe: a router over TWO SLICES — each a
2-process `jax.distributed` world serving one HTTP front-end over its
global mesh — with one slice killed mid-run and recovered by a
coordinator-level world re-initialization (README "Multi-host").

Topology (all on this machine — the single-machine harness maps 1:1
onto two TPU pod slices):

    router (cli route --registry R --registry-ttl-s T)   ← no --backend!
      ├─ slice A: cli serve-slice --world-size 2  (self-registers in R)
      └─ slice B: cli serve-slice --world-size 2  (self-registers in R)

Checks:
  - the router adopts both slices from the shared registry with ZERO
    manual backend config (slice self-registration);
  - requests routed through both slices solve OPTIMAL on the slices'
    multi-process meshes;
  - mid-run, one rank of slice B is SIGKILLed: the whole world dies as
    a unit (coordination-service semantics), the router ejects B
    (failed probe and/or registry heartbeat TTL), traffic keeps
    flowing through A with ZERO lost acknowledged requests;
  - slice B's supervisor re-initializes a SMALLER world (size 1) on
    the same port + journal (a `world_reinit` event with
    `recovery_overhead_s` lands in its world.jsonl), the router
    re-admits it, and it serves again;
  - every async poll URL minted BEFORE the kill resolves honestly
    after recovery (journal replay; router async fan-out);
  - zero warm recompiles at steady state on every surviving front-end
    (programs_compiled flat across a verification wave).

Run: python scripts/probe_multihost.py [--requests N] [--budget-s S]
Exit 0 iff every check passes.
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SHAPE = (8, 24)  # one bucket; process startup, not solving, is the cost
BUCKET = {"m": 8, "n": 24, "batch": 8}


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def http_json(url, body=None, timeout=60.0):
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, OSError, ConnectionError, ValueError):
        return None, {}


def wait_200(url, budget, alive=lambda: True):
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        st, _ = http_json(url, timeout=2.0)
        if st == 200:
            return True
        if not alive():
            return False
        time.sleep(0.2)
    return False


def spawn_slice(workdir, name, port, registry, ladder):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each rank pins its own device count
    log = open(os.path.join(workdir, f"{name}.log"), "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributedlpsolver_tpu.cli",
            "serve-slice",
            "--world-size", "2",
            "--local-devices", "2",
            "--port", str(port),
            "--slice-id", name,
            "--registry", registry,
            "--heartbeat-s", "0.25",
            "--slice-workdir", os.path.join(workdir, f"{name}-world"),
            "--journal-dir", os.path.join(workdir, f"{name}-journal"),
            "--buckets", ladder,
            "--warm-buckets",
            "--batch", "8",
            "--flush-ms", "20",
            "--quiet",
        ],
        stdout=log, stderr=subprocess.STDOUT, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return proc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--budget-s", type=float, default=420.0)
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()
    t_start = time.monotonic()
    workdir = tempfile.mkdtemp(prefix="dlps-probe-multihost-")
    registry = os.path.join(workdir, "registry.json")
    ladder = os.path.join(workdir, "ladder.json")
    with open(ladder, "w") as fh:
        fh.write(json.dumps([BUCKET]))
    procs = {}
    failures = []

    def check(ok, what):
        tag = "ok" if ok else "FAIL"
        print(f"  [{tag}] {what}")
        if not ok:
            failures.append(what)

    try:
        pa, pb, pr = free_port(), free_port(), free_port()
        ua, ub = f"http://127.0.0.1:{pa}", f"http://127.0.0.1:{pb}"
        procs["sliceA"] = spawn_slice(workdir, "sliceA", pa, registry, ladder)
        procs["sliceB"] = spawn_slice(workdir, "sliceB", pb, registry, ladder)
        for name, url in (("sliceA", ua), ("sliceB", ub)):
            ok = wait_200(
                url + "/healthz", 180,
                alive=lambda n=name: procs[n].poll() is None,
            )
            check(ok, f"{name} world up and serving on its global mesh")
        if failures:
            return 1

        # Router learns both slices from the registry alone.
        rlog = open(os.path.join(workdir, "router.log"), "ab")
        procs["router"] = subprocess.Popen(
            [
                sys.executable, "-m", "distributedlpsolver_tpu.cli",
                "route",
                "--registry", registry,
                "--registry-ttl-s", "2.0",
                "--poll-s", "0.25",
                "--port", str(pr),
            ],
            stdout=rlog, stderr=subprocess.STDOUT, env=dict(os.environ),
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        router = f"http://127.0.0.1:{pr}"
        deadline = time.monotonic() + 60
        adopted = False
        while time.monotonic() < deadline:
            st, o = http_json(router + "/statusz", timeout=2.0)
            if st == 200:
                healthy = [
                    b for b in (o.get("backends") or [])
                    if b.get("healthy")
                ]
                if len(healthy) >= 2:
                    adopted = True
                    break
            time.sleep(0.25)
        check(adopted, "router adopted both slices from the registry "
                       "(no --backend config)")
        if not adopted:
            return 1

        # ---- request stream with a mid-run slice-B kill --------------
        n = args.requests
        kill_at = n // 2
        sync_ok = 0
        rejects = 0
        async_ids = []
        killed_ts = None
        m, nn = SHAPE
        for i in range(n):
            if i == kill_at:
                # SIGKILL one RANK of slice B: the whole world must die
                # as a unit; the supervisor then re-initializes a
                # world of 1 on the same port + journal.
                hb = json.load(
                    open(os.path.join(
                        workdir, "sliceB-world", "hb-gen0", "rank1.hb"
                    ))
                )
                os.kill(hb["pid"], signal.SIGKILL)
                killed_ts = time.monotonic()
                print(f"  -- killed sliceB rank1 (pid {hb['pid']}) "
                      f"at request {i}")
            body = {"m": m, "n": nn, "seed": 100 + i, "tol": 1e-8}
            # Honest rejects are NOT lost acks: while a slice world
            # re-initializes the router may 503 (empty rotation) — the
            # contract is that a retrying client is never LIED to, so
            # each request retries until acknowledged (200/202) within
            # its own window.
            is_async = i % 6 == 5
            if is_async:
                body["async"] = True
            acked = False
            deadline_i = time.monotonic() + 90
            while time.monotonic() < deadline_i:
                st, o = http_json(router + "/v1/solve", body, timeout=120)
                if is_async and st == 202 and o.get("id"):
                    async_ids.append(o["id"])
                    acked = True
                    break
                if st == 200 and o.get("status") == "optimal":
                    sync_ok += 1
                    acked = True
                    break
                if st in (503, None) or (st == 429):
                    rejects += 1
                    time.sleep(0.5)
                    continue
                break  # anything else is a hard failure for this request
            if not acked:
                check(False, f"request {i}: {st} {o.get('status')}")
        check(sync_ok == n - len(async_ids),
              f"zero lost acknowledged sync requests across the kill "
              f"({sync_ok} optimal, {rejects} honest rejects retried)")

        # ---- slice B ejected, then re-initialized + re-admitted ------
        deadline = time.monotonic() + 180
        readmitted = False
        while time.monotonic() < deadline:
            st, o = http_json(router + "/statusz", timeout=2.0)
            if st == 200:
                b = next(
                    (x for x in (o.get("backends") or [])
                     if x.get("url") == ub),
                    {},
                )
                if b.get("healthy"):
                    readmitted = True
                    break
            time.sleep(0.3)
        check(
            readmitted,
            "slice B re-initialized (smaller world) and re-admitted "
            + (f"({time.monotonic() - killed_ts:.1f}s after kill)"
               if killed_ts else ""),
        )
        wr_path = os.path.join(workdir, "sliceB-world", "world.jsonl")
        reinits = []
        if os.path.exists(wr_path):
            reinits = [
                json.loads(line)
                for line in open(wr_path)
                if '"world_reinit"' in line
            ]
        check(
            bool(reinits)
            and reinits[0].get("world_size") == 1
            and reinits[0].get("recovery_overhead_s", -1) >= 0,
            f"world_reinit event with recovery_overhead_s "
            f"({[ (r.get('world_size'), r.get('recovery_overhead_s')) for r in reinits ]})",
        )

        # ---- every pre/post-kill async poll URL resolves honestly ----
        resolved = 0
        for jid in async_ids:
            got = None
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                st, o = http_json(
                    f"{router}/v1/solve/{jid}", timeout=5.0
                )
                if st == 200 and o.get("status"):
                    got = o["status"]
                    break
                if st == 404:
                    break
                time.sleep(0.4)
            if got in ("optimal", "timeout"):
                resolved += 1
        check(
            resolved == len(async_ids),
            f"all {len(async_ids)} async poll URLs resolve honestly "
            f"after recovery ({resolved} resolved)",
        )

        # ---- zero warm recompiles at steady state --------------------
        snaps = {}
        for name, url in (("sliceA", ua), ("sliceB", ub)):
            st, o = http_json(url + "/statusz", timeout=5.0)
            if st == 200:
                snaps[name] = int(
                    (o.get("stats") or {}).get("programs_compiled", -1)
                )
        for i in range(4):
            http_json(
                router + "/v1/solve",
                {"m": m, "n": nn, "seed": 900 + i, "tol": 1e-8},
                timeout=120,
            )
        flat = True
        for name, url in (("sliceA", ua), ("sliceB", ub)):
            st, o = http_json(url + "/statusz", timeout=5.0)
            after = int(
                (o.get("stats") or {}).get("programs_compiled", -2)
            ) if st == 200 else -2
            if after != snaps.get(name):
                flat = False
        check(flat, f"zero warm recompiles at steady state ({snaps})")

        # Registry TTL machinery was live for the whole run.
        reg = json.load(open(registry))
        hb_entries = [
            e for e in reg.get("backends", {}).values()
            if e.get("last_heartbeat_ts")
        ]
        check(
            len(hb_entries) >= 2,
            "both slices heartbeat into the shared registry",
        )

        wall = time.monotonic() - t_start
        print(
            f"probe_multihost: {len(failures)} failures, "
            f"{n} requests, wall {wall:.1f}s"
        )
        if args.budget_s and wall > args.budget_s:
            print(f"FAIL: wall {wall:.1f}s exceeded budget {args.budget_s}s")
            return 1
        return 1 if failures else 0
    finally:
        for p in procs.values():
            try:
                p.send_signal(signal.SIGINT)
            except Exception:
                pass
        time.sleep(1.0)
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=15)
            except Exception:
                pass
        # Rank children are not in our process table: kill via their
        # heartbeat pids so nothing lingers after the probe.
        for side in ("sliceA-world", "sliceB-world"):
            base = os.path.join(workdir, side)
            if os.path.isdir(base):
                for d in os.listdir(base):
                    if d.startswith("hb-gen"):
                        for f in os.listdir(os.path.join(base, d)):
                            try:
                                hb = json.load(
                                    open(os.path.join(base, d, f))
                                )
                                os.kill(int(hb["pid"]), signal.SIGKILL)
                            except Exception:
                                pass
        if not args.keep:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"kept workdir: {workdir}")


if __name__ == "__main__":
    sys.exit(main())
