"""Chaos acceptance probe: drive a live MULTI-PROCESS serving plane —
2 replicated routers over a shared registry, 2 journal-backed backends
— through a seeded fault schedule and assert the crash-safe fabric's
invariant end to end (README "Durability & graceful shutdown"):

  **no acknowledged request is ever lost** — every 200/202 the plane
  hands out resolves to an honest terminal verdict after recovery.

Seeded schedule (net/chaos.ChaosSchedule.seeded, fractions of the
200-request / 2-tenant stream):

  ~10%  SIGSTOP backend B for a beat, then SIGCONT (slow-backend stall)
  ~20%  kill -9 backend B       (router failover keeps traffic moving)
  ~35%  restart backend B       (journal replay #1)
  ~50%  kill -9 backend A's front-end (the one with an injected
        journal-write fault earlier in its life)
  ~55%  truncate backend A's WAL tail (torn record, crash-mid-write)
  ~58%  restart backend A       (journal replay #2 over the torn WAL)
  ~75%  kill -9 router 2        (router 1 + the shared registry carry on)

Checks:
  - every sync request ends 200/504-stamped (an honest verdict), every
    async 202's id eventually resolves — including ids minted by a
    backend that was later killed (journal re-binds them) and polled
    through the surviving router (fan-out + registry);
  - zero duplicate solves across both journals (fingerprint-idempotent
    replay; a torn `finished` record must not re-run its job);
  - zero FAILED verdicts;
  - zero warm recompiles at steady state: after recovery, a
    verification wave leaves every live backend's programs_compiled
    untouched;
  - the injected journal-write fault degraded durability, not serving
    (backend A's journal counts ≥1 write error pre-kill);
  - graceful drain: /quitquitquit on a loaded backend resolves every
    in-flight request, flips /readyz to 503 while /healthz stays 200,
    and closes the listener only after the drain.

Run: python scripts/probe_chaos.py [--requests N] [--seed S] [--budget-s S]
Exit 0 iff every check passes.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedlpsolver_tpu.net.chaos import (  # noqa: E402
    ChaosEvent,
    ChaosPlane,
    ChaosSchedule,
    free_port,
    journal_duplicate_solves,
)

SHAPE = (8, 24)  # one bucket: process startup, not solving, is the cost


def http_json(url, body=None, timeout=30.0):
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, OSError, ConnectionError, ValueError) as e:
        return 599, {"error": f"{type(e).__name__}: {e}"}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument(
        "--budget-s", type=float, default=0.0,
        help="fail if the whole probe exceeds this wall time (0 = none)",
    )
    ap.add_argument(
        "--keep-workdir", action="store_true",
        help="leave the journals/logs behind for post-mortem",
    )
    args = ap.parse_args()
    t_probe = time.perf_counter()

    workdir = tempfile.mkdtemp(prefix="dlps-chaos-")
    plane = ChaosPlane(workdir)
    registry_path = os.path.join(workdir, "registry.json")
    buckets_json = os.path.join(workdir, "ladder.json")
    with open(buckets_json, "w") as fh:
        fh.write(json.dumps([{"m": SHAPE[0], "n": SHAPE[1], "batch": 4}]))

    ok = True

    def fail(msg):
        nonlocal ok
        print(f"FAIL: {msg}")
        ok = False

    # -- spawn the plane (fixed ports: restarts and poll URLs need them)
    pa, pb = free_port(), free_port()
    common = ["--flush-ms", "20", "--batch", "4", "--queue-depth", "256"]
    a = plane.spawn_backend(
        "backend-a", port=pa, buckets_json=buckets_json,
        extra_flags=common,
        # Injected journal fault: the 40th WAL append raises once —
        # durability degrades, serving must not.
        extra_env={"DLPS_JOURNAL_FAIL_AFTER": "40"},
    )
    b = plane.spawn_backend(
        "backend-b", port=pb, buckets_json=buckets_json, extra_flags=common,
    )
    t0 = time.perf_counter()
    if not (plane.wait_ready(a, 180) and plane.wait_ready(b, 180)):
        fail("backends did not come up")
        print("FAIL")
        return 1
    print(
        f"backends up in {time.perf_counter() - t0:.1f}s: {a.url} {b.url}"
    )
    r1 = plane.spawn_router("router-1", [a.url, b.url], registry_path)
    r2 = plane.spawn_router("router-2", [a.url, b.url], registry_path)
    if not (plane.wait_ready(r1, 60) and plane.wait_ready(r2, 60)):
        fail("routers did not come up")
        print("FAIL")
        return 1
    print(f"routers up: {r1.url} {r2.url} (registry: {registry_path})")

    # Schedule: the seeded acceptance faults plus a short stall leg
    # (the matching SIGCONT is time-based — a frozen backend can stall
    # the very progress a fraction-based thaw would wait on).
    sched = ChaosSchedule.seeded(args.seed)
    sched.events = sorted(
        sched.events + [ChaosEvent(0.08, "sigstop", "backend-b")],
        key=lambda e: e.at_frac,
    )
    STALL_S = 1.0

    n_total = args.requests
    responses = []  # (tenant, kind, code, body)
    acked_async = []  # (id, tenant)
    res_lock = threading.Lock()
    routers = [r1.url, r2.url]

    def progress() -> float:
        with res_lock:
            return len(responses) / float(n_total)

    def drive(tenant, n, deadline_ms, offset, pace_s):
        for k in range(n):
            body = {
                "m": SHAPE[0], "n": SHAPE[1], "seed": offset + k,
                "tenant": tenant, "id": f"{tenant}-{k}",
            }
            want_async = k % 2 == 0
            if want_async:
                body["async"] = True
            if deadline_ms:
                body["deadline_ms"] = deadline_ms
            deadline = time.perf_counter() + 120.0
            ridx = (offset + k) % 2
            while True:
                code, out = http_json(
                    routers[ridx] + "/v1/solve", body, timeout=60.0
                )
                if code == 429:
                    time.sleep(
                        min(float(out.get("retry_after_s", 0.05) or 0.05), 1.0)
                    )
                elif code in (502, 503, 599):
                    # Transport blip / dead router / no backend: the
                    # client's half of "nothing lost" is to retry —
                    # switching routers, because one may be gone.
                    ridx = 1 - ridx
                    if time.perf_counter() > deadline:
                        break
                    time.sleep(0.05)
                else:
                    break
            with res_lock:
                responses.append((tenant, "async" if want_async else "sync",
                                  code, out))
                if code == 202 and out.get("id"):
                    acked_async.append((out["id"], tenant))
            if pace_s:
                time.sleep(pace_s)

    # Paced so the stream OUTLIVES the fault schedule: kills, torn
    # tails, and restarts land mid-traffic (the scenario under test),
    # not after the last response.
    threads = [
        threading.Thread(
            target=drive, args=("tight", n_total * 3 // 10, 90_000, 0, 0.20),
            daemon=True,
        ),
        threading.Thread(
            target=drive,
            args=("loose", n_total - n_total * 3 // 10, 0, 10_000, 0.12),
            daemon=True,
        ),
    ]
    t_wave = time.perf_counter()
    for t in threads:
        t.start()
    # Fault driver: fire scheduled events as the response count crosses
    # their fractions; everything below is deterministic given the seed.
    fired_notes = []
    fault_seen = None  # backend A's journal write-error count mid-wave
    while any(t.is_alive() for t in threads):
        for ev in sched.due(progress()):
            note = plane.apply(ev)
            fired_notes.append(note)
            print(f"  [{progress():.0%}] {note}")
            if ev.kind == "sigstop":
                time.sleep(STALL_S)
                thaw = plane.apply(ChaosEvent(0.0, "sigcont", ev.target))
                print(f"  [{progress():.0%}] {thaw}")
        # Sample STRICTLY before the backend-a kill window so a slow
        # sweep can't read incarnation 2's fresh (zero) counter.
        if fault_seen is None and 0.30 <= progress() < 0.44:
            c, o = http_json(a.url + "/statusz", timeout=5.0)
            if c == 200:
                fault_seen = int(
                    (((o.get("stats") or {}).get("journal")) or {}).get(
                        "write_errors", 0
                    )
                )
        time.sleep(0.05)
    for t in threads:
        t.join(timeout=300)
    print(
        f"load wave: {len(responses)}/{n_total} responses in "
        f"{time.perf_counter() - t_wave:.1f}s; faults fired: "
        f"{len(fired_notes)}"
    )
    # Any leftover schedule entries (e.g. the wave outran a late event)
    # still fire so the asserted scenario is the full one.
    for ev in sched.due(1.0):
        print(f"  [post] {plane.apply(ev)}")

    if len(responses) != n_total:
        fail(f"lost submissions: {len(responses)} of {n_total} responded")

    # -- every sync ack is an honest verdict
    sync_bad = [
        (t, c, o.get("status") or o.get("error"))
        for t, kind, c, o in responses
        if kind == "sync" and not (
            (c == 200 and o.get("status") == "optimal")
            or (c == 504 and o.get("status") == "timeout")
        )
    ]
    if sync_bad:
        fail(f"sync requests without honest verdicts: {sync_bad[:5]}")

    # -- every 202 id resolves after recovery (through router 1: the
    # survivor; ids from the killed backend resolve via journal replay
    # + the router's fan-out poll)
    n_async = len(acked_async)
    unresolved, statuses = [], {}
    t_poll = time.perf_counter()
    for rid, tenant in acked_async:
        verdict = None
        while time.perf_counter() - t_poll < 120.0:
            c, o = http_json(r1.url + f"/v1/solve/{rid}", timeout=30.0)
            if c == 202:
                time.sleep(0.1)
                continue
            if c in (502, 599):
                time.sleep(0.2)
                continue
            verdict = (c, o.get("status"))
            break
        if verdict is None or verdict[1] is None:
            unresolved.append((rid, tenant, verdict))
        else:
            statuses[verdict[1]] = statuses.get(verdict[1], 0) + 1
    print(
        f"async resolution: {n_async - len(unresolved)}/{n_async} ids "
        f"resolved in {time.perf_counter() - t_poll:.1f}s — {statuses}"
    )
    if unresolved:
        fail(f"acknowledged async ids never resolved: {unresolved[:5]}")
    if statuses.get("failed"):
        fail(f"{statuses['failed']} async ids resolved FAILED")

    # -- zero duplicate solves across both journals
    for name in ("backend-a", "backend-b"):
        dups = journal_duplicate_solves(plane.procs[name].journal_dir)
        if dups:
            fail(f"{name}: {dups} duplicate finished records in the WAL")
    print("  duplicate solves: 0 in both journals")

    # -- journal-write fault degraded durability, not serving: the
    # mid-wave sample of incarnation 1 (taken while it was still
    # serving, after its 40th WAL append raised) must show the error
    # counted — and everything above shows traffic flowed regardless.
    if fault_seen is None:
        print("  journal-fault leg: backend A was killed before the "
              "mid-wave sample (seed timing); skipping the assert")
    elif fault_seen < 1:
        fail(
            f"injected journal-write fault never surfaced "
            f"(write_errors={fault_seen} mid-wave)"
        )
    else:
        print(
            f"  journal-fault leg: write_errors={fault_seen} mid-wave, "
            f"serving uninterrupted"
        )
    c, o = http_json(a.url + "/statusz")
    jstats = ((o.get("stats") or {}).get("journal")) or {}
    print(
        f"  backend A journal after recovery: pending="
        f"{jstats.get('pending')} results={jstats.get('results')} "
        f"write_errors={jstats.get('write_errors')}"
    )

    # -- zero warm recompiles at steady state: snapshot, verify-wave,
    # compare
    snaps = {}
    for name in ("backend-a", "backend-b"):
        c, o = http_json(plane.procs[name].url + "/statusz")
        if c != 200:
            fail(f"{name} statusz unreachable after recovery ({c})")
            continue
        snaps[name] = int((o.get("stats") or {}).get("programs_compiled", -1))
    for k in range(12):
        c, o = http_json(
            r1.url + "/v1/solve",
            {"m": SHAPE[0], "n": SHAPE[1], "seed": 90_000 + k,
             "tenant": "verify"},
            timeout=60.0,
        )
        if c != 200 or o.get("status") != "optimal":
            fail(f"verification request failed: {c} {o}")
            break
    for name, before in snaps.items():
        c, o = http_json(plane.procs[name].url + "/statusz")
        after = int((o.get("stats") or {}).get("programs_compiled", -2))
        if after != before:
            fail(
                f"{name}: warm recompiles at steady state "
                f"({before} -> {after} programs)"
            )
    print(f"  steady-state programs_compiled: {snaps} (flat)")

    # -- graceful drain leg: load backend B directly, quitquitquit,
    # readyz flips while healthz stays live, listener closes after.
    burst_results = []

    def burst(k):
        burst_results.append(
            http_json(
                b.url + "/v1/solve",
                {"m": SHAPE[0], "n": SHAPE[1], "seed": 95_000 + k,
                 "tenant": "drain"},
                timeout=60.0,
            )
        )

    bts = [
        threading.Thread(target=burst, args=(k,), daemon=True)
        for k in range(64)
    ]
    for t in bts:
        t.start()
    time.sleep(0.05)  # let the burst land in the queues
    c, o = http_json(b.url + "/quitquitquit", {})
    if c != 200 or not o.get("draining"):
        fail(f"quitquitquit: {c} {o}")
    # Sampled inside the drain window (the 64-deep burst keeps the
    # service busy long past these two GETs): liveness stays up while
    # readiness is already down.
    c_health, _ = http_json(b.url + "/healthz")
    c_ready, _ = http_json(b.url + "/readyz")
    print(
        f"  drain: readyz={c_ready} healthz={c_health} "
        f"(want 503 / 200)"
    )
    if c_ready != 503:
        fail(f"/readyz did not flip during drain (got {c_ready})")
    if c_health != 200:
        fail(f"/healthz went down during drain (got {c_health})")
    for t in bts:
        t.join(timeout=120)
    # Every burst request either resolved (admitted before the flip)
    # or was shed with the structured draining 503 (never admitted) —
    # anything else means the drain lost admitted work.
    n_drained = sum(
        1 for c, o in burst_results
        if c == 200 and o.get("status") == "optimal"
    )
    n_shed = sum(
        1 for c, o in burst_results
        if c == 503 and o.get("reason") == "draining"
    )
    lost = [
        (c, o) for c, o in burst_results
        if not (
            (c == 200 and o.get("status") == "optimal")
            or (c == 503 and o.get("reason") == "draining")
        )
    ]
    if lost:
        fail(f"drain lost admitted work: {lost[:3]}")
    if n_drained < 1:
        fail("drain leg admitted nothing before the flip (no coverage)")
    # The listener must close (drained process exits) shortly after.
    t_close = time.perf_counter()
    closed = False
    while time.perf_counter() - t_close < 60.0:
        c, _ = http_json(b.url + "/healthz", timeout=2.0)
        if c == 599:
            closed = True
            break
        time.sleep(0.2)
    if not closed:
        fail("backend B's listener never closed after the drain")
    else:
        print(
            f"  drain: {n_drained} in-flight resolved, {n_shed} shed "
            f"with the draining verdict, listener closed "
            f"{time.perf_counter() - t_close:.1f}s after"
        )

    plane.shutdown_all()
    if not args.keep_workdir and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        print(f"workdir kept for post-mortem: {workdir}")

    probe_wall = time.perf_counter() - t_probe
    if args.budget_s and probe_wall > args.budget_s:
        fail(f"probe took {probe_wall:.1f}s > budget {args.budget_s:.0f}s")
    print(f"probe wall: {probe_wall:.1f}s")
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
