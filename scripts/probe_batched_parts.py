"""Component-level timing of the batched f64 Mehrotra step at the
reference member shape (B=128 of 128x512) — the measurement that decides
where the df32 (float-float) layer must land (VERDICT round-4 item 1).

Every timed call varies its inputs (scale by 1+1e-6*k): the axon tunnel
caches results of bitwise-identical dispatches (memory: identical-call
result caching), so classic repeat-the-same-call microbenchmarks lie.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import distributedlpsolver_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp
import numpy as np

from distributedlpsolver_tpu.backends.batched import _single_step, _single_start
from distributedlpsolver_tpu.backends.dense import _make_ops
from distributedlpsolver_tpu.ipm import core
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.models.generators import random_batched_lp

B, m, n = 128, 128, 512
batch = random_batched_lp(B, m, n, seed=0)
dtype = jnp.float64
A = jnp.asarray(np.asarray(batch.A), dtype)
b = jnp.asarray(np.asarray(batch.b), dtype)
c = jnp.asarray(np.asarray(batch.c), dtype)
u = jnp.full((B, n), jnp.inf, dtype)
data = jax.vmap(lambda cc, bb, uu: core.make_problem_data(jnp, cc, bb, uu, dtype))(c, b, u)
cfg = SolverConfig()
params = cfg.step_params()
reg0 = jnp.full(B, 1e-10, dtype)

states = jax.jit(
    lambda A, d: jax.vmap(
        lambda a, dd: _single_start(a, dd, jnp.asarray(1e-10, dtype), params, dtype)
    )(A, d)
)(A, data)
jax.block_until_ready(states)


def timeit(name, fn, *args, reps=6):
    # Warm-up (compile), then time with a FULL value fetch: on this
    # tunnel block_until_ready returned instantly for these vmapped
    # programs while np.asarray paid the real 650 ms — only fetched
    # values are trustworthy timing barriers here.
    np.asarray(fn(*args, 0))
    ts = []
    for k in range(1, reps + 1):
        t0 = time.perf_counter()
        np.asarray(fn(*args, k))
        ts.append(time.perf_counter() - t0)
    print(f"{name:42s} best {min(ts)*1e3:9.1f} ms  med {sorted(ts)[len(ts)//2]*1e3:9.1f} ms")
    return min(ts)


def scale_state(states, k):
    f = 1.0 + 1e-7 * k
    return jax.tree_util.tree_map(lambda v: v * f, states)


# --- 1. full f64 step --------------------------------------------------
@jax.jit
def full_step_f64(A, data, states, regs, k):
    st = scale_state(states, k)
    new, stats = jax.vmap(
        lambda a, d, s, rg: _single_step(a, d, s, rg, params, jnp.float64)
    )(A, data, st, regs)
    return stats.rel_gap

timeit("full f64 step", full_step_f64, A, data, states, reg0)

# --- 2. full f32-factor step (f64 state) -------------------------------
A32 = A.astype(jnp.float32)

@jax.jit
def full_step_f32factor(A, A32, data, states, regs, k):
    st = scale_state(states, k)
    new, stats = jax.vmap(
        lambda a, a32, d, s, rg: _single_step(a, d, s, rg, params, jnp.float32, a32)
    )(A, A32, data, states, regs)
    return stats.rel_gap

timeit("f32-factor step (f64 state)", full_step_f32factor, A, A32, data, states, reg0)

# --- 3. all-f32 step ---------------------------------------------------
data32 = jax.tree_util.tree_map(
    lambda v: v.astype(jnp.float32) if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) else v,
    data,
)
states32 = jax.tree_util.tree_map(lambda v: v.astype(jnp.float32), states)
reg32 = reg0.astype(jnp.float32)

@jax.jit
def full_step_f32(A32, data32, states32, regs, k):
    st = jax.tree_util.tree_map(lambda v: v * (1.0 + 1e-6 * k), states32)
    new, stats = jax.vmap(
        lambda a, d, s, rg: _single_step(a, d, s, rg, params, jnp.float32)
    )(A32, data32, st, regs)
    return stats.rel_gap

timeit("all-f32 step", full_step_f32, A32, data32, states32, reg32)

# --- 4. factorize only (f64) ------------------------------------------
@jax.jit
def fact_f64(A, data, states, k):
    st = scale_state(states, k)

    def one(a, d, s):
        ops = _make_ops(a, jnp.asarray(1e-10, dtype), jnp.float64, 0)
        dd = core.scaling_d(s, d, params)
        L, M = ops.factorize(dd)
        return L[0, 0]

    return jax.vmap(one)(A, data, st)

timeit("factorize only (assembly+chol, f64)", fact_f64, A, data, states)

# --- 5. factorize + 6 solves (f64) ------------------------------------
@jax.jit
def fact_solve_f64(A, b, data, states, k):
    st = scale_state(states, k)

    def one(a, bb, d, s):
        ops = _make_ops(a, jnp.asarray(1e-10, dtype), jnp.float64, 0)
        dd = core.scaling_d(s, d, params)
        f = ops.factorize(dd)
        y = bb
        for _ in range(6):
            y = ops.solve(f, y)
        return y[0]

    return jax.vmap(one)(A, b, data, st)

timeit("factorize + 6 triangular solves (f64)", fact_solve_f64, A, b, data, states)

# --- 6. elementwise back-substitution block (f64), no factor/solve ----
@jax.jit
def backsub_f64(A, data, states, k):
    st = scale_state(states, k)

    def one(a, d, s):
        x, y, sdu, w, z = s
        hub = d.hub
        dd = core.scaling_d(s, d, params)
        r_p = d.b - a @ x
        r_u = hub * (d.u_f - x - w)
        r_d = d.c - a.T @ y - sdu + z
        r_xs = -x * sdu
        r_wz = -(w * z) * hub
        # back-substitution arithmetic with dy := r_p (no solve)
        h = r_d - r_xs / x + (r_wz - z * r_u) / w
        dy = r_p + a @ (dd * h)
        dx = dd * (a.T @ dy - h)
        ds = (r_xs - sdu * dx) / x
        dw = r_u - dx
        dz = hub * (r_wz - z * dw) / w
        return dx[0] + ds[0] + dw[0] + dz[0]

    return jax.vmap(one)(A, data, st)

timeit("residuals+backsub arith (f64, 1 round)", backsub_f64, A, data, states)

# --- 7. centrality backoff grid (f64) ----------------------------------
@jax.jit
def backoff_f64(data, states, k):
    st = scale_state(states, k)

    def one(d, s):
        x, y, sdu, w, z = s
        dirs = (-0.1 * x, -0.1 * sdu, -0.1 * w, -0.1 * z)
        ap, ad = core._centrality_backoff(
            jnp, s, d.hub, dirs, jnp.asarray(0.9, dtype), jnp.asarray(0.9, dtype),
            d.ncomp, params.gamma_cent,
        )
        return ap + ad

    return jax.vmap(one)(data, st)

timeit("centrality backoff grid (f64)", backoff_f64, data, states)

# --- 8. ratio tests (f64) ---------------------------------------------
@jax.jit
def ratio_f64(data, states, k):
    st = scale_state(states, k)

    def one(d, s):
        x, y, sdu, w, z = s
        a1 = core._max_step(jnp, x, -0.3 * x, w, -0.2 * w, d.hub)
        a2 = core._max_step(jnp, sdu, -0.3 * sdu, z, -0.2 * z, d.hub)
        return a1 + a2

    return jax.vmap(one)(data, st)

timeit("2x ratio test (f64)", ratio_f64, data, states)

# --- 9. df32 calibration: fused elementwise chain ---------------------
key = jax.random.PRNGKey(0)
a64 = jax.random.uniform(key, (B, n), jnp.float64) + 0.5
b64 = jax.random.uniform(jax.random.PRNGKey(1), (B, n), jnp.float64) + 0.5
c64 = jax.random.uniform(jax.random.PRNGKey(2), (B, n), jnp.float64) + 0.5

@jax.jit
def chain_f64(a, b, c, k):
    x = a * (1.0 + 1e-7 * k)
    for _ in range(10):
        x = (x * b + c) / (b + 0.5)
    return x[:, 0]

timeit("10x fused (x*b+c)/(b+.5) on (B,n) f64", chain_f64, a64, b64, c64)

a32h = a64.astype(jnp.float32); a32l = (a64 - a32h.astype(jnp.float64)).astype(jnp.float32)
b32 = b64.astype(jnp.float32); c32 = c64.astype(jnp.float32)

def two_sum(ah, al, bh, bl):
    s = ah + bh
    v = s - ah
    e = (ah - (s - v)) + (bh - v) + al + bl
    hi = s + e
    lo = e - (hi - s)
    return hi, lo

def split(a):
    t = a * 4097.0  # 2^12+1 splitter for f32
    hi = t - (t - a)
    return hi, a - hi

def two_prod(ah, al, bh, bl):
    p = ah * bh
    a1, a2 = split(ah)
    b1, b2 = split(bh)
    e = ((a1 * b1 - p) + a1 * b2 + a2 * b1) + a2 * b2
    e = e + ah * bl + al * bh
    hi = p + e
    lo = e - (hi - p)
    return hi, lo

@jax.jit
def chain_df32(ah, al, b, c, k):
    xh, xl = ah * (1.0 + 1e-7 * k), al
    d = b + 0.5
    for _ in range(10):
        ph, pl = two_prod(xh, xl, b, jnp.zeros_like(b))
        sh, sl = two_sum(ph, pl, c, jnp.zeros_like(c))
        # df32 division by plain f32: one Newton step off f32 quotient
        q = sh / d
        rh, rl = two_prod(q, jnp.zeros_like(q), d, jnp.zeros_like(d))
        # remainder = s - q*d  (df32)
        remh, reml = two_sum(sh, sl, -rh, -rl)
        xh = q + remh / d
        xl = (q - xh) + remh / d + reml / d
    return xh[:, 0]

timeit("10x same chain in df32 (two_prod/two_sum)", chain_df32, a32h, a32l, b32, c32)

@jax.jit
def chain_f32(a, b, c, k):
    x = a * (1.0 + 1e-6 * k)
    d = b + 0.5
    for _ in range(10):
        x = (x * b + c) / d
    return x[:, 0]

timeit("10x same chain f32", chain_f32, a32h, b32, c32)
print("done")
