"""Corrected-timing probe of the PCG phase-2 pieces at reference scale.
All timings force value readback; repeated ops run inside ONE jit via
fori_loop so tunnel latency doesn't mask per-op cost."""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.backends import dense as D
from distributedlpsolver_tpu.ops import normal_eq_pallas, pad_for_pallas

m, n = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (10000, 50000)
rng = np.random.default_rng(0)
print(f"shape {m}x{n}", flush=True)
A64 = jnp.asarray(rng.standard_normal((m, n)) / np.sqrt(n), dtype=jnp.float64)
Af = pad_for_pallas(A64.astype(jnp.float32))
d64 = jnp.asarray(10.0 ** rng.uniform(-5, 5, size=n), dtype=jnp.float64)
v0 = jnp.asarray(rng.standard_normal(m), dtype=jnp.float64)


def t_run(label, fn, *args, reps=2):
    t0 = time.perf_counter()
    s = float(jnp.sum(fn(*args)))
    t1 = time.perf_counter()
    ts = []
    for _ in range(reps):
        t2 = time.perf_counter()
        s = float(jnp.sum(fn(*args)))
        ts.append(time.perf_counter() - t2)
    print(f"{label}: first={t1 - t0:.1f}s steady={min(ts):.3f}s (chk {s:.3e})",
          flush=True)


asm = jax.jit(lambda Af, d: normal_eq_pallas(Af, d.astype(jnp.float32), out_m=m))
t_run("pallas f32 assembly", asm, Af, d64)


@jax.jit
def chol_prep(Af, d):
    M = normal_eq_pallas(Af, d.astype(jnp.float32), out_m=m)
    dg = jnp.diagonal(M)
    s = jax.lax.rsqrt(jnp.maximum(dg, 1e-30))
    Ms = M * s[:, None] * s[None, :] + 1e-8 * jnp.eye(m, dtype=M.dtype)
    L = jnp.linalg.cholesky(Ms)
    return D._tri_inv_paneled(L)


t_run("f32 asm+chol+paneled-Linv", chol_prep, Af, d64)


@jax.jit
def gemv20(v):
    def body(i, v):
        w = D._matvec_chunked(A64, d64 * D._rmatvec_chunked(A64, v))
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

    return jax.lax.fori_loop(0, 20, body, v)


t_run("20x f64 chunked GEMV pair", gemv20, v0, reps=1)
print("PROBE DONE", flush=True)
