"""Tail-tolerance acceptance probe: adaptive hedging, deadline
propagation and cancellation over a LIVE 3-backend plane (README "Tail
tolerance").

Legs:

  warm      → sync solves through the router until every backend's
              latency digest is warm (statusz ``forwards`` >=
              ``hedge_min_samples``);
              records the healthy latency distribution;
  straggler → SIGSTOP one backend, then burst a mixed sync/async wave:
              requests routed to the frozen backend must HEDGE to a
              sibling (the retry path never fires — the primary is
              silent, not dead), and the wave's p99 must stay within
              3x the healthy p99; the frozen backend is then thawed
              and its losing 202s are cancelled best-effort;
  slowloris → drip never-completing request headers into the router
              while live traffic flows — the threaded plane must keep
              answering within the same 3x bound;
  budget    → a second router with a ZERO retry budget: forced hedge
              attempts (cold-bucket solves slower than the hedge
              delay) must be suppressed with attributed
              retry_budget events, never launched;
  deadline  → a solve whose deadline budget is already spent when the
              router stamps it must come back as the backend's
              structured expired-on-arrival timeout verdict;
  audit     → zero lost acks (every 202 resolves), zero duplicate
              solves in any journal WAL, zero warm recompiles at
              steady state, and the router's JSONL hedge/cancel/
              retry_budget events RECONCILE with its /statusz hedging
              ledger (cap and budget provably honored).

Run: python scripts/probe_tail.py [--tail-requests N] [--budget-s S]
Exit 0 iff every check passes.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedlpsolver_tpu.net.chaos import (  # noqa: E402
    ChaosPlane,
    SlowLoris,
    journal_duplicate_solves,
)
from distributedlpsolver_tpu.net.router import RouterConfig  # noqa: E402
from distributedlpsolver_tpu.obs.stats import percentile  # noqa: E402

SHAPE = (96, 288)
# Cold shape for the budget leg: on the auto pow2 ladder it opens a
# bucket the warm shape's did not, so its first solve compiles — and a
# compile stall is reliably longer than the hedge delay, the
# deterministic way to force a hedge ATTEMPT against a healthy backend.
COLD_SHAPE = (160, 480)


def http_json(url, body=None, timeout=60.0, headers=None):
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={
            **({"Content-Type": "application/json"} if body else {}),
            **(headers or {}),
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, OSError, ConnectionError, ValueError) as e:
        return 599, {"error": f"{type(e).__name__}: {e}"}


def jsonl_events(path):
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tail-requests", type=int, default=20)
    ap.add_argument(
        "--budget-s", type=float, default=0.0,
        help="fail if the whole probe exceeds this wall time (0 = none)",
    )
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()
    t_probe = time.perf_counter()

    workdir = tempfile.mkdtemp(prefix="dlps-tail-")
    plane = ChaosPlane(workdir)
    registry_path = os.path.join(workdir, "registry.json")
    route_log = os.path.join(workdir, "router.jsonl")
    route2_log = os.path.join(workdir, "router2.jsonl")
    buckets_json = os.path.join(workdir, "ladder.json")
    with open(buckets_json, "w") as fh:
        fh.write(json.dumps([{"m": SHAPE[0], "n": SHAPE[1], "batch": 4}]))

    ok = True

    def fail(msg):
        nonlocal ok
        print(f"FAIL: {msg}")
        ok = False

    # -- plane: 3 warm backends + the hedging router ---------------------
    names = ["backend-a", "backend-b", "backend-c"]
    for name in names:
        plane.spawn_backend(
            name,
            buckets_json=buckets_json,
            extra_flags=["--flush-ms", "20", "--batch", "4"],
        )
    for name in names:
        if not plane.wait_ready(plane.procs[name], 180):
            fail(f"{name} did not come up")
            plane.shutdown_all()
            print("FAIL")
            return 1
    router = plane.spawn_router(
        "router-1",
        [plane.procs[n].url for n in names],
        registry_path,
        extra_flags=[
            # Loose cap for the scenario (the honoring proof is the
            # ledger arithmetic, not the specific value): every
            # straggler-bound request must be ABLE to hedge, or it
            # blocks on the frozen socket for the full forward timeout.
            "--hedge-rate-cap", "0.5",
            "--retry-budget", "50", "--retry-budget-burst", "50",
            "--log-jsonl", route_log,
        ],
    )
    if not plane.wait_ready(router, 60):
        fail("router did not come up")
        plane.shutdown_all()
        print("FAIL")
        return 1
    print(f"plane up: 3 backends behind {router.url}")

    def statusz(url=None):
        c, o = http_json((url or router.url) + "/statusz", timeout=5.0)
        return o if c == 200 else {}

    def wave(n, tenant, url=None, make=None, conc=4, timeout=90.0):
        """n paced sync solves; returns (latencies_ms, responses)."""
        lats, resp = [], []
        lock = threading.Lock()

        def one(k):
            body = (make or (lambda i: {
                "m": SHAPE[0], "n": SHAPE[1], "seed": i,
                "tenant": tenant, "id": f"{tenant}-{i}",
            }))(k)
            t0 = time.perf_counter()
            code, out = http_json(
                (url or router.url) + "/v1/solve", body, timeout=timeout
            )
            with lock:
                lats.append((time.perf_counter() - t0) * 1e3)
                resp.append((code, out))

        ts = []
        for k in range(n):
            t = threading.Thread(target=one, args=(k,), daemon=True)
            t.start()
            ts.append(t)
            if len(ts) % conc == 0:
                time.sleep(0.05)
        for t in ts:
            t.join(timeout=timeout + 30)
        return lats, resp

    # -- warm leg: build every backend's latency digest ------------------
    healthy_lats = []
    sent = 0
    while sent < 120:
        lats, resp = wave(6, "warm", make=lambda i, base=sent: {
            "m": SHAPE[0], "n": SHAPE[1], "seed": base + i,
            "tenant": "warm", "id": f"warm-{base + i}",
        })
        healthy_lats.extend(lats)
        sent += 6
        bad = [(c, o) for c, o in resp if c != 200]
        if bad:
            fail(f"warm request failed: {bad[:3]}")
            break
        # Exit as soon as every digest can drive a hedge delay
        # (hedge_min_samples): each extra wave is ~4-5 s of 1-core wall.
        fwd = [b.get("forwards", 0) for b in statusz().get("backends", [])]
        if fwd and min(fwd) >= RouterConfig().hedge_min_samples:
            break
    p99_healthy = percentile(healthy_lats, 99)
    s = statusz()
    digests = {
        b["url"]: (b.get("latency_ms_p50"), b.get("latency_ms_p95"))
        for b in s.get("backends", [])
    }
    print(
        f"warm: {sent} solves, healthy p50={percentile(healthy_lats, 50):.0f}"
        f"ms p99={p99_healthy:.0f}ms; digests={digests}"
    )
    if any(p95 is None for _, p95 in digests.values()):
        fail(f"a backend digest never warmed: {digests}")

    # -- straggler leg: SIGSTOP one backend, hedge around it -------------
    victim = "backend-c"
    plane.sigstop(victim)
    print(f"[straggler] SIGSTOP {victim}")
    n_tail = max(12, args.tail_requests)

    def tail_body(i):
        body = {
            "m": SHAPE[0], "n": SHAPE[1], "seed": 10_000 + i,
            "tenant": "tail", "id": f"tail-{i}",
        }
        if i % 3 == 0:
            body["async"] = True
        return body

    tail_lats, tail_resp = wave(n_tail, "tail", make=tail_body)
    p99_tail = percentile(tail_lats, 99)
    print(
        f"[straggler] {len(tail_lats)}/{n_tail} responses, "
        f"p50={percentile(tail_lats, 50):.0f}ms p99={p99_tail:.0f}ms "
        f"(bound {3 * p99_healthy:.0f}ms)"
    )
    if len(tail_lats) != n_tail:
        fail(f"straggler leg lost responses: {len(tail_lats)}/{n_tail}")
    if p99_tail > 3 * p99_healthy:
        fail(
            f"hedged p99 {p99_tail:.0f}ms exceeds 3x healthy "
            f"p99 {p99_healthy:.0f}ms"
        )
    acks = []
    for code, out in tail_resp:
        if code == 202 and out.get("id"):
            acks.append(out["id"])
        elif not (code == 200 and out.get("status") == "optimal"):
            fail(f"straggler-leg request without honest verdict: "
                 f"{code} {out}")
    hedges_after_straggler = sum(
        (statusz().get("hedging", {}).get("outcomes", {})).values()
    )
    if not hedges_after_straggler:
        fail("no hedge ever launched or suppressed during the straggler leg")
    plane.sigcont(victim)
    print(f"[straggler] SIGCONT {victim}; {len(acks)} async acks to resolve")

    # -- zero lost acks: every 202 resolves through the router -----------
    unresolved = []
    for rid in acks:
        verdict = None
        pdl = time.perf_counter() + 120.0
        while time.perf_counter() < pdl:
            c, o = http_json(router.url + f"/v1/solve/{rid}", timeout=30.0)
            if c in (202, 404, 502, 503, 599):
                time.sleep(0.1)
                continue
            verdict = (c, o.get("status"))
            break
        if verdict is None or verdict[1] not in ("optimal", "timeout"):
            unresolved.append((rid, verdict))
    if unresolved:
        fail(f"acknowledged async ids never resolved: {unresolved[:5]}")
    else:
        print(f"  zero lost acks: {len(acks)}/{len(acks)} resolved")

    # -- slow-loris leg: drip into the router while traffic flows --------
    loris = SlowLoris("127.0.0.1", router.port, conns=8, drip_s=0.2).start()
    time.sleep(0.5)  # let the drips open before measuring
    loris_lats, loris_resp = wave(12, "loris", make=lambda i: {
        "m": SHAPE[0], "n": SHAPE[1], "seed": 20_000 + i,
        "tenant": "loris", "id": f"loris-{i}",
    })
    p99_loris = percentile(loris_lats, 99)
    loris.stop()
    # A loris-victim forward stalls until the hedge fires, so its best
    # case is hedge_delay + a healthy solve; the bound composes those
    # terms (delay at its config clamp) instead of pretending the hedge
    # is free — on 1-core CPU walls the raw 3x bound sits BELOW the
    # clamp + one solve and fails on machine speed, not tail behavior.
    loris_bound = 3 * p99_healthy + RouterConfig().hedge_delay_max_ms
    print(
        f"[slowloris] {loris.opened} conns, {loris.dripped} bytes dripped; "
        f"live p99={p99_loris:.0f}ms (bound {loris_bound:.0f}ms)"
    )
    if loris.opened == 0:
        fail("slow-loris never connected")
    bad = [
        (c, o) for c, o in loris_resp
        if not (c == 200 and o.get("status") == "optimal")
    ]
    if bad:
        fail(f"requests failed under slow-loris: {bad[:3]}")
    if p99_loris > loris_bound:
        fail(
            f"slow-loris p99 {p99_loris:.0f}ms exceeds "
            f"3x healthy p99 + hedge delay clamp ({loris_bound:.0f}ms)"
        )

    # -- budget leg: a zero-budget router must suppress, never launch ----
    # Its own auto-ladder backend (the explicit-ladder trio rejects
    # off-ladder shapes), so the cold solve's compile stall can force a
    # hedge attempt that the empty budget must refuse.
    backend_d = plane.spawn_backend(
        "backend-d", extra_flags=["--flush-ms", "20", "--batch", "2"]
    )
    router2 = plane.spawn_router(
        "router-2",
        [backend_d.url],
        os.path.join(workdir, "registry2.json"),
        extra_flags=[
            "--hedge-rate-cap", "1.0",
            "--retry-budget", "0", "--retry-budget-burst", "0",
            "--log-jsonl", route2_log,
        ],
    )
    if not plane.wait_ready(backend_d, 120) or not plane.wait_ready(
        router2, 60
    ):
        fail("budget-leg plane did not come up")
    else:
        sent2 = 0
        while sent2 < 60:
            _, resp = wave(6, "starve", url=router2.url, timeout=180.0,
                           make=lambda i, base=sent2: {
                               "m": SHAPE[0], "n": SHAPE[1],
                               "seed": 30_000 + base + i,
                               "tenant": "starve",
                               "id": f"starve-warm-{base + i}"})
            sent2 += 6
            if [(c, o) for c, o in resp if c != 200]:
                break
            fwd = [
                b.get("forwards", 0)
                for b in statusz(router2.url).get("backends", [])
            ]
            if fwd and min(fwd) >= 10:
                break
        # Cold-bucket solve: the compile stall outlasts the hedge
        # delay, so a hedge is ATTEMPTED — and must be suppressed.
        c, o = http_json(
            router2.url + "/v1/solve",
            {"m": COLD_SHAPE[0], "n": COLD_SHAPE[1], "seed": 40_000,
             "tenant": "starve", "id": "starve-cold-0"},
            timeout=300.0,
        )
        if not (c == 200 and o.get("status") == "optimal"):
            fail(f"budget-leg cold solve failed: {c} {o}")
        h2 = statusz(router2.url).get("hedging", {})
        print(
            f"[budget] zero-budget router: launched="
            f"{h2.get('hedges_launched')} exhausted="
            f"{h2.get('budget_exhausted')} outcomes={h2.get('outcomes')}"
        )
        if h2.get("hedges_launched", -1) != 0:
            fail(
                f"zero-budget router launched "
                f"{h2.get('hedges_launched')} hedges"
            )
        if not h2.get("budget_exhausted"):
            fail("zero-budget router never recorded a budget exhaustion")
        ev2 = jsonl_events(route2_log)
        n_budget_ev2 = sum(
            1 for e in ev2 if e.get("event") == "retry_budget"
        )
        if n_budget_ev2 != h2.get("budget_exhausted"):
            fail(
                f"budget events ({n_budget_ev2}) != statusz "
                f"budget_exhausted ({h2.get('budget_exhausted')})"
            )

    # -- deadline leg: spent budget rejects on arrival -------------------
    c, o = http_json(
        router.url + "/v1/solve",
        {"m": SHAPE[0], "n": SHAPE[1], "seed": 50_000, "tenant": "dl",
         "id": "dl-0", "deadline_ms": 0.01},
        timeout=30.0,
    )
    if not (
        c == 504
        and o.get("status") == "timeout"
        and o.get("reason") == "deadline_expired"
    ):
        fail(f"expired deadline not rejected on arrival: {c} {o}")
    else:
        print("[deadline] expired-on-arrival rejected with structured "
              "timeout verdict")

    # -- steady state: zero warm recompiles ------------------------------
    snaps = {}
    for name in names:
        c, o = http_json(plane.procs[name].url + "/statusz", timeout=10.0)
        if c != 200:
            fail(f"{name} statusz unreachable at steady state ({c})")
            continue
        snaps[name] = int((o.get("stats") or {}).get("programs_compiled", -1))
    _, resp = wave(6, "verify", make=lambda i: {
        "m": SHAPE[0], "n": SHAPE[1], "seed": 60_000 + i,
        "tenant": "verify", "id": f"verify-{i}"})
    bad = [
        (c, o) for c, o in resp
        if not (c == 200 and o.get("status") == "optimal")
    ]
    if bad:
        fail(f"steady-state verify failed: {bad[:3]}")
    for name, before in snaps.items():
        c, o = http_json(plane.procs[name].url + "/statusz", timeout=10.0)
        after = int((o.get("stats") or {}).get("programs_compiled", -2))
        if after != before:
            fail(
                f"{name}: warm recompiles at steady state "
                f"({before} -> {after} programs)"
            )
    print(f"  steady-state programs_compiled: {snaps} (flat)")

    # -- audit: WAL duplicates + ledger reconciliation -------------------
    for proc in plane.procs.values():
        if not proc.journal_dir:
            continue
        dups = journal_duplicate_solves(proc.journal_dir)
        if dups:
            fail(
                f"{proc.name}: {dups} duplicate finished records in "
                f"its WAL"
            )
    print("  duplicate solves: 0 across all backend journals")

    h = statusz().get("hedging", {})
    ev = jsonl_events(route_log)
    ev_hedge = {}
    for e in ev:
        if e.get("event") == "hedge":
            ev_hedge[e.get("outcome")] = ev_hedge.get(e.get("outcome"), 0) + 1
    n_cancel_ev = sum(1 for e in ev if e.get("event") == "cancel")
    n_budget_ev = sum(1 for e in ev if e.get("event") == "retry_budget")
    launched_outcomes = {
        k: v for k, v in (h.get("outcomes") or {}).items()
        if not k.startswith("suppressed_")
    }
    print(
        f"  ledger: forwards={h.get('forwards_total')} "
        f"launched={h.get('hedges_launched')} outcomes={h.get('outcomes')} "
        f"cancels={h.get('cancels')} events(hedge)={ev_hedge}"
    )
    if ev_hedge != launched_outcomes:
        fail(
            f"hedge events {ev_hedge} do not reconcile with statusz "
            f"launched outcomes {launched_outcomes}"
        )
    if sum(launched_outcomes.values()) != h.get("hedges_launched"):
        fail(
            f"launched outcomes {launched_outcomes} do not sum to "
            f"hedges_launched {h.get('hedges_launched')}"
        )
    if n_cancel_ev != h.get("cancels"):
        fail(
            f"cancel events ({n_cancel_ev}) != statusz cancels "
            f"({h.get('cancels')})"
        )
    if n_budget_ev != h.get("budget_exhausted"):
        fail(
            f"retry_budget events ({n_budget_ev}) != statusz "
            f"budget_exhausted ({h.get('budget_exhausted')})"
        )
    cap, fwd_total = h.get("rate_cap", 0.0), h.get("forwards_total", 0)
    if h.get("hedges_launched", 0) > cap * max(1, fwd_total) + 1:
        fail(
            f"rate cap violated: {h.get('hedges_launched')} hedges over "
            f"{fwd_total} forwards at cap {cap}"
        )
    if not h.get("hedges_launched"):
        fail("no hedge was ever launched (the straggler leg proved nothing)")

    plane.shutdown_all()
    if not args.keep_workdir and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        print(f"workdir kept for post-mortem: {workdir}")

    probe_wall = time.perf_counter() - t_probe
    if args.budget_s and probe_wall > args.budget_s:
        fail(f"probe took {probe_wall:.1f}s > budget {args.budget_s:.0f}s")
    print(f"probe wall: {probe_wall:.1f}s")
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
