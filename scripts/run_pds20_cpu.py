"""CPU baseline for the pds-20-class block-angular config (VERDICT item 2):
the sparse-direct CPU backend on the ~30k-row K=64 instance."""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import block_angular_lp

K, mb, nb, link = 64, 432, 1400, 1600
print("building...", flush=True)
p = block_angular_lp(K, mb, nb, link, seed=0, sparse=True, density=0.005)
print(f"built {p.shape}, nnz={p.A.nnz}", flush=True)
t0 = time.time()
r = solve(p, backend="cpu-sparse", verbose=True, max_iter=120)
wall = time.time() - t0
print(f"CPU-SPARSE RESULT: {r.status.name} obj={r.objective:.6f} iters={r.iterations} "
      f"gap={r.rel_gap:.2e} solve={r.solve_time:.1f}s wall={wall:.1f}s", flush=True)
with open("/root/repo/.pds20_cpu_baseline.json", "w") as fh:
    json.dump({"backend": "cpu-sparse", "status": r.status.value,
               "objective": r.objective, "iters": int(r.iterations),
               "solve_s": round(r.solve_time, 2)}, fh)
