"""Isolate the PCG solve quality: f32 precond + f64 matrix-free CG vs truth.

For a synthetic IPM-like d with spread 10^s, measure the relative
residual of one PCG solve at increasing spreads, on whatever platform
jax picks (run with JAX_PLATFORMS=cpu for the oracle, default for TPU).
"""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.backends import dense as D
from distributedlpsolver_tpu.ops import normal_eq_pallas, pad_for_pallas, supports_pallas

m, n = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (1024, 4096)
rng = np.random.default_rng(0)
A = jnp.asarray(rng.standard_normal((m, n)) / np.sqrt(n), dtype=jnp.float64)
use_pallas = supports_pallas(jnp.float32)
Af = pad_for_pallas(A.astype(jnp.float32)) if use_pallas else A.astype(jnp.float32)
print(f"m={m} n={n} platform={jax.default_backend()} pallas={use_pallas}", flush=True)

factorize, solve = D._pcg_ops(A, jnp.dtype(jnp.float32), use_pallas, Af, 1e-11, 200)
rhs = jnp.asarray(rng.standard_normal(m), dtype=jnp.float64)

@jax.jit
def one(d, reg, rhs):
    f = factorize(d, reg)
    x = solve(f, rhs)
    # true f64 residual of the returned solve; f = (Linv, s, diagM, d, reg)
    regd = reg * f[2]
    r = rhs - (D._matvec_chunked(A, d * D._rmatvec_chunked(A, x)) + regd * x)
    return x, jnp.linalg.norm(r) / jnp.linalg.norm(rhs)

for spread in [2, 4, 6, 8, 10]:
    logd = rng.uniform(-spread/2, spread/2, size=n)
    d = jnp.asarray(10.0 ** logd, dtype=jnp.float64)
    for reg in [1e-10, 1e-8]:
        t0 = time.perf_counter()
        x, rr = one(d, jnp.asarray(reg, jnp.float64), rhs)
        rr = float(jax.block_until_ready(rr)); dt = time.perf_counter() - t0
        print(f"spread=1e{spread} reg={reg:g}: relres={rr:.3e} ({dt:.1f}s)", flush=True)
print("DONE", flush=True)
