"""Chaos-elasticity acceptance probe: a deterministic load ramp over a
LIVE multi-process plane — one router over the shared registry, a
backend pool owned by an in-process :class:`ElasticController` — with
one pool member SIGKILLed mid-scale (README "Elasticity & overload
protection").

The closed loop under test:

  ramp up   → queue depth / admission rejects / brownout stage push the
              controller past its watermarks → scale-OUT spawns warm
              backends (compile ladder → bind → register, in that
              order) while the brownout ladder sheds batch-priority
              work with structured 429 verdicts;
  mid-scale → kill -9 one pool member: the controller reaps it and
              restores capacity (its slot's journal is reused, so poll
              ids minted by the dead incarnation re-bind);
  ramp down → sustained calm releases the brownout ladder and drains
              the pool back to min_backends via /quitquitquit — every
              admitted request resolves before a victim exits.

Checks:
  - the pool scaled out (>= 2 backends) and back in to min_backends,
    with attributed scale_out/scale_in actions, and the killed member
    was replaced;
  - zero lost acks: every sync request ends with an honest verdict,
    every 202 id resolves through the router's fan-out;
  - zero duplicate solves across every slot journal (the replacement
    replayed the dead member's WAL, it did not re-run it);
  - brownout engaged (>= 1 batch-priority shed carrying
    reason="brownout" + retry_after_s) and released (stage 0 at the
    end);
  - zero warm recompiles at steady state: after scale-in, a verify
    wave leaves programs_compiled flat on the surviving pool.

Run: python scripts/probe_elastic_serve.py [--requests N] [--budget-s S]
Exit 0 iff every check passes.
"""

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedlpsolver_tpu.net.chaos import (  # noqa: E402
    ChaosPlane,
    LoadRamp,
    journal_duplicate_solves,
)
from distributedlpsolver_tpu.serve.elastic import (  # noqa: E402
    ElasticConfig,
    ElasticController,
)

# Heavy enough that one CPU backend saturates under the ramp peak
# (~32 rps capacity at batch 4 vs the 48 rps peak) — the overload is
# real, not simulated.
SHAPE = (96, 288)

BROWNOUT = {
    "depth_high": 0.5,
    "depth_low": 0.125,
    "reject_rate_high": 1.0,
    "engage_after_s": 0.2,
    "escalate_after_s": 0.4,
    "release_after_s": 0.5,
    "retry_after_s": 0.05,
}


def http_json(url, body=None, timeout=30.0):
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, OSError, ConnectionError, ValueError) as e:
        return 599, {"error": f"{type(e).__name__}: {e}"}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=240)
    ap.add_argument(
        "--budget-s", type=float, default=0.0,
        help="fail if the whole probe exceeds this wall time (0 = none)",
    )
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()
    t_probe = time.perf_counter()

    workdir = tempfile.mkdtemp(prefix="dlps-elastic-")
    plane = ChaosPlane(workdir)
    registry_path = os.path.join(workdir, "registry.json")
    buckets_json = os.path.join(workdir, "ladder.json")
    with open(buckets_json, "w") as fh:
        fh.write(json.dumps([{"m": SHAPE[0], "n": SHAPE[1], "batch": 4}]))

    ok = True

    def fail(msg):
        nonlocal ok
        print(f"FAIL: {msg}")
        ok = False

    ctl = ElasticController(
        ElasticConfig(
            registry_path=registry_path,
            min_backends=1,
            max_backends=3,
            poll_s=0.2,
            load_high=6.0,
            reject_rate_high=0.5,
            out_sustain_s=0.4,
            load_low=1.0,
            in_sustain_s=2.0,
            cooldown_s=1.0,
            flap_window_s=60.0,
            flap_max_actions=24,  # the damper must not gate this scenario
            workdir=workdir,
            buckets_json=buckets_json,
            backend_flags=(
                "--flush-ms", "20", "--batch", "4", "--queue-depth", "16",
                "--brownout", json.dumps(BROWNOUT, separators=(",", ":")),
                "--quiet",
            ),
            heartbeat_s=0.25,
            log_jsonl=os.path.join(workdir, "elastic.jsonl"),
        )
    )
    t0 = time.perf_counter()
    ctl.start()  # synchronous first reconcile: the min pool is warm now
    if ctl.pool_size() < 1:
        fail("controller did not bring up the min pool")
        ctl.shutdown(drain=False)
        print("FAIL")
        return 1
    print(
        f"min pool up in {time.perf_counter() - t0:.1f}s: "
        f"{[m['url'] for m in ctl.statusz()['pool']]}"
    )
    router = plane.spawn_router("router-1", [], registry_path)
    if not plane.wait_ready(router, 60):
        fail("router did not come up")
        ctl.shutdown(drain=False)
        print("FAIL")
        return 1
    # The router adopts the self-registered pool from the registry.
    deadline = time.perf_counter() + 30.0
    while time.perf_counter() < deadline:
        c, o = http_json(router.url + "/statusz", timeout=5.0)
        if c == 200 and any(
            b.get("healthy") for b in o.get("backends", [])
        ):
            break
        time.sleep(0.2)
    else:
        fail("router never adopted the elastic pool from the registry")
    print(f"router up: {router.url} (registry: {registry_path})")

    # -- load wave: LoadRamp-paced sync/async stream + batch-priority
    # probes; a monitor kills one pool member once the pool scales out.
    n_total = args.requests
    ramp = LoadRamp(n_total, peak_rps=48.0, base_rps=3.0)
    responses = []  # (kind, code, body)
    async_verdicts = {}  # rid -> (code, status) | None (never resolved)
    sheds = []  # structured brownout verdicts observed
    res_lock = threading.Lock()
    wave_done = threading.Event()
    pool_peak = [ctl.pool_size()]
    brownout_peak = [0]
    killed = {"pid": None, "at_pool": 0, "n_actions": 0}

    def drive(k):
        body = {
            "m": SHAPE[0], "n": SHAPE[1], "seed": k,
            "tenant": "ramp", "id": f"ramp-{k}",
        }
        if k % 3 == 0:
            body["async"] = True
        deadline = time.perf_counter() + 120.0
        while True:
            code, out = http_json(router.url + "/v1/solve", body, timeout=60.0)
            if code == 429:
                time.sleep(
                    min(float(out.get("retry_after_s", 0.05) or 0.05), 1.0)
                )
            elif code in (502, 503, 599):
                if time.perf_counter() > deadline:
                    break
                time.sleep(0.05)
            else:
                break
        with res_lock:
            responses.append(("async" if "async" in body else "sync",
                              code, out))
        if code == 202 and out.get("id"):
            # Poll the ack to its verdict NOW, like a real client: a
            # draining victim answers until every resolved id is
            # claimed (the listener linger), a killed member's ids
            # re-bind in the successor on its slot — 404s during that
            # handoff are transient, so keep polling.
            rid = out["id"]
            verdict = None
            pdl = time.perf_counter() + 180.0
            while time.perf_counter() < pdl:
                c, o = http_json(
                    router.url + f"/v1/solve/{rid}", timeout=30.0
                )
                if c in (202, 404, 502, 503, 599):
                    time.sleep(0.1)
                    continue
                verdict = (c, o.get("status"))
                break
            with res_lock:
                async_verdicts[rid] = verdict

    def batch_probe():
        """Batch-priority feelers: under brownout stage >= 1 these get
        the structured shed verdict — the honest degradation contract."""
        k = 0
        while not wave_done.is_set():
            code, out = http_json(
                router.url + "/v1/solve",
                {"m": SHAPE[0], "n": SHAPE[1], "seed": 50_000 + k,
                 "tenant": "bulk", "priority": "batch",
                 "id": f"bulk-{k}"},
                timeout=30.0,
            )
            if code == 429 and out.get("reason") == "brownout":
                with res_lock:
                    sheds.append(out)
            k += 1
            wave_done.wait(0.1)

    def monitor():
        """Track pool/brownout peaks; kill -9 one member mid-scale."""
        while not wave_done.is_set():
            n = ctl.pool_size()
            pool_peak[0] = max(pool_peak[0], n)
            for m in ctl.statusz()["pool"]:
                c, o = http_json(m["url"] + "/statusz", timeout=2.0)
                if c != 200:
                    continue
                bo = (o.get("stats") or {}).get("brownout") or {}
                brownout_peak[0] = max(
                    brownout_peak[0], int(bo.get("stage", 0) or 0)
                )
            if killed["pid"] is None and n >= 2:
                victim = max(ctl.statusz()["pool"], key=lambda m: m["gen"])
                if ChaosPlane.kill9_pid(victim["pid"]):
                    killed["pid"] = victim["pid"]
                    killed["at_pool"] = n
                    killed["n_actions"] = len(ctl.actions())
                    print(
                        f"  [mid-scale] kill -9 {victim['url']} "
                        f"(pid {victim['pid']}, pool {n})"
                    )
            wave_done.wait(0.1)

    threads = [
        threading.Thread(target=batch_probe, daemon=True),
        threading.Thread(target=monitor, daemon=True),
    ]
    for t in threads:
        t.start()
    t_wave = time.perf_counter()
    workers = []
    for k in range(n_total):
        w = threading.Thread(target=drive, args=(k,), daemon=True)
        w.start()
        workers.append(w)
        time.sleep(ramp.gap_s(k))
    for w in workers:
        w.join(timeout=180)
    wave_done.set()
    for t in threads:
        t.join(timeout=30)
    print(
        f"load wave: {len(responses)}/{n_total} responses in "
        f"{time.perf_counter() - t_wave:.1f}s; pool peak {pool_peak[0]}, "
        f"brownout peak stage {brownout_peak[0]}, "
        f"{len(sheds)} batch sheds"
    )

    if len(responses) != n_total:
        fail(f"lost submissions: {len(responses)} of {n_total} responded")
    sync_bad = [
        (c, o.get("status") or o.get("error"))
        for kind, c, o in responses
        if kind == "sync" and not (
            (c == 200 and o.get("status") == "optimal")
            or (c == 504 and o.get("status") == "timeout")
        )
    ]
    if sync_bad:
        fail(f"sync requests without honest verdicts: {sync_bad[:5]}")

    # -- elasticity: the pool scaled out, and the kill was absorbed
    if pool_peak[0] < 2:
        fail(f"pool never scaled out (peak {pool_peak[0]})")
    if killed["pid"] is None:
        fail("no pool member was killed mid-scale (pool never reached 2)")
    else:
        # Replacement: a scale_out strictly after the kill restored
        # capacity (reasons vary — the signal may still be hot; by now
        # the ramp released, so the POOL SIZE has legitimately scaled
        # back in — the action log is the evidence).
        after_kill = ctl.actions()[killed["n_actions"]:]
        heals = [a for a in after_kill if a["event"] == "scale_out"]
        live_pids = {m["pid"] for m in ctl.statusz()["pool"]}
        if killed["pid"] in live_pids:
            fail("killed pid still listed in the pool (reap failed)")
        if not heals:
            fail(
                "controller never replaced the killed member "
                "(no scale_out after the kill)"
            )
        else:
            print(
                f"  self-heal: {len(heals)} scale_out after the kill "
                f"(first: {heals[0]['reason']}, "
                f"{heals[0]['ms']:.0f}ms lead)"
            )

    # -- brownout: engaged under the ramp, structured verdicts carried
    if brownout_peak[0] < 1 and not sheds:
        fail("brownout never engaged under the ramp")
    bad_sheds = [
        s for s in sheds
        if not (s.get("reason") == "brownout"
                and float(s.get("retry_after_s") or 0) > 0)
    ]
    if bad_sheds:
        fail(f"sheds without structured verdicts: {bad_sheds[:3]}")
    elif sheds:
        print(
            f"  brownout: {len(sheds)} batch sheds, all carrying "
            f"reason=brownout + retry_after_s"
        )

    # -- zero lost acks: every 202 resolved through the router fan-out
    # (each driver polled its ack to a verdict live, across drains and
    # the kill — the client's view of "no acknowledged work vanished").
    unresolved = [
        (rid, v) for rid, v in async_verdicts.items()
        if v is None or v[1] is None
    ]
    statuses = {}
    for _, v in async_verdicts.items():
        if v is not None and v[1] is not None:
            statuses[v[1]] = statuses.get(v[1], 0) + 1
    print(
        f"async resolution: {len(async_verdicts) - len(unresolved)}/"
        f"{len(async_verdicts)} ids resolved — {statuses}"
    )
    if unresolved:
        fail(f"acknowledged async ids never resolved: {unresolved[:5]}")
    if statuses.get("failed"):
        fail(f"{statuses['failed']} async ids resolved FAILED")

    # -- ramp released: the controller drains back to min_backends
    t_in = time.perf_counter()
    while time.perf_counter() - t_in < 120.0:
        if ctl.pool_size() <= ctl.config.min_backends:
            break
        time.sleep(0.3)
    if ctl.pool_size() > ctl.config.min_backends:
        fail(
            f"pool never scaled back in "
            f"({ctl.pool_size()} > min {ctl.config.min_backends})"
        )
    actions = ctl.actions()
    outs = [a for a in actions if a["event"] == "scale_out"]
    ins = [a for a in actions if a["event"] == "scale_in"]
    if not any(a.get("drained") for a in ins):
        fail(f"no scale_in drained gracefully: {ins}")
    else:
        lead = [a["ms"] for a in outs]
        print(
            f"  scale actions: {len(outs)} out "
            f"(lead {min(lead):.0f}..{max(lead):.0f}ms), "
            f"{len(ins)} in ({sum(bool(a.get('drained')) for a in ins)} "
            f"drained)"
        )

    # -- brownout released: every surviving backend at stage 0
    for m in ctl.statusz()["pool"]:
        c, o = http_json(m["url"] + "/statusz", timeout=5.0)
        bo = ((o.get("stats") or {}).get("brownout")) or {}
        if c == 200 and int(bo.get("stage", 0) or 0) != 0:
            fail(f"{m['url']} still browned out at idle: {bo}")

    # -- zero duplicate solves across every slot journal (replacements
    # replay the dead incarnation's WAL, they never re-run it)
    for jdir in sorted(glob.glob(os.path.join(workdir, "elastic-be*-journal"))):
        dups = journal_duplicate_solves(jdir)
        if dups:
            fail(f"{os.path.basename(jdir)}: {dups} duplicate finished "
                 f"records")
    print("  duplicate solves: 0 across all slot journals")

    # -- zero warm recompiles at steady state
    snaps = {}
    for m in ctl.statusz()["pool"]:
        c, o = http_json(m["url"] + "/statusz", timeout=5.0)
        if c != 200:
            fail(f"{m['url']} statusz unreachable at steady state ({c})")
            continue
        snaps[m["url"]] = int(
            (o.get("stats") or {}).get("programs_compiled", -1)
        )
    for k in range(8):
        c, o = http_json(
            router.url + "/v1/solve",
            {"m": SHAPE[0], "n": SHAPE[1], "seed": 90_000 + k,
             "tenant": "verify"},
            timeout=60.0,
        )
        if c != 200 or o.get("status") != "optimal":
            fail(f"verification request failed: {c} {o}")
            break
    for url, before in snaps.items():
        c, o = http_json(url + "/statusz", timeout=5.0)
        after = int((o.get("stats") or {}).get("programs_compiled", -2))
        if after != before:
            fail(
                f"{url}: warm recompiles at steady state "
                f"({before} -> {after} programs)"
            )
    print(f"  steady-state programs_compiled: {snaps} (flat)")

    ctl.shutdown(drain=True)
    plane.shutdown_all()
    if not args.keep_workdir and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        print(f"workdir kept for post-mortem: {workdir}")

    probe_wall = time.perf_counter() - t_probe
    if args.budget_s and probe_wall > args.budget_s:
        fail(f"probe took {probe_wall:.1f}s > budget {args.budget_s:.0f}s")
    print(f"probe wall: {probe_wall:.1f}s")
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
