"""Steady-state timings with FORCED value readback (float(sum(r))) —
block_until_ready alone does not force execution through the tunnel."""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")

m = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
rng = np.random.default_rng(0)
B64 = jnp.asarray(rng.standard_normal((m, 2048)) / 45.0, dtype=jnp.float64)
mk = jax.jit(lambda B, eps: B @ B.T + (1.0 + eps) * jnp.eye(m, dtype=B.dtype))
rhs = jnp.asarray(rng.standard_normal(m), dtype=jnp.float64)

def tme(label, fn, argf, reps=3):
    t0 = time.perf_counter(); s = float(jnp.sum(fn(*argf(0)))); t1 = time.perf_counter()
    ts = []
    for i in range(1, reps + 1):
        t2 = time.perf_counter(); s = float(jnp.sum(fn(*argf(i)))); ts.append(time.perf_counter() - t2)
    print(f"{label}: first={t1-t0:.1f}s steady={min(ts):.3f}s (chk {s:.3e})", flush=True)

M0 = mk(B64, 0.0)
float(jnp.sum(M0))
chol = jax.jit(jnp.linalg.cholesky)
tme(f"f64 cholesky m={m}", chol, lambda i: (mk(B64, 1e-7 * i),), reps=2)
L64 = chol(M0)
cs = jax.jit(lambda L, r: jax.scipy.linalg.cho_solve((L, True), r))
tme("f64 cho_solve 1rhs", cs, lambda i: (L64, rhs + i), reps=3)
chol32 = jax.jit(lambda M: jnp.linalg.cholesky(M.astype(jnp.float32)))
tme("f32 cholesky", chol32, lambda i: (mk(B64, 1e-7 * i),), reps=2)
L32 = chol32(M0)
cs32 = jax.jit(lambda L, r: jax.scipy.linalg.cho_solve((L, True), r.astype(jnp.float32)))
tme("f32 cho_solve 1rhs", cs32, lambda i: (L32, rhs + i), reps=3)
print("DONE", flush=True)
