"""Force the endgame at 2048x10240 (compiles are minutes, not 45) to
reproduce and diagnose the bad-step-at-small-reg pattern from the 10k run."""
import sys
sys.path.insert(0, "/root/repo")
import jax
from distributedlpsolver_tpu.backends import dense as D
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import random_dense_lp

D.DenseJaxBackend._ENDGAME_ENTRIES = 1  # force endgame at this size
p = random_dense_lp(2048, 10240, seed=2)
be = D.DenseJaxBackend()
r = solve(p, backend=be, solve_mode="pcg", max_iter=120)
print(f"RESULT: {r.status.name} gap={r.rel_gap:.2e} pinf={r.pinf:.2e} "
      f"dinf={r.dinf:.2e} iters={r.iterations} solve={r.solve_time:.1f}s",
      flush=True)
for row in getattr(be, "endgame_timings", [])[:40]:
    print(row, flush=True)
