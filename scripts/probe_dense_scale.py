"""Measure the primitive costs that decide the 10k x 50k dense design.

Run on the real TPU. Times (compile separated from steady-state):
  1. f32 Pallas normal-eq assembly at the probe shape
  2. f32 Cholesky + explicit triangular inverse at m
  3. f64 chunked GEMV pair (the PCG engine cost)
  4. f32-assembly error vs f64 chunked assembly (preconditioner quality)
  5. f32 triangular-solve (cho_solve) single-rhs latency, for comparison
"""
import sys, time
import numpy as np
import jax, jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.backends import dense as D
from distributedlpsolver_tpu.ops import normal_eq_pallas, pad_for_pallas, supports_pallas

shape = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (4096, 20480)
m, n = shape
print(f"probe shape m={m} n={n}; devices={jax.devices()}", flush=True)

rng = np.random.default_rng(0)
A64 = jnp.asarray(rng.standard_normal((m, n)) / np.sqrt(n), dtype=jnp.float64)
d64 = jnp.asarray(rng.uniform(1e-4, 1e4, size=n), dtype=jnp.float64)
A32p = pad_for_pallas(A64.astype(jnp.float32))
d32 = d64.astype(jnp.float32)

def tme(label, fn, *args, reps=3):
    t0 = time.perf_counter(); r = jax.block_until_ready(fn(*args)); t1 = time.perf_counter()
    ts = []
    for _ in range(reps):
        t2 = time.perf_counter(); r = jax.block_until_ready(fn(*args)); ts.append(time.perf_counter() - t2)
    print(f"{label}: compile+first={t1-t0:.2f}s steady={min(ts)*1e3:.1f}ms", flush=True)
    return r

# 1. f32 pallas assembly
pallas_asm = jax.jit(lambda Af, d: normal_eq_pallas(Af, d, out_m=m))
M32 = tme("pallas f32 assembly", pallas_asm, A32p, d32)
M32 = M32 + jnp.diag(1e-8 * jnp.diagonal(M32))

# 2. f32 cholesky; explicit inverse of L
chol32 = jax.jit(jnp.linalg.cholesky)
L32 = tme("f32 cholesky", chol32, M32)
tri_inv = jax.jit(lambda L: jax.scipy.linalg.solve_triangular(L, jnp.eye(m, dtype=L.dtype), lower=True))
Linv = tme("f32 triangular inverse", tri_inv, L32)

# 3. f64 chunked GEMV pair: v -> A (d * (A^T y)) (the CG operator)
def cg_op(y):
    return D._matvec_chunked(A64, d64 * D._rmatvec_chunked(A64, y))
y0 = jnp.asarray(rng.standard_normal(m), dtype=jnp.float64)
op_j = jax.jit(cg_op)
tme("f64 chunked GEMV pair (CG operator)", op_j, y0, reps=5)

# precond apply via Linv GEMVs (f32)
prec = jax.jit(lambda r: (Linv.T @ (Linv @ r.astype(jnp.float32))).astype(jnp.float64))
tme("precond apply (2 f32 GEMV via Linv)", prec, y0, reps=5)

# 5. cho_solve single rhs latency
cs = jax.jit(lambda L, r: jax.scipy.linalg.cho_solve((L, True), r))
tme("f32 cho_solve single rhs", cs, L32, y0.astype(jnp.float32), reps=5)

# 4. f32 assembly error vs f64 chunked assembly (skip at huge shape)
if m * n <= (1 << 27):
    asm64 = jax.jit(lambda A, d: D._normal_eq_chunked(A, d))
    M64 = tme("f64 chunked assembly", asm64, A64, d64, reps=1)
    err = jnp.max(jnp.abs(M32.astype(jnp.float64) - jnp.diag(1e-8*jnp.diagonal(M32)).astype(jnp.float64) - M64)) 
    rel = err / jnp.max(jnp.abs(M64))
    dg = jnp.max(jnp.abs(jnp.diagonal(M32).astype(jnp.float64) / (1+1e-8) - jnp.diagonal(M64)) / jnp.abs(jnp.diagonal(M64)))
    print(f"f32 vs f64 assembly: max abs err={float(err):.3e} rel={float(rel):.3e} diag rel={float(dg):.3e}", flush=True)
print("PROBE DONE", flush=True)
