"""Network-plane load probe: drive the full router → 2-backend HTTP
serving topology in one process and assert the ISSUE-9 acceptance
criteria end to end on CPU — the tier-1 smoke for the network serving
plane (README "Network serving").

Topology (all on localhost ephemeral ports, all in this process so the
bucket-program jit cache is shared and warm recompiles are countable):

    client threads → RouterHTTPServer → Router ──► backend A (SolveHTTPServer → SolveService)
                                              └──► backend B (killed mid-run)

Checks:
  - 200 HTTP requests across 2 tenants ("tight" — deadlined, high
    priority, weight 3; "loose" — an undeadlined flood, weight 1) all
    complete OPTIMAL — including the ones that were in flight toward
    backend B when its front-end is killed (failed over by the router's
    retry-once, never dropped);
  - zero warm recompiles across the whole load wave (bucket programs
    compiled only by the warm-up wave);
  - the tight-SLO tenant's p99 queue wait lands BELOW the loose
    tenant's under overload (EDF slot assignment + priority-shaded
    flush + weighted-fair admission doing their jobs), with the loose
    flood actually shedding (≥1 structured 429);
  - /metrics parses as Prometheus text on both a backend and the
    router (and carries the net_* / router_* families);
  - /healthz flips 200 → 503 on injected device loss and recovers.

Run: python scripts/probe_net.py [--requests N] [--budget-s S]
Exit 0 iff every check passes.
"""

import argparse
import json
import os
import re
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distributedlpsolver_tpu.backends.batched import bucket_cache_size  # noqa: E402
from distributedlpsolver_tpu.net import NetConfig, SolveHTTPServer  # noqa: E402
from distributedlpsolver_tpu.net.admission import (  # noqa: E402
    AdmissionConfig,
    TenantQuota,
)
from distributedlpsolver_tpu.net.router import (  # noqa: E402
    Router,
    RouterConfig,
    RouterHTTPServer,
)
from distributedlpsolver_tpu.obs.metrics import MetricsRegistry  # noqa: E402
from distributedlpsolver_tpu.obs.stats import percentile  # noqa: E402
from distributedlpsolver_tpu.parallel.runtime import (  # noqa: E402
    restore_devices,
    simulate_device_loss,
)
from distributedlpsolver_tpu.serve import ServiceConfig, SolveService  # noqa: E402

SHAPES = ((8, 24), (12, 32))  # the standard serve-probe bucket shapes

# Prometheus text exposition: "# HELP/TYPE ..." comments plus
# "name{labels} value" samples.
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+]?([0-9.eE+-]+|inf|nan)$"
)


def http_json(url, body=None, timeout=60.0):
    """(code, parsed_json) for one request; HTTP errors return their
    code + body instead of raising (the 429/503 paths are data here),
    and transport-level failures come back as a synthetic 599 so the
    caller's retry loop owns the decision instead of a dead thread."""
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, socket.timeout, OSError,
            ConnectionError, ValueError) as e:
        return 599, {"error": f"{type(e).__name__}: {e}"}


def prom_valid(text):
    """True iff every non-comment, non-blank line is a well-formed
    sample line."""
    lines = [l for l in text.splitlines() if l and not l.startswith("#")]
    return bool(lines) and all(_PROM_SAMPLE.match(l) for l in lines)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument(
        "--budget-s", type=float, default=0.0,
        help="fail if the whole probe exceeds this wall time (0 = none)",
    )
    args = ap.parse_args()
    t_probe = time.perf_counter()
    print(f"devices: {len(jax.devices())} × {jax.devices()[0].platform}")

    # Caps sized so the loose flood both QUEUES (deep enough for queue
    # waits to separate — EDF needs a queue to reorder) and SHEDS
    # (fairness engages at 24 in-system; the loose tenant's fair share
    # is 24 slots, and its 32 unpaced writers run past that).
    admission = AdmissionConfig(
        quotas={
            "tight": TenantQuota(weight=3.0),
            "loose": TenantQuota(weight=1.0),
        },
        fair_start=0.25,
    )
    svcs, fronts, regs = [], [], []
    for i in range(2):
        reg = MetricsRegistry()
        svc = SolveService(
            ServiceConfig(
                # batch=4 keeps the dispatch cadence fast: a
                # tight request's floor is the already-committed
                # pipeline (~3-4 batches it cannot preempt), so small
                # fast batches shrink that floor while the loose
                # tenant's 24-slot share still queues 6 batches deep.
                batch=4, flush_s=0.02, max_queue_depth=96,
                admission=admission,
                # SLO-sensitive pipeline setting: depth 1 commits fewer
                # popped batches ahead of the scheduler, so EDF can
                # reorder a late-arriving tight request in front of
                # queued loose work instead of behind two in-flight
                # batches of it.
                pipeline_depth=1,
            ),
            metrics=reg,
        )
        front = SolveHTTPServer(
            svc, NetConfig(healthz_cache_s=0.05), metrics=reg
        ).start()
        svcs.append(svc)
        fronts.append(front)
        regs.append(reg)
    router_reg = MetricsRegistry()
    # poll_s outlasts the WHOLE probe on purpose (start() still runs
    # one synchronous sweep, so both backends enter rotation): the
    # router must discover backend B's death through a failed forward
    # (the retry-once failover under test), not through a lucky health
    # poll racing ahead of the traffic — a poll landing between the
    # kill and the next forward to B marks it unhealthy below the
    # eject threshold and no failover is ever exercised.
    router = Router(
        [f.url for f in fronts],
        RouterConfig(poll_s=60.0, eject_after=2),
        metrics=router_reg,
    ).start()
    rhttp = RouterHTTPServer(router, metrics=router_reg).start()
    print(
        f"backends: {[f.url for f in fronts]}; router: {rhttp.url} "
        f"({router.healthy_count()} healthy)"
    )
    ok = True

    def fail(msg):
        nonlocal ok
        print(f"FAIL: {msg}")
        ok = False

    # -- warm-up: compile every (shape, bucket) program once, through
    # both backends, so the load wave is a pure warm-path measurement.
    t0 = time.perf_counter()
    for front in fronts:
        for m, n in SHAPES:
            for seed in range(8):  # two full 4-slot buckets per shape
                code, out = http_json(
                    front.url + "/v1/solve",
                    {"m": m, "n": n, "seed": seed, "tenant": "warmup"},
                )
                if code != 200 or out.get("status") != "optimal":
                    fail(f"warm-up request failed: {code} {out}")
    cache0 = bucket_cache_size()
    print(
        f"warm-up: {len(fronts) * len(SHAPES) * 8} requests in "
        f"{time.perf_counter() - t0:.1f}s, {cache0} bucket programs compiled"
    )

    # -- main wave: a loose flood + a steady tight stream through the
    # router, backend B killed mid-run.
    n_total = args.requests
    n_tight = max(1, n_total * 3 // 10)
    n_loose = n_total - n_tight
    results = []
    rejects = {"tight": 0, "loose": 0}
    res_lock = threading.Lock()
    kill_at = n_total // 3  # responses collected before the kill
    killed = threading.Event()

    def drive(tenant, n, deadline_ms, priority, pace_s, delay_s=0.0):
        # The tight stream starts after the flood has formed real
        # queues: the acceptance scenario is a tight-SLO tenant
        # arriving INTO overload, not sharing the cold thundering-herd
        # surge with it.
        if delay_s:
            time.sleep(delay_s)
        rng_seed = 1000 if tenant == "tight" else 2000
        for k in range(n):
            m, n_ = SHAPES[k % len(SHAPES)]
            body = {
                "m": m, "n": n_, "seed": rng_seed + k,
                "tenant": tenant, "priority": priority,
                "id": f"{tenant}-{k}",
            }
            if deadline_ms:
                body["deadline_ms"] = deadline_ms
            deadline = time.perf_counter() + 120.0
            while True:
                code, out = http_json(rhttp.url + "/v1/solve", body)
                if code == 429:
                    with res_lock:
                        rejects[tenant] += 1
                    retry = float(out.get("retry_after_s", 0.02) or 0.02)
                    if time.perf_counter() + retry > deadline:
                        break
                    time.sleep(min(retry, 1.0))
                    continue
                if code in (502, 503, 599):
                    # Transport blip / no backend in rotation: the
                    # client's half of "no request lost" is to retry.
                    if time.perf_counter() > deadline:
                        break
                    time.sleep(0.05)
                    continue
                break
            with res_lock:
                results.append((tenant, code, out))
                done = len(results)
            if done >= kill_at and not killed.is_set():
                killed.set()
                fronts[1].shutdown()  # the mid-run backend kill
                print(f"  killed backend B after {done} responses")
            if pace_s:
                time.sleep(pace_s)

    t0 = time.perf_counter()
    threads = []
    # 32 unpaced loose writers = the overload (comfortably past the
    # loose tenant's 24-slot fair share); 4 gently paced tight writers
    # = the SLO traffic that must not starve behind it.
    for i in range(32):
        threads.append(threading.Thread(
            target=drive,
            args=("loose", n_loose // 32 + (i < n_loose % 32), 0,
                  "normal", 0.0),
            daemon=True,
        ))
    for i in range(4):
        threads.append(threading.Thread(
            target=drive,
            args=("tight", n_tight // 4 + (i < n_tight % 4), 60_000,
                  "high", 0.02, 0.25),
            daemon=True,
        ))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    wave_wall = time.perf_counter() - t0
    recompiles = bucket_cache_size() - cache0

    n_ok = sum(
        1 for _, code, out in results
        if code == 200 and out.get("status") == "optimal"
    )
    print(
        f"load wave: {len(results)}/{n_total} responses in {wave_wall:.1f}s "
        f"({len(results) / max(wave_wall, 1e-9):.1f} rps), {n_ok} OPTIMAL, "
        f"429s: tight={rejects['tight']} loose={rejects['loose']}, "
        f"warm recompiles: {recompiles}"
    )
    if len(results) != n_total:
        fail(f"lost requests: {len(results)} of {n_total} got a response")
    if n_ok != len(results):
        bad = [
            (t, c, o.get("status"), o.get("error"))
            for t, c, o in results
            if c != 200 or o.get("status") != "optimal"
        ][:5]
        fail(f"not all OPTIMAL: {bad}")
    if recompiles != 0:
        fail(f"load wave compiled {recompiles} bucket programs (want 0)")

    # Failover actually exercised: B ejected, the router retried at
    # least one forward, and traffic kept completing afterwards.
    st = router.statusz()
    b_state = next(
        b for b in st["backends"] if b["url"] == fronts[1].url
    )
    print(
        f"  router: failovers={st['failovers']}, "
        f"B ejected={b_state['ejected']} (fails={b_state['fails']})"
    )
    if not b_state["ejected"]:
        fail("backend B was not ejected after the kill")
    if st["failovers"] < 1:
        fail("no forward was failed over (kill happened between requests?)")

    # SLO separation under overload: EDF + priority flush + fairness
    # must keep the tight tenant's queue waits below the flood's.
    tight_q = [
        o["queue_ms"] for t, c, o in results if t == "tight" and c == 200
    ]
    loose_q = [
        o["queue_ms"] for t, c, o in results if t == "loose" and c == 200
    ]
    p99_t, p99_l = percentile(tight_q, 99), percentile(loose_q, 99)
    print(
        f"  queue wait: tight p50={percentile(tight_q, 50):.0f}ms "
        f"p99={p99_t:.0f}ms | loose p50={percentile(loose_q, 50):.0f}ms "
        f"p99={p99_l:.0f}ms"
    )
    if not (p99_t < p99_l):
        fail(
            f"tight-SLO p99 queue wait {p99_t:.1f}ms not below loose "
            f"{p99_l:.1f}ms"
        )
    if rejects["loose"] < 1:
        fail(
            "loose flood never shed a 429 — the overload leg did not "
            "actually overload"
        )

    # -- /metrics validity on a live backend and the router.
    code, _ = http_json(fronts[0].url + "/healthz")
    if code != 200:
        fail(f"backend A healthz {code} while healthy")
    for label, url in (("backend A", fronts[0].url), ("router", rhttp.url)):
        req = urllib.request.Request(url + "/metrics")
        with urllib.request.urlopen(req, timeout=10) as r:
            text = r.read().decode()
        want = "net_requests_total" if label == "backend A" else (
            "router_backend_healthy"
        )
        if not prom_valid(text):
            fail(f"{label} /metrics is not valid Prometheus text")
        elif want not in text:
            fail(f"{label} /metrics lacks {want}")
        else:
            n_samples = sum(
                1 for l in text.splitlines() if l and not l.startswith("#")
            )
            print(f"  {label} /metrics: {n_samples} samples, parses clean")

    # -- /healthz flips on injected device loss, and recovers.
    try:
        simulate_device_loss([d.id for d in jax.devices()])
        time.sleep(0.1)  # step past the healthz cache window
        code_lost, body_lost = http_json(fronts[0].url + "/healthz")
    finally:
        restore_devices()
    time.sleep(0.1)
    code_back, _ = http_json(fronts[0].url + "/healthz")
    print(
        f"  healthz flip: lost -> {code_lost} "
        f"({body_lost.get('devices_unhealthy')}), restored -> {code_back}"
    )
    if code_lost != 503:
        fail(f"healthz did not flip on device loss (got {code_lost})")
    if code_back != 200:
        fail(f"healthz did not recover after restore (got {code_back})")

    rhttp.shutdown()
    router.shutdown()
    fronts[0].shutdown()
    for svc in svcs:
        svc.shutdown()

    probe_wall = time.perf_counter() - t_probe
    if args.budget_s and probe_wall > args.budget_s:
        fail(f"probe took {probe_wall:.1f}s > budget {args.budget_s:.0f}s")
    print(f"probe wall: {probe_wall:.1f}s")
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
