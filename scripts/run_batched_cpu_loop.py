"""Measured FULL-LOOP CPU baseline for the batched config (B:11):
all 1024 (128x512) members solved one at a time through `cpu-native` —
the reference's natural "one LP per rank" shape — with NO sampling or
extrapolation (VERDICT round-4 item 1 demanded a measured loop).

Wall vs process-CPU time both recorded (1-core host: a gap flags
contention, the round-4 lesson)."""
import json, resource, sys, time

import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")
import numpy as np
from distributedlpsolver_tpu.backends.batched import member_interior_form
from distributedlpsolver_tpu.ipm.driver import solve
from distributedlpsolver_tpu.models.generators import random_batched_lp

B, m, n = 1024, 128, 512
batch = random_batched_lp(B, m, n, seed=0)
print(f"looping {B} members through cpu-native...", flush=True)
u0 = resource.getrusage(resource.RUSAGE_SELF)
t0 = time.time()
n_opt, iters, per = 0, 0, []
for i in range(B):
    r = solve(member_interior_form(batch, i), backend="cpu-native")
    n_opt += r.status.value == "optimal"
    iters += int(r.iterations)
    per.append(r.solve_time)
    if (i + 1) % 128 == 0:
        print(f"  {i+1}/{B}  elapsed {time.time()-t0:.1f}s", flush=True)
wall = time.time() - t0
u1 = resource.getrusage(resource.RUSAGE_SELF)
cpu_s = (u1.ru_utime - u0.ru_utime) + (u1.ru_stime - u0.ru_stime)
per = np.asarray(per)
print(f"LOOP RESULT: {n_opt}/{B} optimal, total wall {wall:.1f}s cpu {cpu_s:.1f}s "
      f"sum(solve) {per.sum():.1f}s mean {per.mean()*1e3:.1f}ms", flush=True)
with open("/root/repo/.batched_cpu_loop.json", "w") as fh:
    json.dump({"config": f"{B} x ({m}x{n}) seed=0 looped cpu-native",
               "n_optimal": int(n_opt), "B": B, "total_iters": iters,
               "wall_s": round(wall, 2), "process_cpu_s": round(cpu_s, 2),
               "sum_solve_s": round(float(per.sum()), 2),
               "mean_solve_ms": round(float(per.mean() * 1e3), 3),
               "sampled": False,
               "contention_check": "wall ~= process_cpu_s => quiet host"},
              fh, indent=1)
print("wrote .batched_cpu_loop.json", flush=True)
