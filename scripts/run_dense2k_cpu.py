"""CPU baseline for the dense 2048x10240 config (VERDICT round-4 item 3):
end-to-end `cpu-native` solve to 1e-8 on the exact suite-row instance
(random_dense_lp(2048, 10240, seed=2) — bench.py's [3/6] row at the
--quick size that the suite actually times on TPU).

Records BOTH wall-clock and process CPU time: the host has one core, so
wall >> cpu_time flags a contended (invalid) measurement — round 4's
contaminated-run lesson, made mechanically checkable.
"""
import json, os, resource, sys, time

import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import random_dense_lp

m, n = 2048, 10240
print("building...", flush=True)
p = random_dense_lp(m, n, seed=2)
print(f"built {p.shape}", flush=True)
u0 = resource.getrusage(resource.RUSAGE_SELF)
t0 = time.time()
r = solve(p, backend="cpu-native", verbose=True, max_iter=100)
wall = time.time() - t0
u1 = resource.getrusage(resource.RUSAGE_SELF)
cpu_s = (u1.ru_utime - u0.ru_utime) + (u1.ru_stime - u0.ru_stime)
print(f"CPU-NATIVE RESULT: {r.status.name} obj={r.objective:.6f} "
      f"iters={r.iterations} gap={r.rel_gap:.2e} solve={r.solve_time:.1f}s "
      f"wall={wall:.1f}s cpu={cpu_s:.1f}s", flush=True)
with open("/root/repo/.dense2k_cpu.json", "w") as fh:
    json.dump({"config": f"random dense {m}x{n} seed=2", "backend": "cpu-native",
               "status": r.status.value, "objective": r.objective,
               "iters": int(r.iterations), "rel_gap": r.rel_gap,
               "solve_s": round(r.solve_time, 2), "wall_s": round(wall, 2),
               "process_cpu_s": round(cpu_s, 2),
               "contention_check": "wall ~= process_cpu_s => quiet host"},
              fh, indent=1)
print("wrote .dense2k_cpu.json", flush=True)
