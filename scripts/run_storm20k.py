"""stormG2-class decisive-win config (VERDICT round 2 item 4): a ≥20k-row,
hundreds-of-blocks sparse block-angular instance, arriving HINT-LESS like
a real MPS file. Structure detection recovers the natural partition
(256 blocks after the round-3 detector tuning — merging blocks squares
their flop share), the TPU block backend solves via the two-phase
segmented Schur path, and cpu-sparse is the baseline.

Writes /root/repo/.storm20k.json. Run with TPULP_SEG_VERBOSE=1 for live
segment progress. Optional argv: K mb nb link density max_iter.
"""
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

# Measurement envelope: `--require-tpu` aborts (exit 4) instead of
# silently measuring host CPU when the accelerator is missing (the
# BENCH_r05 failure class).
from distributedlpsolver_tpu.utils.accel import require_tpu

require_tpu("--require-tpu" in sys.argv)
sys.argv = [a for a in sys.argv if a != "--require-tpu"]

K, mb, nb, link = (
    (int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
    if len(sys.argv) > 4 else (256, 80, 160, 48)
)
density = float(sys.argv[5]) if len(sys.argv) > 5 else 0.08
max_iter = int(sys.argv[6]) if len(sys.argv) > 6 else 120
skip_baseline = os.environ.get("STORM_SKIP_BASELINE") == "1"

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.models.generators import block_angular_lp
from distributedlpsolver_tpu.models.structure import detect_block_structure

print(f"building K={K} {mb}x{nb} link={link} density={density}...", flush=True)
p = block_angular_lp(K, mb, nb, link, seed=3, sparse=True, density=density)
p.block_structure = None  # what a real file looks like
print(f"built {p.shape}, nnz={p.A.nnz}", flush=True)

t0 = time.time()
hint = detect_block_structure(p)
t_detect = time.time() - t0
assert hint is not None, "detection declined the structure"
print(f"detected K={hint['num_blocks']} in {t_detect:.2f}s", flush=True)
p.block_structure = hint

# Warm-up (compile) then timed solve, same discipline as bench.py.
solve(p, backend="block", max_iter=3)
t0 = time.time()
r = solve(p, backend="block", max_iter=max_iter)
wall = time.time() - t0
print(
    f"TPU block: {r.status.name} obj={r.objective:.6f} iters={r.iterations} "
    f"gap={r.rel_gap:.2e} solve={r.solve_time:.2f}s wall={wall:.1f}s",
    flush=True,
)

row = {
    "config": f"stormG2-like sparse block_angular({K},{mb}x{nb},link={link}) "
              f"hint-less, {p.shape[0]} rows",
    "backend": r.backend,
    "time_s": round(r.solve_time, 3),
    "iters": int(r.iterations),
    "iters_per_sec": round(r.iters_per_sec, 2),
    "status": r.status.value,
    "tol": 1e-8,
    "detect_s": round(t_detect, 3),
    "detected_blocks": int(hint["num_blocks"]),
    "vs_baseline": None,
}
if not skip_baseline:
    rb = solve(p, backend="cpu-sparse", max_iter=max_iter)
    print(
        f"cpu-sparse: {rb.status.name} obj={rb.objective:.6f} "
        f"iters={rb.iterations} solve={rb.solve_time:.2f}s",
        flush=True,
    )
    row["baseline_backend"] = "cpu-sparse"
    row["baseline_time_s"] = round(rb.solve_time, 3)
    row["vs_baseline"] = round(rb.solve_time / max(r.solve_time, 1e-9), 2)
with open("/root/repo/.storm20k.json", "w") as fh:
    json.dump(row, fh, indent=2)
print(json.dumps(row), flush=True)
