"""Iterate checkpoint/resume (SURVEY.md §5.4).

IPM state is tiny — (x, y, s, w, z) plus the iteration counter — so a
plain ``.npz`` with atomic rename is the honest mechanism; no Orbax
machinery is warranted for five vectors. The driver writes every
``config.checkpoint_every`` iterations and :func:`load_state` lets a solve
resume with ``warm_start=``.

Format v2 hardening: each checkpoint carries a format version and a
*problem fingerprint* (shapes + a hash of the c/b bytes of the interior
form it was taken from). :func:`load_state` refuses to hand a checkpoint
from a different problem to a resume — the failure mode it closes is a
stale ``--checkpoint`` path silently seeding a solve with another LP's
iterate (shape-coincident garbage converges to the wrong answer; a shape
mismatch merely crashes later and uglier).

Format v3 (elastic recovery): checkpoints are **sharding-layout
independent** by contract. ``save_state`` force-materializes every field
on the host (``np.asarray`` pulls sharded device arrays down), so a
checkpoint written from an 8-device mesh restores onto a 6-device mesh, a
single device, or the CPU — placement belongs to the *active* backend's
``from_host``/``shardings()``, never to the file. v3 additionally records
the canonical (unpadded) problem shapes ``m``/``n`` and refuses a file
whose arrays disagree with them (a truncated/corrupt write fails loudly
instead of resuming garbage). v1 (no version/fingerprint) and v2 (no
shape fields) checkpoints still load.
"""

from __future__ import annotations

import os
import tempfile
import warnings
from typing import Optional, Tuple

import numpy as np

from distributedlpsolver_tpu.ipm.state import IPMState

# One fingerprint definition for the whole repo (utils/fingerprint.py):
# checkpoints and the warm cache must agree on what "same problem" means.
from distributedlpsolver_tpu.utils.fingerprint import (  # noqa: F401
    problem_fingerprint,
)

CKPT_FORMAT_VERSION = 3


class CheckpointMismatch(RuntimeError):
    """Checkpoint belongs to a different problem (fingerprint conflict),
    is internally inconsistent (v3 shape fields vs stored arrays), or was
    written by a newer, unreadable format version."""


def save_state(
    path: str,
    state: IPMState,
    iteration: int,
    name: str = "",
    fingerprint: str = "",
) -> None:
    """Atomically write a host-canonical checkpoint.

    ``np.asarray`` materializes each field on the host regardless of how
    the live iterate was placed (replicated, column-sharded over a mesh,
    already numpy) — the file never encodes a device layout, which is
    what lets the elastic supervisor resume the same checkpoint on a
    re-formed, smaller mesh. Callers hand in the *unpadded* state (the
    driver checkpoints ``backend.to_host`` output, which slices mesh
    padding off); the recorded m/n are the canonical shapes a v3 load
    re-validates.
    """
    arrays = {f: np.asarray(getattr(state, f)) for f in state._fields}
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(
                fh,
                iteration=iteration,
                name=name,
                version=CKPT_FORMAT_VERSION,
                fingerprint=fingerprint,
                m=int(arrays["y"].shape[0]),
                n=int(arrays["x"].shape[0]),
                **arrays,
            )
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_state(
    path: str, expected_fingerprint: Optional[str] = None
) -> Tuple[IPMState, int, str]:
    """Load a checkpoint as host numpy arrays (placement is the caller's
    backend's job — ``from_host`` re-pads/re-shards for the active
    layout); raises :class:`CheckpointMismatch` when
    ``expected_fingerprint`` is given and conflicts with the stored one,
    or when a v3 file's recorded shapes disagree with its arrays. v1
    checkpoints have no fingerprint and are accepted as-is; v2 have no
    shape fields and skip that check."""
    with np.load(path, allow_pickle=False) as data:
        version = int(data["version"]) if "version" in data else 1
        if version > CKPT_FORMAT_VERSION:
            raise CheckpointMismatch(
                f"{path}: checkpoint format v{version} is newer than this "
                f"reader (v{CKPT_FORMAT_VERSION})"
            )
        stored = str(data["fingerprint"]) if "fingerprint" in data else ""
        if expected_fingerprint and stored and stored != expected_fingerprint:
            raise CheckpointMismatch(
                f"{path}: checkpoint fingerprint {stored} does not match the "
                f"problem being solved ({expected_fingerprint}) — refusing to "
                f"resume from a different problem's iterate"
            )
        state = IPMState(*(data[f] for f in IPMState._fields))
        if version >= 3:
            m, n = int(data["m"]), int(data["n"])
            if state.x.shape != (n,) or state.y.shape != (m,):
                raise CheckpointMismatch(
                    f"{path}: stored arrays x{state.x.shape}/y{state.y.shape} "
                    f"disagree with the recorded canonical shapes "
                    f"(n={n}, m={m}) — corrupt or non-canonical checkpoint"
                )
        return state, int(data["iteration"]), str(data["name"])


def maybe_load(
    path: Optional[str], expected_fingerprint: Optional[str] = None
) -> Optional[Tuple[IPMState, int, str]]:
    """Resume helper: None when no checkpoint exists; a fingerprint
    mismatch warns and returns None (fresh start, the path is about to be
    overwritten by this solve's own checkpoints) rather than raising."""
    if path and os.path.exists(path):
        try:
            return load_state(path, expected_fingerprint)
        except CheckpointMismatch as e:
            warnings.warn(f"ignoring checkpoint: {e}", stacklevel=2)
            return None
    return None
