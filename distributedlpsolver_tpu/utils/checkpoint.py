"""Iterate checkpoint/resume (SURVEY.md §5.4).

IPM state is tiny — (x, y, s, w, z) plus the iteration counter — so a
plain ``.npz`` with atomic rename is the honest mechanism; no Orbax
machinery is warranted for five vectors. The driver writes every
``config.checkpoint_every`` iterations and :func:`load_state` lets a solve
resume with ``warm_start=``.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional, Tuple

import numpy as np

from distributedlpsolver_tpu.ipm.state import IPMState


def save_state(path: str, state: IPMState, iteration: int, name: str = "") -> None:
    arrays = {f: np.asarray(getattr(state, f)) for f in state._fields}
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, iteration=iteration, name=name, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_state(path: str) -> Tuple[IPMState, int, str]:
    with np.load(path, allow_pickle=False) as data:
        state = IPMState(*(data[f] for f in IPMState._fields))
        return state, int(data["iteration"]), str(data["name"])


def maybe_load(path: Optional[str]) -> Optional[Tuple[IPMState, int, str]]:
    if path and os.path.exists(path):
        return load_state(path)
    return None
