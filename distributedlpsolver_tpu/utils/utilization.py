"""Utilization folding for phase reports (VERDICT round 3 item 4).

`core.drive_phase_plan` records one ``{"phase", "iters", "wall_s"}`` row
per phase; the backends stamp each row's ``"mode"`` from their own plan
specs ("f32"/"mixed"/"f64"/"f64c"/"pcg"/"endgame"). This helper turns
that into the utilization fields the scale artifacts record: effective
FLOP/s per assembly-bound phase and its percentage of the watchdog seed
rates (`core.SEG_RATE_F32`/`SEG_RATE_F64` — the conservative per-dtype
device rates every backend already budgets segments with). PCG and
endgame phases get no rate: their per-iteration flops are data-dependent
(CG sweep counts; endgame host/device split), so a single
flops-per-iteration figure would be fiction — their rows still carry the
measured iters/wall split.
"""

from __future__ import annotations


def fold_utilization(report, flops_per_iter: float):
    """Annotate ``report`` rows (in place) with ``eff_flops_per_s`` and
    ``pct_of_seed_rate`` for the assembly-bound phases; returns the list.

    ``flops_per_iter`` is the backend's own per-iteration estimate for
    the direct factorization path (e.g. ``BlockAngularBackend._f64_flops``)
    — the same operation count runs in f32 and f64, only the seed rate
    differs.
    """
    from distributedlpsolver_tpu.ipm import core

    rates = {
        "f32": core.SEG_RATE_F32,
        "mixed": core.SEG_RATE_F32,
        "f64": core.SEG_RATE_F64,
        "f64c": core.SEG_RATE_F64,
    }
    for ph in report:
        seed = rates.get(ph.get("mode"))
        if seed and ph.get("iters") and ph.get("wall_s", 0) > 0:
            eff = flops_per_iter * ph["iters"] / ph["wall_s"]
            ph["eff_flops_per_s"] = f"{eff:.3g}"
            ph["pct_of_seed_rate"] = round(100.0 * eff / seed, 1)
    return report
