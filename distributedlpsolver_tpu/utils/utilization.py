"""Utilization folding for phase reports (VERDICT round 3 item 4).

`core.drive_phase_plan` records one ``{"phase", "iters", "wall_s"}`` row
per phase; the backends stamp each row's ``"mode"`` from their own plan
specs ("f32"/"mixed"/"f64"/"f64c"/"pcg"/"endgame"). This helper turns
that into the utilization fields the scale artifacts record: effective
FLOP/s per assembly-bound phase, its percentage of the watchdog seed
rates (`core.SEG_RATE_F32`/`SEG_RATE_F64` — the conservative per-dtype
device rates every backend already budgets segments with), and — the
honest number (VERDICT round 4 item 9) — its percentage of the CHIP's
peak for that arithmetic class. The two percentages answer different
questions: ``pct_of_seed_rate`` is budget-relative (is the phase running
at the rate its watchdog segments were sized for?), while
``pct_of_chip_peak`` is roofline-relative (how much of the silicon does
this phase actually use?). A healthy f32 phase can read ~50% of seed
while using low-single-digit percent of the MXU — both are reported so
neither can be mistaken for the other. PCG and endgame phases get no
rate: their per-iteration flops are data-dependent (CG sweep counts;
endgame host/device split), so a single flops-per-iteration figure would
be fiction — their rows still carry the measured iters/wall split.
"""

from __future__ import annotations

# Chip peaks for the utilization denominator, one TPU v5 lite (v5e) chip:
# ~197 TFLOP/s bf16 MXU; f32 matmul runs as bf16x3/x6 passes (~1/4 of
# bf16 → ~49 TFLOP/s usable f32 peak). Emulated f64 has no hardware
# peak; its practical ceiling is the measured MXU-split GEMM rate on
# this chip (~1.8e11 FLOP/s, scripts/probe_chol_mxu.py) — "100%" for
# f64 phases therefore means "at the platform's software-f64 GEMM
# ceiling", which is the only meaningful roofline for that class.
CHIP_PEAK_F32 = 4.9e13
CHIP_PEAK_F64_SW = 1.8e11


def fold_utilization(report, flops_per_iter: float):
    """Annotate ``report`` rows (in place) with ``eff_flops_per_s``,
    ``pct_of_seed_rate``, and ``pct_of_chip_peak``; returns the list.

    ``flops_per_iter`` is the backend's own per-iteration estimate for
    the direct factorization path (e.g. ``BlockAngularBackend._f64_flops``)
    — the same operation count runs in f32 and f64, only the rates
    differ.
    """
    from distributedlpsolver_tpu.ipm import core

    rates = {
        "f32": (core.SEG_RATE_F32, CHIP_PEAK_F32),
        "mixed": (core.SEG_RATE_F32, CHIP_PEAK_F32),
        "f64": (core.SEG_RATE_F64, CHIP_PEAK_F64_SW),
        "f64c": (core.SEG_RATE_F64, CHIP_PEAK_F64_SW),
    }
    for ph in report:
        pair = rates.get(ph.get("mode"))
        if pair and ph.get("iters") and ph.get("wall_s", 0) > 0:
            seed, peak = pair
            eff = flops_per_iter * ph["iters"] / ph["wall_s"]
            ph["eff_flops_per_s"] = f"{eff:.3g}"
            ph["pct_of_seed_rate"] = round(100.0 * eff / seed, 1)
            ph["pct_of_chip_peak"] = round(100.0 * eff / peak, 2)
            if ph["mode"] in ("f64", "f64c"):
                ph["chip_peak_basis"] = "software-f64 GEMM ceiling"
    return report
