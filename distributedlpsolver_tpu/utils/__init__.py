from distributedlpsolver_tpu.utils.checkpoint import (
    CheckpointMismatch,
    load_state,
    maybe_load,
    problem_fingerprint,
    save_state,
)
from distributedlpsolver_tpu.utils.logging import IterLogger

__all__ = [
    "CheckpointMismatch",
    "IterLogger",
    "load_state",
    "maybe_load",
    "problem_fingerprint",
    "save_state",
]
