from distributedlpsolver_tpu.utils.checkpoint import load_state, save_state
from distributedlpsolver_tpu.utils.logging import IterLogger

__all__ = ["IterLogger", "save_state", "load_state"]
