"""Accelerator-presence guard for measurement envelopes.

BENCH_r05 is the cautionary tale: the requested TPU was unavailable,
bench.py fell back to host CPU, and a whole measurement round produced
rows that — although honestly stamped ``"platform": "cpu-fallback"`` —
were unquotable and had to be thrown away (ROADMAP "Perf trajectory").
Stamping makes a bad round *detectable*; this guard makes it
*impossible*: ``bench.py --require-tpu`` and the ``scripts/run_*``
envelopes hard-fail up front instead of spending hours measuring the
wrong platform.
"""

from __future__ import annotations

import sys

REQUIRE_TPU_EXIT = 4  # distinct from solve-failure (2/3) exit codes


def require_tpu(enabled: bool = True) -> None:
    """Hard-fail (``SystemExit`` with code :data:`REQUIRE_TPU_EXIT`)
    unless jax's default backend is TPU. With ``enabled=False`` this is
    a no-op, so callers can write ``require_tpu("--require-tpu" in
    sys.argv)``. Must run before any fallback logic rewrites
    ``jax_platforms``."""
    if not enabled:
        return
    import jax

    try:
        backend = jax.default_backend()
    except RuntimeError as e:
        print(
            f"--require-tpu: accelerator initialization failed ({e}); "
            "refusing to fall back to CPU",
            file=sys.stderr,
        )
        raise SystemExit(REQUIRE_TPU_EXIT)
    if backend != "tpu":
        print(
            f"--require-tpu: default backend is {backend!r}, not TPU — "
            "aborting before any figure is produced (a fallback round "
            "is a wasted round, see BENCH_r05)",
            file=sys.stderr,
        )
        raise SystemExit(REQUIRE_TPU_EXIT)
