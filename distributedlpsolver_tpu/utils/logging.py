"""Structured per-iteration metrics (SURVEY.md §5.5).

The reference's published metric is "IPM iters/sec + wall-clock to 1e-8
duality gap" (BASELINE.json:2), which implies per-iteration reporting of
iteration count, gap trajectory, and timing. We emit both a human log line
and an optional JSONL stream, one record per iteration.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional, TextIO

from distributedlpsolver_tpu.ipm.state import IterRecord
from distributedlpsolver_tpu.obs import SCHEMA_VERSION

_HEADER = (
    f"{'it':>4} {'mu':>10} {'rel_gap':>10} {'pinf':>10} {'dinf':>10} "
    f"{'a_p':>6} {'a_d':>6} {'sigma':>8} {'pobj':>14} {'t_iter':>8}"
)


def stamp_record(payload: dict) -> dict:
    """Inject the shared record schema into one JSONL payload (in place):
    ``schema_version``, wall-clock ``ts`` (unix seconds — merging streams
    across processes), and monotonic ``t_mono`` (``perf_counter`` seconds
    — ordering within a process, and the clock the Chrome-trace events
    use, so a trace and a JSONL stream line up exactly). Every writer —
    IterLogger rows and events, and the CLI's serve output stream —
    routes through this one helper; ``cli report`` stays backward-
    compatible with unstamped PR 1–4 files."""
    payload.setdefault("schema_version", SCHEMA_VERSION)
    payload.setdefault("ts", round(time.time(), 6))
    payload.setdefault("t_mono", round(time.perf_counter(), 6))
    return payload


class IterLogger:
    """Per-iteration metric emitter.

    Each JSONL record is written as ONE ``write`` call and flushed
    immediately, so a solve killed mid-iteration (watchdog timeout, OOM
    kill, SIGKILL) leaves a complete, parseable telemetry file for
    post-mortem — the one consumer that matters for the crash log is the
    run that did NOT reach ``close()``. ``fsync=True`` additionally forces
    each record to stable storage (survives a machine crash, not just a
    process crash) at a per-iteration syscall cost that is noise next to a
    device step.
    """

    def __init__(
        self,
        verbose: bool = False,
        jsonl_path: Optional[str] = None,
        fsync: bool = False,
        append: bool = False,
    ):
        # ``append`` keeps an existing stream: the supervisor's retries
        # re-enter the driver (one IterLogger per attempt) and must not
        # truncate the telemetry of the attempts — and the supervisor's
        # fault/resume event records — that came before. O_APPEND also
        # makes the supervisor's concurrent event handle safe: both
        # handles write whole flushed lines at the file end.
        self.verbose = verbose
        mode = "a" if append else "w"
        self._fh: Optional[TextIO] = (  # guarded-by: _lock
            open(jsonl_path, mode) if jsonl_path else None
        )
        self._fsync = fsync
        self._printed_header = False
        # The serve layer writes this stream from two threads (the submit
        # thread logs admission rejections while the dispatcher logs
        # results); whole-line writes interleave safely but flush/fsync
        # pairs do not, so serialize record emission.
        self._lock = threading.Lock()

    def log(self, rec: IterRecord) -> None:
        if self.verbose:
            if not self._printed_header:
                print(_HEADER)
                self._printed_header = True
            print(
                f"{rec.iter:>4} {rec.mu:>10.2e} {rec.rel_gap:>10.2e} "
                f"{rec.pinf:>10.2e} {rec.dinf:>10.2e} {rec.alpha_p:>6.3f} "
                f"{rec.alpha_d:>6.3f} {rec.sigma:>8.1e} {rec.pobj:>14.6e} "
                f"{rec.t_iter:>8.4f}"
            )
        self._write(rec.asdict())

    def event(self, payload: dict) -> None:
        """Write one non-iteration event record (fault classified, resume
        landed) into the same JSONL stream, flushed like iteration rows.
        Events carry an ``"event"`` key so consumers separate them from
        iteration records (which never have one)."""
        self._write(payload)

    def _write(self, payload: dict) -> None:
        # The single JSONL emission point: every record — iteration row
        # or event — is schema-stamped here and written as one flushed
        # line. The handle check lives INSIDE the lock: close() nulls
        # _fh under it, and a dispatcher thread outliving shutdown's
        # join timeout must drop records silently, not race a closing
        # handle.
        with self._lock:
            if self._fh:
                self._fh.write(json.dumps(stamp_record(payload)) + "\n")
                self._fh.flush()
                if self._fsync:
                    os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.flush()
                self._fh.close()
                self._fh = None
