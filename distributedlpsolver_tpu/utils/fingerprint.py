"""Problem fingerprints — ONE definition of problem identity, shared by
checkpoint validation (utils/checkpoint.py) and the warm-start cache
(serve/warmcache.py).

Two identities exist because two consumers need different invariances:

* :func:`problem_fingerprint` — the *instance* identity (shapes plus a
  hash over the c/b bytes). Checkpoints carry it so a stale
  ``--checkpoint`` path can never seed a solve with another LP's
  iterate (checkpoint format v2).
* :func:`structural_fingerprint` — the *model* identity: the A pattern
  and values, the shapes, and the bounds shape (which columns/rows are
  bounded), with b and c deliberately left out. Correlated serve
  traffic — the same model re-solved with perturbed b/c, parameterized
  streams — maps to ONE structural key, which is what lets the warm
  cache amortize presolve/scaling/structure work and seed delta-solves
  from a prior iterate of the same structure.
"""

from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np

try:  # scipy is already a hard dependency of models/; guard anyway
    import scipy.sparse as _sp
except Exception:  # pragma: no cover - scipy is baked into the image
    _sp = None


def _hash_array(h, v) -> None:
    h.update(np.ascontiguousarray(np.asarray(v, dtype=np.float64)).tobytes())


def problem_fingerprint(inf) -> str:
    """Stable identity of an interior-form problem: (m, n) plus a SHA-256
    over the c and b bytes (f64-normalized so dtype does not perturb it)."""
    h = hashlib.sha256()
    h.update(f"{int(inf.m)}x{int(inf.n)}".encode())
    for v in (inf.c, inf.b):
        _hash_array(h, v)
    return h.hexdigest()[:16]


def structural_fingerprint(
    A,
    m: Optional[int] = None,
    n: Optional[int] = None,
    lb=None,
    ub=None,
) -> str:
    """Structural identity of an LP model: SHA-256 over (m, n), the A
    pattern *and values* (same-A is the delta-solve contract — a changed
    coefficient is a different model), and the bounds *shape* (the
    finite/infinite pattern of lb/ub, not their values, so a stream that
    jitters bounds within the same pattern still shares the key).

    ``A`` may be dense or scipy-sparse; sparse matrices hash their CSR
    structure (indptr/indices/data), dense ones their f64 bytes. Returns
    the full 64-hex digest — the warm cache keys on it verbatim and
    additionally verifies recorded shapes at lookup (collision guard).
    """
    if m is None or n is None:
        m, n = A.shape
    h = hashlib.sha256()
    h.update(f"{int(m)}x{int(n)}".encode())
    if _sp is not None and _sp.issparse(A):
        csr = A.tocsr()
        h.update(np.ascontiguousarray(csr.indptr, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(csr.indices, dtype=np.int64).tobytes())
        _hash_array(h, csr.data)
    else:
        _hash_array(h, A)
    for tag, bound in (("lb", lb), ("ub", ub)):
        h.update(tag.encode())
        if bound is not None:
            h.update(np.packbits(np.isfinite(np.asarray(bound))).tobytes())
    return h.hexdigest()
