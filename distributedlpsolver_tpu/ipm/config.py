"""Solver configuration (SURVEY.md §5.6: flag system → frozen dataclass).

One frozen dataclass carries every tunable the CLI exposes; backends receive
it at ``setup`` time. Defaults reproduce the reference's published behavior
(convergence at a 1e-8 duality gap, BASELINE.json:2) with TPU-appropriate
numerics (f64 accumulation; optionally f32 factorization with iterative
refinement on hardware where f64 is emulated).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    tol: float = 1e-8  # relative gap + infeasibility tolerance [BASELINE.json:2]
    max_iter: int = 200
    eta: float = 0.99995  # fraction-to-boundary damping (Mehrotra)
    sigma_power: float = 3.0  # σ = (μ_aff/μ)^power
    sigma_min: float = 1e-8
    sigma_max: float = 0.99
    gamma_cent: float = 1e-3  # N₋∞ centrality neighborhood (0 disables)
    # Static primal regularization added to 1/d. 1e-8 caps the scaling
    # spread d_max at ~1e8, keeping the noise floor of the normal-equations
    # back-substitution below the 1e-8 gap tolerance; the resulting
    # direction perturbation is corrected by kkt_refine (the regularized
    # factorization acts as a preconditioner for true-KKT refinement).
    reg_primal: float = 1e-8
    reg_dual: float = 1e-10  # static dual regularization added to M's diagonal
    reg_grow: float = 100.0  # factor applied on factorization failure
    max_refactor: int = 5  # NaN-recovery attempts per iteration
    dtype: str = "float64"  # iterate/residual dtype
    # Cholesky/assembly dtype. "auto" (default) = two-phase on TPU: f32
    # factorizations (MXU-native) until optimal or stalled, then f64
    # warm-started to the full tolerance — elsewhere plain ``dtype``.
    # A concrete name ("float32"/"float64") forces single-phase at that
    # precision; None = same as dtype.
    factor_dtype: Optional[str] = "auto"
    # Accepted steps without ≥10% improvement in max(gap, pinf, dinf)
    # before a fused-loop phase gives up (phase 1 hands over to f64;
    # a final phase reports Status.STALLED). 0 disables.
    stall_window: int = 8
    # Two-phase handoff tolerance: phase 1 (f32) converges to
    # max(tol, phase1_tol) and hands the iterate to f64 — safely above the
    # f32 noise floor (~1e-6), where grinding injures the iterate's
    # centrality beyond what f64 can repair (observed). Phase 1's μ-floor
    # is also keyed to this, keeping the handoff iterate well-centered.
    phase1_tol: float = 3e-5
    # Fused Pallas normal-equations assembly (ops/normal_eq.py). None =
    # auto: on for single-device TPU placement with a single-precision
    # factor_dtype and refine_steps == 0.
    use_pallas: Optional[bool] = None
    refine_steps: int = 0  # normal-equations-level refinement sweeps per solve
    # Full-accuracy solve mode of the dense TPU path. "direct" = the f64
    # factorization phase 2; "pcg" = f32-Cholesky-preconditioned conjugate
    # gradient whose operator applies A·diag(d)·Aᵀ matrix-free in f64 (two
    # chunked GEMVs per CG step) — no f64 assembly or Cholesky ever runs,
    # which is what makes reference-scale dense (10k×50k, BASELINE.json:9)
    # tractable on emulated-f64 hardware. None = auto: "pcg" on
    # single-device TPU two-phase placement above ~16M matrix entries.
    solve_mode: Optional[str] = None
    cg_iters: int = 100  # PCG iteration cap per Newton solve
    cg_tol: float = 1e-11  # PCG relative-residual target
    # PCG-phase handoff tolerance of the DENSE two-phase schedule, the
    # exact phase1_tol mechanism one level down: the f32-assembled
    # preconditioner floors PCG directions near ~1e-6 at scale, and a
    # phase whose μ-floor is keyed to the FINAL tol grinds μ to ~1e-9 on
    # floor-limited directions — an off-center iterate the full-precision
    # finish cannot repair (observed at 10k×50k: the endgame oscillated
    # at 7e-6 from such a handoff). The dense PCG phase therefore
    # converges to max(tol, pcg_handoff_tol) with its μ-floor keyed
    # there, and the f64 finish (fused phase or endgame) owns the last
    # orders. The BLOCK backend's segmented PCG plan applies the same
    # clamp, finishing with the n-chunked true-f64 Schur mode ("f64c" —
    # one-shot f64 assembly cannot be lowered at its huge shapes; see
    # block_angular._solve_segmented).
    pcg_handoff_tol: float = 1e-6
    kkt_refine: int = 2  # KKT-level refinement rounds per Newton solve
    # KKT-refinement rounds of the dense ENDGAME step (ROUND5_NOTES
    # lever 1). The old hardwired kkt_refine=0 was a host-era
    # program-size constraint — each refinement round added a full eager
    # host solve + device residual pair and ~3×'d the emulated-f64
    # program whose compile had to stay under the tunnel's response
    # drop. The round-5 endgame's solves are cheap panel substitutions
    # (ops/chol_mxu.py), so one round is restored by default: it
    # recovers the cancellation digits the regularized normal-equations
    # back-substitution loses, exactly where the terminal μ-stall cycle
    # burns iterations. None = auto (1); 0 restores the legacy
    # no-refinement endgame; host-factor endgame steps still cap at 1
    # (see endgame_host below). CPU equivalence is test-pinned; the TPU
    # iteration-count measurement is deferred to the next accelerator
    # round.
    endgame_kkt_refine: Optional[int] = None
    # Endgame factorization placement (dense huge-m finish). On hardware
    # whose f64 is emulated (TPU), the endgame's Cholesky breaks down
    # (NaN) orders of magnitude above real-f64 breakdown — measured at
    # 10k×50k: unfactorable below reg ≈ 1e-7 on-device while host LAPACK
    # factors the same matrix at reg ≈ 1e-11 — and the attainable
    # pinf/μ floor scales with the reg actually used. True moves ONLY
    # the m×m factorization and triangular solves to host LAPACK (true
    # f64); the O(m²·n) assembly and all refinement matvecs stay on
    # device. False forces the on-device factorization. None = auto:
    # host on TPU, device elsewhere (where device f64 already IS
    # LAPACK-grade). Note: host-endgame steps cap kkt_refine at 1
    # regardless of the setting here — each eager KKT round is a full
    # host solve + device residual pair, and the host solve already
    # refines against the true operator internally; one round restores
    # the cancellation digits, more only adds host↔device latency.
    endgame_host: Optional[bool] = None
    # Gondzio correctors in the ENDGAME only (StepParams.mcc): there the
    # factorization dwarfs a solve (10k×50k: ~10 s mxu factor vs ~2 s
    # extra solve), so extra centrality correctors that lengthen
    # collapsed steps are nearly free per saved iteration. 0 disables.
    endgame_mcc: int = 2
    # Ruiz-equilibrate the interior form before solving (presolve scaling;
    # convergence is then tested in the scaled space, standard practice).
    scale: bool = True
    # Structural presolve (models/presolve.py): singleton/empty/redundant
    # rows, fixed/empty columns, early infeasibility/unboundedness — with
    # exact primal+dual postsolve. Applied to general-form problems only
    # (an InteriorForm input or a block_structure hint skips it).
    presolve: bool = True
    # distribution (sharded backends)
    mesh_shape: Optional[Tuple[int, ...]] = None  # None = all local devices
    mesh_axis: str = "cols"  # axis name for the variable-sharded mesh dim
    # Per-bucket mixed-precision schedule of the SERVING path
    # (backends/batched.solve_bucket): "df32" runs the tolerance-tiered
    # f32-gram → df32-elementwise → f64c-finisher phase ladder (see
    # :meth:`bucket_phases` — the round-5 dense/block schedules pushed
    # into the bucket programs), "f64" forces the legacy single-phase
    # bucket loop at ``factor_dtype_resolved``. None/"auto" = "df32" on
    # TPU (where emulated-f64 elementwise is the measured wall,
    # ROUND5_NOTES lever 3), "f64" elsewhere (native f64 beats the extra
    # phases on CPU). The schedule is a static key of the one compiled
    # program per (bucket, tol) — it never adds warm recompiles.
    bucket_schedule: Optional[str] = None
    # Iterations fused per while-loop trip of the batched/bucket device
    # loops (traced inner fori_loop over the masked step): the loop
    # predicate — the only cross-device collective of a sharded bucket
    # dispatch — and the segment-boundary bookkeeping run k× less often.
    # Semantics are exactly k=1 (each fused micro-step re-checks the
    # loop guard and masks all writes), so results are bitwise stable in
    # k. None = auto: 8 on TPU, 1 elsewhere.
    fused_iters: Optional[int] = None
    # Fused on-device solve loop (lax.while_loop over iterations; no
    # per-iteration host round trip). None = auto: used when the backend
    # supports it and per-iteration checkpointing is off.
    fused_loop: Optional[bool] = None
    # Segment the fused loop into host-driven chunks of ~this many
    # iterations (adaptively resized toward ~15s of device time each).
    # Bounds single-program runtime — tunneled/remote TPUs enforce an
    # execution watchdog (~60s observed) that a long fused solve trips.
    # None = auto: 8 on TPU, 0 (unsegmented) elsewhere.
    segment_iters: Optional[int] = None
    # diagnostics
    verbose: bool = False
    log_jsonl: Optional[str] = None  # per-iteration JSONL path (SURVEY.md §5.5)
    # fsync the JSONL stream after every record: telemetry survives a
    # machine crash, not just a process crash (flush alone covers the
    # latter). Off by default — a per-iteration syscall is noise next to a
    # device step but not next to a 10ms CPU solve.
    log_fsync: bool = False
    # Open the JSONL stream in append mode instead of truncating: the
    # supervisor's retries each re-enter the driver, and attempt N must
    # not erase the telemetry (and fault/resume event records) of
    # attempts 1..N-1. The supervisor truncates the file once up front.
    log_append: bool = False
    checkpoint_path: Optional[str] = None  # iterate checkpoint (SURVEY.md §5.4)
    checkpoint_every: int = 0  # 0 = disabled
    profile_dir: Optional[str] = None  # jax.profiler trace dir (SURVEY.md §5.1)

    def __post_init__(self):
        if self.endgame_host is not None and not isinstance(
            self.endgame_host, bool
        ):
            # A string ("host"/"device") would be truthy and silently
            # select host mode either way — reject like solve_mode does.
            raise ValueError(
                f"endgame_host must be None, True, or False; "
                f"got {self.endgame_host!r}"
            )
        if self.solve_mode not in (None, "direct", "pcg"):
            # A typo ("PCG", "cg") silently selecting the direct path
            # would re-enable the emulated-f64 work the mode exists to
            # avoid — reject it here like the use_pallas checks do.
            raise ValueError(
                f"solve_mode must be None, 'direct', or 'pcg'; "
                f"got {self.solve_mode!r}"
            )
        if self.bucket_schedule not in (None, "auto", "f64", "df32"):
            # A typo ("DF32", "mixed") silently selecting the legacy
            # single-phase loop would drop the mixed-precision win
            # without a trace — reject like solve_mode does.
            raise ValueError(
                f"bucket_schedule must be None, 'auto', 'f64', or "
                f"'df32'; got {self.bucket_schedule!r}"
            )
        if self.fused_iters is not None and self.fused_iters < 1:
            raise ValueError(
                f"fused_iters must be None or >= 1; got {self.fused_iters!r}"
            )

    def replace(self, **kw) -> "SolverConfig":
        return dataclasses.replace(self, **kw)

    def factor_dtype_resolved(self) -> str:
        """Concrete factorization dtype for single-phase execution paths
        ("auto" resolves to ``dtype`` — the two-phase schedule is a backend
        decision, see :meth:`two_phase_enabled`)."""
        fd = self.factor_dtype
        return self.dtype if fd in (None, "auto") else fd

    def two_phase_enabled(self, platform: str) -> bool:
        """Whether the f32→f64 two-phase fused solve should be used."""
        return self.factor_dtype == "auto" and platform == "tpu"

    def bucket_schedule_resolved(self, platform: str) -> str:
        """Concrete bucket schedule name ("df32" or "f64") — auto picks
        "df32" exactly on TPU (ROUND5_NOTES lever 3: the emulated-f64
        elementwise wall the schedule removes doesn't exist on CPU)."""
        bs = self.bucket_schedule
        if bs in (None, "auto"):
            return "df32" if platform == "tpu" else "f64"
        return bs

    def fused_iters_resolved(self, platform: str) -> int:
        """Concrete fused-iterations-per-while-trip for the batched and
        bucket device loops (auto: 8 on TPU, 1 elsewhere)."""
        if self.fused_iters is not None:
            return self.fused_iters
        return 8 if platform == "tpu" else 1

    def bucket_phases(self, tol: float, platform: str):
        """The serving bucket's precision-phase ladder for one tolerance
        tier: a static tuple of ``(engine, phase_tol)`` pairs consumed by
        backends/batched._solve_bucket_jit as part of its compile key
        (one program per (bucket, tol) — the schedule never forks the
        warm cache).

        Engines: ``"f32"`` — f32 factorization + assembly on the precast
        copy (gram-form MXU route; iterates/residuals stay f64, so its
        verdicts are honest whenever its phase tol equals the final
        tol); ``"df32"`` — full-precision factorization route with the
        KKT back-substitution and scaling elementwise chains in df32
        (ops/df32.py, ~1e-13 direction error); ``"f64"`` — the plain
        full-precision loop (the f64c finisher on TPU, where f64 is the
        emulated two-float chain). Tiers mirror what round 5 gave the
        dense/block backends: tight tolerances take all three phases,
        mid tiers stop at df32 (its noise floor is orders below), loose
        tiers run f32 alone.
        """
        if self.bucket_schedule_resolved(platform) != "df32":
            return (("f64", tol),)
        p1 = max(tol, self.phase1_tol)
        if tol <= 1e-6:
            return (("f32", p1), ("df32", tol), ("f64", tol))
        if tol <= 1e-3:
            return (("f32", p1), ("df32", tol))
        return (("f32", tol),)

    def phase1_params(self) -> "StepParams":
        """Step params of the two-phase f32 phase: tol loosened to the
        handoff tolerance (single source of the handoff rule — the
        loosened tol also keys the μ-floor that keeps the handoff iterate
        centered), plus the μ-vs-pinf balance floor — an f32 phase's
        directions bound how fast pinf can fall, and letting μ race
        orders of magnitude below that bound hands the full-precision
        phase an injured iterate (StepParams.mu_pinf_floor)."""
        return self.replace(tol=max(self.tol, self.phase1_tol)).step_params(
            mu_pinf_floor=0.03
        )

    def step_params(self, mu_pinf_floor: float = 0.0,
                    mcc: int = 0, elementwise: str = "native") -> "StepParams":
        return StepParams(
            tol=self.tol,
            eta=self.eta,
            sigma_power=self.sigma_power,
            sigma_min=self.sigma_min,
            sigma_max=self.sigma_max,
            gamma_cent=self.gamma_cent,
            reg_primal=self.reg_primal,
            kkt_refine=self.kkt_refine,
            mu_pinf_floor=mu_pinf_floor,
            mcc=mcc,
            elementwise=elementwise,
        )

    def bucket_phase_params(self, engine: str, phase_tol: float) -> "StepParams":
        """StepParams of one :meth:`bucket_phases` phase. The f32 phase
        carries the μ-vs-pinf balance floor exactly like
        :meth:`phase1_params` (limited-precision directions bound how
        fast pinf can fall); the df32 phase flips the step's elementwise
        engine and needs no floor — its ~1e-13 noise sits five orders
        under the 1e-8 tolerance."""
        base = self.replace(tol=phase_tol)
        if engine == "f32":
            return base.step_params(mu_pinf_floor=0.03)
        return base.step_params(
            elementwise="df32" if engine == "df32" else "native"
        )


@dataclasses.dataclass(frozen=True)
class StepParams:
    """The numeric subset of :class:`SolverConfig` the traced step actually
    reads. This — not the full config — is the static jit key, so changing
    diagnostic fields (log paths, checkpoint paths, verbosity, max_iter)
    never forces an XLA recompile."""

    tol: float
    eta: float
    sigma_power: float
    sigma_min: float
    sigma_max: float
    gamma_cent: float
    reg_primal: float
    kkt_refine: int
    # Pure centering step: skip the predictor entirely and aim every
    # complementarity product at the CURRENT μ (σ=1, no second-order
    # cross term). The blocked-step remedy (dense endgame anti-stagnation
    # ladder): a Mehrotra direction that anti-centers the minimum pair
    # can pin both ratio tests at ~0 while σ stays tiny (the affine step
    # keeps predicting progress the N₋∞ guard cannot accept) — the
    # centering direction is admissible by construction and restores the
    # step room the next Mehrotra iteration needs.
    center: bool = False
    # μ-vs-feasibility balance floor (0 disables): keep the centering
    # target μ ≥ this · pinf_rel · (1+|pobj|)/ncomp, so complementarity
    # cannot run arbitrarily far below the remaining primal
    # infeasibility. Exists for LIMITED-PRECISION phases: the gram-form
    # f32 block phase drove rel_gap to 2e-4 while its f32 directions
    # floored pinf at 3e-3 (μ ~1e5× below pinf) — an injured iterate
    # the f64 finisher could not repair and the divergence heuristic
    # misread as PRIMAL_INFEASIBLE (observed, pds-20-class 2026-08-01).
    mu_pinf_floor: float = 0.0
    # Gondzio-style multiple centrality correctors: up to this many
    # extra complementarity-only solves per iteration, each reusing the
    # factorization to pull outlier pair products back into a band
    # around the centering target and re-testing the step lengths — a
    # candidate is kept only if it lengthens the step. Exists for
    # phases where the factorization dwarfs a solve (the 10k endgame:
    # BENCH_10K.json round 4 shows α collapsing to 0.03–0.18 with
    # near-pure-centering σ across its 41–48 — the textbook signature
    # these correctors fix). 0 = off (every non-endgame path).
    mcc: int = 0
    # Elementwise engine of the KKT back-substitution and scaling chains
    # inside the traced step: "native" runs them in the iterate dtype
    # (emulated f64 on TPU); "df32" routes them through the two-float
    # layer (ops/df32.py — f32 VPU speed, ~1e-13 relative error), the
    # round-5 lever-3 schedule of the serving bucket programs. Residuals,
    # matvecs, factorizations, and the convergence tests stay native, so
    # a df32 phase's OPTIMAL verdicts are honest. jax paths only.
    elementwise: str = "native"
