"""Warm-started IPM: safeguarded initial iterates from prior solutions.

Production LP traffic is correlated — the same model re-solved with
perturbed b/c (MPAX-style parameterized streams, arXiv:2412.09734), so a
prior optimum of the *same structure* is a far better starting point than
Mehrotra's least-squares cold start... once it is pushed back into the
strict interior. A converged iterate sits essentially ON the boundary
(x_i·s_i ≈ tol-level for every pair); restarting there stalls the very
first step. The classic remedy (Gondzio-style warm start) is applied
here in two moves:

1. **shift** — clip every primal/dual pair component to a relative
   interior floor (bounded columns are additionally pulled strictly
   inside [0, u]);
2. **recentre** — lift the *smaller* factor of any complementarity pair
   whose product sits below ``β·μ_w`` (the candidate's own average), so
   no single pair starts the solve anti-centered.

The candidate is then **safeguarded** against adversarial priors: its
initial residual merit ``max(pinf, dinf)`` is compared against the
Mehrotra cold start's, and the warm iterate is only used when it does
not regress by more than :data:`WARM_ACCEPT_FACTOR` — otherwise the
solve falls back to the cold start (counted by the
``warm_start_rejected_total`` metric). The same construction runs traced
inside the batched bucket programs (backends/batched._warm_candidate) so
a serve batch can mix warm and cold members without recompiling.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from distributedlpsolver_tpu.ipm.state import IPMState

# Warm candidate accepted iff merit(warm) <= factor * merit(cold): a
# near-duplicate prior lands orders below the cold start's residuals, an
# adversarial (far-off) one lands orders above — 10x tolerates honest
# perturbation noise without admitting garbage.
WARM_ACCEPT_FACTOR = 10.0
# Second acceptance guard: the candidate's complementarity must not
# exceed this multiple of the cold start's μ. The primal/dual refresh
# makes even a far-off prior nearly FEASIBLE on the new instance (its
# residual merit alone would pass), but a e.g. 1e9-scaled iterate still
# carries a μ orders above any useful start — the μ guard is what
# actually rejects it.
MU_ACCEPT_FACTOR = 10.0
# Relative interior floor of the shift step (fraction of the vector's
# own mean magnitude): big enough that no pair starts frozen, small
# enough to stay near the prior optimum.
INTERIOR_FLOOR = 1e-4
# Recentre target: every pair product is lifted to at least β·μ_w.
CENTRALITY_BETA = 0.1
# Residual-aware μ floor of the recentre step, in mehrotra_step's
# mu_pinf_floor units: a prior OPTIMUM has μ ≈ 0, but on the *new*
# instance the candidate carries residuals ~‖Δb‖/‖Δc‖ — restarting with
# μ orders below that infeasibility hands the solver an iterate
# over-committed to the old active set (the exact failure
# StepParams.mu_pinf_floor exists for, observed here as warm solves
# SLOWER than cold). The recentre target is therefore
# max(β·μ_w, this·merit·(1+|pobj|)/ncomp).
MERIT_MU_FLOOR = 0.1


@dataclasses.dataclass
class WarmStart:
    """A prior iterate offered as a warm start (SAFEGUARDED: the driver
    shifts/recentres it and falls back to a cold start when its initial
    residuals regress — unlike a raw IPMState ``warm_start``, which is
    the trusted checkpoint-resume path and used verbatim).

    ``state`` is in the *unscaled interior space* of the same structure
    (what ``IPMResult``-adjacent host states and the warm cache hold).
    """

    state: IPMState
    source: str = ""  # provenance tag (telemetry: "cache", "caller", ...)


# Primal-projection size bound of the host engine: above this row count
# the AAᵀ factorization is real money on the host and the projection is
# skipped (the bucket engine projects in-program regardless — its
# factorization is MXU microseconds at serve shapes).
PROJECT_MAX_M = 4096


def interior_candidate(state: IPMState, inf) -> IPMState:
    """Build a warm candidate from a prior iterate for the NEW instance
    ``inf`` (host numpy; the traced twin lives in
    backends/batched._warm_select). Four moves:

    1. shift every pair component to a strict relative interior;
    2. **primal projection** (dense A, m ≤ PROJECT_MAX_M): one AAᵀ
       solve moves x onto the new ``Ax = b`` affine — the same-A
       delta-solve refresh, killing the ‖Δb‖ residual outright;
    3. **dual slack refresh**: s is re-derived from ``c − Aᵀy`` (split
       positively with z on bounded columns), killing the ‖Δc‖ residual;
    4. residual-aware centrality lift: every pair product is raised to
       ``max(β·μ_w, MERIT_MU_FLOOR·merit·(1+|pobj|)/ncomp)``.
    """
    x = np.asarray(state.x, dtype=np.float64).copy()
    y = np.asarray(state.y, dtype=np.float64)
    s = np.asarray(state.s, dtype=np.float64)
    z = np.asarray(state.z, dtype=np.float64)
    u = np.asarray(inf.u, dtype=np.float64)
    hub = np.isfinite(u)
    u_f = np.where(hub, u, 1.0)
    b = np.asarray(inf.b, dtype=np.float64)
    c = np.asarray(inf.c, dtype=np.float64)

    xm = max(float(np.mean(np.abs(x))), 1.0)
    sm = max(float(np.mean(np.abs(s))), 1.0)
    x = np.maximum(x, INTERIOR_FLOOR * xm)
    A = inf.A
    if isinstance(A, np.ndarray) and A.shape[0] <= PROJECT_MAX_M:
        try:
            import scipy.linalg as _sla

            M = A @ A.T
            M[np.diag_indices_from(M)] += 1e-10 * max(
                float(np.trace(M)) / max(A.shape[0], 1), 1.0
            )
            F = _sla.cho_factor(M)
            x = x + A.T @ _sla.cho_solve(F, b - A @ x)
            x = np.maximum(x, INTERIOR_FLOOR * xm)
        except Exception:  # degenerate AAᵀ: keep the shifted iterate
            pass
    # Bounded columns: strictly inside [0, u], slack re-derived.
    x = np.where(hub, np.clip(x, 0.01 * u_f, 0.99 * u_f), x)
    w = np.where(hub, u_f - x, 1.0)
    # Dual refresh: s − z = c − Aᵀy exactly wherever the positive split
    # allows, a floor-shift on both parts elsewhere.
    s_hat = c - np.asarray(A.T @ y).ravel()
    z = np.where(hub, np.maximum(z, INTERIOR_FLOOR * sm), 0.0)
    s = np.where(hub, s_hat + z, np.maximum(s_hat, INTERIOR_FLOOR * sm))
    deficit = np.where(hub, np.maximum(INTERIOR_FLOOR * sm - s, 0.0), 0.0)
    s = s + deficit
    z = z + deficit

    ncomp = x.shape[0] + int(hub.sum())
    mu = (x @ s + (hub * w) @ z) / max(ncomp, 1)
    # Residual-aware target (MERIT_MU_FLOOR): μ is rebalanced against
    # the candidate's remaining infeasibility before any step runs.
    merit = residual_merit(
        inf, IPMState(x=x, y=y, s=s, w=w, z=np.where(hub, z, 0.0))
    )
    pobj = float(c @ x)
    target = max(
        CENTRALITY_BETA * mu,
        MERIT_MU_FLOOR * merit * (1.0 + abs(pobj)) / max(ncomp, 1),
        1e-300,
    )
    # Lift the SMALLER factor of any pair below the centering target —
    # raising the larger one would move the iterate further than needed.
    with np.errstate(over="ignore", divide="ignore"):
        lift = np.sqrt(np.clip(target / np.maximum(x * s, 1e-300), 1.0, 1e16))
        liftw = np.sqrt(np.clip(target / np.maximum(w * z, 1e-300), 1.0, 1e16))
    x2 = np.where(x <= s, x * lift, x)
    s2 = np.where(s < x, s * lift, s)
    w2 = np.where(hub & (w <= z), w * liftw, w)
    z2 = np.where(hub & (z < w), z * liftw, z)
    # The lifted w may poke past u; the IPM tolerates r_u != 0 (it is an
    # infeasible-start method), and the pair stays strictly positive.
    return IPMState(x=x2, y=y, s=s2, w=np.where(hub, w2, 1.0),
                    z=np.where(hub, z2, 0.0))


def state_mu(state: IPMState, u) -> float:
    """Average complementarity of a host iterate (the μ-guard input)."""
    x = np.asarray(state.x, dtype=np.float64)
    s = np.asarray(state.s, dtype=np.float64)
    w = np.asarray(state.w, dtype=np.float64)
    z = np.asarray(state.z, dtype=np.float64)
    hub = np.isfinite(np.asarray(u, dtype=np.float64)).astype(np.float64)
    ncomp = x.shape[0] + int(hub.sum())
    return float((x @ s + (hub * w) @ z) / max(ncomp, 1))


def residual_merit(inf, state: IPMState) -> float:
    """``max(pinf, dinf)`` of a host-space iterate against an interior
    form — the same relative norms core.residual_norms computes, in
    plain numpy (A may be dense or scipy-sparse). The warm-vs-cold
    safeguard comparison runs on this."""
    x = np.asarray(state.x, dtype=np.float64)
    y = np.asarray(state.y, dtype=np.float64)
    s = np.asarray(state.s, dtype=np.float64)
    w = np.asarray(state.w, dtype=np.float64)
    z = np.asarray(state.z, dtype=np.float64)
    u = np.asarray(inf.u, dtype=np.float64)
    hub = np.isfinite(u).astype(np.float64)
    u_f = np.where(hub > 0, u, 1.0)
    b = np.asarray(inf.b, dtype=np.float64)
    c = np.asarray(inf.c, dtype=np.float64)
    r_p = b - np.asarray(inf.A @ x).ravel()
    r_u = hub * (u_f - x - w)
    r_d = c - np.asarray(inf.A.T @ y).ravel() - s + z
    pinf = float(np.sqrt(r_p @ r_p + r_u @ r_u) / (1.0 + np.linalg.norm(b)))
    dinf = float(np.linalg.norm(r_d) / (1.0 + np.linalg.norm(c)))
    return max(pinf, dinf)
