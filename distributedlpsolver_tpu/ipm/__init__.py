from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import IPMResult, IPMState, IterRecord, Status, StepStats
from distributedlpsolver_tpu.ipm.driver import solve

__all__ = ["SolverConfig", "IPMResult", "IPMState", "IterRecord", "Status", "StepStats", "solve"]
