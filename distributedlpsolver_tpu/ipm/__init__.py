from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import (
    FaultKind,
    FaultRecord,
    IPMResult,
    IPMState,
    IterRecord,
    Status,
    StepStats,
)
from distributedlpsolver_tpu.ipm.driver import SolveHooks, solve
from distributedlpsolver_tpu.ipm.warm import WarmStart

__all__ = [
    "FaultKind",
    "FaultRecord",
    "IPMResult",
    "IPMState",
    "IterRecord",
    "SolveHooks",
    "SolverConfig",
    "Status",
    "StepStats",
    "WarmStart",
    "solve",
]
