"""Mehrotra predictor-corrector step — the algorithm core.

This module is the single implementation of the IPM math, written
array-library-generically: every function takes a :class:`LinOps` bundle
whose ``xp`` is either ``numpy`` (eager CPU backends) or ``jax.numpy``
(jitted TPU/device backends).  Backends differ only in how they implement
the four linear-algebra callables — ``matvec``/``rmatvec`` with the
constraint matrix and ``factorize``/``solve`` for the normal equations
``M = A·diag(d)·Aᵀ`` (BASELINE.json:5 names exactly this path: normal
equations, dense Cholesky, triangular solves).  The distributed backends
swap in sharded arrays so XLA turns the same expressions into
psum-combined per-shard Schur contributions (SURVEY.md §3.4).

Problem form handled (ipm/state.py): ``min cᵀx  s.t. Ax=b, 0≤x, x+w=u`` on
the columns with finite upper bound.  Columns without a finite upper bound
carry ``w=1, z=0`` and every ``w``/``z`` term is masked by ``hub`` so the
arithmetic stays finite under jit (no data-dependent shapes — SURVEY.md §7
"keep shapes static").

Newton system and its elimination to normal equations::

    A dx               = r_p  := b - Ax
    dx + dw            = r_u  := u - x - w          (masked)
    Aᵀdy + ds - dz     = r_d  := c - Aᵀy - s + z
    S dx + X ds        = r_xs := target - x∘s
    Z dw + W dz        = r_wz := target - w∘z       (masked)

    ⇒  dinv = s/x + z/w,  h = r_d - r_xs/x + (r_wz - z∘r_u)/w
       (A·diag(1/dinv)·Aᵀ) dy = r_p + A(h/dinv)
       dx = (Aᵀdy - h)/dinv ;  ds = (r_xs - s∘dx)/x
       dw = r_u - dx ;  dz = (r_wz - z∘dw)/w
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

from distributedlpsolver_tpu.ipm.config import StepParams
from distributedlpsolver_tpu.ipm.state import IPMState, StepStats


class LinOps(NamedTuple):
    """Backend linear-algebra seam (SURVEY.md §1 L3 — the `SolverBackend`
    interface's execution half)."""

    xp: Any  # numpy or jax.numpy
    matvec: Callable[[Any], Any]  # v ↦ A @ v           (n,) → (m,)
    rmatvec: Callable[[Any], Any]  # v ↦ Aᵀ @ v          (m,) → (n,)
    factorize: Callable[[Any], Any]  # d ↦ factors of A·diag(d)·Aᵀ (+ reg)
    solve: Callable[[Any, Any], Any]  # (factors, rhs) ↦ M⁻¹ rhs
    # Optional exact primal-row closure: rv ↦ Aᵀ(A·Aᵀ)⁻¹·rv. When set,
    # each KKT solve corrects its final dx so A·dx equals its target —
    # the regularized normal-equations solve Tikhonov-filters precisely
    # the near-null-space component of the feasibility RHS (the
    # diagnosed 10k×50k terminal-pinf wall), and iterate-space repair
    # was measured to break centrality/step lengths instead. Two valid
    # implementations exist: a pure-jax closure over a precomputed f32
    # factor of A·Aᵀ (dense._make_ops — traces into fused/jitted
    # programs), and an eager host-LAPACK closure (the dense host
    # endgame). The default None leaves every other path unchanged.
    primal_project: Any = None


class ProblemData(NamedTuple):
    """Problem vectors as backend arrays. ``u_f`` is the upper-bound vector
    with +inf replaced by 1.0; ``hub`` the finite-ub mask as 0/1 floats."""

    c: Any  # (n,)
    b: Any  # (m,)
    u_f: Any  # (n,)
    hub: Any  # (n,)
    ncomp: Any  # scalar: n + #finite-ub (complementarity pair count)
    norm_b: Any  # scalar: 1 + ||b||₂
    norm_c: Any  # scalar: 1 + ||c||₂


def make_problem_data(xp, c, b, u, dtype) -> ProblemData:
    c = xp.asarray(c, dtype=dtype)
    b = xp.asarray(b, dtype=dtype)
    u = xp.asarray(u, dtype=dtype)
    hub = xp.isfinite(u).astype(dtype)
    u_f = xp.where(hub > 0, u, xp.asarray(1.0, dtype=dtype))
    return ProblemData(
        c=c,
        b=b,
        u_f=u_f,
        hub=hub,
        ncomp=c.shape[0] + xp.sum(hub),
        norm_b=1.0 + xp.linalg.norm(b),
        norm_c=1.0 + xp.linalg.norm(c),
    )


def _solve_kkt_once(ops: LinOps, state: IPMState, hub, d, factors, r_p, r_u,
                    r_d, r_xs, r_wz, elementwise: str = "native"):
    """Back-substitute one Newton solve through the normal equations.

    ``elementwise="df32"`` (StepParams.elementwise) runs the elementwise
    chains — the division-heavy h/dx/ds/dw/dz blocks that dominate the
    batched step on emulated-f64 hardware — through the two-float layer
    (ops/df32.py, ~1e-13 relative); the matvecs and the normal-equations
    solve keep their native route either way. jax-only (resolved at
    trace time: ``elementwise`` rides the static StepParams key).
    """
    x, y, s, w, z = state
    if elementwise == "df32":
        # Lazy import keeps jax out of this module's import path (the
        # eager numpy backends pass elementwise="native" and never reach
        # here).
        from distributedlpsolver_tpu.ops import df32 as _df32

        h = _df32.kkt_h(r_d, r_xs, x, r_wz, z, r_u, w)
        dy = ops.solve(factors, r_p + ops.matvec(_df32.mul64(d, h)))
        dx = _df32.kkt_dx(d, ops.rmatvec(dy), h)
        ds = _df32.kkt_ds(r_xs, s, dx, x)
        dw = _df32.sub64(r_u, dx)
        dz = _df32.kkt_dz(hub, r_wz, z, dw, w)
        return dx, dy, ds, dw, dz
    h = r_d - r_xs / x + (r_wz - z * r_u) / w
    dy = ops.solve(factors, r_p + ops.matvec(d * h))
    dx = d * (ops.rmatvec(dy) - h)
    ds = (r_xs - s * dx) / x
    dw = r_u - dx
    dz = hub * (r_wz - z * dw) / w
    return dx, dy, ds, dw, dz


def _solve_kkt(
    ops: LinOps, state: IPMState, hub, d, factors, r_p, r_u, r_d, r_xs, r_wz,
    refine: int, elementwise: str = "native",
):
    """Newton solve + ``refine`` rounds of KKT-level iterative refinement.

    Near convergence the scaling ``d`` spans ~1/μ orders of magnitude and
    the back-substitution ``dx = d·(Aᵀdy - h)`` loses ~μ⁻¹·ε of absolute
    accuracy to cancellation, which stalls primal feasibility around 1e-6
    (observed; refinement of the *normal-equations* solve alone cannot fix
    it). Re-evaluating the full 5-block KKT residual and solving for a
    correction restores the lost digits at the cost of one extra
    factorization-reuse solve per round.
    """
    x, y, s, w, z = state
    dx, dy, ds, dw, dz = _solve_kkt_once(
        ops, state, hub, d, factors, r_p, r_u, r_d, r_xs, r_wz, elementwise
    )
    for _ in range(refine):
        # KKT residuals stay native: they are the accuracy arbiter each
        # refinement round corrects toward, so they must not inherit the
        # df32 chains' (tiny but nonzero) rounding.
        e_p = r_p - ops.matvec(dx)
        e_u = hub * (r_u - (dx + dw))
        e_d = r_d - (ops.rmatvec(dy) + ds - dz)
        e_xs = r_xs - (s * dx + x * ds)
        e_wz = hub * (r_wz - (z * dw + w * dz))
        cx, cy, cs, cw, cz = _solve_kkt_once(
            ops, state, hub, d, factors, e_p, e_u, e_d, e_xs, e_wz, elementwise
        )
        dx, dy, ds, dw, dz = dx + cx, dy + cy, ds + cs, dw + cw, dz + cz
    if ops.primal_project is not None:
        # Exact primal-row closure (LinOps.primal_project), applied ONCE
        # on the final direction and deliberately NOT fed back into
        # ds/dz: those back-substitutions divide by x (resp. w), so a
        # tiny-column correction δ would come back as ds_i ~ δ_i·s_i/x_i
        # — measured at 10k×50k to explode dinf to O(1) and zero every
        # step length. dw IS kept consistent (dw = r_u − dx involves no
        # division), so the closure never leaks into the upper-bound
        # row. The residual it induces in the complementarity rows is
        # ~s·δ with δ the CURRENT solve's filtered junk (reg·D̃·dy-scale,
        # not the accumulated pinf) — absorbed by the corrector at any
        # μ above that scale, which is why the closure must be active
        # from the FIRST phase (junk must never accumulate past μ).
        delta = ops.primal_project(r_p - ops.matvec(dx))
        dx = dx + delta
        dw = dw - hub * delta
    return dx, dy, ds, dw, dz


def _max_step(xp, v, dv, v2, dv2, mask):
    """Largest α ≤ 1 with v+αdv ≥ 0 and (masked) v2+αdv2 ≥ 0 (ratio test,
    kept on device — SURVEY.md §7 'step-length reductions ... return only
    scalars')."""
    inf = xp.asarray(xp.inf, dtype=v.dtype)
    r1 = xp.where(dv < 0, -v / xp.where(dv < 0, dv, -1.0), inf)
    neg2 = (dv2 < 0) & (mask > 0)
    r2 = xp.where(neg2, -v2 / xp.where(neg2, dv2, -1.0), inf)
    return xp.minimum(1.0, xp.minimum(xp.min(r1), xp.min(r2)))


def _centrality_backoff(xp, state, hub, dirs, ap_max, ad_max, ncomp, gamma):
    """N₋∞(γ) neighborhood guard: damp the steps until no complementarity
    product falls below γ·μ(α).

    Iterates that stray orders of magnitude *below* the average
    complementarity create the extreme scaling spreads (d_max/d_min ≳ 1e18)
    that make the f64 normal equations unable to repair primal
    infeasibility — once injured, pinf freezes around 1e-6 (observed).
    Keeping products within γ of μ bounds the spread and prevents the
    injury. Implemented jit-style: evaluate a geometric grid of 24 damped
    (α_p, α_d) candidates at once and pick the least-damped admissible one
    — no data-dependent control flow (SURVEY.md §7).
    """
    if gamma <= 0:
        return ap_max, ad_max
    x, y, s, w, z = state
    dx, ds, dw, dz = dirs
    # If the CURRENT iterate already sits outside N₋∞(γ), demanding γ from
    # every candidate rejects them all (α→0 approaches the current point,
    # which violates γ) and the fallback pins every step at the most-damped
    # candidate — the solve crawls at α≈0.8²³ forever (observed). Relax the
    # demand to 0.9× the current centrality ratio in that case: the guard
    # then only blocks steps that make centrality *worse*, while iterates
    # inside the neighborhood still get the full γ.
    xs0 = x * s
    wz0 = w * z
    mu0 = (xs0.sum() + (wz0 * hub).sum()) / ncomp
    inf0 = xp.asarray(xp.inf, dtype=x.dtype)
    minprod0 = xp.minimum(xs0.min(), xp.where(hub > 0, wz0, inf0).min())
    ratio0 = minprod0 / xp.maximum(mu0, xp.finfo(x.dtype).tiny)
    # Only relax when actually outside — an unconditional min() would let
    # the floor erode geometrically (each accepted step lands near the
    # floor, then 0.9× it again next iteration).
    gamma = xp.where(ratio0 < gamma, 0.9 * ratio0, gamma)
    fac = 0.8 ** xp.arange(24, dtype=x.dtype)
    aps = ap_max * fac
    ads = ad_max * fac
    xs = (x[None, :] + aps[:, None] * dx[None, :]) * (
        s[None, :] + ads[:, None] * ds[None, :]
    )
    wz = (w[None, :] + aps[:, None] * dw[None, :]) * (
        z[None, :] + ads[:, None] * dz[None, :]
    )
    comp = xs.sum(axis=1) + (wz * hub[None, :]).sum(axis=1)
    mu_a = comp / ncomp
    inf_ = xp.asarray(xp.inf, dtype=x.dtype)
    minprod = xp.minimum(
        xs.min(axis=1), xp.where(hub[None, :] > 0, wz, inf_).min(axis=1)
    )
    ok = minprod >= gamma * mu_a
    # Least-damped admissible candidate; fall back to the most damped one.
    idx = xp.argmax(ok)
    idx = xp.where(xp.any(ok), idx, len(fac) - 1)
    return aps[idx], ads[idx]


def pcg_solve(op, prec, rhs, tol, max_iter):
    """Preconditioned conjugate gradient, fully on-device (jax only).

    Shared by the PCG solve modes of the dense and block backends: ``op``
    is the full-precision matrix-free normal-equations operator, ``prec``
    the (typically f32-factorization-based) preconditioner. Terminates at
    relative residual ``tol`` or ``max_iter`` iterations.

    A broken preconditioner (f32 Cholesky breakdown → NaN factor) makes
    the loop exit on its non-finite guard with x still at the FINITE zero
    initial guess; returning that silently would feed a zero direction to
    the step and bypass the driver's bad-step → regularization-escalation
    recovery (observed at 2048×10240). The failure is propagated as NaN
    exactly like a direct Cholesky solve would.
    """
    import jax
    import jax.numpy as jnp

    norm0 = jnp.linalg.norm(rhs)
    thresh = tol * norm0

    x0 = jnp.zeros_like(rhs)
    z0 = prec(rhs)
    carry0 = (x0, rhs, z0, rhs @ z0, jnp.asarray(0, jnp.int32))

    def cond(carry):
        x, r, p, rz, it = carry
        return (it < max_iter) & (jnp.linalg.norm(r) > thresh) & jnp.isfinite(rz)

    def body(carry):
        x, r, p, rz, it = carry
        Ap = op(p)
        denom = p @ Ap
        alpha = rz / jnp.where(denom != 0, denom, 1.0)
        x = x + alpha * p
        r = r - alpha * Ap
        z = prec(r)
        rz_new = r @ z
        beta = rz_new / jnp.where(rz != 0, rz, 1.0)
        p = z + beta * p
        return (x, r, p, rz_new, it + 1)

    x, r, p, rz, it = jax.lax.while_loop(cond, body, carry0)
    bad = ~(jnp.isfinite(rz) & jnp.all(jnp.isfinite(x)))
    # A cap-limited CG that did NOT meaningfully reduce the residual is a
    # failed solve, not an approximate one: the resulting direction is
    # noise with finite entries, and silently returning it poisons the
    # iterate while μ keeps shrinking (observed: pinf freezes at 1e-2 and
    # the divergence heuristic misfires). The failure line is 1e-3
    # relative OR 10× the requested tol, whichever is looser — so a
    # caller running with a deliberately loose cg_tol still gets its
    # approximate directions, and only order-of-magnitude misses NaN.
    bad = bad | (jnp.linalg.norm(r) > jnp.maximum(1e-3 * norm0, 10.0 * thresh))
    return jnp.where(bad, jnp.asarray(jnp.nan, x.dtype), x)


def residual_norms(ops: LinOps, data: ProblemData, state: IPMState):
    """Relative primal/dual infeasibility, gap, and objectives of a state."""
    xp = ops.xp
    x, y, s, w, z = state
    r_p = data.b - ops.matvec(x)
    r_u = data.hub * (data.u_f - x - w)
    r_d = data.c - ops.rmatvec(y) - s + z
    pinf = xp.sqrt(xp.sum(r_p * r_p) + xp.sum(r_u * r_u)) / data.norm_b
    dinf = xp.linalg.norm(r_d) / data.norm_c
    pobj = data.c @ x
    dobj = data.b @ y - (data.hub * data.u_f) @ z
    gap = xp.abs(pobj - dobj)
    rel_gap = gap / (1.0 + xp.abs(pobj))
    mu = (x @ s + (data.hub * w) @ z) / data.ncomp
    return pinf, dinf, gap, rel_gap, pobj, dobj, mu


def scaling_d(state: IPMState, data: ProblemData, cfg: StepParams):
    """The normal-equations diagonal ``d = 1/(s/x + z/w + reg_primal)``.

    One definition shared by :func:`mehrotra_step` and backends that
    precompute factorizations outside the step program (the dense
    endgame phase splits one iteration across dispatches and must form
    the SAME d the step will use). With ``cfg.elementwise == "df32"``
    the division chain runs through the two-float layer (jax paths
    only; see :func:`_solve_kkt_once`)."""
    x, y, s, w, z = state
    if cfg.elementwise == "df32":
        from distributedlpsolver_tpu.ops import df32 as _df32

        return _df32.scaling_d(x, s, w, z, data.hub, cfg.reg_primal)
    dinv = s / x + data.hub * z / w + cfg.reg_primal
    return 1.0 / dinv


def mehrotra_step(
    ops: LinOps, data: ProblemData, cfg: StepParams, state: IPMState
):
    """One full predictor-corrector iteration: state ↦ (state', stats).

    Everything here runs on the backend's device(s) in one traced call; only
    the :class:`StepStats` scalars cross back to the host loop
    (BASELINE.json:5: driver on host, linear algebra on device).
    """
    xp = ops.xp
    x, y, s, w, z = state
    hub, u_f, c, b = data.hub, data.u_f, data.c, data.b

    # Residuals of the current iterate.
    r_p = b - ops.matvec(x)
    r_u = hub * (u_f - x - w)
    r_d = c - ops.rmatvec(y) - s + z
    mu = (x @ s + (hub * w) @ z) / data.ncomp

    # Diagonal scaling and one factorization, shared by both solves.
    d = scaling_d(state, data, cfg)
    factors = ops.factorize(d)

    # Aim the centering target at the convergence tolerance, not at zero:
    # letting μ overshoot orders of magnitude below what a 1e-8 relative
    # gap needs pushes the scaling spread d_max/d_min past what f64 can
    # factor, and the *feasibility* components of subsequent directions
    # collapse (observed: pinf jumps 1e-9 → 5e-6 and freezes). 0.03·tol
    # keeps a safe 30× margin below the gap test.
    pobj_now = c @ x
    mu_floor = 0.03 * cfg.tol * (1.0 + xp.abs(pobj_now)) / data.ncomp
    if cfg.mu_pinf_floor:
        # Balance floor for limited-precision phases (StepParams
        # docstring): μ may trail the remaining primal infeasibility by
        # at most 1/mu_pinf_floor — same unit construction as the tol
        # floor, with pinf_rel in tol's place.
        pinf_now = xp.sqrt(xp.sum(r_p * r_p) + xp.sum(r_u * r_u)) / data.norm_b
        mu_floor = xp.maximum(
            mu_floor,
            cfg.mu_pinf_floor * pinf_now * (1.0 + xp.abs(pobj_now)) / data.ncomp,
        )

    if cfg.center:
        # Pure centering step (StepParams.center): one KKT solve aiming
        # every product at the current μ — no predictor, no cross term.
        sigma = xp.asarray(1.0, dtype=x.dtype)
        target = xp.maximum(mu, mu_floor)
        rxs = target - x * s
        rwz = hub * (target - w * z)
    else:
        # Predictor (affine-scaling) direction.
        rxs_aff = -x * s
        rwz_aff = -(w * z) * hub
        dxa, dya, dsa, dwa, dza = _solve_kkt(
            ops, state, hub, d, factors, r_p, r_u, r_d, rxs_aff, rwz_aff,
            cfg.kkt_refine, cfg.elementwise
        )
        ap_aff = _max_step(xp, x, dxa, w, dwa, hub)
        ad_aff = _max_step(xp, s, dsa, z, dza, hub)
        mu_aff = (
            (x + ap_aff * dxa) @ (s + ad_aff * dsa)
            + ((w + ap_aff * dwa) * (z + ad_aff * dza)) @ hub
        ) / data.ncomp
        sigma = xp.clip(
            (xp.maximum(mu_aff, 0.0) / mu) ** cfg.sigma_power,
            cfg.sigma_min, cfg.sigma_max,
        )
        target = xp.maximum(sigma * mu, mu_floor)

        # Corrector: recenter to the target and cancel the second-order
        # term, reusing the factorization (the defining Mehrotra move,
        # BASELINE.json:5).
        rxs = target - x * s - dxa * dsa
        rwz = hub * (target - w * z - dwa * dza)
    dx, dy, ds, dw, dz = _solve_kkt(
        ops, state, hub, d, factors, r_p, r_u, r_d, rxs, rwz, cfg.kkt_refine,
        cfg.elementwise
    )

    ap_raw = _max_step(xp, x, dx, w, dw, hub)
    ad_raw = _max_step(xp, s, ds, z, dz, hub)
    if cfg.mcc and not cfg.center:
        # Gondzio multiple centrality correctors (StepParams.mcc): each
        # round solves ONCE more on the held factorization with a
        # complementarity-only RHS that pulls the TRIAL point's outlier
        # products back into a [0.1, 10]·target band, and keeps the
        # corrected direction only if it lengthens the combined step.
        # Feasibility RHS is zero, so an accepted correction never
        # perturbs r_p/r_u/r_d reduction — pure recentering.
        zm = xp.zeros_like(b)
        zn = xp.zeros_like(x)
        for mc in range(cfg.mcc):
            # Progressively enlarged trial step per round (Gondzio's own
            # escalation): without it a REJECTED round makes every later
            # round bit-identical — the same trial point, band, RHS, and
            # solve, deterministically rejected again (round-5 review
            # finding: a guaranteed-useless KKT solve per extra round).
            grow = 1.3 + 0.25 * mc
            ap_t = xp.minimum(1.0, grow * ap_raw + 0.1 * (mc + 1))
            ad_t = xp.minimum(1.0, grow * ad_raw + 0.1 * (mc + 1))
            v_xs = (x + ap_t * dx) * (s + ad_t * ds)
            v_wz = hub * ((w + ap_t * dw) * (z + ad_t * dz))
            cxs = xp.clip(v_xs, 0.1 * target, 10.0 * target) - v_xs
            cwz = hub * (xp.clip(v_wz, 0.1 * target, 10.0 * target) - v_wz)
            gx, gy, gs, gw, gz = _solve_kkt_once(
                ops, state, hub, d, factors, zm, zn, zn, cxs, cwz,
                cfg.elementwise
            )
            dx2, dy2, ds2, dw2, dz2 = dx + gx, dy + gy, ds + gs, dw + gw, dz + gz
            ap2 = _max_step(xp, x, dx2, w, dw2, hub)
            ad2 = _max_step(xp, s, ds2, z, dz2, hub)
            better = (ap2 + ad2) > (ap_raw + ad_raw) + 0.01
            keep = lambda new, old: xp.where(better, new, old)
            dx, dy, ds = keep(dx2, dx), keep(dy2, dy), keep(ds2, ds)
            dw, dz = keep(dw2, dw), keep(dz2, dz)
            ap_raw = keep(ap2, ap_raw)
            ad_raw = keep(ad2, ad_raw)

    alpha_p = xp.minimum(1.0, cfg.eta * ap_raw)
    alpha_d = xp.minimum(1.0, cfg.eta * ad_raw)
    alpha_p, alpha_d = _centrality_backoff(
        xp, state, hub, (dx, ds, dw, dz), alpha_p, alpha_d, data.ncomp, cfg.gamma_cent
    )

    finite = (
        xp.all(xp.isfinite(dx))
        & xp.all(xp.isfinite(dy))
        & xp.all(xp.isfinite(ds))
        & xp.all(xp.isfinite(dw))
        & xp.all(xp.isfinite(dz))
    )
    ok = finite & (alpha_p > 0) & (alpha_d > 0)

    def upd(v, dv, a):
        return xp.where(ok, v + a * dv, v)

    x1 = upd(x, dx, alpha_p)
    w1 = xp.where(hub > 0, upd(w, dw, alpha_p), 1.0)
    y1 = upd(y, dy, alpha_d)
    s1 = upd(s, ds, alpha_d)
    z1 = xp.where(hub > 0, upd(z, dz, alpha_d), 0.0)
    new_state = IPMState(x=x1, y=y1, s=s1, w=w1, z=z1)

    pinf, dinf, gap, rel_gap, pobj, dobj, mu1 = residual_norms(ops, data, new_state)
    stats = StepStats(
        mu=mu1,
        gap=gap,
        rel_gap=rel_gap,
        pinf=pinf,
        dinf=dinf,
        pobj=pobj,
        dobj=dobj,
        alpha_p=xp.where(ok, alpha_p, 0.0),
        alpha_d=xp.where(ok, alpha_d, 0.0),
        sigma=sigma,
        bad=~ok,
    )
    return new_state, stats


STATUS_RUNNING, STATUS_OPTIMAL, STATUS_MAXITER, STATUS_NUMERR = 0, 1, 2, 3
STATUS_PINFEAS, STATUS_DINFEAS = 4, 5
STATUS_STALL = 6  # no max(gap,pinf,dinf) improvement over the stall window
N_STAT = 10  # mu, gap, rel_gap, pinf, dinf, pobj, dobj, alpha_p, alpha_d, sigma

DIVERGE_MU = 1e30


def classify_divergence(mu, pinf, dinf, rel_gap, pobj, dobj):
    """Heuristic infeasibility/unboundedness signals (works on host floats
    and on traced scalars).

    * Primal infeasible: complementarity has converged (μ ≈ 0) while primal
      infeasibility is stuck far above tolerance — the iteration found a
      Farkas-like stationary point (observed signature: μ→1e-10, pinf
      frozen ~1e-1) — or the dual objective runs away upward.
    * Primal unbounded (dual infeasible): dual infeasibility is stuck while
      the primal objective dives along a recession ray; rel_gap→1 is the
      scale-free confirmation (gap ≈ |pobj|).
    These are heuristics, not certificates — a homogeneous self-dual
    embedding would give certified rays (future work, SURVEY.md §5.3 notes
    the reference has no such machinery either).

    Every test is scale-relative (dimensionless): μ and the objectives
    carry the problem's c·x units, so absolute cutoffs misfire under bad
    data scaling — scaling c by 1e6 would leave an absolute μ test
    unreachable (muting detection) or let a legitimately large objective
    trip an absolute divergence cutoff on a feasible problem. pinf /
    dinf / rel_gap arrive already normalized (residual_norms divides by
    ‖b‖ / ‖c‖ / 1+|pobj|), and the objective comparisons below normalize
    each objective by the OTHER side's magnitude — at a divergence point
    the runaway side explodes while the other stays finite, so the ratio
    is scale-free.
    """
    # Constants preserve the old absolute behavior at unit scale (1e12
    # for the unguarded runaway legs) — the rewrite makes them relative,
    # it must not also make them 2-4 orders looser: a feasible problem
    # whose legitimate optimum is ~ -1e10 would otherwise trip the
    # primal-dive leg mid-solve while the dual still lags near zero.
    scale_p = 1.0 + abs(pobj)
    scale_d = 1.0 + abs(dobj)
    # μ-converged threshold 1e-11·scale, NOT 1e-8: μ is per-pair, so on
    # a large problem 1e-8·scale still describes a mid-solve iterate —
    # observed at the pds-20 class (ncomp≈1.2e5): μ=2.4e-4 < 1e-8·scale
    # with rel_gap still 6e-4 fired a false PRIMAL_INFEASIBLE one
    # iteration into the f64 finisher. A rel_gap conjunct cannot fix it
    # (a genuine Farkas point has HUGE rel_gap — the dual runs away);
    # the real Farkas signature sits orders lower (μ/scale ~1e-13).
    pinfeas = ((mu < 1e-11 * scale_p) & (pinf > 1e-3)) | (
        dobj > 1e12 * scale_p
    )
    dinfeas = ((dinf > 1e-3) & (pobj < -1e8 * scale_d) & (rel_gap > 0.99)) | (
        pobj < -1e12 * scale_d
    )
    return pinfeas, dinfeas


def buffer_cap(max_iter: int, quantum: int = 512) -> int:
    """Static stats-buffer size for :func:`fused_solve`, bucketed so that
    different ``max_iter`` values share one compiled executable (max_iter
    itself is a *traced* loop bound; only this cap is a jit key). The
    quantum covers two phase budgets of the default max_iter (2×200), so a
    tiny-max_iter warm-up lands in the same bucket as production runs —
    the buffer is (cap, N_STAT) scalars, so a generous cap costs ~40 KB."""
    return ((max(int(max_iter), 1) + quantum - 1) // quantum) * quantum


def fused_solve(
    step_fn,
    state0,
    reg0,
    params,
    max_iter,
    max_refactor,
    reg_grow,
    buf_cap=None,
    *,
    stall_window=0,
    stall_patience_floor=0.0,
    carry_in=None,
    finalize=True,
    it_stop=None,
    resume=None,
    return_carry=False,
):
    """Entire IPM solve as one traced program (``lax.while_loop`` over
    iterations) — jax-only, called from inside a backend's jit.

    Removes the per-iteration host↔device round trip, which dominates
    wall-clock on a tunneled/remote accelerator. Mirrors the host driver's
    loop semantics: deterministic regularization escalation on bad steps
    (state frozen, reg ×= grow, give up after max_refactor), convergence
    at params.tol on rel_gap/pinf/dinf. Per-iteration stats stream into a
    fixed (buf_cap, N_STAT) buffer so the host can reconstruct the full
    iteration log afterwards. Returns (state, iterations, status, buffer).

    ``max_iter``, ``max_refactor``, and ``reg_grow`` may be traced scalars —
    changing them never recompiles; only ``buf_cap`` (static, bucketed via
    :func:`buffer_cap`) is part of the compile key. ``buf_cap`` is REQUIRED
    whenever ``max_iter`` is traced (the default derives it via
    ``int(max_iter)``, which only works on concrete values).

    ``stall_window`` (static) > 0 adds a stall exit: if the error measure
    ``max(rel_gap, pinf, dinf)`` fails to improve by ≥10% over that many
    accepted steps, the loop stops (status ``STATUS_STALL`` if this is the
    ``finalize`` phase, else left ``STATUS_RUNNING`` for a continuation).
    ``stall_patience_floor`` suppresses the stall exit while the best error
    is at or below it — IPM tails can plateau for dozens of iterations
    within ~100× of tolerance and still converge (observed), so final
    phases pass ~1e3·tol here; 0 means stall always exits.

    Phase composition (mixed-precision two-phase solves): pass
    ``finalize=False`` to leave a non-terminal exit as ``STATUS_RUNNING``
    and feed ``(it, status, buf)`` of one call as ``carry_in`` of the next —
    the continuation resumes the global iteration count and appends to the
    same stats buffer.

    Segmentation (bounding single device-program runtime, e.g. for
    execution watchdogs on tunneled accelerators): pass ``it_stop`` (a
    traced iteration bound for THIS call) and ``return_carry=True`` to get
    the raw loop carry back; feed it to the next call via ``resume`` to
    continue exactly where the segment stopped (regularization, stall
    counters and stats buffer included). ``return_carry`` skips the
    ``finalize`` status mapping — the segment driver owns it.
    """
    import jax
    import jax.numpy as jnp

    if buf_cap is None:
        buf_cap = buffer_cap(int(max_iter))

    def cond(carry):
        _, it, _, _, status, _, best_err, since = carry
        go = (status == STATUS_RUNNING) & (it < max_iter) & (it < buf_cap)
        if it_stop is not None:
            go = go & (it < it_stop)
        if stall_window:
            stall = since > stall_window
            if stall_patience_floor:
                stall = stall & (best_err > stall_patience_floor)
            go = go & ~stall
        return go

    def body(carry):
        state, it, reg, badcount, status, buf, best_err, since = carry
        new_state, stats = step_fn(state, reg)
        bad = stats.bad
        conv = (
            (stats.rel_gap <= params.tol)
            & (stats.pinf <= params.tol)
            & (stats.dinf <= params.tol)
        )
        state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(bad, o, n), new_state, state
        )
        row = jnp.stack(
            [stats.mu, stats.gap, stats.rel_gap, stats.pinf, stats.dinf,
             stats.pobj, stats.dobj, stats.alpha_p, stats.alpha_d, stats.sigma]
        )
        buf = jnp.where(bad, buf, buf.at[it].set(row))
        it = jnp.where(bad, it, it + 1)
        badcount = jnp.where(bad, badcount + 1, badcount)
        status = jnp.where(
            bad & ((badcount > max_refactor) | (reg * reg_grow > 1e-2)),
            STATUS_NUMERR,
            jnp.where(conv & ~bad, STATUS_OPTIMAL, status),
        )
        ok = ~bad & (status == STATUS_RUNNING)
        pinfeas, dinfeas = classify_divergence(
            stats.mu, stats.pinf, stats.dinf, stats.rel_gap, stats.pobj, stats.dobj
        )
        status = jnp.where(ok & pinfeas, STATUS_PINFEAS, status)
        status = jnp.where(ok & dinfeas, STATUS_DINFEAS, status)
        status = jnp.where(
            ok & (~jnp.isfinite(stats.mu) | (stats.mu > DIVERGE_MU)),
            STATUS_NUMERR,
            status,
        )
        err = jnp.maximum(stats.rel_gap, jnp.maximum(stats.pinf, stats.dinf))
        improved = ~bad & (err < 0.9 * best_err)
        best_err = jnp.where(improved, err, best_err)
        since = jnp.where(bad, since, jnp.where(improved, 0, since + 1))
        reg = jnp.where(bad, jnp.maximum(reg, 1e-12) * reg_grow, reg)
        return state, it, reg, badcount, status, buf, best_err, since

    if resume is not None:
        carry0 = resume
    else:
        if carry_in is not None:
            it0, status0, buf0 = carry_in
            it0 = jnp.asarray(it0, jnp.int32)
            status0 = jnp.asarray(status0, jnp.int32)
        else:
            it0 = jnp.asarray(0, jnp.int32)
            status0 = jnp.asarray(STATUS_RUNNING, jnp.int32)
            buf0 = jnp.zeros((buf_cap, N_STAT), dtype=state0.x.dtype)
        carry0 = (
            state0,
            it0,
            reg0,
            jnp.asarray(0, jnp.int32),
            status0,
            buf0,
            jnp.asarray(jnp.inf, state0.x.dtype),
            jnp.asarray(0, jnp.int32),
        )
    carry = jax.lax.while_loop(cond, body, carry0)
    if return_carry:
        return carry
    state, it, reg, _, status, buf, _, since = carry
    if finalize:
        stalled = (
            (since > stall_window) if stall_window else jnp.asarray(False, bool)
        )
        status = jnp.where(
            status == STATUS_RUNNING,
            jnp.where(stalled & (it < max_iter), STATUS_STALL, STATUS_MAXITER),
            status,
        )
    return state, it, status, buf


def seg_trace_enabled() -> bool:
    """Whether TPULP_SEG_VERBOSE asks for live progress lines
    (conventional 0/1 contract: "", "0", "false", "no" disable)."""
    import os

    return os.environ.get("TPULP_SEG_VERBOSE", "").lower() not in (
        "", "0", "false", "no",
    )


def drive_segments(
    run_seg, carry0, max_iter, stall_window, seg_init=16, target_s=15.0,
    stall_patience_floor=0.0, it0_status0=(0, STATUS_RUNNING),
    early_stop=None, seg_cap=256,
):
    """Host loop over bounded fused-solve segments.

    ``run_seg(carry, it_stop) -> (carry, meta)`` executes one device
    program continuing from ``carry`` until the iteration count reaches
    ``it_stop`` or the loop exits on its own; ``meta`` is the packed
    ``[it, status, best_err, since]`` scalar array (ONE device→host
    transfer per segment — individually fetching loop scalars costs a
    tunnel round trip each). Repeats — adapting the segment length toward
    ``target_s`` seconds of device time, the guard against single-program
    execution watchdogs on tunneled accelerators — until the status
    leaves RUNNING, the stall window fires, or ``max_iter`` is reached.
    Returns ``(carry, (it, status, best_err, since))`` — the final carry
    plus host copies of the loop scalars, so callers never re-fetch them.

    ``early_stop(it, status, best_err, since) -> bool`` (optional) is
    consulted after each segment; True ends the drive. Callers with extra
    loop state pack it into the meta slots (e.g. the batched driver packs
    its active-problem count into the ``best_err`` slot and stops when the
    tail is small enough to hand to per-problem cleanup).
    """
    import os as _os
    import time as _time

    import numpy as _np

    trace = seg_trace_enabled()
    carry = carry0
    seg = max(int(seg_init), 1)
    # Entry it/status are read from the packed meta the CALLER already has
    # (or known statically at a fresh start) — fetching them from carry
    # here would cost two extra tunnel round trips per phase.
    it, status = it0_status0
    best_err, since = float("inf"), 0
    first = True
    while status == STATUS_RUNNING and it < max_iter:
        prev_it = it
        stop = min(it + seg, max_iter)
        t0 = _time.perf_counter()
        carry, meta = run_seg(carry, stop)
        meta = _np.asarray(meta)  # blocks; the segment's one host read
        dt = _time.perf_counter() - t0
        it, status = int(meta[0]), int(meta[1])
        best_err, since = float(meta[2]), int(meta[3])
        if trace:
            import sys as _sys

            print(
                f"[seg] it={it} status={status} best_err={best_err:.3e} "
                f"since={since} dt={dt:.1f}s seg={seg}",
                file=_sys.stderr, flush=True,
            )
        if (
            stall_window
            and since > stall_window
            and (not stall_patience_floor or best_err > stall_patience_floor)
        ):
            break
        if early_stop is not None and early_stop(it, status, best_err, since):
            break
        if it == prev_it:  # no progress possible (defensive: avoid spinning)
            break
        if not first:  # first call's wall time includes compile — don't adapt
            # Jump straight to the measured rate (dt is clean post-compile);
            # the cap keeps one segment well under the watchdog either way.
            # ``seg_cap`` lets callers that act at segment boundaries
            # (the batched compaction drive) keep boundaries frequent.
            seg = max(1, min(seg_cap, int(seg * target_s / max(dt, 1e-3))))
        first = False
    return carry, (it, status, best_err, since)


def pack_segment_meta(carry):
    """[it, status, best_err, since] as one array — see drive_segments."""
    import jax.numpy as jnp

    _, it, _, _, status, _, best_err, since = carry
    f = best_err.dtype
    return jnp.stack(
        [it.astype(f), status.astype(f), best_err, since.astype(f)]
    )


def fresh_segment_carry(state, reg0, buf_cap, dtype):
    """Initial drive_segments carry for a fused solve starting at ``state``
    (mirrors fused_solve's internal carry layout)."""
    import jax.numpy as jnp

    return (
        state,
        jnp.asarray(0, jnp.int32),
        reg0,
        jnp.asarray(0, jnp.int32),
        jnp.asarray(STATUS_RUNNING, jnp.int32),
        jnp.zeros((buf_cap, N_STAT), dtype),
        jnp.asarray(jnp.inf, dtype),
        jnp.asarray(0, jnp.int32),
    )


_PHASE_RESET_JIT = None


def segment_phase_reset(carry, reg0):
    """Device-side phase-boundary reset (one dispatch): keep state,
    iteration count, and stats buffer; reset everything provisional
    (regularization, bad-count, status, stall tracking) — every phase-1
    verdict is provisional and phase 2 re-derives it at full precision."""
    global _PHASE_RESET_JIT
    if _PHASE_RESET_JIT is None:
        import jax
        import jax.numpy as jnp

        # Cached in the module-level _PHASE_RESET_JIT slot: the wrapper
        # is built ONCE (core.py keeps jax out of its import path), so
        # this is a hoist in disguise, not a per-call jit.
        @jax.jit  # graftcheck: disable=jit-nonhoisted (cached lazy init)
        def _reset(carry, reg0):
            st, it, _, _, _, buf, _, _ = carry
            z = jnp.asarray(0, jnp.int32)
            return (
                st, it, reg0, z,
                jnp.asarray(STATUS_RUNNING, jnp.int32), buf,
                jnp.asarray(jnp.inf, buf.dtype), z,
            )

        _PHASE_RESET_JIT = _reset
    return _PHASE_RESET_JIT(carry, reg0)


def drive_phase_plan(phases, state, reg0, max_iter, buf_cap, dtype,
                     report=None):
    """Host driver for a multi-phase segmented fused solve.

    ``phases`` is a list of ``(make_run_seg, stall_window,
    stall_patience_floor, seg_init)`` where ``make_run_seg(bound) ->
    run_seg(carry, it_stop)`` builds the phase's device program around its
    global iteration bound. Each phase gets its own ``max_iter`` budget;
    between phases the carry is reset via :func:`segment_phase_reset`.
    Returns ``(state, iterations, status, stats_buffer, reg)`` — ``reg``
    is the final phase's escalated regularization (still on device), so
    a follow-on finisher (the dense endgame) can seed from it instead of
    replaying known-bad factorizations — with the final RUNNING status
    mapped to STALL/MAXITER exactly as the fused loop would. ONE
    implementation shared by the dense and block backends so their
    termination semantics can never diverge.

    ``report`` (optional mutable list) receives one ``{"phase", "iters",
    "wall_s"}`` row per phase — the per-phase split the utilization
    artifacts record (VERDICT round 3 item 4), measured here because only
    the driver knows the phase boundaries.
    """
    import time as _time

    import jax.numpy as jnp

    carry = fresh_segment_carry(state, reg0, buf_cap, dtype)
    it, status = 0, STATUS_RUNNING
    window, patience, bound = 0, 0.0, max_iter
    best, since = float("inf"), 0
    for pi, (make_run_seg, window, patience, seg_init) in enumerate(phases):
        bound = it + max_iter
        it_before, t_ph = it, _time.perf_counter()
        carry, (it, status, best, since) = drive_segments(
            make_run_seg(bound), carry, bound, window, seg_init,
            stall_patience_floor=patience, it0_status0=(it, status),
        )
        if report is not None:
            report.append({
                "phase": pi, "iters": int(it - it_before),
                "wall_s": round(_time.perf_counter() - t_ph, 3),
            })
        if pi < len(phases) - 1:
            carry = segment_phase_reset(carry, reg0)
            status = STATUS_RUNNING
    st, buf = carry[0], carry[5]
    if status == STATUS_RUNNING:
        stalled = (
            window
            and since > window
            and it < bound
            and (not patience or best > patience)
        )
        status = STATUS_STALL if stalled else STATUS_MAXITER
    return st, it, jnp.asarray(status, jnp.int32), buf, carry[2]


# Conservative opening-segment cap in auto mode: big enough that a small
# fast solve finishes in one or two segments, small enough that a ~4x
# error in the FLOP-rate model cannot push the (unmeasured) first device
# program past the execution watchdog before adaptation gets a data point.
SEG_OPEN_CAP = 32

# Conservative effective rates for watchdog seeding (ONE definition for
# every backend): f32 paths ride the MXU; f64 is software-emulated on TPU.
SEG_RATE_F32 = 2e12
SEG_RATE_F64 = 2.5e11


def use_segments(seg_cfg, platform: str) -> bool:
    """Whether a backend should host-segment its fused loop: explicit
    ``segment_iters=0`` disables, any positive value enables, and auto
    (None) enables exactly on TPU — where tunneled execution watchdogs
    make unbounded device programs unsafe."""
    if seg_cfg is None:
        return platform == "tpu"
    return seg_cfg > 0


def seg_open(seg_cfg, est_iter_seconds, target_s: float = 15.0) -> int:
    """Opening segment length: the FLOP-estimated iteration count toward
    ``target_s``, capped by SEG_OPEN_CAP in auto mode or by the user's
    explicit ``segment_iters``."""
    cap = seg_cfg if seg_cfg is not None else SEG_OPEN_CAP
    return max(1, min(cap, int(target_s / max(est_iter_seconds, 1e-3))))


def starting_point(ops: LinOps, data: ProblemData, cfg: StepParams) -> IPMState:
    """Mehrotra's least-squares starting point, extended to upper bounds.

    ``x̂ = Aᵀ(AAᵀ)⁻¹b`` (min-norm primal), ``ŷ = (AAᵀ)⁻¹Ac``, ``ŝ = c-Aᵀŷ``,
    then positive shifts sized so initial complementarity is balanced
    (Mehrotra 1992 §7 heuristic — standard, SURVEY.md §2 [INFERRED]).
    Bounded columns are clamped into (5%, 95%) of [0, u] and their dual is
    split ``s-z = ŝ`` with both parts positive, so r_d starts at 0 there.
    """
    xp = ops.xp
    c, b, u_f, hub = data.c, data.b, data.u_f, data.hub
    ones = xp.ones_like(c)
    factors = ops.factorize(ones)
    x_hat = ops.rmatvec(ops.solve(factors, b))
    y_hat = ops.solve(factors, ops.matvec(c))
    s_hat = c - ops.rmatvec(y_hat)

    dx = xp.maximum(-1.5 * xp.min(x_hat), 0.0)
    ds = xp.maximum(-1.5 * xp.min(s_hat), 0.0)
    x1 = x_hat + dx
    s1 = s_hat + ds
    xs = x1 @ s1
    dx_hat = dx + 0.5 * xs / xp.maximum(xp.sum(s1), 1e-30)
    ds_hat = ds + 0.5 * xs / xp.maximum(xp.sum(x1), 1e-30)
    floor = xp.asarray(1.0, dtype=c.dtype)
    x0 = xp.maximum(x_hat + dx_hat, floor * 1e-2)
    s0_free = xp.maximum(s_hat + ds_hat, floor * 1e-2)

    # Bounded columns: interior of [0, u] and positive dual split.
    x0 = xp.where(hub > 0, xp.clip(x0, 0.05 * u_f, 0.95 * u_f), x0)
    w0 = xp.where(hub > 0, u_f - x0, 1.0)
    pad = 1.0 + xp.abs(s_hat)
    s0 = xp.where(hub > 0, xp.maximum(s_hat, 0.0) + 0.1 * pad, s0_free)
    z0 = xp.where(hub > 0, s0 - s_hat, 0.0)
    return IPMState(x=x0, y=y_hat, s=s0, w=w0, z=z0)
