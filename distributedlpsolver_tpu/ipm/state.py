"""IPM iterate state, per-iteration stats, and solve results.

SURVEY.md §1 notes every IPM solver has a "solution/status" layer shared
between the algorithm driver and the CLI; this is ours. The fields mirror
the reference's published metric surface — iteration count, duality-gap
trajectory, primal/dual infeasibility, wall-clock (BASELINE.json:2).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, NamedTuple, Optional

import numpy as np


class IPMState(NamedTuple):
    """Primal-dual iterate for ``min cᵀx s.t. Ax=b, 0≤x, x+w=u (bounded set)``.

    ``w``/``z`` are the upper-bound slack and its dual; on columns without a
    finite upper bound they are pinned to (1, 0) so masked arithmetic stays
    finite (see ipm/core.py).
    """

    x: Any  # (n,) primal
    y: Any  # (m,) equality duals
    s: Any  # (n,) reduced costs (duals of x ≥ 0)
    w: Any  # (n,) upper-bound slack u - x (1 where no ub)
    z: Any  # (n,) duals of x ≤ u (0 where no ub)


class StepStats(NamedTuple):
    """Scalars returned to the host after each device step."""

    mu: Any  # complementarity measure
    gap: Any  # absolute duality gap |pobj - dobj|
    rel_gap: Any
    pinf: Any  # relative primal infeasibility
    dinf: Any  # relative dual infeasibility
    pobj: Any
    dobj: Any
    alpha_p: Any
    alpha_d: Any
    sigma: Any
    bad: Any  # bool: factorization/solve produced non-finite direction


class Status(enum.Enum):
    OPTIMAL = "optimal"
    ITERATION_LIMIT = "iteration_limit"
    NUMERICAL_ERROR = "numerical_error"
    PRIMAL_INFEASIBLE = "primal_infeasible"
    DUAL_INFEASIBLE = "dual_infeasible"  # == primal unbounded
    STALLED = "stalled"  # no progress over the stall window (fused loop)
    FAILED = "failed"  # supervisor exhausted its recovery ladder (supervisor/)
    TIMEOUT = "timeout"  # serve/: request deadline expired before a result
    CANCELLED = "cancelled"  # serve/: queued work cancelled before dispatch


class FaultKind(enum.Enum):
    """Classification of a solve fault observed by the supervisor.

    The taxonomy mirrors the production failure classes: a device dispatch
    that never returns (``HANG``, the watchdog's deadline fired), an
    iterate whose host-side convergence scalars went non-finite or μ
    exploded (``NUMERICAL``), a backend step that raised outright
    (``CRASH``), and a mesh participant dropping out of the runtime
    (``DEVICE_LOST`` — a raised device-loss error, or repeated hangs the
    health probe attributes to the same shard). ``DEVICE_LOST`` is the
    fault class the elastic mesh-shrink rung recovers from: the surviving
    devices re-form a smaller mesh instead of abandoning the pod.
    """

    HANG = "hang"
    NUMERICAL = "numerical"
    CRASH = "crash"
    DEVICE_LOST = "device_lost"


@dataclasses.dataclass
class FaultRecord:
    """One observed fault plus the recovery action the supervisor took."""

    kind: FaultKind
    iteration: int  # driver iteration at which the fault surfaced (-1 unknown)
    backend: str  # backend name active when the fault occurred
    detail: str  # human-readable cause (exception text / guard values)
    action: str = ""  # recovery applied: rollback / reg_bump / recenter / shrink:<K>-><K'> / degrade:<name> / give_up
    at_time: float = 0.0  # unix timestamp when classified
    # Device ids implicated in this fault (DEVICE_LOST, or hangs the
    # health probe attributed to specific shards); empty when unknown.
    devices: tuple = ()
    # Wall-clock seconds from fault classification to the completion of
    # the first post-resume iteration (0.0 until the resume lands) — the
    # recovery-path overhead a post-mortem attributes wall-clock loss to.
    recovery_overhead_s: float = 0.0

    def asdict(self):
        d = dataclasses.asdict(self)
        d["kind"] = self.kind.value
        d["devices"] = list(self.devices)
        return d


@dataclasses.dataclass
class IterRecord:
    """One row of the per-iteration log (SURVEY.md §5.5)."""

    iter: int
    mu: float
    gap: float
    rel_gap: float
    pinf: float
    dinf: float
    alpha_p: float
    alpha_d: float
    sigma: float
    pobj: float
    dobj: float
    t_iter: float  # seconds, device-synchronized

    def asdict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class IPMResult:
    """Solve outcome in the *original* problem space."""

    status: Status
    x: Optional[np.ndarray]  # original-variable primal solution
    objective: float  # original objective (sense-corrected)
    iterations: int
    rel_gap: float
    pinf: float
    dinf: float
    solve_time: float  # seconds, excludes setup/compile
    setup_time: float  # seconds (includes jit compile)
    history: List[IterRecord] = dataclasses.field(default_factory=list)
    backend: str = ""
    name: str = ""
    # Dual solution (minimized sense). For an LPProblem input these are in
    # the ORIGINAL problem space regardless of presolve: y has one entry
    # per original row (0 for presolve-removed rows except singleton rows,
    # which receive their absorbed bound multiplier) and s = c - Aᵀy.
    # For a raw InteriorForm input they are the interior-form duals.
    y: Optional[np.ndarray] = None
    s: Optional[np.ndarray] = None
    # Farkas certificate for non-optimal outcomes (ipm/certificates.py),
    # stated in the solved interior-form space; None when no candidate
    # ray was extractable. ``certificate.certified`` distinguishes a
    # checkable proof from the divergence heuristic alone.
    certificate: Optional[object] = None
    # Faults survived en route to this result (supervised solves only —
    # supervisor/supervisor.py appends one FaultRecord per recovery).
    faults: List["FaultRecord"] = dataclasses.field(default_factory=list)
    # How the solve started: "cold" (Mehrotra start / checkpoint resume),
    # "warm" (a safeguarded WarmStart was accepted), or "rejected" (a
    # WarmStart was offered but its initial residuals regressed past the
    # safeguard and the solve fell back to the cold start). See ipm/warm.
    warm: str = "cold"

    @property
    def iters_per_sec(self) -> float:
        return self.iterations / self.solve_time if self.solve_time > 0 else 0.0

    def summary(self) -> str:
        s = (
            f"{self.name or 'LP'}: {self.status.value} obj={self.objective:.10g} "
            f"iters={self.iterations} gap={self.rel_gap:.2e} pinf={self.pinf:.2e} "
            f"dinf={self.dinf:.2e} time={self.solve_time:.3f}s "
            f"({self.iters_per_sec:.1f} it/s) backend={self.backend}"
        )
        if self.faults:
            s += f" faults={len(self.faults)}"
        return s
