"""Host-side Mehrotra driver loop (SURVEY.md §1 L4, §3.1).

The outer predictor-corrector loop runs on the host (BASELINE.json:5: "the
Mehrotra predictor-corrector driver and step-length line search stay on
the host"); each ``backend.iterate`` call executes one full iteration on
the execution target and returns only convergence scalars. The driver owns
convergence testing at the 1e-8 duality gap (BASELINE.json:2), numerical-
failure recovery (deterministic regularization escalation), per-iteration
logging, checkpoint/resume, and recovery of the solution in the original
variable space.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional, Union

import numpy as np

from typing import TYPE_CHECKING

from distributedlpsolver_tpu.ipm.config import SolverConfig

if TYPE_CHECKING:  # real import is deferred to solve() — backends import ipm
    from distributedlpsolver_tpu.backends.base import SolverBackend
from distributedlpsolver_tpu.ipm.state import (
    IPMResult,
    IPMState,
    IterRecord,
    Status,
)
from distributedlpsolver_tpu.models.problem import (
    InteriorForm,
    LPProblem,
    to_interior_form,
)
from distributedlpsolver_tpu.obs import context as obs_context
from distributedlpsolver_tpu.obs import metrics as obs_metrics
from distributedlpsolver_tpu.obs import trace as obs_trace
from distributedlpsolver_tpu.utils import checkpoint as ckpt
from distributedlpsolver_tpu.utils.logging import IterLogger

_DIVERGE = 1e30


class SolveHooks:
    """Per-iteration instrumentation seam of the host loop.

    The supervisor (supervisor/supervisor.py) subclasses this to run each
    device step under a watchdog deadline and to health-check the
    convergence scalars the moment they land on the host. Both methods may
    raise; an exception aborts the solve (the logger still closes) and
    propagates to the caller for classification. Hooks force the
    per-iteration host loop — the fused on-device path has no iteration
    boundaries to instrument.
    """

    def run_step(self, step_fn, iteration: int):
        """Execute one device step (``step_fn`` returns (state, stats))."""
        return step_fn()

    def on_iterate(self, iteration: int, scalars: dict) -> None:
        """Inspect the host-side scalar dict after iteration ``iteration``.
        Called BEFORE the iterate is checkpointed, so a raising guard keeps
        a poisoned state off disk."""


def solve(
    problem: Union[LPProblem, InteriorForm],
    backend: Union[str, "SolverBackend"] = "tpu",
    config: Optional[SolverConfig] = None,
    warm_start=None,
    hooks: Optional[SolveHooks] = None,
    warm_cache=None,
    **config_overrides,
) -> IPMResult:
    """Solve an LP to the configured duality-gap tolerance.

    ``problem`` may be a general-form :class:`LPProblem` (converted via
    :func:`to_interior_form`; solution is recovered in the original space)
    or an :class:`InteriorForm` directly. ``backend`` is a registry name
    (``--backend=`` in the CLI, BASELINE.json:5) or an instance.

    ``warm_start`` accepts a raw :class:`IPMState` (trusted verbatim —
    the checkpoint-resume contract) or an :class:`ipm.warm.WarmStart`
    (safeguarded: shifted into the strict interior, recentred, and
    DROPPED for the cold start when its initial residuals regress — see
    ipm/warm.py). ``warm_cache`` is an optional
    :class:`serve.warmcache.WarmCache`: the solve looks up the problem's
    structural fingerprint for a cached scaling and prior iterate
    (delta-solve amortization — presolve is skipped on this path, since
    cached iterates live in the unreduced space), and stores its own
    scaling + final iterate back on an OPTIMAL finish.
    """
    from distributedlpsolver_tpu.backends.base import get_backend
    from distributedlpsolver_tpu.ipm import warm as warm_mod

    cfg = config or SolverConfig()
    if config_overrides:
        cfg = cfg.replace(**config_overrides)

    original: Optional[LPProblem] = problem if isinstance(problem, LPProblem) else None
    cache_fp = None
    cache_entry = None
    if warm_cache is not None and original is not None:
        from distributedlpsolver_tpu.utils.fingerprint import (
            structural_fingerprint,
        )

        # Model identity of the RAW problem; the entry's shape guard
        # runs against the interior form below (cached iterates live in
        # interior space, whose dims differ for general-form inputs).
        cache_fp = structural_fingerprint(
            original.A, original.m, original.n, original.lb, original.ub
        )
    presolve_info = None
    if (
        cfg.presolve
        and original is not None
        and original.block_structure is None  # reductions break the hint
        and warm_start is None  # warm starts are in the unreduced space
        and cache_fp is None  # cached iterates/scalings are too
    ):
        from distributedlpsolver_tpu.models.presolve import presolve as _presolve

        reduced, presolve_info = _presolve(original)
        if presolve_info.status is not None:
            return _presolved_result(original, presolve_info, backend)
        inf = to_interior_form(reduced)
    else:
        inf = to_interior_form(problem) if isinstance(problem, LPProblem) else problem
    if cache_fp is not None:
        cache_entry = warm_cache.lookup(cache_fp, inf.m, inf.n)
        if (
            warm_start is None
            and cache_entry is not None
            and cache_entry.state is not None
        ):
            warm_start = warm_mod.WarmStart(cache_entry.state, source="cache")
    if (
        cache_entry is not None
        and cache_entry.structure is not None
        and inf.block_structure is None
    ):
        # Structure detection amortized across the stream: the hint a
        # prior same-structure solve recorded routes this one straight
        # to the block backend without re-detecting.
        inf.block_structure = cache_entry.structure

    scaling = None
    inf_solve = inf
    if cfg.scale:
        if (
            cache_entry is not None
            and cache_entry.scaling is not None
            and cache_entry.scaled_A is not None
        ):
            # Delta-solve amortization: Ruiz factors depend only on A,
            # so a same-structure request reuses the cached (Dr, Dc) and
            # pre-scaled A — only the new b/c/u are rescaled here.
            scaling = cache_entry.scaling
            inf_solve = _rescale_interior(inf, scaling, cache_entry.scaled_A)
        else:
            from distributedlpsolver_tpu.models.scaling import equilibrate

            inf_solve, scaling = equilibrate(inf)

    be = get_backend(backend) if isinstance(backend, str) else backend
    logger = IterLogger(
        cfg.verbose, cfg.log_jsonl, fsync=cfg.log_fsync, append=cfg.log_append
    )

    def to_solver_space(host_state):
        return be.from_host(
            scaling.scale_state(host_state) if scaling else host_state
        )

    t_setup0 = time.perf_counter()
    be.setup(inf_solve, cfg)
    # Warm-cache-supplied preconditioner (the PR 8 follow-on): a backend
    # with the offer/export seam (sparse-iterative) seeds its PCG
    # preconditioner from the cached final scaling of the last OPTIMAL
    # same-structure solve — the factors freeze for the early (loose-
    # forcing) iterations instead of refactoring every step. Only valid
    # when this solve reuses the SAME Ruiz scaling the cached d was
    # exported under (the delta-solve path); offer_precond shape-guards
    # the rest.
    if (
        cache_entry is not None
        and cache_entry.precond_d is not None
        and hasattr(be, "offer_precond")
        and (not cfg.scale or cache_entry.scaling is not None)
    ):
        be.offer_precond(cache_entry.precond_d)
    fingerprint = ckpt.problem_fingerprint(inf) if cfg.checkpoint_path else ""
    resumed = (
        ckpt.maybe_load(cfg.checkpoint_path, fingerprint)
        if warm_start is None
        else None
    )
    warm_label = "cold"
    if isinstance(warm_start, warm_mod.WarmStart):
        state, warm_label = _init_warm_start(
            be, warm_start, inf, inf_solve, scaling, to_solver_space
        )
        start_iter = 0
    elif warm_start is not None:
        state, start_iter = to_solver_space(warm_start), 0
    elif (
        resumed is not None
        and resumed[2] == inf.name
        and resumed[0].x.shape == (inf.n,)
        and resumed[0].y.shape == (inf.m,)
    ):
        # Checkpoints are host-canonical (utils/checkpoint.py v3):
        # to_solver_space → backend.from_host re-pads and re-places the
        # iterate for THIS backend's layout, so the same file resumes on
        # a different mesh size (the elastic shrink path), a single
        # device, or the CPU.
        state, start_iter = to_solver_space(resumed[0]), resumed[1]
    else:
        state, start_iter = be.starting_point(), 0
    setup_time = time.perf_counter() - t_setup0

    on_host_state = None
    if warm_cache is not None and cache_fp is not None:
        def on_host_state(final_status, host_state):
            if final_status is not Status.OPTIMAL:
                return
            export = getattr(be, "export_precond", None)
            warm_cache.store(
                cache_fp,
                m=inf.m,
                n=inf.n,
                state=host_state,
                scaling=scaling,
                scaled_A=inf_solve.A if scaling is not None else None,
                structure=inf.block_structure,
                precond_d=export() if export is not None else None,
                tol=cfg.tol,
            )

    use_fused = cfg.fused_loop
    if use_fused is None:
        use_fused = not (cfg.checkpoint_every and cfg.checkpoint_path)
    if hooks is not None:
        use_fused = False  # hooks need iteration boundaries on the host
    if cfg.profile_dir:
        # Profiling wants per-iteration dispatch boundaries: the fused
        # loop is one opaque device program (and the profiler context
        # only wraps the host loop), so --profile-dir silently produced
        # nothing whenever the fused path ran.
        use_fused = False
    if use_fused:
        fused = _try_fused(be, state, cfg, logger)
        if fused is not None:
            state, status, history, last, solve_time, fused_iters = fused
            return _finalize(
                be, state, status, history, last, solve_time, setup_time,
                inf, original, backend, start_iter, scaling=scaling,
                presolve_info=presolve_info, extra_iters=fused_iters,
                warm_label=warm_label, on_host_state=on_host_state,
            )

    status = Status.ITERATION_LIMIT
    history = []
    last = None
    it = start_iter
    # Hot-path instruments, resolved ONCE before the loop (a registry
    # lookup per iteration would be a locked dict hit; a no-op method
    # call is free). Disabled mode (the default NULL registry) makes
    # every observe below a no-op with zero allocations.
    _reg = obs_metrics.get_registry()
    _m_iters = _reg.counter(
        "ipm_iterations_total", help="completed IPM iterations"
    )
    _m_step = _reg.histogram(
        "ipm_step_seconds", buckets=obs_metrics.SECONDS_BUCKETS,
        help="device-synchronized wall time per IPM iteration",
    )
    _m_refactor = _reg.counter(
        "ipm_refactorizations_total",
        help="bad-step regularization-bump refactorization attempts",
    )
    # Solver-depth tracing, resolved once like the instruments: the
    # owning request's context (set thread-locally by the serve solo
    # path) plus the tracer. Disabled tracer → one bool test per iter.
    _tracer = obs_trace.get_tracer()
    _trace_args = (
        obs_context.current().span_args()
        if _tracer.enabled and obs_context.current() is not None
        else None
    )
    t_solve0 = time.perf_counter()
    profile_stack = contextlib.ExitStack()
    try:
        profile_stack.enter_context(_maybe_profiler(cfg.profile_dir))
        while it < cfg.max_iter:
            t_it0 = time.perf_counter()
            refactor = 0
            while True:
                if hooks is None:
                    new_state, stats = _step_once(be, state)
                else:
                    step_state = state  # freeze for the deferred closure
                    new_state, stats = hooks.run_step(
                        lambda: _step_once(be, step_state), it + 1
                    )
                bad = bool(stats.bad)
                if not bad:
                    break
                refactor += 1
                _m_refactor.inc()
                if refactor > cfg.max_refactor or not be.bump_regularization():
                    status = Status.NUMERICAL_ERROR
                    break
            if bad:
                break
            state = new_state
            it += 1
            t_it = time.perf_counter() - t_it0
            _m_iters.inc()
            _m_step.observe(t_it)
            if _tracer.enabled:
                # One phase span per IPM iteration, trace-linked: a tail
                # request's slow endgame shows up as widening iter spans
                # under its own trace_id instead of a guess.
                it_args = {"iter": it, "refactor": refactor}
                if _trace_args is not None:
                    it_args.update(_trace_args)
                _tracer.complete(
                    f"ipm.iter {it}", t_it, cat="ipm", args=it_args
                )
            last = _to_floats(stats)
            rec = IterRecord(iter=it, t_iter=t_it, **last)
            history.append(rec)
            logger.log(rec)
            if hooks is not None:
                hooks.on_iterate(it, last)
            if cfg.checkpoint_every and it % cfg.checkpoint_every == 0 and cfg.checkpoint_path:
                host_state = be.to_host(state)
                if scaling is not None:
                    host_state = scaling.unscale_state(host_state)
                ckpt.save_state(
                    cfg.checkpoint_path, host_state, it, inf.name, fingerprint
                )
            if (
                last["rel_gap"] <= cfg.tol
                and last["pinf"] <= cfg.tol
                and last["dinf"] <= cfg.tol
            ):
                status = Status.OPTIMAL
                break
            from distributedlpsolver_tpu.ipm import core as _core

            pinfeas, dinfeas = _core.classify_divergence(
                last["mu"], last["pinf"], last["dinf"], last["rel_gap"],
                last["pobj"], last["dobj"],
            )
            if pinfeas:
                status = Status.PRIMAL_INFEASIBLE
                break
            if dinfeas:
                status = Status.DUAL_INFEASIBLE
                break
            if not np.isfinite(last["mu"]) or last["mu"] > _DIVERGE:
                status = Status.NUMERICAL_ERROR
                break
    finally:
        profile_stack.close()
        solve_time = time.perf_counter() - t_solve0
        logger.close()
        if cfg.profile_dir:
            _write_profile_report(
                cfg.profile_dir, history, setup_time, solve_time
            )

    return _finalize(
        be, state, status, history, last, solve_time, setup_time,
        inf, original, backend, start_iter, extra_iters=it - start_iter,
        scaling=scaling, presolve_info=presolve_info,
        warm_label=warm_label, on_host_state=on_host_state,
    )


def _rescale_interior(inf: InteriorForm, scaling, scaled_A) -> InteriorForm:
    """Apply a cached Ruiz scaling (same A by fingerprint contract) to a
    new interior form: the pre-scaled A is reused as-is, only the
    instance vectors b/c/u are rescaled — the delta-solve path's answer
    to re-running the equilibration sweeps per request."""
    import dataclasses as _dc

    import numpy as _np

    return _dc.replace(
        inf,
        c=inf.c * scaling.dc,
        A=scaled_A,
        b=inf.b * scaling.dr,
        u=_np.where(_np.isfinite(inf.u), inf.u / scaling.dc, _np.inf),
    )


def _init_warm_start(be, ws, inf, inf_solve, scaling, to_solver_space):
    """Safeguarded warm-start initialization: shift-and-recentre the
    prior iterate (ipm/warm.py), then accept it only when its initial
    residual merit does not regress past the Mehrotra cold start's —
    the fallback keeps an adversarial prior from costing more than the
    warm start could save. Returns (device_state, "warm"|"rejected")."""
    from distributedlpsolver_tpu.ipm import warm as warm_mod

    cold = be.starting_point()
    try:
        cand = warm_mod.interior_candidate(ws.state, inf)
        cand_scaled = scaling.scale_state(cand) if scaling else cand
        cold_host = be.to_host(cold)
        merit_w = warm_mod.residual_merit(inf_solve, cand_scaled)
        merit_c = warm_mod.residual_merit(inf_solve, cold_host)
        mu_w = warm_mod.state_mu(cand_scaled, inf_solve.u)
        mu_c = warm_mod.state_mu(cold_host, inf_solve.u)
        accept = (
            np.isfinite(merit_w)
            and np.isfinite(mu_w)
            and merit_w
            <= warm_mod.WARM_ACCEPT_FACTOR * max(merit_c, 1e-12)
            # μ guard: the primal/dual refresh makes even a far-off
            # prior nearly feasible — complementarity is what still
            # tells it apart from a useful start.
            and mu_w <= warm_mod.MU_ACCEPT_FACTOR * max(mu_c, 1e-12)
        )
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:  # malformed prior (shape drift): cold start
        accept = False
    if accept:
        return be.from_host(cand_scaled), "warm"
    obs_metrics.get_registry().counter(
        "warm_start_rejected_total",
        help="safeguard fallbacks: warm starts whose initial residuals "
        "regressed past the cold start's",
    ).inc()
    return cold, "rejected"


def _step_once(be, state):
    """One synchronized device step — the unit of work the supervisor's
    watchdog deadlines (the ``block_until_ready`` is where a hung dispatch
    actually blocks)."""
    new_state, stats = be.iterate(state)
    # The one sanctioned per-iteration sync: the convergence test and the
    # watchdog deadline both need the step to have actually finished.
    be.block_until_ready(stats.mu)  # graftcheck: disable=host-sync (watchdog)
    return new_state, stats


_STAT_FIELDS = (
    "mu", "gap", "rel_gap", "pinf", "dinf", "pobj", "dobj",
    "alpha_p", "alpha_d", "sigma",
)


def _try_fused(be, state, cfg: SolverConfig, logger: IterLogger):
    """Run the backend's fused on-device loop; None if unsupported."""
    from distributedlpsolver_tpu.ipm import core

    t0 = time.perf_counter()
    out = be.solve_full(state)
    if out is None:
        return None
    state, it_dev, status_code, buf = out
    be.block_until_ready(it_dev)
    solve_time = time.perf_counter() - t0

    iters = int(np.asarray(it_dev))
    # Backends may report more iterations than stats records (the PDHG
    # backend returns one summary row for thousands of inner steps).
    buf = np.asarray(buf)[: min(iters, len(np.asarray(buf)))]
    status = {
        core.STATUS_OPTIMAL: Status.OPTIMAL,
        core.STATUS_MAXITER: Status.ITERATION_LIMIT,
        core.STATUS_NUMERR: Status.NUMERICAL_ERROR,
        core.STATUS_PINFEAS: Status.PRIMAL_INFEASIBLE,
        core.STATUS_DINFEAS: Status.DUAL_INFEASIBLE,
        core.STATUS_STALL: Status.STALLED,
    }.get(int(np.asarray(status_code)), Status.NUMERICAL_ERROR)

    # Fused-loop records carry the AVERAGE seconds/iteration, not a
    # per-iteration measurement: the whole loop (or segment) runs as one
    # device program, so individual iteration boundaries never cross the
    # host. The host-driver path (fused_loop=False) records true per-
    # iteration wall times; the B:2 aggregate metric is exact either way.
    t_avg = solve_time / max(iters, 1)
    # One aggregate observation per fused solve (there are no host-side
    # iteration boundaries to time individually).
    obs_metrics.get_registry().counter(
        "ipm_iterations_total", help="completed IPM iterations"
    ).inc(iters)
    history, last = [], None
    for i in range(len(buf)):
        last = dict(zip(_STAT_FIELDS, (float(v) for v in buf[i])))
        rec = IterRecord(iter=i + 1, t_iter=t_avg, **last)
        history.append(rec)
        logger.log(rec)
    logger.close()
    return state, status, history, last, solve_time, iters


def _finalize(
    be, state, status, history, last, solve_time, setup_time,
    inf, original, backend, start_iter, extra_iters=None, scaling=None,
    presolve_info=None, warm_label="cold", on_host_state=None,
):
    n_iters = extra_iters if extra_iters is not None else len(history)
    _reg = obs_metrics.get_registry()
    _reg.counter(
        "ipm_solves_total", labels={"status": status.value},
        help="finished IPM solves by terminal status",
    ).inc()
    # Warm-vs-cold attribution: iterations per solve, split by how the
    # solve started (a safeguard-rejected warm start counts as cold — it
    # ran the cold trajectory).
    _reg.histogram(
        "ipm_iterations", buckets=obs_metrics.ITER_BUCKETS,
        labels={"start": "warm" if warm_label == "warm" else "cold"},
        help="IPM iterations per finished solve, by start kind",
    ).observe(n_iters)
    # One X span per solve on the calling thread's trace lane (reported
    # after the fact: the span covers the just-finished solve loop).
    _tracer = obs_trace.get_tracer()
    solve_args = {
        "backend": getattr(be, "name", str(backend)),
        "status": status.value,
        "iterations": n_iters,
    }
    _ctx = obs_context.current() if _tracer.enabled else None
    if _ctx is not None:
        solve_args.update(_ctx.span_args())
    _tracer.complete(
        f"ipm.solve {inf.name}", solve_time, cat="ipm", args=solve_args
    )
    if _tracer.enabled:
        # CG attribution for matrix-free backends: one span carrying
        # the solve's inner-iteration economics (cg_iters, precond,
        # shards, psum_per_iter) linked to the owning request's trace —
        # "blame endgame CG" becomes a lookup, not a guess.
        cg_report = getattr(be, "cg_report", None)
        if cg_report is not None:
            try:
                rep = cg_report()
            except Exception:  # telemetry must never sink a solve
                rep = None
            if rep and rep.get("cg_iters"):
                cg_args = {
                    "cg_iters": rep.get("cg_iters"),
                    "precond": rep.get("precond"),
                    "shards": rep.get("shards"),
                    "psum_per_iter": rep.get("psum_per_iter"),
                }
                if _ctx is not None:
                    cg_args.update(_ctx.span_args())
                _tracer.complete(
                    f"cg.solve {inf.name}", solve_time, cat="cg",
                    args=cg_args,
                )
    host = be.to_host(state)
    if scaling is not None:
        host = scaling.unscale_state(host)
    if on_host_state is not None:
        try:  # warm-cache store must never sink the solve
            on_host_state(status, host)
        except Exception:
            pass
    certificate = None
    if status in (
        Status.PRIMAL_INFEASIBLE,
        Status.DUAL_INFEASIBLE,
        Status.ITERATION_LIMIT,
        Status.STALLED,
        Status.NUMERICAL_ERROR,
    ):
        # Farkas-ray extraction (ipm/certificates.py): a passing
        # certificate is a mathematical proof, so it may UPGRADE a
        # heuristic/indeterminate status — never the other way around.
        try:
            from distributedlpsolver_tpu.ipm import certificates as _certs

            certificate = _certs.extract_certificate(
                inf, host, status.value
            )
        except Exception:  # certificates must never sink a solve
            certificate = None
        if certificate is not None and certificate.certified:
            status = (
                Status.PRIMAL_INFEASIBLE
                if certificate.kind == "primal_infeasible"
                else Status.DUAL_INFEASIBLE
            )
    x_t = np.asarray(host.x, dtype=np.float64)
    obj_min = inf.objective(x_t)
    y = np.asarray(host.y, dtype=np.float64)
    s = np.asarray(host.s, dtype=np.float64)
    if original is not None:
        x_orig = inf.recover(x_t)
        if presolve_info is not None:
            # ``inf`` was built from the presolve-reduced problem: expand
            # the primal back to the full variable space and recover exact
            # duals for the removed rows (models/presolve.py).
            x_orig = presolve_info.postsolve_x(x_orig)
            y, s = presolve_info.postsolve_duals(original, x_orig, y)
            obj_min = float(original.c @ x_orig) + original.c0
        else:
            # Same contract without presolve: rows are preserved by
            # to_interior_form, so y maps 1:1 and the original-space
            # reduced costs re-derive as c - Aᵀy (minimized sense).
            s = original.c - np.asarray(original.A.T @ y).ravel()
        objective = -obj_min if original.maximize else obj_min
    else:
        x_orig = x_t
        objective = obj_min

    return IPMResult(
        status=status,
        x=x_orig,
        objective=objective,
        iterations=extra_iters if extra_iters is not None else len(history),
        rel_gap=last["rel_gap"] if last else np.inf,
        pinf=last["pinf"] if last else np.inf,
        dinf=last["dinf"] if last else np.inf,
        solve_time=solve_time,
        setup_time=setup_time,
        history=history,
        backend=getattr(be, "name", str(backend)),
        name=inf.name,
        y=y,
        s=s,
        certificate=certificate,
        warm=warm_label,
    )


def _presolved_result(original: LPProblem, info, backend) -> IPMResult:
    """Result for a problem presolve settled without running the IPM."""
    optimal = info.status == Status.OPTIMAL
    x = info.postsolve_x(np.empty(0)) if optimal else None
    y = s = None
    if optimal:
        y, s = info.postsolve_duals(original, x, None)
        obj = -info.objective if original.maximize else info.objective
    elif info.status == Status.DUAL_INFEASIBLE:
        # Primal unbounded: the minimized objective runs to -inf
        # (+inf in the original sense for a maximization).
        obj = np.inf if original.maximize else -np.inf
    else:  # infeasible: no attainable objective
        obj = -np.inf if original.maximize else np.inf
    return IPMResult(
        status=info.status,
        x=x,
        objective=obj,
        iterations=0,
        rel_gap=0.0 if optimal else np.inf,
        pinf=0.0 if optimal else np.inf,
        dinf=0.0 if optimal else np.inf,
        solve_time=0.0,
        setup_time=0.0,
        history=[],
        backend=f"presolve+{backend if isinstance(backend, str) else getattr(backend, 'name', '')}",
        name=original.name,
        y=y,
        s=s,
    )


def _to_floats(stats):
    d = {f: float(np.asarray(getattr(stats, f))) for f in stats._fields if f != "bad"}
    return d


def _maybe_profiler(profile_dir: Optional[str]):
    if profile_dir:
        import jax

        return jax.profiler.trace(profile_dir)
    import contextlib

    return contextlib.nullcontext()


def _write_profile_report(
    profile_dir: str, history, setup_time: float, solve_time: float
) -> None:
    """--profile-dir honesty (VERDICT §5.1): through the tunneled-TPU
    path ``jax.profiler.trace`` completes without writing a single file,
    so a profile run used to yield an empty directory. The dispatch-level
    timer (the per-iteration wall times the host loop measures anyway) is
    the measurement that demonstrably works everywhere — always write its
    report into the profile dir, and WARN when the trace produced nothing
    so nobody mistakes an empty trace for a profiled run."""
    import json
    import os
    import sys

    report_name = "dispatch_timings.json"
    try:
        os.makedirs(profile_dir, exist_ok=True)
        traced = any(
            fn != report_name
            for _, _, files in os.walk(profile_dir)
            for fn in files
        )
        t_iters = [r.t_iter for r in history]
        report = {
            "jax_profiler_trace_wrote_files": traced,
            "setup_s": round(setup_time, 6),
            "solve_s": round(solve_time, 6),
            "iterations": len(t_iters),
            "t_iter_s": [round(t, 6) for t in t_iters],
            "t_iter_mean_s": round(
                sum(t_iters) / len(t_iters), 6
            ) if t_iters else None,
            "t_iter_max_s": round(max(t_iters), 6) if t_iters else None,
        }
        with open(os.path.join(profile_dir, report_name), "w") as fh:
            json.dump(report, fh, indent=2)
        if not traced:
            print(
                f"WARNING: jax.profiler.trace produced no files in "
                f"{profile_dir!r} (known through tunneled TPUs); wrote the "
                f"dispatch-level timing report to {report_name} instead",
                file=sys.stderr,
            )
    except Exception as e:  # profiling must never sink the solve
        print(
            f"WARNING: could not write profile report to "
            f"{profile_dir!r}: {e}",
            file=sys.stderr,
        )
