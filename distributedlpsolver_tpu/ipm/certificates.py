"""Infeasibility / unboundedness certificates (Farkas rays).

Upgrades the driver's divergence *heuristics* (core.classify_divergence)
to checkable mathematical certificates, extracted from the diverging
iterate on the host (VERDICT.md round 1, item 10; the reference has no
such machinery on available evidence — SURVEY.md §5.3 — so this is a
capability addition, not a parity item).

All certificates are stated on the interior form
``min cᵀx  s.t.  Ax = b, 0 ≤ x, x_j ≤ u_j (j ∈ bounded)``:

* **Primal infeasibility** (Farkas): a pair ``(y, z)`` with ``z ≥ 0``
  supported on the bounded columns such that ``Aᵀy − z ≤ 0``
  componentwise (so ``Aᵀy ≤ 0`` on unbounded columns) and
  ``bᵀy − Σ u_j z_j > 0``. For any feasible x this gives
  ``bᵀy = xᵀAᵀy ≤ xᵀz ≤ Σ u_j z_j`` — a contradiction, so no feasible
  x exists. The candidate comes from the diverging dual iterate y with
  the optimal compensating ``z = max(Aᵀy, 0)`` on bounded columns.
* **Dual infeasibility / primal unboundedness**: a ray ``r ≥ 0`` with
  ``r_j = 0`` on bounded columns, ``Ar ≈ 0`` and ``cᵀr < 0`` — moving
  along r stays feasible and decreases the objective without bound. The
  candidate is the (blowing-up) primal iterate direction ``x/‖x‖``.

Quality is reported as the certified objective-separation relative to
the residual violation; ``certified`` requires the violation to be at
roundoff-ish scale relative to the separation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import scipy.sparse as sp


@dataclasses.dataclass
class Certificate:
    """A checkable Farkas certificate (interior-form space)."""

    kind: str  # "primal_infeasible" | "dual_infeasible"
    ray: np.ndarray  # y for primal certificates, x-ray for dual ones
    z: Optional[np.ndarray]  # bound multipliers (primal certificates)
    separation: float  # bᵀy − uᵀz  (primal) / −cᵀr (dual); > 0 when valid
    violation: float  # max constraint violation of the ray
    certified: bool  # violation small relative to separation

    def summary(self) -> str:
        tag = "CERTIFIED" if self.certified else "uncertified"
        return (
            f"{self.kind} certificate [{tag}]: separation="
            f"{self.separation:.3e}, violation={self.violation:.3e}"
        )


def _matvecs(A):
    if sp.issparse(A):
        return (lambda v: A @ v), (lambda v: A.T @ v)
    Ad = np.asarray(A)
    return (lambda v: Ad @ v), (lambda v: Ad.T @ v)


def primal_infeasibility_certificate(
    inf, y, rel_tol: float = 1e-6
) -> Optional[Certificate]:
    """Try to certify primal infeasibility from a dual iterate ``y``."""
    y = np.asarray(y, dtype=np.float64)
    ny = float(np.linalg.norm(y))
    if not np.isfinite(ny) or ny == 0.0:
        return None
    yh = y / ny
    _, rmat = _matvecs(inf.A)
    g = np.asarray(rmat(yh)).ravel()
    u = np.asarray(inf.u, dtype=np.float64)
    bounded = np.isfinite(u)
    z = np.where(bounded, np.maximum(g, 0.0), 0.0)
    # Violation: positive reduced ray-cost on UNBOUNDED columns cannot be
    # compensated by any z — it is the certificate's defect.
    viol = float(np.max(np.maximum(g, 0.0) * (~bounded), initial=0.0))
    sep = float(np.asarray(inf.b) @ yh - u[bounded] @ z[bounded])
    scale = 1.0 + float(np.abs(np.asarray(inf.b) @ yh)) + float(
        np.abs(u[bounded] @ z[bounded]) if bounded.any() else 0.0
    )
    # The violation is one component of Aᵀŷ with ‖ŷ‖₂ = 1 — and only
    # UNBOUNDED columns can contribute it — so its natural magnitude is
    # the largest unbounded-column norm of A. Test it relative to that,
    # NOT to max(1, sep): a feasible problem whose feasible points all
    # have huge ‖x‖₁ drives sep large, and a sep-relative tolerance
    # would then admit a materially violated "certificate" that falsely
    # upgrades STALLED to PRIMAL_INFEASIBLE. (Frobenius would be
    # √(m·n)-looser than the component's scale at reference sizes, and a
    # large-norm BOUNDED column must not inflate the tolerance either.)
    A = inf.A
    col_sq = (
        np.asarray(A.power(2).sum(axis=0)).ravel() if sp.issparse(A)
        else np.einsum("ij,ij->j", np.asarray(A), np.asarray(A))
    )
    col_scale = float(np.sqrt(np.max(col_sq[~bounded], initial=0.0)))
    certified = (
        sep > rel_tol * scale and viol <= rel_tol * max(col_scale, 1e-30)
    )
    if sep <= 0:
        return None
    return Certificate(
        kind="primal_infeasible", ray=yh, z=z,
        separation=sep, violation=viol, certified=bool(certified),
    )


def dual_infeasibility_certificate(
    inf, x, rel_tol: float = 1e-6
) -> Optional[Certificate]:
    """Try to certify primal unboundedness from a primal iterate ``x``."""
    x = np.asarray(x, dtype=np.float64)
    nx = float(np.linalg.norm(x))
    if not np.isfinite(nx) or nx == 0.0:
        return None
    u = np.asarray(inf.u, dtype=np.float64)
    bounded = np.isfinite(u)
    r = np.maximum(x / nx, 0.0)
    r[bounded] = 0.0  # a recession ray cannot move bounded coordinates
    nr = float(np.linalg.norm(r))
    if nr == 0.0:
        return None
    r /= nr
    mat, _ = _matvecs(inf.A)
    viol = float(np.linalg.norm(np.asarray(mat(r)).ravel()))
    sep = -float(np.asarray(inf.c) @ r)
    if sep <= 0:
        return None
    # Scale-relative test: ||Ar|| must be small relative to ||A||'s scale
    # (a uniformly tiny A makes every unit ray "near-null" in absolute
    # terms) and the objective descent relative to ||c|| — otherwise a
    # feasible problem with small data could be "certified" unbounded.
    A = inf.A
    normA = float(
        np.sqrt((A.power(2)).sum()) if sp.issparse(A)
        else np.linalg.norm(np.asarray(A))
    )
    normc = float(np.linalg.norm(np.asarray(inf.c)))
    certified = (
        sep > rel_tol * max(normc, 1e-30)
        and viol <= rel_tol * max(normA, 1e-30)
    )
    return Certificate(
        kind="dual_infeasible", ray=r, z=None,
        separation=sep, violation=viol, certified=bool(certified),
    )


def extract_certificate(inf, host_state, status_name: str):
    """Certificate attempt for a non-optimal terminal state.

    Tries the certificate matching the heuristic verdict first, then the
    other one (an ITERATION_LIMIT run may still carry a clean ray).
    Returns the best Certificate or None.
    """
    cands = []
    if status_name != "dual_infeasible":
        c = primal_infeasibility_certificate(inf, host_state.y)
        if c is not None:
            cands.append(c)
    if status_name != "primal_infeasible":
        c = dual_infeasibility_certificate(inf, host_state.x)
        if c is not None:
            cands.append(c)
    certified = [c for c in cands if c.certified]
    if certified:
        return max(certified, key=lambda c: c.separation)
    return max(cands, key=lambda c: c.separation) if cands else None
