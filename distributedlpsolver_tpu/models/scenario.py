"""Two-stage stochastic LP model layer: ``ScenarioLP`` — one base model
× K scenario deltas as a first-class problem object.

The stochastic scenario tier (ROADMAP "stochastic scenario tier") serves
two-stage stochastic LPs

.. code-block:: text

    min  c₀ᵀx₀ + Σ_k p_k·c_kᵀx_k
    s.t. A₀·x₀                 = b₀        (first-stage rows, m0 of them)
         T_k·x₀ + W_k·x_k      = b_k       (recourse rows, scenario k)
         x ≥ 0

whose constraint matrix is the BORDERED (dual block-angular) arrow the
storm generators already emit: scenario blocks couple only through the
shared first-stage columns. ``ScenarioLP`` keeps the blocks unassembled
(A₀/b₀/c₀ + stacked T/W/b/c + probability weights) so the
scenario-decomposed engine (backends/scenario.py) can batch the
per-scenario Schur work over K without re-slicing a monolithic matrix,
while :meth:`ScenarioLP.to_block_angular` lowers to a plain sparse
:class:`LPProblem` — the oracle form every other backend (and HiGHS)
can check the decomposition against.

Serialization is strict JSON (:meth:`to_dict`/:meth:`from_dict`) so a
scenario job survives the durable job journal (serve/journal.py) the
same way plain requests do — all values are finite by construction, so
no inf sentinels are needed.

Generators follow the repo's witness construction (feasible + bounded
by building a strictly feasible primal point and dual certificate
first); ``scenario_delta_stream`` emits waves of b/c-only deltas
against one shared base so the PR 8 structural fingerprints (which
exclude b and c) hit across waves and the warm cache amortizes.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.models.problem import LPProblem

_INF = np.inf


def scenario_k_bucket(k: int) -> int:
    """Padded scenario-count bucket for ``k`` scenarios: the pow2 ladder
    (1, 2, 4, 8, ...) the scenario engine compiles one program per. All
    K inside one bucket share the compiled Schur-batch programs — dead
    lanes are masked, never re-traced."""
    if k < 1:
        raise ValueError(f"scenario count must be >= 1; got {k}")
    b = 1
    while b < k:
        b *= 2
    return b


@dataclasses.dataclass
class ScenarioLP:
    """One base model × K scenario deltas (all scenarios share a block
    shape, so the recourse blocks stack into dense (K, ·, ·) tensors).

    ``c`` holds the RAW per-scenario costs; the lowering multiplies in
    the probability weights (min c₀ᵀx₀ + Σ p_k c_kᵀ x_k)."""

    A0: np.ndarray  # (m0, n0) first-stage rows (m0 may be 0)
    b0: np.ndarray  # (m0,)
    c0: np.ndarray  # (n0,) first-stage objective
    T: np.ndarray  # (K, mk, n0) first-stage coupling per scenario
    W: np.ndarray  # (K, mk, nk) recourse blocks
    b: np.ndarray  # (K, mk) recourse rhs
    c: np.ndarray  # (K, nk) recourse objective (pre-probability)
    probs: Optional[np.ndarray] = None  # (K,) weights; None = uniform
    name: str = "scenario"

    def __post_init__(self):
        self.A0 = np.asarray(self.A0, dtype=np.float64)
        self.b0 = np.asarray(self.b0, dtype=np.float64).ravel()
        self.c0 = np.asarray(self.c0, dtype=np.float64).ravel()
        self.T = np.asarray(self.T, dtype=np.float64)
        self.W = np.asarray(self.W, dtype=np.float64)
        self.b = np.asarray(self.b, dtype=np.float64)
        self.c = np.asarray(self.c, dtype=np.float64)
        if self.A0.ndim != 2:
            raise ValueError(f"A0 must be 2-D; got shape {self.A0.shape}")
        m0, n0 = self.A0.shape
        if self.T.ndim != 3 or self.W.ndim != 3:
            raise ValueError("T and W must be (K, mk, ·) stacks")
        K, mk, n0_t = self.T.shape
        _, mk_w, nk = self.W.shape
        if K < 1:
            raise ValueError("a ScenarioLP needs at least one scenario")
        if n0_t != n0 or mk_w != mk or self.W.shape[0] != K:
            raise ValueError(
                f"block shapes disagree: A0 {self.A0.shape}, "
                f"T {self.T.shape}, W {self.W.shape}"
            )
        if self.b0.shape != (m0,) or self.c0.shape != (n0,):
            raise ValueError("b0/c0 shapes disagree with A0")
        if self.b.shape != (K, mk) or self.c.shape != (K, nk):
            raise ValueError("b/c shapes disagree with T/W")
        if self.probs is None:
            self.probs = np.full(K, 1.0 / K)
        else:
            self.probs = np.asarray(self.probs, dtype=np.float64).ravel()
            if self.probs.shape != (K,):
                raise ValueError(f"probs must have shape ({K},)")
            if np.any(self.probs <= 0):
                raise ValueError("probs must be strictly positive")

    # -- shape surface ----------------------------------------------------

    @property
    def n_scenarios(self) -> int:
        return self.T.shape[0]

    @property
    def first_stage_m(self) -> int:
        return self.A0.shape[0]

    @property
    def first_stage_n(self) -> int:
        return self.A0.shape[1]

    @property
    def block_m(self) -> int:
        return self.T.shape[1]

    @property
    def block_n(self) -> int:
        return self.W.shape[2]

    @property
    def m(self) -> int:
        """Rows of the lowered form."""
        return self.first_stage_m + self.n_scenarios * self.block_m

    @property
    def n(self) -> int:
        """Columns of the lowered form."""
        return self.first_stage_n + self.n_scenarios * self.block_n

    def structure_hint(self) -> dict:
        """The ``two_stage`` block-structure hint the lowered problem
        carries — consumed by backends/auto routing, the scenario
        engine's layout resolution, and (first-stage-row-free patterns)
        the bordered-Woodbury preconditioner."""
        return {
            "kind": "two_stage",
            "num_blocks": int(self.n_scenarios),
            "block_m": int(self.block_m),
            "block_n": int(self.block_n),
            "first_stage_n": int(self.first_stage_n),
            "first_stage_m": int(self.first_stage_m),
        }

    # -- lowering ---------------------------------------------------------

    def to_block_angular(self) -> LPProblem:
        """Lower to one assembled sparse :class:`LPProblem` (rows:
        first-stage then scenario blocks; columns: x₀ then per-scenario
        x_k), with the ``two_stage`` structure hint attached. This is
        the oracle form: any backend that can solve a sparse LP checks
        the decomposed engine, and the serve layer journals/routes it
        like any other general-form request (sparse A keeps it off the
        dense bucketed path)."""
        K, mk, nk = self.n_scenarios, self.block_m, self.block_n
        m0, n0 = self.A0.shape
        blocks = [
            [sp.csr_matrix(self.A0)]
            + [None] * K
        ]
        for k in range(K):
            row = [sp.csr_matrix(self.T[k])] + [None] * K
            row[1 + k] = sp.csr_matrix(self.W[k])
            blocks.append(row)
        A = sp.bmat(blocks, format="csr")
        c = np.concatenate(
            [self.c0] + [self.probs[k] * self.c[k] for k in range(K)]
        )
        b = np.concatenate([self.b0] + [self.b[k] for k in range(K)])
        n = n0 + K * nk
        p = LPProblem(
            c=c, A=A, rlb=b, rub=b, lb=np.zeros(n), ub=np.full(n, _INF),
            name=self.name,
        )
        p.block_structure = self.structure_hint()
        return p

    # -- strict-JSON round-trip -------------------------------------------

    def to_dict(self) -> dict:
        """JSON-serializable round-trip (strict JSON: every value is a
        finite float/int/str) — the scenario payload of ``POST
        /v1/solve`` and the journal's replayable spec."""
        return {
            "A0": [[float(v) for v in row] for row in self.A0],
            "b0": [float(v) for v in self.b0],
            "c0": [float(v) for v in self.c0],
            "T": [[[float(v) for v in r] for r in Tk] for Tk in self.T],
            "W": [[[float(v) for v in r] for r in Wk] for Wk in self.W],
            "b": [[float(v) for v in bk] for bk in self.b],
            "c": [[float(v) for v in ck] for ck in self.c],
            "probs": [float(v) for v in self.probs],
            "name": self.name,
            "shape": {
                "n_scenarios": int(self.n_scenarios),
                "block_m": int(self.block_m),
                "block_n": int(self.block_n),
                "first_stage_m": int(self.first_stage_m),
                "first_stage_n": int(self.first_stage_n),
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioLP":
        """Inverse of :meth:`to_dict`."""
        shape = d.get("shape") or {}
        m0 = int(shape.get("first_stage_m", len(d["b0"])))
        n0 = int(shape.get("first_stage_n", len(d["c0"])))
        A0 = np.asarray(d["A0"], dtype=np.float64).reshape(m0, n0)
        return cls(
            A0=A0,
            b0=np.asarray(d["b0"], dtype=np.float64),
            c0=np.asarray(d["c0"], dtype=np.float64),
            T=np.asarray(d["T"], dtype=np.float64),
            W=np.asarray(d["W"], dtype=np.float64),
            b=np.asarray(d["b"], dtype=np.float64),
            c=np.asarray(d["c"], dtype=np.float64),
            probs=(
                np.asarray(d["probs"], dtype=np.float64)
                if d.get("probs") is not None
                else None
            ),
            name=str(d.get("name", "scenario")),
        )


# -- generators --------------------------------------------------------------


def _witness_blocks(rng, K, mk, nk, m0, n0):
    """Random block data + a strictly feasible primal/dual witness pair
    for the lowered form (the repo's feasible+bounded construction)."""
    A0 = rng.standard_normal((m0, n0))
    T = rng.standard_normal((K, mk, n0)) * 0.5
    W = rng.standard_normal((K, mk, nk))
    # Diagonal-ish boost keeps every W_k full row rank (the per-scenario
    # Schur block S_k = W_k·D_k·W_kᵀ must be SPD), mirroring
    # generators.storm_sparse_lp's guaranteed recourse entries.
    for k in range(K):
        idx = np.arange(mk) % nk
        W[k, np.arange(mk), idx] += 2.0 + rng.uniform(0.5, 1.5, size=mk)
    return A0, T, W


def two_stage_storm(
    num_scenarios: int,
    block_m: int = 8,
    block_n: int = 12,
    first_stage_n: int = 8,
    first_stage_m: int = 2,
    seed: int = 0,
    probs: Optional[np.ndarray] = None,
) -> ScenarioLP:
    """Seeded storm-profile two-stage stochastic LP (dense small blocks
    — the scenario engine's native workload; the sparse 20k-row cousin
    is :func:`~distributedlpsolver_tpu.models.generators.storm_sparse_lp`).

    Feasible + bounded by the witness trick on the LOWERED form: draw
    x* > 0, set b from it; draw (y, s > 0), set the lowered c = Aᵀy + s
    and split it back into (c₀, p_k·c_k). ``block_n >= block_m`` keeps
    every recourse block full row rank. Fully seeded."""
    if num_scenarios < 1:
        raise ValueError(
            f"num_scenarios must be >= 1; got {num_scenarios}"
        )
    if block_n < block_m:
        raise ValueError(
            f"block_n ({block_n}) must be >= block_m ({block_m}) so the "
            f"recourse blocks have full row rank"
        )
    rng = np.random.default_rng(seed)
    K, mk, nk = num_scenarios, block_m, block_n
    m0, n0 = first_stage_m, first_stage_n
    A0, T, W = _witness_blocks(rng, K, mk, nk, m0, n0)
    if probs is None:
        raw = rng.uniform(0.5, 1.5, size=K)
        probs = raw / raw.sum()
    probs = np.asarray(probs, dtype=np.float64)

    # Primal witness x* > 0 → b; dual witness (y, s > 0) → c.
    x0s = rng.uniform(0.5, 2.0, size=n0)
    xks = rng.uniform(0.5, 2.0, size=(K, nk))
    b0 = A0 @ x0s
    b = np.einsum("kmn,n->km", T, x0s) + np.einsum(
        "kmn,kn->km", W, xks
    )
    y0 = rng.standard_normal(m0)
    yk = rng.standard_normal((K, mk))
    s0 = rng.uniform(0.5, 2.0, size=n0)
    sk = rng.uniform(0.5, 2.0, size=(K, nk))
    c0 = A0.T @ y0 + np.einsum("kmn,km->n", T, yk) + s0
    # Lowered column block k carries p_k·c_k = W_kᵀy_k + s_k.
    ck = (np.einsum("kmn,km->kn", W, yk) + sk) / probs[:, None]
    return ScenarioLP(
        A0=A0, b0=b0, c0=c0, T=T, W=W, b=b, c=ck, probs=probs,
        name=f"two_stage_storm_K{K}_{mk}x{nk}_n0{n0}_s{seed}",
    )


def scenario_delta_stream(
    n_requests: int,
    num_scenarios: int = 8,
    block_m: int = 6,
    block_n: int = 10,
    first_stage_n: int = 6,
    first_stage_m: int = 2,
    jitter: float = 0.02,
    seed: int = 0,
    offset: int = 0,
) -> Iterator[ScenarioLP]:
    """Waves of b/c-only scenario deltas against ONE shared base: every
    yielded :class:`ScenarioLP` reuses the identical (A₀, T, W, probs)
    and re-derives b/c from jittered witnesses, so all lowered forms
    share one structural fingerprint (utils/fingerprint — b/c excluded)
    and the warm cache amortizes across the wave. Fully seeded;
    ``offset`` skips the first draws so a follow-on wave continues the
    SAME stream (the warm-vs-cold probe's steady-state leg)."""
    base_rng = np.random.default_rng((seed, 7919))
    K, mk, nk = num_scenarios, block_m, block_n
    m0, n0 = first_stage_m, first_stage_n
    A0, T, W = _witness_blocks(base_rng, K, mk, nk, m0, n0)
    raw = base_rng.uniform(0.5, 1.5, size=K)
    probs = raw / raw.sum()
    x0s = base_rng.uniform(0.5, 2.0, size=n0)
    xks = base_rng.uniform(0.5, 2.0, size=(K, nk))
    y0 = base_rng.standard_normal(m0)
    yk = base_rng.standard_normal((K, mk))
    s0 = base_rng.uniform(0.5, 2.0, size=n0)
    sk = base_rng.uniform(0.5, 2.0, size=(K, nk))

    rng = np.random.default_rng((seed, 104729))
    for r in range(offset + n_requests):
        x0j = x0s * (1.0 + jitter * rng.standard_normal(n0))
        xkj = xks * (1.0 + jitter * rng.standard_normal((K, nk)))
        s0j = np.maximum(
            s0 * (1.0 + jitter * rng.standard_normal(n0)), 0.05
        )
        skj = np.maximum(
            sk * (1.0 + jitter * rng.standard_normal((K, nk))), 0.05
        )
        if r < offset:
            continue
        b0 = A0 @ x0j
        b = np.einsum("kmn,n->km", T, x0j) + np.einsum(
            "kmn,kn->km", W, xkj
        )
        c0 = A0.T @ y0 + np.einsum("kmn,km->n", T, yk) + s0j
        ck = (np.einsum("kmn,km->kn", W, yk) + skj) / probs[:, None]
        yield ScenarioLP(
            A0=A0, b0=b0, c0=c0, T=T, W=W, b=b, c=ck, probs=probs,
            name=f"scenario_delta_K{K}_r{r}",
        )
