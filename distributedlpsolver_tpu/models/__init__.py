from distributedlpsolver_tpu.models.problem import InteriorForm, LPProblem, to_interior_form
from distributedlpsolver_tpu.models.generators import (
    BatchedLP,
    block_angular_lp,
    random_batched_lp,
    random_dense_lp,
    random_general_lp,
)
from distributedlpsolver_tpu.models.presolve import presolve
from distributedlpsolver_tpu.models.scenario import (
    ScenarioLP,
    scenario_delta_stream,
    scenario_k_bucket,
    two_stage_storm,
)
from distributedlpsolver_tpu.models.structure import (
    detect_block_structure,
    detect_two_stage,
)

__all__ = [
    "LPProblem", "InteriorForm", "to_interior_form", "BatchedLP",
    "random_dense_lp", "random_general_lp", "random_batched_lp", "block_angular_lp",
    "presolve", "detect_block_structure", "detect_two_stage",
    "ScenarioLP", "two_stage_storm", "scenario_delta_stream",
    "scenario_k_bucket",
]
