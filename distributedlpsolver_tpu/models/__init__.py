from distributedlpsolver_tpu.models.problem import InteriorForm, LPProblem, to_interior_form
from distributedlpsolver_tpu.models.generators import (
    BatchedLP,
    block_angular_lp,
    random_batched_lp,
    random_dense_lp,
    random_general_lp,
)

__all__ = [
    "LPProblem", "InteriorForm", "to_interior_form", "BatchedLP",
    "random_dense_lp", "random_general_lp", "random_batched_lp", "block_angular_lp",
]
