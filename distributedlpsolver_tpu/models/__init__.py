from distributedlpsolver_tpu.models.problem import InteriorForm, LPProblem, to_interior_form
from distributedlpsolver_tpu.models.generators import (
    BatchedLP,
    block_angular_lp,
    random_batched_lp,
    random_dense_lp,
    random_general_lp,
)
from distributedlpsolver_tpu.models.presolve import presolve
from distributedlpsolver_tpu.models.structure import detect_block_structure

__all__ = [
    "LPProblem", "InteriorForm", "to_interior_form", "BatchedLP",
    "random_dense_lp", "random_general_lp", "random_batched_lp", "block_angular_lp",
    "presolve", "detect_block_structure",
]
