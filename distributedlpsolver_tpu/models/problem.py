"""LP problem representations and standard-form conversion.

Two representations:

* :class:`LPProblem` — the *general* form produced by the MPS reader and the
  generators: ``min cᵀx + c0  s.t.  rlb ≤ Ax ≤ rub,  lb ≤ x ≤ ub``.
  Row senses (E/L/G/ranged) are encoded purely via ``rlb``/``rub``.

* :class:`InteriorForm` — the canonical form consumed by the IPM core:
  ``min c̃ᵀx̃  s.t.  Ãx̃ = b,  0 ≤ x̃ (≤ u where finite)``.
  Inequality rows become slack columns, finite lower bounds are shifted to
  zero, upper-bounded-only columns are negated, and free columns are split —
  so the IPM only ever sees equality rows plus non-negative variables with
  optional finite upper bounds. The conversion records enough metadata to
  recover the original ``x`` and objective value.

The reference's LP model layer is reconstructed from BASELINE.json:5,7-11
(see SURVEY.md §2 "LP standard-form model"); no reference source was
available to cite (SURVEY.md §0).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import numpy as np
import scipy.sparse as sp

Matrix = Union[np.ndarray, sp.spmatrix]

_INF = np.inf


def _is_sparse(A: Matrix) -> bool:
    return sp.issparse(A)


@dataclasses.dataclass
class LPProblem:
    """General-form LP: ``min cᵀx + c0  s.t.  rlb ≤ Ax ≤ rub, lb ≤ x ≤ ub``."""

    c: np.ndarray  # (n,)
    A: Matrix  # (m, n) dense ndarray or scipy sparse
    rlb: np.ndarray  # (m,) row lower bounds (-inf for L rows)
    rub: np.ndarray  # (m,) row upper bounds (+inf for G rows)
    lb: np.ndarray  # (n,) column lower bounds
    ub: np.ndarray  # (n,) column upper bounds
    c0: float = 0.0  # objective constant
    name: str = "LP"
    row_names: Optional[list] = None
    col_names: Optional[list] = None
    integer_cols: list = dataclasses.field(default_factory=list)  # LP-relaxed
    maximize: bool = False  # original sense; c/c0 are always stored minimized
    # Optional block-angular layout hint {num_blocks, block_m, block_n,
    # link_m} describing A's row/col grouping (rows: K·block_m block rows
    # then link_m linking rows; cols: block k owns columns
    # [k·block_n, (k+1)·block_n)). Consumed by the Schur-complement backend.
    block_structure: Optional[dict] = None

    def __post_init__(self):
        if not sp.issparse(self.A):
            self.A = np.asarray(self.A, dtype=np.float64)
        self.c = np.asarray(self.c, dtype=np.float64).ravel()
        self.rlb = np.asarray(self.rlb, dtype=np.float64).ravel()
        self.rub = np.asarray(self.rub, dtype=np.float64).ravel()
        self.lb = np.asarray(self.lb, dtype=np.float64).ravel()
        self.ub = np.asarray(self.ub, dtype=np.float64).ravel()
        m, n = self.shape
        if self.c.shape != (n,):
            raise ValueError(f"c has shape {self.c.shape}, expected ({n},)")
        for arr, k, nm in [
            (self.rlb, m, "rlb"),
            (self.rub, m, "rub"),
            (self.lb, n, "lb"),
            (self.ub, n, "ub"),
        ]:
            if arr.shape != (k,):
                raise ValueError(f"{nm} has shape {arr.shape}, expected ({k},)")
        if np.any(self.rlb > self.rub):
            raise ValueError("rlb > rub for some row")
        if np.any(self.lb > self.ub):
            raise ValueError("lb > ub for some column")

    @property
    def shape(self) -> tuple:
        return self.A.shape

    @property
    def m(self) -> int:
        return self.A.shape[0]

    @property
    def n(self) -> int:
        return self.A.shape[1]

    def objective(self, x: np.ndarray) -> float:
        return float(self.c @ x) + self.c0

    def to_dict(self) -> dict:
        """JSON-serializable round-trip of the problem — the durable job
        journal's replay payload (serve/journal.py). Dense ``A`` stores
        row lists; sparse ``A`` stores COO triplets so journaling never
        densifies. Infinities survive as the strings "inf"/"-inf"
        (strict JSON has no Infinity literal)."""

        def _vec(v):
            return [
                float(x) if np.isfinite(x) else ("inf" if x > 0 else "-inf")
                for x in np.asarray(v, dtype=np.float64).ravel()
            ]

        d = {
            "c": _vec(self.c),
            "rlb": _vec(self.rlb),
            "rub": _vec(self.rub),
            "lb": _vec(self.lb),
            "ub": _vec(self.ub),
            "c0": float(self.c0),
            "name": self.name,
            "maximize": bool(self.maximize),
            "shape": [int(self.m), int(self.n)],
        }
        if _is_sparse(self.A):
            coo = self.A.tocoo()
            d["A_coo"] = {
                "row": [int(i) for i in coo.row],
                "col": [int(j) for j in coo.col],
                "val": [float(v) for v in coo.data],
            }
        else:
            d["A"] = [[float(v) for v in row] for row in np.asarray(self.A)]
        if self.block_structure:
            # Hints carry ints (block sizes), strings ("kind") and index
            # arrays (detection's row_block/col_block) — all must survive
            # the journal round-trip, not just the int fields.
            def _hint_val(v):
                if isinstance(v, str):
                    return v
                if isinstance(v, np.ndarray):
                    return [int(x) for x in v.ravel()]
                if isinstance(v, (list, tuple)):
                    return [int(x) for x in v]
                return int(v)

            d["block_structure"] = {
                k: _hint_val(v) for k, v in self.block_structure.items()
            }
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LPProblem":
        """Inverse of :meth:`to_dict`."""

        def _vec(v):
            # float("inf")/float("-inf") parse the to_dict sentinels.
            return np.array([float(x) for x in v], dtype=np.float64)

        m, n = (int(v) for v in d["shape"])
        if "A_coo" in d:
            coo = d["A_coo"]
            A: Matrix = sp.csr_matrix(
                (coo["val"], (coo["row"], coo["col"])), shape=(m, n)
            )
        else:
            A = np.asarray(d["A"], dtype=np.float64).reshape(m, n)
        hint = d.get("block_structure")
        if hint is not None:
            # Index arrays were listified by to_dict; the block backends
            # consume them as numpy arrays.
            hint = {
                k: (
                    np.asarray(v, dtype=np.int64)
                    if isinstance(v, list)
                    else v
                )
                for k, v in hint.items()
            }
        return cls(
            c=_vec(d["c"]),
            A=A,
            rlb=_vec(d["rlb"]),
            rub=_vec(d["rub"]),
            lb=_vec(d["lb"]),
            ub=_vec(d["ub"]),
            c0=float(d.get("c0", 0.0)),
            name=str(d.get("name", "LP")),
            maximize=bool(d.get("maximize", False)),
            block_structure=hint,
        )

    def row_activity(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.A @ x).ravel()

    def max_violation(self, x: np.ndarray) -> float:
        """Worst constraint/bound violation of ``x`` (0 if feasible)."""
        ax = self.row_activity(x)
        v = 0.0
        v = max(v, float(np.max(self.rlb - ax, initial=0.0)))
        v = max(v, float(np.max(ax - self.rub, initial=0.0)))
        v = max(v, float(np.max(self.lb - x, initial=0.0)))
        v = max(v, float(np.max(x - self.ub, initial=0.0)))
        return v


# Column transform codes recorded by to_interior_form for solution recovery.
_SHIFT = 0  # x_orig = x_tilde + lb
_NEGSHIFT = 1  # x_orig = -(x_tilde + (-ub))  [upper bound only]
_FREE = 2  # x_orig = x_plus - x_minus (two tilde columns)
_SLACK = 3  # synthetic slack column (no original counterpart)


@dataclasses.dataclass
class InteriorForm:
    """Canonical IPM form: ``min cᵀx  s.t.  Ax = b, 0 ≤ x, x_j ≤ u_j (u_j may be +inf)``.

    ``u`` is +inf where the variable is only bounded below. ``has_ub`` is the
    boolean mask of finite upper bounds (precomputed for the IPM's boundary
    handling). Recovery metadata maps tilde-columns back to original columns.
    """

    c: np.ndarray  # (nt,)
    A: Matrix  # (m, nt)
    b: np.ndarray  # (m,)
    u: np.ndarray  # (nt,) finite or +inf upper bounds (lower bounds are 0)
    c0: float  # objective constant (includes contributions of shifts)
    # recovery metadata
    orig_n: int
    col_kind: np.ndarray  # (nt,) one of _SHIFT/_NEGSHIFT/_FREE/_SLACK
    col_orig: np.ndarray  # (nt,) original column index (-1 for slacks)
    col_shift: np.ndarray  # (nt,) additive shift applied before sign flip
    col_sign: np.ndarray  # (nt,) +1 or -1
    name: str = "LP"
    block_structure: Optional[dict] = None  # propagated LPProblem hint
    # Baseline contribution per original column: nonzero only for fixed
    # (lb == ub) columns, which are substituted out during conversion — a
    # zero-width interior variable (u = 0) has no interior point and
    # breaks the IPM's 1/x arithmetic.
    x_base: Optional[np.ndarray] = None

    @property
    def m(self) -> int:
        return self.A.shape[0]

    @property
    def n(self) -> int:
        return self.A.shape[1]

    @property
    def has_ub(self) -> np.ndarray:
        return np.isfinite(self.u)

    def recover(self, x_tilde: np.ndarray) -> np.ndarray:
        """Map an interior-form solution back to the original variable space."""
        x = (
            np.zeros(self.orig_n, dtype=np.float64)
            if self.x_base is None
            else np.asarray(self.x_base, dtype=np.float64).copy()
        )
        contrib = self.col_sign * (np.asarray(x_tilde, dtype=np.float64) + self.col_shift)
        mask = self.col_orig >= 0
        np.add.at(x, self.col_orig[mask], contrib[mask])
        return x

    def objective(self, x_tilde: np.ndarray) -> float:
        return float(self.c @ x_tilde) + self.c0


def to_interior_form(p: LPProblem) -> InteriorForm:
    """Convert a general-form :class:`LPProblem` to :class:`InteriorForm`.

    Transformations, in order:

    1. Every non-equality row ``rlb ≤ aᵀx ≤ rub`` gains a slack column:
       ``aᵀx - s = 0`` with ``rlb ≤ s ≤ rub`` — all rows become equalities
       with rhs 0, and row-bound information moves onto the slack's bounds.
    2. Columns (including slacks) are normalized to ``0 ≤ x̃ ≤ ũ``:
       finite-lb columns are shifted (``x = x̃ + lb``); upper-bound-only
       columns are negated then shifted (``x = -(x̃ - ub)``); free columns
       are split (``x = x̃⁺ - x̃⁻``). The rhs absorbs the shifts.

    Works for dense ndarray and scipy-sparse ``A``; sparse stays sparse (CSC
    during column surgery, returned as CSR).
    """
    m, n = p.shape
    sparse = _is_sparse(p.A)

    # Fixed columns (lb == ub) are substituted out up front: a zero-width
    # variable has no interior point (u = 0 ⇒ x̃ = 0 on the boundary) and
    # wrecks the IPM's 1/x arithmetic. The substitution moves a·v into the
    # row bounds and c·v into the objective constant; recovery restores the
    # value via ``x_base``.
    fixed = np.isfinite(p.lb) & (p.ub <= p.lb)  # validated lb <= ub
    if fixed.any():
        keep = np.flatnonzero(~fixed)
        fidx = np.flatnonzero(fixed)
        v = p.lb[fixed]
        Ac = p.A.tocsc() if sparse else p.A
        shift_rows = np.asarray(Ac[:, fidx] @ v).ravel()
        q = LPProblem(
            c=p.c[keep],
            A=Ac[:, keep],
            rlb=np.where(np.isfinite(p.rlb), p.rlb - shift_rows, p.rlb),
            rub=np.where(np.isfinite(p.rub), p.rub - shift_rows, p.rub),
            lb=p.lb[keep],
            ub=p.ub[keep],
            c0=p.c0 + float(p.c[fidx] @ v),
            name=p.name,
            maximize=p.maximize,
            block_structure=p.block_structure,
        )
        inf = to_interior_form(q)
        x_base = np.zeros(n)
        x_base[fidx] = v
        # Remap reduced column indices back to the original numbering.
        col_orig = inf.col_orig.copy()
        live = col_orig >= 0
        col_orig[live] = keep[col_orig[live]]
        return dataclasses.replace(
            inf, orig_n=n, col_orig=col_orig, x_base=x_base
        )

    is_eq = (p.rlb == p.rub) & np.isfinite(p.rlb)
    ineq_rows = np.flatnonzero(~is_eq)
    n_slack = len(ineq_rows)

    # --- step 1: append slack columns; rows become Ax - s = rhs_eq ---------
    if sparse:
        A = sp.csc_matrix(p.A, dtype=np.float64)
        if n_slack:
            S = sp.csc_matrix(
                (-np.ones(n_slack), (ineq_rows, np.arange(n_slack))),
                shape=(m, n_slack),
            )
            A = sp.hstack([A, S], format="csc")
    else:
        A = np.asarray(p.A, dtype=np.float64)
        if n_slack:
            S = np.zeros((m, n_slack))
            S[ineq_rows, np.arange(n_slack)] = -1.0
            A = np.hstack([A, S])

    b = np.where(is_eq, p.rlb, 0.0).astype(np.float64)
    c = np.concatenate([p.c, np.zeros(n_slack)])
    lb = np.concatenate([p.lb, p.rlb[ineq_rows]])
    ub = np.concatenate([p.ub, p.rub[ineq_rows]])
    col_orig = np.concatenate(
        [np.arange(n), np.full(n_slack, -1, dtype=np.int64)]
    ).astype(np.int64)
    is_slack = col_orig < 0

    # --- step 2: normalize columns to 0 ≤ x̃ ≤ ũ ---------------------------
    lb_f = np.isfinite(lb)
    ub_f = np.isfinite(ub)
    free = ~lb_f & ~ub_f
    negate = ~lb_f & ub_f  # upper bound only → flip sign

    sign = np.where(negate, -1.0, 1.0)
    # After sign flip the effective bounds are [-ub, -lb] for negated cols.
    lo = np.where(negate, -ub, lb)
    hi = np.where(negate, -lb, ub)
    shift = np.where(np.isfinite(lo), lo, 0.0)  # free cols have shift 0

    n_free = int(np.count_nonzero(free))
    free_idx = np.flatnonzero(free)

    # Apply sign to A columns, then fold the shift into b: A(x̃+shift)=b_eq
    # → A x̃ = b_eq - A·shift  (using the signed A).
    if sparse:
        D = sp.diags(sign)
        A = (A @ D).tocsc()
        b = b - A @ shift
        if n_free:
            A_neg = -A[:, free_idx]
            A = sp.hstack([A, A_neg], format="csr")
        else:
            A = A.tocsr()
    else:
        A = A * sign[None, :]
        b = b - A @ shift
        if n_free:
            A = np.hstack([A, -A[:, free_idx]])

    c_signed = c * sign
    c0 = p.c0 + float(c_signed @ shift)
    u_t = hi - shift  # 0-based upper bounds; inf stays inf
    u_t = np.where(np.isfinite(hi), u_t, _INF)

    if n_free:
        c_t = np.concatenate([c_signed, -c_signed[free_idx]])
        u_t = np.concatenate([u_t, np.full(n_free, _INF)])
        col_orig_t = np.concatenate([col_orig, col_orig[free_idx]])
        shift_t = np.concatenate([shift, np.zeros(n_free)])
        sign_t = np.concatenate([sign, -np.ones(n_free)])
        kind = np.where(is_slack, _SLACK, np.where(free, _FREE, np.where(negate, _NEGSHIFT, _SHIFT))).astype(np.int8)
        kind_t = np.concatenate([kind, np.full(n_free, _FREE, dtype=np.int8)])
    else:
        c_t = c_signed
        col_orig_t = col_orig
        shift_t = shift
        sign_t = sign
        kind_t = np.where(is_slack, _SLACK, np.where(negate, _NEGSHIFT, _SHIFT)).astype(np.int8)

    # Slack columns never contribute to recovery.
    col_orig_t = np.where(kind_t == _SLACK, -1, col_orig_t)

    return InteriorForm(
        c=c_t,
        A=A,
        b=b,
        u=u_t,
        c0=c0,
        orig_n=n,
        col_kind=kind_t,
        col_orig=col_orig_t.astype(np.int64),
        col_shift=shift_t,
        col_sign=sign_t,
        name=p.name,
        block_structure=p.block_structure,
    )
