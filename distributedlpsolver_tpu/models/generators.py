"""Synthetic LP generators for the benchmark suite and tests.

Covers the shapes named in BASELINE.json:7-11: random dense LPs
(m=10k, n=50k full-Cholesky config), batched small LPs (1024 × (128, 512)),
and pds-like block-angular problems for the distributed Schur-complement
path. All generators construct problems that are feasible and bounded *by
construction* (primal point and dual certificate built first, data derived
from them), so tests can assert convergence unconditionally.

NOTE: the true Netlib/Mittelmann files (afiro, pds-*, neos3, stormG2_1000)
cannot be downloaded in this zero-egress environment; `bench.py` uses these
generators at the published shapes and the MPS reader accepts the real files
whenever they are dropped into ``data/`` (see BASELINE.md).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.models.problem import LPProblem

_INF = np.inf


def random_dense_lp(m: int, n: int, seed: int = 0, sigma: float = 1.0) -> LPProblem:
    """Random dense standard-form LP ``min cᵀx, Ax=b, x≥0`` (feasible+bounded).

    Construction: draw A; draw an interior primal point ``x0>0`` and set
    ``b = A·x0``; draw dual ``y0`` and slack ``s0>0`` and set
    ``c = Aᵀy0 + s0``. Then x0 is strictly feasible and (y0, s0) is a
    strictly feasible dual point, so an optimum exists (strong duality).
    """
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, n)) * sigma
    x0 = rng.uniform(0.5, 2.0, size=n)
    b = A @ x0
    y0 = rng.standard_normal(m)
    s0 = rng.uniform(0.5, 2.0, size=n)
    c = A.T @ y0 + s0
    return LPProblem(
        c=c, A=A, rlb=b, rub=b, lb=np.zeros(n), ub=np.full(n, _INF),
        name=f"random_dense_{m}x{n}_s{seed}",
    )


def random_sparse_lp(
    m: int, n: int, density: float = 0.002, seed: int = 0
) -> LPProblem:
    """Random UNSTRUCTURED sparse standard-form LP (neos3-class stand-in,
    BASELINE.json:10): a uniformly random sparsity pattern, so
    ``models/structure.py``'s block-angular detection legitimately finds
    nothing (every row couples random column subsets — no permutation
    exposes an arrow form). Feasible + bounded by the same primal/dual
    witness construction as :func:`random_dense_lp`; every row is given
    ≥2 nonzeros so no singleton row lets presolve trivially shrink it.
    """
    rng = np.random.default_rng(seed)
    nnz = max(int(density * m * n), 2 * m)
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.standard_normal(nnz)
    # guarantee ≥2 entries per row (pattern stays random elsewhere)
    rows = np.concatenate([rows, np.arange(m), np.arange(m)])
    cols = np.concatenate(
        [cols, rng.integers(0, n, m), rng.integers(0, n, m)]
    )
    vals = np.concatenate([vals, rng.standard_normal(2 * m)])
    A = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    A.sum_duplicates()
    x0 = rng.uniform(0.5, 2.0, size=n)
    b = A @ x0
    y0 = rng.standard_normal(m)
    s0 = rng.uniform(0.5, 2.0, size=n)
    c = A.T @ y0 + s0
    return LPProblem(
        c=c, A=A, rlb=b, rub=b, lb=np.zeros(n), ub=np.full(n, _INF),
        name=f"random_sparse_{m}x{n}_d{density}_s{seed}",
    )


def random_general_lp(
    m: int, n: int, seed: int = 0, frac_eq: float = 0.3, frac_box: float = 0.5
) -> LPProblem:
    """Random *general-form* LP with mixed row senses, ranges, and bounds.

    Exercises the full ``to_interior_form`` conversion (slacks, shifts,
    negations, free splits). Feasible by construction; boundedness is forced
    by boxing a fraction of the variables and keeping c ≥ dual-feasible on
    the rest.
    """
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, n))
    x0 = rng.uniform(-1.0, 2.0, size=n)

    lb = np.full(n, -_INF)
    ub = np.full(n, _INF)
    kinds = rng.uniform(size=n)
    for j in range(n):
        if kinds[j] < frac_box:  # boxed
            lb[j] = x0[j] - rng.uniform(0.1, 2.0)
            ub[j] = x0[j] + rng.uniform(0.1, 2.0)
        elif kinds[j] < frac_box + 0.2:  # lower-bounded
            lb[j] = x0[j] - rng.uniform(0.1, 2.0)
        elif kinds[j] < frac_box + 0.4:  # upper-bounded
            ub[j] = x0[j] + rng.uniform(0.1, 2.0)
        # else free

    ax0 = A @ x0
    rlb = np.full(m, -_INF)
    rub = np.full(m, _INF)
    senses = rng.uniform(size=m)
    for i in range(m):
        if senses[i] < frac_eq:  # E
            rlb[i] = rub[i] = ax0[i]
        elif senses[i] < frac_eq + 0.3:  # L
            rub[i] = ax0[i] + rng.uniform(0.1, 1.0)
        elif senses[i] < frac_eq + 0.6:  # G
            rlb[i] = ax0[i] - rng.uniform(0.1, 1.0)
        else:  # ranged
            rlb[i] = ax0[i] - rng.uniform(0.1, 1.0)
            rub[i] = ax0[i] + rng.uniform(0.1, 1.0)

    # Bounded objective: make c a nonnegative combination that cannot dive to
    # -inf along any ray of the (partially unbounded) feasible set. Simplest
    # robust choice: c = Aᵀy + s with s>0 only guaranteed to bound the
    # standard-form recession cone, which here may include negative
    # directions for non-lb variables; so penalize those toward their finite
    # side instead.
    c = rng.standard_normal(n)
    for j in range(n):
        if not np.isfinite(lb[j]) and not np.isfinite(ub[j]):
            c[j] = 0.0  # free var: keep objective flat to guarantee bounded
        elif not np.isfinite(lb[j]):
            c[j] = -abs(c[j])  # only ub finite: push up toward ub
        elif not np.isfinite(ub[j]):
            c[j] = abs(c[j])  # only lb finite: push down toward lb
    return LPProblem(
        c=c, A=A, rlb=rlb, rub=rub, lb=lb, ub=ub,
        name=f"random_general_{m}x{n}_s{seed}",
    )


@dataclasses.dataclass
class BatchedLP:
    """A batch of independent standard-form LPs with identical shapes.

    ``A``: (B, m, n); ``b``: (B, m); ``c``: (B, n). Lower bounds are 0 and
    there are no upper bounds — the vmap'd batched backend consumes this
    directly (BASELINE.json:11: 1024 × (m=128, n=512)).
    """

    c: np.ndarray
    A: np.ndarray
    b: np.ndarray
    name: str = "batched"

    @property
    def batch(self) -> int:
        return self.A.shape[0]

    @property
    def m(self) -> int:
        return self.A.shape[1]

    @property
    def n(self) -> int:
        return self.A.shape[2]

    def problem(self, k: int) -> LPProblem:
        m, n = self.m, self.n
        return LPProblem(
            c=self.c[k], A=self.A[k], rlb=self.b[k], rub=self.b[k],
            lb=np.zeros(n), ub=np.full(n, _INF), name=f"{self.name}[{k}]",
        )


def random_batched_lp(batch: int, m: int, n: int, seed: int = 0) -> BatchedLP:
    """Batch of feasible+bounded standard-form LPs (same construction as
    :func:`random_dense_lp`, vectorized over a leading batch axis)."""
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((batch, m, n))
    x0 = rng.uniform(0.5, 2.0, size=(batch, n))
    b = np.einsum("bmn,bn->bm", A, x0)
    y0 = rng.standard_normal((batch, m))
    s0 = rng.uniform(0.5, 2.0, size=(batch, n))
    c = np.einsum("bmn,bm->bn", A, y0) + s0
    return BatchedLP(c=c, A=A, b=b, name=f"batched_{batch}x{m}x{n}_s{seed}")


def random_request_stream(
    n_requests: int,
    shapes=((8, 24), (12, 32)),
    seed: int = 0,
):
    """Deterministic stream of standard-form LP requests at randomly drawn
    shapes — the serve/ layer's test and load-probe workload. Each request
    is a feasible+bounded :func:`random_dense_lp` instance (standard form:
    all-equality rows, x ≥ 0), so the service routes it to the bucketed
    fast path and every request has an OPTIMAL reference solve."""
    rng = np.random.default_rng(seed)
    for k in range(n_requests):
        m, n = shapes[int(rng.integers(len(shapes)))]
        yield random_dense_lp(m, n, seed=int(rng.integers(2**31 - 1)))


def correlated_request_stream(
    n_requests: int,
    shapes=((8, 24), (12, 32)),
    n_models: int = 4,
    jitter: float = 0.01,
    cost_jitter: Optional[float] = None,
    seed: int = 0,
    offset: int = 0,
):
    """Correlated serve traffic: a few base MODELS re-solved with
    perturbed b/c — the workload the warm-start & amortization layer
    exists for (near-duplicate requests, parameterized streams; same A,
    new b/c, so same-model requests share one structural fingerprint).

    Each of the ``n_models`` base models fixes (A, x0, y0, s0) on a
    shape drawn from ``shapes``; each request picks a model uniformly
    and re-derives ``b = A·(x0·(1+jitter·g))`` and
    ``c = Aᵀ·y0 + s0·(1+cost_jitter·g)`` from jittered witnesses —
    every instance stays feasible+bounded by construction (the
    :func:`random_dense_lp` argument), and the perturbation never
    touches A or the bounds pattern. Fully seeded: the same seed yields
    the identical stream, models and jitters included; ``offset`` skips
    the first draws of that stream, so a follow-on wave continues the
    SAME models with fresh perturbations (the warm-vs-cold probe's
    steady-state leg).
    """
    if cost_jitter is None:
        cost_jitter = jitter
    models = []
    for i in range(n_models):
        m, n = shapes[i % len(shapes)]
        mr = np.random.default_rng((seed, 7919, i))
        A = mr.standard_normal((m, n))
        x0 = mr.uniform(0.5, 2.0, size=n)
        y0 = mr.standard_normal(m)
        s0 = mr.uniform(0.5, 2.0, size=n)
        models.append((i, A, x0, y0, s0))
    rng = np.random.default_rng((seed, 104729))
    for k in range(offset + n_requests):
        i, A, x0, y0, s0 = models[int(rng.integers(n_models))]
        m, n = A.shape
        xk = x0 * (1.0 + jitter * rng.standard_normal(n))
        sk = np.maximum(s0 * (1.0 + cost_jitter * rng.standard_normal(n)), 0.05)
        if k < offset:
            continue
        b = A @ xk
        c = A.T @ y0 + sk
        yield LPProblem(
            c=c, A=A, rlb=b, rub=b, lb=np.zeros(n), ub=np.full(n, _INF),
            name=f"corr_m{i}_{m}x{n}_r{k}",
        )


def storm_sparse_lp(
    num_scenarios: int,
    block_m: int = 64,
    block_n: int = 96,
    first_stage_n: int = 64,
    seed: int = 0,
    t_nnz_per_row: int = 4,
    w_nnz_per_row: int = 6,
) -> LPProblem:
    """Storm-class (stormG2-like) two-stage stochastic LP in BORDERED
    (dual block-angular) form — the huge-sparse tier's headline profile.

    Columns are ``[first-stage x₀ (n1) | scenario-local x_b (K·nb)]``;
    rows are K scenario blocks of ``block_m`` equality rows each:

    .. code-block:: text

        T_b·x₀ + W_b·x_b = b_b      (scenario b = 1..K)
        x ≥ 0

    so scenario rows couple ONLY through the n1 first-stage columns —
    exactly the pattern the sparse-iterative backend's bordered Woodbury
    preconditioner inverts without ever forming ADAᵀ. T_b and W_b are
    random sparse with fixed nonzeros per row (every row keeps ≥1
    recourse entry, so no row is first-stage-only).

    Feasible + bounded by the same witness trick as
    :func:`random_dense_lp` / :func:`random_request_stream`'s instances:
    draw x₀, x_b > 0 and set b from them; draw (y₀, s₀ > 0) and set
    ``c = Aᵀy₀ + s₀``. Fully seeded — the same arguments reproduce the
    identical instance, pattern and values.
    """
    rng = np.random.default_rng(seed)
    K, mb, nb, n1 = num_scenarios, block_m, block_n, first_stage_n
    m = K * mb
    n = n1 + K * nb

    rows = []
    cols = []
    vals = []
    for b in range(K):
        r0 = b * mb
        c0 = n1 + b * nb
        # T_b: coupling into the first-stage columns.
        tr = np.repeat(np.arange(r0, r0 + mb), t_nnz_per_row)
        tc = rng.integers(0, n1, size=mb * t_nnz_per_row)
        tv = rng.standard_normal(mb * t_nnz_per_row)
        # W_b: scenario-local recourse block; each row gets a guaranteed
        # diagonal-ish entry (no empty recourse rows) plus random fill.
        wr = np.repeat(np.arange(r0, r0 + mb), w_nnz_per_row)
        wc = c0 + rng.integers(0, nb, size=mb * w_nnz_per_row)
        wv = rng.standard_normal(mb * w_nnz_per_row)
        dr_ = np.arange(r0, r0 + mb)
        dc_ = c0 + (np.arange(mb) % nb)
        dv_ = 1.0 + rng.uniform(0.5, 1.5, size=mb)
        rows += [tr, wr, dr_]
        cols += [tc, wc, dc_]
        vals += [tv, wv, dv_]
    A = sp.coo_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(m, n),
    ).tocsr()
    A.sum_duplicates()

    x0 = rng.uniform(0.5, 2.0, size=n)
    b_vec = np.asarray(A @ x0).ravel()
    y0 = rng.standard_normal(m)
    s0 = rng.uniform(0.5, 2.0, size=n)
    c = np.asarray(A.T @ y0).ravel() + s0
    p = LPProblem(
        c=c, A=A, rlb=b_vec, rub=b_vec, lb=np.zeros(n), ub=np.full(n, _INF),
        name=f"storm_K{K}_{mb}x{nb}_n1{n1}_s{seed}",
    )
    p.block_structure = {
        "kind": "bordered",
        "num_blocks": K,
        "block_m": mb,
        "block_n": nb,
        "first_stage_n": n1,
    }
    return p


def netlib_sparse_lp(
    m: int, n: int, seed: int = 0, mean_col_nnz: float = 5.0
) -> LPProblem:
    """Netlib-like density profile: column nonzero counts drawn from a
    heavy-tailed (geometric) distribution — most columns carry 2–5
    entries, a few are dense-ish, the way real netlib files look —
    rather than the uniform pattern of :func:`random_sparse_lp`.
    Feasible + bounded by the witness construction; fully seeded."""
    rng = np.random.default_rng(seed)
    counts = rng.geometric(1.0 / max(mean_col_nnz - 1.0, 1.0), size=n) + 1
    counts = np.minimum(counts, m)
    rows = np.concatenate(
        [rng.choice(m, size=k, replace=False) for k in counts]
    )
    cols = np.repeat(np.arange(n), counts)
    vals = rng.standard_normal(counts.sum())
    # Every row gets ≥2 entries so presolve can't trivially shrink it.
    rows = np.concatenate([rows, np.arange(m), np.arange(m)])
    cols = np.concatenate([cols, rng.integers(0, n, m), rng.integers(0, n, m)])
    vals = np.concatenate([vals, rng.standard_normal(2 * m)])
    A = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr()
    A.sum_duplicates()
    x0 = rng.uniform(0.5, 2.0, size=n)
    b = np.asarray(A @ x0).ravel()
    y0 = rng.standard_normal(m)
    s0 = rng.uniform(0.5, 2.0, size=n)
    c = np.asarray(A.T @ y0).ravel() + s0
    return LPProblem(
        c=c, A=A, rlb=b, rub=b, lb=np.zeros(n), ub=np.full(n, _INF),
        name=f"netlib_like_{m}x{n}_s{seed}",
    )


def sparse_request_stream(
    n_requests: int,
    shapes=((12, 40), (16, 48)),
    density: float = 0.25,
    seed: int = 0,
    tol: float = 1e-4,
):
    """Deterministic stream of SMALL sparse-profile standard-form
    requests for the serve layer's tolerance-tiered routing: each yields
    ``(problem, tol)`` where the problem's A is sparse in CONTENT but
    stored dense (ndarray) — at bucket shapes the padded batch tensor is
    dense either way, and dense storage keeps it on the bucketed fast
    path (serve.standard_form). Feasible + bounded by the witness trick
    (same construction as :func:`random_request_stream`); fully seeded.
    The default ``tol=1e-4`` is the PDHG tier — the router must send
    these to the first-order engine."""
    rng = np.random.default_rng(seed)
    for k in range(n_requests):
        m, n = shapes[int(rng.integers(len(shapes)))]
        mask = rng.uniform(size=(m, n)) < density
        mask[np.arange(m), rng.integers(0, n, m)] = True  # no empty rows
        A = rng.standard_normal((m, n)) * mask
        x0 = rng.uniform(0.5, 2.0, size=n)
        b = A @ x0
        y0 = rng.standard_normal(m)
        s0 = rng.uniform(0.5, 2.0, size=n)
        c = A.T @ y0 + s0
        yield (
            LPProblem(
                c=c, A=A, rlb=b, rub=b, lb=np.zeros(n),
                ub=np.full(n, _INF),
                name=f"sparse_req_{m}x{n}_r{k}",
            ),
            tol,
        )


def block_angular_lp(
    num_blocks: int,
    block_m: int,
    block_n: int,
    link_m: int,
    seed: int = 0,
    density: float = 0.3,
    sparse: Optional[bool] = None,
) -> LPProblem:
    """pds-like block-angular LP (BASELINE.json:8 structure).

    Structure (primal block-angular, as in multicommodity flow / stochastic
    programs like stormG2):

    .. code-block:: text

        min Σ_k c_kᵀ x_k
        s.t. B_k x_k = b_k           (local block rows, k = 1..K)
             Σ_k L_k x_k ≤ d        (dense-ish linking rows)
             x ≥ 0

    Feasible+bounded by the same primal/dual construction as
    :func:`random_dense_lp`. Returns a single assembled LPProblem whose rows
    are ordered [block 1 rows, ..., block K rows, linking rows]; the
    block-structured backend re-detects the structure from metadata stored in
    ``prob.block_structure``.
    """
    rng = np.random.default_rng(seed)
    K, mb, nb = num_blocks, block_m, block_n
    n = K * nb
    m = K * mb + link_m

    x0 = rng.uniform(0.5, 2.0, size=n)
    blocks = []
    links = []
    b_loc = []
    for k in range(K):
        Bk = rng.standard_normal((mb, nb)) * (rng.uniform(size=(mb, nb)) < density)
        # Guard against empty rows (would make the row trivially infeasible
        # unless rhs is 0; keep the matrix numerically well-posed instead).
        zero_rows = ~Bk.any(axis=1)
        if zero_rows.any():
            Bk[zero_rows, rng.integers(0, nb, size=zero_rows.sum())] = 1.0
        Lk = rng.standard_normal((link_m, nb)) * (rng.uniform(size=(link_m, nb)) < density)
        blocks.append(Bk)
        links.append(Lk)
        b_loc.append(Bk @ x0[k * nb : (k + 1) * nb])

    L_full = np.hstack(links)
    d = L_full @ x0 + rng.uniform(0.1, 1.0, size=link_m)  # strict slack

    use_sparse = sparse if sparse is not None else (m * n > 200_000)
    if use_sparse:
        A = sp.bmat(
            [
                [sp.csr_matrix(blocks[k]) if kk == k else None for kk in range(K)]
                for k in range(K)
            ]
            + [[sp.csr_matrix(links[k]) for k in range(K)]],
            format="csr",
        )
    else:
        A = np.zeros((m, n))
        for k in range(K):
            A[k * mb : (k + 1) * mb, k * nb : (k + 1) * nb] = blocks[k]
        A[K * mb :, :] = L_full

    # Dual certificate for boundedness: c = Aᵀy + s, s > 0.
    y0 = rng.standard_normal(m)
    y0[K * mb :] = -np.abs(y0[K * mb :])  # linking rows are ≤ → dual y ≤ 0
    s0 = rng.uniform(0.5, 2.0, size=n)
    c = np.asarray(A.T @ y0).ravel() + s0

    rlb = np.concatenate([np.concatenate(b_loc), np.full(link_m, -_INF)])
    rub = np.concatenate([np.concatenate(b_loc), d])
    prob = LPProblem(
        c=c, A=A, rlb=rlb, rub=rub, lb=np.zeros(n), ub=np.full(n, _INF),
        name=f"block_angular_K{K}_{mb}x{nb}_link{link_m}_s{seed}",
    )
    prob.block_structure = {
        "num_blocks": K,
        "block_m": mb,
        "block_n": nb,
        "link_m": link_m,
    }
    return prob
