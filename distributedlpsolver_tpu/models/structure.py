"""Automatic block-angular structure detection.

The reference's core distributed path row-partitions block-angular
problems (pds-* multicommodity flow, stormG2 stochastic programs —
BASELINE.json:8) and combines per-block Schur contributions with an
all-reduce (BASELINE.json:5). Generated problems carry an explicit
``block_structure`` hint; real MPS files do not. This module recovers the
structure from the sparsity pattern alone, so hint-less problems still
route to the Schur backend (backends/block_angular.py) instead of the
dense path.

Method (deterministic, O(trials · nnz) with a union-find):

1. Candidate *linking* rows are the densest rows — a block-angular matrix
   in arrow form has linking rows touching many blocks' columns while
   block rows touch only their own. Trials sweep a decreasing nnz
   threshold (each trial marks rows with nnz ≥ threshold as linking).
2. For each trial, union-find over columns joins the columns of every
   non-linking row; the resulting column components are the candidate
   blocks. A trial succeeds when there are ≥ ``min_blocks`` components,
   the linking set stays under ``max_link_frac``·m, and the row padding
   the backend would pay (blocks are padded to the largest) stays under
   ``max_pad_ratio``.
3. Components are bin-packed (largest first into the lightest bin) into
   ``target_blocks`` groups so block row counts are balanced — a union of
   components is still block-angular.

Returns the generalized hint consumed by the block backend:
``{"num_blocks": K, "row_block": (m,) int array}`` with ``-1`` marking
linking rows. Detection never raises on unsuitable inputs — it returns
``None`` and callers fall back to the dense/sparse paths.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.models.problem import LPProblem

# Dense matrices above this entry count are not scanned (detection needs a
# sparse pattern; a big dense LP has no block structure worth finding).
_DENSE_LIMIT = 1 << 24


def detect_block_structure(
    problem: Union[LPProblem, np.ndarray, sp.spmatrix],
    min_blocks: int = 2,
    max_link_frac: float = 0.25,
    max_pad_ratio: float = 1.5,
    target_blocks: Optional[int] = None,
    max_trials: int = 8,
) -> Optional[dict]:
    """Recover a block-angular row partition from the sparsity pattern.

    ``target_blocks`` caps the number of blocks (components are bin-packed
    into that many groups); the default keeps the NATURAL component count
    (capped at 256) — merging distinct blocks squares their share of the
    per-block assembly/Cholesky flops on known-zero cross terms, so the
    partition the sparsity pattern actually has is the cheapest one to
    execute. Returns ``{"num_blocks", "row_block"}`` or ``None`` when no
    acceptable structure exists.
    """
    A = problem.A if isinstance(problem, LPProblem) else problem
    if not sp.issparse(A):
        A = np.asarray(A)
        if A.size > _DENSE_LIMIT:
            return None
        A = sp.csr_matrix(A)
    R = A.tocsr()
    m, n = R.shape
    if m < 2 * min_blocks or n < 2 * min_blocks:
        return None
    nnz_row = np.diff(R.indptr)

    # Threshold sweep: from "only the very densest rows are linking" toward
    # the linking-budget limit. Use nnz quantiles so the sweep adapts to
    # the pattern instead of absolute counts.
    qs = np.unique(
        np.quantile(nnz_row, [1.0, 0.99, 0.97, 0.95, 0.9, 0.85, 0.8, 0.75])
    )[::-1]
    best = None
    trials = 0
    for thr in qs:
        if trials >= max_trials:
            break
        trials += 1
        linking = nnz_row >= max(thr, 1)
        # Degenerate sweep points: all rows linking, or none. The strict
        # linking budget is enforced after refinement below; this loose
        # pre-check just bounds the component work.
        n_link = int(linking.sum())
        if n_link == 0 or n_link > 0.5 * m:
            continue
        # Connected components of the bipartite (non-linking rows, cols)
        # graph — all C-speed. Components holding only columns (border
        # columns untouched by block rows) are irrelevant: components are
        # re-indexed over the rows that appear.
        block_rows = np.flatnonzero(~linking)
        Rsub = R[block_rows]
        G = sp.bmat([[None, Rsub], [Rsub.T, None]], format="csr")
        _, labels = sp.csgraph.connected_components(G, directed=False)
        row_labels = labels[: len(block_rows)]
        # Empty rows form singleton components; park them with the linking
        # set (they contribute nothing to any block's Cholesky).
        nonempty = np.diff(Rsub.indptr) > 0
        uniq, packed = np.unique(row_labels[nonempty], return_inverse=True)
        comp_of_row = np.full(m, -1, dtype=np.int64)
        comp_of_row[block_rows[nonempty]] = packed
        n_comp = len(uniq)
        if n_comp < min_blocks:  # also covers uniq empty (all rows empty)
            continue
        # Refinement: the nnz threshold over-marks dense *block* rows as
        # linking. A marked row whose columns all sit inside ONE component
        # is really a block row — reassign it (true linking rows span
        # several components and stay). Shrinks the dense Schur system.
        col_labels = labels[len(block_rows) :]
        pos = np.searchsorted(uniq, col_labels)
        pos_c = np.minimum(pos, len(uniq) - 1)
        comp_of_col = np.where(uniq[pos_c] == col_labels, pos_c, -1)
        for i in np.flatnonzero(linking):
            cols = R.indices[R.indptr[i] : R.indptr[i + 1]]
            comps = np.unique(comp_of_col[cols])
            if len(comps) == 1 and comps[0] >= 0:
                comp_of_row[i] = comps[0]
        n_link = int((comp_of_row == -1).sum())
        if n_link > max_link_frac * m:
            continue
        # Balance check at the component level: row padding the backend
        # pays is K·max(rows) / Σrows once grouped; grouping can only
        # improve it, so test after grouping below.
        #
        # Default K = the NATURAL component count (capped at 256): the
        # block backend's per-iteration cost is K·(mb²·nb + mb³/3) with
        # mb ≈ m/K, so merging c components into one multiplies their
        # assembly/factor flops by ~c² — on a 20k-row, 256-block
        # stormG2-class instance, packing into 16 super-blocks costs
        # ~250× the flops of the natural partition, all spent on known-
        # zero cross terms. Tiny blocks batch fine (vmap'd Cholesky).
        # IMBALANCED natural partitions (one big component among many
        # small) fail the pad-ratio test at the natural K, so halve K
        # until bin-packing balances the groups — the flop-optimal K
        # that still passes, falling back toward the coarse packing an
        # explicit target would give. An EXPLICIT target_blocks is a
        # single attempt (the caller asked for exactly that K).
        K = min(n_comp, target_blocks or 256)
        while True:
            row_block = _pack_components(comp_of_row, n_comp, K)
            sizes = np.bincount(row_block[row_block >= 0], minlength=K)
            pad_ratio = K * sizes.max() / max(sizes.sum(), 1)
            if sizes.min() > 0 and pad_ratio <= max_pad_ratio:
                break
            if target_blocks is not None or K <= max(min_blocks, 2):
                row_block = None
                break
            K = max(K // 2, max(min_blocks, 2))
        if row_block is None:
            continue
        cand = {"num_blocks": K, "row_block": row_block, "link_rows": n_link,
                "pad_ratio": float(pad_ratio)}
        # Prefer the trial with the fewest linking rows that passes —
        # linking rows are the dense Schur system everyone pays for.
        if best is None or n_link < best["link_rows"]:
            best = cand
    if best is None:
        return None
    return {"num_blocks": int(best["num_blocks"]), "row_block": best["row_block"]}


def detect_two_stage(
    problem: Union[LPProblem, np.ndarray, sp.spmatrix],
    min_scenarios: int = 2,
    max_first_frac: float = 0.25,
    max_pad_ratio: float = 1.5,
    max_trials: int = 8,
) -> Optional[dict]:
    """Recover a TWO-STAGE (bordered / dual block-angular) structure from
    the sparsity pattern: scenario row blocks that couple only through a
    small set of shared first-stage COLUMNS (the transpose of the
    primal block-angular arrow :func:`detect_block_structure` finds —
    there the border is dense linking ROWS).

    Method: candidate first-stage columns are the densest columns (a
    first-stage column carries T-entries from every scenario; a
    recourse column only its own block's). Trials sweep a decreasing
    column-nnz threshold; for each trial the border columns are
    stripped, connected components of the remaining (row, column)
    bipartite graph are the candidate scenario blocks, and rows left
    empty by the strip (they touch only first-stage columns) are the
    first-stage rows. A border column whose rows all sit in ONE
    component is really scenario-local and is reassigned (the exact
    mirror of the linking-row refinement above).

    Returns the generalized ``two_stage`` hint consumed by
    backends/auto routing, the scenario engine's layout resolution,
    and — on first-stage-row-free patterns — the bordered-Woodbury
    preconditioner::

        {"kind": "two_stage", "num_blocks": K,
         "row_block": (m,) int array (-1 = first-stage row),
         "col_block": (n,) int array (-1 = first-stage column),
         "first_stage_n": n0, "first_stage_m": m0,
         "block_m": max rows/block, "block_n": max cols/block}

    Never raises on unsuitable inputs — returns ``None`` and callers
    fall back to the other rungs.
    """
    A = problem.A if isinstance(problem, LPProblem) else problem
    if not sp.issparse(A):
        A = np.asarray(A)
        if A.size > _DENSE_LIMIT:
            return None
        A = sp.csr_matrix(A)
    C = A.tocsc()
    m, n = C.shape
    if m < min_scenarios or n < 2 * min_scenarios:
        return None
    nnz_col = np.diff(C.indptr)

    qs = np.unique(
        np.quantile(nnz_col, [1.0, 0.99, 0.97, 0.95, 0.9, 0.85, 0.8, 0.75])
    )[::-1]
    best = None
    trials = 0
    R = C.tocsr()
    for thr in qs:
        if trials >= max_trials:
            break
        trials += 1
        border = nnz_col >= max(thr, 1)
        n_border = int(border.sum())
        if n_border == 0 or n_border > 0.5 * n:
            continue
        block_cols = np.flatnonzero(~border)
        Csub = C[:, block_cols]  # (m, n_block)
        G = sp.bmat([[None, Csub], [Csub.T, None]], format="csr")
        _, labels = sp.csgraph.connected_components(G, directed=False)
        row_labels = labels[:m]
        # Rows with no non-border entries are first-stage rows (their
        # singleton components are irrelevant).
        nonempty = np.asarray(Csub.getnnz(axis=1)).ravel() > 0
        uniq, packed = np.unique(row_labels[nonempty], return_inverse=True)
        row_block = np.full(m, -1, dtype=np.int64)
        row_block[nonempty] = packed
        K = len(uniq)
        if K < min_scenarios:
            continue
        col_labels = labels[m:]
        pos = np.searchsorted(uniq, col_labels)
        pos_c = np.minimum(pos, max(len(uniq) - 1, 0))
        comp_of_sub = np.where(uniq[pos_c] == col_labels, pos_c, -1)
        col_block = np.full(n, -1, dtype=np.int64)
        col_block[block_cols] = comp_of_sub
        # Refinement: a border column whose rows all sit in one
        # component is scenario-local (an over-marked dense recourse
        # column) — reassign it; true first-stage columns span blocks.
        for j in np.flatnonzero(border):
            rows = C.indices[C.indptr[j] : C.indptr[j + 1]]
            comps = np.unique(row_block[rows])
            comps = comps[comps >= 0]
            if len(comps) == 1:
                col_block[j] = comps[0]
        # Consistency: a first-stage row must touch only first-stage
        # columns. A -1 row whose (reassigned) columns sit in exactly
        # one block is that block's row; one spanning several blocks
        # breaks the arrow — the trial is not two-stage.
        consistent = True
        for i in np.flatnonzero(row_block == -1):
            cols = R.indices[R.indptr[i] : R.indptr[i + 1]]
            comps = np.unique(col_block[cols])
            comps = comps[comps >= 0]
            if len(comps) == 1:
                row_block[i] = comps[0]
            elif len(comps) > 1:
                consistent = False
                break
        if not consistent:
            continue
        # Empty columns constrain nothing and belong to no block; park
        # them with block 0 (a zero column in any W_k is inert) so the
        # first-stage set stays the true border — the bordered-Woodbury
        # preconditioner keys on its leading-contiguous layout.
        col_block[nnz_col == 0] = 0
        n0 = int((col_block == -1).sum())
        if n0 == 0 or n0 > max_first_frac * n:
            continue
        # A first-stage ROW must touch only first-stage columns; a row
        # assigned to block k must touch only first-stage + block-k
        # columns. Components guarantee the latter for non-border
        # columns; verify the refined assignment stayed consistent.
        sizes = np.bincount(row_block[row_block >= 0], minlength=K)
        csizes = np.bincount(col_block[col_block >= 0], minlength=K)
        if sizes.min() == 0 or csizes.min() == 0:
            continue
        pad = K * sizes.max() / max(sizes.sum(), 1)
        cpad = K * csizes.max() / max(csizes.sum(), 1)
        if pad > max_pad_ratio or cpad > max_pad_ratio:
            continue
        cand = {
            "kind": "two_stage",
            "num_blocks": int(K),
            "row_block": row_block,
            "col_block": col_block,
            "first_stage_n": n0,
            "first_stage_m": int((row_block == -1).sum()),
            "block_m": int(sizes.max()),
            "block_n": int(csizes.max()),
            "_n0": n0,
        }
        # Prefer the trial with the smallest first-stage column set —
        # those columns are the dense linking work every solve pays for.
        if best is None or n0 < best["_n0"]:
            best = cand
    if best is None:
        return None
    best.pop("_n0")
    return best


def column_block_ids(
    A_csc: sp.csc_matrix, row_block: np.ndarray, validate: bool = False
) -> np.ndarray:
    """Per-column block id from the CSC pattern: the block of the column's
    non-linking rows (-1 for border columns touched only by linking rows).

    Segment reductions over ``indptr`` — no per-column Python loop. With
    ``validate``, a column whose non-linking rows disagree on the block
    (min != max over the segment) raises — it breaks the arrow structure.
    Shared by the block backend's layout analysis and the tensor-footprint
    estimator, so the two can never diverge.
    """
    n = A_csc.shape[1]
    rb_vals = row_block[A_csc.indices]
    nnz_col = np.diff(A_csc.indptr)
    nz = np.flatnonzero(nnz_col > 0)
    block_of_col = np.full(n, -1, dtype=np.int64)
    if len(nz):
        vmax = np.maximum.reduceat(
            np.where(rb_vals >= 0, rb_vals, -1), A_csc.indptr[nz]
        )
        if validate:
            big = np.iinfo(np.int64).max
            vmin = np.minimum.reduceat(
                np.where(rb_vals >= 0, rb_vals, big), A_csc.indptr[nz]
            )
            spans = (vmax >= 0) & (vmin != vmax)
            if spans.any():
                k = int(np.argmax(spans))
                raise ValueError(
                    f"column {int(nz[k])} spans blocks "
                    f"[{int(vmin[k])}, {int(vmax[k])}] — not block-angular"
                )
        block_of_col[nz] = vmax  # border columns reduce to -1
    return block_of_col


def estimate_block_tensor_entries(A, hint: dict) -> int:
    """Dense entries the block backend's stacked tensors would hold for
    ``hint`` — B_all (K·mb·nb) + L_all (K·link·nb) + A0 (link·n0). Used by
    auto-dispatch to veto detections whose padded tensors wouldn't fit in
    memory (the sparse-direct CPU path is then the better executor)."""
    rb = np.asarray(hint["row_block"], dtype=np.int64)
    K = int(hint["num_blocks"])
    Ac = sp.csc_matrix(A)
    sizes = np.bincount(rb[rb >= 0], minlength=K)
    mb = int(sizes.max()) if K else 0
    link = int((rb == -1).sum())
    colmax = column_block_ids(Ac, rb)
    counts = np.bincount(colmax[colmax >= 0], minlength=K)
    nb = int(counts.max()) if K else 0
    n0 = int((colmax == -1).sum())
    return K * mb * nb + K * link * nb + link * n0


def _pack_components(comp_of_row: np.ndarray, n_comp: int, K: int) -> np.ndarray:
    """Greedy bin-pack components into K balanced blocks by row count."""
    comp_rows = np.bincount(comp_of_row[comp_of_row >= 0], minlength=n_comp)
    order = np.argsort(comp_rows)[::-1]  # largest first
    load = np.zeros(K, dtype=np.int64)
    group_of_comp = np.empty(n_comp, dtype=np.int64)
    for comp in order:
        g = int(np.argmin(load))
        group_of_comp[comp] = g
        load[g] += comp_rows[comp]
    row_block = np.where(comp_of_row >= 0, group_of_comp[comp_of_row], -1)
    return row_block.astype(np.int64)
