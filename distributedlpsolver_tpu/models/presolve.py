"""Structural presolve for general-form LPs (+ exact postsolve).

SURVEY.md §0.1 item 5 lists "presolve / scaling / ordering steps" as a
reference capability to verify; with the reference tree unavailable
(SURVEY.md §0) this module implements the standard reduction set every
production LP solver applies before the IPM sees the problem:

* **empty rows** — feasibility-checked and dropped;
* **singleton rows** — one live nonzero ``a·x_j ∈ [rlb, rub]`` becomes a
  bound on ``x_j`` and the row is dropped (dual recovered at postsolve);
* **fixed columns** (``lb == ub``) — substituted into the rhs and the
  objective constant;
* **empty columns** — set to their cost-optimal bound (detecting primal
  unboundedness when that bound is infinite);
* **redundant rows** — rows whose activity range, implied by the column
  bounds, already lies inside ``[rlb, rub]`` (skipped for large dense
  matrices where the scan would cost more than it saves);
* **infeasibility** — crossing bounds / unsatisfiable rows found during
  any of the above.

Reductions iterate to a fixpoint (a singleton row may fix a column, which
may empty another row, ...). The returned :class:`PresolveInfo` maps a
solution of the reduced problem back to the original space — primal
*and* dual: removed rows get exact multipliers (zero for redundant rows;
the absorbed reduced cost ``s_j / a`` for a singleton row whose derived
bound is binding), and the full reduced-cost vector is re-derived as
``s = c - Aᵀy`` so dual feasibility holds by construction.

Everything here is host-side NumPy/SciPy — presolve is a per-problem
O(nnz) pass, not device work. Counts are maintained *incrementally*
(eliminating a column decrements only the rows it touches) so a no-op
presolve on a large dense matrix costs one scan and no large temporaries.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.ipm.state import Status
from distributedlpsolver_tpu.models.problem import LPProblem

_INF = np.inf

# Above this many dense entries the redundant-row activity scan (which
# needs sign-split full passes over A) is skipped: a large *dense* LP has
# essentially no removable rows and the temporaries are real memory.
_DENSE_SCAN_LIMIT = 1 << 25


@dataclasses.dataclass
class _SingletonRow:
    """Provenance of a bound derived from a singleton row (dual recovery)."""

    row: int
    col: int
    coeff: float
    lo: float  # derived lower bound on x_col (-inf if none)
    hi: float  # derived upper bound on x_col (+inf if none)


@dataclasses.dataclass
class PresolveInfo:
    """Reduction record; maps reduced-space solutions back to the original.

    ``status`` is non-None when presolve itself settled the problem:
    ``OPTIMAL`` (every variable fixed), ``PRIMAL_INFEASIBLE``, or
    ``DUAL_INFEASIBLE`` (primal unbounded — reported only when the
    remaining problem is trivially feasible, otherwise presolve returns
    the reduced problem and lets the IPM decide).
    """

    orig_m: int
    orig_n: int
    row_live: np.ndarray  # (m,) bool — rows kept in the reduced problem
    col_live: np.ndarray  # (n,) bool — columns kept
    xfix: np.ndarray  # (n,) fixed values (NaN where live)
    singletons: List[_SingletonRow]
    lb0: np.ndarray  # original column bounds (binding-side attribution)
    ub0: np.ndarray
    status: Optional[Status] = None
    objective: Optional[float] = None  # set when status == OPTIMAL
    reductions: dict = dataclasses.field(default_factory=dict)

    @property
    def reduced_shape(self) -> Tuple[int, int]:
        return int(self.row_live.sum()), int(self.col_live.sum())

    def postsolve_x(self, x_red: np.ndarray) -> np.ndarray:
        """Reduced-space primal solution → original space."""
        x = self.xfix.copy()
        x[self.col_live] = np.asarray(x_red, dtype=np.float64)
        # Fully-fixed problems may postsolve with an empty x_red.
        return np.nan_to_num(x, nan=0.0) if np.isnan(x).any() else x

    def postsolve_duals(
        self, p: LPProblem, x_full: np.ndarray, y_red: Optional[np.ndarray]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Recover ``(y, s)`` for the original problem (minimized sense).

        ``y`` are row multipliers, ``s = c - Aᵀy`` reduced costs. Dropped
        rows get ``y = 0`` except singleton rows whose derived bound is
        binding at ``x_full`` and strictly tighter than the original
        column bound — those absorb the column's reduced cost
        (``y = s_j / a``), which keeps complementary slackness and strong
        duality exact instead of leaving a phantom bound multiplier.
        """
        y = np.zeros(self.orig_m, dtype=np.float64)
        if y_red is not None and self.row_live.any():
            y[self.row_live] = np.asarray(y_red, dtype=np.float64)
        A = p.A.tocsc() if sp.issparse(p.A) else np.asarray(p.A)

        def scol(j: int) -> float:  # current reduced cost of column j
            return float(p.c[j] - (A[:, j].T @ y))

        # Replay singleton-row eliminations in REVERSE chronological order,
        # recomputing the column's reduced cost against the *current* y each
        # time. A cascade can put an earlier-eliminated column back into a
        # later singleton row (x0 fixed by row 0 turns row 1 = x0+x1 into a
        # singleton on x1); assigning every multiplier from one pre-pass
        # snapshot of s would then double-count and hand back a
        # dual-infeasible certificate. Reverse replay processes row 1's
        # multiplier first, so row 0's attribution sees its effect on x0's
        # reduced cost.
        btol = 1e-7
        for rec in reversed(self.singletons):
            j = rec.col
            sj = scol(j)
            if abs(sj) <= 1e-9 * (1.0 + abs(p.c[j])):
                continue
            if sj > 0:  # binding at a lower bound
                bound, orig = rec.lo, self.lb0[j]
            else:  # binding at an upper bound
                bound, orig = rec.hi, self.ub0[j]
            if not np.isfinite(bound):
                continue
            scale = 1.0 + abs(bound)
            row_supplies_bound = (
                abs(x_full[j] - bound) <= btol * scale
                and (not np.isfinite(orig) or abs(bound - orig) > btol * scale)
            )
            if row_supplies_bound:
                y[rec.row] = sj / rec.coeff
        s = p.c - np.asarray(p.A.T @ y).ravel()
        return y, s


class _Entries:
    """Uniform (rows, vals) / (cols, vals) access over dense or sparse A."""

    def __init__(self, A):
        self.sparse = sp.issparse(A)
        if self.sparse:
            self.Ac = A.tocsc()
            self.Ac.eliminate_zeros()
            self.Ar = self.Ac.tocsr()
        else:
            self.A = np.asarray(A, dtype=np.float64)
        self._split = None

    def sign_split(self):
        """Loop-invariant (pos, neg, pat_p, pat_n) operands of the
        activity-bound scan, built once and reused across presolve rounds
        (A never changes; only bounds and liveness do)."""
        if self._split is None:
            if self.sparse:
                pos = self.Ar.maximum(0)
                neg = self.Ar.minimum(0)
            else:
                pos = np.clip(self.A, 0.0, None)
                neg = self.A - pos
            self._split = (
                pos,
                neg,
                (pos != 0).astype(np.float64),
                (neg != 0).astype(np.float64),
            )
        return self._split

    def row_nnz(self) -> np.ndarray:
        if self.sparse:
            return np.diff(self.Ar.indptr).astype(np.int64)
        return np.count_nonzero(self.A, axis=1).astype(np.int64)

    def col_nnz(self) -> np.ndarray:
        if self.sparse:
            return np.diff(self.Ac.indptr).astype(np.int64)
        return np.count_nonzero(self.A, axis=0).astype(np.int64)

    def col(self, j: int) -> Tuple[np.ndarray, np.ndarray]:
        if self.sparse:
            sl = slice(self.Ac.indptr[j], self.Ac.indptr[j + 1])
            return self.Ac.indices[sl], self.Ac.data[sl]
        col = self.A[:, j]
        rows = np.flatnonzero(col)
        return rows, col[rows]

    def row(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        if self.sparse:
            sl = slice(self.Ar.indptr[i], self.Ar.indptr[i + 1])
            return self.Ar.indices[sl], self.Ar.data[sl]
        row = self.A[i, :]
        cols = np.flatnonzero(row)
        return cols, row[cols]


def _activity_bounds(E: _Entries, lb, ub, col_live):
    """Vectorized per-row (min, max) of ``Σ_j a_ij x_j`` over live columns
    within their bounds. Dead columns contribute 0 (their substituted value
    already moved into the row bounds). Infinite bounds propagate to ±inf
    via sign-pattern matmuls, so no ``0 · inf`` NaNs arise."""
    lbe = np.where(col_live, lb, 0.0)
    ube = np.where(col_live, ub, 0.0)
    linf = (~np.isfinite(lbe)).astype(np.float64)  # -inf lower bounds
    uinf = (~np.isfinite(ube)).astype(np.float64)  # +inf upper bounds
    lbf = np.where(np.isfinite(lbe), lbe, 0.0)
    ubf = np.where(np.isfinite(ube), ube, 0.0)
    pos, neg, pat_p, pat_n = E.sign_split()
    dot = (lambda M, v: np.asarray(M @ v).ravel()) if E.sparse else (lambda M, v: M @ v)
    minact = dot(pos, lbf) + dot(neg, ubf)
    maxact = dot(pos, ubf) + dot(neg, lbf)
    minact = np.where((dot(pat_p, linf) + dot(pat_n, uinf)) > 0, -_INF, minact)
    maxact = np.where((dot(pat_p, uinf) + dot(pat_n, linf)) > 0, _INF, maxact)
    return minact, maxact


def presolve(
    p: LPProblem,
    max_rounds: int = 10,
    feas_tol: float = 1e-9,
    redundant_rows: bool = True,
) -> Tuple[LPProblem, PresolveInfo]:
    """Apply structural reductions; returns ``(reduced, info)``.

    When ``info.status`` is non-None the problem was settled during
    presolve and ``reduced`` should not be solved (it may be degenerate).
    The reduced problem drops any ``block_structure`` hint — row/column
    indices no longer align with it.
    """
    m, n = p.shape
    E = _Entries(p.A)
    rlb = p.rlb.astype(np.float64).copy()
    rub = p.rub.astype(np.float64).copy()
    lb = p.lb.astype(np.float64).copy()
    ub = p.ub.astype(np.float64).copy()
    c = p.c
    c0 = float(p.c0)

    row_live = np.ones(m, dtype=bool)
    col_live = np.ones(n, dtype=bool)
    xfix = np.full(n, np.nan)
    singletons: List[_SingletonRow] = []
    red = {
        "empty_rows": 0, "singleton_rows": 0, "fixed_cols": 0,
        "empty_cols": 0, "redundant_rows": 0, "rounds": 0,
    }
    info = PresolveInfo(
        orig_m=m, orig_n=n, row_live=row_live, col_live=col_live,
        xfix=xfix, singletons=singletons, lb0=p.lb.copy(), ub0=p.ub.copy(),
        reductions=red,
    )

    row_cnt = E.row_nnz()
    col_cnt = E.col_nnz()
    unbounded_cols: set = set()  # empty cols whose optimal bound is infinite

    def tol_of(*vals) -> float:
        fin = [abs(v) for v in vals if np.isfinite(v)]
        return feas_tol * (1.0 + max(fin, default=0.0))

    def infeasible() -> Tuple[LPProblem, PresolveInfo]:
        info.status = Status.PRIMAL_INFEASIBLE
        return _build_reduced(p, info, rlb, rub, lb, ub, c0), info

    def kill_row(i: int) -> None:
        row_live[i] = False
        cols, _ = E.row(i)
        col_cnt[cols] -= 1

    def fix_col(j: int, v: float) -> None:
        nonlocal c0
        xfix[j] = v
        col_live[j] = False
        c0 += float(c[j]) * v
        rows, vals = E.col(j)
        live = row_live[rows]
        rows, vals = rows[live], vals[live]
        rlb[rows] = np.where(np.isfinite(rlb[rows]), rlb[rows] - vals * v, rlb[rows])
        rub[rows] = np.where(np.isfinite(rub[rows]), rub[rows] - vals * v, rub[rows])
        row_cnt[rows] -= 1

    for rnd in range(max_rounds):
        changed = False
        red["rounds"] = rnd + 1

        # --- rows: empty + singleton -----------------------------------
        for i in np.flatnonzero(row_live & (row_cnt <= 1)):
            if row_cnt[i] == 0:
                if rlb[i] > tol_of(rlb[i]) or rub[i] < -tol_of(rub[i]):
                    return infeasible()
                kill_row(i)
                red["empty_rows"] += 1
                changed = True
                continue
            cols, vals = E.row(i)
            live = col_live[cols]
            cols, vals = cols[live], vals[live]
            if len(cols) != 1:  # stale count (already-eliminated col)
                continue
            j, a = int(cols[0]), float(vals[0])
            lo_b, hi_b = rlb[i] / a, rub[i] / a
            if a < 0:
                lo_b, hi_b = hi_b, lo_b
            lo_b = lo_b if np.isfinite(lo_b) else -_INF
            hi_b = hi_b if np.isfinite(hi_b) else _INF
            singletons.append(_SingletonRow(i, j, a, lo_b, hi_b))
            lb[j] = max(lb[j], lo_b)
            ub[j] = min(ub[j], hi_b)
            kill_row(i)
            red["singleton_rows"] += 1
            changed = True

        # --- bound sanity ----------------------------------------------
        live_idx = np.flatnonzero(col_live)
        bad = lb[live_idx] > ub[live_idx] + feas_tol * (
            1.0 + np.abs(np.where(np.isfinite(ub[live_idx]), ub[live_idx], 0.0))
        )
        if bad.any():
            return infeasible()

        # --- columns: fixed + empty ------------------------------------
        for j in live_idx:
            if col_cnt[j] == 0:
                if j in unbounded_cols:
                    continue
                # Cost decides the optimal value; an infinite optimal bound
                # means the problem is unbounded *if* the rest is feasible —
                # leave the column live so the IPM settles that question.
                # The costless branch requires c_j == 0 EXACTLY: a
                # tiny-but-real cost with wide bounds contributes up to
                # |c_j|*(ub-lb) objective error if fixed at an arbitrary
                # feasible value instead of its cost-optimal bound.
                if c[j] > 0.0:
                    v = lb[j]
                elif c[j] < 0.0:
                    v = ub[j]
                else:  # costless: any feasible value (finite by lb<=ub)
                    v = min(max(0.0, lb[j]), ub[j])
                if np.isfinite(v):
                    fix_col(j, float(v))
                    red["empty_cols"] += 1
                    changed = True
                else:
                    unbounded_cols.add(int(j))
            elif ub[j] - lb[j] <= 1e-14 * (1.0 + abs(lb[j])) and np.isfinite(lb[j]):
                fix_col(j, 0.5 * (lb[j] + ub[j]))
                red["fixed_cols"] += 1
                changed = True

        # --- redundant / infeasible rows by activity bounds ------------
        scan_ok = E.sparse or (m * n <= _DENSE_SCAN_LIMIT)
        if redundant_rows and scan_ok and row_live.any():
            minact, maxact = _activity_bounds(E, lb, ub, col_live)
            t = feas_tol * (
                1.0
                + np.abs(np.where(np.isfinite(rlb), rlb, 0.0))
                + np.abs(np.where(np.isfinite(rub), rub, 0.0))
            )
            live_rows = np.flatnonzero(row_live & (row_cnt > 1))
            if ((minact[live_rows] > rub[live_rows] + t[live_rows])
                    | (maxact[live_rows] < rlb[live_rows] - t[live_rows])).any():
                return infeasible()
            for i in live_rows[
                (minact[live_rows] >= rlb[live_rows] - t[live_rows])
                & (maxact[live_rows] <= rub[live_rows] + t[live_rows])
            ]:
                kill_row(int(i))
                red["redundant_rows"] += 1
                changed = True

        if not changed:
            break

    reduced = _build_reduced(p, info, rlb, rub, lb, ub, c0)
    if not col_live.any():
        # Fully solved by presolve; verify any remaining rows.
        x = info.postsolve_x(np.empty(0))
        if p.max_violation(x) > 1e-6:
            info.status = Status.PRIMAL_INFEASIBLE
        else:
            info.status = Status.OPTIMAL
            info.objective = float(p.c @ x) + float(p.c0)
    elif unbounded_cols and not row_live.any():
        # Every constraint row is gone, so the problem is trivially
        # feasible — an unbounded column settles it as primal-unbounded.
        info.status = Status.DUAL_INFEASIBLE
    return reduced, info


def _build_reduced(p, info, rlb, rub, lb, ub, c0) -> LPProblem:
    rl, cl = info.row_live, info.col_live
    ridx, cidx = np.flatnonzero(rl), np.flatnonzero(cl)
    if sp.issparse(p.A):
        A = p.A.tocsr()[ridx][:, cidx]
    else:
        A = np.asarray(p.A, dtype=np.float64)[np.ix_(ridx, cidx)]
    remap = -np.ones(info.orig_n, dtype=np.int64)
    remap[cidx] = np.arange(len(cidx))
    # Tolerated tiny crossings (within feas_tol) must not trip the
    # constructor's strict lb<=ub / rlb<=rub validation.
    return LPProblem(
        c=p.c[cidx],
        A=A,
        rlb=np.minimum(rlb, rub)[ridx],
        rub=rub[ridx],
        lb=np.minimum(lb, ub)[cidx],
        ub=ub[cidx],
        c0=c0,
        name=p.name,
        row_names=[p.row_names[i] for i in ridx] if p.row_names else None,
        col_names=[p.col_names[j] for j in cidx] if p.col_names else None,
        integer_cols=[int(remap[j]) for j in p.integer_cols if remap[j] >= 0],
        maximize=p.maximize,
        block_structure=None,  # indices no longer align with any hint
    )
