"""Ruiz equilibration of the interior form (presolve scaling).

Real Netlib/Mittelmann files mix coefficient magnitudes across many
orders (SURVEY.md §0.1 item 5 lists presolve/scaling as a reference
capability to verify); iterative ∞-norm equilibration (Ruiz 2001) brings
every row and column of A to ~unit max magnitude, which directly tightens
the conditioning of A·diag(d)·Aᵀ — the quantity that limits how far the
f64 normal-equations path can push the duality gap (see ipm/core.py).

Transformation: ``A' = Dr·A·Dc`` with
``x' = Dc⁻¹x, y' = Dr⁻¹y·(scale), s' = Dc·s`` chosen so the scaled
problem is again a valid interior form; :meth:`Scaling.unscale_state`
maps a solved iterate back. Objective values are invariant
(``c'ᵀx' = cᵀx``).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.ipm.state import IPMState
from distributedlpsolver_tpu.models.problem import InteriorForm


@dataclasses.dataclass
class Scaling:
    dr: np.ndarray  # (m,) row scale factors applied to A's rows
    dc: np.ndarray  # (n,) column scale factors applied to A's columns

    def unscale_state(self, st: IPMState) -> IPMState:
        """Scaled-space iterate → original-space iterate.

        x = Dc·x', w = Dc·w' (primal-like, column space);
        y = Dr·y' (A'ᵀy' = Dc·Aᵀ·Dr·y'); s = s'/Dc, z = z'/Dc.
        """
        return IPMState(
            x=np.asarray(st.x) * self.dc,
            y=np.asarray(st.y) * self.dr,
            s=np.asarray(st.s) / self.dc,
            w=np.asarray(st.w) * self.dc,
            z=np.asarray(st.z) / self.dc,
        )

    def scale_state(self, st: IPMState) -> IPMState:
        """Original-space iterate → scaled space (warm starts)."""
        return IPMState(
            x=np.asarray(st.x) / self.dc,
            y=np.asarray(st.y) / self.dr,
            s=np.asarray(st.s) * self.dc,
            w=np.asarray(st.w) / self.dc,
            z=np.asarray(st.z) * self.dc,
        )


def _row_col_maxabs(A):
    if sp.issparse(A):
        Aa = abs(A)
        row = np.asarray(Aa.max(axis=1).todense()).ravel()
        col = np.asarray(Aa.max(axis=0).todense()).ravel()
    else:
        Aa = np.abs(A)
        row = Aa.max(axis=1, initial=0.0)
        col = Aa.max(axis=0, initial=0.0)
    return row, col


def equilibrate(inf: InteriorForm, iterations: int = 10, tol: float = 1e-2):
    """Ruiz-equilibrate an interior form. Returns (scaled_form, Scaling).

    Empty rows/columns keep scale 1. Stops early once every row/col max is
    within ``tol`` of 1.
    """
    m, n = inf.m, inf.n
    dr = np.ones(m)
    dc = np.ones(n)
    if sp.issparse(inf.A):
        A = inf.A.copy().astype(np.float64)
        for _ in range(iterations):
            row, col = _row_col_maxabs(A)
            if (np.abs(row[row > 0] - 1.0) < tol).all() and (
                np.abs(col[col > 0] - 1.0) < tol
            ).all():
                break
            with np.errstate(divide="ignore"):
                r = np.where(row > 0, 1.0 / np.sqrt(row), 1.0)
                c = np.where(col > 0, 1.0 / np.sqrt(col), 1.0)
            A = sp.diags(r) @ A @ sp.diags(c)
            dr *= r
            dc *= c
    else:
        # Dense path works on ONE |A| buffer, updated in place: at the
        # 10k×50k reference scale a per-iteration `(A*r)*c` allocates two
        # fresh 4 GB arrays per sweep (~270 s total observed); in-place
        # sweeps over the magnitude matrix are ~10× faster, and the scaled
        # A itself is formed once at the end from the accumulated factors.
        absA = np.abs(np.asarray(inf.A, dtype=np.float64))
        for _ in range(iterations):
            row = absA.max(axis=1, initial=0.0)
            col = absA.max(axis=0, initial=0.0)
            if (np.abs(row[row > 0] - 1.0) < tol).all() and (
                np.abs(col[col > 0] - 1.0) < tol
            ).all():
                break
            with np.errstate(divide="ignore"):
                r = np.where(row > 0, 1.0 / np.sqrt(row), 1.0)
                c = np.where(col > 0, 1.0 / np.sqrt(col), 1.0)
            absA *= r[:, None]
            absA *= c
            dr *= r
            dc *= c
        A = absA  # reuse the buffer: refill with signed scaled values
        np.multiply(inf.A, dr[:, None], out=A)
        A *= dc

    scaled = InteriorForm(
        c=inf.c * dc,
        A=A,
        b=inf.b * dr,
        u=np.where(np.isfinite(inf.u), inf.u / dc, np.inf),
        c0=inf.c0,
        orig_n=inf.orig_n,
        col_kind=inf.col_kind,
        col_orig=inf.col_orig,
        col_shift=inf.col_shift,
        col_sign=inf.col_sign,
        name=inf.name,
        block_structure=inf.block_structure,
    )
    return scaled, Scaling(dr=dr, dc=dc)
