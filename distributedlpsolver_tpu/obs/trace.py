"""Span tracer emitting Chrome-trace-format JSON (Perfetto-loadable).

One :class:`Tracer` accumulates events in memory (bounded) and writes a
``{"traceEvents": [...]}`` JSON object at :meth:`close`. Event phases
used (the Trace Event Format's stable subset):

- ``X`` complete spans — one lane per pipeline thread (the thread id is
  the OS thread ident; an ``M`` metadata event names each lane the
  first time it emits).
- ``b``/``e`` async spans keyed by ``(cat, id)`` — the cross-thread
  request track: ``request <id>`` begins on the submit thread, its
  nested ``queue``/``pack``/``solve`` phases begin and end on whichever
  pipeline thread handles them, and the track ends where the result is
  finished. Perfetto renders each (cat, id) pair as one connected track
  regardless of which threads emitted the events.
- ``i`` instant events — supervisor faults, reshards, ladder swaps,
  admission rejections.

Timestamps are microseconds on the ``time.perf_counter`` clock (the
same monotonic clock every JSONL record's ``t_mono`` stamp uses, so a
trace and a JSONL stream from one process line up exactly).

Like the metrics registry, the module default is :data:`NULL_TRACER`,
whose methods are no-ops — instrumentation sites call unconditionally
and the disabled path allocates nothing. The real tracer takes one lock
per event append; it is never on the device path.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Iterator, Optional

# Bound on buffered events: a runaway loop must not grow host memory
# without bound. 1M events ≈ a few hundred MB of JSON — far above any
# probe run; on overflow the tracer drops new events and records that it
# did in the file's metadata.
MAX_EVENTS = 1_000_000


def _now_us() -> float:
    return time.perf_counter() * 1e6


class Tracer:
    """Collects Chrome-trace events; ``close()`` writes the JSON file."""

    enabled = True

    def __init__(self, path: str, process_name: str = "distributedlpsolver"):
        self.path = path
        self._lock = threading.Lock()
        self._events: list = []  # guarded-by: _lock
        self._named_threads: set = set()  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._events.append(
            {
                "ph": "M", "name": "process_name", "pid": 1, "tid": 0,
                "args": {"name": process_name},
            }
        )

    # -- internals -------------------------------------------------------

    def _emit(self, ev: dict) -> None:
        tid = ev.setdefault("tid", threading.get_ident())
        ev.setdefault("pid", 1)
        with self._lock:
            if self._closed:
                return
            if len(self._events) >= MAX_EVENTS:
                self._dropped += 1
                return
            if tid not in self._named_threads:
                self._named_threads.add(tid)
                self._events.append(
                    {
                        "ph": "M", "name": "thread_name", "pid": 1,
                        "tid": tid,
                        "args": {"name": threading.current_thread().name},
                    }
                )
            self._events.append(ev)

    # -- synchronous spans (thread lanes) --------------------------------

    @contextlib.contextmanager
    def span(
        self, name: str, cat: str = "", args: Optional[dict] = None
    ) -> Iterator[None]:
        """``X`` complete span on the calling thread's lane."""
        t0 = _now_us()
        try:
            yield
        finally:
            self._emit(
                {
                    "ph": "X", "name": name, "cat": cat or "span",
                    "ts": t0, "dur": _now_us() - t0,
                    **({"args": args} if args else {}),
                }
            )

    def complete(
        self,
        name: str,
        dur_s: float,
        cat: str = "",
        args: Optional[dict] = None,
        end_us: Optional[float] = None,
    ) -> None:
        """``X`` span for an interval that already happened (the caller
        measured ``dur_s`` itself and is reporting after the fact)."""
        end = _now_us() if end_us is None else end_us
        self._emit(
            {
                "ph": "X", "name": name, "cat": cat or "span",
                "ts": end - dur_s * 1e6, "dur": dur_s * 1e6,
                **({"args": args} if args else {}),
            }
        )

    # -- async request tracks (cross-thread) -----------------------------

    def async_begin(
        self, name: str, track: int, cat: str = "request",
        args: Optional[dict] = None,
    ) -> None:
        self._emit(
            {
                "ph": "b", "name": name, "cat": cat, "id": track,
                "ts": _now_us(), **({"args": args} if args else {}),
            }
        )

    def async_end(
        self, name: str, track: int, cat: str = "request",
        args: Optional[dict] = None,
    ) -> None:
        self._emit(
            {
                "ph": "e", "name": name, "cat": cat, "id": track,
                "ts": _now_us(), **({"args": args} if args else {}),
            }
        )

    # -- instants --------------------------------------------------------

    def instant(self, name: str, args: Optional[dict] = None,
                cat: str = "event") -> None:
        self._emit(
            {
                "ph": "i", "name": name, "cat": cat, "ts": _now_us(),
                "s": "p",  # process-scoped marker line
                **({"args": args} if args else {}),
            }
        )

    # -- lifecycle -------------------------------------------------------

    def event_count(self) -> int:
        with self._lock:
            return len(self._events)

    def close(self) -> Optional[str]:
        """Write the trace JSON; returns the path (idempotent — later
        calls rewrite with whatever accumulated since, so a service can
        flush at shutdown while the CLI flushes again at exit)."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "clock": "perf_counter_us",
                **({"dropped_events": dropped} if dropped else {}),
            },
        }
        with open(self.path, "w") as fh:
            json.dump(payload, fh)
            fh.write("\n")
        return self.path


class _NullTracer:
    """Disabled tracer: same surface, every method a no-op (the span
    context manager is a shared reusable null context)."""

    enabled = False
    path = None

    __slots__ = ()

    def span(self, name, cat="", args=None):
        return _NULL_CONTEXT

    def complete(self, name, dur_s, cat="", args=None, end_us=None):
        pass

    def async_begin(self, name, track, cat="request", args=None):
        pass

    def async_end(self, name, track, cat="request", args=None):
        pass

    def instant(self, name, args=None, cat="event"):
        pass

    def event_count(self) -> int:
        return 0

    def close(self):
        return None


_NULL_CONTEXT = contextlib.nullcontext()
NULL_TRACER = _NullTracer()

from distributedlpsolver_tpu.obs import DefaultSlot  # noqa: E402

_DEFAULT = DefaultSlot(NULL_TRACER)


def get_tracer():
    return _DEFAULT.get()


def set_tracer(tracer) -> object:
    """Install ``tracer`` as the module default (None restores the no-op
    tracer); returns the previous default for scoped restore."""
    return _DEFAULT.set(tracer)
