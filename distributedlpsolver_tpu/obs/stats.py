"""The one percentile/summary implementation.

Before this module, p50/p99 was computed three independent ways —
``serve/records.latency_summary`` (np.percentile via a local wrapper),
``bench.py``'s serve row (np.percentile inline), and the probe scripts
(reading whichever of the two they were near) — which is exactly how two
reports of "p99" end up disagreeing on the same data. Everything routes
here now.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence

import numpy as np


def percentile(values: Sequence[float], q: float) -> float:
    """q-th percentile (linear interpolation, numpy semantics); 0.0 for
    an empty input — every caller treats "no data" as a zero row, and a
    NaN would poison downstream JSON."""
    if not len(values):
        return 0.0
    return float(np.percentile(np.asarray(values, dtype=np.float64), q))


def summarize(
    values: Sequence[float], quantiles: Iterable[int] = (50, 95, 99)
) -> Dict[str, float]:
    """``{count, mean, p50, p95, p99, max}`` over ``values`` (all-zero
    row when empty). The standard per-phase row ``cli report`` prints
    and the serve summary embeds."""
    vals: List[float] = [float(v) for v in values]
    out: Dict[str, float] = {"count": len(vals)}
    if not vals:
        out.update({f"p{q}": 0.0 for q in quantiles})
        out.update(mean=0.0, max=0.0)
        return out
    arr = np.asarray(vals, dtype=np.float64)
    for q in quantiles:
        out[f"p{q}"] = round(float(np.percentile(arr, q)), 3)
    out["mean"] = round(float(arr.mean()), 3)
    out["max"] = round(float(arr.max()), 3)
    return out
