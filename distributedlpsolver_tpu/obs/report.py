"""``cli report``: turn the telemetry we already write into answers.

Ingests any mix of the package's JSONL streams — per-iteration rows
(IterLogger), per-request serve records, per-dispatch batch events,
supervisor fault/resume events — plus JSON metric snapshots, and builds
one merged report: per-phase latency breakdowns (p50/p95/p99),
padding-waste-by-bucket tables, recovery-overhead summaries, and the
iters/sec trajectory (the paper's published metric, now reconstructable
from any crash log).

Backward compatibility is a hard requirement: PR 1–4 files carry no
``schema_version``/``ts``/``t_mono`` stamps, and iteration rows never
carry an ``"event"`` key. The loader classifies records by shape, never
by stamp.

Reconciliation: over a service's own log, ``requests.count`` equals
``SolveService.stats()["requests"]`` and ``dispatches.count`` equals
``stats()["dispatches"]`` exactly — both sides count one record per
finished request and one ``batch`` event per bucket dispatch (solo-path
requests never dispatch a bucket, on either side).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from distributedlpsolver_tpu.obs.stats import summarize

_REQUEST_PHASES = ("queue_ms", "pack_ms", "compile_ms", "solve_ms", "total_ms")


def load_file(path: str) -> Tuple[List[dict], Optional[dict], int]:
    """(jsonl_records, metrics_snapshot, skipped) from one file. A file
    holding a single JSON object (the ``write_snapshot`` output) is a
    snapshot; anything else is treated as newline-delimited records.
    Unparseable lines are SKIPPED AND COUNTED, never fatal — a crash
    log's torn final record (the process died mid-write) is exactly the
    file this loader exists for, and the count surfaces in the report
    so a truncation is a visible warning, not silence."""
    with open(path) as fh:
        text = fh.read()
    stripped = text.strip()
    if stripped.startswith("{"):
        # A whole file that parses as ONE dict (possibly pretty-printed)
        # is a snapshot — unless it looks like a single JSONL record.
        try:
            obj = json.loads(stripped)
            if isinstance(obj, dict) and "event" not in obj and "iter" not in obj:
                return [], obj, 0
        except ValueError:
            pass
    records = []
    skipped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
        else:
            skipped += 1
    return records, None, skipped


def build_report(
    records: Sequence[dict], metrics: Optional[dict] = None
) -> dict:
    """Aggregate classified records into the report dict ``render``
    prints (and ``--json`` emits verbatim)."""
    iter_rows = [r for r in records if "event" not in r and "iter" in r]
    events: Dict[str, List[dict]] = {}
    for r in records:
        if "event" in r:
            events.setdefault(r["event"], []).append(r)

    requests = events.get("request", [])
    batches = events.get("batch", [])
    faults = events.get("fault", [])
    resumes = events.get("resume", [])

    report: dict = {
        "records": len(records),
        "events_by_type": {
            k: len(v) for k, v in sorted(events.items())
        },
        "stamped_records": sum(1 for r in records if "schema_version" in r),
    }

    # -- per-phase request latency ---------------------------------------
    by_status: Dict[str, int] = {}
    for r in requests:
        s = r.get("status", "?")
        by_status[s] = by_status.get(s, 0) + 1
    # Warm-vs-cold split (the amortization layer's headline columns):
    # median iterations-per-request and p50/p99 latency by start kind.
    # Legacy records carry no "warm" field and count as cold.
    by_warm: Dict[str, int] = {}
    warm_iters: List[float] = []
    cold_iters: List[float] = []
    warm_lat: List[float] = []
    cold_lat: List[float] = []
    for r in requests:
        wl = r.get("warm") or "cold"
        by_warm[wl] = by_warm.get(wl, 0) + 1
        (warm_iters if wl == "warm" else cold_iters).append(
            float(r.get("iterations", 0))
        )
        (warm_lat if wl == "warm" else cold_lat).append(
            float(r.get("total_ms", 0.0))
        )
    report["requests"] = {
        "count": len(requests),
        "by_status": by_status,
        "solo_retries": sum(1 for r in requests if r.get("retried_solo")),
        "warm": {
            "by_start": by_warm,
            "iterations_warm": summarize(warm_iters, quantiles=(50, 99)),
            "iterations_cold": summarize(cold_iters, quantiles=(50, 99)),
            "latency_ms_warm": summarize(warm_lat, quantiles=(50, 99)),
            "latency_ms_cold": summarize(cold_lat, quantiles=(50, 99)),
        },
        "phases": {
            ph: summarize([r.get(ph, 0.0) for r in requests])
            for ph in _REQUEST_PHASES
        },
    }

    # -- padding waste by bucket -----------------------------------------
    buckets: Dict[str, dict] = {}
    for r in requests:
        b = r.get("bucket")
        key = "solo" if not b else "x".join(str(int(v)) for v in b)
        row = buckets.setdefault(
            key, {"requests": 0, "dispatches": set(), "waste": [],
                  "total_ms": []}
        )
        row["requests"] += 1
        if r.get("dispatch", -1) >= 0:
            row["dispatches"].add(r["dispatch"])
        row["waste"].append(float(r.get("padding_waste", 0.0)))
        row["total_ms"].append(float(r.get("total_ms", 0.0)))
    for b in events.get("batch", []):
        key = "x".join(str(int(v)) for v in b.get("bucket", [])) or "?"
        row = buckets.setdefault(
            key, {"requests": 0, "dispatches": set(), "waste": [],
                  "total_ms": []}
        )
        row["dispatches"].add(b.get("dispatch", -1))
    report["padding_by_bucket"] = {
        key: {
            "requests": row["requests"],
            "dispatches": len(row["dispatches"]),
            "waste_mean": round(
                sum(row["waste"]) / len(row["waste"]), 4
            ) if row["waste"] else 0.0,
            "waste": summarize(row["waste"]),
            "total_ms": summarize(row["total_ms"]),
        }
        for key, row in sorted(buckets.items())
    }

    # -- dispatches ------------------------------------------------------
    solve_tot = sum(float(b.get("solve_ms") or 0.0) for b in batches)
    overlap_tot = sum(float(b.get("overlap_ms") or 0.0) for b in batches)
    report["dispatches"] = {
        "count": len(batches),
        "attempts": sum(int(b.get("attempts", 1)) for b in batches),
        "live_slots": sum(int(b.get("live", 0)) for b in batches),
        "pack_ms": summarize([float(b.get("pack_ms") or 0.0) for b in batches]),
        "solve_ms": summarize(
            [float(b.get("solve_ms") or 0.0) for b in batches]
        ),
        "overlap_ms": summarize(
            [float(b.get("overlap_ms") or 0.0) for b in batches]
        ),
        # Fraction of device-solve wall that had host pack running under
        # it — the pipeline's realized overlap across the whole stream.
        "overlap_ratio": round(overlap_tot / solve_tot, 4)
        if solve_tot > 0 else 0.0,
    }

    # -- faults & recovery -----------------------------------------------
    by_kind: Dict[str, int] = {}
    by_action: Dict[str, int] = {}
    for f in faults:
        by_kind[f.get("kind", "?")] = by_kind.get(f.get("kind", "?"), 0) + 1
        a = f.get("action") or "?"
        by_action[a] = by_action.get(a, 0) + 1
    # world_reinit events (distributed/launcher: coordinator-level
    # world re-initializations) carry recovery_overhead_s exactly like
    # resume events — the multi-host rung joins the same summary.
    overheads = [
        float(r["recovery_overhead_s"])
        for r in resumes + events.get("world_reinit", [])
        if r.get("recovery_overhead_s") is not None
    ]
    report["faults"] = {
        "count": len(faults),
        "by_kind": by_kind,
        "by_action": by_action,
        "rejects": len(events.get("reject", [])),
        "dispatch_errors": len(events.get("dispatch_error", [])),
        "reshards": len(events.get("reshard", [])),
        "ladder_swaps": len(events.get("ladder_swap", [])),
    }
    report["recovery"] = {
        "resumes": len(resumes),
        "world_reinits": len(events.get("world_reinit", [])),
        "overhead_s": summarize(overheads),
        "overhead_s_total": round(sum(overheads), 6),
    }

    # -- scenario tier (stochastic two-stage requests) -------------------
    scen_rs = [r for r in requests if r.get("n_scenarios")]
    scen_buckets: Dict[str, dict] = {}
    for r in scen_rs:
        key = str(int(r.get("scenario_bucket") or 0))
        row = scen_buckets.setdefault(
            key, {"count": 0, "k_max": 0, "total_ms": [], "schur_ms": [],
                  "link_ms": []}
        )
        row["count"] += 1
        row["k_max"] = max(row["k_max"], int(r.get("n_scenarios", 0)))
        row["total_ms"].append(float(r.get("total_ms", 0.0)))
        row["schur_ms"].append(float(r.get("schur_ms", 0.0)))
        row["link_ms"].append(float(r.get("link_ms", 0.0)))
    report["scenario"] = {
        "solves": len(scen_rs),
        "by_bucket": {
            key: {
                "count": row["count"],
                "k_max": row["k_max"],
                "total_ms": summarize(row["total_ms"], quantiles=(50, 99)),
                "schur_ms": summarize(row["schur_ms"], quantiles=(50,)),
                "link_ms": summarize(row["link_ms"], quantiles=(50,)),
            }
            for key, row in sorted(
                scen_buckets.items(), key=lambda kv: int(kv[0])
            )
        },
    }

    # -- durability (crash-safe serving fabric) --------------------------
    replays = events.get("journal_replay", [])
    drains = events.get("drain", [])
    report["durability"] = {
        "journal_replays": len(replays),
        "replayed": sum(int(r.get("replayed", 0)) for r in replays),
        "reenqueued": sum(int(r.get("reenqueued", 0)) for r in replays),
        "expired": sum(int(r.get("expired", 0)) for r in replays),
        "torn_tails": sum(int(r.get("torn", 0)) for r in replays),
        "drains": sum(1 for d in drains if d.get("phase") == "begin"),
        "registry_writes": len(events.get("registry_write", [])),
    }

    # -- iteration trajectory --------------------------------------------
    t_iters = [float(r.get("t_iter", 0.0)) for r in iter_rows]
    total_t = sum(t_iters)
    traj = []
    if iter_rows:
        # Windowed iters/sec over the row sequence (~10 windows): the
        # trajectory that shows a solve slowing down (endgame, faults)
        # rather than one flat average.
        w = max(1, len(iter_rows) // 10)
        for i in range(0, len(iter_rows), w):
            chunk = t_iters[i:i + w]
            tt = sum(chunk)
            traj.append(
                {
                    "rows": [i + 1, i + len(chunk)],
                    "iters_per_sec": round(len(chunk) / tt, 3)
                    if tt > 0 else None,
                    "rel_gap_last": iter_rows[
                        min(i + w, len(iter_rows)) - 1
                    ].get("rel_gap"),
                }
            )
    report["iterations"] = {
        "count": len(iter_rows),
        "time_s": round(total_t, 6),
        "iters_per_sec": round(len(iter_rows) / total_t, 3)
        if total_t > 0 else None,
        "t_iter_s": summarize(t_iters, quantiles=(50, 95, 99)),
        "trajectory": traj,
    }

    if metrics:
        report["metrics"] = metrics
    return report


def _fmt_phase_table(phases: Dict[str, dict]) -> List[str]:
    lines = [
        f"  {'phase':<12} {'count':>6} {'p50':>10} {'p95':>10} "
        f"{'p99':>10} {'max':>10}"
    ]
    for name, s in phases.items():
        lines.append(
            f"  {name:<12} {s['count']:>6} {s['p50']:>10.3f} "
            f"{s['p95']:>10.3f} {s['p99']:>10.3f} {s['max']:>10.3f}"
        )
    return lines


def render(report: dict) -> str:
    """Human-readable rendering of ``build_report``'s dict."""
    out: List[str] = []
    req = report["requests"]
    out.append(
        f"records: {report['records']} "
        f"({report['stamped_records']} stamped, "
        f"{report['records'] - report['stamped_records']} legacy)"
    )
    if report.get("skipped_lines"):
        # A torn final record is the expected crash artifact — counted
        # loudly, parsed around quietly.
        out.append(
            f"warning: {report['skipped_lines']} unparseable line(s) "
            f"skipped (torn/truncated records)"
        )
    if report["events_by_type"]:
        out.append(
            "events: "
            + ", ".join(
                f"{k}={v}" for k, v in report["events_by_type"].items()
            )
        )

    if req["count"]:
        out.append("")
        out.append(
            f"requests: {req['count']} "
            f"(status: "
            + ", ".join(f"{k}={v}" for k, v in sorted(req["by_status"].items()))
            + (f"; solo retries: {req['solo_retries']}"
               if req["solo_retries"] else "")
            + ")"
        )
        out.append("per-phase latency (ms):")
        out.extend(_fmt_phase_table(req["phases"]))
        wm = req.get("warm")
        if wm and wm["by_start"].get("warm"):
            out.append(
                "warm-vs-cold ("
                + ", ".join(
                    f"{k}={v}" for k, v in sorted(wm["by_start"].items())
                )
                + "):"
            )
            out.append(
                f"  {'start':<12} {'count':>6} {'iters_p50':>10} "
                f"{'lat_p50':>10} {'lat_p99':>10}"
            )
            for kind in ("warm", "cold"):
                it_s = wm[f"iterations_{kind}"]
                lat_s = wm[f"latency_ms_{kind}"]
                out.append(
                    f"  {kind:<12} {it_s['count']:>6} {it_s['p50']:>10.1f} "
                    f"{lat_s['p50']:>10.3f} {lat_s['p99']:>10.3f}"
                )

    pb = report["padding_by_bucket"]
    if pb:
        out.append("")
        out.append("padding waste by bucket:")
        out.append(
            f"  {'bucket':<16} {'requests':>8} {'dispatches':>10} "
            f"{'waste_mean':>10} {'waste_p95':>10} {'total_p50ms':>11}"
        )
        for key, row in pb.items():
            out.append(
                f"  {key:<16} {row['requests']:>8} {row['dispatches']:>10} "
                f"{row['waste_mean']:>10.4f} {row['waste']['p95']:>10.4f} "
                f"{row['total_ms']['p50']:>11.3f}"
            )

    scen = report.get("scenario") or {}
    if scen.get("solves"):
        out.append("")
        out.append(f"scenario tier: {scen['solves']} solves")
        out.append(
            f"  {'k_bucket':<10} {'count':>6} {'k_max':>6} "
            f"{'total_p50':>10} {'total_p99':>10} {'schur_p50':>10} "
            f"{'link_p50':>10}"
        )
        for key, row in scen["by_bucket"].items():
            out.append(
                f"  {key:<10} {row['count']:>6} {row['k_max']:>6} "
                f"{row['total_ms']['p50']:>10.3f} "
                f"{row['total_ms']['p99']:>10.3f} "
                f"{row['schur_ms']['p50']:>10.3f} "
                f"{row['link_ms']['p50']:>10.3f}"
            )

    disp = report["dispatches"]
    if disp["count"]:
        out.append("")
        out.append(
            f"dispatches: {disp['count']} ({disp['attempts']} attempts, "
            f"{disp['live_slots']} live slots); "
            f"solve p50={disp['solve_ms']['p50']:.3f}ms "
            f"pack p50={disp['pack_ms']['p50']:.3f}ms "
            f"overlap ratio={disp['overlap_ratio']:.2%}"
        )

    fl = report["faults"]
    if fl["count"] or fl["rejects"] or fl["reshards"] or fl["ladder_swaps"]:
        out.append("")
        out.append(
            f"faults: {fl['count']}"
            + (" by kind: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fl["by_kind"].items())
            ) if fl["by_kind"] else "")
            + (" | actions: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fl["by_action"].items())
            ) if fl["by_action"] else "")
        )
        extras = [
            f"{name}={fl[name]}"
            for name in ("rejects", "dispatch_errors", "reshards",
                         "ladder_swaps")
            if fl[name]
        ]
        if extras:
            out.append("  " + ", ".join(extras))
    rec = report["recovery"]
    if rec["resumes"]:
        o = rec["overhead_s"]
        out.append(
            f"recovery: {rec['resumes']} resumes, overhead "
            f"p50={o['p50']:.3f}s p99={o['p99']:.3f}s "
            f"total={rec['overhead_s_total']:.3f}s"
        )
    dur = report.get("durability") or {}
    if dur.get("journal_replays") or dur.get("drains") or dur.get(
        "registry_writes"
    ):
        out.append(
            f"durability: {dur['journal_replays']} journal replays "
            f"({dur['reenqueued']} re-enqueued, {dur['expired']} expired "
            f"honest-TIMEOUT, {dur['torn_tails']} torn tails), "
            f"{dur['drains']} drains, "
            f"{dur['registry_writes']} registry writes"
        )

    it = report["iterations"]
    if it["count"]:
        out.append("")
        out.append(
            f"iterations: {it['count']} in {it['time_s']:.3f}s"
            + (f" ({it['iters_per_sec']:.2f} iters/sec)"
               if it["iters_per_sec"] else "")
        )
        if it["trajectory"] and len(it["trajectory"]) > 1:
            out.append("iters/sec trajectory:")
            for w in it["trajectory"]:
                ips = w["iters_per_sec"]
                gap = w["rel_gap_last"]
                out.append(
                    f"  rows {w['rows'][0]:>5}-{w['rows'][1]:<5} "
                    + (f"{ips:>9.2f} it/s" if ips is not None
                       else f"{'—':>9}      ")
                    + (f"  rel_gap={gap:.3e}" if gap is not None else "")
                )

    if "metrics" in report:
        out.append("")
        out.append(f"metrics snapshot: {len(report['metrics'])} instruments")
        for name, val in report["metrics"].items():
            if isinstance(val, dict):
                out.append(
                    f"  {name}: count={val.get('count', 0)} "
                    f"sum={val.get('sum', 0.0):g}"
                )
            else:
                out.append(f"  {name}: {val:g}")
    return "\n".join(out)


def report_from_paths(paths: Sequence[str]) -> dict:
    """Load every path (JSONL streams and/or snapshot JSON files) and
    build the merged report."""
    records: List[dict] = []
    metrics: dict = {}
    skipped = 0
    for p in paths:
        recs, snap, skip = load_file(p)
        records.extend(recs)
        skipped += skip
        if snap:
            metrics.update(snap)
    rep = build_report(records, metrics=metrics or None)
    rep["files"] = list(paths)
    rep["skipped_lines"] = skipped
    return rep
