"""Cross-process trace context (W3C-traceparent-shaped).

One request entering the plane gets exactly one ``trace_id``; every
hop that does work on its behalf (router ingress, each retry/hedge
leg, the backend pipeline, the multi-host follower executing its
dispatch, the CG solve at the bottom of the IPM) emits spans stamped
with that id plus its own ``span_id``/``parent_span_id``, so the
fleet aggregator (:mod:`distributedlpsolver_tpu.obs.agg`) can stitch
per-process Perfetto artifacts back into one causal story.

The wire form is the W3C traceparent shape carried in the
``X-DLPS-Trace`` header (:data:`distributedlpsolver_tpu.net.protocol.
TRACE_HEADER`)::

    00-<trace_id:32 hex>-<span_id:16 hex>-<flags:2 hex>

The ``span_id`` slot carries the *sender's* span: the receiver calls
:meth:`TraceContext.child` to mint its own span under that parent.
Calling :meth:`child` twice on the same context yields two fresh
span_ids sharing the same parent — siblings — which is exactly the
hedge-leg semantics: the router's ingress span is the parent, each
launched leg is a sibling child, and the backend that serves a leg
continues *that* leg's branch.

Everything here is host-side string/int work — contexts ride JSONL
records, HTTP headers, and dispatch-journal meta, never program
inputs, so the zero-warm-recompile invariant is untouched.

A thread-local *current context* lets deep solver code (the IPM host
loop, the sparse-iterative backend) annotate its spans with the
owning request's trace without threading an argument through the
backend protocol: the serve pipeline sets the context around each
solve, :func:`current` reads it.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
from typing import Optional

__all__ = [
    "TraceContext",
    "new_context",
    "parse",
    "current",
    "set_current",
    "use",
]

_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace>[0-9a-f]{32})"
    r"-(?P<span>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)


def _rand_hex(n_bytes: int) -> str:
    return os.urandom(n_bytes).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop's view of a trace: who am I (``span_id``), which story
    am I part of (``trace_id``), and who caused me (``parent_span_id``,
    empty at the root)."""

    trace_id: str
    span_id: str
    parent_span_id: str = ""
    flags: str = "01"

    def to_header(self) -> str:
        """Wire form; the receiver sees *our* span_id as its parent."""
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    def child(self) -> "TraceContext":
        """A fresh span under this one. Two children of the same
        context are siblings (hedge-leg semantics)."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_rand_hex(8),
            parent_span_id=self.span_id,
            flags=self.flags,
        )

    def span_args(self) -> dict:
        """The standard trace annotation for a tracer span/event."""
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id:
            args["parent_span_id"] = self.parent_span_id
        return args


def new_context() -> TraceContext:
    """A root context: fresh trace_id, fresh span_id, no parent."""
    return TraceContext(trace_id=_rand_hex(16), span_id=_rand_hex(8))


def parse(header: Optional[str]) -> Optional[TraceContext]:
    """Tolerant header parse: malformed/absent input yields ``None``
    (the request simply starts a new trace) — a bad client header must
    never fail a solve."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    if m.group("trace") == "0" * 32 or m.group("span") == "0" * 16:
        return None
    # The sender's span becomes our parent; we are a fresh span.
    return TraceContext(
        trace_id=m.group("trace"),
        span_id=_rand_hex(8),
        parent_span_id=m.group("span"),
        flags=m.group("flags"),
    )


# --------------------------------------------------------------------------
# Thread-local current context
# --------------------------------------------------------------------------

_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The context set for this thread, or ``None``."""
    return getattr(_tls, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` for this thread; returns the previous value so
    callers can restore it."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


class use:
    """``with use(ctx): ...`` — scoped :func:`set_current`."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._prev = None

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = set_current(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        set_current(self._prev)
