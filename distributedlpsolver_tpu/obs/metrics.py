"""Thread-safe in-process metrics registry (counters, gauges,
fixed-bucket histograms) with Prometheus-text and JSON snapshot
exporters.

Design constraints, in order:

1. **Zero-cost when disabled.** The module default is :data:`NULL`, a
   registry whose instruments are shared singletons with no-op methods —
   no locks taken, no objects allocated per call — so the IPM driver can
   increment an iteration counter unconditionally without the no-obs
   path paying anything measurable (tier-1 timing envelopes and the
   zero-warm-recompile invariant must be untouched).
2. **Hot-path instruments are pre-resolved.** ``registry.counter(name)``
   does a locked dict lookup; callers on per-iteration paths resolve
   their instruments once (driver: before the loop; service: in
   ``__init__``) and then call ``inc()``/``observe()`` — a bare method
   call on a few primitives.
3. **Host-side only.** Nothing here touches a device value; callers
   observe wall-clock floats they already measured. Instrumentation must
   never add a device sync.

Labels are a plain dict; an instrument's identity is (name, sorted
label items), matching Prometheus semantics. Histograms use fixed
upper-inclusive bucket edges (Prometheus ``le``), cumulative in the
text exposition, plus ``sum``/``count``.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional, Sequence, Tuple

# Default histogram edges for millisecond-scale latencies (pack/solve/
# queue) — roughly log-spaced from sub-ms to minutes.
LATENCY_MS_BUCKETS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1_000.0, 2_500.0, 5_000.0, 10_000.0, 30_000.0, 60_000.0,
)
# Seconds-scale variant (IPM step times, recovery overhead).
SECONDS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
)
# Fractions in [0, 1] (padding waste, overlap ratio).
RATIO_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)
# IPM iteration counts per solve/request (the warm-vs-cold split rides
# an {start="warm"|"cold"} label on this histogram).
ITER_BUCKETS = (
    1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 48.0, 64.0,
    96.0, 128.0, 200.0,
)
# Scenario counts per scenario-tier request (the pow2 bucket ladder of
# models/scenario.scenario_k_bucket, extended to pod-scale K).
SCENARIO_K_BUCKETS = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1024.0, 4096.0, 16384.0,
)

_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Optional[dict]) -> _Key:
    if not labels:
        return (name, ())
    return (name, tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing count. ``inc`` is the only mutator."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A value that goes up and down (queue depth, mesh width)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram, Prometheus ``le`` semantics: bucket ``i``
    counts observations ``v <= edges[i]``; values above the last edge
    land only in the implicit ``+Inf`` bucket (``count``)."""

    __slots__ = (
        "edges", "_counts", "_sum", "_count", "_lock", "_exemplar"
    )

    def __init__(self, edges: Sequence[float]):
        if list(edges) != sorted(edges) or len(set(edges)) != len(edges):
            raise ValueError(f"histogram edges must be sorted, unique: {edges}")
        self.edges = tuple(float(e) for e in edges)
        self._counts = [0] * len(self.edges)
        self._sum = 0.0
        self._count = 0
        # Slowest-observation exemplar: (value, trace_id-or-label). One
        # slot, max-value wins — "which request was this histogram's
        # worst" is the question the fleet aggregator answers with it.
        self._exemplar: Optional[Tuple[float, str]] = None
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            # Linear scan beats bisect at these edge counts (<= ~16) and
            # allocates nothing.
            for i, e in enumerate(self.edges):
                if v <= e:
                    self._counts[i] += 1
                    break
            if exemplar is not None and (
                self._exemplar is None or v > self._exemplar[0]
            ):
                self._exemplar = (v, str(exemplar))

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "buckets": {
                    f"{e:g}": c for e, c in zip(self.edges, self._counts)
                },
                "sum": self._sum,
                "count": self._count,
            }
            if self._exemplar is not None:
                snap["exemplar"] = {
                    "value": self._exemplar[0],
                    "trace_id": self._exemplar[1],
                }
            return snap

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class _NullInstrument:
    """Shared do-nothing counter/gauge/histogram. The methods take the
    same arguments as the real ones and return immediately — no lock, no
    allocation — so disabled-mode instrumentation costs one bound-method
    call per site."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    value = 0.0
    count = 0
    sum = 0.0
    edges = ()


_NULL_INSTRUMENT = _NullInstrument()


class MetricsRegistry:
    """Get-or-create home of named instruments.

    ``counter``/``gauge``/``histogram`` return the same instrument for
    the same (name, labels) forever; a name registered as one kind
    cannot be re-registered as another (raises TypeError — silent kind
    confusion corrupts both exporters).
    """

    enabled = True

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[_Key, object] = {}  # guarded-by: _lock
        self._kinds: Dict[str, str] = {}  # guarded-by: _lock
        self._help: Dict[str, str] = {}  # guarded-by: _lock

    def _get(self, kind: str, name: str, labels, help_, factory):
        key = _key(name, labels)
        with self._lock:
            prev = self._kinds.get(name)
            if prev is not None and prev != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {prev}, "
                    f"not {kind}"
                )
            inst = self._instruments.get(key)
            if inst is None:
                inst = factory()
                self._instruments[key] = inst
                self._kinds[name] = kind
                if help_:
                    self._help[name] = help_
            return inst

    def counter(
        self, name: str, labels: Optional[dict] = None, help: str = ""
    ) -> Counter:
        return self._get("counter", name, labels, help, Counter)

    def gauge(
        self, name: str, labels: Optional[dict] = None, help: str = ""
    ) -> Gauge:
        return self._get("gauge", name, labels, help, Gauge)

    def histogram(
        self,
        name: str,
        buckets: Sequence[float] = LATENCY_MS_BUCKETS,
        labels: Optional[dict] = None,
        help: str = "",
    ) -> Histogram:
        return self._get(
            "histogram", name, labels, help, lambda: Histogram(buckets)
        )

    # -- exporters -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable view: ``{name{labels}: value-or-hist}`` —
        the form embedded into bench rows and the serve summary event."""
        with self._lock:
            items = list(self._instruments.items())
            kinds = dict(self._kinds)
        out: dict = {}
        for (name, labels), inst in sorted(items):
            full = name + _label_str(labels)
            if kinds[name] == "histogram":
                out[full] = inst.snapshot()
            else:
                out[full] = inst.value
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition (v0.0.4): HELP/TYPE headers, one
        sample line per instrument, cumulative ``_bucket{le=}`` series
        plus ``_sum``/``_count`` for histograms."""
        with self._lock:
            items = sorted(self._instruments.items())
            kinds = dict(self._kinds)
            helps = dict(self._help)
        lines = []
        seen_header = set()
        for (name, labels), inst in items:
            if name not in seen_header:
                seen_header.add(name)
                if name in helps:
                    lines.append(f"# HELP {name} {helps[name]}")
                lines.append(f"# TYPE {name} {kinds[name]}")
            if kinds[name] == "histogram":
                snap = inst.snapshot()
                cum = 0
                for edge, c in zip(
                    inst.edges, snap["buckets"].values()
                ):
                    cum += c
                    ls = dict(labels)
                    ls["le"] = f"{edge:g}"
                    lines.append(
                        f"{name}_bucket{_label_str(tuple(sorted(ls.items())))}"
                        f" {cum}"
                    )
                ls = dict(labels)
                ls["le"] = "+Inf"
                lines.append(
                    f"{name}_bucket{_label_str(tuple(sorted(ls.items())))}"
                    f" {snap['count']}"
                )
                lines.append(f"{name}_sum{_label_str(labels)} {snap['sum']:g}")
                lines.append(
                    f"{name}_count{_label_str(labels)} {snap['count']}"
                )
            else:
                lines.append(f"{name}{_label_str(labels)} {inst.value:g}")
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_prometheus_text())

    def write_snapshot(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.snapshot(), fh, indent=2, sort_keys=True)
            fh.write("\n")


class NullRegistry(MetricsRegistry):
    """The disabled registry: every instrument request returns the one
    shared no-op instrument; both exporters render empty."""

    enabled = False

    def __init__(self):
        pass  # no lock, no dicts — nothing to protect

    def counter(self, name, labels=None, help=""):
        return _NULL_INSTRUMENT

    def gauge(self, name, labels=None, help=""):
        return _NULL_INSTRUMENT

    def histogram(self, name, buckets=LATENCY_MS_BUCKETS, labels=None, help=""):
        return _NULL_INSTRUMENT

    def snapshot(self) -> dict:
        return {}

    def to_prometheus_text(self) -> str:
        return ""


NULL = NullRegistry()

# Module-level default: NULL until something (the CLI flags, bench.py, a
# test) installs a real registry. Components resolve it at construction
# time, so a registry installed after a service started does not
# retroactively instrument it.
from distributedlpsolver_tpu.obs import DefaultSlot  # noqa: E402

_DEFAULT = DefaultSlot(NULL)


def get_registry() -> MetricsRegistry:
    return _DEFAULT.get()


def set_registry(registry: Optional[MetricsRegistry]) -> MetricsRegistry:
    """Install ``registry`` as the module default (None restores the
    no-op NULL). Returns the previous default so callers can restore it
    (tests, scoped CLI runs)."""
    return _DEFAULT.set(registry)
