"""Unified observability layer: metrics registry, span tracer, shared
stats, and the ``cli report`` analyzer.

The repo's telemetry grew up as crash forensics — per-iteration JSONL
rows (utils/logging.IterLogger), per-request serve records, supervisor
fault events — each with its own ad-hoc schema and no aggregation
tooling. This package is the substrate that turns those streams into
answers (the accelerator-LP literature's recurring point: per-phase
timing attribution is what makes a device-side solver tunable — MPAX,
arXiv:2412.09734; HiOp's accelerator port, arXiv:2605.13736):

- :mod:`obs.metrics` — a thread-safe in-process registry of counters,
  gauges, and fixed-bucket histograms, with a Prometheus-text snapshot
  writer and a JSON snapshot (embedded into bench rows and the serve
  summary event). Disabled by default: the module-level registry is a
  :data:`~distributedlpsolver_tpu.obs.metrics.NULL` no-op whose
  instruments allocate nothing per call.
- :mod:`obs.trace` — a span tracer emitting Chrome-trace-format JSON
  (load it at ui.perfetto.dev). Async begin/end events keyed by request
  id connect one request's life across the serve pipeline's three
  threads; ``X`` spans give each pipeline thread its own lane; instant
  events mark supervisor faults, reshards, and ladder swaps.
- :mod:`obs.stats` — the one percentile/summary implementation the
  serve summary, bench, and probes all share.
- :mod:`obs.report` — ``cli report``: merge iteration/serve/fault JSONL
  streams (old unstamped files included) plus metric snapshots into
  per-phase latency breakdowns, padding-waste-by-bucket tables,
  recovery-overhead summaries, and an iters/sec trajectory.

Every JSONL record the package writes is stamped with
``schema_version`` / wall-clock ``ts`` / monotonic ``t_mono``
(utils/logging.stamp_record) so ``cli report`` can merge streams;
readers stay backward-compatible with unstamped pre-stamp files.
"""

# Version of the shared JSONL record schema (the stamp fields
# schema_version/ts/t_mono plus each stream's own payload). Bump when a
# stamped field changes meaning; readers must keep accepting records
# with a missing or older version (pre-stamp files have none).
SCHEMA_VERSION = 1

import threading  # noqa: E402


class DefaultSlot:
    """The one module-default holder metrics and trace both use (they
    each grew an identical ``_default`` + ``_default_lock`` pair; this
    is the shared shape). ``set`` installs a new default and returns the
    previous one so callers can restore it (tests, scoped CLI runs);
    ``None`` restores the null instance. ``get`` is deliberately
    lockless — the default is resolved on hot paths and a torn read is
    impossible for a single reference."""

    def __init__(self, null):
        self._null = null
        self._lock = threading.Lock()
        self._value = null

    def get(self):
        return self._value

    def set(self, value):
        with self._lock:
            prev = self._value
            self._value = value if value is not None else self._null
        return prev


# NOTE: DefaultSlot must be defined ABOVE these imports — metrics and
# trace import it from the partially-initialized package.
from distributedlpsolver_tpu.obs.metrics import (  # noqa: E402
    MetricsRegistry,
    NULL as NULL_REGISTRY,
    get_registry,
    set_registry,
)
from distributedlpsolver_tpu.obs.stats import (  # noqa: E402
    percentile,
    summarize,
)
from distributedlpsolver_tpu.obs.trace import (  # noqa: E402
    NULL_TRACER,
    Tracer,
    get_tracer,
    set_tracer,
)
from distributedlpsolver_tpu.obs.context import (  # noqa: E402
    TraceContext,
    new_context,
)

__all__ = [
    "SCHEMA_VERSION",
    "DefaultSlot",
    "MetricsRegistry",
    "NULL_REGISTRY",
    "NULL_TRACER",
    "Tracer",
    "TraceContext",
    "get_registry",
    "set_registry",
    "get_tracer",
    "set_tracer",
    "new_context",
    "percentile",
    "summarize",
]
