"""Plane-level telemetry aggregator: one fleet view from many processes.

The serving plane is a fleet of processes — routers, slice rank-0
front-ends, follower ranks — each exporting its own telemetry (``GET
/statusz`` + ``GET /metrics`` over HTTP; heartbeat + metrics-snapshot
files for followers that serve no HTTP; per-process Chrome-trace JSON
files from obs/trace.py). This module pulls those per-process views
together into ONE fleet document:

- **Discovery** — backends come from the shared
  :class:`~distributedlpsolver_tpu.net.registry.BackendRegistry` JSON
  (the same document routers coordinate through), follower ranks from
  heartbeat-directory scans (``rank*.hb`` + ``rank*.metrics.json``),
  and routers/extra backends from explicit URLs. Every source is
  best-effort: an unreachable process becomes an ``error`` row, never
  an aggregator crash — observing the fleet must not depend on the
  fleet being healthy.
- **Rollups** — per-backend request/latency/journal rows, per-slice
  rank tables, and fleet totals.
- **Trace merge** — N per-process Perfetto files become one: each
  source gets its own pid (Perfetto renders it as a separate process
  track), and every cross-process trace_id found in span args gets a
  flow-event chain (``ph: s/t/f``) stitching its spans together across
  pids, so one request's router-ingress → hedge-leg → backend-pipeline
  → CG spans render as one connected arc.
- **Exemplars** — histogram snapshots written as JSON (follower
  ``rank*.metrics.json``, ``--metrics-json`` files) carry the slowest
  observation's trace_id (obs/metrics.py exemplar slot); the fleet view
  surfaces them as a "slowest request, and here is its trace" table.
- **Reconciliation** — the router's hedge ledger, the backends' request
  records, and the journals' lifecycle counts are three independent
  counts of the same work; the reconciliation table lines them up and
  flags any drift (lost requests, double counts, unaccounted hedges).

Everything here is host-side, read-only, and out of process: the
aggregator never touches the device path, so the zero-warm-recompile
invariant is untouched by construction.
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

# -- best-effort HTTP pulls ------------------------------------------------


def fetch_json(url: str, timeout_s: float = 2.0) -> Tuple[Optional[dict], str]:
    """GET ``url`` and parse JSON; returns ``(doc, "")`` or
    ``(None, error-string)`` — aggregation must degrade, not raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
        return (doc if isinstance(doc, dict) else None), (
            "" if isinstance(doc, dict) else "non-object response"
        )
    except (urllib.error.URLError, OSError, ValueError) as exc:
        return None, str(exc)


def fetch_text(url: str, timeout_s: float = 2.0) -> Tuple[Optional[str], str]:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8"), ""
    except (urllib.error.URLError, OSError, ValueError) as exc:
        return None, str(exc)


_PROM_LINE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*(?:\{[^}]*\})?)\s+(\S+)$")


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal Prometheus text parser: ``{name{labels}: value}`` over
    sample lines (comments and malformed lines skipped). Enough to sum
    counters across the fleet; not a general exposition parser."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        try:
            out[m.group(1)] = float(m.group(2))
        except ValueError:
            continue
    return out


# -- discovery -------------------------------------------------------------


_RANK_HB = re.compile(r"^rank(\d+)\.hb$")
_RANK_METRICS = re.compile(r"^rank(\d+)\.metrics\.json$")


def discover(
    registry_path: Optional[str] = None,
    heartbeat_dirs: Sequence[str] = (),
    routers: Sequence[str] = (),
    backends: Sequence[str] = (),
) -> dict:
    """Build the fleet's source list. Backends = registry entries ∪
    explicit URLs (registry metadata — slice_id, world_size, ejected —
    rides along); slices = one entry per heartbeat dir with every rank
    file found in it."""
    backend_meta: Dict[str, dict] = {}
    registry_doc: Optional[dict] = None
    if registry_path:
        from distributedlpsolver_tpu.net.registry import BackendRegistry

        registry_doc = BackendRegistry(registry_path).load()
        for url, entry in sorted(registry_doc.get("backends", {}).items()):
            backend_meta[url.rstrip("/")] = dict(entry)
    for url in backends:
        backend_meta.setdefault(url.rstrip("/"), {})

    slices: List[dict] = []
    for hb_dir in heartbeat_dirs:
        ranks: Dict[int, dict] = {}
        try:
            names = sorted(os.listdir(hb_dir))
        except OSError as exc:
            slices.append({"dir": hb_dir, "error": str(exc), "ranks": {}})
            continue
        for name in names:
            path = os.path.join(hb_dir, name)
            m_hb = _RANK_HB.match(name)
            m_me = _RANK_METRICS.match(name)
            if not (m_hb or m_me):
                continue
            rank = int((m_hb or m_me).group(1))
            slot = ranks.setdefault(rank, {})
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError) as exc:
                slot.setdefault("errors", []).append(f"{name}: {exc}")
                continue
            slot["heartbeat" if m_hb else "metrics"] = doc
        slices.append({"dir": hb_dir, "ranks": ranks})

    return {
        "registry": {
            "path": registry_path,
            "generation": (registry_doc or {}).get("generation"),
        },
        "routers": [u.rstrip("/") for u in routers],
        "backends": backend_meta,
        "slices": slices,
    }


def collect(discovery: dict, timeout_s: float = 2.0) -> dict:
    """Pull ``/statusz`` + ``/metrics`` from every discovered router and
    backend. Returns the fleet document skeleton (rollups/reconciliation
    attach to it afterwards)."""
    routers: Dict[str, dict] = {}
    for url in discovery["routers"]:
        stz, err = fetch_json(url + "/statusz", timeout_s)
        routers[url] = {"statusz": stz} if stz else {"error": err}

    backends: Dict[str, dict] = {}
    for url, meta in discovery["backends"].items():
        row: dict = {"registry": meta} if meta else {}
        stz, err = fetch_json(url + "/statusz", timeout_s)
        if stz is None:
            row["error"] = err
        else:
            row["statusz"] = stz
            text, _ = fetch_text(url + "/metrics", timeout_s)
            if text is not None:
                row["metrics"] = parse_prometheus(text)
        backends[url] = row

    return {
        "collected_ts": time.time(),
        "registry": discovery["registry"],
        "routers": routers,
        "backends": backends,
        "slices": discovery["slices"],
    }


# -- rollups ---------------------------------------------------------------


def rollup(fleet: dict) -> dict:
    """Condense the raw pulls into per-backend rows + fleet totals."""
    rows = []
    totals = {
        "backends": 0,
        "reachable": 0,
        "requests": 0,
        "http_requests": 0,
        "journal_pending": 0,
        "journal_results": 0,
        "dispatches": 0,
        "programs_compiled": 0,
    }
    for url, row in sorted(fleet["backends"].items()):
        totals["backends"] += 1
        stz = row.get("statusz")
        reg = row.get("registry", {})
        if stz is None:
            rows.append(
                {"url": url, "reachable": False, "error": row.get("error", "")}
            )
            continue
        totals["reachable"] += 1
        stats = stz.get("stats") or {}
        net = stz.get("net") or {}
        journal = stats.get("journal") or {}
        out = {
            "url": url,
            "reachable": True,
            "slice_id": reg.get("slice_id"),
            "world_size": reg.get("world_size"),
            "ejected": reg.get("ejected", False),
            "uptime_s": round(float(stz.get("uptime_s", 0.0)), 1),
            "http_requests": int(net.get("requests_total", 0)),
            "requests": int(stats.get("requests", 0)),
            "status_breakdown": stats.get("status_breakdown", {}),
            "latency_ms_p50": stats.get("latency_ms_p50"),
            "latency_ms_p99": stats.get("latency_ms_p99"),
            "queue_depth": stats.get("queue_depth"),
            "dispatches": int(stats.get("dispatches", 0)),
            "programs_compiled": int(stats.get("programs_compiled", 0)),
            "journal": journal or None,
        }
        rows.append(out)
        totals["requests"] += out["requests"]
        totals["http_requests"] += out["http_requests"]
        totals["dispatches"] += out["dispatches"]
        totals["programs_compiled"] += out["programs_compiled"]
        totals["journal_pending"] += int(journal.get("pending", 0))
        totals["journal_results"] += int(journal.get("results", 0))

    slice_rows = []
    for sl in fleet["slices"]:
        ranks = []
        for rank in sorted(sl.get("ranks", {})):
            slot = sl["ranks"][rank]
            hb = slot.get("heartbeat") or {}
            ranks.append(
                {
                    "rank": rank,
                    "pid": hb.get("pid"),
                    "generation": hb.get("generation"),
                    "has_metrics": "metrics" in slot,
                }
            )
        slice_rows.append(
            {
                "dir": sl.get("dir"),
                "world_size_seen": len(ranks),
                "ranks": ranks,
                **({"error": sl["error"]} if "error" in sl else {}),
            }
        )
    return {"backends": rows, "totals": totals, "slices": slice_rows}


def exemplars(fleet: dict, metrics_json: Sequence[str] = ()) -> List[dict]:
    """Histogram exemplars across the fleet: every JSON metrics snapshot
    (follower ``rank*.metrics.json`` files + explicit ``--metrics-json``
    paths) whose histograms recorded a slowest-observation trace_id.
    Sorted slowest-first — the fleet's 'worst request, and here is the
    trace to open' table."""
    out: List[dict] = []

    def _scan(source: str, snap: dict) -> None:
        for name, val in snap.items():
            if isinstance(val, dict) and isinstance(
                val.get("exemplar"), dict
            ):
                ex = val["exemplar"]
                out.append(
                    {
                        "source": source,
                        "metric": name,
                        "value": ex.get("value"),
                        "trace_id": ex.get("trace_id"),
                    }
                )

    def _unwrap(snap: dict) -> dict:
        # Follower files wrap the registry snapshot with identity
        # stamps ({"rank": k, ..., "metrics": {...}}); bare snapshots
        # (--metrics-json files) are the registry dict itself.
        inner = snap.get("metrics")
        return inner if isinstance(inner, dict) else snap

    for sl in fleet["slices"]:
        for rank, slot in sorted(sl.get("ranks", {}).items()):
            snap = slot.get("metrics")
            if isinstance(snap, dict):
                _scan(f"{sl.get('dir')}:rank{rank}", _unwrap(snap))
    for path in metrics_json:
        try:
            with open(path) as fh:
                snap = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(snap, dict):
            _scan(path, _unwrap(snap))
    out.sort(key=lambda e: -(e["value"] or 0.0))
    return out


# -- trace merge -----------------------------------------------------------


def _flow_id(trace_id: str) -> int:
    # Chrome flow events key on an integer id; 15 hex digits of the
    # trace_id keep it unique-in-practice and inside int64.
    try:
        return int(trace_id[:15], 16)
    except (TypeError, ValueError):
        return abs(hash(trace_id)) & 0x7FFFFFFF


def merge_traces(sources: Sequence[Tuple[str, str]]) -> dict:
    """Merge per-process Chrome-trace files into one fleet trace.

    ``sources`` is ``[(label, path), ...]``. Each source becomes its own
    pid (process track) with ``label`` as its process_name; every event
    keeps its original tid (thread lanes stay intact inside each
    process). Spans carrying the same ``args.trace_id`` (or listing it
    in ``args.trace_ids``) across sources get a flow chain — ``s`` at
    the first span, ``t`` through the middle, ``f`` at the last — which
    Perfetto renders as connecting arrows: the visual proof that ONE
    request crossed router → backend → pipeline → solver.
    """
    events: List[dict] = []
    errors: List[dict] = []
    # trace_id -> [(ts, pid, tid)] anchor points for flow stitching.
    anchors: Dict[str, List[Tuple[float, int, int]]] = {}

    for idx, (label, path) in enumerate(sources):
        pid = idx + 1
        try:
            with open(path) as fh:
                doc = json.load(fh)
            src_events = doc["traceEvents"]
        except (OSError, ValueError, KeyError, TypeError) as exc:
            errors.append({"source": label, "path": path, "error": str(exc)})
            continue
        named = False
        for ev in src_events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # One process_name per source; prefix with the label so
                # the fleet view says which file each track came from.
                orig = (ev.get("args") or {}).get("name", "")
                ev["args"] = {"name": f"{label} ({orig})" if orig else label}
                named = True
            args = ev.get("args")
            if isinstance(args, dict):
                ids = []
                if isinstance(args.get("trace_id"), str):
                    ids.append(args["trace_id"])
                if isinstance(args.get("trace_ids"), list):
                    ids.extend(
                        t for t in args["trace_ids"] if isinstance(t, str)
                    )
                ts = ev.get("ts")
                if ids and isinstance(ts, (int, float)):
                    for tid_ in dict.fromkeys(ids):
                        anchors.setdefault(tid_, []).append(
                            (float(ts), pid, ev.get("tid", 0))
                        )
            events.append(ev)
        if not named:
            events.append(
                {
                    "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": label},
                }
            )

    # Flow stitching: one chain per trace_id that has ≥2 anchor points.
    traces_connected = 0
    for trace_id, pts in sorted(anchors.items()):
        if len(pts) < 2:
            continue
        pts.sort()
        traces_connected += 1
        fid = _flow_id(trace_id)
        for i, (ts, pid, tid) in enumerate(pts):
            ph = "s" if i == 0 else ("f" if i == len(pts) - 1 else "t")
            ev = {
                "ph": ph, "name": "trace", "cat": "trace_flow", "id": fid,
                "ts": ts, "pid": pid, "tid": tid,
                "args": {"trace_id": trace_id},
            }
            if ph == "f":
                ev["bp"] = "e"  # bind to enclosing slice
            events.append(ev)

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "perf_counter_us",
            "sources": [label for label, _ in sources],
            "traces_connected": traces_connected,
            **({"merge_errors": errors} if errors else {}),
        },
    }


def trace_summary(merged: dict) -> dict:
    """Cross-process span census of a merged trace: per-trace_id span
    count and the set of pids it touched — what the probe asserts on
    ('one trace_id, ≥4 spans, ≥2 processes')."""
    spans: Dict[str, dict] = {}
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i", "b", "e"):
            continue
        args = ev.get("args")
        if not isinstance(args, dict):
            continue
        ids = []
        if isinstance(args.get("trace_id"), str):
            ids.append(args["trace_id"])
        if isinstance(args.get("trace_ids"), list):
            ids.extend(t for t in args["trace_ids"] if isinstance(t, str))
        for tid_ in dict.fromkeys(ids):
            slot = spans.setdefault(
                tid_, {"spans": 0, "pids": set(), "names": []}
            )
            slot["spans"] += 1
            slot["pids"].add(ev.get("pid", 1))
            if len(slot["names"]) < 64:
                slot["names"].append(ev.get("name", ""))
    return {
        tid_: {
            "spans": slot["spans"],
            "processes": len(slot["pids"]),
            "names": slot["names"],
        }
        for tid_, slot in sorted(spans.items())
    }


# -- reconciliation --------------------------------------------------------


def reconcile(fleet: dict) -> dict:
    """Line up the three independent counts of the same work:

    1. the routers' hedge ledger (forwards launched, hedges launched,
       per-outcome tallies, cancels),
    2. the backends' request records (``stats.requests`` — one per
       completed solve), and
    3. the journals' lifecycle counts (pending + results files).

    Each check reports ``ok`` / ``mismatch`` with the numbers that went
    in, or ``skipped`` with the reason (no routers, unreachable
    backends, journal off) — a reconciliation that silently ignored
    missing data would be worse than none.
    """
    router_rows = []
    forwards = hedges = cancels = outcomes_sum = budget_exhausted = 0
    failovers = 0
    outcomes_total: Dict[str, int] = {}
    routers_ok = 0
    for url, row in sorted(fleet["routers"].items()):
        stz = row.get("statusz")
        if stz is None:
            router_rows.append(
                {"url": url, "reachable": False, "error": row.get("error", "")}
            )
            continue
        routers_ok += 1
        hed = stz.get("hedging") or {}
        out = {k: int(v) for k, v in (hed.get("outcomes") or {}).items()}
        # Suppressed outcomes (rate cap / budget) tally hedge ATTEMPTS
        # that never launched a leg — they must not count against
        # hedges_launched or the backend-record balance.
        launched_out = {
            k: v for k, v in out.items() if not k.startswith("suppressed_")
        }
        router_rows.append(
            {
                "url": url,
                "reachable": True,
                "forwards_total": int(hed.get("forwards_total", 0)),
                "hedges_launched": int(hed.get("hedges_launched", 0)),
                "outcomes": out,
                "cancels": int(hed.get("cancels", 0)),
                "budget_exhausted": int(hed.get("budget_exhausted", 0)),
                "failovers": int(stz.get("failovers", 0)),
            }
        )
        forwards += router_rows[-1]["forwards_total"]
        hedges += router_rows[-1]["hedges_launched"]
        cancels += router_rows[-1]["cancels"]
        budget_exhausted += router_rows[-1]["budget_exhausted"]
        failovers += router_rows[-1]["failovers"]
        outcomes_sum += sum(launched_out.values())
        for k, v in out.items():
            outcomes_total[k] = outcomes_total.get(k, 0) + v

    backend_records = 0
    backends_ok = backends_total = 0
    journal_results = journal_pending = 0
    journal_backends = 0
    journal_records = 0  # records on backends that also report a journal
    for row in fleet["backends"].values():
        backends_total += 1
        stz = row.get("statusz")
        if stz is None:
            continue
        backends_ok += 1
        stats = stz.get("stats") or {}
        n = int(stats.get("requests", 0))
        backend_records += n
        journal = stats.get("journal") or {}
        if journal:
            journal_backends += 1
            journal_results += int(journal.get("results", 0))
            journal_pending += int(journal.get("pending", 0))
            journal_records += n

    checks = []

    def _check(name: str, **kw) -> None:
        checks.append({"name": name, **kw})

    if routers_ok == 0:
        _check("hedge_outcomes_accounted", status="skipped",
               reason="no reachable routers")
    else:
        # Launched (non-suppressed) outcomes must sum to hedges_launched
        # — every launched hedge has exactly one recorded outcome.
        _check(
            "hedge_outcomes_accounted",
            status="ok" if outcomes_sum == hedges else "mismatch",
            hedges_launched=hedges,
            launched_outcomes_sum=outcomes_sum,
            outcomes=outcomes_total,
        )

    # Every routed attempt (primary forward + hedge leg) that was not
    # cancelled before dispatch must have produced exactly one backend
    # request record. delta > 0 = lost work; delta < 0 = double count
    # (or a backend also serving un-routed traffic).
    if routers_ok == 0 or backends_ok < backends_total:
        _check(
            "attempts_vs_backend_records",
            status="skipped",
            reason=(
                "no reachable routers"
                if routers_ok == 0
                else f"{backends_total - backends_ok} backend(s) unreachable"
            ),
        )
    else:
        attempts = forwards + hedges
        delta = attempts - backend_records
        ok = delta == 0 if cancels == 0 else 0 <= delta <= cancels
        # Failover retries blur the balance: a failed attempt may or may
        # not have produced a backend record depending on how it failed.
        # Report indeterminate rather than a false mismatch.
        status = (
            "ok"
            if ok
            else ("indeterminate" if failovers or cancels else "mismatch")
        )
        _check(
            "attempts_vs_backend_records",
            status=status,
            attempts=attempts,
            forwards_total=forwards,
            hedges_launched=hedges,
            backend_records=backend_records,
            cancels=cancels,
            failovers=failovers,
            delta=delta,
        )

    # Journal lifecycle: on journal-enabled backends every recorded
    # request is a completed job (results file) and every admitted-but-
    # unfinished job is pending — records == results when drained.
    if journal_backends == 0:
        _check("journal_vs_backend_records", status="skipped",
               reason="no backend reports a journal")
    else:
        _check(
            "journal_vs_backend_records",
            status="ok" if journal_results == journal_records else "mismatch",
            journal_results=journal_results,
            journal_pending=journal_pending,
            backend_records=journal_records,
            journal_backends=journal_backends,
        )

    return {
        "routers": router_rows,
        "totals": {
            "forwards_total": forwards,
            "hedges_launched": hedges,
            "cancels": cancels,
            "budget_exhausted": budget_exhausted,
            "failovers": failovers,
            "outcomes": outcomes_total,
            "backend_records": backend_records,
            "journal_results": journal_results,
            "journal_pending": journal_pending,
        },
        "checks": checks,
        "consistent": all(c["status"] != "mismatch" for c in checks),
    }


# -- the one-call fleet view ----------------------------------------------


def fleet_view(
    registry_path: Optional[str] = None,
    heartbeat_dirs: Sequence[str] = (),
    routers: Sequence[str] = (),
    backends: Sequence[str] = (),
    traces: Sequence[Tuple[str, str]] = (),
    metrics_json: Sequence[str] = (),
    timeout_s: float = 2.0,
) -> Tuple[dict, Optional[dict]]:
    """Discover → collect → rollup → reconcile (+ optional trace merge).
    Returns ``(fleet_doc, merged_trace_or_None)``."""
    disc = discover(registry_path, heartbeat_dirs, routers, backends)
    fleet = collect(disc, timeout_s=timeout_s)
    fleet["rollup"] = rollup(fleet)
    fleet["exemplars"] = exemplars(fleet, metrics_json)
    fleet["reconciliation"] = reconcile(fleet)
    merged = None
    if traces:
        merged = merge_traces(traces)
        fleet["trace_summary"] = trace_summary(merged)
    return fleet, merged


def render_text(fleet: dict) -> str:
    """Human-readable fleet report (the ``cli obs-agg`` stdout body)."""
    lines: List[str] = []
    roll = fleet.get("rollup", {})
    totals = roll.get("totals", {})
    lines.append(
        f"fleet: {totals.get('reachable', 0)}/{totals.get('backends', 0)} "
        f"backends reachable, {len(fleet.get('routers', {}))} router(s), "
        f"{len(fleet.get('slices', []))} slice dir(s)"
    )
    lines.append("")
    lines.append("backends:")
    for row in roll.get("backends", []):
        if not row.get("reachable"):
            lines.append(f"  {row['url']}  UNREACHABLE ({row.get('error')})")
            continue
        j = row.get("journal") or {}
        lines.append(
            f"  {row['url']}  req={row['requests']} http={row['http_requests']}"
            f" p50={row['latency_ms_p50']}ms p99={row['latency_ms_p99']}ms"
            f" dispatches={row['dispatches']}"
            f" journal={j.get('results', '-')}/{j.get('pending', '-')}"
            + (f" slice={row['slice_id']}" if row.get("slice_id") else "")
            + (" EJECTED" if row.get("ejected") else "")
        )
    for sl in roll.get("slices", []):
        lines.append(
            f"  slice dir {sl['dir']}: {sl['world_size_seen']} rank(s) "
            + ", ".join(
                f"r{r['rank']}(pid={r['pid']}"
                + (",metrics" if r["has_metrics"] else "")
                + ")"
                for r in sl["ranks"]
            )
        )
    ex = fleet.get("exemplars") or []
    if ex:
        lines.append("")
        lines.append("slowest observations (histogram exemplars):")
        for e in ex[:10]:
            lines.append(
                f"  {e['metric']} = {e['value']}  trace={e['trace_id']}"
                f"  [{e['source']}]"
            )
    rec = fleet.get("reconciliation") or {}
    if rec:
        lines.append("")
        t = rec.get("totals", {})
        lines.append(
            "reconciliation: "
            f"forwards={t.get('forwards_total')} "
            f"hedges={t.get('hedges_launched')} "
            f"outcomes={t.get('outcomes')} cancels={t.get('cancels')} | "
            f"backend_records={t.get('backend_records')} | "
            f"journal results={t.get('journal_results')} "
            f"pending={t.get('journal_pending')}"
        )
        for c in rec.get("checks", []):
            status = c["status"].upper()
            extra = {
                k: v for k, v in c.items() if k not in ("name", "status")
            }
            lines.append(f"  [{status}] {c['name']} {extra}")
        lines.append(
            "  => " + ("CONSISTENT" if rec.get("consistent") else "DRIFT")
        )
    ts = fleet.get("trace_summary")
    if ts is not None:
        lines.append("")
        lines.append(f"merged trace: {len(ts)} trace_id(s)")
        for tid_, slot in list(ts.items())[:10]:
            lines.append(
                f"  {tid_}: {slot['spans']} span(s) across "
                f"{slot['processes']} process(es)"
            )
    return "\n".join(lines) + "\n"
