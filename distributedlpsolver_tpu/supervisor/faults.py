"""Deterministic fault injection for the solve supervisor.

Every recovery path in supervisor/supervisor.py must be testable on CPU in
tier-1 — a recovery ladder that is only exercised when a real TPU wedges
is untested code on the critical path. The injector sits at the
supervisor's step boundary (it wraps the same ``step_fn`` the watchdog
deadlines), so injection needs no backend cooperation and works with any
backend:

- ``FaultKind.HANG``: the wrapped step sleeps ``hang_seconds`` before
  dispatching, so the watchdog's deadline fires exactly as it would on a
  wedged device (the abandoned thread finishes its nap and runs the real
  step into the void — same as an eventually-completing hung dispatch).
  With ``shard=<device id>`` the injected hang also marks that device
  unhealthy in the runtime's simulated-loss registry, so the supervisor's
  post-hang health probe attributes the hang to that shard — the "shard 3
  always hangs" scenario the mesh-shrink rung exists for. A shard-keyed
  hang only fires while its device is still part of the active mesh: once
  the supervisor shrinks the wedged shard out, the fault stops matching,
  exactly like the real wedge it stands in for.
- ``FaultKind.NUMERICAL``: the real step runs, then its host-bound scalars
  are poisoned to NaN — what a silently-diverged factorization looks like
  from the host.
- ``FaultKind.CRASH``: the step raises :class:`InjectedCrash` — the
  "whole program class crashes the worker" failure (ROUND5_NOTES.md:
  batched PCG chunk≥256, storm ≥100k).
- ``FaultKind.DEVICE_LOST``: the step marks ``device_ids`` lost in the
  runtime registry (parallel/runtime.py — the health probe then reports
  them unhealthy, as a really-dead device would) and raises
  :class:`InjectedDeviceLoss` carrying the ids, the way a real device
  loss surfaces as a runtime error out of the dispatch.

Injection is keyed on the driver iteration number (1-based, as logged) and
optionally on the backend name, and each fault fires a bounded number of
``times`` — counts persist across the supervisor's retries, which is what
makes "NaN at iteration 5, once" produce exactly one fault and a clean
re-solve, while ``times=None`` models a persistently broken backend.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, List, Optional, Sequence, Tuple

from distributedlpsolver_tpu.ipm.state import FaultKind
from distributedlpsolver_tpu.parallel import runtime as _runtime


class InjectedCrash(RuntimeError):
    """Raised by an injected CRASH fault (stands in for a worker crash)."""


class InjectedDeviceLoss(RuntimeError):
    """Raised by an injected DEVICE_LOST fault — the stand-in for the
    runtime error a dispatch raises when a mesh participant drops out.
    Carries ``device_ids`` so the supervisor's classifier sees the same
    information a real device-loss error message encodes."""

    def __init__(self, iteration: int, device_ids: Tuple[int, ...]):
        self.iteration = iteration
        self.device_ids = tuple(device_ids)
        super().__init__(
            f"injected device loss of devices {list(self.device_ids)} at "
            f"iteration {iteration}"
        )


@dataclasses.dataclass
class InjectedFault:
    """One scheduled fault."""

    kind: FaultKind
    iteration: int  # driver iteration (1-based) at which to fire
    backend: Optional[str] = None  # only fire when this backend is active
    times: Optional[int] = 1  # firings allowed; None = every time it matches
    hang_seconds: float = 30.0  # HANG: how long the dispatch blocks
    # DEVICE_LOST: which device ids drop out of the runtime.
    device_ids: Optional[Sequence[int]] = None
    # HANG: blame this device id — the injected hang marks it unhealthy so
    # the supervisor's health probe attributes the hang to that shard. The
    # fault only matches while the id is in the active backend's mesh.
    shard: Optional[int] = None


class FaultInjector:
    """Stateful executor of a fault plan (the plan is just the list).

    One injector instance lives for the whole supervised solve, so
    ``times`` budgets span retries and backend degradations.
    """

    def __init__(self, plan: List[InjectedFault]):
        self._plan = list(plan)
        self._fired: List[int] = [0] * len(self._plan)

    def _match(
        self,
        iteration: int,
        backend: str,
        mesh_device_ids: Optional[Tuple[int, ...]],
    ) -> Optional[int]:
        for i, f in enumerate(self._plan):
            if f.iteration != iteration:
                continue
            if f.backend is not None and f.backend != backend:
                continue
            if (
                f.shard is not None
                and mesh_device_ids is not None
                and f.shard not in mesh_device_ids
            ):
                continue  # the blamed shard was shrunk out of the mesh
            if f.times is not None and self._fired[i] >= f.times:
                continue
            return i
        return None

    def wrap_step(
        self,
        step_fn: Callable,
        iteration: int,
        backend: str,
        mesh_device_ids: Optional[Tuple[int, ...]] = None,
    ) -> Callable:
        """Return ``step_fn`` or a faulting wrapper of it, and consume one
        firing from the matched fault's budget."""
        i = self._match(iteration, backend, mesh_device_ids)
        if i is None:
            return step_fn
        self._fired[i] += 1
        fault = self._plan[i]
        if fault.kind is FaultKind.CRASH:

            def _crash():
                err = InjectedCrash(
                    f"injected step crash at iteration {iteration} "
                    f"on backend {backend!r}"
                )
                err.iteration = iteration  # supervisor reads it for FaultRecord
                raise err

            return _crash
        if fault.kind is FaultKind.DEVICE_LOST:

            def _lose():
                ids = tuple(int(d) for d in (fault.device_ids or ()))
                _runtime.simulate_device_loss(ids)
                raise InjectedDeviceLoss(iteration, ids)

            return _lose
        if fault.kind is FaultKind.HANG:

            def _hang():
                if fault.shard is not None:
                    # The wedged shard also fails the health probe, so the
                    # supervisor can attribute this hang to it.
                    _runtime.simulate_device_loss([fault.shard])
                time.sleep(fault.hang_seconds)
                return step_fn()

            return _hang

        def _poison():
            new_state, stats = step_fn()
            nan = math.nan
            return new_state, stats._replace(mu=nan, gap=nan, rel_gap=nan)

        return _poison
