"""Deterministic fault injection for the solve supervisor.

Every recovery path in supervisor/supervisor.py must be testable on CPU in
tier-1 — a recovery ladder that is only exercised when a real TPU wedges
is untested code on the critical path. The injector sits at the
supervisor's step boundary (it wraps the same ``step_fn`` the watchdog
deadlines), so injection needs no backend cooperation and works with any
backend:

- ``FaultKind.HANG``: the wrapped step sleeps ``hang_seconds`` before
  dispatching, so the watchdog's deadline fires exactly as it would on a
  wedged device (the abandoned thread finishes its nap and runs the real
  step into the void — same as an eventually-completing hung dispatch).
- ``FaultKind.NUMERICAL``: the real step runs, then its host-bound scalars
  are poisoned to NaN — what a silently-diverged factorization looks like
  from the host.
- ``FaultKind.CRASH``: the step raises :class:`InjectedCrash` — the
  "whole program class crashes the worker" failure (ROUND5_NOTES.md:
  batched PCG chunk≥256, storm ≥100k).

Injection is keyed on the driver iteration number (1-based, as logged) and
optionally on the backend name, and each fault fires a bounded number of
``times`` — counts persist across the supervisor's retries, which is what
makes "NaN at iteration 5, once" produce exactly one fault and a clean
re-solve, while ``times=None`` models a persistently broken backend.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, List, Optional

from distributedlpsolver_tpu.ipm.state import FaultKind


class InjectedCrash(RuntimeError):
    """Raised by an injected CRASH fault (stands in for a worker crash)."""


@dataclasses.dataclass
class InjectedFault:
    """One scheduled fault."""

    kind: FaultKind
    iteration: int  # driver iteration (1-based) at which to fire
    backend: Optional[str] = None  # only fire when this backend is active
    times: Optional[int] = 1  # firings allowed; None = every time it matches
    hang_seconds: float = 30.0  # HANG: how long the dispatch blocks


class FaultInjector:
    """Stateful executor of a fault plan (the plan is just the list).

    One injector instance lives for the whole supervised solve, so
    ``times`` budgets span retries and backend degradations.
    """

    def __init__(self, plan: List[InjectedFault]):
        self._plan = list(plan)
        self._fired: List[int] = [0] * len(self._plan)

    def _match(self, iteration: int, backend: str) -> Optional[int]:
        for i, f in enumerate(self._plan):
            if f.iteration != iteration:
                continue
            if f.backend is not None and f.backend != backend:
                continue
            if f.times is not None and self._fired[i] >= f.times:
                continue
            return i
        return None

    def wrap_step(
        self, step_fn: Callable, iteration: int, backend: str
    ) -> Callable:
        """Return ``step_fn`` or a faulting wrapper of it, and consume one
        firing from the matched fault's budget."""
        i = self._match(iteration, backend)
        if i is None:
            return step_fn
        self._fired[i] += 1
        fault = self._plan[i]
        if fault.kind is FaultKind.CRASH:

            def _crash():
                err = InjectedCrash(
                    f"injected step crash at iteration {iteration} "
                    f"on backend {backend!r}"
                )
                err.iteration = iteration  # supervisor reads it for FaultRecord
                raise err

            return _crash
        if fault.kind is FaultKind.HANG:

            def _hang():
                time.sleep(fault.hang_seconds)
                return step_fn()

            return _hang

        def _poison():
            new_state, stats = step_fn()
            nan = math.nan
            return new_state, stats._replace(mu=nan, gap=nan, rel_gap=nan)

        return _poison
