"""Fault-tolerant solve supervision (watchdog, rollback, degradation).

Public surface: :func:`supervised_solve` wraps ``ipm.solve`` with the
recovery ladder; :class:`SupervisorConfig` tunes it; :class:`SolveFailure`
is the structured terminal failure; ``faults`` provides the deterministic
injection harness that makes every recovery path CPU-testable.
"""

from distributedlpsolver_tpu.ipm.state import FaultKind, FaultRecord
from distributedlpsolver_tpu.supervisor.faults import (
    FaultInjector,
    InjectedCrash,
    InjectedFault,
)
from distributedlpsolver_tpu.supervisor.supervisor import (
    IterateHealthFault,
    SolveFailure,
    SupervisorConfig,
    supervised_solve,
)
from distributedlpsolver_tpu.supervisor.watchdog import (
    StepDeadlineExceeded,
    run_with_deadline,
)

__all__ = [
    "FaultInjector",
    "FaultKind",
    "FaultRecord",
    "InjectedCrash",
    "InjectedFault",
    "IterateHealthFault",
    "SolveFailure",
    "StepDeadlineExceeded",
    "SupervisorConfig",
    "run_with_deadline",
    "supervised_solve",
]
