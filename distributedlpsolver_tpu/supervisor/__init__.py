"""Fault-tolerant solve supervision (watchdog, rollback, degradation,
elastic mesh recovery).

Public surface: :func:`supervised_solve` wraps ``ipm.solve`` with the
recovery ladder; :class:`SupervisorConfig` tunes it; :class:`SolveFailure`
is the structured terminal failure; :class:`AdaptiveDeadline` sizes
watchdog deadlines from the trailing median step time; ``faults`` provides
the deterministic injection harness (hangs, NaNs, crashes, device loss)
that makes every recovery path — including the mesh-shrink rung —
CPU-testable.
"""

from distributedlpsolver_tpu.ipm.state import FaultKind, FaultRecord
from distributedlpsolver_tpu.supervisor.adaptive import AdaptiveDeadline
from distributedlpsolver_tpu.supervisor.faults import (
    FaultInjector,
    InjectedCrash,
    InjectedDeviceLoss,
    InjectedFault,
)
from distributedlpsolver_tpu.supervisor.supervisor import (
    IterateHealthFault,
    SolveFailure,
    SupervisorConfig,
    supervised_solve,
)
from distributedlpsolver_tpu.supervisor.watchdog import (
    StepDeadlineExceeded,
    run_with_deadline,
)

__all__ = [
    "AdaptiveDeadline",
    "FaultInjector",
    "FaultKind",
    "FaultRecord",
    "InjectedCrash",
    "InjectedDeviceLoss",
    "InjectedFault",
    "IterateHealthFault",
    "SolveFailure",
    "StepDeadlineExceeded",
    "SupervisorConfig",
    "run_with_deadline",
    "supervised_solve",
]
