"""Dispatch watchdog: run a device step under a wall-clock deadline.

The production failure this closes (ROUND5_NOTES.md): a single hung device
dispatch — a wedged tunnel, a device in a bad state — blocks
``block_until_ready`` forever and wedges the worker for hours with no
status. There is no portable way to cancel an in-flight XLA dispatch, so
the watchdog runs the step in a daemon worker thread and abandons it on
deadline: the host classifies the fault, rolls back, and retries (possibly
on a degraded backend) while the stuck dispatch either eventually
completes into the void or dies with the process. Abandonment, not
cancellation, is the honest contract — the alternative is the observed
≥1h wedge.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from distributedlpsolver_tpu.obs import metrics as obs_metrics


class StepDeadlineExceeded(RuntimeError):
    """A device step exceeded its watchdog deadline (FaultKind.HANG)."""

    def __init__(self, iteration: int, timeout: float):
        self.iteration = iteration
        self.timeout = timeout
        super().__init__(
            f"device step for iteration {iteration} exceeded its "
            f"{timeout:g}s deadline; dispatch abandoned"
        )


def run_with_deadline(
    fn: Callable,
    timeout: Optional[float],
    iteration: int = -1,
):
    """Run ``fn()`` with at most ``timeout`` seconds of wall clock.

    ``timeout`` of None or <= 0 disables the watchdog (direct call — no
    thread overhead on the hot path). On deadline the worker thread is
    abandoned (daemonized, so it cannot block interpreter exit) and
    :class:`StepDeadlineExceeded` raises on the caller's thread. Exceptions
    from ``fn`` re-raise on the caller's thread unchanged.
    """
    if not timeout or timeout <= 0:
        return fn()

    box: dict = {}

    def _target():
        try:
            box["value"] = fn()
        except BaseException as e:  # re-raised on the supervising thread
            box["error"] = e

    t = threading.Thread(
        target=_target, daemon=True, name=f"dlps-step-it{iteration}"
    )
    t.start()
    t.join(timeout)
    if t.is_alive():
        # Off the hot path by construction: a deadline hit already costs
        # a full recovery cycle, so the instrument resolve is fine here
        # (and the happy path above pays nothing).
        obs_metrics.get_registry().counter(
            "watchdog_timeouts_total",
            help="device dispatches abandoned past their deadline",
        ).inc()
        raise StepDeadlineExceeded(iteration, timeout)
    if "error" in box:
        raise box["error"]
    return box["value"]
