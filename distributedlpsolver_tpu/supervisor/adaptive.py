"""Adaptive watchdog deadlines: 10× the trailing median step time.

A static ``--step-timeout`` cannot serve a mesh-shrink decision: the same
flag value that catches a wedged device on a small problem false-fires on
a big one (a 10k endgame iteration legitimately takes ~15 s; a CPU test
step 50 ms), and mis-sizing it either wedges the worker or mis-classifies
a slow step as a hang — which the elastic ladder would then "recover"
from by shrinking a healthy mesh. The robust deadline is relative to the
solve's own observed cadence:

    deadline = clamp(multiplier × median(last ``window`` step times),
                     floor, ceiling)

The *median* (not mean/max) is deliberate: one slow outlier step — a GC
pause, a host hiccup, the occasional re-factorization retry — must not
ratchet the deadline up and blind the watchdog, and one fast step must
not tighten it into false-positive territory.

Warm-up grace: the first steps of a solve (and the first steps after any
recovery that changes compiled shapes — a mesh shrink, a backend
degradation) include XLA compilation, which is 10–1000× a warm step. For
``warmup`` observed steps the deadline falls back to the static hint
(None = unlimited) instead of a median that does not exist yet, and
:meth:`grant_grace` re-opens that window after a recovery.
"""

from __future__ import annotations

import collections
import statistics
from typing import Optional


class AdaptiveDeadline:
    """Trailing-median step-time tracker producing watchdog deadlines."""

    def __init__(
        self,
        multiplier: float = 10.0,
        floor: float = 0.25,
        ceiling: float = 900.0,
        window: int = 32,
        warmup: int = 3,
        static_hint: Optional[float] = None,
    ):
        if multiplier <= 1.0:
            raise ValueError(f"multiplier must exceed 1 (got {multiplier})")
        if floor > ceiling:
            raise ValueError(f"floor {floor} exceeds ceiling {ceiling}")
        self.multiplier = multiplier
        self.floor = floor
        self.ceiling = ceiling
        self.warmup = warmup
        # The static --step-timeout (None = no deadline): used verbatim
        # while no adaptive estimate exists (warm-up / post-recovery
        # grace), so a user-supplied bound still applies from step one.
        self.static_hint = static_hint
        self._obs = collections.deque(maxlen=window)
        self._grace = warmup

    def observe(self, seconds: float) -> None:
        """Record one *successful* step's duration. Timed-out steps are
        never observed — feeding them back would drag the median toward
        the deadline itself and lock in a false-positive loop."""
        self._obs.append(float(seconds))
        if self._grace > 0:
            self._grace -= 1

    def current(self) -> Optional[float]:
        """Deadline for the next step, or None for no deadline."""
        if self._grace > 0 or not self._obs:
            return self.static_hint
        est = self.multiplier * statistics.median(self._obs)
        return min(self.ceiling, max(self.floor, est))

    def grant_grace(self, steps: Optional[int] = None) -> None:
        """Re-open the warm-up window (post-recovery recompile headroom)
        without discarding the step-time history."""
        self._grace = max(self._grace, self.warmup if steps is None else steps)

    def reset(self) -> None:
        """Forget the history AND re-enter warm-up — the step-time regime
        changed wholesale (backend degradation, mesh shrink)."""
        self._obs.clear()
        self._grace = self.warmup

    @property
    def observations(self) -> int:
        return len(self._obs)
