"""Solve supervisor: watchdog + health guards + rollback-and-degrade +
elastic mesh recovery.

Wraps ``ipm.driver.solve`` in a fault-tolerance loop so a solve survives
the failure classes a benchmark artifact can ignore but a serving system
cannot (ROUND5_NOTES.md: a hung dispatch wedging a worker for ≥1h two
iterations from optimal; program classes that crash the worker outright;
a mesh participant dropping out of a pod mid-solve):

1. **Dispatch watchdog** — every device step runs under a deadline
   (supervisor/watchdog.py); a step that blows it is ``FaultKind.HANG``.
   The deadline is either the static ``step_timeout`` or, with
   ``adaptive_timeout``, 10× the trailing median of observed step times
   (supervisor/adaptive.py: floor/ceiling clamped, with warm-up grace for
   compilation) — the only sizing that distinguishes "slow step on a big
   problem" from "wedged device" across problem scales.
2. **Iterate health guards** — the host-side convergence scalars are
   checked every iteration; non-finite values or exploding μ are
   ``FaultKind.NUMERICAL`` before the driver grinds on a poisoned iterate.
3. **Recovery ladder** — on any fault the supervisor rolls back to the
   last good checkpoint and retries with exponential backoff, escalating
   per backend: plain rollback → rollback + regularization bump →
   re-center (fresh well-centered starting point) → **shrink the mesh**
   (mesh backends: probe device health, re-form a smaller mesh over the
   survivors, re-shard, resume — see below) → degrade to the next backend
   in ``backends.auto.DEGRADATION_CHAIN``. When the ladder and the retry
   budget are both exhausted it raises a structured :class:`SolveFailure`
   carrying the ordered fault history — never a silent wedge, never a
   bare traceback.

**Elastic mesh recovery** (the SHRINK rung): when a fault is classified
as ``FaultKind.DEVICE_LOST`` — a raised device-loss error, or repeated
``HANG`` faults the per-device health probe (parallel/runtime.py)
attributes to the same shard (``hang_shard_threshold``) — and the active
backend runs on a mesh with more than ``min_devices`` healthy
participants, the supervisor re-probes the device set, re-forms a smaller
``Mesh`` over the survivors (parallel.mesh.reform_mesh), re-places the
backend on it (``backend.reshard``), and resumes the IPM from the last
host-canonical checkpoint — the problem data and iterate are re-sharded
onto the new layout by the backend's normal ``setup``/``from_host``
(checkpoints are sharding-layout independent, utils/checkpoint.py v3).
Losing one participant of a healthy pod costs one shard's throughput, not
the pod. Device loss never walks the rollback rungs first — a lost device
does not come back on retry — and only falls through to backend
degradation when no shrinkable mesh remains.

Rollback reuses the existing checkpoint machinery (utils/checkpoint.py):
the supervisor forces per-iteration checkpointing to a (temp, unless
configured) path, and each retry resumes through the driver's normal
checkpoint-resume path — fingerprint-guarded, so a rollback can never
resume into a different problem's iterate.

Telemetry: with ``config.log_jsonl`` set, fault classifications and
resume completions are appended to the same JSONL stream as the
iteration records (``{"event": "fault"|"resume", ...}``); each resume
event — and the corresponding ``FaultRecord.recovery_overhead_s`` —
carries the wall-clock from fault classification to the first completed
post-resume iteration, so a post-mortem can attribute wall-clock loss to
the recovery path itself.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional, Union

import numpy as np

from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.driver import SolveHooks, solve
from distributedlpsolver_tpu.ipm.state import (
    FaultKind,
    FaultRecord,
    IPMResult,
    Status,
)
from distributedlpsolver_tpu.obs import metrics as obs_metrics
from distributedlpsolver_tpu.obs import trace as obs_trace
from distributedlpsolver_tpu.parallel import mesh as mesh_lib
from distributedlpsolver_tpu.parallel import runtime as rt
from distributedlpsolver_tpu.supervisor.adaptive import AdaptiveDeadline
from distributedlpsolver_tpu.supervisor.faults import (
    FaultInjector,
    InjectedDeviceLoss,
    InjectedFault,
)
from distributedlpsolver_tpu.supervisor.watchdog import (
    StepDeadlineExceeded,
    run_with_deadline,
)
from distributedlpsolver_tpu.utils.logging import IterLogger


class IterateHealthFault(RuntimeError):
    """An iterate's host-side scalars failed the health guard."""

    def __init__(self, iteration: int, detail: str):
        self.iteration = iteration
        super().__init__(f"iteration {iteration}: {detail}")


class SolveFailure(RuntimeError):
    """Terminal supervisor outcome: recovery exhausted.

    Carries the full ordered fault history (``faults``) so a post-mortem
    reads what happened and what was tried without log spelunking.
    """

    def __init__(self, faults: List[FaultRecord], detail: str):
        self.faults = list(faults)
        self.status = Status.FAILED
        trail = " -> ".join(
            f"{f.kind.value}@it{f.iteration}[{f.backend}]" for f in faults
        )
        super().__init__(f"{detail}; fault history: {trail or '(none)'}")


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Tunables of the fault-tolerance loop (CLI: --supervise flags)."""

    # Watchdog deadline per device step, seconds. None/0 disables the
    # watchdog (guards and crash recovery still run). Size it ~10× the
    # expected step time: a 15 s/iter 10k endgame wants ~180 s, a CPU test
    # problem 0.5 s. With adaptive_timeout this is only the warm-up
    # fallback — the live deadline tracks the observed step times.
    step_timeout: Optional[float] = None
    # Adaptive watchdog deadline (supervisor/adaptive.py): 10× the
    # trailing median of observed step times, clamped to
    # [timeout_floor, timeout_ceiling], with warm-up grace (step_timeout,
    # or no deadline when unset) during the first timeout_warmup steps
    # and after every recovery that recompiles.
    adaptive_timeout: bool = False
    timeout_multiplier: float = 10.0
    timeout_floor: float = 0.25  # seconds; never deadline below this
    timeout_ceiling: float = 900.0  # seconds; never deadline above this
    timeout_window: int = 32  # trailing step times the median sees
    timeout_warmup: int = 3  # deadline-grace steps (compile headroom)
    max_retries: int = 6  # total recovery attempts before SolveFailure
    snapshot_every: int = 1  # rollback checkpoint cadence (iterations)
    backoff_base: float = 0.05  # seconds; doubles per fault
    backoff_max: float = 5.0
    mu_limit: float = 1e30  # exploding-μ guard threshold
    reg_bump: float = 1e4  # regularization multiplier on the bump rung
    degrade: bool = True  # allow backend degradation
    # Elastic mesh recovery: smallest mesh the SHRINK rung may re-form
    # (below it the supervisor degrades instead). 0/1 = shrink down to a
    # single device before degrading.
    min_devices: int = 1
    # HANG faults the health probe attributes to the same device before
    # that device is treated as lost (shrink it out of the mesh).
    hang_shard_threshold: int = 2
    # Per-device wall-clock budget of the post-fault health probe.
    probe_deadline: float = 2.0
    # Rollback checkpoint path; None = a temp file, removed on success.
    checkpoint_path: Optional[str] = None
    # Deterministic fault injection (tests): a list of InjectedFault.
    fault_plan: Optional[List[InjectedFault]] = None


# Ladder rungs per backend, in escalation order. The SHRINK rung is not a
# counter value: it triggers on classification (DEVICE_LOST / attributed
# hangs) or on rung overflow of a mesh backend with probed-unhealthy
# devices, and resets the rung counter for the re-formed mesh.
_RUNG_ROLLBACK, _RUNG_REG_BUMP, _RUNG_RECENTER = 0, 1, 2

_GUARDED_SCALARS = ("mu", "gap", "rel_gap", "pinf", "dinf", "pobj", "dobj")

# Substrings (lowercased) of runtime errors that mean a device dropped
# out rather than the program being at fault. Conservative: a mismatch
# only costs the fault a trip through the generic CRASH ladder before
# the rung-overflow probe still catches a genuinely dead device.
_DEVICE_LOSS_PATTERNS = (
    "device_lost",
    "device lost",
    "device is lost",
    "device unavailable",
    "failed to connect to device",
    "hardware failure",
)


def _looks_like_device_loss(e: BaseException) -> bool:
    msg = f"{type(e).__name__}: {e}".lower()
    return any(p in msg for p in _DEVICE_LOSS_PATTERNS)


class _SupervisorHooks(SolveHooks):
    """Watchdog + health guard + injection at the driver's step seam."""

    def __init__(
        self,
        backend: str,
        step_timeout: Optional[float],
        mu_limit: float,
        injector: Optional[FaultInjector],
        adaptive: Optional[AdaptiveDeadline] = None,
        mesh_ids_fn=None,
        pending_fault: Optional[FaultRecord] = None,
        events: Optional[IterLogger] = None,
    ):
        self.backend = backend
        self.step_timeout = step_timeout
        self.mu_limit = mu_limit
        self.injector = injector
        self.adaptive = adaptive
        # Lazy: the mesh exists only after the backend's setup ran inside
        # solve(), which is after this hooks object was constructed.
        self.mesh_ids_fn = mesh_ids_fn or (lambda: None)
        # The fault this attempt is recovering from; cleared (and its
        # recovery overhead recorded) when the first iteration lands.
        self.pending_fault = pending_fault
        self.events = events

    def _deadline(self) -> Optional[float]:
        if self.adaptive is not None:
            return self.adaptive.current()
        return self.step_timeout

    def run_step(self, step_fn, iteration: int):
        if self.injector is not None:
            step_fn = self.injector.wrap_step(
                step_fn, iteration, self.backend, self.mesh_ids_fn()
            )
        t0 = time.perf_counter()
        out = run_with_deadline(step_fn, self._deadline(), iteration)
        if self.adaptive is not None:
            # Only completed steps feed the estimate — see
            # AdaptiveDeadline.observe on why timeouts must not.
            self.adaptive.observe(time.perf_counter() - t0)
        return out

    def on_iterate(self, iteration: int, scalars: dict) -> None:
        if self.pending_fault is not None:
            # First completed post-resume iteration: the recovery path's
            # wall-clock cost is now known — record it on the fault and
            # in the telemetry stream (satellite: post-mortems attribute
            # wall-clock loss without diffing timestamps by hand).
            overhead = time.time() - self.pending_fault.at_time
            self.pending_fault.recovery_overhead_s = overhead
            obs_metrics.get_registry().histogram(
                "supervisor_recovery_overhead_seconds",
                buckets=obs_metrics.SECONDS_BUCKETS,
                help="fault classification to first post-resume iteration",
            ).observe(overhead)
            obs_trace.get_tracer().instant(
                "supervisor.resume",
                args={
                    "backend": self.backend,
                    "action": self.pending_fault.action,
                    "recovery_overhead_s": round(overhead, 6),
                },
                cat="supervisor",
            )
            if self.events is not None:
                self.events.event(
                    {
                        "event": "resume",
                        "iteration": iteration,
                        "backend": self.backend,
                        "action": self.pending_fault.action,
                        "recovery_overhead_s": round(overhead, 6),
                    }
                )
            self.pending_fault = None
        bad = [
            k
            for k in _GUARDED_SCALARS
            if not np.isfinite(scalars.get(k, np.nan))
        ]
        if bad:
            raise IterateHealthFault(
                iteration,
                f"non-finite scalars {bad} "
                f"(mu={scalars.get('mu')!r})",
            )
        if scalars["mu"] > self.mu_limit:
            raise IterateHealthFault(
                iteration, f"mu={scalars['mu']:.3e} exceeds {self.mu_limit:g}"
            )


def supervised_solve(
    problem,
    backend: Union[str, object] = "auto",
    config: Optional[SolverConfig] = None,
    supervisor: Optional[SupervisorConfig] = None,
    warm_start=None,
    warm_cache=None,
    **config_overrides,
) -> IPMResult:
    """Solve under the supervisor; same contract as ``ipm.solve`` plus
    fault tolerance. Returns an :class:`IPMResult` whose ``faults`` lists
    every fault survived, or raises :class:`SolveFailure` when the
    recovery ladder and retry budget are exhausted. Terminal non-OPTIMAL
    statuses that are *answers* (infeasible, unbounded, iteration limit)
    return as-is — only faults trigger recovery.

    ``warm_start``/``warm_cache`` thread straight through to
    ``ipm.solve`` (ipm/warm.py): the first attempt may start from a
    safeguarded prior iterate; retries always resume via the rollback
    checkpoint instead (a warm start implicated in a numerical fault
    must not be re-offered).
    """
    from distributedlpsolver_tpu.backends.base import get_backend

    sup = supervisor or SupervisorConfig()
    base_cfg = config or SolverConfig()
    if config_overrides:
        base_cfg = base_cfg.replace(**config_overrides)

    tmpdir = None
    ckpt_path = sup.checkpoint_path or base_cfg.checkpoint_path
    if not ckpt_path:
        tmpdir = tempfile.mkdtemp(prefix="dlps-supervisor-")
        ckpt_path = os.path.join(tmpdir, "rollback.npz")
    base_cfg = base_cfg.replace(
        checkpoint_path=ckpt_path,
        checkpoint_every=base_cfg.checkpoint_every or sup.snapshot_every,
        fused_loop=False,  # supervision needs per-iteration boundaries
        # Attempts append to the telemetry stream; the supervisor
        # truncated it once below, so retries (and the supervisor's own
        # fault/resume events) extend one post-mortem-readable file.
        log_append=bool(base_cfg.log_jsonl),
    )

    events: Optional[IterLogger] = None
    if base_cfg.log_jsonl:
        open(base_cfg.log_jsonl, "w").close()  # one truncation, up front
        events = IterLogger(
            verbose=False,
            jsonl_path=base_cfg.log_jsonl,
            fsync=base_cfg.log_fsync,
            append=True,
        )

    if isinstance(backend, str):
        current_name = backend
        be = get_backend(backend)
    else:
        be = backend
        current_name = getattr(backend, "name", "custom")
    injector = FaultInjector(sup.fault_plan) if sup.fault_plan else None
    adaptive = (
        AdaptiveDeadline(
            multiplier=sup.timeout_multiplier,
            floor=sup.timeout_floor,
            ceiling=sup.timeout_ceiling,
            window=sup.timeout_window,
            warmup=sup.timeout_warmup,
            static_hint=sup.step_timeout or None,
        )
        if sup.adaptive_timeout
        else None
    )
    faults: List[FaultRecord] = []
    # Hang suspicion per device id (health-probe attribution); a device
    # reaching hang_shard_threshold is treated as lost.
    suspects: Dict[int, int] = {}
    attempt_cfg = base_cfg
    rung = 0
    pending: Optional[FaultRecord] = None  # fault being recovered from

    try:
        while True:
            hooks = _SupervisorHooks(
                current_name,
                sup.step_timeout,
                sup.mu_limit,
                injector,
                adaptive=adaptive,
                mesh_ids_fn=lambda: _mesh_ids(be),
                pending_fault=pending,
                events=events,
            )
            # The hooks object owns the pending fault now (it records the
            # recovery overhead when the first iteration lands); a fault
            # in THIS attempt supersedes it below.
            pending = None
            fault = None
            lost_ids: set = set()
            try:
                result = solve(
                    problem,
                    backend=be,
                    config=attempt_cfg,
                    warm_start=warm_start,
                    hooks=hooks,
                    warm_cache=warm_cache,
                )
                if result.status is not Status.NUMERICAL_ERROR:
                    result.faults = faults
                    return result
                fault = FaultRecord(
                    FaultKind.NUMERICAL,
                    result.iterations,
                    current_name,
                    "driver returned numerical_error "
                    "(regularization headroom exhausted)",
                )
            except StepDeadlineExceeded as e:
                fault = FaultRecord(
                    FaultKind.HANG, e.iteration, current_name, str(e)
                )
            except InjectedDeviceLoss as e:
                fault = FaultRecord(
                    FaultKind.DEVICE_LOST,
                    e.iteration,
                    current_name,
                    str(e),
                    devices=tuple(e.device_ids),
                )
                lost_ids.update(e.device_ids)
            except IterateHealthFault as e:
                fault = FaultRecord(
                    FaultKind.NUMERICAL, e.iteration, current_name, str(e)
                )
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                kind = (
                    FaultKind.DEVICE_LOST
                    if _looks_like_device_loss(e)
                    else FaultKind.CRASH
                )
                fault = FaultRecord(
                    kind,
                    getattr(e, "iteration", -1),
                    current_name,
                    f"{type(e).__name__}: {e}",
                )
            fault.at_time = time.time()
            faults.append(fault)
            pending = fault
            warm_start = None  # retries resume via the rollback checkpoint
            warm_cache = None  # and never re-offer a fault-implicated warm start

            if len(faults) > sup.max_retries:
                fault.action = "give_up"
                _emit_fault(events, fault)
                raise SolveFailure(
                    faults, f"retry budget ({sup.max_retries}) exhausted"
                )

            # ---- elastic attribution: who (if anyone) is to blame? -----
            mesh = getattr(be, "mesh", None)
            if mesh is not None and fault.kind in (
                FaultKind.DEVICE_LOST,
                FaultKind.HANG,
            ):
                _, unhealthy = rt.probe_devices(
                    list(mesh.devices.flat), sup.probe_deadline
                )
                probed_ids = {d.id for d in unhealthy}
                if fault.kind is FaultKind.DEVICE_LOST:
                    lost_ids |= probed_ids
                else:  # HANG: count suspicions; promote at the threshold
                    for i in probed_ids:
                        suspects[i] = suspects.get(i, 0) + 1
                    blamed = {
                        i
                        for i, c in suspects.items()
                        if c >= sup.hang_shard_threshold
                    }
                    if blamed:
                        lost_ids |= blamed
                if lost_ids:
                    fault.devices = tuple(sorted(lost_ids))

            # ---- recovery ladder ---------------------------------------
            shrunk = False
            if fault.kind is FaultKind.DEVICE_LOST or lost_ids:
                # A lost device does not come back on retry: go straight
                # to the SHRINK rung; its failure falls through to
                # degradation, never to rollback-and-hope.
                new_be, old_k, new_k = _shrunk_backend(
                    be, lost_ids, sup.min_devices
                )
                if new_be is not None:
                    fault.action = f"shrink:{old_k}->{new_k}"
                    be = new_be
                    rung = 0  # fresh ladder for the re-formed mesh
                    suspects.clear()
                    if adaptive is not None:
                        # Shrunk shapes recompile; re-open the grace
                        # window but keep the (still relevant) cadence.
                        adaptive.grant_grace()
                    shrunk = True
                else:
                    rung = _RUNG_RECENTER + 1  # force the degrade rung

            if not shrunk:
                if rung == _RUNG_ROLLBACK:
                    fault.action = "rollback"
                elif rung == _RUNG_REG_BUMP:
                    fault.action = "rollback+reg_bump"
                    attempt_cfg = attempt_cfg.replace(
                        reg_primal=attempt_cfg.reg_primal * sup.reg_bump,
                        reg_dual=attempt_cfg.reg_dual * sup.reg_bump,
                    )
                elif rung == _RUNG_RECENTER:
                    fault.action = "recenter"
                    _remove_quiet(ckpt_path)  # fresh, well-centered start
                else:
                    # Rung overflow. SHRINK sits above degradation: a mesh
                    # backend whose ladder is exhausted gets one health
                    # probe, and any unhealthy participant is shrunk out
                    # before the pod is abandoned for the next backend.
                    mesh = getattr(be, "mesh", None)
                    new_be = None
                    if mesh is not None:
                        _, unhealthy = rt.probe_devices(
                            list(mesh.devices.flat), sup.probe_deadline
                        )
                        if unhealthy:
                            new_be, old_k, new_k = _shrunk_backend(
                                be,
                                {d.id for d in unhealthy},
                                sup.min_devices,
                            )
                    if new_be is not None:
                        fault.action = f"shrink:{old_k}->{new_k}"
                        fault.devices = tuple(
                            sorted(d.id for d in unhealthy)
                        )
                        be = new_be
                        rung = -1  # += 1 below: fresh ladder on the new mesh
                        suspects.clear()
                        if adaptive is not None:
                            adaptive.grant_grace()
                    else:
                        nxt = (
                            _next_backend(current_name, faults)
                            if sup.degrade
                            else None
                        )
                        if nxt is None:
                            fault.action = "give_up"
                            _emit_fault(events, fault)
                            raise SolveFailure(
                                faults,
                                f"recovery ladder exhausted on backend "
                                f"{current_name!r} and no degradation "
                                "target remains",
                            )
                        fault.action = f"degrade:{nxt}"
                        current_name = nxt
                        be = get_backend(nxt)
                        attempt_cfg = base_cfg  # reset reg escalation
                        rung = -1  # += 1 below: fresh ladder, new backend
                        suspects.clear()
                        if adaptive is not None:
                            # New backend = new step-time regime: the old
                            # cadence would mis-size the first deadlines.
                            adaptive.reset()
                rung += 1
            _emit_fault(events, fault)
            _backoff(sup, len(faults))
    finally:
        if events is not None:
            events.close()
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def _emit_fault(events: Optional[IterLogger], fault: FaultRecord) -> None:
    # Metrics/trace first: faults must be counted (and visible on the
    # trace timeline) even when no JSONL stream is configured.
    obs_metrics.get_registry().counter(
        "supervisor_faults_total", labels={"kind": fault.kind.value},
        help="faults classified by the solve supervisor",
    ).inc()
    obs_metrics.get_registry().counter(
        "supervisor_recoveries_total",
        labels={"action": fault.action.split(":")[0] or "none"},
        help="recovery-ladder actions taken (rung family)",
    ).inc()
    obs_trace.get_tracer().instant(
        "supervisor.fault",
        args={
            "kind": fault.kind.value,
            "backend": fault.backend,
            "action": fault.action,
            "iteration": fault.iteration,
        },
        cat="supervisor",
    )
    if events is None:
        return
    import jax

    events.event(
        {
            "event": "fault",
            "kind": fault.kind.value,
            "iteration": fault.iteration,
            "backend": fault.backend,
            "action": fault.action,
            "devices": list(fault.devices),
            "detail": fault.detail[:300],
            "t": fault.at_time,
            # Which PROCESS observed/attributed this fault: device probes
            # only ever ping addressable devices (parallel/runtime.py), so
            # under a multi-process world the device list above is this
            # rank's local evidence, not a global verdict.
            "rank": jax.process_index(),
        }
    )


def _mesh_ids(be) -> Optional[tuple]:
    mesh = getattr(be, "mesh", None)
    if mesh is None:
        return None
    return tuple(d.id for d in mesh.devices.flat)


def _shrunk_backend(be, exclude_ids, min_devices: int):
    """(new_backend, old_count, new_count) for the SHRINK rung, or
    (None, 0, 0) when shrinking is not possible: no mesh, nothing to
    exclude, too few survivors, or the backend cannot re-place itself."""
    mesh = getattr(be, "mesh", None)
    if mesh is None or not exclude_ids:
        return None, 0, 0
    devs = list(mesh.devices.flat)
    survivors = [d for d in devs if d.id not in exclude_ids]
    if len(survivors) == len(devs):
        return None, 0, 0  # none of the excluded ids are in this mesh
    if len(survivors) < max(1, min_devices):
        return None, 0, 0
    new_mesh = mesh_lib.reform_mesh(mesh, exclude=exclude_ids)
    new_be = be.reshard(new_mesh)
    if new_be is None:
        return None, 0, 0
    return new_be, len(devs), len(survivors)


def _next_backend(current: str, faults: List[FaultRecord]) -> Optional[str]:
    from distributedlpsolver_tpu.backends.auto import degradation_chain

    tried = {f.backend for f in faults} | {current}
    for name in degradation_chain(current):
        if name not in tried:
            return name
    return None


def _backoff(sup: SupervisorConfig, n_faults: int) -> None:
    if sup.backoff_base > 0:
        time.sleep(
            min(sup.backoff_max, sup.backoff_base * 2 ** (n_faults - 1))
        )


def _remove_quiet(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass
