"""Solve supervisor: watchdog + health guards + rollback-and-degrade.

Wraps ``ipm.driver.solve`` in a fault-tolerance loop so a solve survives
the failure classes a benchmark artifact can ignore but a serving system
cannot (ROUND5_NOTES.md: a hung dispatch wedging a worker for ≥1h two
iterations from optimal; program classes that crash the worker outright):

1. **Dispatch watchdog** — every device step runs under a deadline
   (supervisor/watchdog.py); a step that blows it is ``FaultKind.HANG``.
2. **Iterate health guards** — the host-side convergence scalars are
   checked every iteration; non-finite values or exploding μ are
   ``FaultKind.NUMERICAL`` before the driver grinds on a poisoned iterate.
3. **Recovery ladder** — on any fault the supervisor rolls back to the
   last good checkpoint and retries with exponential backoff, escalating
   per backend: plain rollback → rollback + regularization bump →
   re-center (fresh well-centered starting point) → degrade to the next
   backend in ``backends.auto.DEGRADATION_CHAIN``. When the ladder and the
   retry budget are both exhausted it raises a structured
   :class:`SolveFailure` carrying the ordered fault history — never a
   silent wedge, never a bare traceback.

Rollback reuses the existing checkpoint machinery (utils/checkpoint.py):
the supervisor forces per-iteration checkpointing to a (temp, unless
configured) path, and each retry resumes through the driver's normal
checkpoint-resume path — fingerprint-guarded, so a rollback can never
resume into a different problem's iterate.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time
from typing import List, Optional, Union

import numpy as np

from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.driver import SolveHooks, solve
from distributedlpsolver_tpu.ipm.state import (
    FaultKind,
    FaultRecord,
    IPMResult,
    Status,
)
from distributedlpsolver_tpu.supervisor.faults import FaultInjector, InjectedFault
from distributedlpsolver_tpu.supervisor.watchdog import (
    StepDeadlineExceeded,
    run_with_deadline,
)


class IterateHealthFault(RuntimeError):
    """An iterate's host-side scalars failed the health guard."""

    def __init__(self, iteration: int, detail: str):
        self.iteration = iteration
        super().__init__(f"iteration {iteration}: {detail}")


class SolveFailure(RuntimeError):
    """Terminal supervisor outcome: recovery exhausted.

    Carries the full ordered fault history (``faults``) so a post-mortem
    reads what happened and what was tried without log spelunking.
    """

    def __init__(self, faults: List[FaultRecord], detail: str):
        self.faults = list(faults)
        self.status = Status.FAILED
        trail = " -> ".join(
            f"{f.kind.value}@it{f.iteration}[{f.backend}]" for f in faults
        )
        super().__init__(f"{detail}; fault history: {trail or '(none)'}")


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Tunables of the fault-tolerance loop (CLI: --supervise flags)."""

    # Watchdog deadline per device step, seconds. None/0 disables the
    # watchdog (guards and crash recovery still run). Size it ~10× the
    # expected step time: a 15 s/iter 10k endgame wants ~180 s, a CPU test
    # problem 0.5 s.
    step_timeout: Optional[float] = None
    max_retries: int = 6  # total recovery attempts before SolveFailure
    snapshot_every: int = 1  # rollback checkpoint cadence (iterations)
    backoff_base: float = 0.05  # seconds; doubles per fault
    backoff_max: float = 5.0
    mu_limit: float = 1e30  # exploding-μ guard threshold
    reg_bump: float = 1e4  # regularization multiplier on the bump rung
    degrade: bool = True  # allow backend degradation
    # Rollback checkpoint path; None = a temp file, removed on success.
    checkpoint_path: Optional[str] = None
    # Deterministic fault injection (tests): a list of InjectedFault.
    fault_plan: Optional[List[InjectedFault]] = None


# Ladder rungs per backend, in escalation order.
_RUNG_ROLLBACK, _RUNG_REG_BUMP, _RUNG_RECENTER = 0, 1, 2

_GUARDED_SCALARS = ("mu", "gap", "rel_gap", "pinf", "dinf", "pobj", "dobj")


class _SupervisorHooks(SolveHooks):
    """Watchdog + health guard + injection at the driver's step seam."""

    def __init__(
        self,
        backend: str,
        step_timeout: Optional[float],
        mu_limit: float,
        injector: Optional[FaultInjector],
    ):
        self.backend = backend
        self.step_timeout = step_timeout
        self.mu_limit = mu_limit
        self.injector = injector

    def run_step(self, step_fn, iteration: int):
        if self.injector is not None:
            step_fn = self.injector.wrap_step(step_fn, iteration, self.backend)
        return run_with_deadline(step_fn, self.step_timeout, iteration)

    def on_iterate(self, iteration: int, scalars: dict) -> None:
        bad = [
            k
            for k in _GUARDED_SCALARS
            if not np.isfinite(scalars.get(k, np.nan))
        ]
        if bad:
            raise IterateHealthFault(
                iteration,
                f"non-finite scalars {bad} "
                f"(mu={scalars.get('mu')!r})",
            )
        if scalars["mu"] > self.mu_limit:
            raise IterateHealthFault(
                iteration, f"mu={scalars['mu']:.3e} exceeds {self.mu_limit:g}"
            )


def supervised_solve(
    problem,
    backend: Union[str, object] = "auto",
    config: Optional[SolverConfig] = None,
    supervisor: Optional[SupervisorConfig] = None,
    warm_start=None,
    **config_overrides,
) -> IPMResult:
    """Solve under the supervisor; same contract as ``ipm.solve`` plus
    fault tolerance. Returns an :class:`IPMResult` whose ``faults`` lists
    every fault survived, or raises :class:`SolveFailure` when the
    recovery ladder and retry budget are exhausted. Terminal non-OPTIMAL
    statuses that are *answers* (infeasible, unbounded, iteration limit)
    return as-is — only faults trigger recovery.
    """
    sup = supervisor or SupervisorConfig()
    base_cfg = config or SolverConfig()
    if config_overrides:
        base_cfg = base_cfg.replace(**config_overrides)

    tmpdir = None
    ckpt_path = sup.checkpoint_path or base_cfg.checkpoint_path
    if not ckpt_path:
        tmpdir = tempfile.mkdtemp(prefix="dlps-supervisor-")
        ckpt_path = os.path.join(tmpdir, "rollback.npz")
    base_cfg = base_cfg.replace(
        checkpoint_path=ckpt_path,
        checkpoint_every=base_cfg.checkpoint_every or sup.snapshot_every,
        fused_loop=False,  # supervision needs per-iteration boundaries
    )

    current = backend if isinstance(backend, str) else getattr(backend, "name", "custom")
    injector = FaultInjector(sup.fault_plan) if sup.fault_plan else None
    faults: List[FaultRecord] = []
    attempt_cfg = base_cfg
    rung = 0

    try:
        while True:
            hooks = _SupervisorHooks(
                current, sup.step_timeout, sup.mu_limit, injector
            )
            fault = None
            try:
                result = solve(
                    problem,
                    backend=current,
                    config=attempt_cfg,
                    warm_start=warm_start,
                    hooks=hooks,
                )
                if result.status is not Status.NUMERICAL_ERROR:
                    result.faults = faults
                    return result
                fault = FaultRecord(
                    FaultKind.NUMERICAL,
                    result.iterations,
                    current,
                    "driver returned numerical_error "
                    "(regularization headroom exhausted)",
                )
            except StepDeadlineExceeded as e:
                fault = FaultRecord(FaultKind.HANG, e.iteration, current, str(e))
            except IterateHealthFault as e:
                fault = FaultRecord(
                    FaultKind.NUMERICAL, e.iteration, current, str(e)
                )
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                fault = FaultRecord(
                    FaultKind.CRASH,
                    getattr(e, "iteration", -1),
                    current,
                    f"{type(e).__name__}: {e}",
                )
            fault.at_time = time.time()
            faults.append(fault)
            warm_start = None  # retries resume via the rollback checkpoint

            if len(faults) > sup.max_retries:
                fault.action = "give_up"
                raise SolveFailure(
                    faults, f"retry budget ({sup.max_retries}) exhausted"
                )

            # Escalation ladder for the current backend.
            if rung == _RUNG_ROLLBACK:
                fault.action = "rollback"
            elif rung == _RUNG_REG_BUMP:
                fault.action = "rollback+reg_bump"
                attempt_cfg = attempt_cfg.replace(
                    reg_primal=attempt_cfg.reg_primal * sup.reg_bump,
                    reg_dual=attempt_cfg.reg_dual * sup.reg_bump,
                )
            elif rung == _RUNG_RECENTER:
                fault.action = "recenter"
                _remove_quiet(ckpt_path)  # fresh, well-centered start
            else:
                nxt = _next_backend(current, faults) if sup.degrade else None
                if nxt is None:
                    fault.action = "give_up"
                    raise SolveFailure(
                        faults,
                        f"recovery ladder exhausted on backend {current!r} "
                        "and no degradation target remains",
                    )
                fault.action = f"degrade:{nxt}"
                current = nxt
                attempt_cfg = base_cfg  # reset reg escalation on a new backend
                rung = -1  # restart the ladder for the new backend
            rung += 1
            _backoff(sup, len(faults))
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def _next_backend(current: str, faults: List[FaultRecord]) -> Optional[str]:
    from distributedlpsolver_tpu.backends.auto import degradation_chain

    tried = {f.backend for f in faults} | {current}
    for name in degradation_chain(current):
        if name not in tried:
            return name
    return None


def _backoff(sup: SupervisorConfig, n_faults: int) -> None:
    if sup.backoff_base > 0:
        time.sleep(
            min(sup.backoff_max, sup.backoff_base * 2 ** (n_faults - 1))
        )


def _remove_quiet(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass
