"""Command-line driver: ``solve <file.mps> --backend=<name>``.

The reference's top layer is a CLI that parses flags (including backend
selection via ``--backend=``, BASELINE.json:5), loads the problem, runs
the solver, and reports iterations/gap/wall-clock (the published metric
surface, BASELINE.json:2). Subcommands:

    solve       solve an MPS file (or a generated problem) to tolerance
    serve       async batching solve service (JSONL/MPS requests in)
    serve-http  HTTP front-end over the solve service (POST /v1/solve,
                /metrics, /healthz, /statusz; README "Network serving")
    route       router tier over serve-http backends (shape/load-aware
                routing, health-checked failover)
    autotune    refine a serve bucket ladder from telemetry JSONL
    obs-agg     fleet telemetry aggregator: cross-process trace merge +
                hedge-ledger/record/journal reconciliation
    check       graftcheck static-analysis suite (the tier-1 CI gate)
    backends    list registered SolverBackend names
    generate    write a generated benchmark problem to MPS

Run as ``python -m distributedlpsolver_tpu.cli ...``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _add_solver_flags(ap: argparse.ArgumentParser) -> None:
    ap.add_argument(
        "--backend",
        default="auto",
        help="SolverBackend name (auto = pick by problem size/structure)",
    )
    ap.add_argument("--tol", type=float, default=1e-8, help="relative gap/infeasibility tolerance")
    ap.add_argument("--max-iter", type=int, default=200)
    ap.add_argument("--quiet", action="store_true", help="suppress per-iteration log")
    ap.add_argument("--log-jsonl", default=None, help="write per-iteration JSONL here")
    ap.add_argument("--checkpoint", default=None, help="iterate checkpoint path")
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--profile-dir", default=None, help="jax.profiler trace directory")
    ap.add_argument(
        "--factor-dtype",
        default="auto",
        help="Cholesky dtype: auto = f32→f64 two-phase on TPU; or float32/float64",
    )
    ap.add_argument(
        "--no-presolve",
        action="store_true",
        help="disable structural presolve (singleton/redundant rows, fixed cols)",
    )
    ap.add_argument(
        "--no-scale", action="store_true", help="disable Ruiz equilibration"
    )
    ap.add_argument("--json", action="store_true", help="print result as one JSON object")
    ap.add_argument("--x-out", default=None, help="write solution vector as .npy")
    ap.add_argument(
        "--log-fsync",
        action="store_true",
        help="fsync the JSONL log after each record (crash-proof telemetry)",
    )
    ap.add_argument(
        "--supervise",
        action="store_true",
        help="run under the solve supervisor (watchdog + rollback + "
        "backend degradation; see README 'Fault tolerance')",
    )
    ap.add_argument(
        "--step-timeout",
        type=float,
        default=0.0,
        help="watchdog deadline per device step in seconds (0 = no "
        "watchdog; implies --supervise when set)",
    )
    ap.add_argument(
        "--max-retries",
        type=int,
        default=6,
        help="supervisor recovery attempts before a structured failure",
    )
    ap.add_argument(
        "--adaptive-timeout",
        action="store_true",
        help="size the watchdog deadline adaptively (10x the trailing "
        "median step time, clamped, with compile-grace) instead of the "
        "static --step-timeout; implies --supervise",
    )
    ap.add_argument(
        "--min-devices",
        type=int,
        default=1,
        help="smallest mesh the elastic SHRINK recovery may re-form "
        "after device loss before degrading to the next backend",
    )
    ap.add_argument(
        "--jax-cache-dir",
        default=None,
        help="persistent JAX/XLA compilation cache directory — restarts "
        "skip every compile cached by an earlier run (cold-bucket serve "
        "compiles included); logs a hit/miss line at startup",
    )
    ap.add_argument(
        "--metrics-path",
        default=None,
        help="enable the obs/ metrics registry and write a "
        "Prometheus-text snapshot here at exit (README 'Observability')",
    )
    ap.add_argument(
        "--trace-path",
        default=None,
        help="enable the obs/ span tracer and write a Chrome-trace JSON "
        "here at exit (open at ui.perfetto.dev)",
    )


def _apply_jax_cache(args) -> None:
    """Point JAX's persistent compilation cache at --jax-cache-dir (wins
    over the package default) and log the startup hit/miss line."""
    d = getattr(args, "jax_cache_dir", None)
    if not d:
        return
    import os

    import jax

    os.makedirs(d, exist_ok=True)
    n = sum(1 for f in os.listdir(d) if not f.startswith("."))
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    print(
        f"jax compilation cache: {d} — {n} cached programs "
        f"({'warm start, cold compiles will be cache hits' if n else 'cold start, compiles will be cached'})",
        file=sys.stderr,
    )


def _obs_setup(args):
    """Install a process-wide metrics registry / span tracer when
    --metrics-path / --trace-path are given (every layer — driver,
    supervisor, serve, batched backend — resolves the module defaults,
    so one switch instruments the whole process). Returns a finalizer
    that writes both artifacts and restores the no-op defaults."""
    from distributedlpsolver_tpu.obs import metrics as obs_metrics
    from distributedlpsolver_tpu.obs import trace as obs_trace

    reg = tracer = None
    if getattr(args, "metrics_path", None):
        reg = obs_metrics.MetricsRegistry()
        obs_metrics.set_registry(reg)
    if getattr(args, "trace_path", None):
        tracer = obs_trace.Tracer(args.trace_path)
        obs_trace.set_tracer(tracer)

    def finalize():
        if reg is not None:
            reg.write_prometheus(args.metrics_path)
            obs_metrics.set_registry(None)
            print(f"metrics snapshot -> {args.metrics_path}", file=sys.stderr)
        if tracer is not None:
            tracer.close()
            obs_trace.set_tracer(None)
            print(
                f"trace ({tracer.event_count()} events) -> "
                f"{args.trace_path} (open at ui.perfetto.dev)",
                file=sys.stderr,
            )

    return finalize


def _follower_obs_setup(world, metrics: bool, trace: bool):
    """Observability for a nonzero slice rank: install a process-wide
    registry/tracer and export into the world heartbeat dir under
    per-rank names (``rank<k>.metrics.json`` refreshed on the heartbeat
    cadence — the JSON snapshot form the fleet aggregator scans, rank
    and identity stamped alongside; ``rank<k>.trace.json`` at exit).
    Returns a finalizer; no-op when neither flag is set or the world
    has no heartbeat dir."""
    import os
    import threading

    hb_dir = world.cfg.heartbeat_dir
    if hb_dir is None or not (metrics or trace):
        return lambda: None

    from distributedlpsolver_tpu.obs import metrics as obs_metrics
    from distributedlpsolver_tpu.obs import trace as obs_trace

    os.makedirs(hb_dir, exist_ok=True)
    reg = tracer = None
    stop = threading.Event()
    snap_path = os.path.join(hb_dir, f"rank{world.rank}.metrics.json")

    def write_snapshot():
        doc = {
            "rank": world.rank,
            "pid": os.getpid(),
            "generation": world.cfg.generation,
            "slice_id": world.cfg.slice_id,
            "metrics": reg.snapshot(),
        }
        tmp = f"{snap_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, snap_path)

    if metrics:
        reg = obs_metrics.MetricsRegistry()
        obs_metrics.set_registry(reg)
        period = max(float(world.cfg.heartbeat_period_s), 0.25)

        def snap_loop():
            while not stop.wait(period):
                try:
                    write_snapshot()
                except OSError:
                    pass  # snapshot export must never kill the rank

        threading.Thread(
            target=snap_loop, daemon=True, name="dlps-rank-metrics"
        ).start()
    if trace:
        tracer = obs_trace.Tracer(
            os.path.join(hb_dir, f"rank{world.rank}.trace.json"),
            process_name=f"dlps-rank{world.rank}",
        )
        obs_trace.set_tracer(tracer)

    def finalize():
        stop.set()
        if reg is not None:
            try:
                write_snapshot()
            except OSError:
                pass
            obs_metrics.set_registry(None)
        if tracer is not None:
            tracer.close()
            obs_trace.set_tracer(None)

    return finalize


def _config_from(args) -> "SolverConfig":
    from distributedlpsolver_tpu.ipm.config import SolverConfig

    return SolverConfig(
        tol=args.tol,
        max_iter=args.max_iter,
        verbose=not args.quiet,
        log_jsonl=args.log_jsonl,
        checkpoint_path=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        profile_dir=args.profile_dir,
        factor_dtype=args.factor_dtype,
        presolve=not args.no_presolve,
        scale=not args.no_scale,
        log_fsync=args.log_fsync,
    )


def _report(result, as_json: bool, x_out: Optional[str]) -> int:
    if x_out and result.x is not None:
        import numpy as np

        np.save(x_out, result.x)
    if as_json:
        print(
            json.dumps(
                {
                    "name": result.name,
                    "status": result.status.value,
                    "objective": result.objective,
                    "iterations": result.iterations,
                    "rel_gap": result.rel_gap,
                    "pinf": result.pinf,
                    "dinf": result.dinf,
                    "solve_time_s": result.solve_time,
                    "setup_time_s": result.setup_time,
                    "iters_per_sec": result.iters_per_sec,
                    "backend": result.backend,
                    "faults": [f.asdict() for f in result.faults],
                }
            )
        )
    else:
        print(result.summary())
    from distributedlpsolver_tpu.ipm.state import Status

    return 0 if result.status == Status.OPTIMAL else 2


def cmd_solve(args) -> int:
    from distributedlpsolver_tpu.io.mps import read_mps

    _apply_jax_cache(args)
    finalize_obs = _obs_setup(args)
    try:
        return _cmd_solve_inner(args, read_mps(args.file))
    finally:
        finalize_obs()


def _cmd_solve_inner(args, problem) -> int:
    cfg = _config_from(args)
    if args.supervise or args.step_timeout > 0 or args.adaptive_timeout:
        from distributedlpsolver_tpu.supervisor import (
            SolveFailure,
            SupervisorConfig,
            supervised_solve,
        )

        sup = SupervisorConfig(
            step_timeout=args.step_timeout or None,
            adaptive_timeout=args.adaptive_timeout,
            max_retries=args.max_retries,
            min_devices=args.min_devices,
        )
        try:
            result = supervised_solve(
                problem, backend=args.backend, config=cfg, supervisor=sup
            )
        except SolveFailure as e:
            payload = {
                "name": problem.name,
                "status": e.status.value,
                "error": str(e),
                "faults": [f.asdict() for f in e.faults],
            }
            if args.json:
                print(json.dumps(payload))
            else:
                print(f"{problem.name}: FAILED — {e}", file=sys.stderr)
            return 3
    else:
        from distributedlpsolver_tpu.ipm import solve

        result = solve(problem, backend=args.backend, config=cfg)
    return _report(result, args.json, args.x_out)


def _iter_request_specs(args):
    """Yield request-spec dicts from --requests (JSONL file or '-' =
    stdin) or --dir (sorted *.mps files, each one request, plus *.jsonl
    files of specs). A spec is ``{"mps": path}`` or
    ``{"m": .., "n": .., "seed": ..}`` (generated standard-form), plus
    optional ``"id"``, ``"tol"``, ``"deadline_s"``."""
    import os

    if args.dir:
        for fname in sorted(os.listdir(args.dir)):
            path = os.path.join(args.dir, fname)
            if fname.endswith(".mps") or fname.endswith(".mps.gz"):
                yield {"mps": path, "id": fname}
            elif fname.endswith(".jsonl"):
                with open(path) as fh:
                    for line in fh:
                        if line.strip():
                            yield json.loads(line)
        return
    fh = sys.stdin if args.requests == "-" else open(args.requests)
    try:
        for line in fh:
            if line.strip():
                yield json.loads(line)
    finally:
        if fh is not sys.stdin:
            fh.close()


def _admission_from(args):
    """AdmissionConfig from ``--quotas`` (inline JSON or ``@file``):
    ``{"tenants": {"acme": {"rate": 10, "burst": 20, "weight": 2}},
    "default": {...}, "fair_start": 0.5}``. None when the flag is
    absent — the classic depth-only admission."""
    spec = getattr(args, "quotas", None)
    if not spec:
        return None
    from distributedlpsolver_tpu.net.admission import (
        AdmissionConfig,
        TenantQuota,
    )

    if spec.startswith("@"):
        with open(spec[1:]) as fh:
            spec = fh.read()
    cfg = json.loads(spec)

    def _quota(d: dict) -> TenantQuota:
        return TenantQuota(
            rate=float(d.get("rate", float("inf"))),
            burst=float(d.get("burst", float("inf"))),
            weight=float(d.get("weight", 1.0)),
        )

    kwargs = {
        "quotas": {
            t: _quota(q) for t, q in (cfg.get("tenants") or {}).items()
        },
    }
    if "default" in cfg:
        kwargs["default_quota"] = _quota(cfg["default"])
    if "fair_start" in cfg:
        kwargs["fair_start"] = float(cfg["fair_start"])
    if "priority_flush_scale" in cfg:
        kwargs["priority_flush_scale"] = {
            k: float(v) for k, v in cfg["priority_flush_scale"].items()
        }
    return AdmissionConfig(**kwargs)


def _brownout_from(args):
    """BrownoutConfig from ``--brownout`` (``on`` for defaults, or
    inline JSON overriding any BrownoutConfig field, e.g.
    ``{"depth_high": 0.6, "engage_after_s": 0.5}``). None when the
    flag is absent — no brownout ladder."""
    spec = getattr(args, "brownout", None)
    if not spec:
        return None
    from distributedlpsolver_tpu.net.admission import BrownoutConfig

    if spec.strip().lower() == "on":
        return BrownoutConfig()
    return BrownoutConfig(**json.loads(spec))


def _service_config_from(args) -> "ServiceConfig":
    """The ServiceConfig both ``serve`` and ``serve-http`` build from
    the shared serving flags."""
    from distributedlpsolver_tpu.serve import ServiceConfig, ladder_from_json

    buckets = None
    if args.buckets:
        with open(args.buckets) as fh:
            buckets = ladder_from_json(fh.read())
    return ServiceConfig(
        buckets=buckets,
        batch=args.batch,
        flush_s=args.flush_ms / 1e3,
        max_queue_depth=args.queue_depth,
        default_deadline_s=args.deadline_s or None,
        log_jsonl=args.log_jsonl,
        mesh_devices=args.mesh_devices,
        warm_start=not args.no_warm_start,
        warm_cache_entries=args.warm_cache_entries,
        solo_backend=getattr(args, "solo_backend", "auto"),
        admission=_admission_from(args),
        journal_dir=getattr(args, "journal_dir", None),
        journal_fsync=getattr(args, "journal_fsync", "flush"),
        brownout=_brownout_from(args),
    )


def cmd_serve(args) -> int:
    """Serve loop: read LP requests, multiplex them through the async
    batching SolveService, write one JSONL result record per request."""
    import time

    from distributedlpsolver_tpu.io.mps import read_mps
    from distributedlpsolver_tpu.models.generators import random_dense_lp
    from distributedlpsolver_tpu.serve import (
        ServiceOverloaded,
        SolveService,
    )

    _apply_jax_cache(args)
    finalize_obs = _obs_setup(args)
    svc_cfg = _service_config_from(args)
    out = sys.stdout if args.out == "-" else open(args.out, "w")
    n_failed = 0
    backoffs = 0
    backoff_s = 0.0
    try:
        with SolveService(svc_cfg, solver_config=_config_from(args).replace(
            verbose=False
        )) as svc:
            submitted = []
            for spec in _iter_request_specs(args):
                if "mps" in spec:
                    problem = read_mps(spec["mps"])
                else:
                    problem = random_dense_lp(
                        int(spec["m"]), int(spec["n"]),
                        seed=int(spec.get("seed", 0)),
                    )
                while True:
                    try:
                        fut = svc.submit(
                            problem,
                            deadline=spec.get("deadline_s"),
                            tol=spec.get("tol"),
                            name=str(spec.get("id", problem.name)),
                            tenant=str(spec.get("tenant", "default")),
                            priority=str(spec.get("priority", "normal")),
                        )
                        break
                    except ServiceOverloaded as e:
                        # Backpressure: the reader outran the solver.
                        # The admission verdict says exactly how long a
                        # retry is pointless for THIS tenant (token
                        # refill / drain window) — sleep that, not a
                        # blind flush tick.
                        # Clamped on both sides: the verdict is already
                        # finite, but a sleep(inf) here would be fatal.
                        wait = min(max(e.retry_after_s, 1e-3), 60.0)
                        backoffs += 1
                        backoff_s += wait
                        time.sleep(wait)
                submitted.append(fut)
            svc.drain()
            from distributedlpsolver_tpu.utils.logging import stamp_record

            for fut in submitted:
                r = fut.result()
                n_failed += r.status.value == "failed"
                # The CLI's result stream rides the same record schema
                # as every IterLogger stream (cli report merges both).
                out.write(json.dumps(stamp_record(r.record())) + "\n")
            out.flush()
            # The summary surfaces rejects: the service stats carry the
            # per-tenant admission table (admitted / rejected-by-reason),
            # and the client-side backoff loop reports how often (and
            # how long) submission was shed back onto it.
            summary = {
                **svc.stats(),
                "submit_backoffs": backoffs,
                "submit_backoff_s": round(backoff_s, 3),
            }
            print(json.dumps(summary), file=sys.stderr)
    finally:
        if out is not sys.stdout:
            out.close()
        finalize_obs()
    return 2 if n_failed else 0


def cmd_serve_http(args) -> int:
    """HTTP front-end: bind a SolveHTTPServer over one SolveService and
    serve until interrupted (README "Network serving")."""
    from distributedlpsolver_tpu.net import NetConfig, SolveHTTPServer
    from distributedlpsolver_tpu.obs import metrics as obs_metrics
    from distributedlpsolver_tpu.serve import SolveService

    _apply_jax_cache(args)
    finalize_obs = _obs_setup(args)
    svc_cfg = _service_config_from(args)
    net_cfg = NetConfig(
        host=args.host,
        port=args.port,
        max_wait_s=args.max_wait_s,
        wedge_s=args.wedge_s,
        log_jsonl=args.net_log_jsonl,
        deadline_propagation=getattr(args, "deadline_propagation", True),
    )
    # A serving process ADVERTISES /metrics, so it always gets a live
    # registry — the zero-cost NULL default is for the in-process
    # library path, not a front-end whose scrape surface would
    # otherwise be permanently empty. --metrics-path (via _obs_setup)
    # installed a process-wide registry already; reuse it so the
    # shutdown snapshot and the scrape agree.
    reg = obs_metrics.get_registry()
    if not reg.enabled:
        reg = obs_metrics.MetricsRegistry()
    try:
        svc = SolveService(
            svc_cfg,
            solver_config=_config_from(args).replace(verbose=False),
            metrics=reg,
            # Warm-up (below) runs BEFORE the pipeline threads start so
            # even journal-replayed work recovered at construction
            # dispatches against compiled programs.
            auto_start=not args.warm_buckets,
        )
        if args.warm_buckets:
            n = svc.warm_buckets(svc.scheduler.table.specs())
            print(f"warmed {n} bucket programs", file=sys.stderr)
        with svc:
            server = SolveHTTPServer(svc, net_cfg).start()
            import threading

            stopped = threading.Event()
            # The /quitquitquit drain path closes the listener, then
            # this callback lets the process exit cleanly.
            server.on_drained = lambda drained: stopped.set()
            # Self-registration + heartbeats into the shared registry —
            # strictly AFTER warm-up and the listener bind, so an
            # elastic rollout never exposes a backend whose bucket
            # ladder isn't compiled yet (the zero-warm-recompile
            # rollout contract; same beat loop as cli serve-slice).
            hb_stop = threading.Event()
            if getattr(args, "registry", None):
                from distributedlpsolver_tpu.net.registry import (
                    BackendRegistry,
                )

                breg = BackendRegistry(
                    args.registry, logger=svc._logger, metrics=reg
                )
                breg.register(server.url)

                def _beat():
                    while not hb_stop.wait(args.heartbeat_s):
                        breg.heartbeat(server.url)

                threading.Thread(
                    target=_beat, daemon=True, name="dlps-http-hb"
                ).start()
            print(
                f"serving on {server.url} "
                f"(POST /v1/solve; GET /metrics /healthz /readyz "
                f"/statusz; POST /quitquitquit drains)",
                file=sys.stderr,
            )
            try:
                stopped.wait()  # serve until SIGINT or drained
                print("drained; exiting", file=sys.stderr)
            except KeyboardInterrupt:
                print("shutting down", file=sys.stderr)
            finally:
                hb_stop.set()
                server.shutdown()
    finally:
        finalize_obs()
    return 0


def cmd_serve_slice(args) -> int:
    """One-service-per-slice multi-host serving (README "Multi-host").

    Without ``--rank``: SUPERVISOR mode — spawn ``--world-size`` rank
    processes of this same command (the single-machine harness of a TPU
    pod slice), watch them, and on world death relaunch a smaller world
    on the same port + journal (coordinator-level recovery; emits
    ``world_reinit`` events with ``recovery_overhead_s``).

    With ``--rank`` (spawned by the supervisor; env contract set by the
    launcher): rank 0 runs the HTTP front-end whose SolveService
    dispatches onto the slice's GLOBAL mesh and self-registers into the
    shared backend registry (``--registry``) with heartbeats; nonzero
    ranks run the follower loop off the slice dispatch journal.
    """
    import os
    import threading
    import time

    if args.rank is None:
        # ---------------- supervisor mode ----------------------------
        import sys as _sys

        from distributedlpsolver_tpu.distributed.launcher import (
            SupervisorConfig,
            WorldSupervisor,
        )

        workdir = args.slice_workdir or os.path.join(
            args.journal_dir or ".", f"slice-{args.slice_id}-world"
        )
        base_argv = [a for a in _sys.argv[1:]]

        def argv_for_gen(generation, world_size, port):
            def argv_for(rank):
                return (
                    [_sys.executable, "-m", "distributedlpsolver_tpu.cli"]
                    + base_argv
                    + ["--rank", str(rank)]
                )

            return argv_for

        sup = WorldSupervisor(
            argv_for_gen,
            world_size=args.world_size,
            workdir=workdir,
            local_devices=args.local_devices,
            config=SupervisorConfig(
                min_world=1,
                max_reforms=args.max_reforms,
                # Own stream, never the ranks' net log: a relaunched
                # rank re-opens (truncates) its log path, which would
                # eat the very world_reinit record describing it.
                log_jsonl=os.path.join(workdir, "world.jsonl"),
            ),
            slice_id=args.slice_id,
        )
        try:
            sup.run(timeout=args.supervise_timeout_s)
        except KeyboardInterrupt:
            if sup.handle is not None:
                sup.handle.kill_all()
            print("slice supervisor: interrupted", file=sys.stderr)
        return 0

    # -------------------- rank mode ----------------------------------
    from distributedlpsolver_tpu.distributed.slice import (
        FileControlPlane,
        SliceRunner,
        canonical_bucket_config,
        follower_loop,
    )
    from distributedlpsolver_tpu.distributed.world import (
        WorldConfig,
        init_world,
    )

    cfg = WorldConfig.from_env()
    world = init_world(cfg)
    world.start_heartbeat()
    ctrl_dir = os.path.join(
        args.control_dir
        or os.path.join(os.environ.get("DLPS_HEARTBEAT_DIR", "."), ".."),
        f"ctrl-gen{cfg.generation}",
    )
    solver_cfg = canonical_bucket_config(_config_from(args))
    try:
        if world.rank != 0:
            # Follower observability (README "Distributed tracing"):
            # every rank spawns from the SAME argv, so --metrics-path /
            # --trace-path name rank-0's artifacts; followers derive
            # per-rank paths in the world heartbeat dir instead —
            # rank<k>.metrics.json snapshots (JSON form, exemplars
            # included — what `cli obs-agg` scans) refreshed on the
            # heartbeat cadence, rank<k>.trace.json at exit.
            finalize_follower = _follower_obs_setup(
                world,
                metrics=bool(getattr(args, "metrics_path", None)),
                trace=bool(getattr(args, "trace_path", None)),
            )
            try:
                n = follower_loop(
                    world, FileControlPlane(ctrl_dir), solver_cfg
                )
            finally:
                finalize_follower()
            print(
                f"slice follower rank {world.rank}: executed {n} "
                f"dispatches; exiting",
                file=sys.stderr,
            )
            return 0

        # ---- rank 0: front-end + scheduler + demux -------------------
        from distributedlpsolver_tpu.net import NetConfig, SolveHTTPServer
        from distributedlpsolver_tpu.obs import metrics as obs_metrics
        from distributedlpsolver_tpu.serve import SolveService

        _apply_jax_cache(args)
        finalize_obs = _obs_setup(args)
        runner = SliceRunner(world, FileControlPlane(ctrl_dir), solver_cfg)
        svc_cfg = _service_config_from(args)
        net_cfg = NetConfig(
            host=args.host,
            port=args.port,
            max_wait_s=args.max_wait_s,
            wedge_s=args.wedge_s,
            log_jsonl=args.net_log_jsonl,
            deadline_propagation=getattr(
                args, "deadline_propagation", True
            ),
        )
        reg = obs_metrics.get_registry()
        if not reg.enabled:
            reg = obs_metrics.MetricsRegistry()
        try:
            svc = SolveService(
                svc_cfg,
                solver_config=solver_cfg,
                metrics=reg,
                auto_start=not args.warm_buckets,
                slice_runner=runner,
            )
            if args.warm_buckets:
                n = svc.warm_buckets(svc.scheduler.table.specs())
                print(
                    f"warmed {n} bucket programs across "
                    f"{world.world_size} ranks",
                    file=sys.stderr,
                )
            with svc:
                server = SolveHTTPServer(svc, net_cfg).start()
                stopped = threading.Event()
                server.on_drained = lambda drained: stopped.set()

                # Self-registration + heartbeats into the shared
                # registry: routers adopt the slice with no manual
                # config and TTL-eject it when the beats stop.
                hb_stop = threading.Event()
                if args.registry:
                    from distributedlpsolver_tpu.net.registry import (
                        BackendRegistry,
                    )

                    breg = BackendRegistry(
                        args.registry,
                        logger=svc._logger,
                        metrics=reg,
                    )
                    breg.register(
                        server.url,
                        slice_id=args.slice_id,
                        world_size=world.world_size,
                    )

                    def _beat():
                        n_beats = 0
                        while not hb_stop.wait(args.heartbeat_s):
                            breg.heartbeat(server.url)
                            n_beats += 1
                            if n_beats % 60 == 0:
                                # Sparse liveness trace: one heartbeat
                                # event a minute-ish, not one per beat.
                                svc._logger.event(
                                    {
                                        "event": "heartbeat",
                                        "rank": 0,
                                        "slice_id": args.slice_id,
                                        "backend": server.url,
                                    }
                                )

                    threading.Thread(
                        target=_beat, daemon=True, name="dlps-slice-hb"
                    ).start()
                print(
                    f"slice {args.slice_id} gen {cfg.generation} serving "
                    f"on {server.url} (world {world.world_size}, "
                    f"{world.describe()['global_devices']} global devices)",
                    file=sys.stderr,
                )
                try:
                    stopped.wait()
                    print("slice drained; exiting", file=sys.stderr)
                except KeyboardInterrupt:
                    print("slice shutting down", file=sys.stderr)
                finally:
                    hb_stop.set()
                    server.shutdown()
                    runner.stop()  # followers exit their loop cleanly
        finally:
            finalize_obs()
        return 0
    finally:
        world.close()


def cmd_route(args) -> int:
    """Router tier: health-checked, shape/load-aware routing over
    serve-http backends (README "Network serving")."""
    from distributedlpsolver_tpu.net.router import (
        Router,
        RouterConfig,
        RouterHTTPServer,
    )
    from distributedlpsolver_tpu.obs import metrics as obs_metrics

    finalize_obs = _obs_setup(args)
    # Same as serve-http: a router process advertises /metrics, so it
    # always runs with a live registry.
    reg = obs_metrics.get_registry()
    if not reg.enabled:
        reg = obs_metrics.MetricsRegistry()
    if not args.backend and not args.registry:
        print(
            "route: need --backend URLs or a --registry slices register "
            "into",
            file=sys.stderr,
        )
        return 2
    router = Router(
        args.backend or [],
        RouterConfig(
            poll_s=args.poll_s,
            eject_after=args.eject_after,
            log_jsonl=args.log_jsonl,
            registry_path=args.registry,
            probe_backoff_cap_s=args.probe_backoff_cap_s,
            registry_ttl_s=args.registry_ttl_s,
            hedge_enabled=args.hedge,
            hedge_rate_cap=args.hedge_rate_cap,
            retry_budget_rate=args.retry_budget,
            retry_budget_burst=args.retry_budget_burst,
            deadline_propagation=args.deadline_propagation,
        ),
        metrics=reg,
    )
    try:
        router.start()
        server = RouterHTTPServer(router, host=args.host, port=args.port)
        server.start()
        print(
            f"routing on {server.url} over {len(args.backend or [])} "
            f"configured backends ({router.healthy_count()} healthy)",
            file=sys.stderr,
        )
        try:
            import threading

            threading.Event().wait()
        except KeyboardInterrupt:
            print("shutting down", file=sys.stderr)
        finally:
            server.shutdown()
    finally:
        router.shutdown()
        finalize_obs()
    return 0


def cmd_elastic(args) -> int:
    """Closed-loop elasticity controller: telemetry-driven backend pool
    autoscaling over the shared registry (README "Elasticity & overload
    protection")."""
    from distributedlpsolver_tpu.obs import metrics as obs_metrics
    from distributedlpsolver_tpu.serve.elastic import (
        ElasticConfig,
        ElasticController,
    )

    finalize_obs = _obs_setup(args)
    reg = obs_metrics.get_registry()
    if not reg.enabled:
        reg = obs_metrics.MetricsRegistry()
    backend_flags = []
    for item in args.backend_flag or []:
        backend_flags.extend(item.split())
    ctl = ElasticController(
        ElasticConfig(
            registry_path=args.registry,
            min_backends=args.min_backends,
            max_backends=args.max_backends,
            poll_s=args.poll_s,
            load_high=args.load_high,
            load_low=args.load_low,
            reject_rate_high=args.reject_rate_high,
            out_sustain_s=args.out_sustain_s,
            in_sustain_s=args.in_sustain_s,
            cooldown_s=args.cooldown_s,
            host=args.host,
            workdir=args.workdir,
            buckets_json=args.buckets,
            backend_flags=tuple(backend_flags),
            heartbeat_s=args.heartbeat_s,
            log_jsonl=args.log_jsonl,
        ),
        metrics=reg,
    )
    try:
        ctl.start()
        print(
            f"elastic controller over {args.registry}: pool "
            f"{args.min_backends}..{args.max_backends}, "
            f"{ctl.pool_size()} up",
            file=sys.stderr,
        )
        try:
            import threading

            threading.Event().wait()
        except KeyboardInterrupt:
            print("draining managed pool", file=sys.stderr)
    finally:
        ctl.shutdown(drain=True)
        finalize_obs()
    return 0


def cmd_autotune(args) -> int:
    """Refine a serve bucket ladder from a telemetry JSONL file and write
    it as a ladder JSON ``cli serve --buckets`` consumes."""
    from distributedlpsolver_tpu.serve import (
        AutotuneConfig,
        autotune_from_jsonl,
        ladder_from_json,
        ladder_to_json,
    )

    current = None
    if args.current:
        with open(args.current) as fh:
            current = ladder_from_json(fh.read())
    specs, report = autotune_from_jsonl(
        args.telemetry,
        current=current,
        config=AutotuneConfig(
            waste_threshold=args.waste_threshold,
            max_programs=args.max_programs,
            batch=args.batch or None,
            devices=args.devices,
        ),
    )
    if not specs:
        print("no bucketed request telemetry found; nothing to tune",
              file=sys.stderr)
        return 2
    with open(args.out, "w") as fh:
        fh.write(ladder_to_json(specs) + "\n")
    print(json.dumps(report))
    return 0


def cmd_report(args) -> int:
    """Merge telemetry JSONL streams (iteration rows, serve records,
    fault/resume events — stamped or legacy) plus JSON metric snapshots
    into per-phase latency breakdowns, padding-waste-by-bucket tables,
    recovery-overhead summaries, and the iters/sec trajectory."""
    import os

    from distributedlpsolver_tpu.obs import report as obs_report

    for p in args.files:
        if not os.path.exists(p):
            print(f"report: {p!r}: file not found", file=sys.stderr)
            return 2
    rep = obs_report.report_from_paths(args.files)
    if args.json:
        print(json.dumps(rep))
    else:
        print(obs_report.render(rep))
    return 0


def cmd_obs_agg(args) -> int:
    """Fleet telemetry aggregator (README "Distributed tracing & fleet
    telemetry"): discover the serving fleet (backend registry + world
    heartbeat dirs + explicit URLs), pull every process's /statusz and
    /metrics, merge per-process trace files into ONE Perfetto trace
    connected by trace_id, surface histogram exemplars, and print the
    reconciliation table lining up the router hedge ledger, the
    backends' request records, and the journals' lifecycle counts."""
    import os

    from distributedlpsolver_tpu.obs import agg as obs_agg

    traces = []
    for spec in args.trace or []:
        # Either label=path or a bare path (label = basename).
        label, sep, path = spec.partition("=")
        if not sep:
            label, path = os.path.basename(spec), spec
        traces.append((label, path))
    fleet, merged = obs_agg.fleet_view(
        registry_path=args.registry,
        heartbeat_dirs=args.heartbeat_dir or [],
        routers=args.router or [],
        backends=args.backend or [],
        traces=traces,
        metrics_json=args.metrics_json or [],
        timeout_s=args.timeout_s,
    )
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        fleet_path = os.path.join(args.out, "fleet.json")
        with open(fleet_path, "w") as fh:
            json.dump(fleet, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"fleet view -> {fleet_path}", file=sys.stderr)
        if merged is not None:
            trace_path = os.path.join(args.out, "trace_merged.json")
            with open(trace_path, "w") as fh:
                json.dump(merged, fh)
                fh.write("\n")
            print(
                f"merged trace ({len(merged['traceEvents'])} events, "
                f"{merged['otherData']['traces_connected']} trace(s) "
                f"connected) -> {trace_path} (open at ui.perfetto.dev)",
                file=sys.stderr,
            )
    if args.json:
        print(json.dumps(fleet))
    else:
        print(obs_agg.render_text(fleet), end="")
    rec = fleet.get("reconciliation") or {}
    return 0 if rec.get("consistent", True) else 1


def cmd_check(args) -> int:
    """graftcheck: run the repo's static-analysis suite (jit/recompile
    hygiene, dtype discipline, lock + static deadlock discipline, SPMD
    discipline, JSONL schema) over the given paths. Exit 0 iff there are
    no unsuppressed findings — this is the tier-1 CI gate (README
    "Static analysis"). With ``--baseline`` the gate is incremental:
    only findings NOT in the committed baseline fail (the cheap
    diff-gate downstream PRs ride; this repo commits an EMPTY baseline).
    Pure stdlib: no jax import, a few seconds on CPU."""
    import os

    from distributedlpsolver_tpu import analysis

    if args.list_rules:
        for name, doc in analysis.all_rules().items():
            print(f"{name}: {doc}")
        return 0
    paths = args.paths or [os.path.dirname(os.path.abspath(__file__))]
    for p in paths:
        if not os.path.exists(p):
            print(f"check: {p!r}: path not found", file=sys.stderr)
            return 2
    rules = args.rules.split(",") if args.rules else None
    try:
        findings = analysis.check_paths(paths, rules=rules)
    except ValueError as e:  # unknown rule name
        print(f"check: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        with open(args.write_baseline, "w") as fh:
            fh.write(analysis.write_baseline(findings) + "\n")
        print(
            f"check: wrote baseline of "
            f"{sum(1 for f in findings if not f.suppressed)} finding(s) "
            f"to {args.write_baseline}",
            file=sys.stderr,
        )
        return 0
    gating = [f for f in findings if not f.suppressed]
    if args.baseline:
        try:
            with open(args.baseline) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"check: --baseline {args.baseline!r}: {e}", file=sys.stderr)
            return 2
        gating = analysis.diff_baseline(findings, doc)
        known = sum(1 for f in findings if not f.suppressed) - len(gating)
        if known:
            print(
                f"check: {known} known finding(s) covered by baseline "
                f"{args.baseline}",
                file=sys.stderr,
            )
    if args.json:
        print(analysis.render_json(findings))
    else:
        print(analysis.render_text(findings, show_suppressed=args.show_suppressed))
    return 1 if gating else 0


def cmd_backends(_args) -> int:
    from distributedlpsolver_tpu.backends import available_backends

    for name in available_backends():
        print(name)
    return 0


def cmd_generate(args) -> int:
    from distributedlpsolver_tpu.io.mps import write_mps
    from distributedlpsolver_tpu.models import generators as gen

    if args.kind == "dense":
        p = gen.random_dense_lp(args.m, args.n, seed=args.seed)
    elif args.kind == "general":
        p = gen.random_general_lp(args.m, args.n, seed=args.seed)
    elif args.kind == "scenario":
        # Lowered two-stage stochastic LP. The hint is not representable
        # in MPS; for sparse-stored ingests (m·n > 200k) `solve
        # --backend auto` recovers it from the sparsity pattern
        # (models/structure.detect_two_stage) and routes back to the
        # scenario engine — smaller files solve on the dense path,
        # which beats device dispatch at that size anyway.
        from distributedlpsolver_tpu.models.scenario import two_stage_storm

        p = two_stage_storm(
            args.scenarios, block_m=args.m, block_n=args.n,
            seed=args.seed,
        ).to_block_angular()
    else:
        p = gen.block_angular_lp(
            args.blocks, args.m, args.n, args.link, seed=args.seed
        )
    write_mps(p, args.out)
    print(f"wrote {p.name} ({p.m}x{p.n}) to {args.out}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="distributedlpsolver_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    ap_solve = sub.add_parser("solve", help="solve an MPS file")
    ap_solve.add_argument("file", help="MPS path (optionally .gz)")
    _add_solver_flags(ap_solve)
    ap_solve.set_defaults(fn=cmd_solve)

    def _add_serving_flags(p) -> None:
        p.add_argument("--batch", type=int, default=16, help="bucket slots")
        p.add_argument(
            "--flush-ms", type=float, default=50.0,
            help="oldest-request age that launches a part-full bucket "
            "(priority classes shade this per request when --quotas "
            "enables the SLO-aware admission layer)",
        )
        p.add_argument(
            "--queue-depth", type=int, default=1024,
            help="admission-control bound on total queued requests "
            "(the global backstop beneath per-tenant quotas)",
        )
        p.add_argument(
            "--deadline-s", type=float, default=0.0,
            help="default per-request deadline (0 = none)",
        )
        p.add_argument(
            "--mesh-devices", type=int, default=0,
            help="shard each bucket dispatch's batch axis over this many "
            "local devices (0/1 = unsharded, -1 = all local devices)",
        )
        p.add_argument(
            "--buckets", default=None,
            help="explicit bucket ladder JSON (the `autotune` output) "
            "instead of auto power-of-two buckets",
        )
        p.add_argument(
            "--solo-backend", default="auto",
            help="solver backend for the per-request solo path "
            "(general-form / retried requests); 'auto' picks by "
            "problem structure (see `backends`)",
        )
        p.add_argument(
            "--no-warm-start", action="store_true",
            help="disable the warm-start & amortization layer (fingerprint "
            "cache + safeguarded warm-started IPM for correlated requests; "
            "README 'Warm-start & amortization')",
        )
        p.add_argument(
            "--warm-cache-entries", type=int, default=512,
            help="bounded LRU capacity of the problem-fingerprint warm cache",
        )
        p.add_argument(
            "--quotas", default=None,
            help="SLO-aware admission policy, inline JSON or @file: "
            '{"tenants": {"acme": {"rate": 10, "burst": 20, '
            '"weight": 2}}, "default": {...}, "fair_start": 0.5} '
            "(README 'Network serving')",
        )
        p.add_argument(
            "--journal-dir", default=None,
            help="durable job journal directory: write-ahead request "
            "log + on-disk async results; a restart against the same "
            "directory replays unfinished work and re-binds poll URLs "
            "(README 'Durability & graceful shutdown')",
        )
        p.add_argument(
            "--journal-fsync", default="flush",
            choices=["none", "flush", "always"],
            help="journal persistence per record: flush survives "
            "kill -9 (default), always additionally fsyncs",
        )
        p.add_argument(
            "--brownout", default=None,
            help="overload brownout ladder: 'on' for defaults, or "
            "inline JSON overriding BrownoutConfig fields, e.g. "
            '{"depth_high": 0.6, "engage_after_s": 0.5} '
            "(README 'Elasticity & overload protection')",
        )

    ap_srv = sub.add_parser(
        "serve",
        help="async batching solve service: JSONL/MPS requests in, "
        "result records out (README 'Serving')",
    )
    src = ap_srv.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--requests", help="JSONL request file, or '-' for stdin"
    )
    src.add_argument(
        "--dir", help="directory of *.mps requests and/or *.jsonl spec files"
    )
    ap_srv.add_argument("--out", default="-", help="result JSONL path ('-' = stdout)")
    _add_serving_flags(ap_srv)
    _add_solver_flags(ap_srv)
    ap_srv.set_defaults(fn=cmd_serve, quiet=True)

    ap_http = sub.add_parser(
        "serve-http",
        help="HTTP front-end over the solve service: POST /v1/solve, "
        "GET /metrics /healthz /statusz (README 'Network serving')",
    )
    ap_http.add_argument("--host", default="127.0.0.1")
    ap_http.add_argument(
        "--port", type=int, default=8080,
        help="bind port (0 = OS-assigned ephemeral)",
    )
    ap_http.add_argument(
        "--max-wait-s", type=float, default=300.0,
        help="sync-POST wait bound for requests without a deadline",
    )
    ap_http.add_argument(
        "--wedge-s", type=float, default=30.0,
        help="queued depth with zero dispatch progress for this long "
        "flips /healthz unhealthy",
    )
    ap_http.add_argument(
        "--net-log-jsonl", default=None,
        help="http_request JSONL event stream (stamped schema)",
    )
    ap_http.add_argument(
        "--warm-buckets", action="store_true",
        help="pre-compile the explicit --buckets ladder before binding "
        "the listener (restart recovery runs warm from request one)",
    )
    ap_http.add_argument(
        "--registry", default=None,
        help="shared backend-registry file: self-register AFTER "
        "warm-up + listener bind and heartbeat (routers and the "
        "elastic controller adopt this backend with no manual config)",
    )
    ap_http.add_argument(
        "--heartbeat-s", type=float, default=1.0,
        help="registry heartbeat cadence when --registry is set",
    )
    ap_http.add_argument(
        "--deadline-propagation", action=argparse.BooleanOptionalAction,
        default=True,
        help="honor the X-DLPS-Deadline-Ms remaining-budget header: "
        "clamp the request deadline to it and admission-reject work "
        "whose budget expired in flight (README 'Tail tolerance')",
    )
    _add_serving_flags(ap_http)
    _add_solver_flags(ap_http)
    ap_http.set_defaults(fn=cmd_serve_http, quiet=True)

    ap_slice = sub.add_parser(
        "serve-slice",
        help="multi-host slice: N-process world serving one HTTP "
        "front-end over the slice's global mesh, with coordinator-"
        "level recovery (README 'Multi-host')",
    )
    ap_slice.add_argument(
        "--world-size", type=int, default=2,
        help="processes in the slice (harness: CPU processes; pod: "
        "one per host)",
    )
    ap_slice.add_argument(
        "--rank", type=int, default=None,
        help="run ONE rank (spawned by the supervisor; env contract "
        "from the launcher). Omit to run the slice supervisor.",
    )
    ap_slice.add_argument(
        "--local-devices", type=int, default=2,
        help="virtual CPU devices per rank process (harness only)",
    )
    ap_slice.add_argument(
        "--slice-id", default="slice0",
        help="logical slice name stamped into registry entries and "
        "world_reinit events",
    )
    ap_slice.add_argument(
        "--registry", default=None,
        help="shared backend-registry file to self-register into "
        "(routers adopt the slice with no manual config)",
    )
    ap_slice.add_argument(
        "--heartbeat-s", type=float, default=1.0,
        help="registry heartbeat cadence (routers TTL-eject a slice "
        "whose beats stop)",
    )
    ap_slice.add_argument(
        "--control-dir", default=None,
        help="slice dispatch-journal directory (default: next to the "
        "launcher's heartbeat dir)",
    )
    ap_slice.add_argument(
        "--slice-workdir", default=None,
        help="supervisor workdir (heartbeats, rank logs, xla cache)",
    )
    ap_slice.add_argument(
        "--max-reforms", type=int, default=3,
        help="world re-initializations before the supervisor gives up",
    )
    ap_slice.add_argument(
        "--supervise-timeout-s", type=float, default=86400.0,
        help="supervisor wall-clock budget",
    )
    ap_slice.add_argument("--host", default="127.0.0.1")
    ap_slice.add_argument(
        "--port", type=int, default=8080,
        help="rank-0 HTTP port — must be explicit so a re-initialized "
        "world rebinds the same poll URLs",
    )
    ap_slice.add_argument("--max-wait-s", type=float, default=300.0)
    ap_slice.add_argument("--wedge-s", type=float, default=30.0)
    ap_slice.add_argument(
        "--net-log-jsonl", default=None,
        help="http_request / world_reinit JSONL event stream",
    )
    ap_slice.add_argument(
        "--warm-buckets", action="store_true",
        help="pre-compile the bucket ladder on EVERY rank before the "
        "listener binds",
    )
    _add_serving_flags(ap_slice)
    _add_solver_flags(ap_slice)
    ap_slice.set_defaults(fn=cmd_serve_slice, quiet=True)

    ap_rt = sub.add_parser(
        "route",
        help="router tier over serve-http backends: shape/load-aware "
        "routing, health-checked failover (README 'Network serving')",
    )
    ap_rt.add_argument(
        "--backend", action="append",
        help="backend base URL (repeatable), e.g. http://10.0.0.2:8080; "
        "optional when --registry is given (slices self-register)",
    )
    ap_rt.add_argument("--host", default="127.0.0.1")
    ap_rt.add_argument(
        "--port", type=int, default=8079,
        help="bind port (0 = OS-assigned ephemeral)",
    )
    ap_rt.add_argument(
        "--poll-s", type=float, default=1.0,
        help="backend health/status poll cadence",
    )
    ap_rt.add_argument(
        "--eject-after", type=int, default=2,
        help="consecutive failed health probes before ejection",
    )
    ap_rt.add_argument(
        "--log-jsonl", default=None,
        help="route/ejection JSONL event stream (stamped schema)",
    )
    ap_rt.add_argument(
        "--registry", default=None,
        help="shared backend-registry file: replicated routers pointed "
        "at the same path share one consistent view of backends, "
        "ejections and re-admissions (README 'Durability & graceful "
        "shutdown')",
    )
    ap_rt.add_argument(
        "--probe-backoff-cap-s", type=float, default=30.0,
        help="ceiling on the exponential re-probe backoff of ejected "
        "backends",
    )
    ap_rt.add_argument(
        "--registry-ttl-s", type=float, default=0.0,
        help="eject self-registered backends whose registry heartbeat "
        "is older than this (0 = off; README 'Multi-host')",
    )
    ap_rt.add_argument(
        "--hedge", action=argparse.BooleanOptionalAction, default=True,
        help="adaptive hedged solves: when a primary forward is silent "
        "past the backend's recent p95, race ONE duplicate on the "
        "next-best backend (README 'Tail tolerance')",
    )
    ap_rt.add_argument(
        "--hedge-rate-cap", type=float, default=0.05,
        help="global bound on hedges as a fraction of solve forwards",
    )
    ap_rt.add_argument(
        "--retry-budget", type=float, default=5.0,
        help="per-tenant retry-budget refill rate (tokens/s); retries "
        "drain it, hedges require a whole token",
    )
    ap_rt.add_argument(
        "--retry-budget-burst", type=float, default=20.0,
        help="per-tenant retry-budget bucket capacity",
    )
    ap_rt.add_argument(
        "--deadline-propagation", action=argparse.BooleanOptionalAction,
        default=True,
        help="stamp every forward/retry/hedge with the REMAINING "
        "deadline budget (X-DLPS-Deadline-Ms + body re-stamp)",
    )
    ap_rt.add_argument("--metrics-path", default=None, help=argparse.SUPPRESS)
    ap_rt.add_argument("--trace-path", default=None, help=argparse.SUPPRESS)
    ap_rt.set_defaults(fn=cmd_route)

    ap_el = sub.add_parser(
        "elastic",
        help="closed-loop elasticity controller: scale serve-http "
        "backends out/in from pool telemetry (README 'Elasticity & "
        "overload protection')",
    )
    ap_el.add_argument(
        "--registry", required=True,
        help="shared backend-registry file the pool lives in",
    )
    ap_el.add_argument("--min-backends", type=int, default=1)
    ap_el.add_argument("--max-backends", type=int, default=4)
    ap_el.add_argument(
        "--poll-s", type=float, default=0.5, help="decision cadence"
    )
    ap_el.add_argument(
        "--load-high", type=float, default=8.0,
        help="mean per-backend queued+inflight at/above which the pool "
        "counts as overloaded",
    )
    ap_el.add_argument(
        "--load-low", type=float, default=1.0,
        help="mean load at/below which the pool counts as idle",
    )
    ap_el.add_argument(
        "--reject-rate-high", type=float, default=1.0,
        help="pool-wide admission rejects/sec that count as overload",
    )
    ap_el.add_argument(
        "--out-sustain-s", type=float, default=1.0,
        help="overload must hold this long before a scale-out",
    )
    ap_el.add_argument(
        "--in-sustain-s", type=float, default=5.0,
        help="idleness must hold this long before a scale-in",
    )
    ap_el.add_argument(
        "--cooldown-s", type=float, default=5.0,
        help="minimum quiet time between target changes",
    )
    ap_el.add_argument("--host", default="127.0.0.1")
    ap_el.add_argument(
        "--workdir", default=".",
        help="spawned backends' journals and logs live here",
    )
    ap_el.add_argument(
        "--buckets", default=None,
        help="bucket ladder JSON spawned backends warm before they "
        "register (the zero-warm-recompile rollout contract)",
    )
    ap_el.add_argument(
        "--backend-flag", action="append", default=None,
        help="extra serve-http flag(s) for spawned backends "
        "(repeatable; each value is whitespace-split)",
    )
    ap_el.add_argument(
        "--heartbeat-s", type=float, default=0.5,
        help="registry heartbeat cadence of spawned backends",
    )
    ap_el.add_argument(
        "--log-jsonl", default=None,
        help="scale_out/scale_in/scale_veto JSONL event stream",
    )
    ap_el.add_argument("--metrics-path", default=None, help=argparse.SUPPRESS)
    ap_el.add_argument("--trace-path", default=None, help=argparse.SUPPRESS)
    ap_el.set_defaults(fn=cmd_elastic)

    ap_at = sub.add_parser(
        "autotune",
        help="refine a serve bucket ladder from telemetry JSONL "
        "(README 'Serving performance')",
    )
    ap_at.add_argument(
        "--telemetry", required=True,
        help="service telemetry JSONL (the serve --log-jsonl stream)",
    )
    ap_at.add_argument("--out", required=True, help="ladder JSON output path")
    ap_at.add_argument(
        "--current", default=None,
        help="current ladder JSON (reported against, seeds split decisions)",
    )
    ap_at.add_argument("--waste-threshold", type=float, default=0.35)
    ap_at.add_argument("--max-programs", type=int, default=12)
    ap_at.add_argument("--batch", type=int, default=0, help="slots per bucket")
    ap_at.add_argument(
        "--devices", type=int, default=1,
        help="mesh width bucket batches must divide (serve --mesh-devices)",
    )
    ap_at.set_defaults(fn=cmd_autotune)

    ap_r = sub.add_parser(
        "report",
        help="analyze telemetry JSONL streams + metric snapshots: "
        "per-phase p50/p95/p99, padding waste by bucket, recovery "
        "overhead, iters/sec trajectory (README 'Observability')",
    )
    ap_r.add_argument(
        "files", nargs="+",
        help="telemetry JSONL files and/or JSON metric snapshots "
        "(solve/serve --log-jsonl streams, serve --out records)",
    )
    ap_r.add_argument(
        "--json", action="store_true",
        help="emit the full report as one JSON object",
    )
    ap_r.set_defaults(fn=cmd_report)

    ap_oa = sub.add_parser(
        "obs-agg",
        help="fleet telemetry aggregator: pull /statusz + /metrics "
        "across routers/backends/ranks, merge per-process traces into "
        "one Perfetto file connected by trace_id, and reconcile the "
        "hedge ledger against backend records and journal counts "
        "(README 'Distributed tracing & fleet telemetry')",
    )
    ap_oa.add_argument(
        "--registry", default=None,
        help="shared backend-registry JSON — backends are discovered "
        "from it (slice_id/world_size/ejected ride along)",
    )
    ap_oa.add_argument(
        "--router", action="append", default=None, metavar="URL",
        help="router URL to pull the hedge ledger from (repeatable)",
    )
    ap_oa.add_argument(
        "--backend", action="append", default=None, metavar="URL",
        help="extra backend URL beyond the registry (repeatable)",
    )
    ap_oa.add_argument(
        "--heartbeat-dir", action="append", default=None, metavar="DIR",
        help="world heartbeat dir to scan for rank*.hb liveness and "
        "rank*.metrics.json snapshots (repeatable)",
    )
    ap_oa.add_argument(
        "--trace", action="append", default=None, metavar="[LABEL=]PATH",
        help="per-process Chrome-trace JSON to merge (repeatable; "
        "label defaults to the file name)",
    )
    ap_oa.add_argument(
        "--metrics-json", action="append", default=None, metavar="PATH",
        help="JSON metrics snapshot to mine for histogram exemplars "
        "(repeatable)",
    )
    ap_oa.add_argument(
        "--out", default=None, metavar="DIR",
        help="write fleet.json + trace_merged.json here",
    )
    ap_oa.add_argument(
        "--timeout-s", type=float, default=2.0,
        help="per-pull HTTP timeout (unreachable processes degrade to "
        "error rows, never crash the aggregation)",
    )
    ap_oa.add_argument(
        "--json", action="store_true",
        help="print the fleet view as one JSON object",
    )
    ap_oa.set_defaults(fn=cmd_obs_agg)

    ap_c = sub.add_parser(
        "check",
        help="graftcheck static-analysis suite: jit/recompile hygiene, "
        "dtype discipline, lock discipline, JSONL schema — the tier-1 "
        "CI gate (README 'Static analysis')",
    )
    ap_c.add_argument(
        "paths", nargs="*",
        help="files/directories to check (default: the installed "
        "distributedlpsolver_tpu package)",
    )
    ap_c.add_argument(
        "--json", action="store_true",
        help="machine-readable findings (the gate's artifact format)",
    )
    ap_c.add_argument(
        "--rules", default=None,
        help="comma-separated rule subset (see --list-rules)",
    )
    ap_c.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit",
    )
    ap_c.add_argument(
        "--show-suppressed", action="store_true",
        help="also print findings silenced by graftcheck directives",
    )
    ap_c.add_argument(
        "--baseline", default=None, metavar="JSON",
        help="incremental diff-gate: fail only on findings absent from "
        "this committed baseline (see --write-baseline); the tier-1 "
        "gate runs against the empty BASELINE_GRAFTCHECK.json",
    )
    ap_c.add_argument(
        "--write-baseline", default=None, metavar="JSON",
        help="write the current unsuppressed findings as a baseline "
        "document and exit 0 (adopt-then-ratchet for existing trees)",
    )
    ap_c.set_defaults(fn=cmd_check)

    ap_b = sub.add_parser("backends", help="list registered backends")
    ap_b.set_defaults(fn=cmd_backends)

    ap_g = sub.add_parser("generate", help="write a generated problem to MPS")
    ap_g.add_argument("kind", choices=["dense", "general", "block", "scenario"])
    ap_g.add_argument("out")
    ap_g.add_argument("--m", type=int, default=100)
    ap_g.add_argument("--n", type=int, default=250)
    ap_g.add_argument("--blocks", type=int, default=4)
    ap_g.add_argument("--link", type=int, default=20)
    ap_g.add_argument("--scenarios", type=int, default=8,
                      help="scenario count K of the two-stage instance "
                      "(kind=scenario; --m/--n are the recourse block "
                      "shape)")
    ap_g.add_argument("--seed", type=int, default=0)
    ap_g.set_defaults(fn=cmd_generate)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
