"""distributedlpsolver_tpu — a TPU-native distributed LP solver.

A from-scratch, TPU-first rebuild of the capabilities of
shidanxu/DistributedLPSolver (see SURVEY.md; the reference mount was empty at
survey time, so the capability surface is pinned by BASELINE.json — a
primal-dual interior-point LP solver with Mehrotra predictor-corrector,
pluggable ``SolverBackend`` execution backends selected by ``--backend=``,
an MPS reader for the Netlib/Mittelmann suites, a batched solver, and a
distributed path that shards the constraint matrix over a device mesh and
combines Schur-complement / normal-equation blocks with ``jax.lax.psum``
over ICI, replacing the reference's per-iteration ``MPI_Allreduce``).

Design notes
------------
* The Mehrotra predictor-corrector driver and step-length logic live on the
  host; per-iteration linear algebra (normal-equations assembly
  ``A·diag(d)²·Aᵀ``, Cholesky, triangular solves) runs on device under a
  single jitted step (BASELINE.json:5).
* IPM to a 1e-8 duality gap needs f64 accumulation, so the package enables
  JAX x64 at import (opt out with ``TPULP_NO_X64=1``). Backends that target
  hardware without native f64 (TPU MXU) use f32/f64 mixed precision with
  iterative refinement — see ``distributedlpsolver_tpu.ops``.
"""

from __future__ import annotations

import os

if not os.environ.get("TPULP_NO_X64"):
    import jax

    jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from distributedlpsolver_tpu.models.problem import (  # noqa: E402
    InteriorForm,
    LPProblem,
    to_interior_form,
)

__all__ = [
    "LPProblem",
    "InteriorForm",
    "to_interior_form",
    "__version__",
]
