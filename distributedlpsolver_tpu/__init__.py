"""distributedlpsolver_tpu — a TPU-native distributed LP solver.

A from-scratch, TPU-first rebuild of the capabilities of
shidanxu/DistributedLPSolver (see SURVEY.md; the reference mount was empty at
survey time, so the capability surface is pinned by BASELINE.json — a
primal-dual interior-point LP solver with Mehrotra predictor-corrector,
pluggable ``SolverBackend`` execution backends selected by ``--backend=``,
an MPS reader for the Netlib/Mittelmann suites, a batched solver, and a
distributed path that shards the constraint matrix over a device mesh and
combines Schur-complement / normal-equation blocks with ``jax.lax.psum``
over ICI, replacing the reference's per-iteration ``MPI_Allreduce``).

Design notes
------------
* The Mehrotra predictor-corrector driver and step-length logic live on the
  host; per-iteration linear algebra (normal-equations assembly
  ``A·diag(d)²·Aᵀ``, Cholesky, triangular solves) runs on device under a
  single jitted step (BASELINE.json:5).
* IPM to a 1e-8 duality gap needs f64 accumulation, so the package enables
  JAX x64 at import (opt out with ``TPULP_NO_X64=1``). Backends that target
  hardware without native f64 (TPU MXU) use f32/f64 mixed precision with
  iterative refinement — see ``distributedlpsolver_tpu.ops``.
"""

from __future__ import annotations

import os

if not os.environ.get("TPULP_NO_X64"):
    import jax

    jax.config.update("jax_enable_x64", True)

if not os.environ.get("TPULP_NO_COMPILE_CACHE"):
    # Persistent XLA compilation cache. The emulated-f64 batched programs
    # compile in minutes on TPU (measured: 237 s for the batched f64 step
    # at the reference's 1024×(128,512) config) but run in ~1 s — caching
    # the executable makes every process after the first start warm.
    # Opt out with TPULP_NO_COMPILE_CACHE=1 or point TPULP_COMPILE_CACHE
    # somewhere else (default: .tpulp_xla_cache next to this package's
    # parent, i.e. inside the checkout).
    import jax

    # Default next to the checkout when that is writable (a source tree —
    # keeps the cache with the project); for installed packages (read-only
    # site-packages) fall back to the user cache dir.
    _parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if os.access(_parent, os.W_OK):
        _default_cache = os.path.join(_parent, ".tpulp_xla_cache")
    else:
        _default_cache = os.path.join(
            os.environ.get(
                "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
            ),
            "tpulp_xla_cache",
        )
    # An explicit TPULP_COMPILE_CACHE always wins — even over a dir JAX
    # already picked up from JAX_COMPILATION_CACHE_DIR; otherwise only
    # fill in the default when nothing is configured.
    _cache_dir = os.environ.get("TPULP_COMPILE_CACHE") or (
        None if jax.config.jax_compilation_cache_dir else _default_cache
    )
    if _cache_dir:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

__version__ = "0.1.0"

from distributedlpsolver_tpu.models.problem import (  # noqa: E402
    InteriorForm,
    LPProblem,
    to_interior_form,
)

__all__ = [
    "LPProblem",
    "InteriorForm",
    "to_interior_form",
    "__version__",
]
