"""Incomplete-LDLᵀ (IC(0)-class) preconditioner on the normal-equation
pattern — the rung between diag-Jacobi and falling off to cpu-sparse.

Unstructured ill-conditioned endgames have no bordered/block hint, so
the sparse-iterative tier preconditions with diag-Jacobi; when the
normal matrix M = A·diag(d)·Aᵀ + reg·I develops strong off-diagonal
coupling at small μ, jacobi-PCG grinds to its iteration cap and the
serve ladder degrades the instance to the cpu-sparse backend. This
module closes that gap with a zero-fill incomplete LDLᵀ factor on the
SPARSITY PATTERN of A·Aᵀ (incomplete-factorization preconditioning for
IPMs per arXiv 1708.04298; clean-room fixed-shape variant):

* **Symbolic phase (host, once per pattern):** every column c of A is a
  clique of rows; the per-column row pairs enumerate exactly the
  nonzero positions of M and their product terms A_ic·A_jc. These
  flatten into static index arrays, so the numeric phase is pure
  ``segment_sum`` — jittable, fixed shapes, and M is never materialized
  as a matrix (only its O(nnz(pattern)) value vector). The symbolic
  phase also level-schedules the factorization DAG: column j of L
  depends only on columns k < j sharing pattern with row j, so columns
  at the same level finalize simultaneously.
* **Numeric phase (jitted, per factor):** EXACT shifted IC(0) via a
  ``fori_loop`` over the (static) level count — each iteration runs the
  same two segment-sums and commits exactly the columns of that level,
  so the loop reproduces sequential up-looking factorization without
  data-dependent shapes. Fixed-point ("Chow–Patel") simultaneous sweeps
  were tried first and diverge on precisely the ill-conditioned
  endgames this rung exists for; the level schedule costs depth×O(nnz)
  but is exact and unconditionally stable.
* **Robustness:** the factor is computed on the symmetrically SCALED
  matrix S·M·S (unit diagonal, S = diag(M)^{-1/2}) with a Manteuffel
  diagonal shift α — zero-fill factorization of a general SPD matrix
  can break down (negative D); the shift absorbs the dropped fill
  (measured: α≈0.3 eliminates all breakdowns on the netlib-like family
  while keeping max|L| < 1). Any residual breakdown clamps D locally to
  the shifted diagonal — a per-row jacobi fallback that keeps D > 0.
* **Apply (jitted):** truncated Neumann triangular solves. With
  L = I + N (N strictly lower, entries < 1 after scaling+shift),
  K = Σ_{t<T} (−N)ᵗ ≈ L⁻¹ and the apply is
  ``P⁻¹ r = S·Kᵀ·D⁻¹·K·S·r`` — symmetric positive definite for ANY
  truncation depth (K is unit-triangular, hence nonsingular), so CG's
  convergence theory stays intact even when the truncation is rough
  (measured: T=6 matches exact triangular solves on the target family).

Everything on the device is O(nnz(pattern)); the preconditioner refuses
patterns whose product-term count explodes (dense-ish AAᵀ or
clique-heavy columns) by raising ValueError — callers treat that as
"stay on jacobi", not an error.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

import jax
import jax.numpy as jnp

# Refuse symbolic setup beyond these sizes: the per-column cliques give
# Σ_c |rows(c)|² product terms, which explodes on dense-ish columns
# (e.g. bordered first-stage columns — those instances have the
# structured preconditioners anyway).
_MAX_PRODUCT_TERMS = 4_000_000
_MAX_FILL_TERMS = 8_000_000
_MAX_ROWS = 16_384

# Manteuffel diagonal shift on the scaled (unit-diagonal) matrix: the
# factor computed is IC0(S·M·S + α·I). α=0.3 eliminates breakdowns on
# the netlib-like endgame family while keeping max|L| < 1 (so the
# Neumann apply converges fast); the preconditioner mismatch it
# introduces costs a few CG iterations, far less than breakdown costs.
DEFAULT_SHIFT = 0.3

# Neumann terms per triangular solve in the apply. T=6 reproduces the
# exact-substitution iteration counts on the target family.
DEFAULT_TRI_SWEEPS = 6

# Residual-breakdown clamp: a diagonal update at or below this resets
# to the shifted unit diagonal — the local jacobi fallback.
_D_FLOOR = 1e-10


def _pattern_terms(A: sp.csr_matrix):
    """Host symbolic phase: flatten the normal-matrix pattern of A·Aᵀ,
    its product/fill term lists, and the factorization level schedule
    into static index arrays (see ILDLPrecond fields)."""
    A = sp.csr_matrix(A)
    m, _ = A.shape
    if m > _MAX_ROWS:
        raise ValueError(f"ildl: {m} rows exceeds the {_MAX_ROWS} cap")
    Ac = A.tocsc()

    # --- product terms: one per (row-pair, column) clique membership ---
    ti, tj, tv, tc = [], [], [], []
    di, dv, dc = [], [], []
    budget = 0
    for c in range(Ac.shape[1]):
        lo, hi = Ac.indptr[c], Ac.indptr[c + 1]
        rows = Ac.indices[lo:hi].astype(np.int64)
        vals = Ac.data[lo:hi]
        r = len(rows)
        budget += r * r
        if budget > _MAX_PRODUCT_TERMS:
            raise ValueError("ildl: product-term budget exceeded")
        di.append(rows)
        dv.append(vals * vals)
        dc.append(np.full(r, c, dtype=np.int64))
        if r < 2:
            continue
        ii = np.repeat(rows, r).reshape(r, r)
        vv = np.multiply.outer(vals, vals)
        low = ii > ii.T
        ti.append(ii[low])
        tj.append(ii.T[low])
        tv.append(vv[low])
        tc.append(np.full(int(low.sum()), c, dtype=np.int64))

    d_seg = np.concatenate(di) if di else np.zeros(0, dtype=np.int64)
    d_coef = np.concatenate(dv) if dv else np.zeros(0)
    d_col = np.concatenate(dc) if dc else np.zeros(0, dtype=np.int64)

    if ti:
        p_i = np.concatenate(ti)
        p_j = np.concatenate(tj)
        p_coef = np.concatenate(tv)
        p_col = np.concatenate(tc)
    else:
        p_i = np.zeros(0, dtype=np.int64)
        p_j = np.zeros(0, dtype=np.int64)
        p_coef = np.zeros(0)
        p_col = np.zeros(0, dtype=np.int64)

    # Unique strictly-lower pattern entries (i > j), in (i, j) order.
    key = p_i * m + p_j
    uniq, inv = np.unique(key, return_inverse=True)
    l_i = (uniq // m).astype(np.int32)
    l_j = (uniq % m).astype(np.int32)
    nl = len(uniq)

    # --- fill terms for the factorization updates ---
    # For entry e=(i,j): pairs (a,b) of lower-entry indices with
    # a=(i,k), b=(j,k), k<j. For diagonal i: entries a=(i,k), k<i.
    pos = {(int(i), int(j)): e for e, (i, j) in enumerate(zip(l_i, l_j))}
    nbr = [[] for _ in range(m)]  # nbr[i] = ks with (i,k) in L, k<i
    for i, j in zip(l_i, l_j):
        nbr[int(i)].append(int(j))
    f_a, f_b, f_k, f_seg = [], [], [], []
    g_a, g_k, g_seg = [], [], []
    fill = 0
    for e in range(nl):
        i, j = int(l_i[e]), int(l_j[e])
        ks = np.intersect1d(
            np.asarray(nbr[i], dtype=np.int64),
            np.asarray(nbr[j], dtype=np.int64),
            assume_unique=False,
        )
        ks = ks[ks < j]
        fill += len(ks)
        if fill > _MAX_FILL_TERMS:
            raise ValueError("ildl: fill-term budget exceeded")
        for k in ks:
            f_a.append(pos[(i, int(k))])
            f_b.append(pos[(j, int(k))])
            f_k.append(int(k))
            f_seg.append(e)
    for i in range(m):
        for k in nbr[i]:
            g_a.append(pos[(i, k)])
            g_k.append(k)
            g_seg.append(i)

    # --- level schedule: column j finalizes one step after the deepest
    # column its row touches (columns with empty rows are level 0) ---
    lvl = np.zeros(m, dtype=np.int32)
    for j in range(m):
        lvl[j] = 1 + max((lvl[k] for k in nbr[j]), default=-1)
    depth = int(lvl.max()) + 1 if m else 0

    asi32 = lambda x: np.asarray(x, dtype=np.int32)
    return {
        "m": m,
        "nl": nl,
        "depth": depth,
        "l_i": l_i,
        "l_j": l_j,
        "lvl": lvl,
        "d_seg": asi32(d_seg),
        "d_coef": d_coef,
        "d_col": asi32(d_col),
        "p_seg": asi32(inv),
        "p_coef": p_coef,
        "p_col": asi32(p_col),
        "f_a": asi32(f_a),
        "f_b": asi32(f_b),
        "f_k": asi32(f_k),
        "f_seg": asi32(f_seg),
        "g_a": asi32(g_a),
        "g_k": asi32(g_k),
        "g_seg": asi32(g_seg),
    }


class ILDLPrecond:
    """Incomplete-LDLᵀ preconditioner of A·diag(d)·Aᵀ + reg·I.

    Same ``factor(d, reg)`` / ``apply_with(factors)`` protocol as
    :class:`ops.pcg.BlockJacobi`; registered as a pytree so it rides
    the jitted step programs as an ordinary operand. Factors are the
    triple ``(Lvals, D, S)`` — strictly-lower values on the static
    pattern, the positive diagonal, and the symmetric scaling.
    """

    def __init__(
        self,
        A_csr: sp.csr_matrix,
        dtype=np.float64,
        shift: float = DEFAULT_SHIFT,
        tri_sweeps: int = DEFAULT_TRI_SWEEPS,
    ):
        t = _pattern_terms(A_csr)
        self.m = t["m"]
        self.nl = t["nl"]
        self.depth = t["depth"]
        self.shift = float(shift)
        self.tri_sweeps = int(tri_sweeps)
        j = jnp.asarray
        self.l_i = j(t["l_i"])
        self.l_j = j(t["l_j"])
        self.lvl = j(t["lvl"])
        self.lvl_e = j(t["lvl"][t["l_j"]])
        self.d_seg = j(t["d_seg"])
        self.d_coef = j(t["d_coef"].astype(dtype))
        self.d_col = j(t["d_col"])
        self.p_seg = j(t["p_seg"])
        self.p_coef = j(t["p_coef"].astype(dtype))
        self.p_col = j(t["p_col"])
        self.f_a = j(t["f_a"])
        self.f_b = j(t["f_b"])
        self.f_k = j(t["f_k"])
        self.f_seg = j(t["f_seg"])
        self.g_a = j(t["g_a"])
        self.g_k = j(t["g_k"])
        self.g_seg = j(t["g_seg"])

    # -- numeric factorization (jittable) --------------------------------

    def factor(self, d, reg):
        """d (n,) → ``(Lvals, D, S)``: exact level-scheduled shifted
        IC(0) of S·(A·diag(d)·Aᵀ + reg·I)·S + α·I."""
        seg = jax.ops.segment_sum
        s_diag = (
            seg(self.d_coef * d[self.d_col], self.d_seg,
                num_segments=self.m)
            + reg
        )
        s_low = seg(
            self.p_coef * d[self.p_col], self.p_seg, num_segments=self.nl
        )
        S = 1.0 / jnp.sqrt(s_diag)
        sh = s_low * S[self.l_i] * S[self.l_j]
        dg = 1.0 + self.shift

        def body(s, LD):
            L, D = LD
            # Diagonals of this level: their row entries are all in
            # earlier-level columns, already final.
            rn2 = seg(
                L[self.g_a] * L[self.g_a] * D[self.g_k], self.g_seg,
                num_segments=self.m,
            )
            Dn = dg - rn2
            Dn = jnp.where(Dn > _D_FLOOR, Dn, dg)  # breakdown fallback
            D = jnp.where(self.lvl == s, Dn, D)
            # Column entries of this level: need D_j (just committed)
            # and pairs of earlier-level entries.
            corr = seg(
                L[self.f_a] * L[self.f_b] * D[self.f_k], self.f_seg,
                num_segments=self.nl,
            )
            Ln = (sh - corr) / D[self.l_j]
            L = jnp.where(self.lvl_e == s, Ln, L)
            return (L, D)

        L0 = jnp.zeros((self.nl,), dtype=sh.dtype)
        D0 = jnp.full((self.m,), dg, dtype=sh.dtype)
        L, D = jax.lax.fori_loop(0, self.depth, body, (L0, D0))
        return L, D, S

    # -- apply (jittable) -------------------------------------------------

    def _napply(self, L, x):
        """N·x with N the strictly-lower part: out[i] += L_e · x[j]."""
        out = jnp.zeros((self.m,), dtype=x.dtype)
        return out.at[self.l_i].add(L * x[self.l_j])

    def _ntapply(self, L, x):
        """Nᵀ·x: out[j] += L_e · x[i]."""
        out = jnp.zeros((self.m,), dtype=x.dtype)
        return out.at[self.l_j].add(L * x[self.l_i])

    def _neumann(self, nap, L, r):
        """K·r = Σ_{t<T} (−N)ᵗ r — the truncated triangular solve."""
        acc = r
        term = r
        for _ in range(self.tri_sweeps - 1):
            term = -nap(L, term)
            acc = acc + term
        return acc

    def apply_with(self, factors):
        L, D, S = factors

        def one(r):
            z = self._neumann(self._napply, L, S * r)
            z = z / D
            return S * self._neumann(self._ntapply, L, z)

        def apply(r):
            if r.ndim == 2:
                return jax.vmap(one)(r)
            return one(r)

        return apply

    # -- reporting --------------------------------------------------------

    def nbytes(self) -> int:
        return sum(
            int(a.size) * a.dtype.itemsize for a in self._tree_flatten()[0]
        )

    def memory_report(self) -> dict:
        return {
            "ildl_pattern": {
                "shape": (self.nl,),
                "nbytes": self.nbytes(),
            }
        }

    # pytree protocol (matches BlockJacobi's — an ILDL preconditioner is
    # an ordinary traced operand of the jitted IPM step programs).
    def _tree_flatten(self):
        children = (
            self.l_i, self.l_j, self.lvl, self.lvl_e,
            self.d_seg, self.d_coef, self.d_col,
            self.p_seg, self.p_coef, self.p_col,
            self.f_a, self.f_b, self.f_k, self.f_seg,
            self.g_a, self.g_k, self.g_seg,
        )
        aux = (self.m, self.nl, self.depth, self.shift, self.tri_sweeps)
        return children, aux

    @classmethod
    def _tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        obj.m, obj.nl, obj.depth, obj.shift, obj.tri_sweeps = aux
        (
            obj.l_i, obj.l_j, obj.lvl, obj.lvl_e,
            obj.d_seg, obj.d_coef, obj.d_col,
            obj.p_seg, obj.p_coef, obj.p_col,
            obj.f_a, obj.f_b, obj.f_k, obj.f_seg,
            obj.g_a, obj.g_k, obj.g_seg,
        ) = children
        return obj


jax.tree_util.register_pytree_node(
    ILDLPrecond,
    lambda o: o._tree_flatten(),
    ILDLPrecond._tree_unflatten,
)
