"""Custom TPU ops (Pallas kernels) with plain-XLA fallbacks."""

from distributedlpsolver_tpu.ops.normal_eq import (
    normal_eq,
    normal_eq_pallas,
    normal_eq_reference,
    pad_for_pallas,
    supports_pallas,
)

__all__ = [
    "normal_eq",
    "normal_eq_pallas",
    "normal_eq_reference",
    "pad_for_pallas",
    "supports_pallas",
]
