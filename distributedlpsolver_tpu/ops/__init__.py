"""Custom TPU ops (Pallas kernels, mesh-distributed factorizations) with
plain-XLA fallbacks."""

from distributedlpsolver_tpu.ops.dist_chol import chol_tri_inv_mesh
from distributedlpsolver_tpu.ops.normal_eq import (
    normal_eq,
    normal_eq_pallas,
    normal_eq_reference,
    pad_for_pallas,
    supports_pallas,
)
from distributedlpsolver_tpu.ops.sparse import (
    SparseOperator,
    from_problem,
    from_scipy,
    ruiz_equilibrate,
)

__all__ = [
    "SparseOperator",
    "chol_tri_inv_mesh",
    "from_problem",
    "from_scipy",
    "normal_eq",
    "normal_eq_pallas",
    "normal_eq_reference",
    "pad_for_pallas",
    "ruiz_equilibrate",
    "supports_pallas",
]
