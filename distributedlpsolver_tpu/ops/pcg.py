"""Batched preconditioned conjugate gradient + matrix-free preconditioners
for the inexact-IPM (huge-sparse) tier.

The dense tier's PCG mode (ipm/core.pcg_solve) preconditions with an f32
Cholesky of the ASSEMBLED normal matrix — exactly the materialization the
sparse tier must never do. This module provides the matrix-free
replacements, all jittable with fixed shapes:

* :func:`pcg` — single-RHS PCG that also returns the iteration count
  (the ``cg_iters`` telemetry field) and propagates failure as NaN with
  the same honesty contract as ``core.pcg_solve``;
* :func:`pcg_batched` — (B, m) lanes under one ``lax.while_loop`` with a
  per-lane active mask (converged/failed lanes freeze; the loop runs
  until every lane is done or the shared iteration cap), plus
  :func:`solve_chunked` to split wide batches into ≤``CHUNK_WIDTH``-lane
  programs — the healthy TPU program class per ROUND5_NOTES;
* preconditioners: :func:`jacobi` (diag of A·diag(d)·Aᵀ, never forming
  it), :class:`BlockJacobi` (exact bs×bs diagonal blocks of the normal
  matrix from per-block dense row slices, vmapped Cholesky), and
  :class:`BorderedPrecond` — block-Jacobi over scenario row blocks plus
  a Woodbury capacitance correction for the first-stage (bordering)
  columns of storm-class two-stage programs. On an exactly-bordered
  pattern the Woodbury form IS the regularized normal-matrix inverse, so
  PCG converges in a handful of iterations at every μ — the property
  that lets the inexact IPM reach 1e-8 where diag-Jacobi stalls
  (incomplete-factorization preconditioning per arXiv 1708.04298;
  clean-room, structure-exploiting variant).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import numpy as np
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from distributedlpsolver_tpu.ops.sparse import SparseOperator

# Batch lanes per compiled PCG program: wider programs at f64 join the
# kernel-fault class ROUND5_NOTES lever 4 documents on large TPU
# dispatches; 128 lanes stays inside the healthy envelope.
CHUNK_WIDTH = 128

# Stall exit: CG iterations without a meaningful residual reduction
# before the loop gives up on its tolerance. At the IPM endgame the f64
# preconditioner factors bottom out around the normal matrix's
# condition floor; past that point every further iteration is noise —
# measured on the 20k storm profile, the last two IPM steps spent
# 16k CG iterations grinding a residual that had already stalled at the
# achievable floor (the accept/reject test below still decides whether
# the stalled result is usable, so honesty is unaffected).
_STALL_WINDOW = 96
_STALL_FACTOR = 0.999  # an iteration must beat best·this to count as progress


def _pin(x, mesh, axis, batched=False):
    """Constrain a CG carry vector's layout to the mesh row split —
    a no-op off-mesh, so single-device programs are untouched. Under a
    mesh this pins every while_loop carry to the same sharding as the
    operator's flat vectors, keeping the whole solve ONE SPMD program
    whose only collectives are the operator's psum and the scalar dots.
    """
    if mesh is None:
        return x
    spec = (
        jax.sharding.PartitionSpec(None, axis)
        if batched
        else jax.sharding.PartitionSpec(axis)
    )
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec)
    )


def pcg(op, prec, rhs, tol, max_iter, mesh=None, axis=None):
    """Preconditioned CG; returns ``(x, iters)``.

    ``op``/``prec`` are matrix-free callables. Terminates at relative
    residual ``tol`` (of ‖rhs‖) or ``max_iter``. A breakdown (non-finite
    curvature) or a cap-limited run that failed to meaningfully reduce
    the residual returns NaN — the caller's bad-step ladder must see the
    failure, not a noise direction (same contract as core.pcg_solve).
    ``mesh=``/``axis=`` pin the carry vectors to the row-shard layout of
    a distributed operator (see :func:`_pin`).
    """
    rhs = _pin(rhs, mesh, axis)
    norm0 = jnp.linalg.norm(rhs)
    thresh = tol * norm0

    x0 = jnp.zeros_like(rhs)
    z0 = _pin(prec(rhs), mesh, axis)
    zero_i = jnp.asarray(0, jnp.int32)
    carry0 = (x0, rhs, z0, rhs @ z0, zero_i, norm0, zero_i)

    def cond(carry):
        x, r, p, rz, it, best, stall = carry
        return (
            (it < max_iter)
            & (stall < _STALL_WINDOW)
            & (jnp.linalg.norm(r) > thresh)
            & jnp.isfinite(rz)
        )

    def body(carry):
        x, r, p, rz, it, best, stall = carry
        Ap = op(p)
        denom = p @ Ap
        alpha = rz / jnp.where(denom != 0, denom, 1.0)
        x = x + alpha * p
        r = r - alpha * Ap
        z = prec(r)
        rz_new = r @ z
        beta = rz_new / jnp.where(rz != 0, rz, 1.0)
        p = z + beta * p
        res = jnp.linalg.norm(r)
        improved = res < _STALL_FACTOR * best
        best = jnp.minimum(best, res)
        stall = jnp.where(improved, 0, stall + 1)
        return (x, r, p, rz_new, it + 1, best, stall)

    x, r, p, rz, it, best, stall = jax.lax.while_loop(cond, body, carry0)
    bad = ~(jnp.isfinite(rz) & jnp.all(jnp.isfinite(x)))
    bad = bad | (
        jnp.linalg.norm(r) > jnp.maximum(1e-3 * norm0, 10.0 * thresh)
    )
    return jnp.where(bad, jnp.asarray(jnp.nan, x.dtype), x), it


def pcg_batched(op, prec, rhs, tol, max_iter, active=None, mesh=None,
                axis=None):
    """Batched PCG over (B, m) lanes with per-lane early exit.

    One ``lax.while_loop`` drives every lane; a lane leaves the active
    mask when its relative residual passes its ``tol`` (scalar or (B,))
    or it breaks down, and frozen lanes stop contributing work beyond
    the masked arithmetic. Returns ``(X, iters, ok)``: per-lane
    solutions (NaN where failed), iteration counts, and success flags.
    ``mesh=``/``axis=`` pin the (B, m) carries to a row-sharded m axis
    (lanes replicated) so the batch stays one SPMD program per chunk.
    """
    rhs = _pin(rhs, mesh, axis, batched=True)
    B, m = rhs.shape
    dtype = rhs.dtype
    tol = jnp.broadcast_to(jnp.asarray(tol, dtype), (B,))
    if active is None:
        active = jnp.ones((B,), dtype=bool)
    norm0 = jnp.linalg.norm(rhs, axis=1)
    thresh = tol * norm0

    X0 = jnp.zeros_like(rhs)
    Z0 = _pin(prec(rhs), mesh, axis, batched=True)
    rz0 = jnp.sum(rhs * Z0, axis=1)
    carry0 = (
        X0, rhs, Z0, rz0,
        jnp.zeros((B,), jnp.int32),
        active & (norm0 > thresh),
        norm0,
        jnp.zeros((B,), jnp.int32),
    )

    def cond(carry):
        X, R, P, rz, it, act, best, stall = carry
        return jnp.any(act)

    def body(carry):
        X, R, P, rz, it, act, best, stall = carry
        AP = op(P)
        denom = jnp.sum(P * AP, axis=1)
        alpha = rz / jnp.where(denom != 0, denom, 1.0)
        am = jnp.where(act, alpha, 0.0)
        X = X + am[:, None] * P
        R = R - am[:, None] * AP
        Z = prec(R)
        rz_new = jnp.sum(R * Z, axis=1)
        beta = rz_new / jnp.where(rz != 0, rz, 1.0)
        P = jnp.where(act[:, None], Z + beta[:, None] * P, P)
        rz = jnp.where(act, rz_new, rz)
        it = jnp.where(act, it + 1, it)
        res = jnp.linalg.norm(R, axis=1)
        improved = res < _STALL_FACTOR * best
        best = jnp.where(act, jnp.minimum(best, res), best)
        stall = jnp.where(act, jnp.where(improved, 0, stall + 1), stall)
        act = (
            act
            & (res > thresh)
            & jnp.isfinite(rz)
            & (it < max_iter)
            & (stall < _STALL_WINDOW)
        )
        return (X, R, P, rz, it, act, best, stall)

    X, R, P, rz, it, act, best, stall = jax.lax.while_loop(cond, body, carry0)
    res = jnp.linalg.norm(R, axis=1)
    bad = ~(jnp.isfinite(rz) & jnp.all(jnp.isfinite(X), axis=1))
    bad = bad | (res > jnp.maximum(1e-3 * norm0, 10.0 * thresh))
    # Lanes the caller never activated keep their zeros and are not
    # judged by the residual test (their R is still the untouched rhs).
    bad = bad & active
    X = jnp.where(bad[:, None], jnp.asarray(jnp.nan, dtype), X)
    return X, it, ~bad


def solve_chunked(solve_fn, rhs, chunk: int = CHUNK_WIDTH, mesh=None):
    """Split a (B, m) batched solve into ≤``chunk``-lane programs and
    concatenate — wide fan-ins never grow one device program past the
    healthy width. ``solve_fn(rhs_chunk) -> (X, iters, ok)``. The last
    partial chunk is zero-padded to the chunk width (one compiled
    program per width, not per remainder). Under ``mesh=`` the pad
    lanes are committed to the chunk's own sharding before the
    concatenate, so every rank pads identically (no divergent
    placement between the full and remainder chunks)."""
    B = rhs.shape[0]
    outs = []
    for lo in range(0, B, chunk):
        part = rhs[lo : lo + chunk]
        pad = chunk - part.shape[0] if B > chunk else 0
        if pad > 0:
            zeros_np = np.zeros(
                (pad,) + tuple(part.shape[1:]), dtype=part.dtype
            )
            if mesh is not None:
                pad_lanes = jax.device_put(zeros_np, part.sharding)
            else:
                pad_lanes = jnp.zeros(
                    (pad,) + part.shape[1:], part.dtype
                )
            part = jnp.concatenate([part, pad_lanes])
        X, it, ok = solve_fn(part)
        if pad > 0:
            X, it, ok = X[:-pad], it[:-pad], ok[:-pad]
        outs.append((X, it, ok))
    return (
        jnp.concatenate([o[0] for o in outs]),
        jnp.concatenate([o[1] for o in outs]),
        jnp.concatenate([o[2] for o in outs]),
    )


# -- preconditioners --------------------------------------------------------


def jacobi(op: SparseOperator, d, reg):
    """Diagonal (Jacobi) preconditioner of A·diag(d)·Aᵀ + reg·I — the
    default: O(nnz) to build, exact on diagonally-dominant normal
    matrices, graceful everywhere else. Returns ``apply(r)``."""
    idiag = 1.0 / op.normal_diag(d, reg)

    def apply(r):
        if r.ndim == 2:
            return r * idiag[None, :]
        return r * idiag

    return apply


def _block_slices(A_csr: sp.csr_matrix, starts, sizes, exclude_cols=None):
    """Host-side symbolic setup shared by the block preconditioners: for
    each row block, the dense (bs, w) slice of its touched columns plus
    the padded column-index list (pad entries point at a synthetic
    column n whose d is fixed to 0, so they contribute nothing)."""
    m, n = A_csr.shape
    K = len(starts)
    col_lists = []
    w = 1
    excl = (
        np.zeros(n, dtype=bool)
        if exclude_cols is None
        else np.asarray(exclude_cols, dtype=bool)
    )
    for b in range(K):
        lo, hi = starts[b], starts[b] + sizes[b]
        cols = np.unique(A_csr[lo:hi].indices)
        cols = cols[~excl[cols]]
        col_lists.append(cols)
        w = max(w, len(cols))
    w = max(_BLOCK_W_QUANTUM, -(-w // _BLOCK_W_QUANTUM) * _BLOCK_W_QUANTUM)
    bs = int(max(sizes))
    A_blocks = np.zeros((K, bs, w))
    colidx = np.full((K, w), n, dtype=np.int32)  # n = synthetic zero-d col
    rowmask = np.zeros((K, bs), dtype=bool)
    for b in range(K):
        lo = starts[b]
        cols = col_lists[b]
        colidx[b, : len(cols)] = cols
        rowmask[b, : sizes[b]] = True
        if len(cols):
            sub = A_csr[lo : lo + sizes[b], :].tocsc()[:, cols]
            A_blocks[b, : sizes[b], : len(cols)] = np.asarray(sub.todense())
    return A_blocks, colidx, rowmask, bs, w


_BLOCK_W_QUANTUM = 16


@functools.partial(jax.jit, static_argnames=())
def _block_factor_jit(A_blocks, colidx, rowmask, d_pad, reg):
    """Per-block dense normal blocks M_b = A_b·diag(d)·A_bᵀ + reg·I and
    their Cholesky factors, vmapped — bs×bs each, never m×m."""
    dg = d_pad[colidx]  # (K, w)
    M = jnp.einsum("bij,bj,bkj->bik", A_blocks, dg, A_blocks)
    # Real rows get the +reg ridge; padded tail rows (rowmask False, all-
    # zero A slice) get a unit diagonal so the factor stays SPD — their
    # rhs entries are zero by construction.
    diag_fix = jnp.where(rowmask, reg, 1.0)
    M = M + jax.vmap(jnp.diag)(diag_fix)
    L = jnp.linalg.cholesky(M)
    return L


@functools.partial(jax.jit, static_argnames=())
def _block_apply_jit(L, r_blocks):
    """Blockwise two-triangular solve: (K, bs) rhs → (K, bs)."""
    y = jax.scipy.linalg.solve_triangular(L, r_blocks[..., None], lower=True)
    x = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, -1, -2), y, lower=False
    )
    return x[..., 0]


class BlockJacobi:
    """Exact bs×bs diagonal blocks of the normal matrix as the
    preconditioner. Setup is host-side symbolic (per-block dense row
    slices + padded column lists — static shapes); the per-step factor
    is one vmapped einsum + Cholesky, jittable and O(K·bs²·w), never
    forming the m×m matrix."""

    def __init__(
        self,
        A_csr: sp.csr_matrix,
        block_size: int = 32,
        starts=None,
        sizes=None,
        exclude_cols=None,
        dtype=np.float64,
    ):
        A_csr = sp.csr_matrix(A_csr)
        m = A_csr.shape[0]
        if starts is None:
            starts = list(range(0, m, block_size))
            sizes = [min(block_size, m - lo) for lo in starts]
        A_blocks, colidx, rowmask, bs, w = _block_slices(
            A_csr, starts, sizes, exclude_cols
        )
        self.m = m
        self.n = A_csr.shape[1]
        self.starts = np.asarray(starts, dtype=np.int64)
        self.sizes = np.asarray(sizes, dtype=np.int64)
        self.bs = bs
        self.A_blocks = jnp.asarray(A_blocks.astype(dtype))
        self.colidx = jnp.asarray(colidx)
        self.rowmask = jnp.asarray(rowmask)
        # Scatter index from (K, bs) block layout back to flat rows.
        flat = np.full((len(starts), bs), m, dtype=np.int32)
        for b, (lo, szz) in enumerate(zip(starts, sizes)):
            flat[b, :szz] = np.arange(lo, lo + szz, dtype=np.int32)
        self.flatidx = jnp.asarray(flat)

    def factor(self, d, reg):
        """d (n,) → per-block Cholesky factors (traced; one program)."""
        d_pad = jnp.concatenate(
            [d, jnp.zeros((1,), dtype=d.dtype)]
        )  # synthetic pad column
        return _block_factor_jit(
            self.A_blocks, self.colidx, self.rowmask, d_pad,
            jnp.asarray(reg, d.dtype),
        )

    def gather(self, r):
        """(m,) → (K, bs) with zero-padded tail rows."""
        r_pad = jnp.concatenate([r, jnp.zeros((1,), dtype=r.dtype)])
        return r_pad[self.flatidx]

    def scatter(self, xb):
        """(K, bs) → (m,) inverse of :meth:`gather`."""
        flat = self.flatidx.reshape(-1)
        vals = xb.reshape(-1)
        out = jnp.zeros((self.m + 1,), dtype=xb.dtype)
        return out.at[flat].add(vals)[: self.m]

    def apply_with(self, L):
        def apply(r):
            if r.ndim == 2:
                return jax.vmap(
                    lambda rr: self.scatter(
                        _block_apply_jit(L, self.gather(rr))
                    )
                )(r)
            return self.scatter(_block_apply_jit(L, self.gather(r)))

        return apply

    def nbytes(self) -> int:
        return sum(
            int(a.size) * a.dtype.itemsize
            for a in (self.A_blocks, self.colidx, self.rowmask, self.flatidx)
        )

    def memory_report(self) -> dict:
        return {
            "A_blocks": {
                "shape": tuple(int(s) for s in self.A_blocks.shape),
                "nbytes": int(self.A_blocks.size)
                * self.A_blocks.dtype.itemsize,
            }
        }

    # pytree protocol: a preconditioner is an ordinary traced operand of
    # the jitted IPM step (backends/sparse_iterative.py) — the arrays are
    # children, the host metadata is the (hashable) treedef aux.
    def _tree_flatten(self):
        children = (self.A_blocks, self.colidx, self.rowmask, self.flatidx)
        aux = (self.m, self.n, self.bs, tuple(self.starts), tuple(self.sizes))
        return children, aux

    @classmethod
    def _tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        obj.m, obj.n, obj.bs, starts, sizes = (
            aux[0], aux[1], aux[2], aux[3], aux[4]
        )
        obj.starts = np.asarray(starts, dtype=np.int64)
        obj.sizes = np.asarray(sizes, dtype=np.int64)
        obj.A_blocks, obj.colidx, obj.rowmask, obj.flatidx = children
        return obj


@functools.partial(jax.jit, static_argnames=())
def _bordered_factor_jit(A_blocks, colidx, rowmask, V, d_pad, d1, reg):
    """Factors of the bordered (Woodbury) preconditioner:

        P = B̃ + V·diag(d1)·Vᵀ,  B̃ = blockdiag(W_b·D2_b·W_bᵀ) + reg·I

    Returns (L_blocks, Z, capL): per-scenario Cholesky factors, the
    block-solved border Z = B̃⁻¹V, and the n1×n1 capacitance factor of
    C = diag(1/d1) + VᵀZ. On an exactly bordered pattern P equals the
    regularized normal matrix, so the PCG it preconditions converges in
    a handful of iterations at any scaling spread."""
    L = _block_factor_jit(A_blocks, colidx, rowmask, d_pad, reg)
    K, bs = rowmask.shape
    n1 = V.shape[1]
    Vb = V.reshape(K, bs, n1)
    Zb = jax.scipy.linalg.cho_solve((L, True), Vb)
    C = jnp.einsum("bij,bik->jk", Vb, Zb) + jnp.diag(1.0 / d1)
    capL = jnp.linalg.cholesky(C)
    return L, Zb, capL


@functools.partial(jax.jit, static_argnames=())
def _bordered_apply_jit(L, Zb, capL, V, r_blocks):
    """P⁻¹r via Woodbury: B̃⁻¹r − Z·C⁻¹·Vᵀ·B̃⁻¹r, all in block layout."""
    K, bs = r_blocks.shape
    n1 = V.shape[1]
    Vb = V.reshape(K, bs, n1)
    xb = jax.scipy.linalg.cho_solve((L, True), r_blocks[..., None])[..., 0]
    vtx = jnp.einsum("bij,bi->j", Vb, xb)
    y = jax.scipy.linalg.cho_solve((capL, True), vtx)
    return xb - jnp.einsum("bij,j->bi", Zb, y)


class BorderedPrecond:
    """Woodbury preconditioner for bordered (dual block-angular /
    two-stage stochastic) patterns: scenario row blocks coupled only
    through ``n1`` first-stage columns. The scenario-local part of the
    normal matrix is exactly block-diagonal; the first-stage coupling is
    the rank-n1 term V·D1·Vᵀ, inverted through an n1×n1 capacitance.
    Everything stays (K, bs, ·)/(m, n1)-shaped — the m×m normal matrix
    never exists in any format."""

    def __init__(self, A_csr: sp.csr_matrix, hint: dict, dtype=np.float64):
        A_csr = sp.csr_matrix(A_csr)
        m, n = A_csr.shape
        n1 = int(hint["first_stage_n"])
        K = int(hint["num_blocks"])
        mb = int(hint["block_m"])
        if K * mb != m:
            raise ValueError(
                f"bordered hint K={K}, block_m={mb} does not tile m={m}"
            )
        self.n1 = n1
        first = np.zeros(n, dtype=bool)
        first[:n1] = True
        starts = [b * mb for b in range(K)]
        sizes = [mb] * K
        self.blocks = BlockJacobi(
            A_csr, starts=starts, sizes=sizes, exclude_cols=first,
            dtype=dtype,
        )
        self.V = jnp.asarray(
            np.asarray(A_csr[:, :n1].todense(), dtype=dtype)
        )

    def factor(self, d, reg):
        d1 = d[: self.n1]
        d2 = d.at[: self.n1].set(0.0)  # first-stage cols live in V·D1·Vᵀ
        d_pad = jnp.concatenate([d2, jnp.zeros((1,), dtype=d.dtype)])
        return _bordered_factor_jit(
            self.blocks.A_blocks, self.blocks.colidx, self.blocks.rowmask,
            self.V, d_pad, d1, jnp.asarray(reg, d.dtype),
        )

    def apply_with(self, factors):
        L, Zb, capL = factors
        blocks = self.blocks

        def one(r):
            rb = blocks.gather(r)
            return blocks.scatter(
                _bordered_apply_jit(L, Zb, capL, self.V, rb)
            )

        def apply(r):
            if r.ndim == 2:
                return jax.vmap(one)(r)
            return one(r)

        return apply

    def nbytes(self) -> int:
        return self.blocks.nbytes() + int(self.V.size) * self.V.dtype.itemsize

    def memory_report(self) -> dict:
        rep = self.blocks.memory_report()
        rep["V"] = {
            "shape": tuple(int(s) for s in self.V.shape),
            "nbytes": int(self.V.size) * self.V.dtype.itemsize,
        }
        return rep

    def _tree_flatten(self):
        return (self.blocks, self.V), (self.n1,)

    @classmethod
    def _tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        obj.n1 = aux[0]
        obj.blocks, obj.V = children
        return obj


for _cls in (BlockJacobi, BorderedPrecond):
    jax.tree_util.register_pytree_node(
        _cls,
        lambda o: o._tree_flatten(),
        _cls._tree_unflatten,
    )
