"""GEMM-dominated Cholesky factorization + explicit triangular inverse.

Why this exists (measured on the v5e chip, scripts/probe_batched_parts.py
and scripts/probe_chol_mxu.py, 2026-08-01): XLA's emulated-f64
``jnp.linalg.cholesky`` on a (128, 128, 128) batch costs ~345 ms and a
single emulated-f64 ``cho_solve`` ~130 ms — they lower to scalarized
recurrences whose every scalar op pays the f64-emulation tax. Meanwhile
emulated-f64 *GEMM* runs at ~150 GFLOP/s with 2.2e-15 max relative error
(the MXU split path), and fused f64 elementwise streams at ~2 ns/element.
Round 4 misattributed the batched backend's wall to "emulated-f64
elementwise" (BASELINE.md batched row); the component probe shows the
factorization and triangular solves own ~75% of the 622 ms step.

So: restructure the factorization so ALL O(m³) work is GEMM and the only
sequential arithmetic is a p-column recursion inside each diagonal block.
This panel scheme is the single-device sibling of ops/dist_chol.py's
mesh panel factorization (SURVEY.md §2 "LA kernels"; BASELINE.json:5
names the dense-Cholesky path) with two differences: the diagonal block
is factored by an unrolled static-slice recursion instead of
``jnp.linalg.cholesky`` (the builtin is the very thing being avoided),
and the triangular inverse is fused into the same panel loop, so a
factorization's 6+ downstream solves (kkt_refine=2 ⇒ 6 per IPM step)
become two batched GEMVs each.

Measured win (same probe): (128, 128, 128) factor+full-inverse ~35 ms vs
~350 ms builtin factor alone — ~10× — and each solve drops from ~20 ms
to GEMV noise. Accuracy: ||M⁻¹M − I||_max = 1.7e-10 at cond 7.5e5 and
3.2e-13 at m = 2048 — the backward-stable class expected of an IEEE-f64
right-looking Cholesky (identical operation set, blocked order).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _tri_inv_block(C):
    """(p, p) lower-triangular → C⁻¹ masked fori forward substitution on
    the identity — ONE definition, also used for `_factor_diag_block`'s W."""
    p = C.shape[0]
    eye = jnp.eye(p, dtype=C.dtype)

    def sub_body(i, W):
        row = (eye[i] - C[i] @ W) / C[i, i]
        return W.at[i].set(row)

    return jax.lax.fori_loop(0, p, sub_body, jnp.zeros_like(C))


def _pad_spd(M, p):
    """Pad an (m, m) SPD matrix to a panel multiple with an inert
    identity tail; returns (padded, mp)."""
    m = M.shape[0]
    mp = -(-m // p) * p
    if mp != m:
        M = jnp.pad(M, ((0, mp - m), (0, mp - m)))
        M = M.at[jnp.arange(m, mp), jnp.arange(m, mp)].set(1.0)
    return M, mp


def _factor_diag_block(D):
    """(p, p) SPD block → (C, W) with ``C = chol(D)`` and ``W = C⁻¹``.

    Masked ``fori_loop`` column recursion: per column, one sqrt + one
    scaled masked column + one masked rank-1 trailing update; then W by
    a masked forward-substitution loop on the identity. The loop bodies
    are ~10 fused ops regardless of p, so the graph (and compile time)
    stays tiny at the wide panels large m wants — an earlier unrolled
    version put 2p static steps in the panel body and XLA compile
    diverged at p = 256. Runtime is 2p sequential steps of (p,)/(p,p)
    fused VPU work — microseconds against the panel GEMMs. Breakdown
    (non-SPD D) yields NaN from the sqrt and propagates, matching the
    builtin's contract.
    """
    p = D.shape[0]
    rows = jnp.arange(p)

    def fac_body(i, carry):
        D, Ct = carry
        r = jnp.sqrt(D[i, i])
        col = jnp.where(rows >= i, D[:, i] / r, 0.0)
        Ct = Ct.at[i].set(col)  # Ct row i = column i of C
        t = jnp.where(rows > i, col, 0.0)
        D = D - t[:, None] * t[None, :]
        return D, Ct

    _, Ct = jax.lax.fori_loop(0, p, fac_body, (D, jnp.zeros_like(D)))
    C = Ct.T
    return C, _tri_inv_block(C)


def _panel_for(m: int) -> int:
    """Default panel width: small blocks keep the unrolled recursion
    short where the batch axis supplies parallelism (measured best at
    p=16 for the (128, 128) members); large m amortizes panel GEMMs
    better at wider panels (p=256 beat 128 at m=2048)."""
    if m <= 512:
        return 16
    if m < 2048:
        return 128
    return 256


@functools.partial(jax.jit, static_argnames=("panel",))
def chol_inv_mxu(M, panel: int | None = None):
    """``L⁻¹`` for ``M = L·Lᵀ`` (SPD), all O(m³) on the MXU.

    Unbatched (m, m) → (m, m) lower-triangular ``Linv`` with
    ``M⁻¹ = Linvᵀ·Linv``; ``vmap`` supplies the batch axis (the batched
    backend's usage). Ragged m is padded to a panel multiple with an
    identity tail (factors to I, inert, sliced off — same device-side
    trick as ops/dist_chol.py) entirely under jit.

    Per panel j (rows/cols [g0, g0+p)) on the running trailing matrix T
    and inverse accumulator X (init I):

        C, W  = factor+invert T[diag block]         (2p fused steps)
        L_below = T[:, panel] · Wᵀ  masked rows ≥ g0+p   (GEMM)
        T    −= L_below · L_belowᵀ                  (GEMM)
        X[panel rows]  = W · X[panel rows]          (GEMM)
        X[below rows] −= L_below · X[panel rows]    (GEMM)

    The processed region of T is never read again (its garbage is
    masked out of every later panel), and L itself is never stored —
    the inverse substitution consumes each panel in the iteration that
    produces it.
    """
    m = M.shape[0]
    p = panel if panel is not None else _panel_for(m)
    p = min(p, m)
    M, mp = _pad_spd(M, p)
    P = mp // p
    rows = jnp.arange(mp)
    X0 = jnp.eye(mp, dtype=M.dtype)

    def body(j, carry):
        T, X = carry
        g0 = j * p
        D = jax.lax.dynamic_slice(T, (g0, g0), (p, p))
        C, W = _factor_diag_block(D)
        Tpan = jax.lax.dynamic_slice(T, (0, g0), (mp, p))
        # Only the below-panel rows of the L panel are ever consumed
        # (the panel rows' C is already folded into W; rows above hold
        # stale M values the mask discards).
        Lbelow = (Tpan @ W.T) * (rows[:, None] >= g0 + p).astype(M.dtype)
        T = T - Lbelow @ Lbelow.T
        Xp = jax.lax.dynamic_slice(X, (g0, 0), (p, mp))
        Xp = W @ Xp
        X = jax.lax.dynamic_update_slice(X, Xp, (g0, 0))
        X = X - Lbelow @ Xp
        return T, X

    _, X = jax.lax.fori_loop(0, P, body, (M, X0))
    return X[:m, :m] if mp != m else X


@functools.partial(jax.jit, static_argnames=("panel",))
def chol_mxu_factor(M, panel: int | None = None):
    """IN-PLACE panel Cholesky: (m, m) SPD → ``(L, Winv)`` with L padded
    to a panel multiple and ``Winv`` the (P, p, p) inverses of its
    diagonal blocks (collected as the loop factors each — they power
    :func:`panel_cho_solve`'s substitution sweeps). Carries a SINGLE
    (mp, mp) buffer: each panel's columns are overwritten with the
    finished factor while the trailing region keeps the running Schur
    complement.

    The memory-lean large-m path: the fused `chol_inv_mxu` carries
    (T, X) — with XLA's while-loop double-buffering that is ~4 m²
    buffers live, which at m = 10⁴ f64 (800 MB each) OOM'd next to the
    resident 4 GB constraint matrix; even a separate diag-inverse
    dispatch after this one hit RESOURCE_EXHAUSTED in the full-resident
    context (observed repeatedly, 2026-08-01) — hence everything a
    solve needs comes out of this ONE program. (No donation here: the
    identity-tail pad changes the shape, so a donated input could never
    alias the output — the caller's scale/reg stage owns the donation
    instead.)
    """
    m = M.shape[0]
    p = min(panel if panel is not None else _panel_for(m), m)
    M, mp = _pad_spd(M, p)
    P = mp // p
    rows = jnp.arange(mp)

    def body(j, carry):
        T, Wbuf = carry
        g0 = j * p
        D = jax.lax.dynamic_slice(T, (g0, g0), (p, p))
        C, W = _factor_diag_block(D)
        Tpan = jax.lax.dynamic_slice(T, (0, g0), (mp, p))
        # full finished column block: zeros above the panel, C at the
        # panel rows (Tpan @ Wᵀ equals C there), L below.
        colblk = (Tpan @ W.T) * (rows[:, None] >= g0).astype(T.dtype)
        Lbelow = colblk * (rows[:, None] >= g0 + p).astype(T.dtype)

        # Trailing update in COLUMN CHUNKS: a one-shot
        # ``T - Lbelow @ Lbelowᵀ`` materializes the full (mp, mp)
        # emulated-f64 product, whose 8×-f32 operand/accumulator split
        # temps measured 16.83 GB at m=10⁴ via compiled memory_analysis
        # — more than the chip. Chunk width p keeps each product
        # (mp, p): split temps drop to ~8·mp·p·4 B (~80 MB).
        def upd(jc, T):
            c0 = jc * p
            Lc = jax.lax.dynamic_slice(Lbelow, (c0, 0), (p, p))
            Tc = jax.lax.dynamic_slice(T, (0, c0), (mp, p))
            return jax.lax.dynamic_update_slice(
                T, Tc - Lbelow @ Lc.T, (0, c0)
            )

        # chunks at or left of the panel see only Lbelow's zero rows —
        # start at j + 1 (traced lower bound; fori_loop allows it)
        T = jax.lax.fori_loop(j + 1, P, upd, T)
        T = jax.lax.dynamic_update_slice(T, colblk, (0, g0))
        Wbuf = jax.lax.dynamic_update_slice(Wbuf, W[None], (j, 0, 0))
        return T, Wbuf

    return jax.lax.fori_loop(
        0, P, body, (M, jnp.zeros((P, p, p), M.dtype))
    )


@functools.partial(jax.jit, static_argnames=("panel",))
def panel_diag_inv(L, panel: int | None = None):
    """(P, p, p) inverses of L's diagonal blocks. TEST ORACLE: the
    production path gets these from :func:`chol_mxu_factor`'s collected
    ``Winv`` (they fall out of the panel loop for free, and a separate
    dispatch in the full-resident 10k context hit RESOURCE_EXHAUSTED);
    tests cross-check that collection against this standalone
    derivation."""
    mp = L.shape[0]
    p = min(panel if panel is not None else _panel_for(mp), mp)
    P = mp // p
    idx = jnp.arange(P)
    D = L.reshape(P, p, P, p)[idx, :, idx, :]  # (P, p, p) diagonal blocks
    return jax.vmap(_tri_inv_block)(D)


def panel_cho_solve(L, Winv, b):
    """``(L·Lᵀ)⁻¹ b`` via two panel-substitution fori loops — the
    memory-lean solve of the two-stage large-m path: no explicit m×m
    inverse is ever formed (the fused inverse's X/eye buffers were the
    10k endgame's OOM margin), and each solve reads L once per sweep
    (bandwidth-equivalent to the inverse-GEMV it replaces). ``b`` may be
    shorter than L's padded size; the identity pad tail is inert.
    Traceable — the endgame step jits it into its program."""
    mp = L.shape[0]
    P, p, _ = Winv.shape
    m = b.shape[0]
    bp = jnp.zeros(mp, L.dtype).at[:m].set(b) if m != mp else b

    def fwd(j, y):
        g0 = j * p
        Lrows = jax.lax.dynamic_slice(L, (g0, 0), (p, mp))
        r = jax.lax.dynamic_slice(bp, (g0,), (p,)) - Lrows @ y
        return jax.lax.dynamic_update_slice(y, Winv[j] @ r, (g0,))

    y = jax.lax.fori_loop(0, P, fwd, jnp.zeros(mp, L.dtype))

    def bwd(i, x):
        j = P - 1 - i
        g0 = j * p
        Lcols = jax.lax.dynamic_slice(L, (0, g0), (mp, p))
        r = jax.lax.dynamic_slice(y, (g0,), (p,)) - Lcols.T @ x
        return jax.lax.dynamic_update_slice(x, Winv[j].T @ r, (g0,))

    x = jax.lax.fori_loop(0, P, bwd, jnp.zeros(mp, L.dtype))
    return x[:m] if m != mp else x


@functools.partial(jax.jit, static_argnames=("panel", "out_m"))
def tri_inv_mxu(L, panel: int | None = None, out_m: int | None = None):
    """Explicit L⁻¹ of a (possibly identity-tail-padded) lower-
    triangular L. TEST ORACLE for the panel pipeline (production solves
    never form an m×m inverse — :func:`panel_cho_solve` substitutes
    panel-by-panel precisely because this inverse's X/eye buffers were
    the 10k endgame's OOM margin). ``out_m`` slices the pad back off."""
    mp = L.shape[0]
    p = min(panel if panel is not None else _panel_for(mp), mp)
    rows = jnp.arange(mp)
    X0 = jnp.eye(mp, dtype=L.dtype)

    def body(j, X):
        g0 = j * p
        C = jax.lax.dynamic_slice(L, (g0, g0), (p, p))
        W = _tri_inv_block(C)
        Xp = W @ jax.lax.dynamic_slice(X, (g0, 0), (p, mp))
        X = jax.lax.dynamic_update_slice(X, Xp, (g0, 0))
        Lbelow = jax.lax.dynamic_slice(L, (0, g0), (mp, p)) * (
            rows[:, None] >= g0 + p
        ).astype(L.dtype)
        return X - Lbelow @ Xp

    X = jax.lax.fori_loop(0, mp // p, body, X0)
    return X[:out_m, :out_m] if out_m is not None and out_m != mp else X
