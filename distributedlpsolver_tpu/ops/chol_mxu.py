"""GEMM-dominated Cholesky factorization + explicit triangular inverse.

Why this exists (measured on the v5e chip, scripts/probe_batched_parts.py
and scripts/probe_chol_mxu.py, 2026-08-01): XLA's emulated-f64
``jnp.linalg.cholesky`` on a (128, 128, 128) batch costs ~345 ms and a
single emulated-f64 ``cho_solve`` ~130 ms — they lower to scalarized
recurrences whose every scalar op pays the f64-emulation tax. Meanwhile
emulated-f64 *GEMM* runs at ~150 GFLOP/s with 2.2e-15 max relative error
(the MXU split path), and fused f64 elementwise streams at ~2 ns/element.
Round 4 misattributed the batched backend's wall to "emulated-f64
elementwise" (BASELINE.md batched row); the component probe shows the
factorization and triangular solves own ~75% of the 622 ms step.

So: restructure the factorization so ALL O(m³) work is GEMM and the only
sequential arithmetic is a p-column recursion inside each diagonal block.
This panel scheme is the single-device sibling of ops/dist_chol.py's
mesh panel factorization (SURVEY.md §2 "LA kernels"; BASELINE.json:5
names the dense-Cholesky path) with two differences: the diagonal block
is factored by an unrolled static-slice recursion instead of
``jnp.linalg.cholesky`` (the builtin is the very thing being avoided),
and the triangular inverse is fused into the same panel loop, so a
factorization's 6+ downstream solves (kkt_refine=2 ⇒ 6 per IPM step)
become two batched GEMVs each.

Measured win (same probe): (128, 128, 128) factor+full-inverse ~35 ms vs
~350 ms builtin factor alone — ~10× — and each solve drops from ~20 ms
to GEMV noise. Accuracy: ||M⁻¹M − I||_max = 1.7e-10 at cond 7.5e5 and
3.2e-13 at m = 2048 — the backward-stable class expected of an IEEE-f64
right-looking Cholesky (identical operation set, blocked order).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _factor_diag_block(D):
    """(p, p) SPD block → (C, W) with ``C = chol(D)`` and ``W = C⁻¹``.

    Unrolled static-slice column recursion (p is a Python int, so every
    slice below is static): per column, one sqrt + one scaled column +
    one rank-1 trailing update; then W by unrolled forward substitution
    on the identity. 2p fused elementwise steps total — at p ≤ 32 this
    is microseconds of VPU work even under f64 emulation. Breakdown
    (non-SPD D) yields NaN from the sqrt and propagates, matching the
    builtin's contract.
    """
    p = D.shape[0]
    C = jnp.zeros_like(D)
    for i in range(p):
        r = jnp.sqrt(D[i, i])
        col = D[i:, i] / r
        C = C.at[i:, i].set(col)
        if i + 1 < p:
            t = col[1:]
            D = D.at[i + 1 :, i + 1 :].add(-t[:, None] * t[None, :])
    W = jnp.zeros_like(C)
    for i in range(p):
        if i == 0:
            row = jnp.zeros((p,), C.dtype).at[0].set(1.0 / C[0, 0])
        else:
            e = jnp.zeros((p,), C.dtype).at[i].set(1.0)
            row = (e - C[i, :i] @ W[:i, :]) / C[i, i]
        W = W.at[i, :].set(row)
    return C, W


def _panel_for(m: int) -> int:
    """Default panel width: small blocks keep the unrolled recursion
    short where the batch axis supplies parallelism (measured best at
    p=16 for the (128, 128) members); large m amortizes panel GEMMs
    better at wider panels (p=256 beat 128 at m=2048)."""
    if m <= 512:
        return 16
    if m < 2048:
        return 128
    return 256


@functools.partial(jax.jit, static_argnames=("panel",))
def chol_inv_mxu(M, panel: int | None = None):
    """``L⁻¹`` for ``M = L·Lᵀ`` (SPD), all O(m³) on the MXU.

    Unbatched (m, m) → (m, m) lower-triangular ``Linv`` with
    ``M⁻¹ = Linvᵀ·Linv``; ``vmap`` supplies the batch axis (the batched
    backend's usage). Ragged m is padded to a panel multiple with an
    identity tail (factors to I, inert, sliced off — same device-side
    trick as ops/dist_chol.py) entirely under jit.

    Per panel j (rows/cols [g0, g0+p)) on the running trailing matrix T
    and inverse accumulator X (init I):

        C, W  = factor+invert T[diag block]         (2p fused steps)
        L_below = T[:, panel] · Wᵀ  masked rows ≥ g0+p   (GEMM)
        T    −= L_below · L_belowᵀ                  (GEMM)
        X[panel rows]  = W · X[panel rows]          (GEMM)
        X[below rows] −= L_below · X[panel rows]    (GEMM)

    The processed region of T is never read again (its garbage is
    masked out of every later panel), and L itself is never stored —
    the inverse substitution consumes each panel in the iteration that
    produces it.
    """
    m = M.shape[0]
    p = panel if panel is not None else _panel_for(m)
    p = min(p, m)
    mp = -(-m // p) * p
    if mp != m:
        pad = mp - m
        M = jnp.pad(M, ((0, pad), (0, pad)))
        M = M.at[jnp.arange(m, mp), jnp.arange(m, mp)].set(1.0)
    P = mp // p
    rows = jnp.arange(mp)
    X0 = jnp.eye(mp, dtype=M.dtype)

    def body(j, carry):
        T, X = carry
        g0 = j * p
        D = jax.lax.dynamic_slice(T, (g0, g0), (p, p))
        C, W = _factor_diag_block(D)
        Tpan = jax.lax.dynamic_slice(T, (0, g0), (mp, p))
        # Only the below-panel rows of the L panel are ever consumed
        # (the panel rows' C is already folded into W; rows above hold
        # stale M values the mask discards).
        Lbelow = (Tpan @ W.T) * (rows[:, None] >= g0 + p).astype(M.dtype)
        T = T - Lbelow @ Lbelow.T
        Xp = jax.lax.dynamic_slice(X, (g0, 0), (p, mp))
        Xp = W @ Xp
        X = jax.lax.dynamic_update_slice(X, Xp, (g0, 0))
        X = X - Lbelow @ Xp
        return T, X

    _, X = jax.lax.fori_loop(0, P, body, (M, X0))
    return X[:m, :m] if mp != m else X
