"""Padded fixed-shape sparse operator for the matrix-free solve tier.

Everything above this module still rides dense normal equations; the
storm-class ≥100k-row wall (ROUND5_NOTES lever 4) and the 10 GB dense
assembly arena at the 10k flagship say that path is ending. This layer is
the huge-sparse tier's answer: a hybrid row-ELL representation of the
constraint matrix — ``vals``/``cols`` padded to one static
nonzeros-per-row width, plus a fixed-length COO spill ``tail`` for the
few rows heavier than that width — whose ``matvec``/``rmatvec``/
``normal_diag`` are pure gathers + reductions (+ one bounded
scatter-add for the tail), so they jit into fixed-shape XLA programs
(no data-dependent shapes, SURVEY.md §7) and the m×m normal matrix
``A·diag(d)·Aᵀ`` is never materialized in any format.

Why hybrid and not plain ELL: a plain ELL pads EVERY row to the widest
row's count. The storm-class bordered pattern makes that pathological —
a first-stage column touched by every scenario turns into a transpose
row with K·t_nnz entries, padding the other 30k columns to width ~1000
(hundreds of MB and a 100× matvec slowdown for <0.3% of the nonzeros).
The hybrid keeps the ELL width at a quantile of the row-count
distribution and spills the heavy tails into a quantized-length COO
triple processed by one ``at[].add`` — both shapes static.

Why ELL and not BCOO: the serve/backends layers key compiled programs on
array SHAPES. A BCOO's nse rides the value count of one instance; the
ELL pad width and tail length are quantized (``_PAD_QUANTUM``/
``_TAIL_QUANTUM``), so same-profile instances (parameterized storm
scenarios, correlated streams) share one compiled program. The
transpose is stored as a second hybrid ELL (``tvals``/``tcols`` +
``ttail``) — an O(nnz) one-time host cost that turns ``rmatvec`` into
the same gather-reduce shape as ``matvec`` instead of a full scatter.

Dense fallback: below ~25% density the hybrid wins on both bytes and
gather locality; above it (or at tiny shapes) the operator stores a
plain dense array and the same API degenerates to GEMV — callers never
branch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import scipy.sparse as sp

import jax
import jax.numpy as jnp

# Quantize the ELL pad width so instances with nearly-equal row-count
# quantiles share one (m, k) program shape (the bucket-ladder idea
# applied to sparsity): width rounds up to the next multiple.
_PAD_QUANTUM = 8

# Quantize the COO spill-tail length the same way (pad entries point at
# a synthetic row with value 0, so the scatter-add is a no-op for them).
_TAIL_QUANTUM = 256

# ELL width = this quantile of the per-row nonzero counts; rows heavier
# than the (quantized) quantile spill their excess into the tail. 1.0
# would recover plain ELL; 0.98 keeps the width at the bulk of the
# distribution while the bordered pattern's ~n1 dense-ish transpose rows
# ride the tail.
_WIDTH_QUANTILE = 0.98

# Above this density the ELL gathers cost more than a dense GEMV and the
# padded arrays approach the dense footprint — store dense instead.
DENSE_FALLBACK_DENSITY = 0.25

# Below this many entries a dense operator is unconditionally cheaper
# (gather setup dominates at tiny shapes).
_DENSE_FALLBACK_ENTRIES = 16_384


@dataclasses.dataclass(frozen=True)
class SparseOperator:
    """Fixed-shape sparse (or dense-fallback) linear operator.

    ``fmt == "ell"``: ``vals``/``cols`` are (m, k) row-ELL arrays of A
    (pad entries carry col 0 / val 0) and ``tail_vals``/``tail_rows``/
    ``tail_cols`` the fixed-length COO spill of rows wider than k (pad
    entries carry row m / val 0 — they scatter into a synthetic slot
    that is sliced off); ``tvals``/``tcols`` + ``ttail_*`` the same
    hybrid for Aᵀ. ``fmt == "dense"``: ``dense`` holds A itself and the
    hybrid fields are None. Registered as a jax pytree — an operator is
    an ordinary traced operand of the jitted kernels, so two same-shape
    instances share one compiled program.
    """

    shape: Tuple[int, int]
    nnz: int
    fmt: str  # "ell" | "dense"
    vals: Optional[jnp.ndarray] = None  # (m, k)
    cols: Optional[jnp.ndarray] = None  # (m, k) int32
    tail_vals: Optional[jnp.ndarray] = None  # (t,)
    tail_rows: Optional[jnp.ndarray] = None  # (t,) int32, pad → m
    tail_cols: Optional[jnp.ndarray] = None  # (t,) int32
    tvals: Optional[jnp.ndarray] = None  # (n, kt)
    tcols: Optional[jnp.ndarray] = None  # (n, kt) int32
    ttail_vals: Optional[jnp.ndarray] = None  # (tt,)
    ttail_rows: Optional[jnp.ndarray] = None  # (tt,) int32, pad → n
    ttail_cols: Optional[jnp.ndarray] = None  # (tt,) int32
    dense: Optional[jnp.ndarray] = None  # (m, n) fallback

    @property
    def m(self) -> int:
        return self.shape[0]

    @property
    def n(self) -> int:
        return self.shape[1]

    @property
    def density(self) -> float:
        return self.nnz / max(self.m * self.n, 1)

    @property
    def dtype(self):
        return self.dense.dtype if self.fmt == "dense" else self.vals.dtype

    # -- linear maps (jittable: self is a pytree operand) ---------------

    def matvec(self, v):
        """A @ v, (n,) → (m,) — one gather + reduction over the ELL part
        plus one bounded scatter-add for the spill tail."""
        if self.fmt == "dense":
            return self.dense @ v
        out = jnp.sum(self.vals * v[self.cols], axis=1)
        return _tail_add(
            out, self.tail_vals, self.tail_rows, self.tail_cols, v
        )

    def rmatvec(self, v):
        """Aᵀ @ v, (m,) → (n,) — same hybrid shape via the transpose
        ELL (no full scatter on the hot path)."""
        if self.fmt == "dense":
            return self.dense.T @ v
        out = jnp.sum(self.tvals * v[self.tcols], axis=1)
        return _tail_add(
            out, self.ttail_vals, self.ttail_rows, self.ttail_cols, v
        )

    def normal_diag(self, d, reg=0.0):
        """diag(A·diag(d)·Aᵀ) + reg — the Jacobi preconditioner of the
        normal equations, computed WITHOUT forming the normal matrix:
        entry i is Σ_j A_ij²·d_j."""
        if self.fmt == "dense":
            return jnp.sum(self.dense * self.dense * d[None, :], axis=1) + reg
        out = jnp.sum(self.vals * self.vals * d[self.cols], axis=1)
        if self.tail_vals is not None:
            out = _scatter_sq(
                out, self.tail_vals, self.tail_rows, d[self.tail_cols]
            )
        return out + reg

    def row_norms(self):
        """Per-row 2-norms of A (the PDHG/scaling diagnostics surface)."""
        if self.fmt == "dense":
            return jnp.sqrt(jnp.sum(self.dense * self.dense, axis=1))
        sq = jnp.sum(self.vals * self.vals, axis=1)
        if self.tail_vals is not None:
            sq = _scatter_sq(sq, self.tail_vals, self.tail_rows, None)
        return jnp.sqrt(sq)

    def col_norms(self):
        if self.fmt == "dense":
            return jnp.sqrt(jnp.sum(self.dense * self.dense, axis=0))
        sq = jnp.sum(self.tvals * self.tvals, axis=1)
        if self.ttail_vals is not None:
            sq = _scatter_sq(sq, self.ttail_vals, self.ttail_rows, None)
        return jnp.sqrt(sq)

    def scaled(self, dr, dc) -> "SparseOperator":
        """Dr·A·Dc as a new operator — sparse-aware Ruiz application:
        only the O(nnz) value arrays are rescaled, the pattern (and the
        compiled-program shape) is untouched."""
        dr = jnp.asarray(dr, dtype=self.dtype)
        dc = jnp.asarray(dc, dtype=self.dtype)
        if self.fmt == "dense":
            return dataclasses.replace(
                self, dense=self.dense * dr[:, None] * dc[None, :]
            )
        # Pad entries index synthetic row m / col 0; append a 1 so the
        # gather stays a no-op for them (their value is 0 anyway).
        dr1 = jnp.concatenate([dr, jnp.ones((1,), dr.dtype)])
        dc1 = jnp.concatenate([dc, jnp.ones((1,), dc.dtype)])
        rep = {
            "vals": self.vals * dr[:, None] * dc[self.cols],
            "tvals": self.tvals * dc[:, None] * dr[self.tcols],
        }
        if self.tail_vals is not None:
            rep["tail_vals"] = (
                self.tail_vals * dr1[self.tail_rows] * dc[self.tail_cols]
            )
        if self.ttail_vals is not None:
            rep["ttail_vals"] = (
                self.ttail_vals * dc1[self.ttail_rows] * dr[self.ttail_cols]
            )
        return dataclasses.replace(self, **rep)

    # -- host-side helpers ----------------------------------------------

    def to_scipy(self) -> sp.csr_matrix:
        """Exact CSR reconstruction (tests / oracles)."""
        if self.fmt == "dense":
            return sp.csr_matrix(np.asarray(self.dense, dtype=np.float64))
        m, k = self.vals.shape
        rows = np.repeat(np.arange(m), k)
        vals = np.asarray(self.vals, dtype=np.float64).ravel()
        cols = np.asarray(self.cols).ravel()
        if self.tail_vals is not None:
            rows = np.concatenate([rows, np.asarray(self.tail_rows)])
            vals = np.concatenate(
                [vals, np.asarray(self.tail_vals, dtype=np.float64)]
            )
            cols = np.concatenate([cols, np.asarray(self.tail_cols)])
        live = (vals != 0.0) & (rows < m)
        return sp.csr_matrix(
            (vals[live], (rows[live], cols[live])), shape=self.shape
        )

    def nbytes(self) -> int:
        return sum(int(a.size) * a.dtype.itemsize for a in self._arrays())

    def memory_report(self) -> dict:
        """name → {shape, nbytes} of every device array held — the
        no-dense-normal-matrix guard: the acceptance test asserts no
        array approaches (m, m) and total bytes stay far below m²·8."""
        out = {}
        for name in (
            "vals", "cols", "tail_vals", "tail_rows", "tail_cols",
            "tvals", "tcols", "ttail_vals", "ttail_rows", "ttail_cols",
            "dense",
        ):
            a = getattr(self, name)
            if a is not None:
                out[name] = {
                    "shape": tuple(int(s) for s in a.shape),
                    "nbytes": int(a.size) * a.dtype.itemsize,
                }
        return out

    def _arrays(self):
        return [
            a
            for a in (
                self.vals, self.cols,
                self.tail_vals, self.tail_rows, self.tail_cols,
                self.tvals, self.tcols,
                self.ttail_vals, self.ttail_rows, self.ttail_cols,
                self.dense,
            )
            if a is not None
        ]


def _tail_add(out, tail_vals, tail_rows, tail_cols, v):
    """out += scatter(tail · v[tail_cols]) with the synthetic pad slot
    (index len(out)) sliced off — a no-op when there is no tail."""
    if tail_vals is None:
        return out
    pad = jnp.zeros((1,), dtype=out.dtype)
    acc = jnp.concatenate([out, pad])
    acc = acc.at[tail_rows].add(tail_vals * v[tail_cols])
    return acc[:-1]


def _scatter_sq(out, tail_vals, tail_rows, w):
    """out += scatter(tail² · w) (w=None → 1) through the pad slot."""
    contrib = tail_vals * tail_vals if w is None else tail_vals * tail_vals * w
    pad = jnp.zeros((1,), dtype=out.dtype)
    acc = jnp.concatenate([out, pad])
    acc = acc.at[tail_rows].add(contrib)
    return acc[:-1]


def _quantize(k: int, q: int) -> int:
    return max(q, -(-k // q) * q)


def _hybrid_width(A: sp.csr_matrix) -> int:
    """Quantized ``_WIDTH_QUANTILE`` ELL width for CSR ``A`` — the shape
    probe, split out so the row shards can agree on one common width."""
    m = A.shape[0]
    counts = np.diff(A.indptr)
    kmax = int(counts.max(initial=0))
    kq = int(np.quantile(counts, _WIDTH_QUANTILE)) if m else 0
    k = _quantize(max(kq, 1), _PAD_QUANTUM)
    if k >= kmax:
        k = _quantize(max(kmax, 1), _PAD_QUANTUM)
    return k


def _hybrid_fill(A: sp.csr_matrix, dtype, k, t, rows_out, pad_row):
    """Hybrid row-ELL of CSR ``A`` at FORCED shapes: an (rows_out, k)
    ELL block (rows beyond A's are all-pad) plus a COO tail of exactly
    ``t`` entries (``t == 0`` → no tail; pad tail entries point at
    ``pad_row`` with value 0). The forced-shape builder lets every row
    shard of a distributed operator run one program shape regardless of
    which shard drew the heavy rows."""
    m = A.shape[0]
    counts = np.diff(A.indptr)
    # Position of each nonzero within its row, vectorized.
    offs = np.arange(A.nnz, dtype=np.int64) - np.repeat(
        A.indptr[:-1].astype(np.int64), counts
    )
    rowidx = np.repeat(np.arange(m, dtype=np.int64), counts)
    main = offs < k

    vals = np.zeros((rows_out, k), dtype=dtype)
    cols = np.zeros((rows_out, k), dtype=np.int32)
    vals[rowidx[main], offs[main]] = A.data[main]
    cols[rowidx[main], offs[main]] = A.indices[main]

    if t == 0:
        return vals, cols, None, None, None
    spill = ~main
    t_live = int(spill.sum())
    tail_vals = np.zeros((t,), dtype=dtype)
    tail_rows = np.full((t,), pad_row, dtype=np.int32)
    tail_cols = np.zeros((t,), dtype=np.int32)
    tail_vals[:t_live] = A.data[spill]
    tail_rows[:t_live] = rowidx[spill]
    tail_cols[:t_live] = A.indices[spill]
    return vals, cols, tail_vals, tail_rows, tail_cols


def _tail_len(A: sp.csr_matrix, k: int) -> int:
    """Live spill-tail length of CSR ``A`` at ELL width ``k``."""
    counts = np.diff(A.indptr)
    return int(np.maximum(counts - k, 0).sum())


def _hybrid_from_csr(A: sp.csr_matrix, dtype):
    """(vals, cols, tail_vals, tail_rows, tail_cols) hybrid row-ELL of a
    CSR matrix. ELL width is the quantized ``_WIDTH_QUANTILE`` of the
    per-row counts; heavier rows spill their excess into the COO tail
    (quantized length; pad entries point at synthetic row m with value
    0). ELL pad entries point at column 0 with value 0 — the matvec
    gather stays in bounds and the padded products vanish."""
    m = A.shape[0]
    k = _hybrid_width(A)
    t_live = _tail_len(A, k)
    t = _quantize(t_live, _TAIL_QUANTUM) if t_live else 0
    return _hybrid_fill(A, dtype, k, t, m, m)


def from_scipy(
    A,
    dtype=np.float64,
    density_threshold: float = DENSE_FALLBACK_DENSITY,
) -> SparseOperator:
    """Build a :class:`SparseOperator` from scipy-sparse or dense input
    WITHOUT densifying sparse inputs (the whole point of the tier);
    dense-ish or tiny inputs take the dense fallback."""
    if sp.issparse(A):
        A = A.tocsr()
        m, n = A.shape
        nnz = int(A.nnz)
        dens = nnz / max(m * n, 1)
        if dens <= density_threshold and m * n > _DENSE_FALLBACK_ENTRIES:
            vals, cols, tv_, tr_, tc_ = _hybrid_from_csr(A, dtype)
            tvals, tcols, ttv, ttr, ttc = _hybrid_from_csr(
                A.T.tocsr(), dtype
            )
            j = jnp.asarray
            return SparseOperator(
                shape=(m, n),
                nnz=nnz,
                fmt="ell",
                vals=j(vals),
                cols=j(cols),
                tail_vals=None if tv_ is None else j(tv_),
                tail_rows=None if tr_ is None else j(tr_),
                tail_cols=None if tc_ is None else j(tc_),
                tvals=j(tvals),
                tcols=j(tcols),
                ttail_vals=None if ttv is None else j(ttv),
                ttail_rows=None if ttr is None else j(ttr),
                ttail_cols=None if ttc is None else j(ttc),
            )
        Ad = np.asarray(A.todense(), dtype=dtype)
    else:
        Ad = np.asarray(A, dtype=dtype)
        nnz = int(np.count_nonzero(Ad))
    m, n = Ad.shape
    return SparseOperator(
        shape=(m, n), nnz=nnz, fmt="dense", dense=jnp.asarray(Ad)
    )


def from_problem(inf, dtype=np.float64, **kw) -> SparseOperator:
    """Operator over an LPProblem/InteriorForm's constraint matrix."""
    return from_scipy(inf.A, dtype=dtype, **kw)


def ruiz_equilibrate(
    op: SparseOperator, iterations: int = 10, tol: float = 1e-2
):
    """Sparse-aware Ruiz scaling on the operator itself: ∞-norm row/col
    equilibration computed from the hybrid value arrays (O(nnz) per
    sweep, no CSR round trips), returning ``(scaled_op, dr, dc)`` with
    the same convention as models/scaling.equilibrate (A' = Dr·A·Dc)."""
    if op.fmt == "dense":
        absA = np.abs(np.asarray(op.dense, dtype=np.float64))
        m, n = absA.shape
        dr = np.ones(m)
        dc = np.ones(n)
        for _ in range(iterations):
            row = absA.max(axis=1, initial=0.0)
            col = absA.max(axis=0, initial=0.0)
            if (np.abs(row[row > 0] - 1.0) < tol).all() and (
                np.abs(col[col > 0] - 1.0) < tol
            ).all():
                break
            r = np.where(row > 0, 1.0 / np.sqrt(row), 1.0)
            c = np.where(col > 0, 1.0 / np.sqrt(col), 1.0)
            absA *= r[:, None]
            absA *= c
            dr *= r
            dc *= c
        return op.scaled(dr, dc), dr, dc
    vals = np.abs(np.asarray(op.vals, dtype=np.float64))
    tvals = np.abs(np.asarray(op.tvals, dtype=np.float64))
    cols = np.asarray(op.cols)
    tcols = np.asarray(op.tcols)
    has_tail = op.tail_vals is not None
    has_ttail = op.ttail_vals is not None
    if has_tail:
        a_tv = np.abs(np.asarray(op.tail_vals, dtype=np.float64))
        a_tr = np.asarray(op.tail_rows)
        a_tc = np.asarray(op.tail_cols)
    if has_ttail:
        t_tv = np.abs(np.asarray(op.ttail_vals, dtype=np.float64))
        t_tr = np.asarray(op.ttail_rows)
        t_tc = np.asarray(op.ttail_cols)
    dr = np.ones(op.m)
    dc = np.ones(op.n)
    for _ in range(iterations):
        row = np.zeros(op.m + 1)
        row[: op.m] = vals.max(axis=1, initial=0.0)
        if has_tail:
            np.maximum.at(row, a_tr, a_tv)
        row = row[: op.m]
        col = np.zeros(op.n + 1)
        col[: op.n] = tvals.max(axis=1, initial=0.0)
        if has_ttail:
            np.maximum.at(col, t_tr, t_tv)
        col = col[: op.n]
        if (np.abs(row[row > 0] - 1.0) < tol).all() and (
            np.abs(col[col > 0] - 1.0) < tol
        ).all():
            break
        r = 1.0 / np.sqrt(np.where(row > 0, row, 1.0))
        c = 1.0 / np.sqrt(np.where(col > 0, col, 1.0))
        vals *= r[:, None]
        vals *= c[cols]
        tvals *= c[:, None]
        tvals *= r[tcols]
        if has_tail:
            r1 = np.concatenate([r, [1.0]])
            a_tv *= r1[a_tr] * c[a_tc]
        if has_ttail:
            c1 = np.concatenate([c, [1.0]])
            t_tv *= c1[t_tr] * r[t_tc]
        dr *= r
        dc *= c
    return op.scaled(dr, dc), dr, dc


_CHILD_FIELDS = (
    "vals", "cols", "tail_vals", "tail_rows", "tail_cols",
    "tvals", "tcols", "ttail_vals", "ttail_rows", "ttail_cols",
    "dense",
)


def _flatten(op: SparseOperator):
    children = tuple(getattr(op, f) for f in _CHILD_FIELDS)
    aux = (op.shape, op.nnz, op.fmt)
    return children, aux


def _unflatten(aux, children):
    shape, nnz, fmt = aux
    kw = dict(zip(_CHILD_FIELDS, children))
    return SparseOperator(shape=shape, nnz=nnz, fmt=fmt, **kw)


jax.tree_util.register_pytree_node(SparseOperator, _flatten, _unflatten)


# ===========================================================================
# Row-distributed tier: RowShardedOperator + shard_rows
# ===========================================================================
#
# The SDSL design (PAPERS.md, arXiv 2604.23979): partition A's ROWS over
# the mesh, keep every product local to its shard, and let exactly one
# n-vector collective per normal-operator application carry the coupling:
#
#     v ↦ psum_r( A_r · (d ∘ A_rᵀ v) ) + reg·v
#
# Each shard holds a hybrid row-ELL block padded to a common row count
# ``mb_pad`` (one program shape on every rank); ELL/tail widths are the
# max over shards, quantized, so the stacked (R, mb_pad, k) arrays shard
# cleanly along the leading axis via ``batch_sharding``. Column indices
# stay GLOBAL int32 — the n-sized vectors (v, d, rmatvec output) are
# replicated, so local gathers index them directly. The transpose hybrid
# is per-shard with LOCAL row indices; its (R, n, kt) partial products
# reduce over the shard axis — that ``jnp.sum(·, axis=0)`` over a
# mesh-sharded leading axis IS the psum (XLA inserts the all-reduce),
# and it is the only collective in the distributed normal matvec.
# ADAᵀ is still never materialized — now per-shard.


@dataclasses.dataclass(frozen=True)
class RowShardedOperator:
    """Row-distributed hybrid-ELL operator over a device mesh.

    Children are stacked per-shard arrays with the shard axis leading;
    m-sized vectors travel FLAT as (R·mb_pad,) = ``m_pad`` arrays
    sharded along the same mesh axis (shard r owns slots
    [r·mb_pad, (r+1)·mb_pad)), so a reshape to (R, mb_pad) is free and
    local. ``row_map`` (replicated) sends global row i to its padded
    flat slot; ``row_ok`` masks the pad rows. Registered as a pytree
    with the (hashable) mesh in the treedef aux — jit keys one program
    per (shapes, mesh) automatically.
    """

    shape: Tuple[int, int]
    nnz: int
    fmt: str  # "ell" | "dense"
    num_shards: int
    rows_per: int  # global rows per shard (last shard may own fewer)
    mb_pad: int  # padded per-shard row count (common program shape)
    mesh: Optional[object] = None  # jax.sharding.Mesh (hashable) | None
    axis: Optional[str] = None
    vals: Optional[jnp.ndarray] = None  # (R, mb_pad, k)
    cols: Optional[jnp.ndarray] = None  # (R, mb_pad, k) int32, GLOBAL
    tail_vals: Optional[jnp.ndarray] = None  # (R, t)
    tail_rows: Optional[jnp.ndarray] = None  # (R, t) int32 LOCAL, pad → mb_pad
    tail_cols: Optional[jnp.ndarray] = None  # (R, t) int32 GLOBAL
    tvals: Optional[jnp.ndarray] = None  # (R, n, kt)
    tcols: Optional[jnp.ndarray] = None  # (R, n, kt) int32 LOCAL row
    ttail_vals: Optional[jnp.ndarray] = None  # (R, tt)
    ttail_rows: Optional[jnp.ndarray] = None  # (R, tt) int32 out-row, pad → n
    ttail_cols: Optional[jnp.ndarray] = None  # (R, tt) int32 LOCAL row
    dense: Optional[jnp.ndarray] = None  # (R, mb_pad, n) fallback
    row_map: Optional[jnp.ndarray] = None  # (m,) int32, replicated
    row_ok: Optional[jnp.ndarray] = None  # (R, mb_pad) bool

    @property
    def m(self) -> int:
        return self.shape[0]

    @property
    def n(self) -> int:
        return self.shape[1]

    @property
    def m_pad(self) -> int:
        return self.num_shards * self.mb_pad

    @property
    def dtype(self):
        return self.dense.dtype if self.fmt == "dense" else self.vals.dtype

    def _constrain_flat(self, x):
        """Pin an (m_pad,) vector's layout to the row-shard split."""
        if self.mesh is None:
            return x
        sh = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(self.axis)
        )
        return jax.lax.with_sharding_constraint(x, sh)

    # -- local products (jittable; self is a pytree operand) ------------

    def matvec_local(self, v):
        """A_r @ v per shard: (n,) replicated → (R, mb_pad) local.
        Pure gathers + reductions; no collective."""
        if self.fmt == "dense":
            return jnp.einsum("rmn,n->rm", self.dense, v)
        out = jnp.sum(self.vals * v[self.cols], axis=2)
        if self.tail_vals is None:
            return out
        R = self.num_shards
        pad = jnp.zeros((R, 1), dtype=out.dtype)
        acc = jnp.concatenate([out, pad], axis=1)
        acc = acc.at[jnp.arange(R)[:, None], self.tail_rows].add(
            self.tail_vals * v[self.tail_cols]
        )
        return acc[:, :-1]

    def rmatvec_partial(self, y_flat):
        """Per-shard A_rᵀ y_r: (m_pad,) sharded → (R, n) partial sums.
        Still no collective — callers reduce over axis 0."""
        R = self.num_shards
        y2 = y_flat.reshape(R, self.mb_pad)
        if self.fmt == "dense":
            return jnp.einsum("rmn,rm->rn", self.dense, y2)
        gathered = y2[jnp.arange(R)[:, None, None], self.tcols]
        out = jnp.sum(self.tvals * gathered, axis=2)
        if self.ttail_vals is None:
            return out
        pad = jnp.zeros((R, 1), dtype=out.dtype)
        acc = jnp.concatenate([out, pad], axis=1)
        contrib = self.ttail_vals * y2[
            jnp.arange(R)[:, None], self.ttail_cols
        ]
        acc = acc.at[jnp.arange(R)[:, None], self.ttail_rows].add(contrib)
        return acc[:, :-1]

    # -- distributed maps ------------------------------------------------

    def rmatvec_flat(self, y_flat):
        """Aᵀy for a flat padded m-vector — the ONE collective: the
        (R, n) partials reduce over the mesh-sharded shard axis, which
        XLA compiles to a single n-vector all-reduce (psum)."""
        return jnp.sum(self.rmatvec_partial(y_flat), axis=0)

    def normal_matvec(self, d, reg, v_flat):
        """The distributed normal-operator seam
        ``v ↦ psum_r(A_r(d∘A_rᵀv)) + reg·v`` on flat padded m-vectors.
        Exactly one n-vector rides the collective per application; the
        m-sized work never leaves its shard. Pad slots stay exactly 0
        (zero rows, and CG feeds them zero rhs)."""
        w = self.rmatvec_flat(v_flat)
        u = self.matvec_local(d * w).reshape(-1)
        return self._constrain_flat(u + reg * v_flat)

    def normal_diag(self, d, reg=0.0):
        """diag(A·diag(d)·Aᵀ) + reg as a flat (m_pad,) vector, computed
        shard-locally (no collective); pad rows get 1.0 so Jacobi stays
        finite there."""
        if self.fmt == "dense":
            sq = jnp.einsum("rmn,n->rm", self.dense * self.dense, d)
        else:
            sq = jnp.sum(self.vals * self.vals * d[self.cols], axis=2)
            if self.tail_vals is not None:
                R = self.num_shards
                pad = jnp.zeros((R, 1), dtype=sq.dtype)
                acc = jnp.concatenate([sq, pad], axis=1)
                acc = acc.at[jnp.arange(R)[:, None], self.tail_rows].add(
                    self.tail_vals * self.tail_vals * d[self.tail_cols]
                )
                sq = acc[:, :-1]
        out = jnp.where(self.row_ok, sq + reg, jnp.ones((), dtype=sq.dtype))
        return self._constrain_flat(out.reshape(-1))

    def embed(self, r):
        """(m,) global rhs → (m_pad,) flat padded vector on the mesh."""
        z = jnp.zeros((self.m_pad,), dtype=r.dtype)
        return self._constrain_flat(z.at[self.row_map].set(r))

    def extract(self, x_flat):
        """(m_pad,) flat padded vector → (m,) global order."""
        return x_flat[self.row_map]

    # -- whole-matrix adapters (tests / residuals; not the CG hot path) -

    def matvec(self, v):
        """A @ v, (n,) → (m,) in global row order."""
        return self.extract(
            self._constrain_flat(self.matvec_local(v).reshape(-1))
        )

    def rmatvec(self, y):
        """Aᵀ @ y, (m,) global → (n,)."""
        return self.rmatvec_flat(self.embed(y))

    # -- host-side helpers ----------------------------------------------

    def nbytes(self) -> int:
        return sum(int(a.size) * a.dtype.itemsize for a in self._arrays())

    def nbytes_per_device(self) -> int:
        """Max live operand bytes on ONE device: sharded arrays divide
        by R, replicated ones (row_map) count whole — the quantity the
        ≈1/N memory-scaling acceptance guard asserts on."""
        total = 0
        for name, a in self._named_arrays():
            if name == "row_map":
                total += int(a.size) * a.dtype.itemsize
            else:
                total += int(a.size) * a.dtype.itemsize // self.num_shards
        return total

    def memory_report(self) -> dict:
        """name → {shape, nbytes, nbytes_per_device} — the per-device
        view of the no-dense-normal-matrix guard."""
        out = {}
        for name, a in self._named_arrays():
            per = int(a.size) * a.dtype.itemsize
            out[name] = {
                "shape": tuple(int(s) for s in a.shape),
                "nbytes": per,
                "nbytes_per_device": (
                    per if name == "row_map" else per // self.num_shards
                ),
            }
        return out

    def to_scipy(self) -> sp.csr_matrix:
        """Exact CSR reconstruction in global row order (tests)."""
        if self.fmt == "dense":
            blocks = np.asarray(self.dense, dtype=np.float64)
            flat = blocks.reshape(self.m_pad, self.n)
            rows = np.asarray(self.row_map)
            return sp.csr_matrix(flat[rows])
        R, mb, k = self.vals.shape
        vals = np.asarray(self.vals, dtype=np.float64).ravel()
        cols = np.asarray(self.cols).ravel()
        rows = np.repeat(np.arange(R * mb), k)  # flat padded row ids
        if self.tail_vals is not None:
            tv = np.asarray(self.tail_vals, dtype=np.float64).ravel()
            tr = (
                np.asarray(self.tail_rows)
                + np.arange(R)[:, None] * mb
            ).ravel()
            tc = np.asarray(self.tail_cols).ravel()
            # Pad tail entries point at local row mb → clamp to a dead
            # flat slot; their values are 0 so the live filter drops them.
            tr = np.minimum(tr, R * mb)
            vals = np.concatenate([vals, tv])
            rows = np.concatenate([rows, tr])
            cols = np.concatenate([cols, tc])
        # Invert row_map: flat padded slot → global row (dead slots → m).
        inv = np.full(R * mb + 1, self.m, dtype=np.int64)
        inv[np.asarray(self.row_map)] = np.arange(self.m)
        grow = inv[np.minimum(rows, R * mb)]
        live = (vals != 0.0) & (grow < self.m)
        return sp.csr_matrix(
            (vals[live], (grow[live], cols[live])), shape=self.shape
        )

    def _named_arrays(self):
        for name in (
            "vals", "cols", "tail_vals", "tail_rows", "tail_cols",
            "tvals", "tcols", "ttail_vals", "ttail_rows", "ttail_cols",
            "dense", "row_map", "row_ok",
        ):
            a = getattr(self, name)
            if a is not None:
                yield name, a

    def _arrays(self):
        return [a for _, a in self._named_arrays()]


_RS_CHILD_FIELDS = (
    "vals", "cols", "tail_vals", "tail_rows", "tail_cols",
    "tvals", "tcols", "ttail_vals", "ttail_rows", "ttail_cols",
    "dense", "row_map", "row_ok",
)


def _rs_flatten(op: RowShardedOperator):
    children = tuple(getattr(op, f) for f in _RS_CHILD_FIELDS)
    aux = (
        op.shape, op.nnz, op.fmt, op.num_shards, op.rows_per, op.mb_pad,
        op.mesh, op.axis,
    )
    return children, aux


def _rs_unflatten(aux, children):
    shape, nnz, fmt, num_shards, rows_per, mb_pad, mesh, axis = aux
    kw = dict(zip(_RS_CHILD_FIELDS, children))
    return RowShardedOperator(
        shape=shape, nnz=nnz, fmt=fmt, num_shards=num_shards,
        rows_per=rows_per, mb_pad=mb_pad, mesh=mesh, axis=axis, **kw
    )


jax.tree_util.register_pytree_node(
    RowShardedOperator, _rs_flatten, _rs_unflatten
)


def _shard_axis(mesh, axis: Optional[str]) -> str:
    if axis is not None:
        return axis
    return "batch" if "batch" in mesh.axis_names else mesh.axis_names[-1]


def shard_rows(
    op,
    mesh,
    dtype=None,
    axis: Optional[str] = None,
) -> RowShardedOperator:
    """Partition a :class:`SparseOperator` (or scipy matrix) row-wise
    over ``mesh`` into a :class:`RowShardedOperator`.

    Shard r owns the contiguous global rows
    [r·rows_per, min((r+1)·rows_per, m)) with rows_per = ⌈m/R⌉; every
    shard's hybrid block is padded to the COMMON quantized row count
    ``mb_pad`` and the COMMON (max-over-shards, quantized) ELL/tail
    widths, so all ranks trace one program shape. Host-built arrays are
    placed through ``put_global``/``batch_sharding`` (the committed
    single-collective contract); ``row_map`` replicates.
    """
    from distributedlpsolver_tpu.parallel import mesh as mesh_lib

    if isinstance(op, SparseOperator):
        A = op.to_scipy()
        fmt = op.fmt
        if dtype is None:
            dtype = np.dtype(op.dtype)
    else:
        A = sp.csr_matrix(op)
        fmt = "ell"
        if dtype is None:
            dtype = np.float64
    m, n = A.shape
    nnz = int(A.nnz)
    ax = _shard_axis(mesh, axis)
    R = int(mesh.shape[ax])
    if m < R:
        raise ValueError(f"cannot shard {m} rows over {R} devices")
    rows_per = -(-m // R)
    mb_pad = _quantize(rows_per, _PAD_QUANTUM)

    blocks = [A[r * rows_per : min((r + 1) * rows_per, m)] for r in range(R)]
    tblocks = [B.T.tocsr() for B in blocks]

    put = mesh_lib.put_global
    bsh = lambda nd: mesh_lib.batch_sharding(mesh, nd, axis=ax)
    row_map = (
        (np.arange(m, dtype=np.int64) // rows_per) * mb_pad
        + np.arange(m, dtype=np.int64) % rows_per
    ).astype(np.int32)
    row_ok = np.zeros((R, mb_pad), dtype=bool)
    for r, B in enumerate(blocks):
        row_ok[r, : B.shape[0]] = True

    if fmt == "dense":
        dense = np.zeros((R, mb_pad, n), dtype=dtype)
        for r, B in enumerate(blocks):
            dense[r, : B.shape[0]] = np.asarray(B.todense(), dtype=dtype)
        return RowShardedOperator(
            shape=(m, n), nnz=nnz, fmt="dense", num_shards=R,
            rows_per=rows_per, mb_pad=mb_pad, mesh=mesh, axis=ax,
            dense=put(dense, bsh(3)),
            row_map=put(row_map, mesh_lib.replicated(mesh)),
            row_ok=put(row_ok, bsh(2)),
        )

    # Common forced widths: max over shards, already quantized by the
    # probe; tail lengths re-measured at the common ELL width.
    k = max(_hybrid_width(B) for B in blocks)
    kt = max(_hybrid_width(T) for T in tblocks)
    t_live = max(_tail_len(B, k) for B in blocks)
    tt_live = max(_tail_len(T, kt) for T in tblocks)
    t = _quantize(t_live, _TAIL_QUANTUM) if t_live else 0
    tt = _quantize(tt_live, _TAIL_QUANTUM) if tt_live else 0

    vals = np.zeros((R, mb_pad, k), dtype=dtype)
    cols = np.zeros((R, mb_pad, k), dtype=np.int32)
    tvs = np.zeros((R, t), dtype=dtype) if t else None
    trs = np.full((R, t), mb_pad, dtype=np.int32) if t else None
    tcs = np.zeros((R, t), dtype=np.int32) if t else None
    tvals = np.zeros((R, n, kt), dtype=dtype)
    tcols = np.zeros((R, n, kt), dtype=np.int32)
    ttvs = np.zeros((R, tt), dtype=dtype) if tt else None
    ttrs = np.full((R, tt), n, dtype=np.int32) if tt else None
    ttcs = np.zeros((R, tt), dtype=np.int32) if tt else None
    for r in range(R):
        v_, c_, tv_, tr_, tc_ = _hybrid_fill(
            blocks[r], dtype, k, t, mb_pad, mb_pad
        )
        vals[r], cols[r] = v_, c_
        if t:
            tvs[r], trs[r], tcs[r] = tv_, tr_, tc_
        v_, c_, tv_, tr_, tc_ = _hybrid_fill(
            tblocks[r], dtype, kt, tt, n, n
        )
        tvals[r], tcols[r] = v_, c_
        if tt:
            ttvs[r], ttrs[r], ttcs[r] = tv_, tr_, tc_

    maybe = lambda a, nd: None if a is None else put(a, bsh(nd))
    return RowShardedOperator(
        shape=(m, n), nnz=nnz, fmt="ell", num_shards=R,
        rows_per=rows_per, mb_pad=mb_pad, mesh=mesh, axis=ax,
        vals=put(vals, bsh(3)),
        cols=put(cols, bsh(3)),
        tail_vals=maybe(tvs, 2),
        tail_rows=maybe(trs, 2),
        tail_cols=maybe(tcs, 2),
        tvals=put(tvals, bsh(3)),
        tcols=put(tcols, bsh(3)),
        ttail_vals=maybe(ttvs, 2),
        ttail_rows=maybe(ttrs, 2),
        ttail_cols=maybe(ttcs, 2),
        dense=None,
        row_map=put(row_map, mesh_lib.replicated(mesh)),
        row_ok=put(row_ok, bsh(2)),
    )
