"""Fused normal-equations assembly ``M = A·diag(d)·Aᵀ`` as a Pallas TPU kernel.

The jnp expression ``(A * d) @ A.T`` materializes the scaled matrix
``A·diag(d)`` — an m×n HBM round trip per IPM iteration that at the
random-dense benchmark shape (10k×50k, BASELINE.json:9) is 4 GB of pure
bandwidth waste in f64.  This kernel (SURVEY.md §7 stage 7) streams A tiles
through VMEM once per (i, k) block, applies the column scaling in-register,
and feeds the MXU directly, accumulating ``M[i, j] += (A[i,k]·d[k])·A[j,k]ᵀ``
in an f32 VMEM scratch accumulator.

Only f32/bf16 inputs are supported — TPUs have no native f64 and Pallas does
not emulate it — so the dense backend routes through here exactly when its
assembly dtype is single precision (the mixed-precision configuration from
SURVEY.md §7: f32 factorization + KKT-level refinement in f64).
:func:`normal_eq` is the dispatching entry point; it falls back to the jnp
expression for f64 or non-TPU platforms, so callers never need to branch.

Reference parity note: the reference's analogue is its BLAS dsyrk/dgemm call
inside normal-equations assembly (capability pinned by BASELINE.json:5 —
"normal equations A·D²·Aᵀ"; the reference tree itself is unavailable,
SURVEY.md §0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 names the Mosaic compiler-params class TPUCompilerParams.
_CompilerParams = getattr(
    pltpu, "CompilerParams", None
) or getattr(pltpu, "TPUCompilerParams")


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


# Shared tile-size defaults: pad_for_pallas and normal_eq_pallas MUST agree,
# or the kernel re-pads the m×n matrix every call (the exact per-iteration
# HBM copy the setup-time pre-pad exists to avoid) — guarded by the out_m
# alignment check below.
BLOCK_M = 256
BLOCK_K = 512


def _ne_kernel(a_i_ref, a_j_ref, d_ref, out_ref, acc_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    scaled = a_i_ref[:] * d_ref[:]  # (bm, bk) * (1, bk) — fused in VMEM
    acc_ref[:] += jax.lax.dot_general(
        scaled,
        a_j_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),  # contract both on axis 1
        preferred_element_type=jnp.float32,
        # HIGHEST = true-f32 MXU passes. The TPU default is bf16 multiplies
        # (~1e-3 relative error), which poisons the Cholesky preconditioner
        # enough that KKT iterative refinement diverges near convergence.
        precision=jax.lax.Precision.HIGHEST,
    )

    @pl.when(k == pl.num_programs(2) - 1)
    def _flush():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def pad_for_pallas(A, block_m: int = BLOCK_M, block_k: int = BLOCK_K):
    """Zero-pad ``A`` to the kernel's tile multiples ONCE (call at setup).

    ``A`` is loop-invariant across IPM iterations; padding it per
    ``normal_eq_pallas`` call would re-materialize an m×n HBM copy every
    factorization. Pass the padded matrix plus ``out_m=<true m>`` instead.
    """
    m, n = A.shape
    mp, np_ = _round_up(m, block_m), _round_up(n, block_k)
    if (mp, np_) == (m, n):
        return A
    return jnp.pad(A, ((0, mp - m), (0, np_ - n)))


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_k", "interpret", "out_dtype", "out_m")
)
def normal_eq_pallas(
    A,
    d,
    *,
    block_m: int = BLOCK_M,
    block_k: int = BLOCK_K,
    out_dtype=None,
    interpret: bool = False,
    out_m: int | None = None,
):
    """``A @ diag(d) @ A.T`` without materializing the scaled matrix.

    A: (m, n) f32/bf16; d: (n',) with n' ≤ n — both padded to tile
    multiples (zero-padding d zeroes the padded columns' contribution, so
    the result is exact). ``A`` may be pre-padded via :func:`pad_for_pallas`
    with ``out_m`` giving the true row count; padding here is skipped when
    shapes are already aligned. Returns (out_m, out_m) in ``out_dtype``
    (default f32).
    """
    m, n = A.shape
    out_dtype = jnp.dtype(out_dtype or jnp.float32)
    mp, np_ = _round_up(m, block_m), _round_up(n, block_k)
    if out_m is not None and (mp, np_) != (m, n):
        raise ValueError(
            f"A {A.shape} with out_m={out_m} must be pre-padded to tile "
            f"multiples ({block_m}, {block_k}) — use pad_for_pallas with "
            "matching block sizes"
        )
    # Without out_m, A is unpadded and d must match its columns exactly;
    # with out_m, d is the pre-pad-length vector (shorter than the padded
    # n) and the zero-extension below is the intended semantics.
    if out_m is None and d.shape[0] != n:
        raise ValueError(f"d has shape {d.shape}, expected ({n},) to match A")
    out_m = out_m if out_m is not None else m
    Ap = A if (mp, np_) == (m, n) else jnp.pad(A, ((0, mp - m), (0, np_ - n)))
    dp = jnp.pad(d.astype(A.dtype), (0, np_ - d.shape[0])).reshape(1, np_)

    grid = (mp // block_m, mp // block_m, np_ // block_k)
    out = pl.pallas_call(
        _ne_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (j, k)),
            # (k - k, k) not (0, k): a literal 0 traces as i64 under x64
            # mode and Mosaic rejects the mixed i64/i32 index map.
            pl.BlockSpec((1, block_k), lambda i, j, k: (k - k, k)),
        ],
        out_specs=pl.BlockSpec((block_m, block_m), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, mp), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_m), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(Ap, Ap, dp)
    return out[:out_m, :out_m]


def normal_eq_reference(A, d):
    """The plain-XLA expression (also the oracle for the kernel tests)."""
    return (A * d[None, :]) @ A.T


def supports_pallas(dtype, platform: str | None = None) -> bool:
    platform = platform or jax.default_backend()
    return platform == "tpu" and jnp.dtype(dtype) in (
        jnp.dtype(jnp.float32),
        jnp.dtype(jnp.bfloat16),
    )


def normal_eq(A, d, *, use_pallas: bool | None = None, interpret: bool = False):
    """Dispatching assembly: Pallas when (requested or auto-)supported,
    plain XLA otherwise. Safe to call under jit/trace in either path."""
    if use_pallas is None:
        use_pallas = supports_pallas(A.dtype)
    if use_pallas and (interpret or supports_pallas(A.dtype)):
        return normal_eq_pallas(A, d, out_dtype=A.dtype, interpret=interpret)
    return normal_eq_reference(A, d)
