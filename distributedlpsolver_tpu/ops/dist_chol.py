"""Mesh-distributed Cholesky factorization + triangular inversion.

The second distributed-factorization cut (SURVEY.md §2.2; VERDICT round 3
item 6). Round 3 sharded the TRSM slabs of L⁻¹ (`dense._tri_inv_mesh`),
but the m×m Cholesky itself — and the full M and L it reads and writes —
stayed REPLICATED on every device: per-device HBM held 3 full m×m buffers
(M, L, and the TRSM's read copy of L), which is the memory ceiling for
dense m ≳ 10k on a real multi-chip mesh. This module distributes the
whole pipeline: M arrives column-block-sharded (one reduce-scatter out of
the GSPMD assembly instead of an all-reduce), the factorization runs as a
left-looking panel Cholesky inside ``shard_map``, and the inversion is a
right-looking blocked forward substitution on each device's identity
slab — no stage materializes a replicated m×m array on any device.

Dataflow per panel (pb columns, P = m/pb panels):

  factor:  U = psum( ownerʼs M panel − L_loc · L_loc[panel rows]ᵀ )
           C = chol(U[diag block])          (pb×pb, replicated compute)
           L panel = U · C⁻ᵀ                (TRSM, pb rhs, replicated)
           owner stores its panel slab
  invert:  Lp = psum( ownerʼs L panel )     (the only broadcast of L)
           X[panel rows] = C⁻¹ · X[panel rows]
           X[below]     −= Lp[below] · X[panel rows]

Left-looking contraction trick: each device contracts ALL of its local
columns every panel (``L_loc @ L_loc[panel_rows].T``) — columns not yet
factored are still zero and contribute nothing, so no dynamic column
masking is needed and the total per-device flop count telescopes to
m³/K + O(m²·pb) (the ideal 1/K share plus the replicated pb-wide panel
math). Communication: one (m, pb) psum per panel per stage — 2m² words
total, the same volume as one replicated all-reduce of M, riding ICI.

Numerics match the replicated factorization: identical IEEE operations
per panel, only the summation ORDER of the psum differs (deterministic
on a fixed mesh — XLA collectives are reduction-order-stable, the
property tests/test_determinism.py pins).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec


def _axis_of(shard: NamedSharding) -> str:
    """The (single) mesh axis a ``P(None, axis)`` column sharding names."""
    return next(a for a in shard.spec if a is not None)


def chol_tri_inv_mesh(Ms, shard: NamedSharding, panel: int = 256):
    """``L⁻¹`` of ``chol(Ms)``, column-sharded end-to-end over the mesh.

    ``Ms`` is the (already scaled + regularized) SPD matrix, accepted with
    ANY placement — a ``with_sharding_constraint`` immediately pins it to
    ``shard`` (``P(None, axis)``), so when the caller's assembly is GSPMD
    column-partials the compiler emits a reduce-scatter instead of an
    all-reduce and the replicated m² buffer never exists. Returns L⁻¹
    (unit layout as `dense._tri_inv_mesh`: column-sharded, ready for the
    preconditioner's two sharded GEMVs).

    ``panel`` is a target: the actual panel width is ``min(panel, w)``
    with the per-device slab ``w`` rounded UP to a panel multiple (the
    pad carries an identity tail, sliced off at the end) so every panel
    lies inside one device's slab.
    """
    from distributedlpsolver_tpu.parallel.mesh import (
        pvary_compat,
        shard_map_compat,
    )

    mesh = shard.mesh
    axis = _axis_of(shard)
    K = int(mesh.shape[axis])
    m = Ms.shape[0]
    w0 = -(-m // K)  # per-device slab before panel alignment
    pb = min(panel, w0)
    w = -(-w0 // pb) * pb  # slab width: multiple of pb
    mp = w * K
    P = mp // pb  # global panel count

    sharding = NamedSharding(mesh, PartitionSpec(None, axis))
    if mp != m:
        pad = mp - m
        # Constrain the PADDED buffer before the identity-tail scatter:
        # building it via zeros+set and constraining only at the end let
        # GSPMD materialize an unconstrained replicated (mp, mp)
        # intermediate — exactly the buffer this module promises never
        # exists (ADVICE round 4). The raw m is not divisible by the
        # mesh axis (that is what the pad is for), so the constraint can
        # only attach from the padded shape onward; the diagonal scatter
        # preserves it.
        Ms = jax.lax.with_sharding_constraint(
            jnp.pad(Ms, ((0, pad), (0, pad))), sharding
        )
        # Identity tail: pad rows factor to L=I there and stay inert.
        Ms = Ms.at[jnp.arange(m, mp), jnp.arange(m, mp)].set(1.0)
    Ms = jax.lax.with_sharding_constraint(Ms, sharding)

    rows = jnp.arange(mp)

    def device_fn(Msloc):
        # Msloc: (mp, w) — this device's column slab of Ms.
        k = jax.lax.axis_index(axis)
        base = k * w

        def factor_panel(p, Lloc):
            g0 = p * pb  # global first column of this panel
            owner = g0 // w
            lc = g0 - owner * w  # same scalar on every device, always valid
            mine = (k == owner).astype(Msloc.dtype)
            # Owner contributes its M panel; everyone subtracts the
            # left-looking update from its already-factored local columns
            # (unfactored columns are still zero — no masking needed).
            Mpan = jax.lax.dynamic_slice(Msloc, (0, lc), (mp, pb))
            Lrows = jax.lax.dynamic_slice(Lloc, (g0, 0), (pb, w))
            U = jax.lax.psum(mine * Mpan - Lloc @ Lrows.T, axis)
            D = jax.lax.dynamic_slice(U, (g0, 0), (pb, pb))
            C = jnp.linalg.cholesky(D)
            # Panel of L: rows ≥ g0+pb get U·C⁻ᵀ; rows in the panel get C
            # itself (algebraically U·C⁻ᵀ there too); rows above are not
            # part of the lower factor — mask to zero.
            Lpan = jax.scipy.linalg.solve_triangular(
                C, U.T, lower=True
            ).T
            Lpan = jnp.where((rows >= g0)[:, None], Lpan, 0.0)
            cur = jax.lax.dynamic_slice(Lloc, (0, lc), (mp, pb))
            Lpan = jnp.where(mine > 0, Lpan, cur)  # non-owners keep slab
            return jax.lax.dynamic_update_slice(Lloc, Lpan, (0, lc))

        init = pvary_compat(jnp.zeros((mp, w), Msloc.dtype), (axis,))
        Lloc = jax.lax.fori_loop(0, P, factor_panel, init)

        # ---- distributed inversion: solve L·X = I_slab for this
        # device's identity slab (columns [base, base+w)).
        X0 = (rows[:, None] == (base + jnp.arange(w))[None, :]).astype(
            Msloc.dtype
        )

        def subst_panel(p, X):
            g0 = p * pb
            owner = g0 // w
            lc = g0 - owner * w
            mine = (k == owner).astype(Msloc.dtype)
            # The only broadcast of L: the owner's (mp, pb) panel.
            Lpan = jax.lax.psum(
                mine * jax.lax.dynamic_slice(Lloc, (0, lc), (mp, pb)), axis
            )
            C = jax.lax.dynamic_slice(Lpan, (g0, 0), (pb, pb))
            Xp = jax.lax.dynamic_slice(X, (g0, 0), (pb, w))
            Xp = jax.scipy.linalg.solve_triangular(C, Xp, lower=True)
            X = jax.lax.dynamic_update_slice(X, Xp, (g0, 0))
            # Right-looking update of the rows below the panel; rows in
            # and above the panel are masked out of Lpan (L's rows above
            # g0 are zero already, but the C block is not).
            Lbelow = jnp.where((rows >= g0 + pb)[:, None], Lpan, 0.0)
            return X - Lbelow @ Xp

        return jax.lax.fori_loop(0, P, subst_panel, X0)

    Linv = shard_map_compat(
        device_fn,
        mesh=mesh,
        in_specs=(PartitionSpec(None, axis),),
        out_specs=PartitionSpec(None, axis),
    )(Ms)
    return Linv[:m, :m] if mp != m else Linv
