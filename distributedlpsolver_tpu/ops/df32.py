"""Double-f32 ("two-float") elementwise arithmetic — the round-5 lever 3
engine for the batched serve hot path.

A value is carried as an unevaluated pair of f32 arrays ``(hi, lo)`` with
``hi = fl32(hi + lo)`` (renormalized), giving an effective 48-bit
significand. On TPUs f64 is software-emulated and *scalarized* elementwise
f64 chains (divisions in the KKT back-substitution and scaling, the ratio
grids) are the measured wall of the batched step (ROUND5_NOTES lever 3);
a df32 chain runs the same arithmetic as ~5–20 native-f32 VPU ops per
result element — f32 speed, ~f64 accuracy.

Error model (u = 2⁻²⁴, the f32 unit roundoff; bounds from Joldes, Muller
& Popescu, "Tight and rigorous error bounds for basic building blocks of
double-word arithmetic", ACM TOMS 2017, instantiated for binary32):

* ``pack``     : |x − (hi+lo)| ≤ 2⁻⁴⁹·|x|  (hi, lo each correctly rounded)
* ``add/sub``  : relative error ≤ 3u² ≈ 1.1e-14   (AccurateDWPlusDW)
* ``mul``      : relative error ≤ 5u² ≈ 1.8e-14   (DWTimesDW, Dekker split)
* ``div``      : relative error ≤ 15u² ≈ 5.3e-14  (DWDivDW2)
* chain of k ops: ≲ 15·k·u² — the KKT chains here are ≤ 6 ops deep, so a
  direction component carries ≲ 1e-13 relative error, five orders below
  the 1e-8 convergence tolerance (the f64c finisher phase owns the rest).

Validity range: the Dekker splitting constant multiplies operands by
2¹²+1, so |values| must stay below ~2¹¹⁵ (≈4e34) for full accuracy, and
a result's low limb holds bits down to |x|·2⁻⁴⁸ — once that falls under
the f32 subnormal floor (1.4e-45, i.e. |x| ≲ 4e-31) accuracy degrades
gracefully toward plain f32. Late-IPM scaling diagonals span ~1e±12 —
comfortably inside. Non-finite inputs propagate: any NaN/±inf operand yields a
non-finite result (the exact value — inf vs NaN — is unspecified; the
solver's bad-step detection only tests finiteness).

The algorithms rely on IEEE-exact f32 add/sub/mul (error-free
transformations): XLA preserves per-op float semantics (no fast-math
reassociation), so the sequences below survive jit/fusion verbatim.

This module is a sanctioned mixed-precision schedule owner
(analysis/config.NARROW_SANCTIONED): every f64→f32 narrowing of the df32
engine lives HERE — callers (ipm/core.py) pass f64 arrays to the chain
helpers and get f64 back, and never narrow themselves.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_F32 = jnp.float32
_F64 = jnp.float64
# Dekker splitting constant for a 24-bit significand: 2^ceil(24/2) + 1.
_SPLIT = np.float32(4097.0)


# -- error-free transformations (f32 in, f32 pair out) -----------------------


def two_sum(a, b):
    """Knuth 2Sum: ``a + b = s + e`` exactly (s = fl(a+b))."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def fast_two_sum(a, b):
    """Dekker Fast2Sum: exact under |a| ≥ |b| (or a = 0) — the
    renormalization step, where the precondition holds by construction."""
    s = a + b
    e = b - (s - a)
    return s, e


def two_prod(a, b):
    """Dekker 2Prod via splitting: ``a · b = p + e`` exactly (no FMA —
    XLA exposes none portably; the split form is exact on IEEE f32)."""
    p = a * b
    aa = _SPLIT * a
    a_hi = aa - (aa - a)
    a_lo = a - a_hi
    bb = _SPLIT * b
    b_hi = bb - (bb - b)
    b_lo = b - b_hi
    e = ((a_hi * b_hi - p) + a_hi * b_lo + a_lo * b_hi) + a_lo * b_lo
    return p, e


# -- df32 pair algebra -------------------------------------------------------


def renorm(hi, lo):
    """Re-establish the pair invariant |lo| ≤ ulp(hi)/2."""
    return fast_two_sum(hi, lo)


def pack(x):
    """f64 → df32: ``hi = fl32(x)``, ``lo = fl32(x − hi)`` — both
    roundings correct, so the pair holds x to ~2⁻⁴⁹ relative."""
    hi = x.astype(_F32)
    lo = (x - hi.astype(_F64)).astype(_F32)
    return hi, lo


def unpack(d):
    """df32 → f64 (exact: both components are f32, their f64 sum is
    representable)."""
    hi, lo = d
    return hi.astype(_F64) + lo.astype(_F64)


def const(v, like_hi):
    """A Python-float constant as a df32 pair broadcast against
    ``like_hi`` (split exactly at trace time with numpy)."""
    hi = np.float32(v)
    lo = np.float32(v - float(hi))
    return (
        jnp.full_like(like_hi, hi),
        jnp.full_like(like_hi, lo),
    )


def neg(x):
    return -x[0], -x[1]


def add(x, y):
    """AccurateDWPlusDW (Joldes et al. alg. 6): rel. err ≤ 3u²."""
    sh, sl = two_sum(x[0], y[0])
    th, tl = two_sum(x[1], y[1])
    c = sl + th
    vh, vl = fast_two_sum(sh, c)
    w = tl + vl
    return fast_two_sum(vh, w)


def sub(x, y):
    return add(x, neg(y))


def mul(x, y):
    """DWTimesDW (Joldes et al. alg. 12, FMA-free): rel. err ≤ 5u²."""
    ph, pl = two_prod(x[0], y[0])
    pl = pl + (x[0] * y[1] + x[1] * y[0])
    return fast_two_sum(ph, pl)


def div(x, y):
    """DWDivDW2 (Joldes et al. alg. 17): rel. err ≤ 15u²."""
    th = x[0] / y[0]
    # r = x − th·y, computed in df32 (exact two_prod inside mul).
    rh, rl = sub(x, mul((th, jnp.zeros_like(th)), y))
    tl = rh / y[0]
    return fast_two_sum(th, tl)


# -- f64-in / f64-out chain helpers for the IPM hot path ---------------------
#
# These are the ONLY entry points ipm/core.py uses: pack the f64 operands,
# run the whole elementwise chain at df32, unpack once. Each mirrors one
# elementwise block of the KKT back-substitution / scaling (core.py's
# _solve_kkt_once and scaling_d) — keeping the chain definitions next to
# the arithmetic makes the error-bound accounting local to this file.


def mul64(a, b):
    """``a ∘ b`` through df32 (f64 in/out)."""
    return unpack(mul(pack(a), pack(b)))


def sub64(a, b):
    """``a − b`` through df32 (f64 in/out)."""
    return unpack(sub(pack(a), pack(b)))


def scaling_d(x, s, w, z, hub, reg_primal):
    """``1 / (s/x + hub·z/w + reg_primal)`` — the normal-equations
    diagonal (core.scaling_d) as one df32 chain. ``hub`` is the 0/1
    finite-upper-bound mask (exact in f32)."""
    X, S, W, Z = pack(x), pack(s), pack(w), pack(z)
    hub32 = hub.astype(_F32)
    zw = div(Z, W)
    zw = (zw[0] * hub32, zw[1] * hub32)  # exact: mask is 0/1
    dinv = add(add(div(S, X), zw), const(reg_primal, X[0]))
    return unpack(div(const(1.0, X[0]), dinv))


def kkt_h(r_d, r_xs, x, r_wz, z, r_u, w):
    """``h = r_d − r_xs/x + (r_wz − z·r_u)/w`` (back-substitution RHS)."""
    RD, RXS, X = pack(r_d), pack(r_xs), pack(x)
    RWZ, Z, RU, W = pack(r_wz), pack(z), pack(r_u), pack(w)
    t = div(sub(RWZ, mul(Z, RU)), W)
    return unpack(add(sub(RD, div(RXS, X)), t))


def kkt_dx(d, aty, h):
    """``dx = d · (Aᵀdy − h)``; the matvec ``aty`` arrives in f64."""
    return unpack(mul(pack(d), sub(pack(aty), pack(h))))


def kkt_ds(r_xs, s, dx, x):
    """``ds = (r_xs − s·dx)/x``."""
    return unpack(div(sub(pack(r_xs), mul(pack(s), pack(dx))), pack(x)))


def kkt_dz(hub, r_wz, z, dw, w):
    """``dz = hub · (r_wz − z·dw)/w`` (mask applied in f64 — exact)."""
    return hub * unpack(div(sub(pack(r_wz), mul(pack(z), pack(dw))), pack(w)))
