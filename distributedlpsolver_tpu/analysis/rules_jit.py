"""jit/recompile-hygiene rules.

The serving and solver layers stake their throughput on compiled-program
reuse (the zero-warm-recompile invariant, PR 3/4): every device program
is built once per (shape, dtype, mesh) key and dispatched verbatim
forever after. The failure modes that break this are all host-side
Python and all statically visible:

- ``jit-nonhoisted`` — a ``jax.jit`` (or ``functools.partial(jax.jit,
  ...)``) *created inside a function body*. Each call builds a fresh
  wrapper with an empty trace cache, so the program recompiles (or at
  best re-traces against the XLA cache) on every invocation — the exact
  warm-recompile class the bucket cache exists to prevent. Hoist the
  wrapper to module level.
- ``jit-scalar-default`` — a jitted function parameter with a Python
  scalar default that is not declared static. A scalar default marks a
  host config knob; traced, it becomes a weak-typed 0-d array whose
  promotions differ from the array path and whose use in Python control
  flow fails only at trace time. Knobs are static by repo convention;
  values travel as arrays.
- ``jit-donate`` — the programs catalogued donate-eligible in
  analysis/config.DONATE_EXPECTED (per-call buffers dead after the
  call) must pass ``donate_argnums`` so the device reuses their buffers
  in place instead of doubling peak memory.
- ``host-sync`` — ``float()`` / ``np.asarray`` / ``.item()`` /
  ``block_until_ready`` inside the serve pack/solve thread bodies or
  the IPM driver loop (config.HOT_SCOPES). Each one is a device
  round-trip that serializes the pipeline; the sanctioned sync points
  carry explanatory suppression comments.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from distributedlpsolver_tpu.analysis import config
from distributedlpsolver_tpu.analysis.core import FileContext, Finding, rule


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` attribute reference."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


def _is_partial_jit(call: ast.Call) -> bool:
    """``functools.partial(jax.jit, ...)`` / ``partial(jax.jit, ...)``."""
    fn = call.func
    named_partial = (
        isinstance(fn, ast.Attribute) and fn.attr == "partial"
    ) or (isinstance(fn, ast.Name) and fn.id == "partial")
    return named_partial and bool(call.args) and _is_jax_jit(call.args[0])


def _jit_wrappers(ctx: FileContext) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and (
            _is_jax_jit(node.func) or _is_partial_jit(node)
        ):
            yield node


def _decorating(ctx: FileContext, node: ast.AST, fn: ast.FunctionDef) -> bool:
    """True if ``node`` lives inside one of ``fn``'s decorators (a
    decorator expression parents to the FunctionDef it decorates, but it
    executes in the *enclosing* scope)."""
    for dec in fn.decorator_list:
        for sub in ast.walk(dec):
            if sub is node:
                return True
    return False


def _executing_scope(ctx: FileContext, node: ast.AST):
    """The function whose *execution* runs ``node`` — skips FunctionDefs
    entered via their decorator list."""
    fn = ctx.enclosing_function(node)
    while fn is not None and _decorating(ctx, node, fn):
        fn = ctx.enclosing_function(fn)
    return fn


@rule(
    "jit-nonhoisted",
    "jax.jit wrappers must be created at module level, not per call",
)
def check_nonhoisted(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []

    def flag(node: ast.AST, scope: ast.FunctionDef) -> None:
        out.append(
            Finding(
                rule="jit-nonhoisted",
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"jax.jit created inside {scope.name}(): the "
                    "wrapper's trace cache dies with each call — hoist "
                    "to module level (warm-recompile hazard)"
                ),
            )
        )

    for call in _jit_wrappers(ctx):
        fn = _executing_scope(ctx, call)
        if fn is not None:
            flag(call, fn)
    # Bare `@jax.jit` decorators on nested defs are not Call nodes but
    # run jax.jit once per enclosing call all the same.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        outer = ctx.enclosing_function(node)
        if outer is None:
            continue
        for dec in node.decorator_list:
            if _is_jax_jit(dec):
                flag(dec, outer)
    return out


def _static_names(call: ast.Call) -> set:
    """Names/indices declared static in a jit(...) or partial(jax.jit,...)."""
    names: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    names.add(el.value)
    return names


@rule(
    "jit-scalar-default",
    "jitted params with Python scalar defaults must be declared static",
)
def check_scalar_default(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        statics: set = set()
        jitted = False
        for dec in node.decorator_list:
            if _is_jax_jit(dec):
                jitted = True
            elif isinstance(dec, ast.Call) and (
                _is_jax_jit(dec.func) or _is_partial_jit(dec)
            ):
                jitted = True
                statics |= _static_names(dec)
        if not jitted:
            continue
        args = node.args.args
        defaults = node.args.defaults
        offset = len(args) - len(defaults)
        for i, default in enumerate(defaults):
            arg = args[offset + i]
            pos = offset + i
            if not (
                isinstance(default, ast.Constant)
                and isinstance(default.value, (int, float, bool))
                and not isinstance(default.value, type(None))
            ):
                continue
            if arg.arg in statics or pos in statics:
                continue
            out.append(
                Finding(
                    rule="jit-scalar-default",
                    path=ctx.path,
                    line=arg.lineno,
                    col=arg.col_offset,
                    message=(
                        f"param {arg.arg!r} of jitted {node.name}() has a "
                        f"Python scalar default ({default.value!r}) but is "
                        "not in static_argnames — a traced weak-typed "
                        "scalar knob (recompile/promotion hazard)"
                    ),
                )
            )
    return out


@rule(
    "jit-donate",
    "catalogued donate-eligible programs must pass donate_argnums",
)
def check_donate(ctx: FileContext) -> List[Finding]:
    expected = {
        fn_name: desc
        for (pkg, fn_name), desc in config.DONATE_EXPECTED.items()
        if pkg == ctx.pkg_path
    }
    if not expected:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.FunctionDef) or node.name not in expected:
            continue
        donated = False
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and (
                _is_jax_jit(dec.func) or _is_partial_jit(dec)
            ):
                donated = any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords
                )
        if not donated:
            out.append(
                Finding(
                    rule="jit-donate",
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{node.name}() is donate-eligible "
                        f"({expected[node.name]}) but its jit passes no "
                        "donate_argnums — per-call buffers are copied, "
                        "not reused"
                    ),
                )
            )
    return out


def _qualname(ctx: FileContext, fn: ast.FunctionDef) -> str:
    parts = [fn.name]
    for anc in ctx.ancestors(fn):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(anc.name)
    return ".".join(reversed(parts))


def _sync_call(node: ast.Call) -> str:
    """Describe the host-sync pattern a Call matches, or ''."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "float":
        # float(literal) is host arithmetic, not a device fetch
        if node.args and isinstance(node.args[0], ast.Constant):
            return ""
        return "float(...)"
    if isinstance(fn, ast.Attribute):
        if fn.attr == "item":
            return ".item()"
        if fn.attr == "block_until_ready":
            return "block_until_ready"
        if fn.attr in ("asarray", "array") and isinstance(fn.value, ast.Name) and (
            fn.value.id in ("np", "numpy")
        ):
            return f"np.{fn.attr}"
    return ""


@rule(
    "host-sync",
    "no device->host syncs inside serve pipeline threads / IPM loop",
)
def check_host_sync(ctx: FileContext) -> List[Finding]:
    hot = config.HOT_SCOPES.get(ctx.pkg_path)
    if not hot:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        what = _sync_call(node)
        if not what:
            continue
        # Match the innermost enclosing hot function, closures included
        # (a sync inside a nested helper still runs on the hot thread).
        scope = None
        fn = ctx.enclosing_function(node)
        while fn is not None:
            if _qualname(ctx, fn) in hot:
                scope = fn
                break
            fn = ctx.enclosing_function(fn)
        if scope is None:
            continue
        out.append(
            Finding(
                rule="host-sync",
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"{what} inside hot scope {_qualname(ctx, scope)} — a "
                    "host<->device sync that stalls the pipeline; move it "
                    "out of the loop or annotate the sanctioned sync point"
                ),
            )
        )
    return out
