"""Lock-discipline rule: the ``# guarded-by:`` annotation convention.

The serve dispatcher is a three-thread pipeline (scheduler → pack →
solve) sharing mutable state with submitters and introspection calls;
the metrics registry, tracer, and JSONL logger are written from all of
them. The repo's convention makes each shared attribute's lock explicit
at its birthplace:

    def __init__(self):
        self._results = []      # guarded-by: _lock
        self._wake = threading.Condition(self._lock)

and this rule verifies, lexically, that every later read or write of an
annotated attribute happens inside ``with self.<lock>`` (or a
``threading.Condition`` the checker saw constructed over that lock —
entering the condition acquires it). Methods whose *callers* hold the
lock declare it on the def line:

    def _is_idle(self):  # holds: _lock

``__init__`` is exempt: construction happens-before publication.

The static check is lexical by design — it cannot see cross-function
lock flow, which is why it pairs with the *dynamic* lock-order recorder
(analysis/lockorder.py): tests wrap the live locks, drain a real
3-thread service, and assert the acquisition graph stays acyclic. The
static rule catches unguarded access; the recorder catches ordering
inversions between guards the static rule approved.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from distributedlpsolver_tpu.analysis.core import FileContext, Finding, rule

_GUARDED = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_HOLDS = re.compile(r"#\s*holds:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _self_attr(node: ast.AST) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _collect_annotations(ctx: FileContext, init: ast.FunctionDef):
    """(guards, aliases) from a class's __init__: guards maps attr ->
    lock attr; aliases maps condition attr -> underlying lock attr
    (``self.C = threading.Condition(self.L)``)."""
    guards: Dict[str, str] = {}
    aliases: Dict[str, str] = {}
    for node in ast.walk(init):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        attrs = [a for a in (_self_attr(t) for t in targets) if a]
        if not attrs:
            continue
        m = _GUARDED.search(ctx.line(node.lineno))
        if m:
            for a in attrs:
                guards[a] = m.group(1)
        value = node.value
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "Condition"
            and value.args
        ):
            base = _self_attr(value.args[0])
            if base:
                for a in attrs:
                    aliases[a] = base
    return guards, aliases


def _held_locks(ctx: FileContext, node: ast.AST, aliases: Dict[str, str]) -> Set[str]:
    """Lock attrs lexically held at ``node``: enclosing ``with
    self.<lock>`` items (conditions resolved through aliases) plus any
    ``# holds:`` annotation on an enclosing def."""
    held: Set[str] = set()
    chain = [node] + list(ctx.ancestors(node))
    for anc in chain:
        if isinstance(anc, ast.With):
            for item in anc.items:
                a = _self_attr(item.context_expr)
                if a:
                    held.add(aliases.get(a, a))
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for line in range(anc.lineno, anc.body[0].lineno):
                m = _HOLDS.search(ctx.line(line))
                if m:
                    lock = m.group(1)
                    held.add(aliases.get(lock, lock))
    return held


@rule(
    "guarded-by",
    "annotated shared attributes accessed only under their lock",
)
def check_guarded_by(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            continue
        guards, aliases = _collect_annotations(ctx, init)
        if not guards:
            continue
        for method in cls.body:
            if (
                not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef))
                or method.name == "__init__"
            ):
                continue
            for node in ast.walk(method):
                attr = _self_attr(node)
                if attr not in guards:
                    continue
                lock = guards[attr]
                if lock in _held_locks(ctx, node, aliases):
                    continue
                kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                out.append(
                    Finding(
                        rule="guarded-by",
                        path=ctx.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"{kind} of {cls.name}.{attr} (guarded-by "
                            f"{lock}) outside `with self.{lock}` in "
                            f"{method.name}()"
                        ),
                    )
                )
    return out
