"""Lock-discipline rules: the ``# guarded-by:`` annotation convention,
plus the interprocedural deadlock families (graftcheck v2).

The serve dispatcher is a three-thread pipeline (scheduler → pack →
solve) sharing mutable state with submitters and introspection calls;
the metrics registry, tracer, and JSONL logger are written from all of
them. The repo's convention makes each shared attribute's lock explicit
at its birthplace:

    def __init__(self):
        self._results = []      # guarded-by: _lock
        self._wake = threading.Condition(self._lock)

and this rule verifies, lexically, that every later read or write of an
annotated attribute happens inside ``with self.<lock>`` (or a
``threading.Condition`` the checker saw constructed over that lock —
entering the condition acquires it). Methods whose *callers* hold the
lock declare it on the def line:

    def _is_idle(self):  # holds: _lock

``__init__`` is exempt: construction happens-before publication.

The ``guarded-by`` check is lexical by design. Since graftcheck v2 it
pairs with two *interprocedural* families built on the package call
graph (analysis/callgraph.py):

- ``lock-order`` — the static half of the dynamic lockorder recorder:
  every ``with self._a: ... self._m() ... with self._b`` path
  contributes a held→acquired edge (including edges through resolved
  calls, cross-class via inferred attribute types), and any cycle in
  the global edge graph is an ordering inversion that CAN deadlock,
  whether or not a run has hit it yet. Tests cross-check this graph
  against the edges the dynamic recorder observes on a live 3-thread
  SolveService drain.
- ``blocking-under-lock`` — a collective, HTTP round-trip, fsync,
  subprocess, sleep, or Future.result reached (transitively) while a
  known lock is held. A collective blocks until every RANK arrives;
  holding a lock across one turns a slow peer into a whole-process
  stall, and two such locks into a distributed deadlock. Deliberate
  seams (the slice dispatch-order lock, the WAL append) are sanctioned
  in :data:`analysis.config.BLOCKING_SANCTIONED`.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from distributedlpsolver_tpu.analysis import config
from distributedlpsolver_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectContext,
    project_rule,
    rule,
)

_GUARDED = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_HOLDS = re.compile(r"#\s*holds:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _self_attr(node: ast.AST) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _collect_annotations(ctx: FileContext, init: ast.FunctionDef):
    """(guards, aliases) from a class's __init__: guards maps attr ->
    lock attr; aliases maps condition attr -> underlying lock attr
    (``self.C = threading.Condition(self.L)``)."""
    guards: Dict[str, str] = {}
    aliases: Dict[str, str] = {}
    for node in ast.walk(init):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        attrs = [a for a in (_self_attr(t) for t in targets) if a]
        if not attrs:
            continue
        m = _GUARDED.search(ctx.line(node.lineno))
        if m:
            for a in attrs:
                guards[a] = m.group(1)
        value = node.value
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "Condition"
            and value.args
        ):
            base = _self_attr(value.args[0])
            if base:
                for a in attrs:
                    aliases[a] = base
    return guards, aliases


def _held_locks(ctx: FileContext, node: ast.AST, aliases: Dict[str, str]) -> Set[str]:
    """Lock attrs lexically held at ``node``: enclosing ``with
    self.<lock>`` items (conditions resolved through aliases) plus any
    ``# holds:`` annotation on an enclosing def."""
    held: Set[str] = set()
    chain = [node] + list(ctx.ancestors(node))
    for anc in chain:
        if isinstance(anc, ast.With):
            for item in anc.items:
                a = _self_attr(item.context_expr)
                if a:
                    held.add(aliases.get(a, a))
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for line in range(anc.lineno, anc.body[0].lineno):
                m = _HOLDS.search(ctx.line(line))
                if m:
                    lock = m.group(1)
                    held.add(aliases.get(lock, lock))
    return held


@rule(
    "guarded-by",
    "annotated shared attributes accessed only under their lock",
)
def check_guarded_by(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            continue
        guards, aliases = _collect_annotations(ctx, init)
        if not guards:
            continue
        for method in cls.body:
            if (
                not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef))
                or method.name == "__init__"
            ):
                continue
            for node in ast.walk(method):
                attr = _self_attr(node)
                if attr not in guards:
                    continue
                lock = guards[attr]
                if lock in _held_locks(ctx, node, aliases):
                    continue
                kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                out.append(
                    Finding(
                        rule="guarded-by",
                        path=ctx.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"{kind} of {cls.name}.{attr} (guarded-by "
                            f"{lock}) outside `with self.{lock}` in "
                            f"{method.name}()"
                        ),
                    )
                )
    return out


# ---------------------------------------------------------------------------
# Interprocedural deadlock families (graftcheck v2)


def _blocking_sanctioned(key: Tuple[str, str]) -> bool:
    pkg, qual = key
    if (pkg, qual) in config.BLOCKING_SANCTIONED:
        return True
    head = qual.split(".", 1)[0]
    return (pkg, head) in config.BLOCKING_SANCTIONED


@project_rule(
    "lock-order",
    "the cross-method lock acquisition graph must stay acyclic",
)
def check_lock_order(project: ProjectContext) -> List[Finding]:
    cycle = project.locks.find_cycle()
    if not cycle:
        return []
    path_str = " -> ".join([a for a, _b, _p, _l in cycle] + [cycle[0][0]])
    sites = ", ".join(f"{a}->{b} at {p}:{l}" for a, b, p, l in cycle)
    pkg = cycle[0][2]
    ctx = project.by_path.get(pkg)
    return [
        Finding(
            rule="lock-order",
            path=ctx.path if ctx is not None else pkg,
            line=cycle[0][3],
            col=0,
            message=(
                f"lock-order cycle {path_str} ({sites}) — inconsistent "
                "acquisition order can deadlock; pick one global order "
                "(the dynamic lockorder recorder asserts the same "
                "invariant at runtime)"
            ),
        )
    ]


@project_rule(
    "blocking-under-lock",
    "no collective/IO/subprocess/sleep while a lock is held",
)
def check_blocking_under_lock(project: ProjectContext) -> List[Finding]:
    out: List[Finding] = []
    graph = project.graph
    locks = project.locks
    blocking = set(config.BLOCKING_CALLS)

    # Transitive blocking summaries, with sanctioned functions
    # contributing nothing (their blocking is their documented design;
    # callers do not inherit it).
    chains: Dict[Tuple[str, str], Tuple[str, ...]] = {}
    for key, unit in graph.functions.items():
        if _blocking_sanctioned(key):
            continue
        for call, resolved, term in unit.call_sites:
            if term in blocking and not (
                resolved is not None and _blocking_sanctioned(resolved)
            ):
                chains[key] = (term,)
                break
    changed = True
    while changed:
        changed = False
        for key, unit in graph.functions.items():
            if key in chains or _blocking_sanctioned(key):
                continue
            for call, resolved, term in unit.call_sites:
                if (
                    resolved is not None
                    and resolved != key
                    and resolved in chains
                ):
                    chains[key] = (resolved[1],) + chains[resolved]
                    changed = True
                    break

    for key, unit in graph.functions.items():
        if "<locals>" in key[1] or _blocking_sanctioned(key):
            continue
        for call, resolved, term in unit.call_sites:
            chain: Tuple[str, ...] = ()
            if term in blocking and not (
                resolved is not None and _blocking_sanctioned(resolved)
            ):
                chain = (term,)
            elif resolved is not None and chains.get(resolved):
                chain = (resolved[1],) + chains[resolved]
            if not chain:
                continue
            held = locks._held_at(unit, call)
            if not held:
                continue
            out.append(
                Finding(
                    rule="blocking-under-lock",
                    path=unit.ctx.path,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"blocking op `{' -> '.join(chain)}` while "
                        f"holding {', '.join(sorted(held))} in "
                        f"{key[1]}() — move the wait outside the lock "
                        "or sanction the seam in analysis/config."
                        "BLOCKING_SANCTIONED"
                    ),
                )
            )
    return out
