"""SPMD-discipline rules — the multi-host contract, statically gated.

PR 13 made the solver a multi-process SPMD system: every rank of a
world must execute a bit-identical program sequence, because the
collectives inside the compiled bucket programs block until EVERY rank
arrives and the jit caches must agree world-wide (distributed/world.py,
distributed/slice.py module docs). Three bug classes broke that
contract during landing, all statically detectable once the checker
can see across calls (analysis/callgraph.py):

- ``spmd-divergent-collective`` — a rank-derived value (``world.rank``,
  ``jax.process_index()``, ``.is_primary``, ``DLPS_RANK``) guarding a
  branch or early return on a path that reaches a collective or a
  bucket-program dispatch. One rank takes the branch, its peers do
  not, and the peers hang inside XLA forever. Taint propagates through
  assignments, through returns (an ``is_primary()``-style predicate
  taints its callers), and through call arguments (passing a rank fact
  into a function that branches a collective on its parameter). The
  deliberate rank-0-publish / follower-execute seams are sanctioned in
  :data:`analysis.config.SPMD_SANCTIONED`.
- ``spmd-unordered-dispatch`` — iteration order that differs across
  ranks feeding world-visible state: an unsorted ``os.listdir`` /
  ``glob`` scan (filesystem order is arbitrary), or a loop over a
  ``set`` (iteration order depends on the per-process hash seed)
  whose body publishes to a dispatch journal, JSONL stream, registry,
  or jit warm-up. Scans consumed order-insensitively (``sorted``,
  ``set``, ``len``, ``sum``...) are exempt.
- ``spmd-uncommitted-input`` — a bare ``jax.device_put(x)`` or
  ``jnp.asarray(x)`` result (committed to the *default device*)
  flowing into a ``mesh=``-taking program. On a single process that
  works by accident; on a multi-process mesh the program's sharding
  contract is broken at dispatch. Host data enters global programs
  only through the committed placers (``put_global`` /
  ``place_bucket`` / sharded ``device_put``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from distributedlpsolver_tpu.analysis import config
from distributedlpsolver_tpu.analysis.callgraph import terminal_name
from distributedlpsolver_tpu.analysis.core import (
    Finding,
    ProjectContext,
    project_rule,
)

_SCAN_CALLS = {"listdir", "scandir", "glob", "iglob", "iterdir", "rglob"}


def _is_sanctioned(key: Tuple[str, str], table) -> bool:
    pkg, qual = key
    if (pkg, qual) in table:
        return True
    head = qual.split(".", 1)[0]
    return (pkg, head) in table


def _top_level_units(project: ProjectContext):
    """Units whose bodies are not already covered by an enclosing unit
    (nested ``<locals>`` defs are walked as part of their outer frame)."""
    for key, unit in project.graph.functions.items():
        if "<locals>" not in key[1]:
            yield key, unit


def _chain_str(chain) -> str:
    return " -> ".join(chain)


# ---------------------------------------------------------------------------
# spmd-divergent-collective


def _branch_terminates(body: List[ast.stmt]) -> bool:
    if not body:
        return False
    last = body[-1]
    return isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _calls_in(node: ast.AST, site_map) -> list:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and id(sub) in site_map:
            out.append((sub,) + site_map[id(sub)])
    return out


@project_rule(
    "spmd-divergent-collective",
    "rank-derived branches must not guard paths into collectives",
)
def check_divergent_collective(project: ProjectContext) -> List[Finding]:
    out: List[Finding] = []
    graph = project.graph
    taint = project.taint
    reach = graph.reach(config.COLLECTIVE_CALLS)
    names_set = set(config.COLLECTIVE_CALLS)

    # Param sensitivity: functions that branch a collective path on one
    # of their own parameters — a caller passing a rank fact there
    # diverges just as hard as an inline branch.
    param_divergent: Dict[Tuple[str, str], Set[str]] = {}
    for key, unit in _top_level_units(project):
        # Only functions that can reach a collective at all.
        if not reach.get(key) and not any(
            t in names_set for _, _, t in unit.call_sites
        ):
            continue
        args = unit.node.args
        params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        sensitive: Set[str] = set()
        for p in params:
            if p == "self":
                continue
            seeded = taint.tainted_names(unit, seed_params=[p])
            for node in ast.walk(unit.node):
                if isinstance(node, (ast.If, ast.While)) and taint.expr_tainted(
                    node.test, seeded
                ):
                    sensitive.add(p)
                    break
        if sensitive:
            param_divergent[key] = sensitive

    for key, unit in _top_level_units(project):
        if _is_sanctioned(key, config.SPMD_SANCTIONED):
            continue
        site_map = {
            id(c): (r, t) for c, r, t in unit.call_sites
        }
        local_taint = taint.tainted_names(unit)

        def call_chain(call, resolved, term):
            return graph.call_reach(
                unit, call, resolved, term, names_set, reach
            )

        # Branches guarded by a rank-derived test.
        for node in ast.walk(unit.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if not taint.expr_tainted(node.test, local_taint):
                continue
            guarded = list(node.body) + list(getattr(node, "orelse", []))
            hit = None
            for stmt in guarded:
                for call, resolved, term in _calls_in(stmt, site_map):
                    chain = call_chain(call, resolved, term)
                    if chain:
                        hit = chain
                        break
                if hit:
                    break
            if hit is None and _branch_terminates(node.body):
                # Early exit: the divergence is everything AFTER the
                # branch — one rank leaves, the others go on to the
                # collective.
                body_lo = node.body[0].lineno
                body_hi = node.body[-1].end_lineno or body_lo
                for call, resolved, term in _calls_in(unit.node, site_map):
                    if body_lo <= call.lineno <= body_hi:
                        continue
                    chain = call_chain(call, resolved, term)
                    if chain:
                        hit = chain
                        break
            if hit:
                out.append(
                    Finding(
                        rule="spmd-divergent-collective",
                        path=unit.ctx.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"rank-derived branch in {key[1]}() guards a "
                            f"path reaching collective "
                            f"`{_chain_str(hit)}` — peers that skip the "
                            "branch hang in the collective (sanction "
                            "deliberate seams in analysis/config."
                            "SPMD_SANCTIONED)"
                        ),
                    )
                )

        # Comprehension filters guarded by a rank-derived test: the
        # element expression runs a different number of times per rank,
        # so a collective inside it diverges exactly like an ``if``
        # branch — but lives in a generator's ``ifs``, which the
        # statement walk above never visits.
        for comp, _cond in taint.comp_rank_filters(unit, local_taint):
            bodies = (
                [comp.key, comp.value]
                if isinstance(comp, ast.DictComp)
                else [comp.elt]
            )
            hit = None
            for body in bodies:
                for call, resolved, term in _calls_in(body, site_map):
                    chain = call_chain(call, resolved, term)
                    if chain:
                        hit = chain
                        break
                if hit:
                    break
            if hit:
                out.append(
                    Finding(
                        rule="spmd-divergent-collective",
                        path=unit.ctx.path,
                        line=comp.lineno,
                        col=comp.col_offset,
                        message=(
                            f"rank-derived comprehension filter in "
                            f"{key[1]}() gates collective "
                            f"`{_chain_str(hit)}` — ranks that filter "
                            "out the element skip the collective their "
                            "peers enter (sanction deliberate seams in "
                            "analysis/config.SPMD_SANCTIONED)"
                        ),
                    )
                )

        # Rank facts passed into param-sensitive callees.
        for call, resolved, term in unit.call_sites:
            if resolved is None or resolved not in param_divergent:
                continue
            callee = graph.functions[resolved]
            cargs = callee.node.args
            pos_params = [
                a.arg for a in cargs.posonlyargs + cargs.args
            ]
            if pos_params and pos_params[0] == "self":
                pos_params = pos_params[1:]
            passed: List[Tuple[str, ast.AST]] = []
            for i, a in enumerate(call.args):
                if i < len(pos_params):
                    passed.append((pos_params[i], a))
            for kw in call.keywords:
                if kw.arg:
                    passed.append((kw.arg, kw.value))
            for pname, expr in passed:
                if pname in param_divergent[resolved] and taint.expr_tainted(
                    expr, local_taint
                ):
                    out.append(
                        Finding(
                            rule="spmd-divergent-collective",
                            path=unit.ctx.path,
                            line=call.lineno,
                            col=call.col_offset,
                            message=(
                                f"rank-derived value passed as "
                                f"`{pname}` to {resolved[1]}(), which "
                                "branches a collective path on it — "
                                "the divergence just moved one call "
                                "down"
                            ),
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# spmd-unordered-dispatch


def _order_safe(ctx, node: ast.Call) -> bool:
    """True when the scan's result is consumed order-insensitively: the
    call sits (transitively) inside a ``sorted(...)`` / ``set`` / ``len``
    / ``sum`` / ... consumer within the same expression."""
    cur = node
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.Call):
            fn = anc.func
            if (
                isinstance(fn, ast.Name)
                and fn.id in config.ORDER_SAFE_CONSUMERS
                and cur is not fn
            ):
                return True
        elif isinstance(anc, ast.stmt):
            return False
        cur = anc
    return False


def _set_bound_names(unit) -> Set[str]:
    """Local names bound to set values (literal, comp, or set()/
    frozenset() call) anywhere in the unit."""
    out: Set[str] = set()
    for node in ast.walk(unit.node):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        is_set = isinstance(v, (ast.Set, ast.SetComp)) or (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Name)
            and v.func.id in ("set", "frozenset")
        )
        if not is_set:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


@project_rule(
    "spmd-unordered-dispatch",
    "world-visible iteration must not follow filesystem/set order",
)
def check_unordered_dispatch(project: ProjectContext) -> List[Finding]:
    out: List[Finding] = []
    graph = project.graph
    sink_names = set(config.ORDER_SINKS)
    reach = graph.reach(config.ORDER_SINKS)

    for key, unit in _top_level_units(project):
        site_map = {id(c): (r, t) for c, r, t in unit.call_sites}

        # (a) unsorted directory scans, package-wide: filesystem order
        # is arbitrary and differs across hosts.
        for node in ast.walk(unit.node):
            if (
                isinstance(node, ast.Call)
                and terminal_name(node.func) in _SCAN_CALLS
                and not _order_safe(unit.ctx, node)
            ):
                out.append(
                    Finding(
                        rule="spmd-unordered-dispatch",
                        path=unit.ctx.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"unsorted `{terminal_name(node.func)}` scan "
                            f"in {key[1]}() — filesystem order is "
                            "arbitrary; wrap in sorted() (or an order-"
                            "insensitive consumer) before anything "
                            "world-visible iterates it"
                        ),
                    )
                )

        # (b) loops over set values whose body reaches an order sink.
        set_names = _set_bound_names(unit)
        for node in ast.walk(unit.node):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            over_set = (
                isinstance(it, (ast.Set, ast.SetComp))
                or (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id in ("set", "frozenset")
                )
                or (isinstance(it, ast.Name) and it.id in set_names)
            )
            if not over_set:
                continue
            hit = None
            for stmt in node.body:
                for call, resolved, term in _calls_in(stmt, site_map):
                    chain = graph.call_reach(
                        unit, call, resolved, term, sink_names, reach
                    )
                    if chain:
                        hit = chain
                        break
                if hit:
                    break
            if hit:
                out.append(
                    Finding(
                        rule="spmd-unordered-dispatch",
                        path=unit.ctx.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"loop over a set in {key[1]}() publishes "
                            f"via `{_chain_str(hit)}` — set iteration "
                            "order depends on the per-process hash "
                            "seed; iterate a sorted() view"
                        ),
                    )
                )
    return out


# ---------------------------------------------------------------------------
# spmd-uncommitted-input


def _is_bare_put(node: ast.AST) -> bool:
    """``jax.device_put(x)`` (no sharding) or ``jnp.asarray(x)`` — a
    default-device commitment."""
    if not isinstance(node, ast.Call):
        return False
    term = terminal_name(node.func)
    if term == "device_put":
        return len(node.args) < 2 and not node.keywords
    if term == "asarray":
        return (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "jnp"
        )
    return False


def _is_committed(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    term = terminal_name(node.func)
    if term in config.COMMITTED_PLACERS:
        return True
    return term == "device_put" and (len(node.args) >= 2 or bool(node.keywords))


def _mesh_sink(node: ast.Call) -> bool:
    term = terminal_name(node.func)
    for kw in node.keywords:
        if kw.arg == "mesh" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None
        ):
            return True
    return term == "execute_dispatch" and bool(node.args)


def _mesh_none_guarded(ctx, node: ast.AST) -> bool:
    """True when ``node`` sits under an ``if`` whose test compares a
    ``mesh``-named value against None — the single-device fallback
    branch, where a bare default-device put is exactly right."""
    for anc in ctx.ancestors(node):
        if not isinstance(anc, ast.If):
            continue
        has_mesh = any(
            (isinstance(s, ast.Name) and "mesh" in s.id)
            or (isinstance(s, ast.Attribute) and "mesh" in s.attr)
            for s in ast.walk(anc.test)
        )
        has_none = any(
            isinstance(s, ast.Constant) and s.value is None
            for s in ast.walk(anc.test)
        )
        if has_mesh and has_none:
            return True
    return False


@project_rule(
    "spmd-uncommitted-input",
    "mesh programs take put_global/place_bucket-committed arrays only",
)
def check_uncommitted_input(project: ProjectContext) -> List[Finding]:
    out: List[Finding] = []
    for key, unit in _top_level_units(project):
        uncommitted: Set[str] = set()
        for node in ast.walk(unit.node):
            if not isinstance(node, ast.Assign):
                continue
            if _is_bare_put(node.value) and not _mesh_none_guarded(
                unit.ctx, node
            ):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            uncommitted.add(sub.id)
            elif _is_committed(node.value):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            uncommitted.discard(sub.id)
        for node in ast.walk(unit.node):
            if not (isinstance(node, ast.Call) and _mesh_sink(node)):
                continue
            exprs = list(node.args) + [
                kw.value for kw in node.keywords if kw.arg != "mesh"
            ]
            for expr in exprs:
                bad: Optional[str] = None
                if _is_bare_put(expr):
                    bad = terminal_name(expr.func)
                else:
                    for sub in ast.walk(expr):
                        if isinstance(sub, ast.Name) and sub.id in uncommitted:
                            bad = sub.id
                            break
                        if isinstance(sub, ast.Call):
                            break  # nested call results judged at their own site
                if bad:
                    out.append(
                        Finding(
                            rule="spmd-uncommitted-input",
                            path=unit.ctx.path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{bad}` enters a mesh program in "
                                f"{key[1]}() without a committed "
                                "placement — default-device arrays "
                                "break the multi-process sharding "
                                "contract; route through put_global/"
                                "place_bucket (or device_put with an "
                                "explicit sharding)"
                            ),
                        )
                    )
                    break
    return out
