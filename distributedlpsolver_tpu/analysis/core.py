"""graftcheck framework: rule registry, suppressions, reporters.

The checker is deliberately stdlib-only (``ast`` + ``tokenize``-free
line scanning): it must run on CPU-only CI in well under a second with
no jax import, because its whole point is catching accelerator-hygiene
regressions *before* a TPU round is spent discovering them at runtime
(README "Static analysis").

A rule is a function ``rule(ctx) -> Iterable[Finding]`` registered with
:func:`rule`. ``ctx`` is a :class:`FileContext` carrying the parsed AST,
raw source lines, and the file's package-relative path (``pkg_path``) so
rules can scope themselves to ``ops/``, ``serve/service.py``, etc.
Repo-specific tuning (hot scopes, sanctioned modules, the JSONL field
catalogue) lives in :mod:`analysis.config`, keeping this module generic.

Suppressions
------------
``# graftcheck: disable=<rule>[,<rule>...]`` on a finding's line — or on
a standalone comment line directly above it — suppresses those rules
there (``disable=all`` suppresses every rule). The same directive on a
``def``/``class`` line suppresses within that whole definition.
``# graftcheck: disable-file=<rule>[,...]`` anywhere in a file (by
convention the top) suppresses file-wide. Suppressed findings are still
collected and reported (``suppressed: true`` in the JSON reporter) so
the deliberate-exception inventory stays visible; only unsuppressed
findings fail the gate.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_DIRECTIVE = re.compile(
    r"#\s*graftcheck:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # as given to the checker (display path)
    line: int  # 1-indexed
    col: int  # 0-indexed
    message: str
    suppressed: bool = False

    def asdict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


# ---------------------------------------------------------------------------
# Rule registry

_RULES: Dict[str, Tuple[Callable, str]] = {}
_PROJECT_RULES: Dict[str, Tuple[Callable, str]] = {}


def rule(name: str, doc: str):
    """Register a checker function under ``name`` (its gate identity and
    the token suppression comments name)."""

    def deco(fn):
        if name in _RULES or name in _PROJECT_RULES:
            raise ValueError(f"duplicate graftcheck rule {name!r}")
        _RULES[name] = (fn, doc)
        fn.rule_name = name
        return fn

    return deco


def project_rule(name: str, doc: str):
    """Register an *interprocedural* rule: ``fn(project) ->
    Iterable[Finding]`` over a :class:`ProjectContext` (whole analyzed
    file set + call graph) instead of one file. Project rules run once
    per check invocation; their findings are attributed to individual
    files and go through the same per-file suppression machinery."""

    def deco(fn):
        if name in _RULES or name in _PROJECT_RULES:
            raise ValueError(f"duplicate graftcheck rule {name!r}")
        _PROJECT_RULES[name] = (fn, doc)
        fn.rule_name = name
        return fn

    return deco


def all_rules() -> Dict[str, str]:
    """{rule name: one-line description} for --list-rules and docs."""
    _load_rules()
    merged = dict(_RULES)
    merged.update(_PROJECT_RULES)
    return {name: doc for name, (fn, doc) in sorted(merged.items())}


_loaded = False


def _load_rules() -> None:
    # Import-for-side-effect: each rules module populates the registry.
    global _loaded
    if _loaded:
        return
    from distributedlpsolver_tpu.analysis import (  # noqa: F401
        rules_dtype,
        rules_jit,
        rules_locks,
        rules_schema,
        rules_spmd,
    )

    _loaded = True


# ---------------------------------------------------------------------------
# Per-file context

class FileContext:
    """Everything a rule needs about one file."""

    def __init__(self, path: str, source: str, pkg_path: Optional[str] = None):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # Path relative to the package root ("serve/service.py") — the
        # key rules scope on. Inferred from the real path; tests checking
        # fixture files pass ``pkg_path`` to emulate a package location.
        self.pkg_path = pkg_path if pkg_path is not None else _infer_pkg_path(path)
        # parent links let rules walk outward (enclosing With/FunctionDef)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def in_dirs(self, *dirs: str) -> bool:
        """True if the file lives under any of the given package dirs."""
        top = self.pkg_path.split("/", 1)[0]
        return top in dirs

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None


def _infer_pkg_path(path: str) -> str:
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "distributedlpsolver_tpu" in parts:
        i = len(parts) - 1 - parts[::-1].index("distributedlpsolver_tpu")
        return "/".join(parts[i + 1 :])
    return parts[-1]


# ---------------------------------------------------------------------------
# Whole-file-set context (graftcheck v2)

class ProjectContext:
    """Everything an interprocedural rule needs about the analyzed file
    set: the per-file contexts plus the lazily-built call graph, taint
    engine, and lock model (analysis/callgraph.py). A single fixture
    file checked via :func:`check_file` gets a degenerate one-file
    project — the same rules run, just with nothing to resolve across.
    """

    def __init__(self, contexts: Sequence[FileContext]):
        self.contexts = list(contexts)
        self.by_path: Dict[str, FileContext] = {
            c.pkg_path: c for c in self.contexts
        }
        self._graph = None
        self._taint = None
        self._locks = None

    @property
    def graph(self):
        if self._graph is None:
            from distributedlpsolver_tpu.analysis.callgraph import CallGraph

            self._graph = CallGraph(self.contexts)
        return self._graph

    @property
    def taint(self):
        if self._taint is None:
            from distributedlpsolver_tpu.analysis import config
            from distributedlpsolver_tpu.analysis.callgraph import TaintEngine

            self._taint = TaintEngine(self.graph, config.RANK_ENV_KEYS)
        return self._taint

    @property
    def locks(self):
        if self._locks is None:
            from distributedlpsolver_tpu.analysis.callgraph import LockModel

            self._locks = LockModel(self.graph)
        return self._locks


# ---------------------------------------------------------------------------
# Suppressions

class _Suppressions:
    def __init__(self, ctx: FileContext):
        self.file_wide: set = set()
        self.by_line: Dict[int, set] = {}
        for i, text in enumerate(ctx.lines, start=1):
            m = _DIRECTIVE.search(text)
            if not m:
                continue
            names = {t.strip() for t in m.group(2).split(",") if t.strip()}
            if m.group(1) == "disable-file":
                self.file_wide |= names
            else:
                self.by_line.setdefault(i, set()).update(names)
        # A directive on a def/class line covers the whole definition.
        self.spans: List[Tuple[int, int, set]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                names = self.by_line.get(node.lineno)
                if names:
                    self.spans.append(
                        (node.lineno, node.end_lineno or node.lineno, names)
                    )
        self._lines = ctx.lines

    def covers(self, f: Finding) -> bool:
        def match(names: set) -> bool:
            return "all" in names or f.rule in names

        if match(self.file_wide):
            return True
        names = self.by_line.get(f.line)
        if names and match(names):
            return True
        # A standalone comment line directly above the finding.
        prev = self.by_line.get(f.line - 1)
        if (
            prev
            and match(prev)
            and f.line - 2 < len(self._lines)
            and self._lines[f.line - 2].lstrip().startswith("#")
        ):
            return True
        return any(
            lo <= f.line <= hi and match(names) for lo, hi, names in self.spans
        )


# ---------------------------------------------------------------------------
# Entry points

def _split_rule_names(rules: Optional[Sequence[str]]):
    """(file_rule_names, project_rule_names) with unknown-name check."""
    names = list(rules) if rules is not None else None
    if names is None:
        return list(_RULES), list(_PROJECT_RULES)
    unknown = [n for n in names if n not in _RULES and n not in _PROJECT_RULES]
    if unknown:
        raise ValueError(f"unknown graftcheck rule(s): {unknown}")
    return (
        [n for n in names if n in _RULES],
        [n for n in names if n in _PROJECT_RULES],
    )


def _run_rules(
    contexts: Sequence[FileContext],
    parse_errors: Sequence[Finding],
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """File rules per context + project rules once over the whole set,
    then suppressions per file. The shared tail of check_file/check_paths."""
    file_rules, project_rules = _split_rule_names(rules)
    findings: List[Finding] = list(parse_errors)
    for ctx in contexts:
        for name in file_rules:
            fn, _doc = _RULES[name]
            findings.extend(fn(ctx))
    if project_rules and contexts:
        project = ProjectContext(contexts)
        for name in project_rules:
            fn, _doc = _PROJECT_RULES[name]
            findings.extend(fn(project))
    by_display: Dict[str, FileContext] = {c.path: c for c in contexts}
    sups: Dict[str, _Suppressions] = {}
    for f in findings:
        ctx = by_display.get(f.path)
        if ctx is None:
            continue
        sup = sups.get(f.path)
        if sup is None:
            sup = sups[f.path] = _Suppressions(ctx)
        f.suppressed = sup.covers(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def check_file(
    path: str,
    source: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    pkg_path: Optional[str] = None,
) -> List[Finding]:
    """Run ``rules`` (default: all registered) over one file — project
    rules see a one-file project. Returns every finding, suppressed ones
    flagged — callers filter."""
    _load_rules()
    if source is None:
        with open(path) as fh:
            source = fh.read()
    try:
        ctx = FileContext(path, source, pkg_path=pkg_path)
    except SyntaxError as e:
        return [
            Finding(
                rule="parse-error",
                path=path,
                line=e.lineno or 0,
                col=e.offset or 0,
                message=f"file does not parse: {e.msg}",
            )
        ]
    return _run_rules([ctx], [], rules=rules)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                out.extend(
                    os.path.join(root, f) for f in sorted(files) if f.endswith(".py")
                )
        else:
            out.append(p)
    return out


def check_paths(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the suite over files and directories (recursed). All files
    are parsed up front so the interprocedural rules resolve calls
    across every file given in one project view."""
    _load_rules()
    contexts: List[FileContext] = []
    parse_errors: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path) as fh:
            source = fh.read()
        try:
            contexts.append(FileContext(path, source))
        except SyntaxError as e:
            parse_errors.append(
                Finding(
                    rule="parse-error",
                    path=path,
                    line=e.lineno or 0,
                    col=e.offset or 0,
                    message=f"file does not parse: {e.msg}",
                )
            )
    return _run_rules(contexts, parse_errors, rules=rules)


# ---------------------------------------------------------------------------
# Findings baseline (incremental diff-gate)

def baseline_key(f: Finding) -> str:
    """Line-number-independent identity of a finding for baseline
    comparison: rule + package-relative path + message. Line numbers
    drift with every edit; the message (which names the symbol and the
    violated contract) is stable until the code actually changes."""
    parts = f.path.replace(os.sep, "/").split("/")
    if "distributedlpsolver_tpu" in parts:
        i = len(parts) - 1 - parts[::-1].index("distributedlpsolver_tpu")
        rel = "/".join(parts[i + 1 :])
    else:
        rel = parts[-1]
    return f"{f.rule}::{rel}::{f.message}"


def write_baseline(findings: Sequence[Finding]) -> str:
    """Serialize the unsuppressed findings as a committed baseline
    document (``cli check --write-baseline``)."""
    keys: Dict[str, int] = {}
    for f in findings:
        if not f.suppressed:
            k = baseline_key(f)
            keys[k] = keys.get(k, 0) + 1
    return json.dumps(
        {"schema": 1, "findings": keys},
        indent=2,
        sort_keys=True,
    )


def diff_baseline(
    findings: Sequence[Finding], baseline_doc: dict
) -> List[Finding]:
    """The unsuppressed findings NOT covered by the baseline — the
    diff-gate's failure set. A baseline entry covers as many findings of
    its key as it counted; the (N+1)-th is new."""
    budget = dict(baseline_doc.get("findings", {}))
    new: List[Finding] = []
    for f in findings:
        if f.suppressed:
            continue
        k = baseline_key(f)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(f)
    return new


# ---------------------------------------------------------------------------
# Reporters

def render_text(findings: Sequence[Finding], show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [f.render() for f in shown]
    n_bad = sum(1 for f in findings if not f.suppressed)
    n_sup = len(findings) - n_bad
    lines.append(
        f"graftcheck: {n_bad} finding(s), {n_sup} suppressed"
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    """Machine-readable gate output (``cli check --json``)."""
    return json.dumps(
        {
            "findings": [f.asdict() for f in findings if not f.suppressed],
            "suppressed": [f.asdict() for f in findings if f.suppressed],
            "counts": {
                "findings": sum(1 for f in findings if not f.suppressed),
                "suppressed": sum(1 for f in findings if f.suppressed),
            },
            "rules": all_rules(),
        },
        indent=2,
    )
