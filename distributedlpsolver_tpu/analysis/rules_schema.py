"""JSONL schema-conformance rules.

Every telemetry stream in the repo — iteration rows, serve
request/batch records, supervisor fault events, the CLI's serve result
stream — shares one record schema (obs.SCHEMA_VERSION + the field
catalogue in analysis/config), and ``cli report`` / ``cli autotune``
dispatch on those fields. Two statically visible drift modes:

- ``jsonl-fields`` — an ``IterLogger.event({...})`` payload carrying an
  uncatalogued field or event type. Uncatalogued fields are invisible
  to every consumer (report silently drops them; autotune can't use
  them), so adding one must be a deliberate catalogue edit, not a
  stray key. Literal keys are checked; ``**splat`` payloads are checked
  at their own literal source.
- ``jsonl-stamp`` — a record written to a stream (``X.write(
  json.dumps(...))``) without routing through ``stamp_record``, losing
  the schema_version/ts/t_mono stamps that let report merge streams
  across processes. Whole-file JSON artifacts (Chrome traces, metric
  snapshots) use ``json.dump(obj, fh)`` and are exempt by pattern;
  HTTP response bodies are ``json.dumps(...).encode()`` bytes and
  exempt by the same token (replies, not stream records).

Since graftcheck v2 both rules see through one level of local dataflow:
``payload = json.dumps({...}); fh.write(payload)`` is checked at the
write (the PR 13 heartbeat-writer pattern the lexical rule missed), and
literal dicts passed to ``stamp_record({...})`` have their keys checked
against the catalogue exactly like ``.event({...})`` payloads.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from distributedlpsolver_tpu.analysis import config
from distributedlpsolver_tpu.analysis.core import FileContext, Finding, rule


def _is_event_call(node: ast.Call) -> bool:
    """``<logger-ish>.event({...})`` — the IterLogger event surface (the
    tracer has no ``event`` method, so attribute name is decisive) —
    or a literal record stamped for a stream, ``stamp_record({...})``."""
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "event"
        and len(node.args) == 1
    ):
        return True
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
    return name == "stamp_record" and len(node.args) == 1


@rule(
    "jsonl-fields",
    "IterLogger.event/stamp_record payloads carry only catalogued fields/types",
)
def check_event_fields(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and _is_event_call(node)):
            continue
        payload = node.args[0]
        if not isinstance(payload, ast.Dict):
            continue  # non-literal payloads are checked at their source
        event_type = None
        for key, value in zip(payload.keys, payload.values):
            if key is None:  # **splat — its literal source is checked
                continue
            if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                continue
            if key.value == "event" and isinstance(value, ast.Constant):
                event_type = value.value
            if key.value not in config.JSONL_FIELDS:
                out.append(
                    Finding(
                        rule="jsonl-fields",
                        path=ctx.path,
                        line=key.lineno,
                        col=key.col_offset,
                        message=(
                            f"JSONL field {key.value!r} is not in the "
                            "schema catalogue (analysis/config."
                            "JSONL_FIELDS) — consumers will drop it; "
                            "catalogue it deliberately"
                        ),
                    )
                )
        if event_type is not None and event_type not in config.JSONL_EVENT_TYPES:
            out.append(
                Finding(
                    rule="jsonl-fields",
                    path=ctx.path,
                    line=payload.lineno,
                    col=payload.col_offset,
                    message=(
                        f"event type {event_type!r} is not in "
                        "analysis/config.JSONL_EVENT_TYPES — report/"
                        "autotune will not recognize these records"
                    ),
                )
            )
    return out


def _dumps_arg(node: ast.AST, ctx: Optional[FileContext] = None):
    """The first argument of a ``json.dumps(...)`` call found anywhere
    inside ``node`` (write argument expressions are concatenations).
    ``json.dumps(...).encode()`` results are exempt when ``ctx`` is
    given — those are HTTP body bytes, not stream records."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "dumps"
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "json"
            and sub.args
        ):
            if ctx is not None:
                parent = ctx.parents.get(sub)
                if isinstance(parent, ast.Attribute) and parent.attr == "encode":
                    continue
            return sub.args[0]
    return None


def _local_bindings(ctx: FileContext, node: ast.AST) -> dict:
    """name -> last assigned value expression in the enclosing function
    (or module body) — the one level of dataflow the stamp rule sees
    through (``payload = json.dumps(...); fh.write(payload)``)."""
    fn = ctx.enclosing_function(node) or ctx.tree
    out: dict = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = sub.value
    return out


@rule(
    "jsonl-stamp",
    "stream writes of json.dumps records must route through stamp_record",
)
def check_stamp(ctx: FileContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "write"
            and len(node.args) == 1
        ):
            continue
        arg = node.args[0]
        payload = _dumps_arg(arg, ctx)
        if payload is None:
            # One level of local dataflow: a Name in the write argument
            # bound to a json.dumps(...) expression earlier in the
            # function (the heartbeat-writer pattern).
            bindings = None
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Name):
                    continue
                if bindings is None:
                    bindings = _local_bindings(ctx, node)
                bound = bindings.get(sub.id)
                if bound is not None:
                    payload = _dumps_arg(bound, ctx)
                    if payload is not None:
                        break
        if payload is None:
            continue
        stamped = (
            isinstance(payload, ast.Call)
            and (
                (isinstance(payload.func, ast.Name) and payload.func.id == "stamp_record")
                or (
                    isinstance(payload.func, ast.Attribute)
                    and payload.func.attr == "stamp_record"
                )
            )
        )
        if not stamped:
            out.append(
                Finding(
                    rule="jsonl-stamp",
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        "JSONL record written without stamp_record — it "
                        "loses schema_version/ts/t_mono and cli report "
                        "cannot merge the stream"
                    ),
                )
            )
    return out
