"""dtype-discipline rules.

The solver's precision story is deliberate and layered: f64 iterates,
f32 factorizations under the two-phase schedule, f32-gram/f64c Schur
assembly, and the MXU panel kernels — each narrowing is a *scheduled*
decision with a measured error budget (ROUND5_NOTES). Two statically
visible ways that discipline erodes:

- ``dtype-explicit`` — a ``jnp.zeros``/``jnp.array``-family call in the
  device-math layers (config.DTYPE_SCOPE_DIRS) without an explicit
  dtype. The default depends on the x64 flag and on TPU quietly differs
  from the CPU test rig, so "whatever the default is" is exactly how a
  CPU-green/TPU-wrong buffer is born. ``*_like`` constructors and
  ``arange`` (index arithmetic) inherit deliberately and are exempt.
- ``dtype-narrow`` — an ``.astype(float32)`` (or ``jnp.float32(x)``)
  outside the sanctioned mixed-precision schedule modules
  (config.NARROW_SANCTIONED). Narrowing anywhere else silently spends
  precision the two-phase design never budgeted.
"""

from __future__ import annotations

import ast
from typing import List

from distributedlpsolver_tpu.analysis import config
from distributedlpsolver_tpu.analysis.core import FileContext, Finding, rule


def _jnp_call(node: ast.Call) -> str:
    """The constructor name for ``jnp.<name>(...)`` calls, else ''."""
    fn = node.func
    if (
        isinstance(fn, ast.Attribute)
        and isinstance(fn.value, ast.Name)
        and fn.value.id in ("jnp",)
    ):
        return fn.attr
    return ""


def _literalish(node: ast.AST) -> bool:
    """Python-literal-valued expressions whose array dtype is minted by
    the constructor: constants, list/tuple displays of them, and unary
    minus. Name/Attribute/Call inputs carry their own dtype."""
    if isinstance(node, ast.Constant):
        return not isinstance(node.value, str)
    if isinstance(node, ast.UnaryOp):
        return _literalish(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_literalish(el) for el in node.elts)
    return False


@rule(
    "dtype-explicit",
    "jnp constructors in ops/ipm/backends must pin an explicit dtype",
)
def check_dtype_explicit(ctx: FileContext) -> List[Finding]:
    if not ctx.in_dirs(*config.DTYPE_SCOPE_DIRS):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _jnp_call(node)
        if name not in config.DTYPE_CONSTRUCTORS:
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        dtype_pos = config.DTYPE_CONSTRUCTORS[name]
        if len(node.args) > dtype_pos:
            continue  # dtype given positionally (the repo's short form)
        # array/asarray inherit the input's dtype; the default only
        # kicks in for Python literals (where x64-flag dependence bites).
        if name in ("array", "asarray") and node.args and not _literalish(
            node.args[0]
        ):
            continue
        out.append(
            Finding(
                rule="dtype-explicit",
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"jnp.{name}(...) without an explicit dtype — the "
                    "default is x64-flag- and platform-dependent; pin it"
                ),
            )
        )
    return out


_F32_NAMES = {"f32", "F32"}


def _is_float32(node: ast.AST) -> bool:
    """Expression that denotes float32: jnp/np.float32, the repo's f32
    alias, or the string literal."""
    if isinstance(node, ast.Attribute) and node.attr == "float32":
        return True
    if isinstance(node, ast.Name) and node.id in _F32_NAMES:
        return True
    if isinstance(node, ast.Constant) and node.value == "float32":
        return True
    return False


@rule(
    "dtype-narrow",
    "f64->f32 narrowing only inside sanctioned mixed-precision modules",
)
def check_dtype_narrow(ctx: FileContext) -> List[Finding]:
    if not ctx.in_dirs(*config.DTYPE_SCOPE_DIRS):
        return []
    if ctx.pkg_path in config.NARROW_SANCTIONED:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        narrow = None
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "astype"
            and node.args
            and _is_float32(node.args[0])
        ):
            narrow = ".astype(float32)"
        elif (
            isinstance(fn, ast.Attribute)
            and fn.attr == "float32"
            and isinstance(fn.value, ast.Name)
            and fn.value.id in ("jnp",)
            and node.args
        ):
            narrow = "jnp.float32(...)"
        if narrow is None:
            continue
        out.append(
            Finding(
                rule="dtype-narrow",
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"{narrow} in {ctx.pkg_path}, which is not a "
                    "sanctioned mixed-precision schedule module — "
                    "unbudgeted precision loss"
                ),
            )
        )
    return out
