"""Repo-specific graftcheck tuning: which scopes are hot, which modules
may narrow precision, which programs must donate, and the JSONL record
schema catalogue. Rules read these tables; changing project policy means
editing here, not the rule logic.
"""

from __future__ import annotations

# -- host-sync (rules_jit) ---------------------------------------------------
# Function scopes where a host↔device synchronization is a pipeline
# stall: the serve dispatcher's pack/solve thread bodies (a sync there
# serializes the two-deep pipeline PR 4 built) and the IPM driver's
# per-iteration loop (a sync there caps iters/sec). Keys are
# package-relative paths; values are qualnames ("Class.method" or bare
# function names). Deliberate sync points inside these scopes carry
# line-level ``# graftcheck: disable=host-sync`` comments explaining why.
HOT_SCOPES = {
    "serve/service.py": {
        "SolveService._run_pack",
        "SolveService._pack_bucket",
        "SolveService._run_solve",
        "SolveService._dispatch",
        "SolveService._dispatch_bucket",
    },
    "ipm/driver.py": {
        "solve",
        "_step_once",
    },
    # Network serving plane thread bodies: the router's poll loop and
    # forward path run concurrently with every backend's pipeline, and
    # the HTTP front-end's handler/health threads must never touch a
    # device value (all device work stays on the service's pipeline
    # threads — a sync here would serialize handler threads behind it).
    "net/router.py": {
        "Router._poll_loop",
        "Router.poll_once",
        "Router._record_probe",
        "Router.forward",
    },
    "net/server.py": {
        "SolveHTTPServer.health",
        "_Handler.do_POST",
        "_Handler.do_GET",
    },
}

# -- jit-donate (rules_jit) --------------------------------------------------
# Programs whose big per-call buffers are consumed by the call and dead
# afterwards; their jit definitions must carry donate_argnums so the
# device reuses the buffers in place. NOT in this table (deliberately):
# the fused bucket program's INPUTS (_solve_bucket_jit) — they are
# re-dispatched verbatim on batch retry and shared with warm-up calls,
# so donating them would poison the retry path; and A/data of the
# segment programs, which are loop-invariant across segments. The bucket
# SEGMENT carry (_bucket_segment_jit) is internal to one dispatch and
# rebound per segment, so it donates like the batched one.
DONATE_EXPECTED = {
    # (pkg_path, function name) -> human description of the donated arg
    ("backends/batched.py", "_batched_segment_jit"): "carry (arg 2)",
    ("backends/batched.py", "_bucket_segment_jit"): "carry (arg 2)",
    ("backends/dense.py", "_eg_scale_reg"): "M (arg 0)",
}

# -- dtype rules (rules_dtype) -----------------------------------------------
# Package dirs where every jnp constructor must pin its dtype: these are
# the device-math layers where "whatever the default is" has already
# produced silent f32-on-TPU / x64-flag surprises.
DTYPE_SCOPE_DIRS = ("ops", "ipm", "backends")

# jnp constructors and the positional index their signature accepts
# dtype at (the repo writes both ``jnp.zeros(n, jnp.f32)`` and
# ``dtype=``). ``*_like`` variants inherit and are exempt; ``arange`` is
# exempt — its int default is the index-arithmetic convention here.
DTYPE_CONSTRUCTORS = {
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "eye": 3,
    "identity": 1,
    "array": 1,
    "asarray": 1,
}

# Modules sanctioned to narrow f64→f32: the mixed-precision schedule
# owners (ROUND5_NOTES — the f32-gram/f64c and df32 schedules, the
# two-phase f32 factorization ladder, and the MXU panel kernels).
# Anywhere else, an ``.astype(float32)`` is a silent precision loss the
# two-phase design never sanctioned.
NARROW_SANCTIONED = {
    "ops/chol_mxu.py",
    "ops/df32.py",  # the two-float layer: every df32 narrowing lives there
    "ops/normal_eq.py",
    "backends/dense.py",
    "backends/block_angular.py",
    "backends/batched.py",
    # Huge-sparse tier: the ELL operator stores int32 column indices and
    # may down-convert cached f64 value arrays to the configured solve
    # dtype; the PCG preconditioners build f32 probe factors for the
    # loose (early-μ) forcing-sequence solves.
    "ops/sparse.py",
    "ops/pcg.py",
}

# -- JSONL schema (rules_schema) ---------------------------------------------
# Event types the telemetry streams may carry (IterLogger.event payloads
# and RequestResult.record). ``cli report`` and the autotuner dispatch on
# these; an uncatalogued type is invisible to every consumer.
JSONL_EVENT_TYPES = {
    "batch",
    "dispatch_error",
    "fault",
    "ladder_swap",
    "reject",
    "request",
    "reshard",
    "resume",
    "service",
    "warmup",
    "warmup_error",
    # Network serving plane (net/): one record per HTTP request on a
    # front-end, per routed forward on the router, and per backend
    # rotation change (ejection on failed health / forward, re-admission
    # on recovery).
    "http_request",
    "route",
    "backend_ejected",
    "backend_readmitted",
    # Crash-safe serving fabric: one record per journal recovery pass
    # (serve/service._replay_journal), per drain phase transition
    # (begin/end/listener_close), and per applied shared-registry
    # mutation (net/registry.py).
    "journal_replay",
    "drain",
    "registry_write",
    # Multi-host runtime (distributed/): one record per coordinator-
    # level world re-initialization (launcher.WorldSupervisor — a dead
    # rank kills the world as a unit, recovery relaunches a smaller
    # one), per slice self-registration into the shared backend
    # registry, and per registry liveness beat where a stream consumer
    # wants them (cli serve-slice).
    "world_reinit",
    "slice_register",
    "heartbeat",
}

# Every field a stamped JSONL record may carry, across all streams: the
# stamp_record fields, iteration-row fields (ipm.state.IterRecord), the
# serve request/batch/service records, and the supervisor fault/resume
# events. The checker flags literal keys outside this set — adding a
# field is fine, but it must be catalogued here (and picked up by
# obs/report) in the same change.
JSONL_FIELDS = {
    # stamp_record
    "schema_version",
    "t_mono",
    "ts",
    # IterRecord rows
    "alpha_d",
    "alpha_p",
    "dinf",
    "dobj",
    "gap",
    "iter",
    "mu",
    "pinf",
    "pobj",
    "rel_gap",
    "sigma",
    "t_iter",
    # event discriminator
    "event",
    # serve request records (serve/records.py RequestResult.record)
    "bucket",
    "compile_ms",
    "dispatch",
    "faults",
    "id",
    "iterations",
    "m",
    "n",
    "name",
    "objective",
    "overlap_ms",
    "pack_ms",
    "padding_waste",
    "queue_ms",
    "retried_solo",
    "slot",
    "solve_ms",
    "status",
    "total_ms",
    # serve batch/fault/lifecycle events (serve/service.py)
    "action",
    "attempts",
    "buckets",
    "cache",
    "detail",
    "devices",
    "excluded",
    "fused_iters",
    "kind",
    "live",
    "mesh_devices",
    "metrics",
    "migrated",
    "misfits",
    "occupancy",
    "queue_depth",
    "schedule",
    "tol",
    # warm-start & amortization layer: request records carry the
    # "warm"/"rejected"/"cold" start label, batch events the number of
    # warm-started slots (serve/service.py, serve/records.py)
    "warm",
    # huge-sparse tier (tolerance-tiered serve ladder + inexact IPM):
    # request/batch records carry the solve engine ("ipm"|"pdhg"),
    # sparse-iterative iteration rows/bench rows the PCG iteration count
    # and the resolved preconditioner (jacobi/block/bordered)
    "engine",
    "cg_iters",
    "precond",
    # stochastic scenario tier: scenario-request records carry the
    # scenario count, the padded scenario-count bucket
    # (models/scenario.scenario_k_bucket), and the decomposition's
    # stage split — batched per-scenario Schur wall vs first-stage
    # linking wall (serve/records.py, backends/scenario.py)
    "n_scenarios",
    "scenario_bucket",
    "schur_ms",
    "link_ms",
    # network serving plane (net/): http_request records (method/path/
    # code/ms), admission-verdict reject records (tenant/priority/
    # reason/retry_after_s), router route records (backend/padding/
    # retried) and rotation events (fails), and the summary event's
    # per-tenant admission table
    "admission",
    "code",
    "fails",
    "method",
    "ms",
    "path",
    "priority",
    "reason",
    "retried",
    "retry_after_s",
    "tenant",
    # supervisor fault/resume events (supervisor/supervisor.py)
    "backend",
    "iteration",
    "recovery_overhead_s",
    "t",
    # crash-safe serving fabric: journal_replay tallies (replayed/
    # re-enqueued/expired-honest-TIMEOUT/failed-spec, torn/skipped WAL
    # lines, result files re-bound), drain phases (begin/end/
    # listener_close + drained verdict + in-flight count), and
    # registry_write records (ejected flag, file generation, writer id)
    "replayed",
    "reenqueued",
    "expired",
    "failed",
    "torn",
    "skipped",
    "results",
    "phase",
    "inflight",
    "drained",
    "ejected",
    "generation",
    "writer",
    # multi-host runtime (distributed/, cli serve-slice, supervisor
    # probe-fault attribution): which process observed/emitted the
    # record, the world it belonged to, and the logical slice — stamped
    # on world_reinit / slice_register / heartbeat events and on
    # supervisor fault records (probes only see addressable devices, so
    # the rank scopes the evidence).
    "rank",
    "world_size",
    "slice_id",
}

# ``X.write(json.dumps(...))`` record emission points that must stamp:
# every JSONL stream a consumer merges needs schema_version/ts/t_mono.
# (Chrome-trace and metric-snapshot files use ``json.dump(obj, fh)`` and
# are whole-file JSON, not JSONL records — the pattern doesn't match
# them, by design.)
