"""Repo-specific graftcheck tuning: which scopes are hot, which modules
may narrow precision, which programs must donate, and the JSONL record
schema catalogue. Rules read these tables; changing project policy means
editing here, not the rule logic.
"""

from __future__ import annotations

# -- host-sync (rules_jit) ---------------------------------------------------
# Function scopes where a host↔device synchronization is a pipeline
# stall: the serve dispatcher's pack/solve thread bodies (a sync there
# serializes the two-deep pipeline PR 4 built) and the IPM driver's
# per-iteration loop (a sync there caps iters/sec). Keys are
# package-relative paths; values are qualnames ("Class.method" or bare
# function names). Deliberate sync points inside these scopes carry
# line-level ``# graftcheck: disable=host-sync`` comments explaining why.
HOT_SCOPES = {
    "serve/service.py": {
        "SolveService._run_pack",
        "SolveService._pack_bucket",
        "SolveService._run_solve",
        "SolveService._dispatch",
        "SolveService._dispatch_bucket",
    },
    "ipm/driver.py": {
        "solve",
        "_step_once",
    },
    # Network serving plane thread bodies: the router's poll loop and
    # forward path run concurrently with every backend's pipeline, and
    # the HTTP front-end's handler/health threads must never touch a
    # device value (all device work stays on the service's pipeline
    # threads — a sync here would serialize handler threads behind it).
    "net/router.py": {
        "Router._poll_loop",
        "Router.poll_once",
        "Router._record_probe",
        "Router.forward",
        # Hedge legs run on their own threads concurrently with the
        # client-facing forward — same no-device-value contract.
        "Router._forward_hedged",
        "Router._attempt_result",
        "Router._cancel_loser",
    },
    "net/server.py": {
        "SolveHTTPServer.health",
        "_Handler.do_POST",
        "_Handler.do_GET",
    },
}

# -- jit-donate (rules_jit) --------------------------------------------------
# Programs whose big per-call buffers are consumed by the call and dead
# afterwards; their jit definitions must carry donate_argnums so the
# device reuses the buffers in place. NOT in this table (deliberately):
# the fused bucket program's INPUTS (_solve_bucket_jit) — they are
# re-dispatched verbatim on batch retry and shared with warm-up calls,
# so donating them would poison the retry path; and A/data of the
# segment programs, which are loop-invariant across segments. The bucket
# SEGMENT carry (_bucket_segment_jit) is internal to one dispatch and
# rebound per segment, so it donates like the batched one.
DONATE_EXPECTED = {
    # (pkg_path, function name) -> human description of the donated arg
    ("backends/batched.py", "_batched_segment_jit"): "carry (arg 2)",
    ("backends/batched.py", "_bucket_segment_jit"): "carry (arg 2)",
    ("backends/dense.py", "_eg_scale_reg"): "M (arg 0)",
}

# -- dtype rules (rules_dtype) -----------------------------------------------
# Package dirs where every jnp constructor must pin its dtype: these are
# the device-math layers where "whatever the default is" has already
# produced silent f32-on-TPU / x64-flag surprises.
DTYPE_SCOPE_DIRS = ("ops", "ipm", "backends")

# jnp constructors and the positional index their signature accepts
# dtype at (the repo writes both ``jnp.zeros(n, jnp.f32)`` and
# ``dtype=``). ``*_like`` variants inherit and are exempt; ``arange`` is
# exempt — its int default is the index-arithmetic convention here.
DTYPE_CONSTRUCTORS = {
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "eye": 3,
    "identity": 1,
    "array": 1,
    "asarray": 1,
}

# Modules sanctioned to narrow f64→f32: the mixed-precision schedule
# owners (ROUND5_NOTES — the f32-gram/f64c and df32 schedules, the
# two-phase f32 factorization ladder, and the MXU panel kernels).
# Anywhere else, an ``.astype(float32)`` is a silent precision loss the
# two-phase design never sanctioned.
NARROW_SANCTIONED = {
    "ops/chol_mxu.py",
    "ops/df32.py",  # the two-float layer: every df32 narrowing lives there
    "ops/normal_eq.py",
    "backends/dense.py",
    "backends/block_angular.py",
    "backends/batched.py",
    # Huge-sparse tier: the ELL operator stores int32 column indices and
    # may down-convert cached f64 value arrays to the configured solve
    # dtype; the PCG preconditioners build f32 probe factors for the
    # loose (early-μ) forcing-sequence solves.
    "ops/sparse.py",
    "ops/pcg.py",
}

# -- JSONL schema (rules_schema) ---------------------------------------------
# Event types the telemetry streams may carry (IterLogger.event payloads
# and RequestResult.record). ``cli report`` and the autotuner dispatch on
# these; an uncatalogued type is invisible to every consumer.
JSONL_EVENT_TYPES = {
    "batch",
    "dispatch_error",
    "fault",
    "ladder_swap",
    "reject",
    "request",
    "reshard",
    "resume",
    "service",
    "warmup",
    "warmup_error",
    # Network serving plane (net/): one record per HTTP request on a
    # front-end, per routed forward on the router, and per backend
    # rotation change (ejection on failed health / forward, re-admission
    # on recovery).
    "http_request",
    "route",
    "backend_ejected",
    "backend_readmitted",
    # Crash-safe serving fabric: one record per journal recovery pass
    # (serve/service._replay_journal), per drain phase transition
    # (begin/end/listener_close), and per applied shared-registry
    # mutation (net/registry.py).
    "journal_replay",
    "drain",
    "registry_write",
    # Multi-host runtime (distributed/): one record per coordinator-
    # level world re-initialization (launcher.WorldSupervisor — a dead
    # rank kills the world as a unit, recovery relaunches a smaller
    # one), per slice self-registration into the shared backend
    # registry, and per registry liveness beat where a stream consumer
    # wants them (cli serve-slice).
    "world_reinit",
    "slice_register",
    "heartbeat",
    # Closed-loop elasticity (serve/elastic.py, net/admission.py
    # BrownoutController, net/router.py circuit breaker): one record per
    # controller scale action (or vetoed intent), per brownout-ladder
    # stage transition, and per breaker state change on a backend.
    "scale_out",
    "scale_in",
    "scale_veto",
    "brownout_enter",
    "brownout_exit",
    "breaker_open",
    "breaker_close",
    # Tail tolerance (net/router.py, net/server.py, serve/service.py):
    # one record per hedge resolution (launched hedges only — the
    # suppressed ones surface through router_hedges_total and the
    # statusz ledger), per cancellation (router loser-cancel AND the
    # backend's queue-removal), per unfunded retry-budget spend, and
    # per expired-on-arrival deadline rejection at a backend.
    "hedge",
    "cancel",
    "retry_budget",
    "deadline_expired",
}

# Every field a stamped JSONL record may carry, across all streams: the
# stamp_record fields, iteration-row fields (ipm.state.IterRecord), the
# serve request/batch/service records, and the supervisor fault/resume
# events. The checker flags literal keys outside this set — adding a
# field is fine, but it must be catalogued here (and picked up by
# obs/report) in the same change.
JSONL_FIELDS = {
    # stamp_record
    "schema_version",
    "t_mono",
    "ts",
    # IterRecord rows
    "alpha_d",
    "alpha_p",
    "dinf",
    "dobj",
    "gap",
    "iter",
    "mu",
    "pinf",
    "pobj",
    "rel_gap",
    "sigma",
    "t_iter",
    # event discriminator
    "event",
    # serve request records (serve/records.py RequestResult.record)
    "bucket",
    "compile_ms",
    "dispatch",
    "faults",
    "id",
    "iterations",
    "m",
    "n",
    "name",
    "objective",
    "overlap_ms",
    "pack_ms",
    "padding_waste",
    "queue_ms",
    "retried_solo",
    "slot",
    "solve_ms",
    "status",
    "total_ms",
    # serve batch/fault/lifecycle events (serve/service.py)
    "action",
    "attempts",
    "buckets",
    "cache",
    "detail",
    "devices",
    "excluded",
    "fused_iters",
    "kind",
    "live",
    "mesh_devices",
    "metrics",
    "migrated",
    "misfits",
    "occupancy",
    "queue_depth",
    "schedule",
    "tol",
    # warm-start & amortization layer: request records carry the
    # "warm"/"rejected"/"cold" start label, batch events the number of
    # warm-started slots (serve/service.py, serve/records.py)
    "warm",
    # huge-sparse tier (tolerance-tiered serve ladder + inexact IPM):
    # request/batch records carry the solve engine ("ipm"|"pdhg"),
    # sparse-iterative iteration rows/bench rows the PCG iteration count
    # and the resolved preconditioner (jacobi/block/bordered)
    "engine",
    "cg_iters",
    "precond",
    # row-sharded matrix-free tier: cg_report/bench rows carry the row
    # shard count and the per-CG-iteration psum count (1 n-vector
    # all-reduce when sharded, 0 single-device); ``precond`` gains the
    # "ildl" value (incomplete-LDLᵀ escalation). block_angular phase
    # records/A-B harness rows stamp the per-phase program class
    # (oneshot vs K-grouped f64 — backends.block_angular.
    # phase_program_class)
    "shards",
    "psum_per_iter",
    "program_class",
    # stochastic scenario tier: scenario-request records carry the
    # scenario count, the padded scenario-count bucket
    # (models/scenario.scenario_k_bucket), and the decomposition's
    # stage split — batched per-scenario Schur wall vs first-stage
    # linking wall (serve/records.py, backends/scenario.py)
    "n_scenarios",
    "scenario_bucket",
    "schur_ms",
    "link_ms",
    # network serving plane (net/): http_request records (method/path/
    # code/ms), admission-verdict reject records (tenant/priority/
    # reason/retry_after_s), router route records (backend/padding/
    # retried) and rotation events (fails), and the summary event's
    # per-tenant admission table
    "admission",
    "code",
    "fails",
    "method",
    "ms",
    "path",
    "priority",
    "reason",
    "retried",
    "retry_after_s",
    "tenant",
    # supervisor fault/resume events (supervisor/supervisor.py)
    "backend",
    "iteration",
    "recovery_overhead_s",
    "t",
    # crash-safe serving fabric: journal_replay tallies (replayed/
    # re-enqueued/expired-honest-TIMEOUT/failed-spec, torn/skipped WAL
    # lines, result files re-bound), drain phases (begin/end/
    # listener_close + drained verdict + in-flight count), and
    # registry_write records (ejected flag, file generation, writer id)
    "replayed",
    "reenqueued",
    "expired",
    "failed",
    "torn",
    "skipped",
    "results",
    "phase",
    "inflight",
    "drained",
    "ejected",
    "generation",
    "writer",
    # multi-host runtime (distributed/, cli serve-slice, supervisor
    # probe-fault attribution): which process observed/emitted the
    # record, the world it belonged to, and the logical slice — stamped
    # on world_reinit / slice_register / heartbeat events and on
    # supervisor fault records (probes only see addressable devices, so
    # the rank scopes the evidence).
    "rank",
    "world_size",
    "slice_id",
    # graftcheck v2 catalogue-drift audit: jsonl-fields now also checks
    # literal payloads routed through stamp_record(...), which brought
    # two stamped streams the lexical rule never saw into coverage —
    # the job-journal WAL (serve/journal.py: the "j" lifecycle
    # discriminator and its admitted-record fields) and the per-rank
    # heartbeat files (distributed/world.py: writer pid, merged into
    # the world's JSONL view post-mortem).
    "j",
    "jid",
    "fp",
    "spec",
    "nonce",
    "next_seq",
    "stage",
    "deadline_ts",
    "pid",
    # closed-loop elasticity: scale_out/scale_in/scale_veto events carry
    # the pool size after the action and the controller's target; the
    # breaker_open event attributes its trip (observed error rate over
    # the outcome window, hold before the half-open probe).
    "pool",
    "target",
    "error_rate",
    "backoff_s",
    # tail tolerance: hedge events carry the primary backend, the delay
    # that fired, and the resolution outcome; route events flag hedge
    # legs; cancel events carry the cancellation state verdict; the
    # backend's deadline_expired rejection records the (zero) budget
    # that arrived.
    "primary",
    "delay_ms",
    "outcome",
    "hedge",
    "state",
    "remaining_ms",
    # Distributed tracing (obs/context.py): request/hedge/route records
    # stamp the W3C-shaped trace identity (trace_id + the emitting hop's
    # span_id + its parent), journal WAL records carry the wire-form
    # header under ``trace`` so replays resume the ORIGINAL trace, batch
    # events list every member request's trace under ``trace_ids``, and
    # JSON histogram snapshots carry the slowest observation's trace as
    # an ``exemplar`` — the keys the fleet aggregator (obs/agg.py)
    # stitches cross-process Perfetto flows and exemplar tables from.
    "trace_id",
    "span_id",
    "parent_span_id",
    "trace",
    "trace_ids",
    "exemplar",
}

# ``X.write(json.dumps(...))`` record emission points that must stamp:
# every JSONL stream a consumer merges needs schema_version/ts/t_mono.
# (Chrome-trace and metric-snapshot files use ``json.dump(obj, fh)`` and
# are whole-file JSON, not JSONL records — the pattern doesn't match
# them, by design. HTTP response bodies are ``json.dumps(...).encode()``
# bytes and exempt by the same token: they are replies, not stream
# records.)

# -- SPMD rules (rules_spmd) -------------------------------------------------
# The multi-host contract (distributed/world.py): every rank of a world
# executes a bit-identical program sequence. Three statically visible
# ways to break it, each with its own rule family below.

# Environment keys whose values differ per rank (distributed/world.py
# env contract) — reading one is a rank-taint source exactly like
# ``jax.process_index()`` or ``world.rank``.
RANK_ENV_KEYS = {"DLPS_RANK"}

# Calls that are (or dispatch) world collectives: every rank must reach
# them in the same order with the same static arguments. A rank-derived
# branch guarding a path into one of these is the
# every-follower-hangs-in-XLA bug class PR 13 debugged by hand.
COLLECTIVE_CALLS = {
    "barrier",
    "allgather",
    "agree",
    "sync_global_devices",
    "process_allgather",
    "psum",
    "pmean",
    "put_global",
    "host_values",
    "host_value",
    # bucket-program dispatch: the collective lives inside the compiled
    # program, so dispatching it IS reaching a collective
    "solve_bucket",
    "solve_pdhg_bucket",
    "execute_dispatch",
}

# Deliberate rank-divergence seams — the rank-0-publish /
# follower-execute architecture (distributed/slice.py): both sides of
# the branch execute the SAME dispatch sequence, one via the
# SolveService, one via the control-plane journal, so the divergence is
# the design, not a bug. Entries are (pkg_path, qualname).
SPMD_SANCTIONED = {
    # cli serve-slice: rank 0 runs the HTTP front-end + SliceRunner,
    # followers run follower_loop — the two sides reach the collectives
    # through the one shared execute_dispatch path, in journal order.
    ("cli.py", "cmd_serve_slice"),
}

# Order-insensitive consumers: a directory scan wrapped in one of these
# never feeds iteration order anywhere, so it is exempt from
# spmd-unordered-dispatch.
ORDER_SAFE_CONSUMERS = {
    "sorted",
    "set",
    "frozenset",
    "len",
    "sum",
    "min",
    "max",
    "any",
    "all",
}

# Order-sensitive sinks: a call reaching one of these from inside a
# loop over an unordered collection publishes the iteration order to
# the rest of the world (dispatch journals, JSONL streams, registry
# merges, jit cache warm order).
ORDER_SINKS = {
    "publish",
    "publish_stop",
    "event",
    "dispatch",
    "execute_dispatch",
    "solve_bucket",
    "solve_pdhg_bucket",
    "warm_buckets",
    "put_global",
    "record",
    "register",
}

# Committed-placement helpers (spmd-uncommitted-input): host data enters
# a multi-process program ONLY through these — they materialize each
# process's addressable shards against the global mesh. A bare
# ``jax.device_put(x)`` / ``jnp.asarray(x)`` commits to the default
# device instead and breaks the program's sharding contract on a pod.
COMMITTED_PLACERS = {
    "put_global",
    "place_bucket",
    "place_warm",
    "batch_sharding",
    "col_sharding",
    "vec_sharding",
    "make_array_from_callback",
    # ops/sparse.py: builds the row-sharded hybrid-ELL operator with
    # every leaf placed against the global mesh (shard axis leading).
    "shard_rows",
}

# Calls that take a ``mesh=`` keyword and compile/execute against it —
# the sinks the uncommitted-input rule guards.
MESH_PROGRAM_SINKS = {
    "solve_bucket",
    "solve_pdhg_bucket",
    "execute_dispatch",
    "solve_batched",
}

# -- deadlock rules (rules_locks) --------------------------------------------
# Blocking operations that must not run while a lock is held: a
# collective blocks until EVERY rank arrives (seconds to forever), an
# HTTP round-trip or fsync blocks on I/O, subprocess waits on another
# process, Future.result on another thread. Any of them under a lock
# extends the lock's hold time from nanoseconds to unbounded — the
# pipeline-stall / deadlock-feeding class. Terminal call names.
BLOCKING_CALLS = COLLECTIVE_CALLS | {
    "urlopen",
    "fsync",
    "sleep",
    "Popen",
    "check_call",
    "check_output",
    "communicate",
}

# Deliberately-blocking-under-lock seams, (pkg_path, qualname) — a bare
# class name sanctions every method of that class:
BLOCKING_SANCTIONED = {
    # The slice dispatch lock IS the cross-rank ordering contract:
    # publish order must equal execute order, so the collective runs
    # under the lock by design (distributed/slice.py module doc).
    ("distributed/slice.py", "SliceRunner"),
    # The WAL's append ordering + fsync durability is the journal's
    # whole contract: appends are one small write each and the lock IS
    # the WAL order, and compaction must be atomic against appends
    # (serve/journal.py module doc). Only these two methods are
    # sanctioned — the bounded result-store write in finish() was moved
    # OUT of the lock in the same PR that added this rule.
    ("serve/journal.py", "JobJournal._append_locked"),
    ("serve/journal.py", "JobJournal.compact"),
    # flush()/close() are the drain path's explicit force-to-disk
    # calls; the lock is the WAL order they are flushing.
    ("serve/journal.py", "JobJournal.flush"),
    ("serve/journal.py", "JobJournal.close"),
    # IterLogger/Tracer emit one small flushed write per record under
    # their own lock — that lock exists only to serialize the stream,
    # never wraps device work, and fsync mode is opt-in diagnostics.
    ("utils/logging.py", "IterLogger"),
}
