"""Dynamic lock-order recorder — the runtime companion to the static
``guarded-by`` rule.

The static rule proves each shared attribute is accessed under its
lock; it cannot prove two locks are always taken in a consistent
*order* (the classic deadlock: thread A holds ``_lock`` wanting
``_span_lock`` while thread B holds ``_span_lock`` wanting ``_lock``).
This module records the order at runtime: tests wrap the live lock
objects of a real 3-thread ``SolveService`` drain, every acquisition
adds held→acquired edges to a graph, and :meth:`LockOrderRecorder.check`
asserts the graph is acyclic — any cycle is a lock-order inversion that
*can* deadlock, whether or not this run happened to.

The wrapped lock is duck-type compatible with ``threading.Lock`` (and
with being handed to ``threading.Condition``: acquire/release are all
the default Condition shims need), so instrumentation is attribute
replacement, no production-code changes::

    rec = LockOrderRecorder()
    svc._span_lock = rec.wrap(svc._span_lock, "span")
    ...run traffic...
    rec.check()   # raises LockOrderViolation on any cycle
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set, Tuple


class LockOrderViolation(AssertionError):
    """A cycle in the observed lock-acquisition graph."""


class _RecordingLock:
    """Proxy delegating to a real lock, recording acquisition order."""

    def __init__(self, inner, name: str, recorder: "LockOrderRecorder"):
        self._inner = inner
        self._name = name
        self._recorder = recorder

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = (
            self._inner.acquire(blocking, timeout)
            if timeout != -1
            else self._inner.acquire(blocking)
        )
        if ok:
            self._recorder._acquired(self._name)
        return ok

    def release(self) -> None:
        self._recorder._released(self._name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()


class LockOrderRecorder:
    """Accumulates held→acquired edges across every wrapped lock."""

    def __init__(self):
        self._graph_lock = threading.Lock()
        self._held = threading.local()  # per-thread stack of held names
        self._edges: Dict[str, Set[str]] = {}
        self._names: List[str] = []

    def wrap(self, lock, name: str) -> _RecordingLock:
        with self._graph_lock:
            if name not in self._names:
                self._names.append(name)
        return _RecordingLock(lock, name, self)

    # -- called by the proxies -------------------------------------------

    def _stack(self) -> List[str]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def _acquired(self, name: str) -> None:
        stack = self._stack()
        if stack:
            with self._graph_lock:
                for held in stack:
                    if held != name:
                        self._edges.setdefault(held, set()).add(name)
        stack.append(name)

    def _released(self, name: str) -> None:
        stack = self._stack()
        # Condition.wait releases out of FIFO order is impossible for a
        # plain lock, but be tolerant: remove the most recent entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                break

    # -- inspection ------------------------------------------------------

    def edges(self) -> Set[Tuple[str, str]]:
        with self._graph_lock:
            return {(a, b) for a, succ in self._edges.items() for b in succ}

    def find_cycle(self) -> List[str]:
        """One observed ordering cycle as a lock-name path, or []."""
        with self._graph_lock:
            graph = {a: set(b) for a, b in self._edges.items()}
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        path: List[str] = []

        def dfs(n: str):
            color[n] = GRAY
            path.append(n)
            for m in sorted(graph.get(n, ())):
                c = color.get(m, WHITE)
                if c == GRAY:
                    return path[path.index(m) :] + [m]
                if c == WHITE:
                    found = dfs(m)
                    if found:
                        return found
            path.pop()
            color[n] = BLACK
            return []

        for n in sorted(graph):
            if color.get(n, WHITE) == WHITE:
                cycle = dfs(n)
                if cycle:
                    return cycle
        return []

    def check(self) -> None:
        """Raise :class:`LockOrderViolation` if any ordering cycle was
        observed (a potential deadlock, independent of this run's luck)."""
        cycle = self.find_cycle()
        if cycle:
            raise LockOrderViolation(
                "lock-order inversion observed: " + " -> ".join(cycle)
            )
