"""Interprocedural layer of graftcheck: a whole-package call graph with
dataflow summaries (graftcheck v2).

The PR 6 checker was lexical-per-file by design — fast, zero deps — but
the multi-host runtime (PR 13) added bug classes a single function
cannot witness: a rank-derived branch whose *callee three frames down*
runs a collective, a lock held across a method call that acquires
another lock in the opposite order elsewhere, a WAL append that fsyncs
under a lock taken by the HTTP poll path. This module gives the rules a
package-wide view while staying stdlib-only (``ast`` + dicts, no jax):

- :class:`CallGraph` — every function/method definition in the analyzed
  file set, with call sites resolved through ``self.`` dispatch, same-
  module calls, package imports (``from X import Y`` / ``import X``),
  attribute types inferred from ``self._a = ClassName(...)`` in
  ``__init__``, and local-variable construction (``r = Runner(...)``).
  Unresolvable calls keep their *terminal name* (the rightmost
  attribute) so name-keyed pattern sets still apply to them.
- Transitive **reach summaries** (:meth:`CallGraph.reach`) — the
  fixed-point closure of "calling this function eventually executes an
  op in <name set>" used for collectives and blocking operations. The
  summary carries a witness chain (``a -> b -> barrier``) so findings
  can explain the path.
- **Rank-taint dataflow** (:class:`TaintEngine`) — rank sources
  (``process_index()``, ``.rank`` / ``.is_primary``, ``DLPS_RANK`` env
  reads) propagated through local assignments, through *returns*
  (``is_primary()``-style predicates taint their callers), and through
  *call arguments* (a function that branches a collective on its
  parameter is divergent exactly when a caller passes it a rank fact).
- **Lock model** (:class:`LockModel`) — per-class lock attributes
  (``threading.Lock/RLock`` assigned in ``__init__``, ``Condition``
  aliases resolved), module-level locks, transitively-acquired lock
  sets per function, and the global lock-order edge graph the static
  deadlock rule runs a cycle search over.

Resolution is deliberately *best-effort and conservative*: a call the
graph cannot resolve contributes only its terminal name. That keeps the
engine sound for the gate (no crash on dynamic dispatch) at the cost of
missing exotic flows — the dynamic lockorder recorder and the runtime
tests stay the backstop for those.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# A function key: (pkg_path, qualname) where qualname is "func" or
# "Class.method". One entry per def, nested defs keyed "outer.<locals>.f"
# are skipped (they execute under their outer frame's findings anyway).
FuncKey = Tuple[str, str]


class FunctionUnit:
    """One analyzed function/method definition."""

    __slots__ = ("key", "node", "ctx", "class_name", "call_sites")

    def __init__(self, key: FuncKey, node, ctx, class_name: Optional[str]):
        self.key = key
        self.node = node
        self.ctx = ctx
        self.class_name = class_name
        # filled by CallGraph._resolve: [(call_node, resolved_key|None,
        # terminal_name)]
        self.call_sites: List[Tuple[ast.Call, Optional[FuncKey], str]] = []

    @property
    def pkg_path(self) -> str:
        return self.key[0]

    @property
    def qualname(self) -> str:
        return self.key[1]


def terminal_name(func: ast.AST) -> str:
    """The rightmost name of a call target — ``a.b.c()`` -> ``c``,
    ``f()`` -> ``f``. Name-keyed pattern sets match on this."""
    while isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _self_attr(node: ast.AST) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _pkg_path_of_module(dotted: str, files: Dict[str, object]) -> Optional[str]:
    """Map a dotted import (``distributedlpsolver_tpu.serve.journal`` or a
    relative remainder like ``serve.journal``) to a pkg_path present in
    the analyzed file set."""
    parts = dotted.split(".")
    if parts and parts[0] == "distributedlpsolver_tpu":
        parts = parts[1:]
    if not parts:
        return None
    cand = "/".join(parts) + ".py"
    if cand in files:
        return cand
    cand_init = "/".join(parts) + "/__init__.py"
    if cand_init in files:
        return cand_init
    return None


class CallGraph:
    """Whole-file-set function index + resolved call sites + summaries."""

    def __init__(self, contexts: Sequence):
        # contexts: FileContext list (analysis.core). Keyed by pkg_path.
        self.files: Dict[str, object] = {c.pkg_path: c for c in contexts}
        self.functions: Dict[FuncKey, FunctionUnit] = {}
        # (pkg_path, ClassName) -> ClassDef
        self.classes: Dict[Tuple[str, str], ast.ClassDef] = {}
        # pkg_path -> {local name: ("mod", pkg_path2) | ("sym", pkg_path2, name)}
        self.imports: Dict[str, Dict[str, tuple]] = {}
        # (pkg_path, ClassName) -> {attr: (pkg_path2, ClassName2)}
        self.attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        self._reach_cache: Dict[tuple, Dict[FuncKey, Tuple[str, ...]]] = {}
        for ctx in contexts:
            self._index_file(ctx)
        for ctx in contexts:
            self._infer_attr_types(ctx)
        for unit in self.functions.values():
            self._resolve_calls(unit)

    # -- indexing ----------------------------------------------------------

    def _index_file(self, ctx) -> None:
        imports: Dict[str, tuple] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = _pkg_path_of_module(alias.name, self.files)
                    if target:
                        imports[alias.asname or alias.name.split(".")[-1]] = (
                            "mod",
                            target,
                        )
            elif isinstance(node, ast.ImportFrom) and node.module:
                target = _pkg_path_of_module(node.module, self.files)
                for alias in node.names:
                    # ``from X import Y`` where Y is itself a module file
                    # (``from ...obs import trace as obs_trace``): the
                    # submodule interpretation wins over "symbol of X's
                    # __init__".
                    sub = _pkg_path_of_module(
                        f"{node.module}.{alias.name}", self.files
                    )
                    if sub:
                        imports[alias.asname or alias.name] = ("mod", sub)
                    elif target:
                        imports[alias.asname or alias.name] = (
                            "sym",
                            target,
                            alias.name,
                        )
        self.imports[ctx.pkg_path] = imports

        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (ctx.pkg_path, node.name)
                self.functions[key] = FunctionUnit(key, node, ctx, None)
                self._index_nested(ctx, node, node.name, None)
            elif isinstance(node, ast.ClassDef):
                self.classes[(ctx.pkg_path, node.name)] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        key = (ctx.pkg_path, f"{node.name}.{sub.name}")
                        self.functions[key] = FunctionUnit(
                            key, sub, ctx, node.name
                        )
                        self._index_nested(
                            ctx, sub, f"{node.name}.{sub.name}", node.name
                        )

    def _index_nested(self, ctx, fn, qual: str, class_name) -> None:
        # Nested defs are analyzed as part of their enclosing unit for
        # dataflow, but indexed so `# holds:`-style lookups by line work.
        for sub in ast.walk(fn):
            if sub is fn:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (ctx.pkg_path, f"{qual}.<locals>.{sub.name}")
                self.functions.setdefault(
                    key, FunctionUnit(key, sub, ctx, class_name)
                )

    def _resolve_class_name(
        self, pkg_path: str, node: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """``ClassName`` / ``mod.ClassName`` expression -> class key."""
        if isinstance(node, ast.Name):
            if (pkg_path, node.id) in self.classes:
                return (pkg_path, node.id)
            imp = self.imports.get(pkg_path, {}).get(node.id)
            if imp and imp[0] == "sym" and (imp[1], imp[2]) in self.classes:
                return (imp[1], imp[2])
        elif isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            imp = self.imports.get(pkg_path, {}).get(node.value.id)
            if imp and imp[0] == "mod" and (imp[1], node.attr) in self.classes:
                return (imp[1], node.attr)
        return None

    def _infer_attr_types(self, ctx) -> None:
        """``self._a = ClassName(...)`` in ``__init__`` -> attr type."""
        for (pkg, cls_name), cls in list(self.classes.items()):
            if pkg != ctx.pkg_path:
                continue
            init = next(
                (
                    n
                    for n in cls.body
                    if isinstance(n, ast.FunctionDef) and n.name == "__init__"
                ),
                None,
            )
            if init is None:
                continue
            types: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(init):
                if not isinstance(node, ast.Assign):
                    continue
                if not (
                    isinstance(node.value, ast.Call)
                ):
                    continue
                target_cls = self._resolve_class_name(pkg, node.value.func)
                if target_cls is None:
                    continue
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        types[a] = target_cls
            self.attr_types[(pkg, cls_name)] = types

    # -- call resolution ---------------------------------------------------

    def _local_instance_types(self, unit: FunctionUnit) -> Dict[str, Tuple[str, str]]:
        out: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(unit.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                cls = self._resolve_class_name(unit.pkg_path, node.value.func)
                if cls is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = cls
        return out

    def _resolve_calls(self, unit: FunctionUnit) -> None:
        pkg = unit.pkg_path
        imports = self.imports.get(pkg, {})
        local_types = self._local_instance_types(unit)
        for node in ast.walk(unit.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            resolved: Optional[FuncKey] = None
            if isinstance(func, ast.Name):
                if (pkg, func.id) in self.functions:
                    resolved = (pkg, func.id)
                else:
                    imp = imports.get(func.id)
                    if imp and imp[0] == "sym" and (imp[1], imp[2]) in self.functions:
                        resolved = (imp[1], imp[2])
                    elif imp and imp[0] == "sym" and (imp[1], imp[2]) in self.classes:
                        resolved = (imp[1], f"{imp[2]}.__init__")
                        if resolved not in self.functions:
                            resolved = None
            elif isinstance(func, ast.Attribute):
                base = func.value
                if isinstance(base, ast.Name) and base.id == "self":
                    if unit.class_name:
                        cand = (pkg, f"{unit.class_name}.{func.attr}")
                        if cand in self.functions:
                            resolved = cand
                elif _self_attr(base):
                    # self._attr.method() through the inferred attr type
                    if unit.class_name:
                        types = self.attr_types.get((pkg, unit.class_name), {})
                        owner = types.get(_self_attr(base))
                        if owner:
                            cand = (owner[0], f"{owner[1]}.{func.attr}")
                            if cand in self.functions:
                                resolved = cand
                elif isinstance(base, ast.Name):
                    imp = imports.get(base.id)
                    if imp and imp[0] == "mod":
                        cand = (imp[1], func.attr)
                        if cand in self.functions:
                            resolved = cand
                    elif base.id in local_types:
                        owner = local_types[base.id]
                        cand = (owner[0], f"{owner[1]}.{func.attr}")
                        if cand in self.functions:
                            resolved = cand
            unit.call_sites.append((node, resolved, terminal_name(func)))

    # -- transitive reach --------------------------------------------------

    def reach(self, names: Iterable[str]) -> Dict[FuncKey, Tuple[str, ...]]:
        """For every function, a witness chain (qualname, ..., op) iff
        calling it eventually executes a call whose terminal name is in
        ``names`` — () when it cannot. Fixed-point over the resolved
        graph; memoized per name set."""
        names_t = tuple(sorted(set(names)))
        cached = self._reach_cache.get(names_t)
        if cached is not None:
            return cached
        name_set = set(names_t)
        chains: Dict[FuncKey, Tuple[str, ...]] = {}
        # Direct hits first.
        for key, unit in self.functions.items():
            for call, resolved, term in unit.call_sites:
                if term in name_set:
                    chains[key] = (term,)
                    break
        changed = True
        while changed:
            changed = False
            for key, unit in self.functions.items():
                if key in chains:
                    continue
                for call, resolved, term in unit.call_sites:
                    if resolved is not None and resolved in chains:
                        if resolved == key:
                            continue
                        chains[key] = (resolved[1],) + chains[resolved]
                        changed = True
                        break
        out = {k: chains.get(k, ()) for k in self.functions}
        self._reach_cache[names_t] = out
        return out

    def call_reach(
        self,
        unit: FunctionUnit,
        call: ast.Call,
        resolved: Optional[FuncKey],
        term: str,
        names: Set[str],
        reach_map: Dict[FuncKey, Tuple[str, ...]],
    ) -> Tuple[str, ...]:
        """Witness chain for one call site (() = does not reach)."""
        if term in names:
            return (term,)
        if resolved is not None and reach_map.get(resolved):
            return (resolved[1],) + reach_map[resolved]
        return ()


# ---------------------------------------------------------------------------
# Rank-taint dataflow


def _match_rank_source(node: ast.AST, env_keys: Set[str]) -> bool:
    """Syntactic rank sources: ``process_index()`` calls, ``.rank`` /
    ``.is_primary`` attributes, and DLPS_RANK env reads."""
    if isinstance(node, ast.Call) and terminal_name(node.func) == "process_index":
        return True
    if isinstance(node, ast.Attribute) and node.attr in ("rank", "is_primary"):
        return True
    if isinstance(node, ast.Call) and terminal_name(node.func) == "get":
        for arg in node.args[:1]:
            if isinstance(arg, ast.Constant) and arg.value in env_keys:
                return True
    if isinstance(node, ast.Subscript):
        sl = node.slice
        if isinstance(sl, ast.Constant) and sl.value in env_keys:
            return True
    return False


class TaintEngine:
    """Rank-taint propagation: local assignments, returns, call args.

    ``rank_returns`` is the fixed-point set of functions whose return
    value derives from a rank source (``is_primary()``-style). A
    function's *local* taint pass seeds from syntactic sources plus
    calls into ``rank_returns``; optionally from named parameters (the
    call-argument propagation used by the divergence rule)."""

    def __init__(self, graph: CallGraph, env_keys: Iterable[str]):
        self.graph = graph
        self.env_keys = set(env_keys)
        self.rank_returns: Set[FuncKey] = self._fixed_point_returns()

    def _fixed_point_returns(self) -> Set[FuncKey]:
        tainted: Set[FuncKey] = set()
        changed = True
        while changed:
            changed = False
            for key, unit in self.graph.functions.items():
                if key in tainted:
                    continue
                names = self.tainted_names(unit, extra_tainted_fns=tainted)
                for node in ast.walk(unit.node):
                    if isinstance(node, ast.Return) and node.value is not None:
                        if self.expr_tainted(
                            node.value, names, extra_tainted_fns=tainted
                        ):
                            tainted.add(key)
                            changed = True
                            break
        return tainted

    def expr_tainted(
        self,
        expr: ast.AST,
        tainted_names: Set[str],
        extra_tainted_fns: Optional[Set[FuncKey]] = None,
    ) -> bool:
        fns = (
            extra_tainted_fns
            if extra_tainted_fns is not None
            else self.rank_returns
        )
        for node in ast.walk(expr):
            if _match_rank_source(node, self.env_keys):
                return True
            if isinstance(node, ast.Name) and node.id in tainted_names:
                return True
            if isinstance(node, ast.Call):
                term = terminal_name(node.func)
                for key in fns:
                    if key[1] == term or key[1].endswith("." + term):
                        return True
        return False

    def comp_rank_filters(
        self,
        unit: FunctionUnit,
        tainted_names: Set[str],
        extra_tainted_fns: Optional[Set[FuncKey]] = None,
    ) -> List[Tuple[ast.AST, ast.AST]]:
        """Comprehensions whose generator filters test a rank-derived
        value — ``[f(x) for x in xs if rank == 0]`` runs its element a
        different number of times per rank, the same divergence an
        ``if`` statement would carry, but invisible to any walker that
        only looks at ``ast.If``/``ast.While`` tests. Returns
        ``(comprehension, tainted_filter)`` pairs."""
        out: List[Tuple[ast.AST, ast.AST]] = []
        for node in ast.walk(unit.node):
            if not isinstance(
                node,
                (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp),
            ):
                continue
            for gen in node.generators:
                hit = None
                for cond in gen.ifs:
                    if self.expr_tainted(
                        cond, tainted_names, extra_tainted_fns
                    ):
                        hit = cond
                        break
                if hit is not None:
                    out.append((node, hit))
                    break
        return out

    def tainted_names(
        self,
        unit: FunctionUnit,
        seed_params: Iterable[str] = (),
        extra_tainted_fns: Optional[Set[FuncKey]] = None,
    ) -> Set[str]:
        """One forward pass over the unit's statements (in source order)
        collecting local names bound to rank-derived values."""
        names: Set[str] = set(seed_params)
        for node in ast.walk(unit.node):
            value = None
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.AugAssign):
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.NamedExpr):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            if self.expr_tainted(value, names, extra_tainted_fns):
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            names.add(sub.id)
        return names


# ---------------------------------------------------------------------------
# Lock model


def _is_lock_ctor(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and terminal_name(node.func) in (
        "Lock",
        "RLock",
    )


class LockModel:
    """Lock inventory + acquisition summaries + the global order graph.

    Lock identity is ``ClassName.attr`` for instance locks (``self._x =
    threading.Lock()`` in ``__init__``; Conditions over a lock alias to
    it) and ``<pkg_path>:NAME`` for module-level locks. The identity is
    per *class*, not per instance — exactly the granularity a lock-order
    contract is written at.
    """

    def __init__(self, graph: CallGraph):
        self.graph = graph
        # (pkg_path, ClassName) -> {attr -> canonical lock name}
        self.class_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
        # pkg_path -> {name -> canonical}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        self._acquires: Dict[FuncKey, Set[str]] = {}
        self._edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._collect_locks()
        self._summarize()

    def _collect_locks(self) -> None:
        for (pkg, cls_name), cls in self.graph.classes.items():
            init = next(
                (
                    n
                    for n in cls.body
                    if isinstance(n, ast.FunctionDef) and n.name == "__init__"
                ),
                None,
            )
            locks: Dict[str, str] = {}
            if init is not None:
                aliases: Dict[str, str] = {}
                for node in ast.walk(init):
                    if not isinstance(node, ast.Assign):
                        continue
                    attrs = [
                        a for a in (_self_attr(t) for t in node.targets) if a
                    ]
                    if not attrs:
                        continue
                    if _is_lock_ctor(node.value):
                        for a in attrs:
                            locks[a] = f"{cls_name}.{a}"
                    elif (
                        isinstance(node.value, ast.Call)
                        and terminal_name(node.value.func) == "Condition"
                        and node.value.args
                    ):
                        base = _self_attr(node.value.args[0])
                        if base:
                            for a in attrs:
                                aliases[a] = base
                for a, base in aliases.items():
                    if base in locks:
                        locks[a] = locks[base]
            self.class_locks[(pkg, cls_name)] = locks
        for pkg_path, ctx in self.graph.files.items():
            mod: Dict[str, str] = {}
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            mod[t.id] = f"{pkg_path}:{t.id}"
            self.module_locks[pkg_path] = mod

    def lock_of_with_item(self, unit: FunctionUnit, expr: ast.AST) -> Optional[str]:
        """Canonical lock name for a ``with <expr>`` item, or None when
        the item is not a known lock (file handles, meshes, ...)."""
        attr = _self_attr(expr)
        if attr and unit.class_name:
            locks = self.class_locks.get((unit.pkg_path, unit.class_name), {})
            return locks.get(attr)
        if isinstance(expr, ast.Name):
            return self.module_locks.get(unit.pkg_path, {}).get(expr.id)
        # self._obj._lock style: resolve the attr type's lock
        if (
            isinstance(expr, ast.Attribute)
            and _self_attr(expr.value)
            and unit.class_name
        ):
            owner = self.graph.attr_types.get(
                (unit.pkg_path, unit.class_name), {}
            ).get(_self_attr(expr.value))
            if owner:
                return self.class_locks.get(owner, {}).get(expr.attr)
        return None

    def _direct_acquires(self, unit: FunctionUnit) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(unit.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lk = self.lock_of_with_item(unit, item.context_expr)
                    if lk:
                        out.add(lk)
        return out

    def _summarize(self) -> None:
        # Transitive acquired-locks per function (fixed point).
        acquires = {
            key: self._direct_acquires(unit)
            for key, unit in self.graph.functions.items()
        }
        changed = True
        while changed:
            changed = False
            for key, unit in self.graph.functions.items():
                for call, resolved, term in unit.call_sites:
                    if resolved is None or resolved == key:
                        continue
                    extra = acquires.get(resolved, set()) - acquires[key]
                    if extra:
                        acquires[key] |= extra
                        changed = True
        self._acquires = acquires

    def acquired_by(self, key: FuncKey) -> Set[str]:
        return self._acquires.get(key, set())

    def order_edges(self) -> Dict[Tuple[str, str], Tuple[str, int]]:
        """held-lock -> acquired-lock edges across the whole file set,
        each with one witness location (pkg_path, lineno). Includes
        edges through calls: holding A and calling a function that
        (transitively) takes B adds A -> B."""
        if self._edges:
            return self._edges
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

        def record(a: str, b: str, pkg: str, line: int) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (pkg, line)

        for key, unit in self.graph.functions.items():
            # map each node to the set of locks held at it (lexical)
            for node in ast.walk(unit.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner = [
                        self.lock_of_with_item(unit, it.context_expr)
                        for it in node.items
                    ]
                    inner = [lk for lk in inner if lk]
                    if not inner:
                        continue
                    held = self._held_at(unit, node)
                    for a in held:
                        for b in inner:
                            record(a, b, unit.pkg_path, node.lineno)
                elif isinstance(node, ast.Call):
                    held = self._held_at(unit, node)
                    if not held:
                        continue
                    resolved = None
                    for c, r, t in unit.call_sites:
                        if c is node:
                            resolved = r
                            break
                    if resolved is None:
                        continue
                    for b in self.acquired_by(resolved):
                        for a in held:
                            record(a, b, unit.pkg_path, node.lineno)
        self._edges = edges
        return edges

    def _held_at(self, unit: FunctionUnit, node: ast.AST) -> Set[str]:
        """Locks lexically held at ``node`` inside ``unit`` (enclosing
        with-items, excluding the node itself), plus ``# holds:``."""
        held: Set[str] = set()
        ctx = unit.ctx
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                # only count the with if `node` is inside its BODY (not
                # one of its own context expressions)
                in_body = any(
                    self._node_within(node, stmt) for stmt in anc.body
                )
                if not in_body:
                    continue
                for item in anc.items:
                    lk = self.lock_of_with_item(unit, item.context_expr)
                    if lk:
                        held.add(lk)
            elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held |= self._holds_annotation(unit, anc)
                break
        else:
            held |= self._holds_annotation(unit, unit.node)
        return held

    def _node_within(self, node: ast.AST, root: ast.AST) -> bool:
        if node is root:
            return True
        lo = getattr(root, "lineno", None)
        hi = getattr(root, "end_lineno", None)
        nl = getattr(node, "lineno", None)
        if lo is None or hi is None or nl is None:
            return False
        return lo <= nl <= hi

    def _holds_annotation(self, unit: FunctionUnit, fn) -> Set[str]:
        import re

        held: Set[str] = set()
        ctx = unit.ctx
        body_line = fn.body[0].lineno if fn.body else fn.lineno
        for line in range(fn.lineno, body_line):
            m = re.search(
                r"#\s*holds:\s*([A-Za-z_][A-Za-z0-9_]*)", ctx.line(line)
            )
            if m and unit.class_name:
                locks = self.class_locks.get(
                    (unit.pkg_path, unit.class_name), {}
                )
                lk = locks.get(m.group(1))
                if lk:
                    held.add(lk)
        return held

    def find_cycle(self) -> List[Tuple[str, str, str, int]]:
        """One lock-order cycle as [(lock_a, lock_b, pkg_path, line),
        ...] edges, or [] when the graph is acyclic."""
        edges = self.order_edges()
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        path: List[str] = []

        def dfs(n: str) -> List[str]:
            color[n] = GRAY
            path.append(n)
            for m in sorted(graph.get(n, ())):
                c = color.get(m, WHITE)
                if c == GRAY:
                    return path[path.index(m):] + [m]
                if c == WHITE:
                    found = dfs(m)
                    if found:
                        return found
            path.pop()
            color[n] = BLACK
            return []

        for n in sorted(graph):
            if color.get(n, WHITE) == WHITE:
                cyc = dfs(n)
                if cyc:
                    out = []
                    for a, b in zip(cyc, cyc[1:]):
                        pkg, line = edges[(a, b)]
                        out.append((a, b, pkg, line))
                    return out
        return []
