"""graftcheck: the repo's static-analysis suite, wired into tier-1 as a
CI gate (``cli check distributedlpsolver_tpu/`` must exit 0).

Four rule families enforce the invariants the runtime tests can only
spot-check (README "Static analysis" has the catalogue and suppression
syntax):

- jit/recompile hygiene — ``jit-nonhoisted``, ``jit-scalar-default``,
  ``jit-donate``, ``host-sync`` (rules_jit)
- dtype discipline — ``dtype-explicit``, ``dtype-narrow`` (rules_dtype)
- lock discipline — ``guarded-by`` (rules_locks), paired with the
  dynamic :mod:`~distributedlpsolver_tpu.analysis.lockorder` recorder
- JSONL schema conformance — ``jsonl-fields``, ``jsonl-stamp``
  (rules_schema)

Stdlib-only on purpose: the gate runs on CPU CI in well under a second,
with no jax import.
"""

from distributedlpsolver_tpu.analysis.core import (
    FileContext,
    Finding,
    all_rules,
    check_file,
    check_paths,
    iter_py_files,
    render_json,
    render_text,
    rule,
)
from distributedlpsolver_tpu.analysis.lockorder import (
    LockOrderRecorder,
    LockOrderViolation,
)

__all__ = [
    "FileContext",
    "Finding",
    "LockOrderRecorder",
    "LockOrderViolation",
    "all_rules",
    "check_file",
    "check_paths",
    "iter_py_files",
    "render_json",
    "render_text",
    "rule",
]
