"""graftcheck: the repo's static-analysis suite, wired into tier-1 as a
CI gate (``cli check distributedlpsolver_tpu/`` must exit 0).

Six rule families enforce the invariants the runtime tests can only
spot-check (README "Static analysis" has the catalogue and suppression
syntax):

- jit/recompile hygiene — ``jit-nonhoisted``, ``jit-scalar-default``,
  ``jit-donate``, ``host-sync`` (rules_jit)
- dtype discipline — ``dtype-explicit``, ``dtype-narrow`` (rules_dtype)
- lock discipline — ``guarded-by`` (rules_locks), paired with the
  dynamic :mod:`~distributedlpsolver_tpu.analysis.lockorder` recorder
- static deadlock analysis — ``lock-order`` (cross-method acquisition
  cycles) and ``blocking-under-lock`` (rules_locks, graftcheck v2)
- SPMD discipline — ``spmd-divergent-collective``,
  ``spmd-unordered-dispatch``, ``spmd-uncommitted-input`` (rules_spmd,
  graftcheck v2): the multi-host every-rank-runs-the-same-programs
  contract of distributed/world.py, gated statically
- JSONL schema conformance — ``jsonl-fields``, ``jsonl-stamp``
  (rules_schema)

The v2 families are *interprocedural*: they run over a package-wide
call graph with taint/reach summaries (analysis/callgraph.py) exposed
to rules as a :class:`~distributedlpsolver_tpu.analysis.core.
ProjectContext`. Still stdlib-only on purpose: the gate runs on CPU CI
in a few seconds, with no jax import.

Incremental gating: ``cli check --baseline <json>`` fails only on
findings not present in a committed baseline (``--write-baseline``
produces one), so downstream consumers get a cheap diff-gate; this
repo's own tier-1 gate runs against the empty committed baseline
(BASELINE_GRAFTCHECK.json) — zero tolerated findings.
"""

from distributedlpsolver_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectContext,
    all_rules,
    baseline_key,
    check_file,
    check_paths,
    diff_baseline,
    iter_py_files,
    project_rule,
    render_json,
    render_text,
    rule,
    write_baseline,
)
from distributedlpsolver_tpu.analysis.lockorder import (
    LockOrderRecorder,
    LockOrderViolation,
)

__all__ = [
    "FileContext",
    "Finding",
    "LockOrderRecorder",
    "LockOrderViolation",
    "ProjectContext",
    "all_rules",
    "baseline_key",
    "check_file",
    "check_paths",
    "diff_baseline",
    "iter_py_files",
    "project_rule",
    "render_json",
    "render_text",
    "rule",
    "write_baseline",
]
