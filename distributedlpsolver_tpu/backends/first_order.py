"""First-order (restarted PDHG / "PDLP"-style) backend for huge sparse LPs.

The reference's large-sparse configs (neos3, stormG2 — BASELINE.json:10)
strain a normal-equations IPM on TPU: unstructured sparsity densifies, and
the Cholesky is the wrong tool at Mittelmann scale. The TPU-native answer
for this problem class is a matrix-free first-order method — each
iteration is two SpMV/GEMV passes plus vector arithmetic, which maps to
HBM bandwidth instead of MXU Cholesky flops and shards trivially (prior
art: MPAX, PAPERS.md:7 — patterns only, clean-room implementation).

Algorithm: primal-dual hybrid gradient on the interior form
``min cᵀx s.t. Ax = b, 0 ≤ x ≤ u`` —

    x⁺ = clip(x − τ·(c − Aᵀy), 0, u)
    y⁺ = y + σ·(b − A·(2x⁺ − x))

with the PDLP toolbox on top:

* step sizes ``τ = η/ω, σ = η·ω`` where ``η = 0.9/‖A‖₂`` (power-iteration
  estimate) and ω is the primal weight;
* Polyak–Ruppert averaging inside each restart cycle;
* adaptive restarts: restart at the average when its normalized KKT error
  beats the last restart point's by ``restart_beta``, or on a fixed long
  cycle as a safety net;
* primal-weight updates at restarts from the primal/dual movement ratio.

The whole loop — including restart bookkeeping — is one
``lax.while_loop`` device program; only final scalars return to the host.
Sparse inputs use BCOO SpMV (gather/scatter on TPU, bandwidth-bound);
dense inputs use plain GEMV.

This backend has no analogue in the reference (its sparse path is a
direct solver); it is an addition for the problem class the reference's
own benchmarks name. Accuracy: first-order methods earn their keep at
1e-4..1e-6; 1e-8 is reachable on well-conditioned problems but can take
many restarts — the default ``tol`` here is still read from the config, so
callers choose.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.backends.base import SolverBackend, register_backend
from distributedlpsolver_tpu.ipm import core
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import IPMState, StepStats
from distributedlpsolver_tpu.models.problem import InteriorForm


class PDHGState(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    x_sum: jnp.ndarray  # running averages within the restart cycle
    y_sum: jnp.ndarray
    n_avg: jnp.ndarray
    x_restart: jnp.ndarray  # cycle start (for primal-weight updates)
    y_restart: jnp.ndarray
    err_restart: jnp.ndarray  # KKT error at the last restart point
    omega: jnp.ndarray  # primal weight
    it_cycle: jnp.ndarray


def _estimate_norm(matvec, rmatvec, n, dtype, iters: int = 30, seed=0):
    """Power iteration for ‖A‖₂ (σ_max) — sets the PDHG step size.

    ``seed`` may be a Python int or a traced int32 scalar — the batched
    bucket program threads each lane's slot index through here, so lane
    k of every dispatch runs the identical power iteration (deterministic
    per slot; the old fixed seed=0 made every lane share one start
    vector, which tied lane results to the batch layout)."""
    v = jax.random.normal(
        jax.random.PRNGKey(jnp.asarray(seed, jnp.uint32)), (n,), dtype=dtype
    )
    v = v / jnp.linalg.norm(v)

    def body(_, v):
        w = rmatvec(matvec(v))
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

    v = jax.lax.fori_loop(0, iters, body, v)
    return jnp.sqrt(jnp.linalg.norm(rmatvec(matvec(v))))


def _kkt_error(matvec, rmatvec, data, x, y):
    """(pinf, dinf, gap_rel, pobj, dobj) of an (x, y) pair.

    Reduced costs split by bound structure: r = c − Aᵀy; on finite-u
    columns a negative r is priced by the upper bound (contributes r·u to
    the dual objective); on unbounded columns a negative r is dual
    infeasibility.
    """
    c, b, u_f, hub = data.c, data.b, data.u_f, data.hub
    r_p = b - matvec(x)
    r = c - rmatvec(y)
    r_neg = jnp.minimum(r, 0.0)
    dinf_vec = jnp.where(hub > 0, 0.0, r_neg)  # unbounded cols: r must be ≥ 0
    pinf = jnp.linalg.norm(r_p) / data.norm_b
    dinf = jnp.linalg.norm(dinf_vec) / data.norm_c
    pobj = c @ x
    dobj = b @ y + (hub * u_f) @ r_neg
    gap = jnp.abs(pobj - dobj) / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))
    return pinf, dinf, gap, pobj, dobj


def _err_of(matvec, rmatvec, data, x, y):
    pinf, dinf, gap, _, _ = _kkt_error(matvec, rmatvec, data, x, y)
    return jnp.maximum(pinf, jnp.maximum(dinf, gap))


@functools.partial(
    jax.jit, static_argnames=("check_every", "restart_len", "restart_beta")
)
def _pdhg_solve(
    A, AT, data, x0, y0, eta, omega0, err_restart0, max_iter, tol,
    check_every=40, restart_len=2000, restart_beta=0.5,
):
    """Fused restarted-PDHG loop. ``A``/``AT`` are dense arrays or BCOO
    pytrees — both trace as ordinary jit operands, so one compiled program
    serves every problem of the same shape/sparsity pattern.

    ``omega0``/``err_restart0`` make the loop resumable: a caller driving
    bounded bursts feeds back the returned ``(omega, err_restart)`` so the
    adaptive primal weight and restart baseline survive burst boundaries
    (a fresh start passes ``omega0=1, err_restart0=inf``)."""
    matvec = lambda v: A @ v
    rmatvec = lambda v: AT @ v
    dtype = x0.dtype
    u = jnp.where(data.hub > 0, data.u_f, jnp.inf)

    def one_pdhg(x, y, omega):
        tau = eta / omega
        sigma = eta * omega
        x_new = jnp.clip(x - tau * (data.c - rmatvec(y)), 0.0, u)
        y_new = y + sigma * (data.b - matvec(2.0 * x_new - x))
        return x_new, y_new

    err0 = _err_of(matvec, rmatvec, data, x0, y0)
    st0 = PDHGState(
        x=x0, y=y0,
        x_sum=jnp.zeros_like(x0), y_sum=jnp.zeros_like(y0),
        n_avg=jnp.asarray(0.0, dtype),
        x_restart=x0, y_restart=y0,
        err_restart=jnp.minimum(jnp.asarray(err_restart0, dtype), err0),
        omega=jnp.asarray(omega0, dtype),
        it_cycle=jnp.asarray(0, jnp.int32),
    )

    def cond(carry):
        st, it, err = carry
        return (it < max_iter) & (err > tol)

    def body(carry):
        st, it, _ = carry

        # `check_every` inner PDHG steps, fully fused.
        def inner(_, xy):
            x, y = xy
            return one_pdhg(x, y, st.omega)

        x, y = jax.lax.fori_loop(0, check_every, inner, (st.x, st.y))
        x_sum = st.x_sum + x * check_every  # cheap running average proxy
        y_sum = st.y_sum + y * check_every
        n_avg = st.n_avg + check_every
        x_avg = x_sum / n_avg
        y_avg = y_sum / n_avg

        err_cur = _err_of(matvec, rmatvec, data, x, y)
        err_avg = _err_of(matvec, rmatvec, data, x_avg, y_avg)
        it_cycle = st.it_cycle + check_every

        # Restart candidate: whichever of (current, average) is better.
        use_avg = err_avg < err_cur
        x_cand = jnp.where(use_avg, x_avg, x)
        y_cand = jnp.where(use_avg, y_avg, y)
        err_cand = jnp.minimum(err_avg, err_cur)
        do_restart = (err_cand <= restart_beta * st.err_restart) | (
            it_cycle >= restart_len
        )

        # Primal-weight update at restarts (PDLP rule: ratio of movements).
        dx = jnp.linalg.norm(x_cand - st.x_restart)
        dy = jnp.linalg.norm(y_cand - st.y_restart)
        omega_new = jnp.where(
            (dx > 1e-30) & (dy > 1e-30),
            jnp.exp(0.5 * jnp.log(st.omega) + 0.5 * jnp.log(dy / dx)),
            st.omega,
        )

        st_restart = PDHGState(
            x=x_cand, y=y_cand,
            x_sum=jnp.zeros_like(x), y_sum=jnp.zeros_like(y),
            n_avg=jnp.asarray(0.0, dtype),
            x_restart=x_cand, y_restart=y_cand,
            err_restart=err_cand,
            omega=omega_new,
            it_cycle=jnp.asarray(0, jnp.int32),
        )
        st_cont = st._replace(
            x=x, y=y, x_sum=x_sum, y_sum=y_sum, n_avg=n_avg, it_cycle=it_cycle
        )
        st_new = jax.tree_util.tree_map(
            lambda a, b: jnp.where(do_restart, a, b), st_restart, st_cont
        )
        best_err = jnp.minimum(err_cand, err_cur)
        return st_new, it + check_every, best_err

    st, it, err = jax.lax.while_loop(
        cond, body, (st0, jnp.asarray(0, jnp.int32), st0.err_restart)
    )
    # Report the better of (last, average-of-cycle).
    has_avg = st.n_avg > 0
    x_avg = jnp.where(has_avg, st.x_sum / jnp.maximum(st.n_avg, 1.0), st.x)
    y_avg = jnp.where(has_avg, st.y_sum / jnp.maximum(st.n_avg, 1.0), st.y)
    err_avg = _err_of(matvec, rmatvec, data, x_avg, y_avg)
    err_cur = _err_of(matvec, rmatvec, data, st.x, st.y)
    use_avg = err_avg < err_cur
    x_fin = jnp.where(use_avg, x_avg, st.x)
    y_fin = jnp.where(use_avg, y_avg, st.y)
    return x_fin, y_fin, it, jnp.minimum(err_avg, err_cur), st.omega, st.err_restart


@register_backend("pdlp", "first-order", "pdhg")
class FirstOrderBackend(SolverBackend):
    """Restarted-PDHG execution backend (matrix-free; huge-sparse class).

    Plugs into the same driver/CLI surface as every other backend; the
    IPM-shaped ``iterate`` contract is satisfied by running a bounded
    number of PDHG sweeps per call and reporting KKT stats.
    """

    def __init__(
        self,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: Optional[int] = None,
    ):
        self._sparse = False
        self._mesh = mesh
        # Norm-estimate seed: explicit wins; else derived from the
        # problem name at setup — deterministic per request, so two
        # solves of the same instance share step sizes bit-for-bit
        # while distinct requests stop sharing one fixed seed=0.
        self._seed = seed

    def setup(self, inf: InteriorForm, config: SolverConfig) -> None:
        self._cfg = config
        # Working precision. PDHG needs no f64 operands at the accuracy it
        # targets, and on TPU an emulated-f64 GEMV materializes ~8
        # full-size f32 component copies of A (observed: a 15 GB temp for
        # ONE 10k×50k matvec — OOM where the f32 operand is 1.9 GB). Under
        # the default "auto" schedule on TPU, run everything in f32 as
        # long as the tolerance is above f32's ~1e-6 noise floor; an
        # explicit factor_dtype or a tighter tol keeps full precision.
        dtype = jnp.dtype(config.dtype)
        if config.factor_dtype == "float32" or (
            config.factor_dtype == "auto"
            and jax.default_backend() == "tpu"
            and config.tol >= 1e-6
        ):
            dtype = jnp.dtype(jnp.float32)
        self._dtype = dtype
        self._n_pad = 0
        self._col_sharding = None
        A = inf.A
        mesh_explicit = self._mesh is not None
        if self._mesh is None and config.mesh_shape is not None and not sp.issparse(A):
            # A config-supplied mesh applies to dense operands only —
            # sparse inputs keep the single-device BCOO path (this
            # backend's whole purpose is huge sparse; a shared
            # config.mesh_shape must not hijack it).
            from distributedlpsolver_tpu.parallel import make_mesh

            self._mesh = make_mesh(shape=config.mesh_shape)
        if self._mesh is not None and sp.issparse(A):
            # Only an EXPLICITLY passed mesh reaches here: densify small
            # sparse inputs, refuse ones where densification is the hazard.
            assert mesh_explicit
            if A.shape[0] * A.shape[1] > (1 << 26):
                raise ValueError(
                    "mesh-sharded pdlp supports dense operands; sparse input "
                    f"of shape {A.shape} is too large to densify "
                    "(drop the mesh to use the single-device BCOO path)"
                )
            A = np.asarray(A.todense())
        self._sparse = sp.issparse(A)
        if self._sparse:
            from jax.experimental import sparse as jsparse

            Ac = sp.coo_matrix(A)
            self._A = jsparse.BCOO(
                (jnp.asarray(Ac.data, dtype=dtype),
                 jnp.asarray(np.stack([Ac.row, Ac.col], axis=1))),
                shape=Ac.shape,
            )
            AT = Ac.T.tocoo()
            self._AT = jsparse.BCOO(
                (jnp.asarray(AT.data, dtype=dtype),
                 jnp.asarray(np.stack([AT.row, AT.col], axis=1))),
                shape=AT.shape,
            )
        else:
            A_host = np.asarray(A, dtype=dtype)
            if self._mesh is not None:
                # PDHG distributes for free under GSPMD: shard A's columns
                # (and x) over the mesh; Aᵀ shards its rows to match. The
                # GEMV in matvec then reduces partial products with one
                # all-reduce over ICI — the same dataflow as the Schur
                # psum, at O(m) volume per iteration instead of O(m²).
                from jax.sharding import NamedSharding, PartitionSpec as P

                axis = self._mesh.axis_names[0]
                n_pad = (-A_host.shape[1]) % self._mesh.shape[axis]
                if n_pad:
                    # Zero columns with +1 cost never leave x=0 under PDHG
                    # projections from a zero start; sliced off in to_host.
                    A_host = np.hstack(
                        [A_host, np.zeros((A_host.shape[0], n_pad), dtype)]
                    )
                self._n_pad = n_pad
                sh = lambda *spec: NamedSharding(self._mesh, P(*spec))
                self._A = jax.device_put(A_host, sh(None, axis))
                self._AT = jax.device_put(A_host.T.copy(), sh(axis, None))
                self._col_sharding = sh(axis)
            else:
                self._A = jnp.asarray(A_host)
                self._AT = self._A.T
        c_host = np.asarray(inf.c, dtype=np.float64)
        u_host = np.asarray(inf.u, dtype=np.float64)
        self._n_orig = inf.n
        if self._n_pad:
            # Padded zero columns: cost 1, no upper bound — PDHG's
            # projection pins them at 0 from a zero start (r = 1 > 0).
            c_host = np.concatenate([c_host, np.ones(self._n_pad)])
            u_host = np.concatenate([u_host, np.full(self._n_pad, np.inf)])
        put_col = (
            (lambda v: jax.device_put(v, self._col_sharding))
            if self._col_sharding is not None
            else jnp.asarray
        )
        self._data = core.make_problem_data(
            jnp,
            put_col(c_host.astype(dtype)),
            jnp.asarray(np.asarray(inf.b), dtype=dtype),
            put_col(u_host.astype(dtype)),
            dtype,
        )
        A_, AT_ = self._A, self._AT
        self._matvec = lambda v: A_ @ v
        self._rmatvec = lambda v: AT_ @ v
        if self._seed is not None:
            seed = int(self._seed)
        else:
            import zlib

            seed = zlib.crc32(inf.name.encode()) & 0x7FFFFFFF
        nrm = _estimate_norm(
            self._matvec, self._rmatvec, inf.n + self._n_pad, dtype,
            seed=seed,
        )
        self._eta = float(0.9 / max(float(nrm), 1e-12))
        self._it_done = 0
        self._reset_adaptive()

    def _reset_adaptive(self) -> None:
        # Adaptive PDHG state persisted ACROSS bursts (iterate calls and
        # solve_full segments): the learned primal weight and the restart
        # baseline. Discarding these at every burst boundary makes the
        # non-fused driver path converge measurably slower than one fused
        # loop on the same budget (round-1 advisor finding).
        self._omega = 1.0
        self._err_restart = float("inf")

    def _pdhg_iter_seconds(self) -> float:
        """Conservative per-inner-iteration time estimate for watchdog
        segmentation. PDHG is two matvec passes (+ periodic KKT checks ~
        two more per check_every block) — bandwidth-bound, not MXU-bound,
        so the effective flop rate is far below core.SEG_RATE_F32.
        Measured anchor: 167 it/s at 10000x50000 dense f32 (BASELINE.md)
        -> ~3.3e11 effective flops/s on 4mn flops/iter; seed at 2e11.
        Sparse BCOO SpMV gathers/scatters instead of riding the MXU —
        seed an order of magnitude lower per nonzero."""
        if self._sparse:
            return 4.0 * float(self._A.nse) / 2e10
        m, n = self._A.shape
        return 4.0 * float(m) * float(n) / 2e11

    def starting_point(self) -> IPMState:
        n = self._data.c.shape[0]
        m = self._data.b.shape[0]
        x = jnp.zeros(n, dtype=self._dtype)
        y = jnp.zeros(m, dtype=self._dtype)
        return self._wrap(x, y)

    def _wrap(self, x, y) -> IPMState:
        # Carry (x, y) through the IPMState container; s/w/z are derived
        # quantities for PDHG and reported as reduced costs at the end.
        r = self._data.c - self._rmatvec(y)
        s = jnp.maximum(r, 0.0)
        z = jnp.maximum(-r, 0.0) * (self._data.hub > 0)
        w = jnp.where(self._data.hub > 0, self._data.u_f - x, 1.0)
        return IPMState(x=x, y=y, s=s, w=w, z=z)

    def iterate(self, state: IPMState) -> Tuple[IPMState, StepStats]:
        # One driver "iteration" = a bounded PDHG burst; stats are true KKT
        # measures so the host convergence test stays meaningful. The
        # adaptive primal weight and restart baseline persist across
        # bursts (self._omega / self._err_restart).
        x, y, it, err, omega, err_restart = _pdhg_solve(
            self._A, self._AT, self._data,
            state.x, state.y,
            jnp.asarray(self._eta, self._dtype),
            jnp.asarray(self._omega, self._dtype),
            jnp.asarray(self._err_restart, self._dtype),
            jnp.asarray(400, jnp.int32),
            jnp.asarray(self._cfg.tol, self._dtype),
        )
        self._omega = float(omega)
        self._err_restart = float(err_restart)
        pinf, dinf, gap, pobj, dobj = _kkt_error(
            self._matvec, self._rmatvec, self._data, x, y
        )
        zero = jnp.asarray(0.0, self._dtype)
        stats = StepStats(
            mu=gap, gap=jnp.abs(pobj - dobj), rel_gap=gap, pinf=pinf,
            dinf=dinf, pobj=pobj, dobj=dobj, alpha_p=zero, alpha_d=zero,
            sigma=zero, bad=~jnp.isfinite(gap),
        )
        return self._wrap(x, y), stats

    def bump_regularization(self) -> bool:
        return False  # nothing to regularize

    def solve_full(self, state: IPMState):
        cfg = self._cfg
        import time as _time

        # PDHG counts iterations in the thousands; interpret the config's
        # (IPM-scaled) max_iter as bursts of 400 inner steps.
        max_inner = int(cfg.max_iter) * 400
        eta = jnp.asarray(self._eta, self._dtype)
        tol = jnp.asarray(cfg.tol, self._dtype)
        x, y = state.x, state.y
        omega = jnp.asarray(self._omega, self._dtype)
        err_restart = jnp.asarray(self._err_restart, self._dtype)
        if core.use_segments(cfg.segment_iters, jax.default_backend()):
            # Host-segmented bursts: one unbounded lax.while_loop at, say,
            # 57 s for the flagship config sits right at the tunneled-TPU
            # execution watchdog (~60 s) — a slightly harder problem gets
            # the run killed instead of returning ITERATION_LIMIT. Carry
            # (x, y, omega, err_restart) across bounded bursts instead;
            # burst length is seeded from the bandwidth estimate and then
            # adapted to the measured rate, mirroring core.drive_segments.
            if cfg.segment_iters is not None:
                burst = max(400, int(cfg.segment_iters) * 400)
            else:
                est = self._pdhg_iter_seconds()
                burst = max(400, min(40000, int(15.0 / max(est, 1e-9))))
            it_total, err, first = 0, float("inf"), True
            while it_total < max_inner:
                this = min(burst, max_inner - it_total)
                t0 = _time.perf_counter()
                x, y, it_b, err_b, omega, err_restart = _pdhg_solve(
                    self._A, self._AT, self._data, x, y, eta,
                    omega, err_restart,
                    jnp.asarray(this, jnp.int32), tol,
                )
                err_b.block_until_ready()
                dt = _time.perf_counter() - t0
                it_b, err = int(it_b), float(err_b)
                it_total += it_b
                if err <= float(cfg.tol) or it_b == 0:
                    break
                if not first:  # first burst's wall time includes compile
                    burst = max(
                        400, min(200000, int(burst * 15.0 / max(dt, 1e-3)))
                    )
                first = False
            it = jnp.asarray(it_total, jnp.int32)
        else:
            x, y, it, err, omega, err_restart = _pdhg_solve(
                self._A, self._AT, self._data, x, y, eta,
                omega, err_restart,
                jnp.asarray(max_inner, jnp.int32), tol,
            )
        self._omega = float(omega)
        self._err_restart = float(err_restart)
        pinf, dinf, gap, pobj, dobj = _kkt_error(
            self._matvec, self._rmatvec, self._data, x, y
        )
        ok = (gap <= cfg.tol) & (pinf <= cfg.tol) & (dinf <= cfg.tol)
        status = jnp.where(ok, core.STATUS_OPTIMAL, core.STATUS_MAXITER)
        zero = jnp.asarray(0.0, self._dtype)
        row = jnp.stack(
            [gap, jnp.abs(pobj - dobj), gap, pinf, dinf, pobj, dobj,
             zero, zero, zero]
        )
        # One summary stats record, but the REAL inner-iteration count —
        # the driver reports iterations from it (and caps the history read
        # at the buffer's length), so iters/sec reflects actual PDHG work.
        # Floor at 1: an immediately-optimal start (it == 0) must still
        # surface its stats row, or the result reports infinite residuals.
        buf = row[None, :]
        return self._wrap(x, y), jnp.maximum(it, 1), status, buf

    def to_host(self, state: IPMState) -> IPMState:
        n = self._n_orig
        return IPMState(
            x=np.asarray(state.x)[:n],
            y=np.asarray(state.y),
            s=np.asarray(state.s)[:n],
            w=np.asarray(state.w)[:n],
            z=np.asarray(state.z)[:n],
        )

    def from_host(self, state: IPMState) -> IPMState:
        # A restored iterate invalidates the burst-adaptive baselines.
        self._reset_adaptive()
        x, y, s, w, z = (np.asarray(v, dtype=self._dtype) for v in state)
        if self._n_pad:
            x = np.concatenate([x, np.zeros(self._n_pad, dtype=self._dtype)])
            s = np.concatenate([s, np.ones(self._n_pad, dtype=self._dtype)])
            w = np.concatenate([w, np.ones(self._n_pad, dtype=self._dtype)])
            z = np.concatenate([z, np.zeros(self._n_pad, dtype=self._dtype)])
        put = (
            (lambda v: jax.device_put(v, self._col_sharding))
            if self._col_sharding is not None
            else jnp.asarray
        )
        return IPMState(
            x=put(x), y=jnp.asarray(y), s=put(s), w=put(w), z=put(z)
        )

    def block_until_ready(self, obj) -> None:
        jax.block_until_ready(obj)


# -- bucketed batched PDHG: the serve ladder's first-order engine -----------
#
# One compiled program per (B, m, n, dtype) bucket shape — tol and
# max_iter are traced operands, so the tolerance tiers share the
# executable and a warm bucket NEVER recompiles (the same invariant as
# backends/batched._solve_bucket_jit). Each lane runs the restarted-PDHG
# loop of this module (averaging + adaptive restarts + primal-weight
# updates), vectorized over the batch with per-lane convergence masks;
# per-lane step sizes come from a slot-seeded power iteration
# (deterministic per slot — the norm-estimate seed satellite). Verdicts
# are crossover-honest: a lane is OPTIMAL only when its true KKT error
# (pinf, dinf, relative gap) passes the REQUEST tolerance.


class _PDHGLanes(NamedTuple):
    x: jnp.ndarray  # (B, n)
    y: jnp.ndarray  # (B, m)
    x_sum: jnp.ndarray
    y_sum: jnp.ndarray
    n_avg: jnp.ndarray  # (B,)
    x_restart: jnp.ndarray
    y_restart: jnp.ndarray
    err_restart: jnp.ndarray  # (B,)
    omega: jnp.ndarray  # (B,)
    it_cycle: jnp.ndarray  # (B,) int32


def _lanes_kkt(A, b, c, x, y):
    """Per-lane (pinf, dinf, gap, pobj, dobj) for bucket standard form
    (x ≥ 0, no upper bounds)."""
    r_p = b - jnp.einsum("bmn,bn->bm", A, x)
    r = c - jnp.einsum("bmn,bm->bn", A, y)
    pinf = jnp.linalg.norm(r_p, axis=1) / (
        1.0 + jnp.linalg.norm(b, axis=1)
    )
    dinf = jnp.linalg.norm(jnp.minimum(r, 0.0), axis=1) / (
        1.0 + jnp.linalg.norm(c, axis=1)
    )
    pobj = jnp.sum(c * x, axis=1)
    dobj = jnp.sum(b * y, axis=1)
    gap = jnp.abs(pobj - dobj) / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))
    return pinf, dinf, gap, pobj, dobj


def _lanes_err(A, b, c, x, y):
    pinf, dinf, gap, _, _ = _lanes_kkt(A, b, c, x, y)
    return jnp.maximum(pinf, jnp.maximum(dinf, gap))


@functools.partial(
    jax.jit, static_argnames=("check_every", "restart_len", "restart_beta")
)
def _pdhg_bucket_jit(
    A, b, c, active, tol, max_iter,
    check_every=40, restart_len=2000, restart_beta=0.5,
):
    """Fused batched restarted-PDHG over one padded bucket.

    Carry: per-lane PDHG state + iteration counts + a live mask. Every
    trip runs ``check_every`` fused primal-dual steps for ALL lanes
    (finished lanes' updates are masked out), then re-measures each
    lane's KKT error and applies the restart/averaging bookkeeping
    per lane. The loop exits when no live lane remains.
    """
    B, m, n = A.shape
    dtype = A.dtype

    # Per-lane ‖A_k‖₂ from a slot-seeded power iteration (slot index IS
    # the seed — deterministic per slot across dispatches).
    def one_norm(Ak, slot):
        return _estimate_norm(
            lambda v: Ak @ v, lambda v: Ak.T @ v, n, dtype, seed=slot
        )

    nrm = jax.vmap(one_norm)(A, jnp.arange(B, dtype=jnp.int32))
    eta = 0.9 / jnp.maximum(nrm, 1e-12)

    def one_pdhg(x, y, omega, Ak, bk, ck, eta_k):
        tau = eta_k / omega
        sigma = eta_k * omega
        x_new = jnp.maximum(x - tau * (ck - Ak.T @ y), 0.0)
        y_new = y + sigma * (bk - Ak @ (2.0 * x_new - x))
        return x_new, y_new

    zB = jnp.zeros((B,), dtype=dtype)
    err0 = _lanes_err(A, b, c, jnp.zeros_like(c), jnp.zeros_like(b))
    st0 = _PDHGLanes(
        x=jnp.zeros_like(c), y=jnp.zeros_like(b),
        x_sum=jnp.zeros_like(c), y_sum=jnp.zeros_like(b),
        n_avg=zB,
        x_restart=jnp.zeros_like(c), y_restart=jnp.zeros_like(b),
        err_restart=err0,
        omega=jnp.ones((B,), dtype=dtype),
        it_cycle=jnp.zeros((B,), jnp.int32),
    )
    live0 = active & (err0 > tol)

    def cond(carry):
        st, it, err, live = carry
        return jnp.any(live)

    def body(carry):
        st, it, err, live = carry

        def inner(_, xy):
            x, y = xy
            xn, yn = jax.vmap(one_pdhg)(x, y, st.omega, A, b, c, eta)
            x = jnp.where(live[:, None], xn, x)
            y = jnp.where(live[:, None], yn, y)
            return x, y

        x, y = jax.lax.fori_loop(0, check_every, inner, (st.x, st.y))
        ce = jnp.asarray(check_every, dtype)
        x_sum = st.x_sum + x * ce
        y_sum = st.y_sum + y * ce
        n_avg = st.n_avg + ce
        x_avg = x_sum / n_avg[:, None]
        y_avg = y_sum / n_avg[:, None]

        err_cur = _lanes_err(A, b, c, x, y)
        err_avg = _lanes_err(A, b, c, x_avg, y_avg)
        it_cycle = st.it_cycle + check_every

        use_avg = err_avg < err_cur
        x_cand = jnp.where(use_avg[:, None], x_avg, x)
        y_cand = jnp.where(use_avg[:, None], y_avg, y)
        err_cand = jnp.minimum(err_avg, err_cur)
        do_restart = (err_cand <= restart_beta * st.err_restart) | (
            it_cycle >= restart_len
        )

        dx = jnp.linalg.norm(x_cand - st.x_restart, axis=1)
        dy = jnp.linalg.norm(y_cand - st.y_restart, axis=1)
        omega_new = jnp.where(
            (dx > 1e-30) & (dy > 1e-30),
            jnp.exp(0.5 * jnp.log(st.omega) + 0.5 * jnp.log(dy / dx)),
            st.omega,
        )

        rs = do_restart & live
        rcol = rs[:, None]
        st_new = _PDHGLanes(
            x=jnp.where(rcol, x_cand, x),
            y=jnp.where(rcol, y_cand, y),
            x_sum=jnp.where(rcol, jnp.zeros_like(x), x_sum),
            y_sum=jnp.where(rcol, jnp.zeros_like(y), y_sum),
            n_avg=jnp.where(rs, zB, n_avg),
            x_restart=jnp.where(rcol, x_cand, st.x_restart),
            y_restart=jnp.where(rcol, y_cand, st.y_restart),
            err_restart=jnp.where(rs, err_cand, st.err_restart),
            omega=jnp.where(rs, omega_new, st.omega),
            it_cycle=jnp.where(rs, jnp.zeros_like(it_cycle), it_cycle),
        )
        # Frozen lanes keep their previous state verbatim.
        st_new = jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                live.reshape((B,) + (1,) * (new.ndim - 1)), new, old
            ),
            st_new, st,
        )
        err_new = jnp.where(live, jnp.minimum(err_cand, err_cur), err)
        it = jnp.where(live, it + check_every, it)
        live = live & (err_new > tol) & (it < max_iter) & jnp.isfinite(err_new)
        return st_new, it, err_new, live

    st, it, err, live = jax.lax.while_loop(
        cond, body, (st0, jnp.zeros((B,), jnp.int32), err0, live0)
    )
    # Report the better of (last, cycle average) per lane.
    has_avg = st.n_avg > 0
    x_avg = jnp.where(
        has_avg[:, None], st.x_sum / jnp.maximum(st.n_avg, 1.0)[:, None], st.x
    )
    y_avg = jnp.where(
        has_avg[:, None], st.y_sum / jnp.maximum(st.n_avg, 1.0)[:, None], st.y
    )
    err_avg = _lanes_err(A, b, c, x_avg, y_avg)
    err_cur = _lanes_err(A, b, c, st.x, st.y)
    use_avg = err_avg < err_cur
    x_fin = jnp.where(use_avg[:, None], x_avg, st.x)
    y_fin = jnp.where(use_avg[:, None], y_avg, st.y)
    pinf, dinf, gap, pobj, dobj = _lanes_kkt(A, b, c, x_fin, y_fin)
    return x_fin, y_fin, it, pinf, dinf, gap, pobj


def pdhg_bucket_cache_size() -> int:
    """Compiled bucket-PDHG program count — the serve layer's
    zero-warm-recompile accounting (summed into
    backends.batched.bucket_cache_size)."""
    return int(_pdhg_bucket_jit._cache_size())


def solve_pdhg_bucket(
    batch,
    active,
    config: Optional[SolverConfig] = None,
    mesh=None,
    batch_axis: str = "batch",
    max_iter: Optional[int] = None,
    **config_overrides,
):
    """Solve one pre-padded serving bucket with batched restarted PDHG —
    the first-order engine of the tolerance-tiered serve ladder
    (requests at tol ≥ ServiceConfig.pdhg_tol route here; see
    serve/service.py).

    Mirrors :func:`backends.batched.solve_bucket`'s contract: ``batch``
    is (B, m, n)/(B, m)/(B, n) arrays already padded to the bucket
    shape, ``active`` the live-slot mask; returns a ``BatchedResult``.
    ``config.max_iter`` is interpreted as bursts of 400 inner PDHG
    steps (the same scaling as the solo backend's ``solve_full``).
    Verdicts are crossover-honest: OPTIMAL only where the final true
    KKT error meets the request tolerance — anything else is
    ITERATION_LIMIT and the service's solo ladder (IPM polish at the
    same tolerance) owns it. ``y``/``s``/``w``/``z`` are deliberately
    left None: a tol-loose PDHG iterate must not seed the warm cache
    the IPM engine draws from.
    """
    import time as _time

    from distributedlpsolver_tpu.backends.batched import (
        BatchedResult,
        place_bucket,
    )
    from distributedlpsolver_tpu.ipm.state import Status

    cfg = config or SolverConfig()
    if config_overrides:
        cfg = cfg.replace(**config_overrides)
    dtype = jnp.dtype(cfg.dtype)

    t0 = _time.perf_counter()
    if isinstance(batch.A, jax.Array) and batch.A.dtype == dtype:
        A, b, c = batch.A, batch.b, batch.c
        if not isinstance(active, jax.Array):
            # Commit a host mask against the same mesh sharding as the
            # pre-placed batch — a bare jnp.asarray pins it to the
            # default local device, which a multi-process program
            # cannot consume (see batched.solve_bucket).
            from distributedlpsolver_tpu.parallel import mesh as mesh_lib

            act_h = np.asarray(active, dtype=bool)
            if mesh is not None:
                active = jax.device_put(
                    act_h, mesh_lib.batch_sharding(mesh, 1, batch_axis)
                )
            else:
                active = jnp.asarray(act_h)
    else:
        placed, active = place_bucket(
            batch, active, cfg, mesh=mesh, batch_axis=batch_axis
        )
        A, b, c = placed.A, placed.b, placed.c
    setup_time = _time.perf_counter() - t0

    inner_cap = int(max_iter if max_iter is not None else cfg.max_iter) * 400
    t1 = _time.perf_counter()
    x, y, it, pinf, dinf, gap, pobj = _pdhg_bucket_jit(
        A, b, c, active,
        jnp.asarray(cfg.tol, dtype),
        jnp.asarray(inner_cap, jnp.int32),
    )
    jax.block_until_ready(x)
    solve_time = _time.perf_counter() - t1

    # Multi-process-safe demux (see backends/batched.solve_bucket): a
    # batch axis spanning processes rides one replicating gather
    # program; single-process meshes take the plain np.asarray path.
    from distributedlpsolver_tpu.parallel.mesh import host_values

    pinf, dinf, gap, act_h, pobj_h, x_h, it_host = host_values(
        (pinf, dinf, gap, active, pobj, x, it)
    )
    pinf = np.asarray(pinf, dtype=np.float64)
    dinf = np.asarray(dinf, dtype=np.float64)
    gap = np.asarray(gap, dtype=np.float64)
    ok = (gap <= cfg.tol) & (pinf <= cfg.tol) & (dinf <= cfg.tol)
    # Inactive (padding) slots report the same placeholder OPTIMAL as
    # solve_bucket — demux by slot and ignore them.
    ok = ok | ~act_h.astype(bool)
    status = np.array(
        [Status.OPTIMAL if o else Status.ITERATION_LIMIT for o in ok],
        dtype=object,
    )
    return BatchedResult(
        status=status,
        objective=np.asarray(pobj_h, dtype=np.float64),
        x=np.asarray(x_h, dtype=np.float64),
        iterations=it_host,
        rel_gap=gap,
        pinf=pinf,
        dinf=dinf,
        solve_time=solve_time,
        setup_time=setup_time,
        phase_report=[
            {"phase": 0, "engine": "pdhg", "tol": float(cfg.tol),
             "iters": int(it_host.max(initial=0))}
        ],
        fused_iters=40,  # check_every inner steps per while trip
    )
