"""CPU backend on the native C++ kernels (ctypes → kernels.cpp).

Same eager host loop as :class:`CpuBackend`, but the three hot operations
— normal-equations assembly, Cholesky, triangular solves — run in the
OpenMP C++ kernels (SURVEY.md §2.1: where the reference's CPU path is
native/LAPACK, the rebuild's baseline is genuinely native too). This is
the backend `bench.py` uses as the stand-in for the reference's 8-rank
MPI/CPU baseline.
"""

from __future__ import annotations

import ctypes

import numpy as np
import scipy.sparse as sp

from distributedlpsolver_tpu.backends.base import register_backend
from distributedlpsolver_tpu.backends.cpu import CpuBackend
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.models.problem import InteriorForm
import distributedlpsolver_tpu.native.build as native_build


def _dp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


@register_backend("cpu-native", "native")
class CpuNativeBackend(CpuBackend):
    """CpuBackend with the factorize/solve seam re-pointed at C++."""

    def setup(self, inf: InteriorForm, config: SolverConfig) -> None:
        self._lib = native_build.load()  # raises NativeBuildError w/o g++
        super().setup(inf, config)
        # The native assembly wants a dense row-major A.
        A = inf.A.toarray() if sp.issparse(inf.A) else np.asarray(inf.A)
        self._A_dense = np.ascontiguousarray(A, dtype=np.float64)
        m, n = self._A_dense.shape
        self._scratch = np.empty((m, n), dtype=np.float64)
        self._M = np.empty((m, m), dtype=np.float64)

    def _factorize(self, d: np.ndarray, reg: float):
        m, n = self._A_dense.shape
        d = np.ascontiguousarray(d, dtype=np.float64)
        self._lib.dlps_normal_eq(
            _dp(self._A_dense), _dp(d), m, n, float(reg),
            _dp(self._scratch), _dp(self._M),
        )
        info = self._lib.dlps_cholesky(_dp(self._M), m)
        if info != 0:
            raise np.linalg.LinAlgError(f"native cholesky: pivot {info} <= 0")
        return self._M  # lower factor, in place

    def _solve(self, factors, rhs: np.ndarray) -> np.ndarray:
        m = factors.shape[0]
        rhs = np.ascontiguousarray(rhs, dtype=np.float64)
        out = np.empty(m, dtype=np.float64)
        self._lib.dlps_cho_solve(_dp(factors), _dp(rhs), m, _dp(out))
        return out
