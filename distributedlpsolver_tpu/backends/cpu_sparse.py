"""Sparse-direct CPU backend for large unstructured LPs.

The reference's large sparse workloads (Mittelmann neos3 / stormG2_1000,
BASELINE.json:10) have normal matrices far too large to densify — the
dense CPU/TPU paths form the m×m matrix explicitly, which at m≈10⁵ is
hundreds of GB. This backend keeps the whole chain sparse: CSR
``A·diag(d)·Aᵀ`` assembly and a SuperLU factorization of the (SPD,
regularized) normal matrix via ``scipy.sparse.linalg.splu`` with COLAMD
ordering. SuperLU rather than a sparse Cholesky because SciPy ships no
CHOLMOD binding in this image; the factorization cost is ~2× a Cholesky
but the fill-reducing ordering — the part that matters at this scale —
is the same class of machinery the reference's sparse path would use
(SURVEY.md §7 "truly unstructured sparse may route to the CPU backend";
block-structured instances should use the block-angular backend
instead, which is the TPU-native path for stormG2-style problems).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sp
import scipy.sparse.linalg as spla

from distributedlpsolver_tpu.backends.base import register_backend
from distributedlpsolver_tpu.backends.cpu import CpuBackend
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.models.problem import InteriorForm


@register_backend("cpu-sparse", "sparse")
class CpuSparseBackend(CpuBackend):
    """Eager sparse-direct execution of the shared IPM core."""

    def setup(self, inf: InteriorForm, config: SolverConfig) -> None:
        if not sp.issparse(inf.A):
            inf = dataclasses.replace(
                inf, A=sp.csr_matrix(np.asarray(inf.A, dtype=np.float64))
            )
        super().setup(inf, config)

    def _factorize(self, d: np.ndarray, reg: float):
        A = self._A
        M = (A.multiply(d)) @ A.T
        M = sp.csc_matrix(M)
        M.setdiag(M.diagonal() * (1.0 + reg) + 1e-300)  # keep diagonal structurally present
        try:
            return spla.splu(M, permc_spec="COLAMD")
        except RuntimeError as e:  # singular factor → numerical failure
            raise np.linalg.LinAlgError(str(e)) from e

    def _solve(self, lu, rhs: np.ndarray) -> np.ndarray:
        y = lu.solve(rhs)
        if not np.all(np.isfinite(y)):
            raise np.linalg.LinAlgError("non-finite triangular solve")
        return y
