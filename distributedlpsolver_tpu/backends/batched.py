"""Batched solver: many independent small LPs as one device program.

BASELINE.json:11 names the workload — 1024 independent (m=128, n=512)
problems solved concurrently. The reference plausibly loops problems over
ranks (SURVEY.md §2 "Batched solver"); the TPU-native design makes the
batch a *first-class array axis*: the Mehrotra step is ``vmap``-ed over
the batch, the outer iteration is a ``lax.while_loop`` on device, and
per-problem convergence is handled by masking (never early exit — shapes
stay static, SURVEY.md §7 "ragged convergence ... masking, not early
exit"). The whole solve — every iteration of every problem — is ONE
compiled XLA program; nothing crosses the host boundary until the final
states come back.

Batch parallelism over a mesh (SURVEY.md §2.2: batch-axis sharding *is*
the data parallelism of this domain) falls out of placement: shard the
leading axis of (A, b, c) over the mesh and the same compiled program
runs B/K problems per device with no per-iteration collectives at all —
the only cross-device reduction is the cheap ``any(active)`` loop
predicate.

Converged problems are frozen by masking rather than dropped: their
iterates stay exactly at the accepted solution while stragglers continue.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributedlpsolver_tpu.backends.dense import _make_ops
from distributedlpsolver_tpu.ipm import core
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import IPMState, Status
from distributedlpsolver_tpu.models.generators import BatchedLP
from distributedlpsolver_tpu.parallel import mesh as mesh_lib

_RUNNING, _OPTIMAL, _MAXITER, _NUMERR = 0, 1, 2, 3


@dataclasses.dataclass
class BatchedResult:
    """Per-problem outcomes of a batched solve."""

    status: np.ndarray  # (B,) Status values
    objective: np.ndarray  # (B,)
    x: np.ndarray  # (B, n)
    iterations: np.ndarray  # (B,)
    rel_gap: np.ndarray  # (B,)
    pinf: np.ndarray  # (B,)
    dinf: np.ndarray  # (B,)
    solve_time: float = 0.0
    setup_time: float = 0.0

    @property
    def n_optimal(self) -> int:
        return int(np.sum(self.status == Status.OPTIMAL))


def _single_step(A, data, state, reg, params, factor_dtype):
    ops = _make_ops(A, reg, factor_dtype, 0)
    return core.mehrotra_step(ops, data, params, state)


def _single_start(A, data, reg, params, factor_dtype):
    ops = _make_ops(A, reg, factor_dtype, 0)
    return core.starting_point(ops, data, params)


@functools.partial(jax.jit, static_argnames=("params", "factor_dtype"))
def _solve_batched_jit(A, data, reg0, params, max_iter, max_refactor, reg_grow, factor_dtype):
    # max_iter / max_refactor / reg_grow are traced scalars so one compile
    # serves every iteration-limit config (warm-up shares the timed compile).
    fdt = jnp.dtype(factor_dtype)
    B = A.shape[0]
    states0 = jax.vmap(lambda a, d: _single_start(a, d, reg0, params, fdt))(A, data)

    def cond(carry):
        _, active, it, *_ = carry
        return jnp.any(active) & (it < max_iter)

    def body(carry):
        states, active, it, regs, badcount, status, iters = carry
        new_states, stats = jax.vmap(
            lambda a, d, st, rg: _single_step(a, d, st, rg, params, fdt)
        )(A, data, states, regs)
        bad = stats.bad
        conv = (
            (stats.rel_gap <= params.tol)
            & (stats.pinf <= params.tol)
            & (stats.dinf <= params.tol)
        )
        accept = active & ~bad
        # Freeze non-accepted problems component-wise.
        states = jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                accept.reshape((B,) + (1,) * (new.ndim - 1)), new, old
            ),
            new_states,
            states,
        )
        iters = iters + accept.astype(jnp.int32)
        # Per-problem regularization escalation on failed factorizations.
        regs = jnp.where(active & bad, jnp.maximum(regs, 1e-12) * reg_grow, regs)
        badcount = jnp.where(active & bad, badcount + 1, badcount)
        give_up = badcount > max_refactor
        newly_opt = accept & conv
        status = jnp.where(newly_opt, _OPTIMAL, status)
        status = jnp.where(active & give_up, _NUMERR, status)
        active = active & ~newly_opt & ~give_up
        return states, active, it + 1, regs, badcount, status, iters

    dtype = A.dtype
    carry0 = (
        states0,
        jnp.ones(B, dtype=bool),
        jnp.asarray(0, jnp.int32),
        jnp.full(B, reg0, dtype=dtype),
        jnp.zeros(B, jnp.int32),
        jnp.full(B, _RUNNING, jnp.int32),
        jnp.zeros(B, jnp.int32),
    )
    states, active, _, _, _, status, iters = jax.lax.while_loop(cond, body, carry0)
    status = jnp.where(status == _RUNNING, _MAXITER, status)

    # Final per-problem diagnostics.
    def final_norms(a, d, st):
        ops = _make_ops(a, jnp.asarray(0.0, dtype), fdt, 0)
        pinf, dinf, _, rel_gap, pobj, _, _ = core.residual_norms(ops, d, st)
        return pinf, dinf, rel_gap, pobj

    pinf, dinf, rel_gap, pobj = jax.vmap(final_norms)(A, data, states)
    return states, status, iters, pinf, dinf, rel_gap, pobj


def solve_batched(
    batch: BatchedLP,
    config: Optional[SolverConfig] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    batch_axis: str = "batch",
    **config_overrides,
) -> BatchedResult:
    """Solve every problem in ``batch`` concurrently on device.

    ``mesh`` shards the batch axis (data parallelism); the batch size must
    then be divisible by the mesh size.
    """
    import time

    cfg = config or SolverConfig()
    if config_overrides:
        cfg = cfg.replace(**config_overrides)
    dtype = jnp.dtype(cfg.dtype)
    fname = jnp.dtype(cfg.factor_dtype_resolved()).name

    t0 = time.perf_counter()
    A = np.asarray(batch.A, dtype=dtype)
    b = np.asarray(batch.b, dtype=dtype)
    c = np.asarray(batch.c, dtype=dtype)
    Bsz, m, n = A.shape
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        if Bsz % mesh.shape[batch_axis] != 0:
            raise ValueError(
                f"batch {Bsz} not divisible by mesh axis {mesh.shape[batch_axis]}"
            )
        sh = lambda *spec: NamedSharding(mesh, P(*spec))
        A = jax.device_put(A, sh(batch_axis, None, None))
        b = jax.device_put(b, sh(batch_axis, None))
        c = jax.device_put(c, sh(batch_axis, None))
    else:
        A, b, c = jnp.asarray(A), jnp.asarray(b), jnp.asarray(c)

    u = jnp.full((Bsz, n), jnp.inf, dtype=dtype)
    data = jax.vmap(lambda cc, bb, uu: core.make_problem_data(jnp, cc, bb, uu, dtype))(
        c, b, u
    )
    params = cfg.step_params()
    setup_time = time.perf_counter() - t0

    t1 = time.perf_counter()
    states, status, iters, pinf, dinf, rel_gap, pobj = _solve_batched_jit(
        A,
        data,
        jnp.asarray(cfg.reg_dual, dtype),
        params,
        cfg.max_iter,
        cfg.max_refactor,
        cfg.reg_grow,
        fname,
    )
    jax.block_until_ready(states)
    solve_time = time.perf_counter() - t1

    code_map = {
        _OPTIMAL: Status.OPTIMAL,
        _MAXITER: Status.ITERATION_LIMIT,
        _NUMERR: Status.NUMERICAL_ERROR,
    }
    status_np = np.asarray(status)
    return BatchedResult(
        status=np.array([code_map[int(sc)] for sc in status_np], dtype=object),
        objective=np.asarray(pobj, dtype=np.float64),
        x=np.asarray(states.x, dtype=np.float64),
        iterations=np.asarray(iters),
        rel_gap=np.asarray(rel_gap, dtype=np.float64),
        pinf=np.asarray(pinf, dtype=np.float64),
        dinf=np.asarray(dinf, dtype=np.float64),
        solve_time=solve_time,
        setup_time=setup_time,
    )
