"""Batched solver: many independent small LPs as one device program.

BASELINE.json:11 names the workload — 1024 independent (m=128, n=512)
problems solved concurrently. The reference plausibly loops problems over
ranks (SURVEY.md §2 "Batched solver"); the TPU-native design makes the
batch a *first-class array axis*: the Mehrotra step is ``vmap``-ed over
the batch, the outer iteration is a ``lax.while_loop`` on device, and
per-problem convergence is handled by masking (never early exit — shapes
stay static, SURVEY.md §7 "ragged convergence ... masking, not early
exit"). The whole solve — every iteration of every problem — is ONE
compiled XLA program; nothing crosses the host boundary until the final
states come back.

Batch parallelism over a mesh (SURVEY.md §2.2: batch-axis sharding *is*
the data parallelism of this domain) falls out of placement: shard the
leading axis of (A, b, c) over the mesh and the same compiled program
runs B/K problems per device with no per-iteration collectives at all —
the only cross-device reduction is the cheap ``any(active)`` loop
predicate.

Converged problems are frozen by masking rather than dropped: their
iterates stay exactly at the accepted solution while stragglers continue.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributedlpsolver_tpu.backends.dense import _make_ops
from distributedlpsolver_tpu.ipm import core
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import IPMState, Status
from distributedlpsolver_tpu.models.generators import BatchedLP
from distributedlpsolver_tpu.obs import metrics as obs_metrics
from distributedlpsolver_tpu.parallel import mesh as mesh_lib

_RUNNING, _OPTIMAL, _MAXITER, _NUMERR = 0, 1, 2, 3
_STALL = 6  # aligned with core.STATUS_STALL


@dataclasses.dataclass
class BatchedResult:
    """Per-problem outcomes of a batched solve."""

    status: np.ndarray  # (B,) Status values
    objective: np.ndarray  # (B,)
    x: np.ndarray  # (B, n)
    iterations: np.ndarray  # (B,)
    rel_gap: np.ndarray  # (B,)
    pinf: np.ndarray  # (B,)
    dinf: np.ndarray  # (B,)
    solve_time: float = 0.0
    setup_time: float = 0.0
    # Per-phase iters/wall rows (segmented path; per CHUNK when chunked) —
    # the utilization split the scale artifacts record. Bucket solves
    # fill it with their precision-schedule rows
    # ({"phase", "engine", "tol", "iters"}) instead.
    phase_report: Optional[list] = None
    # Iterations fused per while-loop trip of the device loop (the
    # serve telemetry's fused-iterations-per-dispatch figure).
    fused_iters: int = 1
    # Full final iterates of the bucket path (the warm cache stores the
    # complete (x, y, s, w, z) per member); None where not populated.
    y: Optional[np.ndarray] = None
    s: Optional[np.ndarray] = None
    w: Optional[np.ndarray] = None
    z: Optional[np.ndarray] = None
    # Per-slot warm-start acceptance (bucket path): True where a warm
    # iterate was offered AND survived the in-program safeguard; False
    # for cold slots and safeguard fallbacks. None off the bucket path.
    warm_used: Optional[np.ndarray] = None

    @property
    def n_optimal(self) -> int:
        return int(np.sum(self.status == Status.OPTIMAL))


def _single_step(A, data, state, reg, params, factor_dtype, Af=None,
                 cg_iters=0, cg_tol=0.0):
    # Af: loop-invariant precast copy — with a low-precision factor_dtype
    # the O(m²n) normal-equations assembly then runs at that precision on
    # the MXU instead of in emulated f64 (see dense._cholesky_ops).
    # cg_iters > 0 selects the PCG ops (f32 preconditioner + matrix-free
    # full-precision CG — dense._pcg_ops, everything traceable, so the
    # whole solve vmaps over the batch).
    ops = _make_ops(A, reg, factor_dtype, 0, False, Af, cg_iters, cg_tol)
    return core.mehrotra_step(ops, data, params, state)


def _single_start(A, data, reg, params, factor_dtype, Af=None):
    ops = _make_ops(A, reg, factor_dtype, 0, False, Af)
    return core.starting_point(ops, data, params)


def _batched_phase(
    A, data, carry, params, max_iter, max_refactor, reg_grow, fdt,
    it_stop=None, stall_window=0, stall_status=_RUNNING, A32=None,
    cg_iters=0, cg_tol=0.0, fuse_iters=1,
):
    """One masked batched IPM while_loop phase over the whole batch.

    ``carry = (states, active, it, regs, badcount, status, iters, best,
    since)``; ``it`` is phase-local, ``iters`` counts accepted steps per
    problem globally, ``best``/``since`` drive per-problem stall detection
    (``stall_window`` accepted steps without 10% improvement in
    max(gap,pinf,dinf) deactivates a problem with ``stall_status`` — in a
    non-final phase that's _RUNNING, so the next phase picks it up; without
    this, f32-stalled problems grind the whole max_iter budget).
    ``it_stop`` (traced) additionally bounds this call for host
    segmentation (core.drive_segments' watchdog guard).

    ``fuse_iters`` (static) > 1 fuses that many masked micro-steps into
    ONE while-loop trip via an inner ``fori_loop``: each micro-step
    re-evaluates the loop guard itself and commits its writes only under
    it, so results are bitwise-identical in k while the while predicate
    — the only cross-device collective of a mesh-sharded batch — and the
    loop bookkeeping run k× less often. At most k−1 guarded no-op steps
    are wasted where a block straddles the finish.
    """
    B = A.shape[0]

    def guard(active, it):
        go = jnp.any(active) & (it < max_iter)
        if it_stop is not None:
            go = go & (it < it_stop)
        return go

    def cond(carry):
        _, active, it, *_ = carry
        return guard(active, it)

    def body(carry):
        states, active, it, regs, badcount, status, iters, best, since = carry
        if A32 is not None:
            new_states, stats = jax.vmap(
                lambda a, a32, d, st, rg: _single_step(
                    a, d, st, rg, params, fdt, a32, cg_iters, cg_tol
                )
            )(A, A32, data, states, regs)
        else:
            new_states, stats = jax.vmap(
                lambda a, d, st, rg: _single_step(
                    a, d, st, rg, params, fdt, None, cg_iters, cg_tol
                )
            )(A, data, states, regs)
        bad = stats.bad
        conv = (
            (stats.rel_gap <= params.tol)
            & (stats.pinf <= params.tol)
            & (stats.dinf <= params.tol)
        )
        accept = active & ~bad
        # Freeze non-accepted problems component-wise.
        states = jax.tree_util.tree_map(
            lambda new, old: jnp.where(
                accept.reshape((B,) + (1,) * (new.ndim - 1)), new, old
            ),
            new_states,
            states,
        )
        iters = iters + accept.astype(jnp.int32)
        # Per-problem regularization escalation on failed factorizations.
        regs = jnp.where(active & bad, jnp.maximum(regs, 1e-12) * reg_grow, regs)
        badcount = jnp.where(active & bad, badcount + 1, badcount)
        give_up = badcount > max_refactor
        newly_opt = accept & conv
        err = jnp.maximum(stats.rel_gap, jnp.maximum(stats.pinf, stats.dinf))
        improved = accept & (err < 0.9 * best)
        best = jnp.where(improved, err, best)
        since = jnp.where(
            active & ~bad, jnp.where(improved, 0, since + 1), since
        )
        if stall_window:
            stalled = active & (since > stall_window)
            if stall_status == _STALL:
                # Final phase: near-tol plateaus deserve patience — only
                # give up while still far (>1e3·tol) from tolerance.
                stalled = stalled & (best > 1e3 * params.tol)
        else:
            stalled = jnp.zeros_like(active)
        status = jnp.where(newly_opt, _OPTIMAL, status)
        status = jnp.where(active & give_up, _NUMERR, status)
        status = jnp.where(stalled & ~newly_opt & ~give_up, stall_status, status)
        active = active & ~newly_opt & ~give_up & ~stalled
        return states, active, it + 1, regs, badcount, status, iters, best, since

    if fuse_iters > 1:
        def micro(carry):
            # The while cond admits the whole k-block; each micro-step
            # re-checks the same guard on its own carry and commits only
            # under it — the guarded tail steps are exact no-ops, so the
            # fused loop's accepted-state sequence matches k=1 bitwise.
            go = guard(carry[1], carry[2])
            new = body(carry)
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(go, n, o), new, carry
            )

        fused_body = lambda c: jax.lax.fori_loop(
            0, fuse_iters, lambda _, cc: micro(cc), c
        )
        return jax.lax.while_loop(cond, fused_body, carry)
    return jax.lax.while_loop(cond, body, carry)


@functools.partial(
    jax.jit,
    static_argnames=(
        "params", "factor_dtype", "stall_window", "stall_status",
        "cg_iters", "cg_tol", "fuse_iters",
    ),
    # The carry is consumed: drive_segments rebinds it on every segment
    # and nothing reads the old one, so the (B, n)/(B, m, m) state
    # buffers recycle in place instead of doubling peak device memory.
    # A/data are loop-invariant across segments and must NOT donate.
    donate_argnums=(2,),
)
def _batched_segment_jit(
    A, data, carry, it_stop, max_iter, max_refactor, reg_grow, params,
    factor_dtype, stall_window=0, stall_status=_RUNNING, A32=None,
    cg_iters=0, cg_tol=0.0, fuse_iters=1,
):
    out = _batched_phase(
        A, data, carry, params, max_iter, max_refactor, reg_grow,
        jnp.dtype(factor_dtype), it_stop, stall_window, stall_status, A32,
        cg_iters, cg_tol, fuse_iters,
    )
    # Packed [it, status, n_active, n_unfinished] in core.drive_segments'
    # meta layout (one device→host transfer per segment — separate scalar
    # fetches cost a tunnel round trip each). Per-problem statuses/stall
    # live inside the loop, so the batch-level "status" is just the
    # all-settled predicate; the active and total-unfinished counts ride
    # the best_err/since slots for tail-extraction early stops.
    f = A.dtype
    settled = jnp.where(jnp.any(out[1]), core.STATUS_RUNNING, core.STATUS_OPTIMAL)
    unfinished = jnp.sum(out[5] != _OPTIMAL)
    meta = jnp.stack(
        [out[2].astype(f), settled.astype(f), jnp.sum(out[1]).astype(f),
         unfinished.astype(f)]
    )
    return out, meta


@functools.partial(jax.jit, static_argnames=("factor_dtype",))
def _batched_norms_jit(A, data, states, factor_dtype):
    fdt = jnp.dtype(factor_dtype)

    def final_norms(a, d, st):
        ops = _make_ops(a, jnp.asarray(0.0, a.dtype), fdt, 0)
        pinf, dinf, _, rel_gap, pobj, _, _ = core.residual_norms(ops, d, st)
        return pinf, dinf, rel_gap, pobj

    return jax.vmap(final_norms)(A, data, states)


@functools.partial(jax.jit, static_argnames=("params", "factor_dtype"))
def _batched_start_jit(A, data, reg0, params, factor_dtype):
    fdt = jnp.dtype(factor_dtype)
    return jax.vmap(lambda a, d: _single_start(a, d, reg0, params, fdt))(A, data)


@functools.partial(
    jax.jit,
    static_argnames=(
        "params", "params_p1", "factor_dtype", "two_phase", "stall_window",
        "cg_iters", "cg_tol", "fuse_iters",
    ),
)
def _solve_batched_jit(
    A, data, reg0, params, params_p1, max_iter, max_refactor, reg_grow,
    factor_dtype, two_phase, stall_window=0, cg_iters=0, cg_tol=0.0,
    fuse_iters=1,
):
    # max_iter / max_refactor / reg_grow are traced scalars so one compile
    # serves every iteration-limit config (warm-up shares the timed compile).
    # With ``two_phase`` the batch first runs with f32 factorizations to the
    # handoff tolerance (params_p1.tol), then every problem — including
    # phase-1 "optimal"/"numerical-error"/stalled ones, whose verdicts are
    # provisional — re-enters a full-precision loop warm-started from its
    # phase-1 iterate (same design as the dense two-phase, SURVEY.md §7;
    # each phase has its own ``max_iter`` budget). Per-problem stall
    # detection keeps f32-stalled members from grinding the whole budget.
    fdt = jnp.dtype(factor_dtype)
    B = A.shape[0]
    dtype = A.dtype
    # Loop-invariant f32 copy for f32 factorizations AND their assembly
    # (without it the O(m²n) assembly runs emulated-f64) — used by the
    # two-phase first phase, the PCG middle phase's preconditioner, and
    # an explicit single-phase f32 config.
    f32 = jnp.dtype(jnp.float32)
    A32 = A.astype(f32) if (two_phase or fdt == f32 or cg_iters) else None
    # The starting point stays at full precision even under two-phase: it
    # is ONE factorization amortized over the whole solve, and an f32
    # Mehrotra least-squares start can be bad enough on an ill-conditioned
    # member to strand its entire trajectory (observed: a problem that
    # solves solo in 16 iterations stalls at gap 6e-2 from an f32 start).
    states0 = jax.vmap(lambda a, d: _single_start(a, d, reg0, params, fdt))(
        A, data
    )

    carry = _fresh_batch_carry(states0, jnp.zeros(B, jnp.int32), B, reg0, dtype)
    if two_phase:
        carry = _batched_phase(
            A, data, carry, params_p1, max_iter, max_refactor, reg_grow,
            jnp.dtype(jnp.float32), None, stall_window, _RUNNING, A32,
            fuse_iters=fuse_iters,
        )
        # keep states + per-problem iters; reset provisional verdicts
        carry = _fresh_batch_carry(carry[0], carry[6], B, reg0, dtype)
    if cg_iters:
        # PCG middle phase at FULL tolerance: f32 preconditioner + f64
        # matrix-free CG — no emulated-f64 assembly or Cholesky. Its
        # OPTIMAL verdicts are final (honest f64 residuals); only
        # stalled/unconverged members re-enter the f64 finish.
        carry = _batched_phase(
            A, data, carry, params, max_iter, max_refactor, reg_grow,
            jnp.dtype(jnp.float32), None, stall_window, _RUNNING, A32,
            cg_iters, cg_tol, fuse_iters,
        )
        carry = _fresh_batch_carry(
            carry[0], carry[6], B, reg0, dtype, status=carry[5]
        )
    states, active, _, _, _, status, iters, _, _ = _batched_phase(
        A, data, carry, params, max_iter, max_refactor, reg_grow, fdt,
        None, 2 * stall_window if stall_window else 0, _STALL,
        A32 if fdt == f32 else None, fuse_iters=fuse_iters,
    )
    status = jnp.where(status == _RUNNING, _MAXITER, status)

    # Final per-problem diagnostics.
    def final_norms(a, d, st):
        ops = _make_ops(a, jnp.asarray(0.0, dtype), fdt, 0)
        pinf, dinf, _, rel_gap, pobj, _, _ = core.residual_norms(ops, d, st)
        return pinf, dinf, rel_gap, pobj

    pinf, dinf, rel_gap, pobj = jax.vmap(final_norms)(A, data, states)
    return states, status, iters, pinf, dinf, rel_gap, pobj


_CHUNK_DEFAULT = 256  # per-device-program batch slice; see solve_batched


def _cleanup_cap(B: int) -> int:
    """Max members the solo-cleanup pass will re-solve — ONE definition,
    shared by tail extraction's early stop (which promises every abandoned
    member a cleanup solve) and the cleanup gate itself."""
    return max(4, B // 8)


# Backend name the solo-cleanup pass re-solves through — exported so a
# warm-up (bench.py) can pre-compile the exact path cleanup will take.
CLEANUP_BACKEND = "tpu"


# Member size (m·n entries) above which the multi-phase schedules can pay
# for themselves in the batched loop. MEASURED at the reference batched
# config (B=256 of 128×512 members, one chip, 2026-07-31):
#     single-phase f64 direct      39.6 s   <- auto
#     two-phase (all-f32 phase 1)  60.7 s
#     two-phase (f32 factor only)  66.8 s
#     PCG middle phase            575   s   (and its chunk>=256 programs
#                                            crash the current TPU worker)
# Small members invert every large-scale intuition: the per-iteration
# factorization is microseconds of MXU work, the real cost is ELEMENTWISE
# emulated-f64 arithmetic (~100 ns/element measured — a 648 ms f64 step
# vs 108 ms all-f32 at B=128), and a phase-1 handoff at 3e-5 does NOT cut
# the f64 finish's iteration count enough to amortize the phase's own
# cost (observed: 27 f64 iterations after handoff vs ~30 from scratch).
# PCG is strictly worse: it multiplies the elementwise work per solve.
# Both schedules only win where the f64 FACTORIZATION is the wall (dense
# 10k-scale); below this threshold auto runs the single-phase f64 loop.
_PHASED_MEMBER_ENTRIES = 1 << 24


def _phase_plan(cfg: SolverConfig, member_entries: Optional[int] = None):
    """(two_phase, use_pcg, n_phases) — the batched loop's phase schedule,
    ONE definition shared by solve_batched and the cleanup-budget helper
    so the per-problem iteration budget (n_phases·max_iter) cannot
    silently diverge from the schedule that spends it.

    ``member_entries`` (m·n of ONE member) gates the auto phase rules; None
    (the cleanup-budget helper, which has no batch in hand) assumes the
    reference batched class — small members, single phase."""
    phased_pays = (
        member_entries is not None and member_entries >= _PHASED_MEMBER_ENTRIES
    )
    two_phase = cfg.two_phase_enabled(jax.default_backend()) and phased_pays
    use_pcg = cfg.cg_iters > 0 and (
        cfg.solve_mode == "pcg" or (cfg.solve_mode is None and two_phase)
    )
    return two_phase, use_pcg, 1 + (1 if two_phase else 0) + (1 if use_pcg else 0)


def cleanup_solo_max_iter(config: Optional[SolverConfig] = None,
                          member_entries: Optional[int] = None,
                          typical_spent: int = 40) -> int:
    """The ``max_iter`` a typical solo-cleanup solve runs with (cleanup
    budget = n_phases·max_iter − iterations already spent in the batched
    loop, via the shared :func:`_phase_plan`). Compile-cache buckets
    (core.buffer_cap) are keyed by this figure, so a warm-up must use it —
    a hardcoded number silently compiles a never-reused executable
    whenever the defaults move. Pass the batch's ``member_entries``
    (m·n of one member) so the phase count matches the member-gated
    schedule the real solve will run."""
    cfg = config or SolverConfig()
    _, _, n_phases = _phase_plan(cfg, member_entries=member_entries)
    return max(1, n_phases * cfg.max_iter - typical_spent)


def _fresh_batch_carry(states, iters, B, reg0, dtype, status=None):
    """Phase-boundary carry reset. With ``status=None`` every member
    re-enters the next phase (the f32 phase-1 reset: its verdicts are
    provisional — tol was loosened). Passing the previous phase's status
    keeps _OPTIMAL members SETTLED: a full-tolerance phase (the PCG
    middle phase) judged them with honest f64 residuals, so re-running
    them through the f64 finish would burn its per-iteration cost on
    already-final members."""
    if status is None:
        active = jnp.ones(B, dtype=bool)
        status = jnp.full(B, _RUNNING, jnp.int32)
    else:
        active = status != _OPTIMAL
        status = jnp.where(status == _OPTIMAL, _OPTIMAL, _RUNNING)
    return (
        states,
        active,
        jnp.asarray(0, jnp.int32),
        jnp.full(B, reg0, dtype=dtype),
        jnp.zeros(B, jnp.int32),
        status,
        iters,
        jnp.full(B, jnp.inf, dtype=dtype),
        jnp.zeros(B, jnp.int32),
    )


def _cast_batch_carry(carry, dtype):
    """Cast the batched carry's floating leaves (state, regs, best) to
    ``dtype`` across an f32-phase boundary; integer/bool lanes (active,
    counters, status) pass through untouched."""
    states, active, it, regs, badcount, status, iters, best, since = carry
    cast = lambda v: v.astype(dtype)
    states = jax.tree_util.tree_map(cast, states)
    return (states, active, it, cast(regs), badcount, status, iters,
            cast(best), since)


_COMPACT_FLOOR = 32  # smallest compacted program size


def _compact_gather(carry, order, keep_idx, new_size, B):
    """Gather the ``keep_idx`` members of a batched carry into a
    ``new_size`` program (padding by repeating the first kept member,
    padded entries forced inactive/settled with sentinel scatter target
    ``B`` so they can never write back)."""
    states, active, it, regs, badcount, status, iters, best, since = carry
    k = len(keep_idx)
    pad = np.full(new_size - k, keep_idx[0] if k else 0, np.int64)
    sel = jnp.asarray(np.concatenate([keep_idx, pad]))
    valid = jnp.arange(new_size) < k
    g = lambda v: v[sel]
    carry2 = (
        jax.tree_util.tree_map(g, states),
        g(active) & valid,
        it,
        g(regs),
        g(badcount),
        jnp.where(valid, g(status), _OPTIMAL),
        g(iters),
        g(best),
        g(since),
    )
    order2 = jnp.where(valid, order[sel], B)
    return carry2, order2, sel


def _scatter_out(outs, order, carry):
    """Scatter a (possibly compacted) carry's per-member lanes into the
    full-size out buffers (one sentinel row at index B absorbs pads)."""
    states_out, status_out, iters_out = outs
    states, _, _, _, _, status, iters, _, _ = carry
    states_out = jax.tree_util.tree_map(
        lambda o, v: o.at[order].set(v), states_out, states
    )
    return states_out, status_out.at[order].set(status), iters_out.at[order].set(iters)


def _solve_batched_segmented(
    A, data, cfg, params, params_p1, fname, two_phase, seg, cg=(0, 0.0),
    compact_ok=False, fuse_iters=1,
):
    """Host-segmented batched solve: same phases as _solve_batched_jit but
    each device program is bounded to ~15s (execution-watchdog guard —
    long fused batched solves trip the ~60s limit on tunneled TPUs).

    ``compact_ok`` additionally enables FINAL-phase compaction: whenever
    the active-member count falls to half the current program size, the
    still-active members are gathered into a half-size program
    (B → B/2 → … → 32) and the loop continues there. Rationale
    (measured, 2026-08-01): the masked whole-batch loop runs to the
    slowest member — ~62 accepted steps per 256-chunk while the MEAN
    member needs 16, so ~60% of step compute was spent advancing frozen
    members. Program sizes are fixed halvings, so each size compiles
    once and is reused by every chunk. Disabled under a mesh (the batch
    axis is sharded; gathers would reshard it)."""
    B = A.shape[0]
    dtype = A.dtype
    f32 = jnp.float32
    reg0 = jnp.asarray(cfg.reg_dual, dtype)
    mi = jnp.asarray(cfg.max_iter, jnp.int32)
    mr = jnp.asarray(cfg.max_refactor, jnp.int32)
    rg = jnp.asarray(cfg.reg_grow, dtype)
    cgi, cgt = cg
    A32 = (
        A.astype(f32)
        if (two_phase or fname == "float32" or cgi)
        else None
    )
    # Phase 1 runs ENTIRELY in f32 — state, residuals, ratio tests,
    # backoff, not just the factorization. Measured at the reference
    # batched member shape (B=128 of 128×512): a full step with f64
    # state costs 578 ms (f64-factor) / 121 ms (f32-factor) while the
    # MXU dots in it are microseconds — the cost is ELEMENTWISE
    # emulated-f64 arithmetic over the (B, n) vectors (~100 ns/element:
    # divisions in scaling_d and the ratio tests, the (B, 24, n)
    # backoff grid, residual updates). f32 elementwise is native VPU
    # work, so the f32 phase's per-iteration cost drops by an order of
    # magnitude, and the f64 finish only pays the emulation tax for the
    # last 3 orders of magnitude. The f32 noise floor (~1e-6 relative)
    # sits safely below the 3e-5 handoff tolerance that phase-1 params
    # already encode.
    data32 = (
        jax.tree_util.tree_map(
            lambda v: v.astype(f32)
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)
            else v,
            data,
        )
        if two_phase
        else None
    )
    # Starting point at the resolved factor dtype (== full dtype under the
    # auto two-phase schedule) — see _solve_batched_jit for why an f32
    # start under two-phase is dangerous.
    states0 = _batched_start_jit(A, data, reg0, params, fname)

    # Phase tuples: (step params, factor dtype, stall window, stall
    # status, cg iters, keep-optimal-at-exit, f32-state). The PCG middle
    # phase runs at FULL tolerance, so its optimal verdicts survive the
    # boundary; the f32 phase-1 verdicts are provisional and reset.
    w = cfg.stall_window
    phases = []
    if two_phase:
        phases.append((params_p1, "float32", w, _RUNNING, 0, False, True))
    if cgi:
        phases.append((params, "float32", w, _RUNNING, cgi, True, False))
    phases.append((params, fname, 2 * w if w else 0, _STALL, 0, False, False))
    carry = _fresh_batch_carry(states0, jnp.zeros(B, jnp.int32), B, reg0, dtype)
    # Tail extraction: a handful of stragglers would otherwise keep the
    # full-batch masked loop running at whole-batch cost per iteration.
    # Once ≤ tail problems are active in the FINAL phase, stop — the
    # leftover problems finish solo through the dense path (solve_batched
    # cleanup), warm-started from their batched iterates. tail = B//32 is
    # 0 for small batches (no extraction — a lone member might converge in
    # the very next segment), and the stop also requires the TOTAL
    # unfinished count to fit the solo-cleanup bound, so an abandoned
    # problem is never left without its cleanup solve.
    tail = B // 32
    cleanup_cap = _cleanup_cap(B)
    phase_report = []  # same shape as drive_phase_plan's report rows
    for pi, (p, f, win, wstat, pcgi, keep_opt, f32_state) in enumerate(phases):
        final = pi == len(phases) - 1
        t_ph = time.perf_counter()
        if f32_state:
            # Enter the all-f32 phase: cast the CARRY's state and float
            # trackers down; the phase program then sees f32 arrays
            # everywhere and every op in the step runs native-f32.
            carry = _cast_batch_carry(carry, f32)
            Ap, datap, A32p = A32, data32, None  # factor from Ap itself
        else:
            if carry[0].x.dtype != dtype:  # leaving the f32 phase
                carry = _cast_batch_carry(carry, dtype)
            Ap, datap = A, data
            A32p = A32 if f == "float32" else None

        def mk_run_seg(Ax, dx, A32x, _p=(p, f, win, wstat, pcgi)):
            pp, ff, w, ws, ci = _p

            def run_seg(c, stop):
                # reg_grow cast to the PHASE dtype: an f64 scalar would
                # promote the f32 carry's regs lane out of its
                # while_loop carry type.
                return _batched_segment_jit(
                    Ax, dx, c, jnp.asarray(stop, jnp.int32), mi, mr,
                    rg.astype(Ax.dtype), pp, ff, w, ws, A32x, ci,
                    cgt if ci else 0.0, fuse_iters,
                )

            return run_seg

        # Batch-level stall/status live per problem inside the device loop;
        # the driver only watches the all-settled predicate (window 0).
        if final and compact_ok and B >= 2 * _COMPACT_FLOOR:
            carry = _drive_compacting(
                mk_run_seg, carry, Ap, datap, A32p, cfg, seg, B, tail,
                cleanup_cap, dtype,
            )
        else:
            carry, _ = core.drive_segments(
                mk_run_seg(Ap, datap, A32p), carry, cfg.max_iter, 0, seg,
                early_stop=(
                    (
                        lambda it, status, n_active, n_unfinished: 0
                        < n_active
                        <= tail
                        and n_unfinished <= cleanup_cap
                    )
                    if final and tail
                    else None
                ),
            )
        phase_report.append({
            "phase": pi,
            "mode": ("f32-state" if f32_state
                     else ("pcg" if pcgi else f)),
            "iters": int(carry[2]),  # phase-local iteration count
            "wall_s": round(time.perf_counter() - t_ph, 3),
        })
        if not final:
            # Phase boundary: iterates kept; verdicts reset — except a
            # full-tolerance phase's OPTIMAL members, which stay settled.
            if carry[0].x.dtype != dtype:
                carry = _cast_batch_carry(carry, dtype)
            carry = _fresh_batch_carry(
                carry[0], carry[6], B, reg0, dtype,
                status=carry[5] if keep_opt else None,
            )

    states, _, _, _, _, status, iters, _, _ = carry
    status = jnp.where(status == _RUNNING, _MAXITER, status)
    pinf, dinf, rel_gap, pobj = _batched_norms_jit(A, data, states, fname)
    return states, status, iters, pinf, dinf, rel_gap, pobj, phase_report


def _drive_compacting(
    mk_run_seg, carry, A, data, A32, cfg, seg, B, tail, cleanup_cap, dtype
):
    """Final-phase segment drive with program compaction (see
    _solve_batched_segmented). Returns a FULL-SIZE carry whose states /
    status / iters lanes hold every member's final values (the only
    lanes the caller consumes after the final phase)."""
    states_out = jax.tree_util.tree_map(
        lambda v: jnp.zeros((B + 1,) + v.shape[1:], v.dtype), carry[0]
    )
    status_out = jnp.full(B + 1, _OPTIMAL, jnp.int32)
    iters_out = jnp.zeros(B + 1, jnp.int32)
    order = jnp.arange(B)
    size = B
    out_nonopt = 0  # non-optimal members already scattered out
    it_g, status_g = 0, core.STATUS_RUNNING
    run_seg = mk_run_seg(A, data, A32)
    while True:
        def early(it, status, n_active, n_unfinished, _size=size,
                  _out=out_nonopt):
            if (
                tail
                and 0 < n_active <= max(1, _size // 32)
                and n_unfinished + _out <= cleanup_cap
            ):
                return True
            return _size > _COMPACT_FLOOR and n_active <= _size // 2

        prev_it = it_g
        # Short segments (4 s target, ≤8 iterations) keep boundaries —
        # the only points compaction can act — frequent; the ~0.1 s
        # meta fetch per segment is noise against the step cost.
        carry, (it_g, status_g, n_act, n_unf) = core.drive_segments(
            run_seg, carry, cfg.max_iter, 0, min(seg, 8), target_s=4.0,
            early_stop=early, it0_status0=(it_g, status_g), seg_cap=8,
        )
        n_act, n_unf = int(n_act), int(n_unf)
        if (
            status_g != core.STATUS_RUNNING
            or it_g >= cfg.max_iter
            or n_act == 0
            or (
                tail
                and n_act <= max(1, size // 32)
                and n_unf + out_nonopt <= cleanup_cap
            )
            or size <= _COMPACT_FLOOR
            or it_g == prev_it  # spin guard: drive made no progress
        ):
            break
        # Shrink: gather actives into the smallest half-size that fits.
        act = np.asarray(carry[1])
        stat_host = np.asarray(carry[5])
        keep = np.flatnonzero(act)
        new_size = size // 2
        while new_size > _COMPACT_FLOOR and len(keep) <= new_size // 2:
            new_size //= 2
        if len(keep) > new_size:
            break  # defensive: actives cannot exceed the early trigger
        out_nonopt += int(np.sum(~act & (stat_host != _OPTIMAL)))
        states_out, status_out, iters_out = _scatter_out(
            (states_out, status_out, iters_out), order, carry
        )
        carry, order, sel = _compact_gather(carry, order, keep, new_size, B)
        A = A[sel]
        A32 = A32[sel] if A32 is not None else None
        data = jax.tree_util.tree_map(lambda v: v[sel], data)
        size = new_size
        run_seg = mk_run_seg(A, data, A32)
    states_out, status_out, iters_out = _scatter_out(
        (states_out, status_out, iters_out), order, carry
    )
    states = jax.tree_util.tree_map(lambda v: v[:B], states_out)
    zi = jnp.zeros(B, jnp.int32)
    return (
        states,
        jnp.zeros(B, bool),
        carry[2],
        jnp.full(B, cfg.reg_dual, dtype),
        zi,
        status_out[:B],
        iters_out[:B],
        jnp.full(B, jnp.inf, dtype),
        zi,
    )


# ---------------------------------------------------------------------------
# Bucket entry point (serve/): a pre-padded batch + active mask, one compiled
# program per bucket shape, reused verbatim across service dispatches.


def _warm_build_single(a, d, x, y, s, w, z, reg0, fdt):
    """Traced twin of ipm.warm.interior_candidate for ONE bucket slot
    (vmapped by :func:`_warm_select`): interior shift → primal
    projection onto the new b (one AAᵀ solve — same-A delta-solve
    refresh) → dual slack refresh on the new c → residual-aware
    centrality lift. Policy constants come from ipm/warm.py — one
    definition, two engines. Returns (candidate, merit, μ_w)."""
    from distributedlpsolver_tpu.ipm import warm as warm_mod

    dtype = a.dtype
    floor = jnp.asarray(warm_mod.INTERIOR_FLOOR, dtype)
    one = jnp.asarray(1.0, dtype)
    tiny = jnp.asarray(1e-30, dtype)
    ops = _make_ops(a, reg0, fdt, 0)
    xm = jnp.maximum(jnp.mean(jnp.abs(x)), one)
    sm = jnp.maximum(jnp.mean(jnp.abs(s)), one)
    x1 = jnp.maximum(x, floor * xm)
    # Primal projection: x += Aᵀ(AAᵀ)⁻¹(b − Ax) lands the candidate on
    # the new feasible affine (the clip after re-opens a floor-sized
    # residual at worst). A degenerate factorization NaNs the merit and
    # the slot falls back to cold — the safeguard's job.
    fac = ops.factorize(jnp.ones_like(x))
    x1 = x1 + ops.rmatvec(ops.solve(fac, d.b - ops.matvec(x1)))
    x1 = jnp.maximum(x1, floor * xm)
    hub, u_f = d.hub, d.u_f
    x1 = jnp.where(hub > 0, jnp.clip(x1, 0.01 * u_f, 0.99 * u_f), x1)
    w1 = jnp.where(hub > 0, u_f - x1, jnp.ones_like(w))
    # Dual refresh: s − z = c − Aᵀy exactly wherever the positive split
    # allows, a floor-shift on both parts elsewhere.
    s_hat = d.c - ops.rmatvec(y)
    z1 = jnp.where(hub > 0, jnp.maximum(z, floor * sm), jnp.zeros_like(z))
    s1 = jnp.where(hub > 0, s_hat + z1, jnp.maximum(s_hat, floor * sm))
    deficit = jnp.where(
        hub > 0, jnp.maximum(floor * sm - s1, 0.0), jnp.zeros_like(s1)
    )
    s1 = s1 + deficit
    z1 = z1 + deficit
    mu_w = (x1 @ s1 + (hub * w1) @ z1) / d.ncomp
    pinf, dinf, *_ = core.residual_norms(
        ops, d, IPMState(x=x1, y=y, s=s1, w=w1, z=z1)
    )
    merit = jnp.maximum(pinf, dinf)
    # Residual-aware centrality lift (MERIT_MU_FLOOR): raise the SMALLER
    # factor of any pair whose product trails the recentre target.
    pobj = d.c @ x1
    target = jnp.maximum(
        jnp.asarray(warm_mod.CENTRALITY_BETA, dtype) * mu_w,
        jnp.asarray(warm_mod.MERIT_MU_FLOOR, dtype)
        * merit * (one + jnp.abs(pobj)) / d.ncomp,
    )
    lift = jnp.sqrt(jnp.clip(target / jnp.maximum(x1 * s1, tiny), 1.0, 1e16))
    x2 = jnp.where(x1 <= s1, x1 * lift, x1)
    s2 = jnp.where(s1 < x1, s1 * lift, s1)
    liftw = jnp.sqrt(jnp.clip(target / jnp.maximum(w1 * z1, tiny), 1.0, 1e16))
    w2 = jnp.where((hub > 0) & (w1 <= z1), w1 * liftw, w1)
    z2 = jnp.where((hub > 0) & (z1 < w1), z1 * liftw, z1)
    return IPMState(x=x2, y=y, s=s2, w=w2, z=z2), merit, mu_w


def _warm_select(A, data, states_cold, warm_raw, warm_mask, fdt, reg0):
    """Per-slot safeguarded warm-start selection: candidates built by
    :func:`_warm_build_single`, each compared against the cold start's
    initial residual merit AND complementarity (the refresh makes even
    far-off priors nearly feasible; μ is what still tells them apart);
    a slot takes the warm iterate only where the mask requests it and
    both guards accept. Runs INSIDE the bucket programs — warm arrays
    are ordinary traced inputs (zeros on cold dispatches), so one
    compiled program serves any warm/cold mix with zero warm
    recompiles. Returns (states0, warm_used)."""
    from distributedlpsolver_tpu.ipm import warm as warm_mod

    dtype = A.dtype
    wx, wy, ws_, ww, wz = warm_raw
    cand, merit_w, mu_w = jax.vmap(
        lambda a, d, x, y, s, w, z: _warm_build_single(
            a, d, x, y, s, w, z, reg0, fdt
        )
    )(A, data, wx, wy, ws_, ww, wz)

    def cold_stats(a, d, st):
        ops = _make_ops(a, jnp.asarray(0.0, dtype), fdt, 0)
        pinf, dinf, _, _, _, _, mu = core.residual_norms(ops, d, st)
        return jnp.maximum(pinf, dinf), mu

    merit_c, mu_c = jax.vmap(cold_stats)(A, data, states_cold)
    tiny = jnp.asarray(1e-12, dtype)
    ok = (
        warm_mask
        & jnp.isfinite(merit_w)
        & jnp.isfinite(mu_w)
        & (
            merit_w
            <= jnp.asarray(warm_mod.WARM_ACCEPT_FACTOR, dtype)
            * jnp.maximum(merit_c, tiny)
        )
        & (
            mu_w
            <= jnp.asarray(warm_mod.MU_ACCEPT_FACTOR, dtype)
            * jnp.maximum(mu_c, tiny)
        )
    )
    B = A.shape[0]
    pick = lambda wv, cv: jnp.where(
        ok.reshape((B,) + (1,) * (wv.ndim - 1)), wv, cv
    )
    return jax.tree_util.tree_map(pick, cand, states_cold), ok


def _bucket_phase_carry(states, iters, B, reg0, dtype, active0, status=None):
    """Bucket phase-entry carry: :func:`_fresh_batch_carry` with the
    padding mask re-applied — padding slots are inactive and report a
    placeholder _OPTIMAL in EVERY schedule phase, not just the first
    (the all-settled loop predicate and the demux logic treat them as
    finished; serve/service.py demuxes by slot index, so a padding
    verdict is never read)."""
    c = _fresh_batch_carry(states, iters, B, reg0, dtype, status=status)
    states, active, it, regs, bad, st, iters, best, since = c
    return (
        states,
        active & active0,
        it,
        regs,
        bad,
        jnp.where(active0, st, _OPTIMAL),
        iters,
        best,
        since,
    )


@functools.partial(
    jax.jit,
    static_argnames=("schedule", "factor_dtype", "stall_window", "fuse_iters"),
)
def _solve_bucket_jit(
    A, data, active0, warm_x, warm_y, warm_s, warm_w, warm_z, warm_mask,
    reg0, max_iter, max_refactor, reg_grow, schedule,
    factor_dtype, stall_window, fuse_iters=1,
):
    # ``schedule`` is the static per-tolerance-tier precision ladder from
    # SolverConfig.bucket_phases — a tuple of (engine, StepParams) pairs,
    # sequenced as masked phases INSIDE this one program, so one compiled
    # executable still serves every dispatch of a (bucket, tol) pair.
    # The legacy behavior is the single-phase ("f64", params) schedule.
    # Serving members sit far below _PHASED_MEMBER_ENTRIES, where the
    # LARGE-member schedules (PCG, all-f32 state) lose; the df32 ladder
    # is different — it attacks the elementwise emulation tax, which IS
    # the wall at bucket shapes (ROUND5_NOTES lever 3). max_iter /
    # max_refactor / reg_grow are traced so per-request iteration budgets
    # never fork the compile cache; ``active0`` masks padding slots
    # inactive from iteration 0 — the same machinery that freezes
    # converged members freezes slots that never held a request.
    fdt = jnp.dtype(factor_dtype)
    B = A.shape[0]
    dtype = A.dtype
    # Starting point at the RESOLVED factor dtype regardless of an f32
    # first phase — it is one factorization amortized over the whole
    # solve, and an f32 least-squares start can strand an
    # ill-conditioned member (see _solve_batched_jit).
    start_params = schedule[-1][1]
    states0 = jax.vmap(
        lambda a, d: _single_start(a, d, reg0, start_params, fdt)
    )(A, data)
    # Warm slots override the cold start where the in-program safeguard
    # accepts (cold dispatches pass zero warm arrays + an all-false mask
    # — same shapes, same program, zero warm recompiles).
    states0, warm_used = _warm_select(
        A, data, states0, (warm_x, warm_y, warm_s, warm_w, warm_z),
        warm_mask, fdt, reg0,
    )
    need_f32 = any(e == "f32" for e, _ in schedule)
    # Loop-invariant precast copy: f32 phases factor AND assemble from it
    # on the MXU instead of in emulated f64 (dense._cholesky_ops).
    A32 = A.astype(jnp.float32) if need_f32 else None
    final_tol = schedule[-1][1].tol
    carry = _bucket_phase_carry(
        states0, jnp.zeros(B, jnp.int32), B, reg0, dtype, active0
    )
    phase_its = []
    for pi, (engine, pp) in enumerate(schedule):
        final = pi == len(schedule) - 1
        fdt_p = jnp.dtype(jnp.float32) if engine == "f32" else fdt
        win = (2 * stall_window if stall_window else 0) if final else stall_window
        carry = _batched_phase(
            A, data, carry, pp, max_iter, max_refactor, reg_grow, fdt_p,
            None, win, _STALL if final else _RUNNING,
            A32 if engine == "f32" else None, fuse_iters=fuse_iters,
        )
        phase_its.append(carry[2])
        if not final:
            # Phase boundary: iterates kept, provisional verdicts reset.
            # A phase that ran at the FINAL tolerance judged its members
            # with honest full-precision residuals (state, residual
            # norms, and convergence tests stay f64 in every engine), so
            # its OPTIMAL verdicts survive; loosened-tol phases are
            # provisional and every member re-enters.
            carry = _bucket_phase_carry(
                carry[0], carry[6], B, reg0, dtype, active0,
                status=carry[5] if pp.tol <= final_tol else None,
            )
    states, _, _, _, _, status, iters, _, _ = carry
    status = jnp.where(status == _RUNNING, _MAXITER, status)

    def final_norms(a, d, st):
        ops = _make_ops(a, jnp.asarray(0.0, dtype), fdt, 0)
        pinf, dinf, _, rel_gap, pobj, _, _ = core.residual_norms(ops, d, st)
        return pinf, dinf, rel_gap, pobj

    pinf, dinf, rel_gap, pobj = jax.vmap(final_norms)(A, data, states)
    return (states, status, iters, pinf, dinf, rel_gap, pobj,
            jnp.stack(phase_its), warm_used)


@functools.partial(jax.jit, static_argnames=("params", "factor_dtype"))
def _bucket_start_jit(
    A, data, warm_x, warm_y, warm_s, warm_w, warm_z, warm_mask, reg0,
    params, factor_dtype,
):
    """Starting point of the SEGMENTED bucket drive (own cache so
    :func:`bucket_cache_size` accounts every bucket-path program), with
    the same safeguarded per-slot warm override as the fused program.
    Returns (states0, warm_used)."""
    fdt = jnp.dtype(factor_dtype)
    states0 = jax.vmap(lambda a, d: _single_start(a, d, reg0, params, fdt))(
        A, data
    )
    return _warm_select(
        A, data, states0, (warm_x, warm_y, warm_s, warm_w, warm_z),
        warm_mask, fdt, reg0,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "params", "factor_dtype", "stall_window", "stall_status", "fuse_iters",
    ),
    # The carry is consumed: the bucket segment drive rebinds it on every
    # dispatch and nothing reads the old one, so the per-bucket state
    # buffers recycle in place (donation satellite — same rationale as
    # _batched_segment_jit; verified by bucket_donation_report / the
    # compiled program's memory analysis). A / data / A32 are
    # loop-invariant across segments and shared with retry dispatches,
    # so they must NOT donate.
    donate_argnums=(2,),
)
def _bucket_segment_jit(
    A, data, carry, it_stop, max_iter, max_refactor, reg_grow, params,
    factor_dtype, stall_window=0, stall_status=_RUNNING, A32=None,
    fuse_iters=1,
):
    out = _batched_phase(
        A, data, carry, params, max_iter, max_refactor, reg_grow,
        jnp.dtype(factor_dtype), it_stop, stall_window, stall_status, A32,
        fuse_iters=fuse_iters,
    )
    f = A.dtype
    settled = jnp.where(jnp.any(out[1]), core.STATUS_RUNNING, core.STATUS_OPTIMAL)
    unfinished = jnp.sum(out[5] != _OPTIMAL)
    meta = jnp.stack(
        [out[2].astype(f), settled.astype(f), jnp.sum(out[1]).astype(f),
         unfinished.astype(f)]
    )
    return out, meta


@functools.partial(jax.jit, static_argnames=("factor_dtype",))
def _bucket_norms_jit(A, data, states, factor_dtype):
    """Final per-member diagnostics of the segmented bucket drive (own
    cache — see :func:`_bucket_start_jit`)."""
    fdt = jnp.dtype(factor_dtype)

    def final_norms(a, d, st):
        ops = _make_ops(a, jnp.asarray(0.0, a.dtype), fdt, 0)
        pinf, dinf, _, rel_gap, pobj, _, _ = core.residual_norms(ops, d, st)
        return pinf, dinf, rel_gap, pobj

    return jax.vmap(final_norms)(A, data, states)


def _solve_bucket_segmented(
    A, data, active0, cfg, schedule, fname, seg, fuse, warm_raw, warm_mask
):
    """Host-segmented bucket drive (TPU watchdog guard, same design as
    _solve_batched_segmented): each device dispatch is one bounded
    :func:`_bucket_segment_jit` continuation with the carry DONATED —
    the bucket's state buffers recycle in place across dispatches — and
    ``fuse`` IPM iterations fused per while-loop trip, so the serve
    solve thread crosses the host boundary once per segment instead of
    once per iteration. No compaction (bucket batches are small and may
    be mesh-sharded) and no cleanup (the service owns the retry
    budget)."""
    B = A.shape[0]
    dtype = A.dtype
    fdt = jnp.dtype(fname)
    reg0 = jnp.asarray(cfg.reg_dual, dtype)
    mi = jnp.asarray(cfg.max_iter, jnp.int32)
    mr = jnp.asarray(cfg.max_refactor, jnp.int32)
    rg = jnp.asarray(cfg.reg_grow, dtype)
    need_f32 = any(e == "f32" for e, _ in schedule)
    A32 = A.astype(jnp.float32) if need_f32 else None
    states0, warm_used = _bucket_start_jit(
        A, data, *warm_raw, warm_mask, reg0, schedule[-1][1], fname
    )
    carry = _bucket_phase_carry(
        states0, jnp.zeros(B, jnp.int32), B, reg0, dtype, active0
    )
    final_tol = schedule[-1][1].tol
    w = cfg.stall_window
    phase_its = []
    for pi, (engine, pp) in enumerate(schedule):
        final = pi == len(schedule) - 1
        fdt_name = "float32" if engine == "f32" else jnp.dtype(fdt).name
        win = (2 * w if w else 0) if final else w
        wstat = _STALL if final else _RUNNING
        A32p = A32 if engine == "f32" else None

        def run_seg(c, stop, _pp=pp, _f=fdt_name, _w=win, _ws=wstat,
                    _a32=A32p):
            return _bucket_segment_jit(
                A, data, c, jnp.asarray(stop, jnp.int32), mi, mr, rg,
                _pp, _f, _w, _ws, _a32, fuse,
            )

        carry, (it, _, _, _) = core.drive_segments(
            run_seg, carry, cfg.max_iter, 0, seg
        )
        phase_its.append(it)
        if not final:
            carry = _bucket_phase_carry(
                carry[0], carry[6], B, reg0, dtype, active0,
                status=carry[5] if pp.tol <= final_tol else None,
            )
    states, _, _, _, _, status, iters, _, _ = carry
    status = jnp.where(status == _RUNNING, _MAXITER, status)
    pinf, dinf, rel_gap, pobj = _bucket_norms_jit(A, data, states, fname)
    return states, status, iters, pinf, dinf, rel_gap, pobj, phase_its, warm_used


def bucket_cache_size() -> int:
    """Number of compiled bucket programs in this process — the serve
    layer's recompile telemetry, and the warm-bucket zero-recompile
    assertion in tests (repeat dispatches to a warm bucket must not grow
    this). Sums every bucket-path program: the fused single-program
    route plus the segmented start/segment/norms route. The cache keys
    include the input shardings, so the invariant holds per
    (bucket, mesh) pair: the same bucket dispatched over a different
    mesh compiles once more, then stays warm there too. The PDHG
    bucket engine's programs (backends/first_order) count too — the
    serve layer's zero-warm-recompile invariant covers every engine of
    the tolerance-tiered ladder."""
    from distributedlpsolver_tpu.backends.first_order import (
        pdhg_bucket_cache_size,
    )

    return (
        _solve_bucket_jit._cache_size()
        + _bucket_start_jit._cache_size()
        + _bucket_segment_jit._cache_size()
        + _bucket_norms_jit._cache_size()
        + pdhg_bucket_cache_size()
    )


def bucket_donation_report(
    m: int, n: int, batch: int, config: Optional[SolverConfig] = None
):
    """AOT-compile the bucket segment program at the given shape and
    return its memory-analysis figures — ``alias_bytes`` is the donated
    input/output aliasing XLA actually established (0 would mean the
    donated carry is being COPIED, defeating the in-place reuse). Uses
    ``jit.lower().compile()``, which bypasses the dispatch cache, so the
    zero-warm-recompile accounting is untouched. Returns None where the
    backend exposes no memory analysis."""
    cfg = config or SolverConfig()
    dtype = jnp.dtype(cfg.dtype)
    B = batch
    A = jnp.zeros((B, m, n), dtype)
    b = jnp.ones((B, m), dtype)
    c = jnp.ones((B, n), dtype)
    u = jnp.full((B, n), jnp.inf, dtype=dtype)
    data = jax.vmap(
        lambda cc, bb, uu: core.make_problem_data(jnp, cc, bb, uu, dtype)
    )(c, b, u)
    states0 = IPMState(
        x=jnp.ones((B, n), dtype), y=jnp.zeros((B, m), dtype),
        s=jnp.ones((B, n), dtype), w=jnp.ones((B, n), dtype),
        z=jnp.zeros((B, n), dtype),
    )
    reg0 = jnp.asarray(cfg.reg_dual, dtype)
    carry = _bucket_phase_carry(
        states0, jnp.zeros(B, jnp.int32), B, reg0, dtype,
        jnp.ones(B, dtype=bool),
    )
    pp = cfg.bucket_phase_params("f64", cfg.tol)
    lowered = _bucket_segment_jit.lower(
        A, data, carry, jnp.asarray(8, jnp.int32),
        jnp.asarray(cfg.max_iter, jnp.int32),
        jnp.asarray(cfg.max_refactor, jnp.int32),
        jnp.asarray(cfg.reg_grow, dtype), pp,
        jnp.dtype(cfg.factor_dtype_resolved()).name, 0, _RUNNING, None, 1,
    )
    try:
        # Force a REAL compile: an executable deserialized from the
        # persistent compilation cache (the package enables one by
        # default) reports zero alias/temp figures, which would read as
        # "donation silently copied" when the donation is fine. Neither
        # the enable flag nor unsetting the cache dir is enough on this
        # jax version once the cache backend singleton has initialized
        # (observed: a populated .tpulp_xla_cache still served the
        # deserialized executable under enable=False) — the singleton
        # must be RESET so the compile re-resolves the (now disabled)
        # config, and reset again afterwards so later compiles re-init
        # with the restored dir.
        prev = jax.config.jax_enable_compilation_cache
        prev_dir = jax.config.jax_compilation_cache_dir
        try:
            from jax._src import compilation_cache as _cc
        except ImportError:  # private API moved: degrade to flag-only
            _cc = None

        def _reset_cc():
            if _cc is not None:
                try:
                    _cc.reset_cache()
                except Exception:
                    pass

        jax.config.update("jax_enable_compilation_cache", False)
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_cc()
        try:
            ma = lowered.compile().memory_analysis()
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            _reset_cc()
    except Exception:
        return None
    if ma is None:
        return None

    def _get(attr):
        try:
            v = getattr(ma, attr)
        except Exception:
            return None
        return None if v is None else int(v)

    return {
        "alias_bytes": _get("alias_size_in_bytes"),
        "argument_bytes": _get("argument_size_in_bytes"),
        "output_bytes": _get("output_size_in_bytes"),
        "temp_bytes": _get("temp_size_in_bytes"),
    }


def place_bucket(
    batch: BatchedLP,
    active,
    config: Optional[SolverConfig] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    batch_axis: str = "batch",
):
    """Host→device transfer of a pre-padded bucket — the PACK stage of the
    serving pipeline. Casts to the solve dtype and places the leading
    (batch) axis over ``mesh`` (data parallelism: the same compiled
    program then runs B/K problems per device), or on the default device
    unsharded. Split out of :func:`solve_bucket` so the service can run
    this host work for batch k+1 while the device still solves batch k;
    ``solve_bucket`` accepts the returned (batch, active) verbatim and
    skips its own conversion.
    """
    cfg = config or SolverConfig()
    dtype = jnp.dtype(cfg.dtype)
    A = np.asarray(batch.A, dtype=dtype)
    b = np.asarray(batch.b, dtype=dtype)
    c = np.asarray(batch.c, dtype=dtype)
    act = np.asarray(active, dtype=bool)
    Bsz = A.shape[0]
    if act.shape != (Bsz,):
        raise ValueError(f"active mask shape {act.shape} != ({Bsz},)")
    if mesh is not None:
        k = mesh.shape[batch_axis]
        if Bsz % k != 0:
            raise ValueError(
                f"bucket batch {Bsz} not divisible by mesh axis {k}"
            )
        sh = lambda nd: mesh_lib.batch_sharding(mesh, nd, batch_axis)
        A = jax.device_put(A, sh(3))
        b = jax.device_put(b, sh(2))
        c = jax.device_put(c, sh(2))
        act = jax.device_put(act, sh(1))
    else:
        A, b, c = jax.device_put(A), jax.device_put(b), jax.device_put(c)
        act = jax.device_put(act)
    return BatchedLP(c=c, A=A, b=b, name=batch.name), act


def place_warm(
    warm: Optional[IPMState],
    warm_mask,
    shape,
    config: Optional[SolverConfig] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    batch_axis: str = "batch",
):
    """Host→device transfer of a bucket's warm-start lanes — the warm
    half of :func:`place_bucket`, run by the serve pack stage. ``warm``
    is an IPMState of (B, n)/(B, m) host arrays (None = cold dispatch:
    zeros), ``warm_mask`` the (B,) offered-slots mask; ``shape`` is the
    bucket's (B, m, n). The lanes are placed with the SAME batch-axis
    sharding as the bucket data, so a warm dispatch reuses the exact
    compiled program a cold/warm-up dispatch built (the warm arrays are
    ordinary traced inputs, never part of the cache key)."""
    cfg = config or SolverConfig()
    dtype = jnp.dtype(cfg.dtype)
    B, m, n = shape
    if warm is None:
        wx = np.zeros((B, n), dtype=dtype)
        wy = np.zeros((B, m), dtype=dtype)
        ws_ = np.zeros((B, n), dtype=dtype)
        ww = np.zeros((B, n), dtype=dtype)
        wz = np.zeros((B, n), dtype=dtype)
        wm = np.zeros(B, dtype=bool)
    else:
        wx = np.asarray(warm.x, dtype=dtype)
        wy = np.asarray(warm.y, dtype=dtype)
        ws_ = np.asarray(warm.s, dtype=dtype)
        ww = np.asarray(warm.w, dtype=dtype)
        wz = np.asarray(warm.z, dtype=dtype)
        wm = np.asarray(warm_mask, dtype=bool)
    if wm.shape != (B,):
        raise ValueError(f"warm mask shape {wm.shape} != ({B},)")
    if mesh is not None:
        sh = lambda nd: mesh_lib.batch_sharding(mesh, nd, batch_axis)
        wx, ws_, ww, wz = (jax.device_put(v, sh(2)) for v in (wx, ws_, ww, wz))
        wy = jax.device_put(wy, sh(2))
        wm = jax.device_put(wm, sh(1))
    else:
        wx, wy, ws_, ww, wz, wm = (
            jax.device_put(v) for v in (wx, wy, ws_, ww, wz, wm)
        )
    return IPMState(x=wx, y=wy, s=ws_, w=ww, z=wz), wm


def solve_bucket(
    batch: BatchedLP,
    active,
    config: Optional[SolverConfig] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    batch_axis: str = "batch",
    warm: Optional[IPMState] = None,
    warm_mask=None,
    **config_overrides,
) -> BatchedResult:
    """Solve one pre-padded serving bucket: ``batch`` is (B, m, n) arrays
    already padded to the bucket shape (serve/buckets.py), ``active`` a
    (B,) bool mask — False slots are padding and are frozen from the
    first iteration (their returned status is a placeholder OPTIMAL;
    demux by slot and ignore them).

    ``mesh`` shards the batch axis over its devices (B must divide by the
    mesh size) — batch-axis data parallelism, the same placement-only
    scheme as :func:`solve_batched`: one dispatch drives every device and
    the only cross-device traffic is the ``any(active)`` loop predicate.
    Inputs already placed by :func:`place_bucket` (the serve pipeline's
    pack stage) are used as-is.

    Unlike :func:`solve_batched` there is no chunking and no solo
    cleanup: the service owns the retry budget of unfinished members
    (supervisor ladder / solo re-solve). The per-bucket PRECISION
    schedule (``config.bucket_schedule`` → :meth:`SolverConfig.
    bucket_phases`: f32-gram early phase → df32-elementwise mid → f64c
    finisher, tiered by the request tolerance) runs as masked phases
    inside the one jitted program per (B, m, n, dtype, tol, schedule,
    sharding) key, reused across every dispatch — a warm bucket never
    recompiles (:func:`bucket_cache_size`). On TPU the drive is
    host-segmented (watchdog guard) with the carry donated per segment;
    results are identical either way.

    ``warm``/``warm_mask`` offer per-slot warm-start iterates (an
    IPMState of (B, n)/(B, m) arrays, see :func:`place_warm`): offered
    slots start from the shifted-and-recentred prior iterate when the
    in-program safeguard accepts it; cold slots (and safeguard
    fallbacks) run Mehrotra's start — one dispatch freely mixes both,
    and ``BatchedResult.warm_used`` reports the per-slot outcome. The
    warm lanes are ordinary traced inputs (zeros when omitted), so
    offering them never compiles a new program.
    """
    cfg = config or SolverConfig()
    if config_overrides:
        cfg = cfg.replace(**config_overrides)
    dtype = jnp.dtype(cfg.dtype)
    fname = jnp.dtype(cfg.factor_dtype_resolved()).name
    platform = jax.default_backend()
    tiers = cfg.bucket_phases(cfg.tol, platform)
    schedule = tuple(
        (e, cfg.bucket_phase_params(e, t)) for e, t in tiers
    )
    fuse = cfg.fused_iters_resolved(platform)

    t0 = time.perf_counter()
    if isinstance(batch.A, jax.Array) and batch.A.dtype == dtype:
        # Pre-placed by place_bucket (pack stage): np.asarray here would
        # drag the arrays back to host and forfeit the overlapped
        # transfer. Divisibility was checked at placement time.
        A, b, c, active = batch.A, batch.b, batch.c, active
        if not isinstance(active, jax.Array):
            # A host mask next to a pre-placed batch must still commit
            # against the SAME mesh sharding as the data: a bare
            # jnp.asarray pins it to the default local device, which a
            # multi-process mesh program cannot consume.
            act_h = np.asarray(active, dtype=bool)
            if mesh is not None:
                active = jax.device_put(
                    act_h, mesh_lib.batch_sharding(mesh, 1, batch_axis)
                )
            else:
                active = jnp.asarray(act_h)
    else:
        placed, active = place_bucket(
            batch, active, cfg, mesh=mesh, batch_axis=batch_axis
        )
        A, b, c = placed.A, placed.b, placed.c
    Bsz, _, n = A.shape
    u = jnp.full((Bsz, n), jnp.inf, dtype=dtype)
    data = jax.vmap(
        lambda cc, bb, uu: core.make_problem_data(jnp, cc, bb, uu, dtype)
    )(c, b, u)
    # Warm lanes ALWAYS enter the program (zeros + all-false mask on a
    # cold dispatch) so warm-up, cold, warm, and mixed dispatches share
    # one executable — the zero-warm-recompile invariant extends to the
    # warm path by construction.
    if (
        warm is not None
        and isinstance(warm.x, jax.Array)
        and warm.x.dtype == dtype
    ):
        warm_states, wm = warm, warm_mask  # pre-placed by place_warm
        if not isinstance(wm, jax.Array):
            wm_h = np.asarray(wm, dtype=bool)
            if mesh is not None:
                wm = jax.device_put(
                    wm_h, mesh_lib.batch_sharding(mesh, 1, batch_axis)
                )
            else:
                wm = jnp.asarray(wm_h)
    else:
        warm_states, wm = place_warm(
            warm, warm_mask, (Bsz, A.shape[1], n), cfg,
            mesh=mesh, batch_axis=batch_axis,
        )
    setup_time = time.perf_counter() - t0

    t1 = time.perf_counter()
    cache0 = bucket_cache_size()
    seg_cfg = cfg.segment_iters
    warm_raw = (
        warm_states.x, warm_states.y, warm_states.s, warm_states.w,
        warm_states.z,
    )
    if core.use_segments(seg_cfg, platform):
        (states, status, iters, pinf, dinf, rel_gap, pobj,
         phase_its, warm_used) = _solve_bucket_segmented(
            A, data, active, cfg, schedule, fname,
            seg_cfg if seg_cfg else 8, fuse, warm_raw, wm,
        )
    else:
        (states, status, iters, pinf, dinf, rel_gap, pobj,
         phase_its, warm_used) = _solve_bucket_jit(
            A,
            data,
            active,
            *warm_raw,
            wm,
            jnp.asarray(cfg.reg_dual, dtype),
            jnp.asarray(cfg.max_iter, jnp.int32),
            jnp.asarray(cfg.max_refactor, jnp.int32),
            jnp.asarray(cfg.reg_grow, dtype),
            schedule,
            fname,
            cfg.stall_window,
            fuse,
        )
    jax.block_until_ready(states)
    solve_time = time.perf_counter() - t1
    compiled = bucket_cache_size() - cache0
    phase_report = [
        {"phase": pi, "engine": tiers[pi][0], "tol": tiers[pi][1],
         "iters": int(v)}
        for pi, v in enumerate(np.asarray(phase_its))
    ]
    if compiled:  # recompile accounting at the cache itself: every
        # caller (service dispatch, warm_buckets, direct tests) is
        # covered, and the warm path costs one cache-size read.
        obs_metrics.get_registry().counter(
            "bucket_programs_compiled_total",
            help="batched bucket programs compiled in this process",
        ).inc(compiled)

    code_map = {
        _OPTIMAL: Status.OPTIMAL,
        _MAXITER: Status.ITERATION_LIMIT,
        _NUMERR: Status.NUMERICAL_ERROR,
        _STALL: Status.STALLED,
    }
    # Demux through the multi-process-safe fetch: on a single-process
    # mesh this is np.asarray verbatim; on a multi-process (pod-slice)
    # mesh the batch axis spans processes and every result field rides
    # ONE replicating gather program all ranks reach together.
    (status_h, pobj_h, x_h, iters_h, rel_gap_h, pinf_h, dinf_h, y_h,
     s_h, w_h, z_h, warm_h) = mesh_lib.host_values(
        (status, pobj, states.x, iters, rel_gap, pinf, dinf, states.y,
         states.s, states.w, states.z, warm_used)
    )
    status_arr = np.array(
        [code_map[int(sc)] for sc in status_h], dtype=object
    )
    return BatchedResult(
        status=status_arr,
        objective=np.asarray(pobj_h, dtype=np.float64),
        x=np.asarray(x_h, dtype=np.float64),
        iterations=iters_h,
        rel_gap=np.asarray(rel_gap_h, dtype=np.float64),
        pinf=np.asarray(pinf_h, dtype=np.float64),
        dinf=np.asarray(dinf_h, dtype=np.float64),
        solve_time=solve_time,
        setup_time=setup_time,
        phase_report=phase_report,
        fused_iters=fuse,
        y=np.asarray(y_h, dtype=np.float64),
        s=np.asarray(s_h, dtype=np.float64),
        w=np.asarray(w_h, dtype=np.float64),
        z=np.asarray(z_h, dtype=np.float64),
        warm_used=warm_h,
    )


def member_interior_form(batch: BatchedLP, i: int):
    """One batch member as a standalone InteriorForm — the solo-cleanup
    path's input, exported so bench warm-ups can compile the SAME dense
    solo programs the cleanup will run (its first compile otherwise lands
    inside the timed solve)."""
    from distributedlpsolver_tpu.models.problem import InteriorForm, _SHIFT

    n = np.asarray(batch.A).shape[2]
    return InteriorForm(
        c=np.asarray(batch.c[i], dtype=np.float64),
        A=np.asarray(batch.A[i], dtype=np.float64),
        b=np.asarray(batch.b[i], dtype=np.float64),
        u=np.full(n, np.inf), c0=0.0, orig_n=n,
        col_kind=np.full(n, _SHIFT, dtype=np.int8),
        col_orig=np.arange(n), col_shift=np.zeros(n),
        col_sign=np.ones(n), name=f"{batch.name}[{i}]",
    )


def _concat_results(parts, solve_time, setup_time) -> BatchedResult:
    cat = lambda f: np.concatenate([getattr(p, f) for p in parts])
    return BatchedResult(
        status=cat("status"),
        objective=cat("objective"),
        x=cat("x"),
        iterations=cat("iterations"),
        rel_gap=cat("rel_gap"),
        pinf=cat("pinf"),
        dinf=cat("dinf"),
        solve_time=solve_time,
        setup_time=setup_time,
        # Flat rows with a chunk tag — same shape chunked or not, so
        # consumers never branch on the solve's chunking.
        phase_report=[
            {**ph, "chunk": ci}
            for ci, p in enumerate(parts)
            for ph in (p.phase_report or [])
        ],
    )


def solve_batched(
    batch: BatchedLP,
    config: Optional[SolverConfig] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    batch_axis: str = "batch",
    chunk: Optional[int] = None,
    **config_overrides,
) -> BatchedResult:
    """Solve every problem in ``batch`` concurrently on device.

    ``mesh`` shards the batch axis (data parallelism); the batch size must
    then be divisible by the mesh size.

    ``chunk`` bounds how many problems one device program holds (HBM: the
    per-iteration temps of B emulated-f64 batched GEMMs are ~64·B·m·n
    bytes — B=1024×(128,512) alone exceeds a v5e's 16 GB). Chunks run
    sequentially through ONE compiled executable, so throughput is
    unaffected once B saturates the chip. Default: 256 on TPU (None —
    no chunking — elsewhere); chunking preserves mesh divisibility by
    requiring chunk % mesh size == 0.
    """

    cfg = config or SolverConfig()
    if config_overrides:
        cfg = cfg.replace(**config_overrides)
    # kkt_refine stays at the global default (2): a refine=1 schedule
    # measured 2.6× faster on one easy 256-draw but LOST on the real
    # 1024-row — masked iterations rose 62→76 per chunk and one member
    # left optimality (1023/1024, 133.8 s vs 115.7 s) — the second
    # round is what keeps the hard tail's directions accurate enough
    # to converge (A/B 2026-08-01).
    dtype = jnp.dtype(cfg.dtype)
    fname = jnp.dtype(cfg.factor_dtype_resolved()).name

    B_total = np.asarray(batch.A).shape[0]
    if chunk is None and jax.default_backend() == "tpu":
        chunk = _CHUNK_DEFAULT
    if chunk and mesh is not None and chunk % mesh.shape[batch_axis] != 0:
        raise ValueError(
            f"chunk {chunk} not divisible by mesh axis {mesh.shape[batch_axis]}"
        )
    if chunk and B_total > chunk:
        # A non-multiple B leaves one smaller remainder chunk (one extra
        # compile at that shape) — still chunked: falling through to a
        # single whole-batch program is exactly the HBM blow-up chunking
        # exists to prevent. With a mesh, the remainder must still divide
        # the mesh axis (checked by the recursive call).
        t0 = time.perf_counter()
        parts = [
            solve_batched(
                BatchedLP(
                    c=batch.c[i : i + chunk],
                    A=batch.A[i : i + chunk],
                    b=batch.b[i : i + chunk],
                    name=f"{batch.name}[{i}:{i + chunk}]",
                ),
                cfg,
                mesh=mesh,
                batch_axis=batch_axis,
                chunk=0,  # no further splitting
            )
            for i in range(0, B_total, chunk)
        ]
        wall = time.perf_counter() - t0
        solve_time = sum(p.solve_time for p in parts)
        return _concat_results(
            parts,
            solve_time=solve_time,
            setup_time=max(wall - solve_time, 0.0),  # wall minus solve, no double count
        )

    t0 = time.perf_counter()
    A = np.asarray(batch.A, dtype=dtype)
    b = np.asarray(batch.b, dtype=dtype)
    c = np.asarray(batch.c, dtype=dtype)
    Bsz, m, n = A.shape
    if mesh is not None:
        if Bsz % mesh.shape[batch_axis] != 0:
            raise ValueError(
                f"batch {Bsz} not divisible by mesh axis {mesh.shape[batch_axis]}"
            )
        sh = lambda nd: mesh_lib.batch_sharding(mesh, nd, batch_axis)
        A = jax.device_put(A, sh(3))
        b = jax.device_put(b, sh(2))
        c = jax.device_put(c, sh(2))
    else:
        A, b, c = jnp.asarray(A), jnp.asarray(b), jnp.asarray(c)

    u = jnp.full((Bsz, n), jnp.inf, dtype=dtype)
    data = jax.vmap(lambda cc, bb, uu: core.make_problem_data(jnp, cc, bb, uu, dtype))(
        c, b, u
    )
    params = cfg.step_params()
    setup_time = time.perf_counter() - t0

    t1 = time.perf_counter()
    # Phase schedule (shared _phase_plan): phases are auto-gated on
    # MEMBER size — at the reference batched shape single-phase f64 was
    # measured fastest and PCG 5.6× worse (see _PHASED_MEMBER_ENTRIES),
    # and the PCG chunk≥256 programs crash the current TPU worker;
    # "pcg" still opts in explicitly.
    two_phase, use_pcg, n_phases = _phase_plan(cfg, member_entries=m * n)
    params_p1 = cfg.phase1_params()
    cg = (cfg.cg_iters, cfg.cg_tol) if use_pcg else (0, 0.0)
    fuse = cfg.fused_iters_resolved(jax.default_backend())
    seg = cfg.segment_iters
    if seg is None:
        seg = 8 if jax.default_backend() == "tpu" else 0
    phase_report = []
    if seg:
        (states, status, iters, pinf, dinf, rel_gap, pobj,
         phase_report) = _solve_batched_segmented(
            A, data, cfg, params, params_p1, fname, two_phase, seg, cg,
            compact_ok=mesh is None, fuse_iters=fuse,
        )
        # Same row shape chunked or not (the chunked path tags rows in
        # _concat_results) — consumers never branch on chunking.
        phase_report = [{**ph, "chunk": 0} for ph in phase_report]
    else:
        states, status, iters, pinf, dinf, rel_gap, pobj = _solve_batched_jit(
            A,
            data,
            jnp.asarray(cfg.reg_dual, dtype),
            params,
            params_p1,
            cfg.max_iter,
            cfg.max_refactor,
            cfg.reg_grow,
            fname,
            two_phase,
            cfg.stall_window,
            cg[0],
            cg[1],
            fuse,
        )
    jax.block_until_ready(states)

    code_map = {
        _OPTIMAL: Status.OPTIMAL,
        _MAXITER: Status.ITERATION_LIMIT,
        _NUMERR: Status.NUMERICAL_ERROR,
        _STALL: Status.STALLED,
    }
    status_arr = np.array(
        [code_map[int(sc)] for sc in np.asarray(status)], dtype=object
    )
    # .array (not .asarray): device arrays convert to read-only views and
    # the solo cleanup below writes per-member rows.
    objective = np.array(pobj, dtype=np.float64)
    x = np.array(states.x, dtype=np.float64)
    iterations = np.array(iters)
    rel_gap = np.array(rel_gap, dtype=np.float64)
    pinf = np.array(pinf, dtype=np.float64)
    dinf = np.array(dinf, dtype=np.float64)

    # Solo cleanup: members the batched loop left unfinished (tail
    # extraction stopped early, stalls, iteration limits) re-solve
    # individually through the dense path, warm-started from their batched
    # iterates — a handful of solo solves beats keeping the whole batch's
    # masked loop alive at full-batch cost per iteration. Bounded so a
    # pathological batch can't turn into B sequential solves.
    bad = [i for i in range(Bsz) if status_arr[i] != Status.OPTIMAL]
    if bad and len(bad) <= _cleanup_cap(Bsz):
        from distributedlpsolver_tpu.ipm.driver import solve as _solve

        base_cfg = cfg.replace(
            verbose=False, log_jsonl=None, checkpoint_path=None,
            checkpoint_every=0, profile_dir=None,
        )
        # The batched loop's total budget is max_iter PER PHASE (the f32
        # phase's accepted steps land in the same per-problem counter;
        # n_phases from the shared _phase_plan above), so the cleanup
        # comparison must use the same total — comparing against a single
        # max_iter would deny tail-extracted members the cleanup solve
        # the early stop promised them.
        for i in bad:
            # The solo solve only gets what the batched loop left unspent
            # (tail-extracted members keep most of theirs; genuine
            # iteration-limit members get none and keep that verdict).
            remaining = n_phases * cfg.max_iter - int(iterations[i])
            if remaining <= 0:
                continue
            solo_cfg = base_cfg.replace(max_iter=remaining)
            # Per-member host conversion — full-batch f64 copies just to
            # patch a handful of rows would be ~hundreds of MB transient.
            inf_i = member_interior_form(batch, i)
            ws = IPMState(
                x=x[i],
                y=np.asarray(states.y[i], dtype=np.float64),
                s=np.asarray(states.s[i], dtype=np.float64),
                w=np.asarray(states.w[i], dtype=np.float64),
                z=np.asarray(states.z[i], dtype=np.float64),
            )
            r = _solve(inf_i, backend=CLEANUP_BACKEND, config=solo_cfg,
                       warm_start=ws)
            status_arr[i] = r.status
            objective[i] = r.objective
            x[i] = r.x
            iterations[i] += r.iterations
            rel_gap[i], pinf[i], dinf[i] = r.rel_gap, r.pinf, r.dinf

    solve_time = time.perf_counter() - t1
    return BatchedResult(
        status=status_arr,
        objective=objective,
        x=x,
        iterations=iterations,
        rel_gap=rel_gap,
        pinf=pinf,
        dinf=dinf,
        solve_time=solve_time,
        setup_time=setup_time,
        phase_report=phase_report,
    )
